"""Property-based fuzz of the socket collective family: random rank
counts (including 1 and non-powers-of-2), dtypes (including BFLOAT16),
compression, algorithms, operators, and sub-ranges — all against the
numpy oracle over real loopback TCP (SURVEY.md section 4's check-program
pattern, driven by hypothesis)."""

import numpy as np
import pytest
pytest.importorskip("hypothesis")  # property tests need hypothesis
from hypothesis import given, settings, strategies as st

from tests.helpers import run_slaves
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operators

_DTYPES = {
    "FLOAT": Operands.FLOAT,
    "DOUBLE": Operands.DOUBLE,
    "INT": Operands.INT,
    "LONG": Operands.LONG,
    "SHORT": Operands.SHORT,
}
_BF16 = getattr(Operands, "BFLOAT16", None)
if _BF16 is not None:
    _DTYPES["BFLOAT16"] = _BF16

_NP_OPS = {"SUM": np.sum, "MAX": np.max, "MIN": np.min}


@settings(max_examples=12, deadline=None)
@given(
    n=st.integers(1, 5),
    length=st.integers(1, 60),
    dtype_name=st.sampled_from(sorted(_DTYPES)),
    op_name=st.sampled_from(sorted(_NP_OPS)),
    algo=st.sampled_from(["rhd", "ring"]),
    compress=st.booleans(),
    data=st.data(),
)
def test_allreduce_fuzz(n, length, dtype_name, op_name, algo, compress,
                        data):
    operand = _DTYPES[dtype_name]
    if compress:
        operand = Operands.compressed(operand)
    lo = data.draw(st.integers(0, length), label="lo")
    hi = data.draw(st.integers(lo, length), label="hi")
    seed = data.draw(st.integers(0, 2**31), label="seed")
    rng = np.random.default_rng(seed)

    if operand.dtype.kind == "f" or operand.dtype.kind == "V":
        base = [rng.uniform(-4, 4, length).astype(operand.dtype)
                for _ in range(n)]
    else:
        base = [rng.integers(-20, 20, length).astype(operand.dtype)
                for _ in range(n)]
    want = (_NP_OPS[op_name](
        np.stack([b[lo:hi].astype(np.float64) for b in base]), axis=0)
        if hi > lo else None)

    def fn(slave, rank):
        arr = base[rank].copy()
        slave.allreduce_array(arr, operand, Operators.by_name(op_name),
                              from_=lo, to=hi, algo=algo)
        return arr

    outs = run_slaves(n, fn)
    # tolerance scaled to the dtype: bf16 rounds at ~2^-8 RELATIVE TO
    # THE INTERMEDIATE partial sums (magnitude up to n*4), so the
    # absolute floor must cover cancellation down to |want| ~ 0;
    # f32/f64/int paths are (near-)exact
    if dtype_name == "BFLOAT16":
        rtol, atol = 0.05, n * 4 * 2 ** -8 * 2
    else:
        rtol, atol = 1e-5, 1e-5
    for out, orig in zip(outs, base):
        if hi > lo:
            np.testing.assert_allclose(
                np.asarray(out[lo:hi], np.float64), want, rtol=rtol,
                atol=atol)
        np.testing.assert_array_equal(np.asarray(out[:lo]),
                                      np.asarray(orig[:lo]))
        np.testing.assert_array_equal(np.asarray(out[hi:]),
                                      np.asarray(orig[hi:]))


@settings(max_examples=8, deadline=None)
@given(
    n=st.integers(2, 5),
    length=st.integers(2, 40),
    root=st.integers(0, 4),
    seed=st.integers(0, 2**31),
)
def test_rooted_collectives_fuzz(n, length, root, seed):
    """broadcast + gather + scatter with a random root and ranges."""
    root = root % n
    rng = np.random.default_rng(seed)
    base = [rng.standard_normal(length).astype(np.float32)
            for _ in range(n)]

    def fn(slave, rank):
        a = base[rank].copy()
        slave.broadcast_array(a, Operands.FLOAT, root=root)
        b = base[rank].copy()
        slave.gather_array(b, Operands.FLOAT, root=root)
        c = base[rank].copy()
        slave.scatter_array(c, Operands.FLOAT, root=root)
        return a, b, c

    outs = run_slaves(n, fn)
    from ytk_mp4j_tpu import meta

    ranges = meta.partition_range(0, length, n)
    for rank, (a, b, c) in enumerate(outs):
        np.testing.assert_array_equal(a, base[root])
        if rank == root:
            for q, (s, e) in enumerate(ranges):
                np.testing.assert_array_equal(b[s:e], base[q][s:e])
        s, e = ranges[rank]
        np.testing.assert_array_equal(c[s:e], base[root][s:e])
        # untouched positions keep the local values
        np.testing.assert_array_equal(c[:s], base[rank][:s])
        np.testing.assert_array_equal(c[e:], base[rank][e:])
