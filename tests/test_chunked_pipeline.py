"""Pipelined chunked collective engine: equivalence, stats, knobs.

The chunked engine must be INVISIBLE except for speed: for every
operand x operator x rank count (including non-powers-of-2) x chunk
size (including chunk >= segment and pathologically tiny), the result
must be bit-identical to the unchunked reference — chunks merge in
ascending offset order, preserving the per-element merge order exactly,
so even float results may not drift. Also covers the per-collective
stats schema (bytes / chunk counts against the collective's analytic
volume) and the env knobs' validation.
"""

import os
import socket
from contextlib import contextmanager

import numpy as np
import pytest

from tests.helpers import run_slaves
from ytk_mp4j_tpu.comm.thread_comm import ThreadCommSlave
from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operators
from ytk_mp4j_tpu.transport.tcp import TcpChannel as Channel
from ytk_mp4j_tpu.utils import tuning

_DTYPES = {
    "FLOAT": Operands.FLOAT,
    "DOUBLE": Operands.DOUBLE,
    "INT": Operands.INT,
    "LONG": Operands.LONG,
    "SHORT": Operands.SHORT,
}
_NP_OPS = {"SUM": np.add, "MAX": np.maximum, "MIN": np.minimum,
           "PROD": np.multiply}


@contextmanager
def _env(**kv):
    old = {k: os.environ.get(k) for k in kv}
    try:
        for k, v in kv.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = str(v)
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _oracle(base, op_name, lo, hi, dtype):
    stack = np.stack([np.asarray(b[lo:hi]) for b in base])
    return _NP_OPS[op_name].reduce(stack.astype(dtype, copy=False), axis=0)


def _allreduce_outs(base, operand, op_name, algo, chunk_bytes, lo, hi,
                    native):
    with _env(MP4J_CHUNK_BYTES=chunk_bytes):
        def fn(slave, rank):
            arr = base[rank].copy()
            slave.allreduce_array(arr, operand, Operators.by_name(op_name),
                                  from_=lo, to=hi, algo=algo)
            return arr

        return run_slaves(len(base), fn, native_transport=native)


# ----------------------------------------------------------------------
# bit-exact equivalence: chunked == unchunked, all operands/operators
# ----------------------------------------------------------------------
def _equivalence_case(n, length, dtype_name, op_name, algo, native,
                      chunk_bytes, lo, hi, seed, compress=False):
    operand = _DTYPES[dtype_name]
    if compress:
        operand = Operands.compressed(operand)
    rng = np.random.default_rng(seed)
    if operand.dtype.kind == "f":
        base = [rng.uniform(-4, 4, length).astype(operand.dtype)
                for _ in range(n)]
    else:
        # PROD-safe magnitudes: per-rank factors in {1, 2}, so the
        # product across <= 5 ranks stays within every int dtype
        base = [rng.integers(1, 3, length).astype(operand.dtype)
                for _ in range(n)]

    # chunk >= segment (one chunk) is the unchunked reference; the
    # tiny chunk size forces many chunks through the same rounds
    ref = _allreduce_outs(base, operand, op_name, algo, 1 << 30,
                          lo, hi, native)
    got = _allreduce_outs(base, operand, op_name, algo, chunk_bytes,
                          lo, hi, native)
    for r, g in zip(ref, got):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(r))

    # and both match the numpy oracle (int: bit-exact; float: the
    # algorithms' association order differs from numpy's, tolerance)
    if hi > lo:
        want = _oracle(base, op_name, lo, hi, operand.dtype)
        for g in got:
            if operand.dtype.kind == "f":
                np.testing.assert_allclose(np.asarray(g[lo:hi]), want,
                                           rtol=1e-5, atol=1e-5)
            else:
                np.testing.assert_array_equal(np.asarray(g[lo:hi]), want)
    for g, b in zip(got, base):
        np.testing.assert_array_equal(np.asarray(g[:lo]),
                                      np.asarray(b[:lo]))
        np.testing.assert_array_equal(np.asarray(g[hi:]),
                                      np.asarray(b[hi:]))


@pytest.mark.parametrize("native", [True, False])
@pytest.mark.parametrize("algo", ["rhd", "ring", "tree"])
def test_chunked_equivalence_smoke(algo, native):
    """Non-pow2 ranks, tiny chunks, both wire formats, every algo."""
    _equivalence_case(n=3, length=1500, dtype_name="FLOAT",
                      op_name="SUM", algo=algo, native=native,
                      chunk_bytes=256, lo=3, hi=1401, seed=7)


@pytest.mark.parametrize("op_name", sorted(_NP_OPS))
@pytest.mark.parametrize("dtype_name", sorted(_DTYPES))
def test_chunked_equivalence_operand_operator_grid(dtype_name, op_name):
    """All numeric operands x SUM/MAX/MIN/PROD, non-pow2 ranks, chunks
    far smaller than the segments; ints assert BIT-exact vs the
    oracle."""
    _equivalence_case(n=5, length=700, dtype_name=dtype_name,
                      op_name=op_name, algo="rhd", native=True,
                      chunk_bytes=128, lo=0, hi=None or 700, seed=11)


def test_chunked_equivalence_compressed_stream():
    """The framed compressed path (TAG_ARRAY_ZC streamed inflate) is
    chunk-size-invariant too."""
    _equivalence_case(n=3, length=2000, dtype_name="DOUBLE",
                      op_name="SUM", algo="rhd", native=False,
                      chunk_bytes=512, lo=0, hi=2000, seed=3,
                      compress=True)


def test_zero_length_segments_and_empty_ranges():
    """length < n leaves some ranks with empty segments; chunking a
    zero-length segment must be a no-op, not a hang."""
    for algo in ("rhd", "ring"):
        _equivalence_case(n=5, length=3, dtype_name="INT",
                          op_name="SUM", algo=algo, native=True,
                          chunk_bytes=64, lo=0, hi=3, seed=1)
    # empty [from_, to) sub-range: untouched buffers
    _equivalence_case(n=3, length=40, dtype_name="FLOAT",
                      op_name="SUM", algo="rhd", native=True,
                      chunk_bytes=64, lo=7, hi=7, seed=2)


try:
    from hypothesis import given, settings, strategies as st
    _HAVE_HYP = True
except ImportError:          # pragma: no cover - tier-1 gates skip
    _HAVE_HYP = False


if _HAVE_HYP:
    @settings(max_examples=10, deadline=None)
    @given(
        n=st.integers(1, 5),
        length=st.integers(0, 80),
        dtype_name=st.sampled_from(sorted(_DTYPES)),
        op_name=st.sampled_from(sorted(_NP_OPS)),
        algo=st.sampled_from(["rhd", "ring"]),
        native=st.booleans(),
        chunk_bytes=st.sampled_from([64, 256, 1 << 20]),
        data=st.data(),
    )
    def test_chunked_equivalence_fuzz(n, length, dtype_name, op_name,
                                      algo, native, chunk_bytes, data):
        lo = data.draw(st.integers(0, length), label="lo")
        hi = data.draw(st.integers(lo, length), label="hi")
        seed = data.draw(st.integers(0, 2 ** 31), label="seed")
        _equivalence_case(n, length, dtype_name, op_name, algo, native,
                          chunk_bytes, lo, hi, seed)


# ----------------------------------------------------------------------
# partitioned collectives: tree path == ring path
# ----------------------------------------------------------------------
def test_reduce_scatter_tree_matches_ring():
    rng = np.random.default_rng(5)
    base = [rng.standard_normal(37).astype(np.float32) for _ in range(4)]

    def run(algo):
        def fn(slave, rank):
            arr = base[rank].copy()
            slave.reduce_scatter_array(arr, Operands.FLOAT,
                                       Operators.SUM, algo=algo)
            return arr
        return run_slaves(4, fn)

    from ytk_mp4j_tpu import meta
    ranges = meta.partition_range(0, 37, 4)
    tree, ring = run("tree"), run("ring")
    for r, (s, e) in enumerate(ranges):
        np.testing.assert_allclose(tree[r][s:e], ring[r][s:e],
                                   rtol=1e-5, atol=1e-6)
        # positions outside the owned range stay local on both paths
        np.testing.assert_array_equal(tree[r][:s], base[r][:s])
        np.testing.assert_array_equal(tree[r][e:], base[r][e:])
        np.testing.assert_array_equal(ring[r][:s], base[r][:s])


def test_allgather_tree_matches_ring():
    rng = np.random.default_rng(6)
    base = [rng.standard_normal(41).astype(np.float64) for _ in range(5)]

    def run(algo):
        def fn(slave, rank):
            arr = base[rank].copy()
            slave.allgather_array(arr, Operands.DOUBLE, algo=algo)
            return arr
        return run_slaves(5, fn)

    tree, ring = run("tree"), run("ring")
    for t, g in zip(tree, ring):
        np.testing.assert_array_equal(t, g)


def test_allgather_tree_rejects_gapped_ranges():
    def fn(slave, rank):
        arr = np.zeros(10, np.float32)
        with pytest.raises(Mp4jError):
            slave.allgather_array(arr, Operands.FLOAT,
                                  ranges=[(0, 2), (5, 10)], algo="tree")
        return True

    assert all(run_slaves(2, fn))


# ----------------------------------------------------------------------
# algo="auto": threshold-driven selection stays correct
# ----------------------------------------------------------------------
def test_auto_is_correct_across_thresholds():
    """Force auto through all three regimes via env thresholds; every
    regime must produce the oracle result."""
    rng = np.random.default_rng(9)
    base = [rng.standard_normal(512).astype(np.float32)
            for _ in range(4)]  # 2 KiB payload
    want = _oracle(base, "SUM", 0, 512, np.float32)
    for small, large in ((1 << 20, 2 << 20),   # payload <= small: tree
                         (16, 1 << 20),        # middle: rhd
                         (16, 64)):            # payload >= large: ring
        with _env(MP4J_ALGO_SMALL_BYTES=small, MP4J_ALGO_LARGE_BYTES=large):
            def fn(slave, rank):
                arr = base[rank].copy()
                slave.allreduce_array(arr, Operands.FLOAT, Operators.SUM)
                return arr
            for out in run_slaves(4, fn):
                np.testing.assert_allclose(out, want, rtol=1e-5,
                                           atol=1e-5)


def test_select_allreduce_algo_pure():
    assert tuning.select_allreduce_algo(100, 4, 1000, 10**9) == "tree"
    assert tuning.select_allreduce_algo(10**6, 4, 1000, 10**9) == "rhd"
    assert tuning.select_allreduce_algo(10**10, 4, 1000, 10**9) == "ring"
    # n=2: RHD is the single optimal pairwise exchange in every regime
    assert tuning.select_allreduce_algo(100, 2, 1000, 10**9) == "rhd"
    assert tuning.select_partitioned_algo(100, 4, 1000, 10**9) == "tree"
    assert tuning.select_partitioned_algo(10**6, 4, 1000, 10**9) == "ring"


# ----------------------------------------------------------------------
# comm.stats(): analytic volume
# ----------------------------------------------------------------------
def test_process_stats_match_analytic_volume():
    """Raw path, n=2, rhd, L float32 elements, chunk C bytes: each rank
    sends exactly L/2 elements in halving + L/2 in doubling = L*4
    bytes, receives the same, and the halving exchange splits into
    ceil((L/2)*4 / C) chunks plus 1 monolithic doubling exchange."""
    L, C = 16384, 16384          # 64 KiB payload, 16 KiB chunks
    with _env(MP4J_CHUNK_BYTES=C):
        def fn(slave, rank):
            arr = np.ones(L, np.float32)
            slave.allreduce_array(arr, Operands.FLOAT, Operators.SUM,
                                  algo="rhd")
            return slave.stats()

        for snap in run_slaves(2, fn, native_transport=True):
            e = snap["allreduce_array"]
            assert e["calls"] == 1
            assert e["bytes_sent"] == L * 4
            assert e["bytes_recv"] == L * 4
            half_bytes = (L // 2) * 4
            assert e["chunks"] == -(-half_bytes // C) + 1
            assert e["wire_seconds"] > 0
            assert e["reduce_seconds"] > 0
            # raw path: no pickle/zlib on the data plane
            assert e["serialize_seconds"] == 0


def test_process_stats_framed_counts_wire_bytes():
    """Framed path: wire bytes cover payload + framing (strictly more
    than the analytic payload, within a small framing overhead)."""
    L = 8192
    def fn(slave, rank):
        arr = np.ones(L, np.float32)
        slave.allreduce_array(arr, Operands.FLOAT, Operators.SUM,
                              algo="rhd")
        return slave.stats()

    for snap in run_slaves(2, fn, native_transport=False):
        e = snap["allreduce_array"]
        assert e["calls"] == 1
        assert L * 4 < e["bytes_sent"] < L * 4 + 512
        assert L * 4 < e["bytes_recv"] < L * 4 + 512
        assert e["chunks"] >= 1
        assert e["serialize_seconds"] > 0   # header pickling


def test_stats_cover_every_collective_family():
    def fn(slave, rank):
        arr = np.arange(8, dtype=np.float64)
        slave.allreduce_array(arr, Operands.DOUBLE, Operators.SUM)
        slave.broadcast_array(arr, Operands.DOUBLE, root=0)
        slave.gather_array(arr, Operands.DOUBLE, root=0)
        slave.allreduce_map({rank: 1.0}, Operands.DOUBLE, Operators.SUM)
        slave.barrier()
        return slave.stats()

    for snap in run_slaves(3, fn):
        for name in ("allreduce_array", "broadcast_array",
                     "gather_array", "allreduce_map", "barrier"):
            assert snap[name]["calls"] == 1, name
        # composed collectives attribute to the OUTERMOST call only
        assert "reduce_map" not in snap


def test_thread_stats_merge_group_and_proc():
    group = ThreadCommSlave.spawn_group(4)
    import threading

    outs = [None] * 4

    def worker(t):
        arr = np.ones(1024, np.float32) * (t + 1)
        group[t].allreduce_array(arr, Operands.FLOAT, Operators.SUM)
        outs[t] = (arr.copy(), group[t].stats())

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(30)
        assert not th.is_alive()
    want = np.full(1024, 1 + 2 + 3 + 4, np.float32)
    for arr, snap in outs:
        np.testing.assert_array_equal(arr, want)
        e = snap["allreduce_array"]
        assert e["calls"] == 4          # one begin per thread
        assert e["reduce_seconds"] > 0  # intra-process tree merges


# ----------------------------------------------------------------------
# env knobs: validation + application
# ----------------------------------------------------------------------
def test_chunk_bytes_validation():
    with _env(MP4J_CHUNK_BYTES="banana"):
        with pytest.raises(Mp4jError):
            tuning.chunk_bytes()
    with _env(MP4J_CHUNK_BYTES="0"):
        with pytest.raises(Mp4jError):
            tuning.chunk_bytes()
    with _env(MP4J_CHUNK_BYTES="4096"):
        assert tuning.chunk_bytes() == 4096
    with _env(MP4J_CHUNK_BYTES=None):
        assert tuning.chunk_bytes() == tuning.DEFAULT_CHUNK_BYTES


def test_algo_threshold_validation():
    with _env(MP4J_ALGO_SMALL_BYTES="1000000",
              MP4J_ALGO_LARGE_BYTES="1000"):
        with pytest.raises(Mp4jError):
            tuning.algo_thresholds()


def test_socket_buffer_knobs_applied():
    with _env(MP4J_SO_SNDBUF="65536", MP4J_SO_RCVBUF="65536"):
        a, b = socket.socketpair()
        try:
            Channel(a)
            # kernels round/double the requested size; >= is the contract
            assert a.getsockopt(socket.SOL_SOCKET,
                                socket.SO_SNDBUF) >= 65536
            assert a.getsockopt(socket.SOL_SOCKET,
                                socket.SO_RCVBUF) >= 65536
        finally:
            a.close()
            b.close()
    with _env(MP4J_SO_SNDBUF="nope"):
        a, b = socket.socketpair()
        try:
            with pytest.raises(Mp4jError):
                Channel(a)
        finally:
            a.close()
            b.close()


def test_bad_chunk_bytes_fails_slave_setup():
    """A typo'd knob must fail the job at construction, not hang a
    collective mid-flight."""
    from ytk_mp4j_tpu.comm.master import Master
    from ytk_mp4j_tpu.comm.process_comm import ProcessCommSlave

    with _env(MP4J_CHUNK_BYTES="-5"):
        master = Master(1, timeout=10.0).serve_in_thread()
        with pytest.raises(Mp4jError):
            ProcessCommSlave("127.0.0.1", master.port, timeout=10.0)
