"""Observability layer (ISSUE 3): span ring + Chrome-trace export,
master telemetry aggregation, cross-rank skew, hang diagnosis, the
barrier watchdog, the upgraded log sink, and the mp4j-scope CLI."""

import io
import json
import re
import threading
import time

import numpy as np
import pytest

from ytk_mp4j_tpu.comm.master import Master
from ytk_mp4j_tpu.comm.process_comm import ProcessCommSlave
from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.obs import spans, telemetry
from ytk_mp4j_tpu.obs.cli import main as scope_main
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operators
from ytk_mp4j_tpu.utils import trace


def run_job(n, fn, master_kwargs=None, slave_kwargs=None, join=30.0):
    """Master + n slave threads with log capture; returns
    (results, errors, log_text, master). Unlike helpers.run_slaves it
    does NOT assert success — hang tests expect slave errors."""
    log = io.StringIO()
    master = Master(n, timeout=join, log_stream=log,
                    **(master_kwargs or {})).serve_in_thread()
    results, errors = [None] * n, []

    def worker():
        slave = None
        try:
            slave = ProcessCommSlave("127.0.0.1", master.port,
                                     timeout=join,
                                     **(slave_kwargs or {}))
            results[slave.rank] = fn(slave, slave.rank)
            slave.close(0)
        except Exception as e:
            errors.append((slave.rank if slave is not None else -1, e))
            if slave is not None:
                try:
                    slave.close(1)
                except Exception:
                    pass

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(join)
        assert not t.is_alive(), "slave thread hung past the deadline"
    master.join(join)
    return results, errors, log.getvalue(), master


# ----------------------------------------------------------------------
# span timelines / Chrome-trace export
# ----------------------------------------------------------------------
def _validate_chrome_trace(doc):
    """The trace-event JSON schema gate: required keys on every event,
    monotone ts per (pid, tid) track."""
    assert isinstance(doc, dict) and isinstance(doc["traceEvents"], list)
    tracks = {}
    for ev in doc["traceEvents"]:
        for key in ("name", "ph", "ts", "pid", "tid"):
            assert key in ev, f"event missing {key!r}: {ev}"
        assert ev["ph"] == "X" and ev["dur"] >= 0
        track = (ev["pid"], ev["tid"])
        assert ev["ts"] >= tracks.get(track, float("-inf")), \
            f"ts not monotone on track {track}"
        tracks[track] = ev["ts"]
    return doc["traceEvents"]


def test_export_chrome_trace_socket_job(tmp_path, monkeypatch):
    """Acceptance: a 4-rank socket job exports valid trace-event JSON
    with chunk-level wire/reduce phase spans for allreduce_array."""
    monkeypatch.setenv("MP4J_CHUNK_BYTES", "8192")  # 8 KiB -> chunking
    from helpers import run_slaves

    spans.clear()

    def fn(slave, r):
        arr = np.full(16384, float(r))  # 128 KiB float64
        # rhd: every rank both exchanges and merges, so every rank's
        # timeline gets wire AND reduce spans (the tree's leaf ranks
        # only send)
        slave.allreduce_array(arr, Operands.DOUBLE, Operators.SUM,
                              algo="rhd")
        return float(arr[0])

    run_slaves(4, fn)
    path = tmp_path / "trace.json"
    n = trace.export_chrome_trace(str(path))
    assert n > 0
    events = _validate_chrome_trace(json.loads(path.read_text()))

    # one timeline track per rank (pid = mp4j rank)
    assert {e["pid"] for e in events} == {0, 1, 2, 3}
    # collective spans carry the per-slave sequence number
    colls = [e for e in events
             if e["cat"] == "collective" and e["name"] == "allreduce_array"]
    assert len(colls) == 4 and all(e["args"]["seq"] >= 1 for e in colls)
    # chunk-level phase spans attributed to the collective: several
    # wire AND reduce spans per rank (128 KiB over 8 KiB chunks)
    for pid in range(4):
        wires = [e for e in events if e["pid"] == pid
                 and e["name"] == "wire"
                 and e["args"]["collective"] == "allreduce_array"]
        reduces = [e for e in events if e["pid"] == pid
                   and e["name"] == "reduce"
                   and e["args"]["collective"] == "allreduce_array"]
        assert len(wires) >= 2, "expected chunk-level wire spans"
        assert len(reduces) >= 2, "expected chunk-level reduce spans"


def test_span_ring_is_bounded():
    spans.configure(8)
    try:
        for i in range(100):
            spans.record(f"s{i}", "phase", float(i), 0.001, 0)
        snap = spans.snapshot()
        assert len(snap) == 8
        assert snap[0][0] == "s92"  # oldest fell off
    finally:
        from ytk_mp4j_tpu.utils import tuning
        spans.configure(tuning.span_ring_capacity())


def test_scope_merge_cli(tmp_path, capsys):
    a = tmp_path / "r0.json"
    b = tmp_path / "r1.json"
    a.write_text(json.dumps({"traceEvents": [
        {"name": "wire", "cat": "phase", "ph": "X", "ts": 5.0,
         "dur": 1.0, "pid": 0, "tid": 0}]}))
    b.write_text(json.dumps({"traceEvents": [
        {"name": "wire", "cat": "phase", "ph": "X", "ts": 1.0,
         "dur": 1.0, "pid": 1, "tid": 0}]}))
    out = tmp_path / "merged.json"
    assert scope_main(["merge", "-o", str(out), str(a), str(b)]) == 0
    events = _validate_chrome_trace(json.loads(out.read_text()))
    assert [e["pid"] for e in events] == [1, 0]  # re-sorted by ts
    assert "merged 2 events" in capsys.readouterr().out


# ----------------------------------------------------------------------
# master telemetry: heartbeats, skew, diagnosis
# ----------------------------------------------------------------------
def test_injected_hang_produces_master_diagnosis(monkeypatch):
    """Acceptance: one rank skips an allreduce -> the master names the
    stuck rank, its last collective, and its sequence-number lag,
    within the bounded peer timeout."""
    monkeypatch.setenv("MP4J_HEARTBEAT_SECS", "0.1")

    def fn(slave, r):
        arr = np.ones(64)
        slave.allreduce_array(arr, Operands.DOUBLE, Operators.SUM)
        if r == 2:
            time.sleep(3.0)   # skip the second allreduce entirely
            return None
        slave.allreduce_array(arr, Operands.DOUBLE, Operators.SUM)
        return None

    _, errors, log, _ = run_job(3, fn,
                                slave_kwargs={"peer_timeout": 1.0})
    # the healthy ranks' bounded waits expired (no new deadlock path)
    assert len(errors) == 2
    assert all(isinstance(e, Mp4jError) for _, e in errors)
    assert {r for r, _ in errors} == {0, 1}
    # ... and the master printed the cluster diagnosis
    assert "cluster diagnosis" in log
    assert re.search(r"rank 2: seq 1 \(lag 1\).*'allreduce_array'", log)
    assert "likely stuck rank(s): 2" in log
    # debounced: both healthy ranks report the same incident and the
    # repeat collapses to a single line. Since ISSUE 5 the exhausted
    # retry budget ALSO escalates to one terminal abort (its fan-out
    # logs its own diagnosis), so the full dump appears at most twice —
    # never once per reporting rank
    assert log.count("cluster diagnosis") <= 2
    assert "full diagnosis already logged above" in log
    assert "terminal abort" in log


def test_cluster_stats_skew(monkeypatch):
    monkeypatch.setenv("MP4J_HEARTBEAT_SECS", "0.1")

    def fn(slave, r):
        arr = np.ones(4096)
        for _ in range(3):
            slave.allreduce_array(arr, Operands.DOUBLE, Operators.SUM)
        slave.barrier()
        return None

    _, errors, _, master = run_job(2, fn)
    assert not errors
    skew = master.cluster_stats()
    assert "allreduce_array" in skew
    s = skew["allreduce_array"]
    assert s["ranks"] == 2 and s["calls"] == 3
    assert s["bytes"] > 0
    assert 0 <= s["busy_min"] <= s["busy_median"] <= s["busy_max"]
    assert set(s["stragglers"]) <= {0, 1}
    # the live table renders
    assert "allreduce_array" in master.format_cluster_stats()


def test_barrier_watchdog_diagnoses_stall(monkeypatch):
    monkeypatch.setenv("MP4J_HEARTBEAT_SECS", "0.1")

    def fn(slave, r):
        if r == 1:
            time.sleep(1.5)   # rank 0 waits at the barrier alone
        slave.barrier()
        return None

    _, errors, log, _ = run_job(
        2, fn, master_kwargs={"stall_timeout": 0.5})
    assert not errors          # watchdog logs, barrier still completes
    assert "stalled" in log and "waiting on ranks [1]" in log
    assert "cluster diagnosis" in log


def test_scope_report_cli(tmp_path, capsys):
    def snap(wire, nbytes):
        return {"allreduce_array": {
            "calls": 2, "bytes_sent": nbytes, "bytes_recv": nbytes,
            "chunks": 4, "wire_seconds": wire, "reduce_seconds": 0.1,
            "serialize_seconds": 0.0}}

    a = tmp_path / "s0.json"
    b = tmp_path / "s1.json"
    a.write_text(json.dumps(snap(0.2, 1000)))
    b.write_text(json.dumps({"rank": 1, "stats": snap(0.9, 1000)}))
    assert scope_main(["report", str(a), str(b)]) == 0
    out = capsys.readouterr().out
    assert "allreduce_array" in out and "stragglers" in out

    assert scope_main(["report", "--json", str(a), str(b)]) == 0
    skew = json.loads(capsys.readouterr().out)
    assert skew["allreduce_array"]["stragglers"] == [1]  # rank 1 slower
    assert skew["allreduce_array"]["busy_max"] == 1.0

    bad = tmp_path / "bad.json"
    bad.write_text("[1, 2]")
    assert scope_main(["report", str(bad)]) == 2


def test_render_diagnosis_no_telemetry():
    lines = telemetry.render_diagnosis({}, 4)
    assert any("no telemetry" in ln for ln in lines)


def test_render_diagnosis_never_heard_rank():
    table = {0: {"seq": 5, "current": None, "last": "barrier",
                 "phase": "wire", "current_secs": 0.0, "age": 0.2}}
    lines = "\n".join(telemetry.render_diagnosis(table, 2))
    assert "rank 1: NO heartbeat ever received" in lines
    assert "likely stuck rank(s): 1" in lines


# ----------------------------------------------------------------------
# log sink (satellite: timestamps, fixed-width prefix, level filter)
# ----------------------------------------------------------------------
def test_log_sink_format_and_level_filter(monkeypatch):
    monkeypatch.setenv("MP4J_LOG_LEVEL", "WARN")
    log = io.StringIO()
    m = Master(12, log_stream=log)
    try:
        m._log(3, "INFO", "dropped")
        m._log(3, "WARN", "kept")
        m._log("M", "ERROR", "master line")
    finally:
        m._server.close()
    out = log.getvalue()
    assert "dropped" not in out and "kept" in out
    # ISO-8601 timestamp + fixed-width [rank/size LEVEL] prefix
    assert re.search(
        r"^\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}\.\d{3} "
        r"\[ 3/12 WARN \] kept$", out, re.M)
    assert re.search(r"\[ M/12 ERROR\] master line$", out, re.M)


def test_log_level_env_validated(monkeypatch):
    monkeypatch.setenv("MP4J_LOG_LEVEL", "LOUD")
    with pytest.raises(Mp4jError):
        Master(1)
