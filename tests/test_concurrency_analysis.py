"""ISSUE 14 — whole-program concurrency analysis tests.

Covers the interprocedural substrate (callgraph index, lock model),
the three whole-program rules (R19 lock-order cycles, R20
blocking-under-lock, R21 callback/dispatch-under-lock) with
firing/non-firing/suppression grids — including a known-deadlock toy
module and the outbox-pattern negative case — plus the stale-baseline
strictness, the graph/explain/json CLI surfaces, and the regression
for the one true positive the pass found on the tree (the native
g++ build under the progression scheduler's condition variable).
"""

import textwrap

import pytest

from ytk_mp4j_tpu.analysis import baseline as baseline_mod
from ytk_mp4j_tpu.analysis import cli as cli_mod
from ytk_mp4j_tpu.analysis.engine import Engine, Program
from ytk_mp4j_tpu.analysis.rules import ALL_RULES, get_rules

COMM_PATH = "ytk_mp4j_tpu/comm/snippet.py"


def run_rule(rule_id, src, path=COMM_PATH, baseline=None):
    engine = Engine(rules=get_rules([rule_id]), baseline=baseline)
    result = engine.lint_source(textwrap.dedent(src), path)
    assert not [f for f in result.findings if f.rule == "E001"], \
        f"snippet failed to parse: {result.findings}"
    return result


def program_of(src, path=COMM_PATH):
    eng = Engine(rules=[])
    ctx, errs = eng._parse(textwrap.dedent(src), path)
    assert ctx is not None, errs
    return Program([ctx])


# ----------------------------------------------------------------------
# callgraph: index + conservative resolution
# ----------------------------------------------------------------------
def test_callgraph_resolves_self_methods_and_bases():
    idx = program_of("""
        class Base:
            def shared(self):
                return 1

        class C(Base):
            def run(self):
                self.helper()
                self.shared()

            def helper(self):
                pass
    """).index
    [mod] = idx.modules.values()
    c = mod.classes["C"]
    run = c.methods["run"]
    import ast
    calls = [n for n in ast.walk(run.node) if isinstance(n, ast.Call)]
    got = {idx.resolve_call(call, run)[0].display for call in calls}
    assert got == {"C.helper", "Base.shared"}


def test_callgraph_types_ctor_param_and_list_attrs():
    idx = program_of("""
        import threading

        class _Slot:
            def __init__(self):
                self.lock = threading.Lock()

        class Master:
            def __init__(self):
                self._slots: list[_Slot] = []
                self._lock = threading.Lock()

        class Controller:
            def __init__(self, master):
                self._master = master      # param-name heuristic
    """).index
    [mod] = idx.modules.values()
    master = mod.classes["Master"]
    assert idx.attr_type(master, "_slots").endswith(":_Slot") \
        and idx.attr_type(master, "_slots").startswith("list:")
    assert idx.attr_type(master, "_lock") == "threading.Lock"
    ctl = mod.classes["Controller"]
    assert idx.attr_type(ctl, "_master").endswith(":Master")


def test_callgraph_class_attr_method_binding():
    idx = program_of("""
        class V:
            def visit_A(self, n):
                return n
            visit_B = visit_A
    """).index
    [mod] = idx.modules.values()
    v = mod.classes["V"]
    assert v.methods["visit_B"] is v.methods["visit_A"]


def test_callgraph_unresolvable_contributes_no_edge():
    idx = program_of("""
        def f(x):
            x.mystery()         # unknown receiver
            unknown_fn()        # unknown function
    """).index
    [mod] = idx.modules.values()
    f = mod.functions["f"]
    import ast
    calls = [n for n in ast.walk(f.node) if isinstance(n, ast.Call)]
    assert all(idx.resolve_call(c, f) == [] for c in calls)


# ----------------------------------------------------------------------
# lock model: discovery, held sets, edges, witnesses
# ----------------------------------------------------------------------
def test_lockmodel_discovers_attr_module_and_local_locks():
    model = program_of("""
        import threading

        _mod_lock = threading.Lock()

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition()

            def local(self):
                lk = threading.Lock()
                with lk:
                    pass
    """).locks
    kinds = {d.display: d.kind for d in model.locks.values()}
    assert kinds["C._lock"] == "Lock"
    assert kinds["C._cv"] == "Condition"
    assert kinds["snippet._mod_lock"] == "Lock"
    assert any("<local:lk>" in k or "local" in d.attr
               for k, d in model.locks.items())


def test_lockmodel_with_nesting_builds_order_edge():
    model = program_of("""
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def f(self):
                with self._a:
                    with self._b:
                        pass
    """).locks
    [edge] = model.edges.values()
    assert model.locks[edge.src].display == "C._a"
    assert model.locks[edge.dst].display == "C._b"
    assert edge.chain == ("C.f",)


def test_lockmodel_interprocedural_edge_with_witness_chain():
    model = program_of("""
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def top(self):
                with self._a:
                    self.mid()

            def mid(self):
                self.bottom()

            def bottom(self):
                with self._b:
                    pass
    """).locks
    [edge] = model.edges.values()
    assert model.locks[edge.src].display == "C._a"
    assert model.locks[edge.dst].display == "C._b"
    assert edge.chain == ("C.top", "C.mid", "C.bottom")


def test_lockmodel_acquire_release_linear_tracking():
    model = program_of("""
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def f(self):
                self._a.acquire()
                with self._b:       # edge a -> b
                    pass
                self._a.release()
                with self._b:       # NOT under a anymore
                    pass
    """).locks
    assert len(model.edges) == 1


def test_lockmodel_closure_bodies_get_empty_held_set():
    # a thread-body closure defined inside a `with` does NOT inherit
    # the definition site's held locks
    model = program_of("""
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def f(self):
                with self._a:
                    def worker():
                        with self._b:
                            pass
    """).locks
    assert len(model.edges) == 0


def test_lockmodel_subscripted_receiver_resolves():
    model = program_of("""
        import threading

        class _Slot:
            def __init__(self):
                self.lock = threading.Lock()

        class M:
            def __init__(self):
                self._slots: list[_Slot] = []
                self._lock = threading.Lock()

            def push(self, r):
                with self._lock:
                    with self._slots[r].lock:
                        pass
    """).locks
    [edge] = model.edges.values()
    assert model.locks[edge.src].display == "M._lock"
    assert model.locks[edge.dst].display == "_Slot.lock"


TOY_DEADLOCK = """
    import threading

    class Master:
        def __init__(self):
            self._lock = threading.Lock()
            self._ctl = Controller(self)

        def status(self):
            with self._lock:
                return self._ctl.snapshot()

    class Controller:
        def __init__(self, master):
            self._lock = threading.Lock()
            self._master = master

        def snapshot(self):
            with self._lock:
                return 1

        def dispatch(self, ev):
            with self._lock:
                self._master.status()
"""


def test_lockmodel_cycle_detection_on_toy_deadlock():
    model = program_of(TOY_DEADLOCK).locks
    [scc] = model.cycles()
    names = {model.locks[k].display for k in scc}
    assert names == {"Master._lock", "Controller._lock"}


# ----------------------------------------------------------------------
# R19 — lock-order cycles
# ----------------------------------------------------------------------
def test_r19_fires_on_toy_deadlock_module():
    r = run_rule("R19", TOY_DEADLOCK)
    [f] = [f for f in r.findings if f.rule == "R19"]
    assert "Master._lock" in f.message
    assert "Controller._lock" in f.message
    assert "via" in f.message          # witness chains present


def test_r19_quiet_on_consistent_order():
    r = run_rule("R19", """
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def f(self):
                with self._a:
                    with self._b:
                        pass

            def g(self):
                with self._a:
                    with self._b:
                        pass
    """)
    assert not r.findings


def test_r19_cross_module_cycle(tmp_path):
    pkg = tmp_path / "ytk_mp4j_tpu" / "comm"
    pkg.mkdir(parents=True)
    (pkg / "a.py").write_text(textwrap.dedent("""
        import threading
        from ytk_mp4j_tpu.comm.b import B

        class A:
            def __init__(self):
                self._lock = threading.Lock()
                self._b = B(self)

            def fold(self):
                with self._lock:
                    self._b.peek()
    """))
    (pkg / "b.py").write_text(textwrap.dedent("""
        import threading

        class B:
            def __init__(self, a):
                self._lock = threading.Lock()
                self._a = a

            def peek(self):
                with self._lock:
                    return 1

            def push(self):
                with self._lock:
                    self._a.fold()
    """))
    eng = Engine(rules=get_rules(["R19"]))
    result = eng.lint_paths([str(tmp_path)])
    assert [f.rule for f in result.findings] == ["R19"]


def test_r19_inline_suppression():
    # the cycle is charged at the first witness edge's frame
    # (Controller.dispatch's call into the master); a directive on
    # that line accepts it
    src = TOY_DEADLOCK.replace(
        "self._master.status()",
        "self._master.status()  # mp4j-lint: disable=R19 (toy)")
    r = run_rule("R19", src)
    assert not [f for f in r.findings if f.rule == "R19"]
    assert any(f.rule == "R19" for f in r.suppressed)


def test_r19_baseline_suppression():
    bl = baseline_mod.parse(textwrap.dedent("""
        [[suppression]]
        rule = "R19"
        file = "ytk_mp4j_tpu/comm/snippet.py"
        reason = "toy"
    """))
    r = run_rule("R19", TOY_DEADLOCK, baseline=bl)
    assert not r.findings
    assert any(f.rule == "R19" for f in r.suppressed)


# ----------------------------------------------------------------------
# R20 — blocking under a held lock
# ----------------------------------------------------------------------
def test_r20_fires_on_direct_send_under_lock():
    r = run_rule("R20", """
        import threading

        class S:
            def __init__(self, chan):
                self._lock = threading.Lock()
                self._chan = chan

            def flush(self, obj):
                with self._lock:
                    self._chan.send_obj(obj)
    """)
    [f] = r.findings
    assert f.rule == "R20" and "send_obj" in f.message
    assert "S._lock" in f.message


def test_r20_fires_interprocedurally_with_chain():
    r = run_rule("R20", """
        import threading

        class S:
            def __init__(self, chan):
                self._lock = threading.Lock()
                self._chan = chan

            def flush(self, obj):
                with self._lock:
                    self._ship(obj)

            def _ship(self, obj):
                self._relay(obj)

            def _relay(self, obj):
                self._chan.send_obj(obj)
    """)
    [f] = r.findings
    assert "S.flush -> S._ship -> S._relay" in f.message
    assert f.context == "S.flush"      # charged at the held frame


def test_r20_fires_on_wait_on_other_object():
    r = run_rule("R20", """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._done = threading.Event()

            def stall(self):
                with self._lock:
                    self._done.wait()
    """)
    [f] = r.findings
    assert "wait" in f.message


def test_r20_quiet_on_wait_on_held_condition():
    # the house barrier pattern: cv.wait releases the cv
    r = run_rule("R20", """
        import threading

        class S:
            def __init__(self):
                self._cv = threading.Condition()

            def park(self):
                with self._cv:
                    self._cv.wait_for(lambda: True)
    """)
    assert not r.findings


def test_r20_fires_on_thread_join_and_subprocess():
    r = run_rule("R20", """
        import subprocess
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._thread = threading.Thread(target=print)

            def a(self):
                with self._lock:
                    self._thread.join()

            def b(self):
                with self._lock:
                    subprocess.run(["true"])
    """)
    assert len(r.findings) == 2


def test_r20_quiet_on_str_and_path_join():
    r = run_rule("R20", """
        import os
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def fmt(self, parts):
                with self._lock:
                    return ", ".join(parts) + os.path.join("a", "b")
    """)
    assert not r.findings


def test_r20_quiet_outside_lock():
    r = run_rule("R20", """
        import threading

        class S:
            def __init__(self, chan):
                self._lock = threading.Lock()
                self._chan = chan

            def flush(self, obj):
                with self._lock:
                    out = obj
                self._chan.send_obj(out)
    """)
    assert not r.findings


def test_r20_inline_suppression():
    r = run_rule("R20", """
        import threading

        class S:
            def __init__(self, chan):
                self._lock = threading.Lock()
                self._chan = chan

            def flush(self, obj):
                with self._lock:
                    # mp4j-lint: disable=R20 (send serialization lock)
                    self._chan.send_obj(obj)
    """)
    assert not r.findings
    assert any(f.rule == "R20" for f in r.suppressed)


def test_r20_quiet_outside_covered_dirs():
    r = run_rule("R20", """
        import threading

        class S:
            def __init__(self, chan):
                self._lock = threading.Lock()
                self._chan = chan

            def flush(self, obj):
                with self._lock:
                    self._chan.send_obj(obj)
    """, path="ytk_mp4j_tpu/models/snippet.py")
    assert not r.findings


# ----------------------------------------------------------------------
# R21 — callback/dispatch under the minting lock
# ----------------------------------------------------------------------
def test_r21_fires_on_hook_under_lock():
    r = run_rule("R21", """
        import threading

        class C:
            def __init__(self, on_alert):
                self._lock = threading.Lock()
                self._terminal_hook = on_alert

            def settle(self, ev):
                with self._lock:
                    self._terminal_hook(ev)
    """)
    [f] = r.findings
    assert "_terminal_hook" in f.message and "C._lock" in f.message


def test_r21_fires_on_hook_via_chain():
    r = run_rule("R21", """
        import threading

        class C:
            def __init__(self, cb):
                self._lock = threading.Lock()
                self._cb = cb

            def settle(self, ev):
                with self._lock:
                    self._fan(ev)

            def _fan(self, ev):
                self._cb(ev)
    """)
    [f] = r.findings
    assert "C.settle -> C._fan" in f.message


def test_r21_fires_on_reentrant_dispatch():
    r = run_rule("R21", """
        import threading

        class Ctl:
            def __init__(self, master):
                self._lock = threading.Lock()
                self._master = master

            def dispatch(self, ev):
                with self._lock:
                    self._master.push(ev)

            def status(self):
                with self._lock:
                    return 1

        class Master:
            def __init__(self):
                self._ctl = Ctl(self)

            def push(self, ev):
                self._ctl.status()
    """)
    assert any("re-acquires" in f.message and "Ctl._lock" in f.message
               for f in r.findings)


def test_r21_quiet_on_rlock_reentry():
    r = run_rule("R21", """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.RLock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    return 1
    """)
    assert not [f for f in r.findings if "re-acquires" in f.message]


def test_r21_quiet_on_outbox_pattern():
    # the PR 13 negative case: mint under the lock, dispatch outside
    r = run_rule("R21", """
        import threading

        class Ctl:
            def __init__(self, hook):
                self._lock = threading.Lock()
                self._hook = hook
                self._outbox = []

            def settle(self, ev):
                with self._lock:
                    self._outbox.append(ev)
                self._flush()

            def _flush(self):
                with self._lock:
                    out, self._outbox = self._outbox, []
                for ev in out:
                    self._hook(ev)
    """)
    assert not r.findings


def test_r21_inline_suppression():
    r = run_rule("R21", """
        import threading

        class C:
            def __init__(self, cb):
                self._lock = threading.Lock()
                self._cb = cb

            def settle(self, ev):
                with self._lock:
                    # mp4j-lint: disable=R21 (hook is a pure counter)
                    self._cb(ev)
    """)
    assert not r.findings
    assert any(f.rule == "R21" for f in r.suppressed)


# ----------------------------------------------------------------------
# stale-baseline strictness + prune
# ----------------------------------------------------------------------
STALE_BL = """
    [[suppression]]
    rule = "R1"
    file = "ytk_mp4j_tpu/comm/gone.py"
    context = "Gone.f"
    reason = "site was deleted two PRs ago"
"""


def _pkg_tree(tmp_path):
    """A throwaway tree whose linted paths cover the ytk_mp4j_tpu
    package segment (staleness is only judged for covered entries)."""
    pkg = tmp_path / "ytk_mp4j_tpu" / "comm"
    pkg.mkdir(parents=True)
    (pkg / "ok.py").write_text("def f():\n    return 1\n")
    return tmp_path


def test_stale_baseline_entry_is_finding_in_strict_mode(tmp_path):
    bl = baseline_mod.parse(textwrap.dedent(STALE_BL))
    eng = Engine(rules=get_rules(["R1"]), baseline=bl,
                 strict_baseline=True, baseline_path="bl.toml")
    result = eng.lint_paths([str(_pkg_tree(tmp_path))])
    [f] = result.findings
    assert f.rule == "B001" and "stale baseline entry" in f.message
    assert f.path == "bl.toml" and f.line == 2   # the entry's own line


def test_stale_baseline_quiet_without_strict(tmp_path):
    bl = baseline_mod.parse(textwrap.dedent(STALE_BL))
    eng = Engine(rules=get_rules(["R1"]), baseline=bl)
    assert eng.lint_paths([str(_pkg_tree(tmp_path))]).ok


def test_strict_partial_runs_cannot_condemn_out_of_scope_entries(
        tmp_path):
    """Code-review regression: a --select run (the entry's rule never
    ran) or a single-file run (the entry's file out of scope) must
    not flag entries it could not judge."""
    tree = _pkg_tree(tmp_path)
    bl = baseline_mod.parse(textwrap.dedent(STALE_BL))   # an R1 entry
    eng = Engine(rules=get_rules(["R2"]), baseline=bl,
                 strict_baseline=True, baseline_path="bl.toml")
    assert eng.lint_paths([str(tree)]).ok     # R1 never ran
    other = tmp_path / "standalone.py"
    other.write_text("def f():\n    return 1\n")
    eng = Engine(rules=get_rules(["R1"]), baseline=bl,
                 strict_baseline=True, baseline_path="bl.toml")
    assert eng.lint_paths([str(other)]).ok    # file out of scope


def test_prune_baseline_select_keeps_unjudged_entries(tmp_path):
    """Code-review regression: `--select R18 --prune-baseline` used to
    delete every entry whose rule did not run."""
    target = tmp_path / "bl.toml"
    tree = _pkg_tree(tmp_path)
    bad = tmp_path / "ytk_mp4j_tpu" / "comm" / "bad.py"
    bad.write_text("def f(c):\n    if c.rank:\n        c.barrier()\n")
    target.write_text(textwrap.dedent("""
        [[suppression]]
        rule = "R1"
        file = "ytk_mp4j_tpu/comm/bad.py"
        context = "f"
        reason = "live, but R1 will not run"
    """))
    rc = cli_mod.main([str(tree), "--baseline", str(target),
                       "--select", "R2", "--prune-baseline"])
    assert rc == 0
    assert 'reason = "live, but R1 will not run"' in target.read_text()


def test_prune_baseline_rewrites_keeping_reasons(tmp_path):
    target = tmp_path / "bl.toml"
    bad = tmp_path / "ytk_mp4j_tpu" / "comm" / "bad.py"
    bad.parent.mkdir(parents=True)
    bad.write_text("def f(c):\n    if c.rank:\n        c.barrier()\n")
    target.write_text(textwrap.dedent("""
        # header comment survives the rewrite

        [[suppression]]
        rule = "R1"
        file = "ytk_mp4j_tpu/comm/bad.py"
        context = "f"
        reason = "the live entry"
    """) + textwrap.dedent(STALE_BL))
    rc = cli_mod.main([str(tmp_path), "--baseline", str(target),
                       "--prune-baseline"])
    assert rc == 0
    text = target.read_text()
    assert "header comment survives" in text
    assert 'reason = "the live entry"' in text
    assert "gone.py" not in text
    # and the pruned baseline still suppresses the live finding
    rc = cli_mod.main([str(tmp_path), "--baseline", str(target),
                       "--strict"])
    assert rc == 0


# ----------------------------------------------------------------------
# CLI surfaces: --json, --explain, graph --dot
# ----------------------------------------------------------------------
def test_cli_json_flag(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("def f(c):\n    if c.rank:\n        c.barrier()\n")
    assert cli_mod.main([str(bad), "--json"]) == 1
    out = capsys.readouterr().out
    import json
    doc = json.loads(out)
    assert doc["findings"][0]["rule"] == "R1"


@pytest.mark.parametrize("cls", ALL_RULES,
                         ids=[c.rule_id for c in ALL_RULES])
def test_every_rule_example_fires(cls):
    """--explain's catalogue stays honest: each rule's example is a
    real firing case (program rules included, proving single-file
    mode runs them)."""
    assert cls.example, f"{cls.rule_id} has no example"
    eng = Engine(rules=[cls()])
    r = eng.lint_source(cls.example, cls.example_path)
    assert not [f for f in r.findings if f.rule == "E001"]
    assert any(f.rule == cls.rule_id for f in r.findings)


def test_cli_explain(capsys):
    assert cli_mod.main(["--explain", "R20"]) == 0
    out = capsys.readouterr().out
    assert "R20" in out and "firing example" in out and "fires:" in out
    assert cli_mod.main(["--explain", "R99"]) == 2


def test_cli_graph_dot(tmp_path, capsys):
    pkg = tmp_path / "ytk_mp4j_tpu" / "comm"
    pkg.mkdir(parents=True)
    (pkg / "mod.py").write_text(textwrap.dedent("""
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def f(self):
                with self._a:
                    with self._b:
                        pass
    """))
    assert cli_mod.main(["graph", str(tmp_path), "--dot"]) == 0
    out = capsys.readouterr().out
    assert out.startswith("digraph mp4j_lock_order")
    assert "C._a" in out and "C._b" in out and "C.f" in out


def test_cli_graph_text_reports_cycles(tmp_path, capsys):
    pkg = tmp_path / "ytk_mp4j_tpu" / "comm"
    pkg.mkdir(parents=True)
    (pkg / "mod.py").write_text(textwrap.dedent(TOY_DEADLOCK))
    assert cli_mod.main(["graph", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "1 cycle(s)" in out and "CYCLE:" in out


# ----------------------------------------------------------------------
# regression: the true positive R20 found on the tree
# ----------------------------------------------------------------------
def test_reduce_opcode_never_builds(monkeypatch):
    """PR 14's R20 true positive: `reduce_opcode` used to trigger the
    lazy native load — whose first call shells out to g++ — from
    under the progression scheduler's condition variable. It must now
    read the cached verdict only; the scheduler forces the one-time
    attempt at construction, outside any lock."""
    from ytk_mp4j_tpu.utils import native
    from ytk_mp4j_tpu.operators import Operators

    def boom():
        raise AssertionError("reduce_opcode must not trigger _load")

    monkeypatch.setattr(native, "_load", boom)
    # unattempted verdict: no native kernels, NO build attempt
    monkeypatch.setattr(native, "HAVE_NATIVE", None)
    monkeypatch.setattr(native, "_lib", None)
    assert native.reduce_opcode(Operators.SUM, "float32") is None
    # negative cached verdict: same
    monkeypatch.setattr(native, "HAVE_NATIVE", False)
    assert native.reduce_opcode(Operators.SUM, "float32") is None


def test_analysis_package_is_self_clean():
    """ISSUE 14 satellite: analysis/ itself is in the linted path set
    and passes every rule — the linter polices the linter."""
    import os

    import ytk_mp4j_tpu
    from ytk_mp4j_tpu.analysis import lint_paths

    pkg = os.path.join(os.path.dirname(ytk_mp4j_tpu.__file__),
                       "analysis")
    result = lint_paths([pkg])
    assert result.ok, "\n".join(f.format() for f in result.findings)
    # and the tier-1 gate really collects it (no skip list hides it)
    files = Engine.collect_files(
        [os.path.dirname(ytk_mp4j_tpu.__file__)])
    assert any(f.replace(os.sep, "/").endswith("analysis/locks.py")
               for f in files)


def test_lint_runtime_extra_within_budget():
    """ISSUE 14 satellite: the whole-program pass rides the tier-1
    gate, so its cost is tracked — and budgeted at <= 2x the per-file
    pass on this repo. ISSUE 16 tightens the marginal cost of the v3
    passes (R23 lockset + R24/R25 resources): <= 1.5x the v2 run,
    because they reuse v2's parsed index, call graph and lock
    summaries instead of re-walking the tree. min-of-2 reps: the
    legs run sequentially, so a load spike landing on one leg of a
    single rep skews the ratio; the min per leg absorbs it."""
    import bench

    doc = bench.bench_lint_runtime(reps=2)
    assert doc["lint_runtime_secs"] > 0
    assert doc["lint_perfile_secs"] > 0
    assert doc["lint_wholeprogram_ratio"] <= 2.0, doc
    assert doc["lint_v2_secs"] > 0
    assert doc["lint_v3_over_v2_ratio"] <= 1.5, doc


def test_ensure_loaded_matches_have_native():
    from ytk_mp4j_tpu.utils import native
    from ytk_mp4j_tpu.operators import Operators

    ok = native.ensure_loaded()
    assert ok is bool(native.HAVE_NATIVE)
    if ok:
        # with the verdict cached, reduce_opcode serves codes again
        assert native.reduce_opcode(Operators.SUM, "float32") \
            is not None
