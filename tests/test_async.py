"""mp4j-async (ISSUE 11): nonblocking collectives + the helper-thread
communication scheduler.

The futures-conformance grid proves ``i*().wait()`` == the blocking
twin BIT-FOR-BIT across all numeric operands x SUM/MAX/MIN/PROD x
n in {2, 3, 5} on all four backends (socket engine + inline paths,
thread, tpu, distributed), plus: future semantics (epoch tags,
timeouts, error delivery, wait_all as the collective-boundary drain),
the count-negotiated map coalescing (``allreduce_map_multi``: ragged
offers converge on min, columnar and negotiated-pickle fusion both
bit-exact, de-fuse leftovers), the new ``comm.stats()`` counters
(outstanding_peak / coalesced_frames / overlap seconds) with analytic
attribution, the ``mp4j_outstanding_collectives`` gauge + ``ovl%``
live column, audit verify mode staying green (zero false divergences)
over the async grid, and the async knob validation.
"""

import io
import os
import threading
import time

import numpy as np
import pytest

from helpers import run_slaves
from ytk_mp4j_tpu.comm import progress as progress_mod
from ytk_mp4j_tpu.comm.master import Master
from ytk_mp4j_tpu.comm.process_comm import ProcessCommSlave
from ytk_mp4j_tpu.comm.thread_comm import ThreadCommSlave
from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.obs import metrics as metrics_mod
from ytk_mp4j_tpu.obs import telemetry
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operators
from ytk_mp4j_tpu.utils import tuning

NUMERIC = [Operands.DOUBLE, Operands.FLOAT, Operands.INT,
           Operands.LONG, Operands.SHORT, Operands.BYTE]
OPS = [Operators.SUM, Operators.MAX, Operators.MIN, Operators.PROD]
JOIN = 60.0


def _inputs(n, length, operand, rng):
    if operand.dtype.kind == "f":
        return [rng.standard_normal(length).astype(operand.dtype)
                for _ in range(n)]
    # values in {1, 2}: PROD over 5 ranks stays within every int width
    return [rng.integers(1, 3, length).astype(operand.dtype)
            for _ in range(n)]


# ----------------------------------------------------------------------
# futures-conformance grid: socket backend (engine + inline paths)
# ----------------------------------------------------------------------
@pytest.mark.parametrize("n", [2, 3, 5])
def test_socket_conformance_grid(n):
    """i*().wait() == blocking, bit for bit, across every numeric
    operand x operator on small payloads (the inline path: tree-algo
    sizes run the blocking engine on the progression thread)."""
    rng = np.random.default_rng(7)
    cases = [(operand, op, _inputs(n, 1200, operand, rng))
             for operand in NUMERIC for op in OPS]

    def blocking(slave, r):
        outs = []
        for operand, op, data in cases:
            a = data[r].copy()
            slave.allreduce_array(a, operand, op)
            outs.append(a)
        return outs

    def asyncb(slave, r):
        futs = []
        arrs = []
        for operand, op, data in cases:
            a = data[r].copy()
            arrs.append(a)
            futs.append(slave.iallreduce(a, operand, op))
        slave.wait_all()
        for f, a in zip(futs, arrs):
            assert f.done()
            assert f.wait() is a
        return arrs

    want = run_slaves(n, blocking, timeout=JOIN)
    got = run_slaves(n, asyncb, timeout=JOIN)
    for r in range(n):
        for k in range(len(cases)):
            np.testing.assert_array_equal(got[r][k], want[r][k])


@pytest.mark.parametrize("n", [2, 3, 5])
def test_socket_engine_grid_bit_exact(n):
    """The interleaved raw engine (rhd / ring schedules, gather) at
    engine-eligible sizes, several futures outstanding at once —
    bit-exact against the blocking path, all four i* families."""
    rng = np.random.default_rng(8)
    data = [rng.standard_normal(150_000) for _ in range(n)]

    def blocking(slave, r):
        rhd = data[r].copy()
        slave.allreduce_array(rhd, Operands.DOUBLE, Operators.SUM)
        ring = data[r].copy()
        slave.allreduce_array(ring, Operands.DOUBLE, Operators.SUM,
                              algo="ring")
        rs = data[r].copy()
        slave.reduce_scatter_array(rs, Operands.DOUBLE, Operators.SUM)
        ag = data[r].copy()
        slave.allgather_array(ag, Operands.DOUBLE)
        g = data[r].copy()
        slave.gather_array(g, Operands.DOUBLE, root=n - 1)
        return rhd, ring, rs, ag, g

    def asyncb(slave, r):
        rhd = data[r].copy()
        ring = data[r].copy()
        rs = data[r].copy()
        ag = data[r].copy()
        g = data[r].copy()
        futs = [
            slave.iallreduce(rhd, Operands.DOUBLE, Operators.SUM),
            slave.iallreduce(ring, Operands.DOUBLE, Operators.SUM,
                             algo="ring"),
            slave.ireduce_scatter(rs, Operands.DOUBLE, Operators.SUM),
            slave.iallgather(ag, Operands.DOUBLE),
            slave.igather(g, Operands.DOUBLE, root=n - 1),
        ]
        slave.wait_all()
        assert all(f.done() for f in futs)
        return rhd, ring, rs, ag, g

    want = run_slaves(n, blocking, timeout=JOIN)
    got = run_slaves(n, asyncb, timeout=JOIN)
    for r in range(n):
        for k in range(5):
            np.testing.assert_array_equal(got[r][k], want[r][k])


def test_socket_map_conformance():
    """iallreduce_map (coalesced AND classic) == allreduce_map, bit
    for bit, including operator variety and string keys."""
    def mk(r, tag):
        return {f"{tag}{k}": np.float64((r + 1) * (k + 1))
                for k in range(40)}

    def blocking(slave, r):
        outs = []
        for i, op in enumerate(OPS):
            d = mk(r, f"b{i}_")
            slave.allreduce_map(d, Operands.DOUBLE, op)
            outs.append(d)
        return outs

    def asyncb(slave, r):
        ds = [mk(r, f"b{i}_") for i in range(len(OPS))]
        futs = [slave.iallreduce_map(d, Operands.DOUBLE, op)
                for d, op in zip(ds, OPS)]
        slave.wait_all()
        [f.wait() for f in futs]
        return ds

    want = run_slaves(3, blocking, timeout=JOIN)
    prior = os.environ.get("MP4J_COALESCE_USECS")
    try:
        os.environ["MP4J_COALESCE_USECS"] = "300"
        got = run_slaves(3, asyncb, timeout=JOIN)
    finally:
        if prior is None:
            os.environ.pop("MP4J_COALESCE_USECS", None)
        else:
            os.environ["MP4J_COALESCE_USECS"] = prior
    got_off = run_slaves(3, asyncb, timeout=JOIN)
    for got_one in (got, got_off):
        for r in range(3):
            for k in range(len(OPS)):
                assert set(got_one[r][k]) == set(want[r][k])
                for key in want[r][k]:
                    assert got_one[r][k][key] == want[r][k][key]


def test_eager_mode_conformance():
    """MP4J_ASYNC=0 (the frozen-leg pin): i* executes eagerly on the
    caller thread behind the same future contract."""
    rng = np.random.default_rng(9)
    data = [rng.standard_normal(5000) for _ in range(3)]

    def blocking(slave, r):
        a = data[r].copy()
        slave.allreduce_array(a, Operands.DOUBLE, Operators.SUM)
        return a

    def asyncb(slave, r):
        a = data[r].copy()
        fut = slave.iallreduce(a, Operands.DOUBLE, Operators.SUM)
        assert fut.done()        # eager: resolved at submit
        return fut.wait()

    want = run_slaves(3, blocking, timeout=JOIN)
    got = run_slaves(3, asyncb, timeout=JOIN,
                     async_collectives=False)
    for r in range(3):
        np.testing.assert_array_equal(got[r], want[r])


# ----------------------------------------------------------------------
# the other three backends (eager / device-pipelined futures)
# ----------------------------------------------------------------------
@pytest.mark.parametrize("n", [2, 3, 5])
def test_thread_backend_conformance(n):
    rng = np.random.default_rng(10)
    cases = [(operand, op, _inputs(n, 600, operand, rng))
             for operand in NUMERIC for op in OPS]
    group = ThreadCommSlave.spawn_group(n)
    want = [[None] * len(cases) for _ in range(n)]
    got = [[None] * len(cases) for _ in range(n)]

    def worker(slave, t):
        for k, (operand, op, data) in enumerate(cases):
            a = data[t].copy()
            slave.allreduce_array(a, operand, op)
            want[t][k] = a
            b = data[t].copy()
            fut = slave.iallreduce(b, operand, op)
            got[t][k] = fut.wait()
            assert fut.done()
        slave.wait_all()         # no-op drain, kept for portability
        d = {k: np.float64(t + k) for k in range(20)}
        e = dict(d)
        slave.allreduce_map(d, Operands.DOUBLE, Operators.SUM)
        out = slave.iallreduce_map(e, Operands.DOUBLE,
                                   Operators.SUM).wait()
        assert out == d

    threads = [threading.Thread(target=worker, args=(s, t),
                                daemon=True)
               for t, s in enumerate(group)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(JOIN)
        assert not t.is_alive()
    for t in range(n):
        for k in range(len(cases)):
            np.testing.assert_array_equal(got[t][k], want[t][k])


@pytest.mark.parametrize("n", [2, 3, 5])
def test_tpu_backend_conformance(n):
    from ytk_mp4j_tpu.comm.tpu_comm import TpuCommCluster

    cluster = TpuCommCluster(n)
    rng = np.random.default_rng(11)
    for operand in (Operands.DOUBLE, Operands.FLOAT, Operands.INT,
                    Operands.LONG):
        for op in OPS:
            data = _inputs(n, 400, operand, rng)
            want = [d.copy() for d in data]
            cluster.allreduce_array(want, operand, op)
            got = [d.copy() for d in data]
            fut = cluster.iallreduce(got, operand, op)
            fut.wait()           # driver mode mutates `got` in place
            for r in range(n):
                np.testing.assert_array_equal(got[r], want[r])
    # the device map twin rides the chained-dispatch PendingMap
    maps_w = [{k: np.float64(r + k) for k in range(30)}
              for r in range(n)]
    maps_g = [dict(m) for m in maps_w]
    cluster.allreduce_map(maps_w, Operands.DOUBLE, Operators.SUM)
    fut = cluster.iallreduce_map(maps_g, Operands.DOUBLE,
                                 Operators.SUM)
    assert not fut.done()        # fetch+decode deferred to wait()
    fut.wait()
    assert maps_g == maps_w
    cluster.wait_all()


def test_distributed_backend_conformance():
    from ytk_mp4j_tpu.comm import distributed as dist_mod

    comm = dist_mod.DistributedComm()
    try:
        rng = np.random.default_rng(12)
        for operand in (Operands.DOUBLE, Operands.INT):
            for op in OPS:
                data = _inputs(comm.slave_num, 300, operand, rng)
                a = data[comm.rank].copy()
                comm.allreduce_array(a, operand, op)
                b = data[comm.rank].copy()
                fut = comm.iallreduce(b, operand, op)
                np.testing.assert_array_equal(fut.wait(), a)
        d = {k: np.float64(k) for k in range(20)}
        e = dict(d)
        comm.allreduce_map(d, Operands.DOUBLE, Operators.SUM)
        assert comm.iallreduce_map(e, Operands.DOUBLE,
                                   Operators.SUM).wait() == d
        comm.wait_all()
    finally:
        comm.close(0)


# ----------------------------------------------------------------------
# future semantics
# ----------------------------------------------------------------------
def test_future_semantics_and_boundary_drain():
    def fn(slave, r):
        a = np.ones(150_000)
        fut = slave.iallreduce(a, Operands.DOUBLE, Operators.SUM)
        assert fut.op == "allreduce_array"
        assert fut.epoch == 0          # the submit epoch rides along
        out = fut.wait(timeout=JOIN)
        assert out is a and fut.exception() is None
        # blocking collectives drain outstanding futures first: the
        # blocking result must order after the async one
        b = np.ones(150_000)
        slave.iallreduce(b, Operands.DOUBLE, Operators.SUM)
        c = np.ones(1000)
        slave.allreduce_array(c, Operands.DOUBLE, Operators.SUM)
        assert slave.outstanding() == 0   # the drain happened
        # a validation failure is delivered at wait(), not swallowed
        bad = np.ones((10, 10))
        fbad = slave.iallreduce(bad, Operands.DOUBLE, Operators.SUM)
        with pytest.raises(Mp4jError):
            fbad.wait(timeout=JOIN)
        assert isinstance(fbad.exception(), Mp4jError)
        # barrier is also a drain point
        d = np.ones(2000)
        slave.iallreduce(d, Operands.DOUBLE, Operators.SUM)
        slave.barrier()
        assert slave.outstanding() == 0
        np.testing.assert_array_equal(d, 3 * np.ones(2000))
        return True

    assert all(run_slaves(3, fn, timeout=JOIN))


def test_wait_all_reraises_unawaited_failure():
    def fn(slave, r):
        bad = np.ones((4, 4))
        slave.iallreduce(bad, Operands.DOUBLE, Operators.SUM)
        with pytest.raises(Mp4jError):
            slave.wait_all()
        # the failure was delivered; a second drain is clean
        slave.wait_all()
        return True

    assert all(run_slaves(2, fn, timeout=JOIN))


def test_future_wait_timeout_does_not_consume():
    fut = progress_mod.CollectiveFuture("allreduce_array")
    with pytest.raises(Mp4jError, match="not complete"):
        fut.wait(timeout=0.01)
    fut._resolve("x")
    assert fut.wait(timeout=0.01) == "x"


# ----------------------------------------------------------------------
# the fused map collective (count negotiation)
# ----------------------------------------------------------------------
def test_multi_ragged_offers_converge_on_min():
    """Ranks offering different batch depths negotiate m = min and
    stay in lockstep over successive calls; every map's result is
    bit-identical to its own allreduce_map."""
    def mk(r, i):
        return {int(k + 100 * i): np.float64((r + 1) * (k + 1))
                for k in range(25)}

    def blocking(slave, r):
        outs = []
        for i in range(3):
            d = mk(r, i)
            slave.allreduce_map(d, Operands.DOUBLE, Operators.SUM)
            outs.append(d)
        return outs

    def fused(slave, r):
        ds = [mk(r, i) for i in range(3)]
        if r == 0:
            m1 = slave.allreduce_map_multi([ds[0]], Operands.DOUBLE,
                                           Operators.SUM)
            assert m1 == 1
            m2 = slave.allreduce_map_multi(ds[1:], Operands.DOUBLE,
                                           Operators.SUM)
            assert m2 == 2
        else:
            m1 = slave.allreduce_map_multi(list(ds), Operands.DOUBLE,
                                           Operators.SUM)
            assert m1 == 1          # min over offers (rank 0 offered 1)
            # un-merged maps were left untouched
            assert ds[1] == mk(r, 1)
            m2 = slave.allreduce_map_multi(ds[1:], Operands.DOUBLE,
                                           Operators.SUM)
            assert m2 == 2
        return ds

    want = run_slaves(3, blocking, timeout=JOIN)
    got = run_slaves(3, fused, timeout=JOIN)
    for r in range(3):
        for i in range(3):
            assert got[r][i] == want[r][i]


def test_multi_negotiated_pickle_fallback_and_nop():
    """A batch whose maps cannot ride the columnar plane (mixed key
    kinds) fuses over the negotiated pickled plane; an all-empty batch
    negotiates a nop."""
    def mk(r):
        return {1: np.float64(r + 1), "s": np.float64(2 * r)}

    def blocking(slave, r):
        d = mk(r)
        slave.allreduce_map(d, Operands.DOUBLE, Operators.SUM)
        return d

    def fused(slave, r):
        ds = [mk(r), mk(r)]
        m = slave.allreduce_map_multi(ds, Operands.DOUBLE,
                                      Operators.SUM)
        assert m == 2
        empties = [{}, {}]
        assert slave.allreduce_map_multi(
            empties, Operands.DOUBLE, Operators.SUM) == 2
        assert empties == [{}, {}]
        return ds

    want = run_slaves(3, blocking, timeout=JOIN)
    got = run_slaves(3, fused, timeout=JOIN)
    for r in range(3):
        assert got[r][0] == want[r] and got[r][1] == want[r]


def test_multi_rejects_garbage():
    def fn(slave, r):
        with pytest.raises(Mp4jError, match="non-empty list"):
            slave.allreduce_map_multi([], Operands.DOUBLE,
                                      Operators.SUM)
        with pytest.raises(Mp4jError, match="non-empty list"):
            slave.allreduce_map_multi({}, Operands.DOUBLE,
                                      Operators.SUM)
        return True

    assert all(run_slaves(1, fn, timeout=JOIN))


# ----------------------------------------------------------------------
# stats / metrics / live view (analytic attribution)
# ----------------------------------------------------------------------
def test_async_stats_counters_analytic():
    K = 6

    def fn(slave, r):
        bufs = [np.ones(150_000) for _ in range(K)]
        futs = [slave.iallreduce(b, Operands.DOUBLE, Operators.SUM)
                for b in bufs]
        slave.wait_all()
        [f.wait() for f in futs]
        return slave.stats(), slave._comm_stats.metrics.snapshot()

    out = run_slaves(3, fn, timeout=JOIN)
    for st, mets in out:
        asy = st["<async>"]
        # peak: the submit loop outruns the engine on this host, but
        # whatever the race, the peak is within [1, K] and the delta
        # algebra kept it monotone
        assert 1 <= asy["outstanding_peak"] <= K
        assert asy["async_inflight"] > 0.0
        assert 0.0 <= asy["async_overlap"] <= asy["async_inflight"]
        # every engine collective booked calls + wire on its family
        fam = st["allreduce_array"]
        assert fam["calls"] == K
        assert fam["bytes_sent"] > 0 and fam["bytes_recv"] > 0
        # the outstanding gauge exists and is back to 0 at the drain
        assert mets["gauges"]["async/outstanding"] == 0.0


def test_coalesced_frames_counter_and_keys():
    MAPS, KEYS = 12, 10

    def fn(slave, r):
        ds = [{k + 100 * i: np.float64(r + 1) for k in range(KEYS)}
              for i in range(MAPS)]
        futs = [slave.iallreduce_map(d, Operands.DOUBLE,
                                     Operators.SUM) for d in ds]
        slave.wait_all()
        [f.wait() for f in futs]
        return slave.stats()

    prior = os.environ.get("MP4J_COALESCE_USECS")
    try:
        os.environ["MP4J_COALESCE_USECS"] = "400"
        out = run_slaves(3, fn, timeout=JOIN)
    finally:
        if prior is None:
            os.environ.pop("MP4J_COALESCE_USECS", None)
        else:
            os.environ["MP4J_COALESCE_USECS"] = prior
    for st in out:
        multi = st["allreduce_map_multi"]
        assert multi["coalesced_frames"] >= 1
        # keys: every map entry encoded columnar exactly once
        assert multi["keys"] == MAPS * KEYS
        assert multi["calls"] < MAPS     # fusion actually fused


def test_live_view_ovl_column_and_prometheus_gauge():
    doc = {
        "slave_num": 2, "window_secs": 60.0,
        "cluster": {"rates": {}, "stats": {}},
        "ranks": {
            "0": {"progress": {"seq": 4}, "age": 0.1,
                  "rates": {"bytes_per_sec": 1e6},
                  "gauges": {"async/outstanding": 3.0},
                  "stats": {"<async>": {"async_inflight": 2.0,
                                        "async_overlap": 1.0}}},
            "1": {"progress": {"seq": 4}, "age": 0.1, "rates": {},
                  "stats": {}},
        },
    }
    live = telemetry.format_live(doc)
    assert "ovl%" in live
    row0 = next(ln for ln in live.splitlines()
                if ln.strip().startswith("0"))
    assert "50" in row0              # 1.0 / 2.0 overlap fraction
    row1 = next(ln for ln in live.splitlines()
                if ln.strip().startswith("1"))
    assert row1.split()[6] == "-"    # no async work -> no ovl%
    prom = metrics_mod.to_prometheus(doc)
    assert 'mp4j_outstanding_collectives{rank="0"} 3' in prom
    assert 'mp4j_outstanding_collectives{rank="cluster"} 3' in prom


# ----------------------------------------------------------------------
# audit verify mode stays green over the async grid
# ----------------------------------------------------------------------
@pytest.mark.parametrize("shm", [True, False])
def test_audit_verify_green_on_async_grid(shm):
    """The acceptance grid: engine batches + coalesced maps under
    MP4J_AUDIT=verify — every seq cross-rank verified, ZERO false
    divergences (per-collective wire folds stay exact whatever the
    local interleaving). shm=False matters: the thread harness
    co-locates ranks, and only the all-TCP run exercises the engine's
    at-wire-time folds (the round-13 drive caught post-hoc send folds
    reading buffers later rounds had overwritten)."""
    n = 4
    log = io.StringIO()
    master = Master(n, timeout=JOIN, log_stream=log).serve_in_thread()
    results = [None] * n
    errors: list = [None] * n
    rng = np.random.default_rng(13)
    data = [rng.standard_normal(150_000) for _ in range(n)]

    def fn(slave, r):
        n_coll = 0
        futs = [slave.iallreduce(data[r].copy() * (k + 1),
                                 Operands.DOUBLE, Operators.SUM)
                for k in range(4)]
        slave.wait_all()
        [f.wait() for f in futs]
        n_coll += 4
        ds = [{int(k + 50 * i): np.float64((r + 1) * (k + 1))
               for k in range(30)} for i in range(5)]
        mfuts = [slave.iallreduce_map(d, Operands.DOUBLE,
                                      Operators.SUM) for d in ds]
        slave.wait_all()
        [f.wait() for f in mfuts]
        # the fused plane consumes one ordinal per negotiated batch;
        # read the actual count from the schedule position
        return slave.progress()["seq"]

    def worker(i):
        slave = None
        try:
            slave = ProcessCommSlave(
                "127.0.0.1", master.port, timeout=JOIN,
                audit="verify", dead_rank_secs=20.0, shm=shm)
            results[slave.rank] = fn(slave, slave.rank)
            time.sleep(1.2)      # two heartbeats: deltas reach master
            slave.close(0)
        except Exception as e:
            errors[i] = e

    prior = os.environ.get("MP4J_COALESCE_USECS")
    try:
        os.environ["MP4J_COALESCE_USECS"] = "300"
        threads = [threading.Thread(target=worker, args=(i,),
                                    daemon=True) for i in range(n)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + JOIN
        for t in threads:
            t.join(max(0.1, deadline - time.monotonic()))
        assert not any(t.is_alive() for t in threads), log.getvalue()
    finally:
        if prior is None:
            os.environ.pop("MP4J_COALESCE_USECS", None)
        else:
            os.environ["MP4J_COALESCE_USECS"] = prior
    master.join(10.0)
    assert all(e is None for e in errors), (errors, log.getvalue())
    st = master.audit_status()
    assert st["divergences"] == 0, (st, log.getvalue())
    assert st["verified_seq"] > 0, st


# ----------------------------------------------------------------------
# knob validation
# ----------------------------------------------------------------------
def test_async_knob_validation(monkeypatch):
    monkeypatch.setenv("MP4J_ASYNC", "2")
    with pytest.raises(Mp4jError, match="MP4J_ASYNC"):
        tuning.async_enabled()
    monkeypatch.setenv("MP4J_ASYNC", "0")
    assert tuning.async_enabled() is False
    monkeypatch.delenv("MP4J_ASYNC")
    assert tuning.async_enabled() is True

    monkeypatch.setenv("MP4J_COALESCE_USECS", "-5")
    with pytest.raises(Mp4jError, match="MP4J_COALESCE_USECS"):
        tuning.coalesce_usecs()
    monkeypatch.setenv("MP4J_COALESCE_USECS", "250")
    assert tuning.coalesce_usecs() == 250

    monkeypatch.setenv("MP4J_MAX_OUTSTANDING", "0")
    with pytest.raises(Mp4jError, match="MP4J_MAX_OUTSTANDING"):
        tuning.max_outstanding()
    monkeypatch.setenv("MP4J_MAX_OUTSTANDING", "8")
    assert tuning.max_outstanding() == 8


def test_max_outstanding_backpressure():
    def fn(slave, r):
        bufs = [np.ones(150_000) for _ in range(6)]
        futs = [slave.iallreduce(b, Operands.DOUBLE, Operators.SUM)
                for b in bufs]
        slave.wait_all()
        [f.wait() for f in futs]
        st = slave.stats()["<async>"]
        # the cap bounded concurrency: the peak can never exceed it
        assert st["outstanding_peak"] <= 2
        return True

    prior = os.environ.get("MP4J_MAX_OUTSTANDING")
    try:
        os.environ["MP4J_MAX_OUTSTANDING"] = "2"
        assert all(run_slaves(3, fn, timeout=JOIN))
    finally:
        if prior is None:
            os.environ.pop("MP4J_MAX_OUTSTANDING", None)
        else:
            os.environ["MP4J_MAX_OUTSTANDING"] = prior


def test_eager_mode_wait_all_reraises_unawaited_failure():
    """MP4J_ASYNC=0: the drain's re-raise contract must not depend on
    the knob — an eager failure nobody awaited surfaces at
    wait_all()."""
    def fn(slave, r):
        bad = np.ones((4, 4))
        slave.iallreduce(bad, Operands.DOUBLE, Operators.SUM)
        with pytest.raises(Mp4jError):
            slave.wait_all()
        slave.wait_all()         # delivered once; second drain clean
        f2 = slave.iallreduce(np.ones((2, 2)), Operands.DOUBLE,
                              Operators.SUM)
        with pytest.raises(Mp4jError):
            f2.wait()
        slave.wait_all()         # observed at wait(): nothing to raise
        return True

    assert all(run_slaves(2, fn, timeout=JOIN,
                          async_collectives=False))
