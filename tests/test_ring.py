"""Hand-scheduled ppermute ring collectives (ops/ring.py) —
differential tests against the one-op XLA path on the virtual mesh."""

from functools import partial

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.operators import Operators
from ytk_mp4j_tpu.ops import ring
from ytk_mp4j_tpu.parallel import make_mesh


def _run(mesh, fn, data):
    """data: [n, L] — one row per member; fn runs per shard."""
    @partial(jax.shard_map, mesh=mesh, in_specs=P("mp4j"),
             out_specs=P("mp4j"))
    def wrapped(x):
        return fn(x[0])[None]

    return np.asarray(jax.jit(wrapped)(jnp.asarray(data)))


@pytest.mark.parametrize("n", [2, 4, 8])
@pytest.mark.parametrize("op_name", ["SUM", "MAX"])
def test_ring_allreduce_matches_psum(rng, n, op_name):
    mesh = make_mesh(n)
    op = Operators.by_name(op_name)
    L = 6 * n
    data = rng.standard_normal((n, L)).astype(np.float32)
    out = _run(mesh, lambda x: ring.ring_allreduce(x, op, "mp4j"), data)
    want = (np.sum(data, 0) if op_name == "SUM" else np.max(data, 0))
    for r in range(n):
        np.testing.assert_allclose(out[r], want, rtol=1e-5, atol=1e-6)


def test_ring_reduce_scatter_layout(rng):
    n, L = 4, 8
    mesh = make_mesh(n)
    data = rng.standard_normal((n, L)).astype(np.float32)
    out = _run(mesh,
               lambda x: ring.ring_reduce_scatter(x, Operators.SUM,
                                                  "mp4j"), data)
    want = np.sum(data, 0).reshape(n, L // n)
    for r in range(n):
        np.testing.assert_allclose(out[r], want[(r + 1) % n], rtol=1e-5)


def test_ring_allgather(rng):
    n, L = 4, 3
    mesh = make_mesh(n)
    data = rng.standard_normal((n, L)).astype(np.float32)
    out = _run(mesh, lambda x: ring.ring_allgather(x, "mp4j"), data)
    want = data.reshape(-1)
    for r in range(n):
        np.testing.assert_allclose(out[r], want, rtol=1e-6)


def test_ring_requires_divisible_length():
    mesh = make_mesh(4)
    data = np.ones((4, 7), np.float32)
    with pytest.raises(Mp4jError):
        _run(mesh, lambda x: ring.ring_allreduce(x, Operators.SUM,
                                                 "mp4j"), data)


def test_ring_single_member_noop(rng):
    mesh = make_mesh(1)
    data = rng.standard_normal((1, 6)).astype(np.float32)
    out = _run(mesh, lambda x: ring.ring_allreduce(x, Operators.SUM,
                                                   "mp4j"), data)
    np.testing.assert_array_equal(out[0], data[0])
