"""bench.py must stay runnable — the driver executes it at round end,
so an API drift that breaks it would lose the round's headline number.
Toy-sized smoke runs on the CPU test rig."""

import numpy as np

import bench


def test_bench_tpu_smoke():
    gbs, tps, n_chips, fps, hist_fps = bench.bench_tpu(
        n=512, f=4, b=256, depth=2, trees=1)
    assert np.isfinite(gbs) and gbs > 0
    assert np.isfinite(tps) and tps > 0
    assert n_chips >= 1
    assert fps is None or fps > 0          # MFU numerator (best-effort)
    assert np.isfinite(hist_fps) and hist_fps > 0
    # analytic count: level 0 full + sibling-subtracted level 1
    assert bench.gbdt_hist_mxu_flops(512, 4, 256, 2) == (
        2.0 * 512 * 4 * (1 + 1) * 256 * 4)


def test_bench_device_paths_smoke():
    steps, fps = bench.bench_ffm_tpu(n=64, n_features=128, n_fields=2,
                                     k=2, max_nnz=2, steps=1)
    assert np.isfinite(steps) and steps > 0
    assert fps is None or fps > 0
    rate = bench.bench_device_map_chained(keys=64, chain=2)
    assert np.isfinite(rate) and rate > 0
    rows = bench.bench_libsvm_reader(rows=256, chunk_rows=128)
    assert np.isfinite(rows) and rows > 0
    e2e = bench.bench_ffm_stream_text(chunks=2, rows=64)
    assert np.isfinite(e2e) and e2e > 0


def _check_socket_stats(stats):
    """Every socket workload emits the merged cross-rank comm.stats()
    snapshot; it must be JSON-ready and carry real wire traffic."""
    import json

    assert stats and json.dumps(stats)
    total_wire = sum(e.get("bytes_sent", 0) + e.get("bytes_recv", 0)
                     for e in stats.values())
    assert total_wire > 0


def test_bench_socket_smoke():
    gbs, coll, stats = bench.bench_socket(n=400, f=4, b=8, depth=2,
                                          procs=2)
    assert np.isfinite(gbs) and gbs > 0
    assert np.isfinite(coll) and coll > 0
    _check_socket_stats(stats)
    assert "allreduce_array" in stats


def test_bench_socket_collective_smoke():
    rate, stats = bench.bench_socket_collective(f=4, b=8, depth=2,
                                                procs=2, reps=1)
    assert np.isfinite(rate) and rate > 0
    _check_socket_stats(stats)


def test_bench_socket_map_smoke():
    rate, stats = bench.bench_socket_map(procs=2, keys=50, reps=1)
    assert np.isfinite(rate) and rate > 0
    _check_socket_stats(stats)
    assert "allreduce_map" in stats


def test_bench_socket_allreduce_sweep_smoke():
    sweep, stats = bench.bench_socket_allreduce_sweep(procs=2, reps=1)
    assert sweep, "sweep must report at least one size"
    for row in sweep.values():
        assert set(row) == {"tree", "rhd", "ring", "auto"}
        for rate in row.values():
            assert np.isfinite(rate) and rate > 0
    _check_socket_stats(stats)


def test_bench_socket_map_sweep_smoke():
    sweep, stats = bench.bench_socket_map_sweep(procs=2, sizes=(40,),
                                                reps=1)
    assert set(sweep) == {"40"}
    for kind in ("int", "str"):
        cell = sweep["40"][kind]
        assert set(cell) == {"columnar", "pickle"}
        for rate in cell.values():
            assert np.isfinite(rate) and rate > 0
    _check_socket_stats(stats)


def test_bench_socket_map_pickle_leg_smoke():
    rate, stats = bench.bench_socket_map(procs=2, keys=50, reps=1,
                                         columnar=False)
    assert np.isfinite(rate) and rate > 0
    # the forced-pickle leg must not touch the columnar encoder
    assert all(e.get("keys", 0) == 0 for e in stats.values())


def test_bench_socket_recovery_latency_smoke():
    summary, stats = bench.bench_socket_recovery_latency(
        procs=2, reps=5, size=4096)
    assert summary["retries"] >= 1          # the reset actually fired
    assert np.isfinite(summary["recovery_latency_ms"])
    ss = summary["steady_state"]
    assert ss["default_gbs"] > 0 and ss["failstop_gbs"] > 0
    _check_socket_stats(stats)


def test_bench_socket_framed_shm_smoke(monkeypatch):
    # the ISSUE 15 frame-routing leg: framed plane over the shm
    # rings. The smoke's tiny frames sit below the default
    # MP4J_SHM_FRAME_MIN, so lower it — the assertion must prove the
    # bytes rode the RINGS (wire_bytes_shm alone also counts the shm
    # pair's carrier traffic and would pass with routing broken)
    monkeypatch.setenv("MP4J_SHM_FRAME_MIN", "64")
    rate, stats = bench.bench_socket_collective(f=4, b=8, depth=2,
                                                procs=2, reps=1,
                                                native_transport=False,
                                                shm=True)
    assert np.isfinite(rate) and rate > 0
    _check_socket_stats(stats)
    assert sum(e["wire_bytes_shm"] for e in stats.values()) > 0
    assert sum(e["wire_bytes_shm_ring"] for e in stats.values()) > 0


def test_bench_socket_map_shm_smoke(monkeypatch):
    monkeypatch.setenv("MP4J_SHM_FRAME_MIN", "64")
    rate, stats = bench.bench_socket_map(procs=2, keys=50, reps=1,
                                         shm=True)
    assert np.isfinite(rate) and rate > 0
    assert sum(e["wire_bytes_shm"] for e in stats.values()) > 0
    assert sum(e["wire_bytes_shm_ring"] for e in stats.values()) > 0


def test_bench_socket_coalesce_array_smoke():
    # procs=3: the fused array plane is pinned to the tree schedule
    # and algo=auto only selects tree at n >= 3 (leg docstring)
    out = bench.bench_socket_coalesce_array(procs=3, arrays=40,
                                            size=64)
    assert np.isfinite(out["on"]) and out["on"] > 0
    assert np.isfinite(out["off"]) and out["off"] > 0
    # the window leg actually fused: coalesced_elems books the
    # count-negotiated multi-exchange totals
    assert sum(e.get("coalesced_elems", 0)
               for e in out["stats"].values()) > 0


def test_bench_trainer_overlap_skips_or_measures():
    import os

    out = bench.bench_trainer_overlap(procs=2, steps=3,
                                      grad_elems=512, matmul_dim=32,
                                      matmul_reps=1)
    nproc = len(os.sched_getaffinity(0))
    if nproc < 2:
        # the 1-core contract: a recorded marker, never a bogus figure
        assert out == {"skipped_1core": True, "nproc": nproc}
    else:
        assert np.isfinite(out["ratio"]) and out["ratio"] > 0
        assert out["overlap"] > 0 and out["blocking"] > 0
        assert out["gate_min"] == 1.3 and "gate" in out


def test_bench_socket_tuner_act_smoke():
    out = bench.bench_socket_tuner_act(procs=2, size=60_000, reps=2,
                                       warmup_secs=1.3)
    assert np.isfinite(out["off"]) and out["off"] > 0
    assert np.isfinite(out["act"]) and out["act"] > 0
    # the act leg's slaves report their tuner documents (the `tuner`
    # extra); the win itself is asserted by bench-diff on real runs,
    # not by this smoke (2-rank tiny payloads are noise-dominated)
    assert out["decisions"] and all(
        st is not None and st["mode"] == "act"
        for st in out["decisions"].values())
