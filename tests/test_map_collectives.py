"""Map (sparse) collectives: TPU cluster + socket backends + differential.

The reference's Map<K, V> collective family (SURVEY.md section 3c):
key-union semantics with operator merge on shared keys; hash partitioning
(meta.key_partition) for the scatter family on both backends.
"""

import numpy as np
import pytest

from ytk_mp4j_tpu import meta
from ytk_mp4j_tpu.comm.tpu_comm import TpuCommCluster
from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operators

from helpers import run_slaves


@pytest.fixture(scope="module")
def cluster():
    return TpuCommCluster(4)


def make_maps(n, rng, n_keys=20, fill=0.6):
    keys = [f"feat:{i}" for i in range(n_keys)]
    maps = []
    for r in range(n):
        m = {}
        for k in keys:
            if rng.random() < fill:
                m[k] = float(rng.standard_normal())
        maps.append(m)
    return maps


def expected_map_reduce(maps, op_name):
    ref = {"SUM": np.add, "PROD": np.multiply, "MAX": np.maximum,
           "MIN": np.minimum}[op_name]
    out = {}
    for m in maps:
        for k, v in m.items():
            out[k] = ref(out[k], v) if k in out else v
    return {k: float(v) for k, v in out.items()}


def assert_map_close(got, want):
    assert set(got) == set(want)
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=1e-9)


# ---------------------------------------------------------------- TPU path
@pytest.mark.parametrize("op", ["SUM", "PROD", "MAX", "MIN"])
def test_tpu_allreduce_map(cluster, op, rng):
    maps = make_maps(4, rng)
    want = expected_map_reduce(maps, op)
    cluster.allreduce_map(maps, Operands.DOUBLE, Operators.by_name(op))
    for m in maps:
        assert_map_close(m, want)


def test_tpu_allreduce_map_async_matches_sync(cluster, rng):
    """allreduce_map_async + result() must leave the maps in exactly
    the synchronous post-state; chained dispatches stay independent and
    result() is idempotent."""
    maps_a = make_maps(4, rng)
    maps_b = make_maps(4, rng, n_keys=35)
    want_a = expected_map_reduce(maps_a, "SUM")
    want_b = expected_map_reduce(maps_b, "SUM")
    # chain two dispatches before resolving either
    ha = cluster.allreduce_map_async(maps_a, Operands.DOUBLE,
                                     Operators.SUM)
    hb = cluster.allreduce_map_async(maps_b, Operands.DOUBLE,
                                     Operators.SUM)
    got_b = hb.result()
    got_a = ha.result()
    assert got_a is maps_a and got_b is maps_b   # in-place semantics
    for m in maps_a:
        assert_map_close(m, want_a)
    for m in maps_b:
        assert_map_close(m, want_b)
    ha.result()                                  # idempotent
    for m in maps_a:
        assert_map_close(m, want_a)
    # all-empty maps resolve to all-empty
    empty = [{} for _ in range(4)]
    assert cluster.allreduce_map_async(empty).result() is empty
    assert all(m == {} for m in empty)


def test_tpu_reduce_map(cluster, rng):
    maps = make_maps(4, rng)
    origs = [dict(m) for m in maps]
    want = expected_map_reduce(maps, "SUM")
    cluster.reduce_map(maps, Operands.DOUBLE, Operators.SUM, root=2)
    assert_map_close(maps[2], want)
    for r in (0, 1, 3):
        assert maps[r] == origs[r]


def test_tpu_reduce_scatter_map(cluster, rng):
    maps = make_maps(4, rng)
    want = expected_map_reduce(maps, "SUM")
    cluster.reduce_scatter_map(maps, Operands.DOUBLE, Operators.SUM)
    seen = {}
    for r, m in enumerate(maps):
        for k, v in m.items():
            assert meta.key_partition(k, 4) == r
            seen[k] = v
    assert_map_close(seen, want)


def test_tpu_allgather_map(cluster, rng):
    maps = [{f"k{r}:{i}": float(i) for i in range(3)} for r in range(4)]
    union = {}
    for m in maps:
        union.update(m)
    cluster.allgather_map(maps, Operands.DOUBLE)
    for m in maps:
        assert m == union


def test_tpu_allgather_map_dup_rejected(cluster):
    maps = [{"same": 1.0} for _ in range(4)]
    with pytest.raises(Mp4jError):
        cluster.allgather_map(maps, Operands.DOUBLE)


def test_tpu_gather_scatter_broadcast_map(cluster, rng):
    maps = [{f"k{r}:{i}": float(r * 10 + i) for i in range(2)}
            for r in range(4)]
    union = {}
    for m in maps:
        union.update(m)
    gm = [dict(m) for m in maps]
    cluster.gather_map(gm, Operands.DOUBLE, root=1)
    assert gm[1] == union
    assert gm[0] == maps[0]

    bm = [dict(m) for m in maps]
    cluster.broadcast_map(bm, Operands.DOUBLE, root=3)
    for m in bm:
        assert m == maps[3]

    sm = [dict(m) for m in maps]
    src = dict(sm[0])
    cluster.scatter_map(sm, Operands.DOUBLE, root=0)
    rebuilt = {}
    for r, m in enumerate(sm):
        for k, v in m.items():
            assert meta.key_partition(k, 4) == r
            rebuilt[k] = v
    assert rebuilt == src


def test_tpu_scatter_map_partitioner_override(cluster):
    """Contract parity with ProcessCommSlave.scatter_map(partitioner=):
    the thread backend's global-thread-rank placement rule must be
    expressible on the driver backend too."""
    N, T = 4, 2   # 4 global thread ranks blocked onto 2 processes
    src = {f"k{i}": float(i) for i in range(12)}
    maps = [dict(src)] + [{"junk": 0.0} for _ in range(3)]
    cluster.scatter_map(maps, Operands.DOUBLE, root=0,
                        partitioner=lambda k: meta.key_partition(k, N) // T)
    rebuilt = {}
    for r, m in enumerate(maps):
        for k, v in m.items():
            assert meta.key_partition(k, N) // T == r
            rebuilt[k] = v
    assert rebuilt == src
    # an out-of-range placement is an error, not a silent drop
    bad = [dict(src)] + [{} for _ in range(3)]
    with pytest.raises(Mp4jError, match="outside"):
        cluster.scatter_map(bad, Operands.DOUBLE, root=0,
                            partitioner=lambda k: 99)


def test_socket_scatter_map_partitioner_range_checked():
    """A buggy partitioner returning -1 must raise on the SOCKET backend
    too — not silently wrap to the last rank via negative indexing
    (backends must agree on bad input; meta.check_partition_rank)."""
    from helpers import run_slaves

    def fn(slave, r):
        if r != 0:
            return "skipped"    # root fails before any wire exchange
        d = {f"k{i}": float(i) for i in range(4)}
        try:
            slave.scatter_map(d, Operands.DOUBLE, root=0,
                              partitioner=lambda k: -1)
        except Mp4jError as e:
            return "raised" if "outside" in str(e) else str(e)
        return "no error"

    res = run_slaves(2, fn)
    assert res[0] == "raised", res


def test_tpu_map_vector_values(cluster, rng):
    maps = [{"a": np.array([1.0, 2.0]), "b": np.array([1.0, 1.0])},
            {"a": np.array([10.0, 20.0])},
            {"c": np.array([5.0, 5.0])},
            {}]
    cluster.allreduce_map(maps, Operands.DOUBLE, Operators.SUM)
    for m in maps:
        np.testing.assert_allclose(m["a"], [11.0, 22.0])
        np.testing.assert_allclose(m["b"], [1.0, 1.0])
        np.testing.assert_allclose(m["c"], [5.0, 5.0])


def test_tpu_map_bfloat16_values(cluster):
    """BFLOAT16 map values halve the collective payload and merge on
    the device path (values come back as bf16 scalars; small-int sums
    are exact in bf16)."""
    maps = [{f"w{i}": float(i + r) for i in range(20)} for r in range(4)]
    want = {f"w{i}": sum(float(i + r) for r in range(4))
            for i in range(20)}
    cluster.allreduce_map(maps, Operands.BFLOAT16, Operators.SUM)
    for m in maps:
        assert set(m) == set(want)
        for k in want:
            assert abs(float(m[k]) - want[k]) <= 0.5, (k, m[k])


def test_tpu_empty_maps(cluster):
    maps = [{} for _ in range(4)]
    cluster.allreduce_map(maps, Operands.DOUBLE, Operators.SUM)
    assert all(m == {} for m in maps)


# ------------------------------------------------------------- socket path
@pytest.mark.parametrize("op", ["SUM", "MAX"])
def test_socket_allreduce_map(op, rng):
    n = 4
    maps = make_maps(n, rng)
    want = expected_map_reduce(maps, op)

    def fn(slave, r):
        d = dict(maps[r])
        slave.allreduce_map(d, Operands.DOUBLE, Operators.by_name(op))
        return d

    for got in run_slaves(n, fn):
        assert_map_close(got, want)


def test_socket_reduce_scatter_map(rng):
    n = 3
    maps = make_maps(n, rng)
    want = expected_map_reduce(maps, "SUM")

    def fn(slave, r):
        d = dict(maps[r])
        slave.reduce_scatter_map(d, Operands.DOUBLE, Operators.SUM)
        return d

    seen = {}
    for r, got in enumerate(run_slaves(n, fn)):
        for k, v in got.items():
            assert meta.key_partition(k, n) == r
            seen[k] = v
    assert_map_close(seen, want)


def test_socket_gather_scatter_broadcast_map():
    n = 3
    maps = [{f"k{r}:{i}": float(r + i) for i in range(2)} for r in range(n)]
    union = {}
    for m in maps:
        union.update(m)

    def fn(slave, r):
        d = dict(maps[r])
        slave.gather_map(d, Operands.DOUBLE, root=0)
        g = dict(d)
        d2 = dict(maps[r])
        slave.broadcast_map(d2, Operands.DOUBLE, root=1)
        d3 = dict(maps[0]) if r == 0 else {}
        slave.scatter_map(d3, Operands.DOUBLE, root=0)
        return g, d2, d3

    res = run_slaves(n, fn)
    assert res[0][0] == union
    for r, (g, b, sc) in enumerate(res):
        assert b == maps[1]
        for k in sc:
            assert meta.key_partition(k, n) == r


def test_socket_allgather_map():
    n = 3
    maps = [{f"k{r}": float(r)} for r in range(n)]
    union = {}
    for m in maps:
        union.update(m)

    def fn(slave, r):
        d = dict(maps[r])
        slave.allgather_map(d, Operands.DOUBLE)
        return d

    for got in run_slaves(n, fn):
        assert got == union


# ------------------------------------------------------------ differential
@pytest.mark.parametrize("op", ["SUM", "PROD", "MAX", "MIN"])
def test_map_differential(cluster, op, rng):
    n = 4
    maps = make_maps(n, rng, n_keys=31)
    operator = Operators.by_name(op)

    def fn(slave, r):
        d = dict(maps[r])
        slave.allreduce_map(d, Operands.DOUBLE, operator)
        return d

    sock = run_slaves(n, fn)
    tpu = [dict(m) for m in maps]
    cluster.allreduce_map(tpu, Operands.DOUBLE, operator)
    for got_s, got_t in zip(sock, tpu):
        assert set(got_s) == set(got_t)
        for k in got_s:
            np.testing.assert_allclose(got_t[k], got_s[k], rtol=1e-9)


def test_tpu_map_mixed_value_shapes_rejected(cluster):
    maps = [{"a": 1.0}, {"b": np.ones(3)}, {}, {}]
    with pytest.raises(Mp4jError):
        cluster.allreduce_map(maps, Operands.DOUBLE, Operators.SUM)
    # scalar vs shape-(1,) arrays must raise too, not silently flatten
    maps = [{"a": 1.0}, {"a": np.ones(1)}, {}, {}]
    with pytest.raises(Mp4jError, match="share"):
        cluster.allreduce_map(maps, Operands.DOUBLE, Operators.SUM)


@pytest.mark.parametrize("op", ["SUM", "MAX"])
def test_socket_allreduce_map_int_keys(op, rng):
    """Integer feature-id keys (the ytk-learn sparse-gradient shape)
    must merge exactly like string keys through the socket path."""
    n = 4
    maps = [{int(k): float(v) for k, v in
             zip(rng.integers(0, 400, 120), rng.standard_normal(120))}
            for _ in range(n)]
    want = expected_map_reduce(maps, op)

    def fn(slave, r):
        d = dict(maps[r])
        slave.allreduce_map(d, Operands.DOUBLE, Operators.by_name(op))
        return d

    for got in run_slaves(n, fn):
        assert set(got) == set(want)
        for k in want:
            np.testing.assert_allclose(got[k], want[k], rtol=1e-12)


def test_drifting_key_counts_bound_recompiles(rng):
    """Real sparse-gradient streams drift in key count every step; the
    pow2 bucketing of Lmax and union capacity must bound the number of
    distinct compiled programs at O(log max-keys), not O(steps)."""
    cl = TpuCommCluster(4)
    n_sizes = set()
    for step in range(24):
        n_keys = 30 + 7 * step            # drifts 30..191
        maps = make_maps(4, rng, n_keys=n_keys, fill=0.7)
        want = expected_map_reduce(maps, "SUM")
        work = [dict(m) for m in maps]
        cl.allreduce_map(work, Operands.DOUBLE, Operators.SUM)
        for m in work:
            assert_map_close(m, want)
        n_sizes.add(n_keys)
    n_programs = sum(1 for k in cl._jits if k[0] == "sparse_allreduce")
    assert len(n_sizes) == 24
    # 24 distinct key counts spanning 30..191 must land in a handful of
    # (pow2 Lmax, pow2 capacity) pairs — the pairs cross-combine, so the
    # bound is O(log^2) worst case, not O(steps); without bucketing this
    # run compiles 24 programs, with it 7
    assert n_programs <= 8, cl._jits.keys()
