"""Dead-peer diagnosability: peer_timeout turns the reference's
fail-stop hang into a clean Mp4jError (SURVEY.md section 5)."""

import threading

import numpy as np
import pytest

from ytk_mp4j_tpu.comm.master import Master
from ytk_mp4j_tpu.comm.process_comm import ProcessCommSlave
from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operators


def test_dead_peer_raises_instead_of_hanging():
    master = Master(2, timeout=30.0).serve_in_thread()
    outcome = {}

    def worker():
        # timeout bounds peer-connect waits too; keep both short so the
        # dead peer surfaces quickly whichever phase it dies in
        slave = ProcessCommSlave("127.0.0.1", master.port, timeout=4.0,
                                 peer_timeout=1.5)
        if slave.rank == 1:
            # defect without participating in the collective
            slave.close(1)
            return
        arr = np.ones(64, np.float32)
        try:
            slave.allreduce_array(arr, Operands.FLOAT, Operators.SUM)
            outcome["err"] = None
        except Mp4jError as e:
            outcome["err"] = str(e)
        slave.close(0)

    ts = [threading.Thread(target=worker, daemon=True) for _ in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(20)
        assert not t.is_alive(), "collective hung despite peer_timeout"
    assert outcome["err"] is not None, "dead peer must surface as Mp4jError"
    master.join(10)
    assert master.final_code == 1  # rank 1's defect code aggregates


def test_default_is_reference_failstop():
    """Without peer_timeout the channel has no receive deadline (the
    reference's fail-stop semantics)."""
    s = ProcessCommSlave.__new__(ProcessCommSlave)
    assert "peer_timeout" in ProcessCommSlave.__init__.__doc__
    import inspect

    sig = inspect.signature(ProcessCommSlave.__init__)
    assert sig.parameters["peer_timeout"].default is None
