"""CommStats attribution edges (utils.stats): the <untracked> bucket,
snapshot merging, the helper-thread fallback, and the telemetry
progress/sequence-number record the heartbeats ship."""

import threading

from ytk_mp4j_tpu.utils.stats import CommStats, merge_snapshots


def test_untracked_bucket_outside_any_collective():
    cs = CommStats()
    cs.add_wire(100, 50, 0.25)
    cs.add("reduce_seconds", 0.5)
    snap = cs.snapshot()
    assert set(snap) == {"<untracked>"}
    e = snap["<untracked>"]
    assert e["bytes_sent"] == 100 and e["bytes_recv"] == 50
    assert e["wire_seconds"] == 0.25 and e["reduce_seconds"] == 0.5
    assert e["calls"] == 0  # nothing ever entered a collective scope


def test_helper_thread_fallback_requires_open_scope():
    """A helper thread inherits the slave's active collective via the
    shared name; with no scope open (_shared_name unset) it must land
    on <untracked>, and again after the scope closes."""
    cs = CommStats()
    seen = []

    def helper():
        seen.append(cs.bucket())

    t = threading.Thread(target=helper)
    t.start()
    t.join()
    assert seen == ["<untracked>"]

    outer = cs.begin("allreduce_array")
    assert outer  # outermost
    t = threading.Thread(target=lambda: seen.append(cs.bucket()))
    t.start()
    t.join()
    assert seen[-1] == "allreduce_array"
    cs.end(outer)
    t = threading.Thread(target=lambda: seen.append(cs.bucket()))
    t.start()
    t.join()
    assert seen[-1] == "<untracked>"


def test_nested_scopes_and_sequence_numbers():
    cs = CommStats()
    s1 = cs.begin("allreduce_map")
    assert s1 == 1
    nested = cs.begin("reduce_map")     # composed collective
    assert nested == 0                  # not outermost: no seq bump
    assert cs.bucket() == "allreduce_map"
    cs.add("serialize_seconds", 0.1)
    cs.end(nested)
    cs.end(s1)
    s2 = cs.begin("barrier")
    assert s2 == 2                      # monotonically increasing
    cs.end(s2)
    snap = cs.snapshot()
    # phase work inside the nested call attributed to the OUTER call
    assert snap["allreduce_map"]["serialize_seconds"] == 0.1
    assert "reduce_map" not in snap
    assert snap["allreduce_map"]["calls"] == 1
    assert snap["barrier"]["calls"] == 1


def test_progress_record_transitions():
    cs = CommStats()
    p = cs.progress()
    assert p == {"seq": 0, "current": None, "last": None, "phase": None,
                 "current_secs": 0.0}
    tok = cs.begin("allreduce_array")
    cs.add_wire(10, 10, 0.01)
    p = cs.progress()
    assert p["seq"] == 1 and p["current"] == "allreduce_array"
    assert p["phase"] == "wire" and p["current_secs"] >= 0.0
    cs.end(tok)
    p = cs.progress()
    assert p["current"] is None and p["last"] == "allreduce_array"


def test_merge_snapshots_disjoint_and_overlapping():
    a = CommStats()
    tok = a.begin("allreduce_array")
    a.add_wire(100, 100, 0.5, chunks=2)
    a.end(tok)
    b = CommStats()
    tok = b.begin("allreduce_array")
    b.add_wire(10, 10, 0.1, chunks=1)
    b.end(tok)
    tok = b.begin("barrier")
    b.end(tok)

    merged = merge_snapshots(a.snapshot(), b.snapshot())
    assert set(merged) == {"allreduce_array", "barrier"}
    e = merged["allreduce_array"]
    assert e["calls"] == 2 and e["chunks"] == 3
    assert e["bytes_sent"] == 110 and abs(e["wire_seconds"] - 0.6) < 1e-12
    # disjoint key keeps the full schema, zero-filled elsewhere
    assert merged["barrier"]["calls"] == 1
    assert merged["barrier"]["wire_seconds"] == 0.0
    assert merge_snapshots() == {}


def test_add_wire_transport_split_and_frame_families():
    """ISSUE 7: wire events tagged with a transport book the
    ``wire_bytes_{tcp,shm}`` split and land in the matching
    ``frame_bytes/<transport>`` histogram family; untagged events keep
    the untagged totals + legacy ``frame_bytes`` family only."""
    cs = CommStats()
    tok = cs.begin("allreduce_array")
    cs.add_wire(100, 50, 0.01, transport="tcp")
    cs.add_wire(200, 0, 0.01, transport="shm")
    cs.add_wire(7, 7, 0.01)                       # untagged (bare test
    cs.add_wire(9, 0, 0.01, transport="weird")    # channel / unknown)
    cs.end(tok)

    e = cs.snapshot()["allreduce_array"]
    assert e["bytes_sent"] == 316 and e["bytes_recv"] == 57
    assert e["wire_bytes_tcp"] == 150
    assert e["wire_bytes_shm"] == 200
    # the split never invents bytes: tagged <= total
    assert (e["wire_bytes_tcp"] + e["wire_bytes_shm"]
            <= e["bytes_sent"] + e["bytes_recv"])

    hists = cs.metrics.snapshot()["histograms"]
    assert hists["frame_bytes/tcp"]["count"] == 2   # 100 sent + 50 recv
    assert hists["frame_bytes/shm"]["count"] == 1   # one direction moved
    assert hists["frame_bytes"]["count"] == 3       # untagged + unknown


def test_transport_split_renders_in_prometheus():
    from ytk_mp4j_tpu.obs import metrics as metrics_mod

    cs = CommStats()
    tok = cs.begin("allreduce_array")
    cs.add_wire(4096, 4096, 0.01, transport="shm")
    cs.end(tok)
    doc = {"slave_num": 1, "window_secs": 60.0,
           "ranks": {"0": {"progress": {"seq": 1}, "age": 0.0,
                           "stats": cs.snapshot(), "rates": {},
                           "histograms": {}}},
           "cluster": {"stats": cs.snapshot(), "rates": {},
                       "histograms":
                           cs.metrics.snapshot()["histograms"]}}
    text = metrics_mod.to_prometheus(doc)
    assert 'mp4j_wire_bytes_shm_total{rank="0",' in text
    assert 'mp4j_frame_bytes_bucket{transport="shm",le=' in text
