"""Persistent key<->code vocabularies (comm.keycodec) + their use by the
TpuCommCluster map collectives (the configs[2] hot path)."""

import numpy as np
import pytest

from ytk_mp4j_tpu import meta
from ytk_mp4j_tpu.comm.keycodec import (IntKeyCodec, ObjKeyCodec,
                                        codec_for_key)
from ytk_mp4j_tpu.comm.tpu_comm import TpuCommCluster
from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operators


def test_codec_for_key_kinds():
    assert isinstance(codec_for_key(7), IntKeyCodec)
    assert isinstance(codec_for_key(np.int32(7)), IntKeyCodec)
    assert isinstance(codec_for_key("w7"), ObjKeyCodec)
    assert isinstance(codec_for_key(True), ObjKeyCodec)   # bool is NOT int
    assert isinstance(codec_for_key((1, 2)), ObjKeyCodec)


@pytest.mark.parametrize("codec_cls,mk", [
    (IntKeyCodec, lambda i: i * 13 - 40),
    (ObjKeyCodec, lambda i: f"feat:{i * 13 - 40}"),
])
def test_codec_roundtrip_and_growth(codec_cls, mk):
    c = codec_cls()
    d1 = {mk(i): None for i in range(50)}
    codes1 = c.encode(d1.keys(), len(d1))
    assert codes1.dtype == np.int32 and c.size == 50
    assert sorted(codes1.tolist()) == list(range(50))  # dense codes
    assert c.decode(codes1) == list(d1.keys())
    # re-encoding the same keys is stable and does not grow the vocab
    codes_again = c.encode(d1.keys(), len(d1))
    np.testing.assert_array_equal(codes_again, codes1)
    assert c.size == 50
    # overlapping novelty grows; old codes keep their values
    d2 = {mk(i): None for i in range(30, 80)}
    codes2 = c.encode(d2.keys(), len(d2))
    assert c.size == 80
    assert c.decode(codes2) == list(d2.keys())
    overlap = [k for k in d2 if k in d1]
    old = dict(zip(d1.keys(), codes1.tolist()))
    new = dict(zip(d2.keys(), codes2.tolist()))
    assert all(old[k] == new[k] for k in overlap)


@pytest.mark.parametrize("codec_cls,mk", [
    (IntKeyCodec, lambda i: i * 7 - 11),
    (ObjKeyCodec, lambda i: f"k{i * 7 - 11}"),
])
def test_codec_partition_matches_meta(codec_cls, mk):
    c = codec_cls()
    keys = [mk(i) for i in range(40)]
    codes = c.encode(keys, len(keys))
    for n in (3, 4):
        got = c.partition(codes, n)
        want = [meta.key_partition(k, n) for k in keys]
        np.testing.assert_array_equal(got, want)
    # growth after a partition call extends the cache coherently
    more = [mk(i) for i in range(40, 55)]
    codes2 = c.encode(more, len(more))
    np.testing.assert_array_equal(
        c.partition(codes2, 4), [meta.key_partition(k, 4) for k in more])


def test_int_codec_rejects_non_int_keys():
    c = IntKeyCodec()
    with pytest.raises(Mp4jError, match="integer"):
        c.encode(["a", "b"], 2)
    # floats must RAISE, not silently truncate into a colliding int key
    with pytest.raises(Mp4jError, match="integer"):
        c.encode([2.5, 3.0], 2)
    cl = TpuCommCluster(2)
    with pytest.raises(Mp4jError, match="integer"):
        cl.allreduce_map([{2: 1.0}, {2.5: 1.0}], Operands.DOUBLE,
                         Operators.SUM)


@pytest.mark.parametrize("codec_cls,mk", [
    (IntKeyCodec, lambda i: i),
    (ObjKeyCodec, lambda i: f"k{i}"),
])
def test_codec_overflow_checked_before_growth(codec_cls, mk,
                                              monkeypatch):
    """The int32/SENTINEL overflow must raise BEFORE the vocabulary
    grows (ADVICE round 4, low): a post-insert check left an oversized
    vocab whose sentinel-colliding codes the all-known fast path then
    returned without error."""
    from ytk_mp4j_tpu.comm import keycodec
    monkeypatch.setattr(keycodec, "SENTINEL", 3)
    c = codec_cls()
    c.encode([mk(0), mk(1)], 2)
    with pytest.raises(Mp4jError, match="overflow"):
        c.encode([mk(2), mk(3)], 2)
    assert c.size == 2                 # NOT mutated by the failed call
    np.testing.assert_array_equal(     # fast path stays sentinel-free
        c.encode([mk(0), mk(1)], 2), [0, 1])


def test_int_codec_negative_and_large_keys():
    c = IntKeyCodec()
    keys = [-(2 ** 62), -1, 0, 5, 2 ** 62]
    codes = c.encode(keys, len(keys))
    assert c.decode(codes) == keys
    assert all(isinstance(k, int) for k in c.decode(codes))


# ------------------------------------------------- device map integration
def test_device_allreduce_map_int_keys(rng):
    """Int feature-id keys (the ytk-learn gradient shape) on the DEVICE
    map path: values merge exactly, keys come back as python ints."""
    cl = TpuCommCluster(4)
    maps = [{int(k): float(v) for k, v in
             zip(rng.integers(0, 300, 90), rng.standard_normal(90))}
            for _ in range(4)]
    want = {}
    for m in maps:
        for k, v in m.items():
            want[k] = want.get(k, 0.0) + v
    cl.allreduce_map(maps, Operands.DOUBLE, Operators.SUM)
    for m in maps:
        assert set(m) == set(want)
        assert all(type(k) is int for k in m)
        for k in want:
            np.testing.assert_allclose(m[k], want[k], rtol=1e-9)


def test_device_map_vocab_persists_across_calls(rng):
    """Repeated calls over a near-persistent vocabulary reuse the codec:
    the vocab stops growing once the key stream stabilizes."""
    cl = TpuCommCluster(4)
    for step in range(3):
        maps = [{f"w{i}": 1.0 for i in range(100)} for _ in range(4)]
        cl.allreduce_map(maps, Operands.DOUBLE, Operators.SUM)
    codec = cl._codecs["obj"]
    assert codec.size == 100
    # int maps on the same cluster take their own codec
    imaps = [{i: 1.0 for i in range(40)} for _ in range(4)]
    cl.allreduce_map(imaps, Operands.DOUBLE, Operators.SUM)
    assert cl._codecs["int"].size == 40
    assert cl._codecs["obj"].size == 100
    for m in imaps:
        assert m == {i: 4.0 for i in range(40)}


def test_reset_map_vocabularies(rng):
    """Key churn on a long-lived cluster: reset drops the grow-only
    vocabularies; the next call rebuilds from live keys and results
    stay correct."""
    cl = TpuCommCluster(4)
    maps = [{f"epoch0:{i}": 1.0 for i in range(50)} for _ in range(4)]
    cl.allreduce_map(maps, Operands.DOUBLE, Operators.SUM)
    assert cl._codecs["obj"].size == 50
    cl.reset_map_vocabularies()
    assert "obj" not in cl._codecs
    maps = [{f"epoch1:{i}": 1.0 for i in range(30)} for _ in range(4)]
    cl.allreduce_map(maps, Operands.DOUBLE, Operators.SUM)
    assert cl._codecs["obj"].size == 30        # only live keys
    assert maps[0] == {f"epoch1:{i}": 4.0 for i in range(30)}


def test_device_map_mixed_key_kinds_in_one_call_raise():
    cl = TpuCommCluster(4)
    maps = [{1: 1.0}, {"a": 1.0}, {}, {}]
    with pytest.raises(Mp4jError):
        cl.allreduce_map(maps, Operands.DOUBLE, Operators.SUM)


def test_device_reduce_scatter_map_int_keys(rng):
    """Partition cache must place int keys exactly like the socket
    backend's per-key meta.key_partition."""
    cl = TpuCommCluster(4)
    maps = [{int(k): float(r) for k in rng.integers(0, 200, 60)}
            for r in range(4)]
    want = {}
    for m in maps:
        for k, v in m.items():
            want[k] = want.get(k, 0.0) + v
    cl.reduce_scatter_map(maps, Operands.DOUBLE, Operators.SUM)
    seen = {}
    for r, m in enumerate(maps):
        for k, v in m.items():
            assert meta.key_partition(k, 4) == r
            seen[k] = v
    assert set(seen) == set(want)
    for k in want:
        np.testing.assert_allclose(seen[k], want[k], rtol=1e-9)
