"""mp4j-serve (ISSUE 19): hot-key cache accounting, micro-batcher
deadline semantics, request framing, and the bit-exact sharded-serve
grid — 4 model families x {tcp, shm} x n in {2, 4} — plus the
slow-rank deadline story and the serve observability surfaces."""

import threading
import time

import numpy as np
import pytest

from helpers import run_slaves
from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.models import fm as fm_mod
from ytk_mp4j_tpu.models import gbdt as gbdt_mod
from ytk_mp4j_tpu.models import linear as linear_mod
from ytk_mp4j_tpu.models.fm import FMConfig
from ytk_mp4j_tpu.models.gbdt import GBDTConfig, GBDTTrainer
from ytk_mp4j_tpu.models.linear import LinearConfig
from ytk_mp4j_tpu.serve import framing
from ytk_mp4j_tpu.serve.batcher import MicroBatcher, ServeFuture
from ytk_mp4j_tpu.serve.cache import HotKeyCache, validate_version
from ytk_mp4j_tpu.serve.dispatcher import ServeFrontend, serve_worker
from ytk_mp4j_tpu.utils import tuning


# ----------------------------------------------------------------------
# hot-key cache: analytic accounting
# ----------------------------------------------------------------------
def test_cache_hit_miss_eviction_accounting():
    c = HotKeyCache(capacity_rows=2, stale_versions=0)
    r = np.ones(3)
    assert c.lookup(1, 0) is None            # miss
    c.insert(1, r, 0)
    assert c.lookup(1, 0) is r               # hit
    c.insert(2, r, 0)
    c.insert(3, r, 0)                        # evicts LRU id=1
    assert c.evictions == 1
    assert c.lookup(1, 0) is None            # miss (evicted)
    assert c.lookup(3, 0) is r
    s = c.stats()
    assert (s["hits"], s["misses"], s["evictions"]) == (2, 2, 1)
    assert s["rows"] == 2
    assert s["hit_rate"] == pytest.approx(0.5)


def test_cache_lru_order_follows_lookups():
    c = HotKeyCache(capacity_rows=2, stale_versions=0)
    r = np.ones(1)
    c.insert(1, r, 0)
    c.insert(2, r, 0)
    c.lookup(1, 0)                           # 1 becomes most recent
    c.insert(3, r, 0)                        # evicts 2, not 1
    assert c.lookup(1, 0) is not None
    assert c.lookup(2, 0) is None


def test_cache_staleness_bound_counts_stale_and_miss():
    c = HotKeyCache(capacity_rows=8, stale_versions=1)
    r = np.ones(1)
    c.insert(5, r, 0)
    assert c.lookup(5, 1) is r               # within the bound
    assert c.lookup(5, 2) is None            # 2 bumps behind: stale
    s = c.stats()
    assert s["stale"] == 1
    # the stale drop is ALSO a miss: staleness explains the miss, it
    # does not replace it
    assert s["misses"] == 1 and s["hits"] == 1
    assert len(c) == 0                       # stale row was dropped


def test_cache_capacity_zero_disables():
    c = HotKeyCache(capacity_rows=0)
    c.insert(1, np.ones(1), 0)
    assert len(c) == 0 and c.lookup(1, 0) is None


def test_version_validation():
    assert validate_version(3) == 3
    with pytest.raises(Mp4jError):
        validate_version(-1)


def test_serve_knob_validation(monkeypatch):
    with pytest.raises(Mp4jError):
        tuning.serve_deadline_ms(0.0)
    with pytest.raises(Mp4jError):
        tuning.serve_max_batch(0)
    with pytest.raises(Mp4jError):
        tuning.serve_cache_rows(-1)
    monkeypatch.setenv("MP4J_SERVE_IDLE_QPS", "10")
    monkeypatch.setenv("MP4J_SERVE_BUSY_QPS", "5")
    with pytest.raises(Mp4jError):
        tuning.serve_busy_qps()


# ----------------------------------------------------------------------
# micro-batcher: deadline / full / drain semantics
# ----------------------------------------------------------------------
def test_batcher_full_batch_dispatches_immediately():
    seen = []
    b = MicroBatcher(lambda reqs: [r * 10 for r in seen.append(list(reqs))
                                   or reqs],
                     deadline_ms=10_000.0, max_batch=4)
    try:
        futs = [b.submit(i) for i in range(4)]
        t0 = time.monotonic()
        out = [f.wait(5.0) for f in futs]
        # a FULL batch must not wait the 10s deadline out
        assert time.monotonic() - t0 < 5.0
        assert out == [0, 10, 20, 30]
        assert seen == [[0, 1, 2, 3]]
        assert b.batch_full == 1 and b.batch_deadline == 0
    finally:
        b.close()


def test_batcher_deadline_bounds_oldest_wait():
    waits = []
    b = MicroBatcher(lambda reqs: reqs, deadline_ms=20.0, max_batch=64,
                     on_batch=lambda n, reason, w: waits.append(
                         (reason, w)))
    try:
        fut = b.submit("only")
        assert fut.wait(5.0) == "only"
        (reason, wait_secs), = waits
        assert reason == "deadline"
        # the oldest request's accumulation wait honored the deadline
        # (generous slack: shared CI hosts wake late, never early)
        assert 0.015 <= wait_secs < 1.0
        assert b.batch_deadline == 1
    finally:
        b.close()


def test_batcher_close_drains_and_rejects():
    b = MicroBatcher(lambda reqs: reqs, deadline_ms=60_000.0,
                     max_batch=64)
    futs = [b.submit(i) for i in range(3)]
    b.close()                                # drain, not discard
    assert [f.wait(1.0) for f in futs] == [0, 1, 2]
    with pytest.raises(Mp4jError):
        b.submit("late")
    b.close()                                # idempotent


def test_batcher_dispatch_failure_fans_out_and_plane_survives():
    state = {"boom": True}

    def dispatch(reqs):
        if state["boom"]:
            raise RuntimeError("poisoned batch")
        return reqs

    b = MicroBatcher(dispatch, deadline_ms=5.0, max_batch=64)
    try:
        bad = b.submit("a")
        with pytest.raises(RuntimeError):
            bad.wait(5.0)
        state["boom"] = False
        assert b.submit("b").wait(5.0) == "b"   # plane still serving
    finally:
        b.close()


def test_batcher_result_count_mismatch_fails_futures():
    b = MicroBatcher(lambda reqs: [], deadline_ms=5.0, max_batch=64)
    try:
        with pytest.raises(Mp4jError, match="0 results"):
            b.submit("x").wait(5.0)
    finally:
        b.close()


def test_future_timeout_does_not_consume():
    fut = ServeFuture()
    with pytest.raises(Mp4jError):
        fut.wait(0.01)
    fut._resolve(7)
    assert fut.wait(0.01) == 7


# ----------------------------------------------------------------------
# framing round-trips
# ----------------------------------------------------------------------
def test_frame_request_roundtrip_pull_family():
    ids = np.asarray([3, 1, 4], np.int64)
    fields = np.asarray([0, 1, 0], np.int32)
    vals = np.asarray([1.0, 0.5, 0.0], np.float32)
    buf = framing.encode_request("ffm", 42, ids, fields, vals)
    family, req_id, i2, f2, v2 = framing.decode_request(buf)
    assert (family, req_id) == ("ffm", 42)
    np.testing.assert_array_equal(i2, ids)
    np.testing.assert_array_equal(f2, fields)
    np.testing.assert_array_equal(v2, vals)


def test_frame_request_roundtrip_gbdt_bins_only():
    bins = np.asarray([7, 0, 255, 3], np.int64)
    buf = framing.encode_request("gbdt", 1, bins)
    family, req_id, i2, f2, v2 = framing.decode_request(buf)
    assert family == "gbdt" and req_id == 1
    np.testing.assert_array_equal(i2, bins)
    assert not f2.any() and not v2.any()     # unused lanes ride zero


def test_frame_response_roundtrip_and_status():
    preds = np.asarray([0.25, 0.75], np.float64)
    buf = framing.encode_response(9, preds,
                                  status=framing.STATUS_DEGRADED)
    req_id, p2, status = framing.decode_response(buf)
    assert req_id == 9 and status == framing.STATUS_DEGRADED
    np.testing.assert_array_equal(p2, preds)


def test_frame_rejects_garbage():
    with pytest.raises(Mp4jError):
        framing.decode_request(b"not a frame at all....")
    with pytest.raises(Mp4jError):
        framing.encode_request("nope", 1, np.zeros(1, np.int64))


# ----------------------------------------------------------------------
# the bit-exact sharded grid: 4 families x {tcp, shm} x n in {2, 4}
# ----------------------------------------------------------------------
_RNG = np.random.default_rng(7)


def _linear_servable():
    cfg = LinearConfig(n_features=24, loss="logistic")
    w = _RNG.standard_normal(24).astype(np.float32)
    b = np.float32(0.3)
    return linear_mod.servable((w, b), cfg)


def _fm_servable():
    cfg = FMConfig(n_features=24, k=4, max_nnz=6, model="fm",
                   loss="logistic")
    w0 = np.float32(0.1)
    w = _RNG.standard_normal(24).astype(np.float32)
    V = (0.1 * _RNG.standard_normal((24, 4))).astype(np.float32)
    return fm_mod.servable((w0, w, V), cfg)


def _ffm_servable():
    cfg = FMConfig(n_features=24, n_fields=3, k=4, max_nnz=6,
                   model="ffm", loss="logistic")
    w0 = np.float32(-0.2)
    w = _RNG.standard_normal(24).astype(np.float32)
    V = (0.1 * _RNG.standard_normal((24 * 3, 4))).astype(np.float32)
    return fm_mod.servable((w0, w, V), cfg)


_GBDT = {}


def _gbdt_servable():
    # train ONCE per session (jit compile dominates); tiny ensemble
    if "s" not in _GBDT:
        from ytk_mp4j_tpu.parallel import make_mesh
        cfg = GBDTConfig(n_features=5, n_bins=8, depth=2, n_trees=4,
                         loss="logistic", hist_mode="flat")
        rng = np.random.default_rng(3)
        bins = rng.integers(0, 8, (64, 5)).astype(np.int8)
        y = (bins[:, 0] > 3).astype(np.float32)
        trees, _ = GBDTTrainer(cfg, mesh=make_mesh(1)).train(bins, y)
        _GBDT["s"] = gbdt_mod.servable(trees, cfg)
    return _GBDT["s"]


_FAMILIES = {
    "linear": _linear_servable,
    "fm": _fm_servable,
    "ffm": _ffm_servable,
    "gbdt": _gbdt_servable,
}


def _requests(servable, n_reqs=10):
    """Deterministic request set; every family sees repeated hot ids
    (cache hits) plus tail ids."""
    rng = np.random.default_rng(11)
    reqs = []
    if servable.kind == "reduce":
        for _ in range(n_reqs):
            reqs.append(rng.integers(
                0, servable.cfg.n_bins,
                servable.req_width).astype(np.int64))
        return reqs
    nnz = servable.cfg.max_nnz if hasattr(servable.cfg, "max_nnz") \
        else 6
    nf = getattr(servable.cfg, "n_fields", 1)
    for _ in range(n_reqs):
        ids = rng.choice(servable.n_rows, size=nnz, replace=False)
        fields = (np.arange(nnz) % nf).astype(np.int32)
        vals = rng.standard_normal(nnz).astype(np.float32)
        vals[rng.random(nnz) < 0.2] = 0.0    # padded slots
        reqs.append((ids.astype(np.int64), fields, vals))
    return reqs


def _reference(servable, reqs):
    """Single-process per-example scoring — the sequential oracle the
    batched sharded path must match BITWISE."""
    if servable.kind == "pull":
        all_ids = np.arange(servable.n_rows, dtype=np.int64)
        mat = servable.rows(all_ids)
        rowmap = {int(i): mat[j] for j, i in enumerate(all_ids)}
        return servable.predict_sharded(reqs, rowmap)
    bins = np.stack(reqs)
    return servable.link(servable.partial_margins(bins, 0, 1))


@pytest.mark.parametrize("family", sorted(_FAMILIES))
@pytest.mark.parametrize("shm", [False, True],
                         ids=["tcp", "shm"])
@pytest.mark.parametrize("n", [2, 4])
def test_sharded_serve_bit_exact_grid(family, shm, n):
    servable = _FAMILIES[family]()
    reqs = _requests(servable)
    want = _reference(servable, reqs)

    def fn(slave, rank):
        if rank != 0:
            return serve_worker(slave, servable, max_batch=8)
        fe = ServeFrontend(slave, servable, deadline_ms=50.0,
                           max_batch=8)
        try:
            futs = [fe.submit(r) for r in reqs]
            return [f.wait(30.0) for f in futs]
        finally:
            fe.close()

    results = run_slaves(n, fn, shm=shm)
    got = results[0]
    assert len(got) == len(want)
    for g, w in zip(got, want):
        # bitwise, not allclose: per-example scoring in fixed op order
        # makes batched == sequential exact by construction
        np.testing.assert_array_equal(g, w)
    for r in range(1, n):
        assert results[r]["rounds"] >= 1


def test_sequential_equals_batched_single_rank():
    """max_batch=1 (pure sequential) and max_batch=8 (batched) produce
    bitwise-identical predictions — the ISSUE's headline contract."""
    servable = _FAMILIES["fm"]()
    reqs = _requests(servable)

    def serve_all(max_batch):
        def fn(slave, rank):
            fe = ServeFrontend(slave, servable, deadline_ms=5.0,
                               max_batch=max_batch)
            try:
                return [fe.predict(r, timeout=30.0) for r in reqs]
            finally:
                fe.close()
        return run_slaves(1, fn)[0]

    seq = serve_all(1)
    bat = serve_all(8)
    for a, b in zip(seq, bat):
        np.testing.assert_array_equal(a, b)


# ----------------------------------------------------------------------
# cache accounting over the live pull plane
# ----------------------------------------------------------------------
def test_warm_cache_serves_with_zero_collectives():
    servable = _FAMILIES["ffm"]()
    reqs = _requests(servable, n_reqs=6)

    def fn(slave, rank):
        if rank != 0:
            return serve_worker(slave, servable)
        fe = ServeFrontend(slave, servable, deadline_ms=5.0,
                           max_batch=4)
        try:
            cold = [fe.predict(r, timeout=30.0) for r in reqs]
            stats_cold = dict(fe.cache_stats())
            warm = [fe.predict(r, timeout=30.0) for r in reqs]
            stats_warm = dict(fe.cache_stats())
            return cold, warm, stats_cold, stats_warm
        finally:
            fe.close()

    results = run_slaves(2, fn)
    cold, warm, stats_cold, stats_warm = results[0]
    worker = results[1]
    for a, b in zip(cold, warm):
        np.testing.assert_array_equal(a, b)
    # the warm pass touched only cached rows: zero new misses, so the
    # worker saw no pull rounds beyond the cold pass
    assert stats_warm["misses"] == stats_cold["misses"]
    assert stats_warm["hits"] > stats_cold["hits"]
    assert worker["pull_ids"] == stats_cold["misses"]


def test_version_bump_invalidates_cache():
    servable = _FAMILIES["linear"]()
    req = _requests(servable, n_reqs=1)[0]

    def fn(slave, rank):
        fe = ServeFrontend(slave, servable, deadline_ms=5.0,
                           max_batch=4, stale_versions=0)
        try:
            fe.predict(req, timeout=30.0)
            fe.predict(req, timeout=30.0)          # warm hit
            fe.bump_version()
            fe.predict(req, timeout=30.0)          # stale -> re-pull
            return dict(fe.cache_stats())
        finally:
            fe.close()

    stats = run_slaves(1, fn)[0]
    assert stats["stale"] >= 1
    assert stats["hits"] >= 1


# ----------------------------------------------------------------------
# deadline honored under a slow-rank fault
# ----------------------------------------------------------------------
def test_deadline_honored_under_slow_rank():
    """A persistently slow worker cannot stretch the batcher's
    accumulation wait: batches keep dispatching at the deadline and
    every request completes (the slow collective costs latency
    DOWNSTREAM of the batcher, never an unbounded queue)."""
    servable = _FAMILIES["gbdt"]()
    reqs = _requests(servable, n_reqs=6)
    waits = []

    def fn(slave, rank):
        if rank != 0:
            return serve_worker(slave, servable, max_batch=4)
        fe = ServeFrontend(slave, servable, deadline_ms=2.0,
                           max_batch=4)
        fe._batcher._on_batch = lambda n, reason, w: (
            waits.append(w), fe._note_batch(n, reason, w))
        try:
            return [fe.predict(r, timeout=30.0) for r in reqs]
        finally:
            fe.close()

    results = run_slaves(
        2, fn, fault_plan="slow:rank=1:secs=0.01")
    want = _reference(servable, reqs)
    for g, w in zip(results[0], want):
        np.testing.assert_array_equal(g, w)
    # accumulation waits stayed near the 2ms deadline even though each
    # dispatch round was an order of magnitude slower than that
    assert waits and max(waits) < 1.0


# ----------------------------------------------------------------------
# serve metrics + observability surfaces
# ----------------------------------------------------------------------
def test_serve_metrics_and_master_serve_status():
    servable = _FAMILIES["fm"]()
    reqs = _requests(servable, n_reqs=8)

    def fn(slave, rank):
        if rank != 0:
            return serve_worker(slave, servable)
        fe = ServeFrontend(slave, servable, deadline_ms=5.0,
                           max_batch=4)
        try:
            futs = [fe.submit(r) for r in reqs]
            [f.wait(30.0) for f in futs]
        finally:
            fe.close()
        return slave.metrics_registry().snapshot()

    snap = run_slaves(2, fn)[0]
    counters = snap["counters"]
    assert counters["serve/requests"] == len(reqs)
    assert counters["serve/batches"] >= 2
    assert counters["serve/cache_misses"] >= 1
    h = snap["histograms"]["latency/serve_request"]
    assert h["count"] == len(reqs)
    assert snap["gauges"]["serve/qps"] > 0.0


def test_serve_section_and_live_headline_render():
    from ytk_mp4j_tpu.comm.master import _serve_section
    from ytk_mp4j_tpu.obs.telemetry import format_fleet, format_live

    ranks = {"0": {"counters": {
        "serve/requests": 100, "serve/batches": 20,
        "serve/batch_deadline": 5, "serve/batch_full": 15,
        "serve/cache_hits": 80, "serve/cache_misses": 20,
        "serve/cache_stale": 2, "serve/degraded_batches": 1,
    }, "gauges": {"serve/qps": 42.5}}}
    sec = _serve_section(ranks, {})
    assert sec["active"] and sec["qps"] == pytest.approx(42.5)
    assert sec["requests"] == 100
    assert sec["hit_rate"] == pytest.approx(0.8)
    assert sec["degraded_batches"] == 1

    doc = {"job_id": "j", "slave_num": 1, "window_secs": 5.0,
           "ranks": {}, "cluster": {"rates": {}, "stats": {},
                                    "serve": sec}}
    live = format_live(doc)
    assert "serve: 42.5 QPS" in live
    assert "80% hit" in live and "1 DEGRADED" in live

    # a training job's doc (no serve section) renders no serve line
    doc2 = {"job_id": "j", "slave_num": 1, "window_secs": 5.0,
            "ranks": {}, "cluster": {"rates": {}, "stats": {}}}
    assert "serve:" not in format_live(doc2)

    # fleet: a serve job carries a QPS cell, a batch job shows "-"
    summary = {"job_id": "sj", "slave_num": 2, "ranks_reporting": 2,
               "bytes_per_sec": 0.0, "collectives_per_sec": 0.0,
               "keys_per_sec": 0.0, "wire_bytes": 0, "retries": 0,
               "hosts": {}, "health": {"states": {}}, "roster_gen": 0,
               "serve": sec}
    batch = dict(summary, job_id="bj", serve=None)
    model = {"aggregate": {"jobs": 2, "live": 2, "ranks": 4},
             "jobs": {"a": {"state": "LIVE", "age": 0.0, "url": "u1",
                            "summary": summary},
                      "b": {"state": "LIVE", "age": 0.0, "url": "u2",
                            "summary": batch}},
             "hosts": {}, "shared_hosts": [], "contention": []}
    out = format_fleet(model)
    line_serve = next(ln for ln in out.splitlines() if "sj" in ln)
    line_batch = next(ln for ln in out.splitlines() if "bj" in ln)
    assert "42.5" in line_serve
    assert "42.5" not in line_batch


def test_job_summary_carries_serve_section():
    from ytk_mp4j_tpu.obs.fleet import job_summary
    doc = {"job_id": "x", "slave_num": 1, "roster_gen": 0,
           "ranks": {}, "cluster": {
               "rates": {}, "serve": {"active": True, "qps": 7.0}}}
    s = job_summary(doc)
    assert s["serve"]["qps"] == pytest.approx(7.0)
    doc["cluster"].pop("serve")
    assert job_summary(doc)["serve"] is None


def test_frontend_requires_rank_zero():
    servable = _FAMILIES["linear"]()

    def fn(slave, rank):
        if rank == 0:
            fe = ServeFrontend(slave, servable, deadline_ms=5.0)
            try:
                fe.predict(_requests(servable, 1)[0], timeout=30.0)
            finally:
                fe.close()
            return "frontend"
        with pytest.raises(Mp4jError, match="rank 0"):
            ServeFrontend(slave, servable)
        return serve_worker(slave, servable)

    run_slaves(2, fn)
