"""Shared test helpers: numpy reference reductions, seeded input
generation, and the master+slave-threads socket harness."""

import threading
from pathlib import Path

import numpy as np

# repo root for subprocess-based tests (cwd-independent)
REPO_ROOT = str(Path(__file__).resolve().parents[1])

# single source of truth for the numpy oracle: the check programs' module
from ytk_mp4j_tpu.check._oracle import NP_REF, expected_reduce  # noqa: F401
from ytk_mp4j_tpu.comm.master import Master
from ytk_mp4j_tpu.comm.process_comm import ProcessCommSlave


def make_inputs(n, length, operand, rng):
    if operand.dtype.kind == "f":
        return [rng.standard_normal(length).astype(operand.dtype)
                for _ in range(n)]
    return [rng.integers(1, 4, length).astype(operand.dtype)
            for _ in range(n)]


def run_slaves(n, fn, timeout=60.0, **slave_kwargs):
    """Start a master + n slave threads; fn(slave, rank) runs per rank.
    Returns per-rank results; raises the first slave error; asserts the
    master's aggregate exit code is 0. ``slave_kwargs`` are forwarded to
    every ProcessCommSlave (e.g. native_transport=False)."""
    master = Master(n, timeout=timeout).serve_in_thread()
    results = [None] * n
    errors = []

    def worker():
        slave = None
        try:
            slave = ProcessCommSlave("127.0.0.1", master.port,
                                     timeout=timeout, **slave_kwargs)
            results[slave.rank] = fn(slave, slave.rank)
            slave.close(0)
        except Exception as e:  # pragma: no cover - surfaced via errors
            errors.append(e)
            if slave is not None:
                try:
                    slave.close(1)
                except Exception:
                    pass

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
        assert not t.is_alive(), "slave thread hung"
    if errors:
        raise errors[0]
    master.join(timeout)
    assert master.final_code == 0
    return results
