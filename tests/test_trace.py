"""Per-collective tracing subsystem (SURVEY.md section 5: the tracing
aux subsystem the reference lacks)."""

import numpy as np

from ytk_mp4j_tpu import trace_collectives
from ytk_mp4j_tpu.comm.thread_comm import ThreadCommSlave
from ytk_mp4j_tpu.comm.tpu_comm import TpuCommCluster
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operators
from ytk_mp4j_tpu.utils import trace

from helpers import run_slaves


def test_disabled_records_nothing():
    trace.clear()
    cluster = TpuCommCluster(2)
    arrs = [np.ones(8, np.float32) for _ in range(2)]
    cluster.allreduce_array(arrs, Operands.FLOAT, Operators.SUM)
    assert trace.events() == []


def test_device_path_traced():
    cluster = TpuCommCluster(2)
    arrs = [np.ones(1024, np.float32) for _ in range(2)]
    with trace_collectives():
        cluster.allreduce_array(arrs, Operands.FLOAT, Operators.SUM)
        cluster.broadcast_array(arrs, Operands.FLOAT, root=0)
    ev = trace.events()
    names = [e[0] for e in ev]
    assert "TpuCommCluster.allreduce_array" in names
    assert "TpuCommCluster.broadcast_array" in names
    for name, sec, nb in ev:
        assert sec > 0
    # first data arg is the per-rank array list: 2 ranks x 4 KiB
    ar = dict((e[0], e) for e in ev)["TpuCommCluster.allreduce_array"]
    assert ar[2] == 2 * 1024 * 4
    # tracing stops outside the scope
    cluster.allreduce_array(arrs, Operands.FLOAT, Operators.SUM)
    assert len(trace.events()) == len(ev)


def test_socket_path_traced_and_summary():
    with trace_collectives():
        def fn(slave, r):
            arr = np.full(256, float(r))
            slave.allreduce_array(arr, Operands.DOUBLE, Operators.SUM)
            slave.allreduce_array(arr, Operands.DOUBLE, Operators.SUM)
            return arr

        run_slaves(2, fn)
    agg = trace.summary()
    a = agg["ProcessCommSlave.allreduce_array"]
    assert a["calls"] == 4  # 2 ranks x 2 calls
    assert a["bytes"] == 4 * 256 * 8
    assert a["gb_per_s"] > 0
    text = trace.format_summary()
    assert "ProcessCommSlave.allreduce_array" in text


def test_thread_path_traced():
    slaves = ThreadCommSlave.spawn_group(2)
    import threading

    with trace_collectives():
        def worker(sl):
            d = {"a": 1.0}
            sl.allreduce_map(d, Operands.DOUBLE, Operators.SUM)

        ts = [threading.Thread(target=worker, args=(s,)) for s in slaves]
        [t.start() for t in ts]
        [t.join(30) for t in ts]
    names = [e[0] for e in trace.events()]
    assert names.count("ThreadCommSlave.allreduce_map") == 2


def test_composed_collectives_record_once():
    """allgather_map composes gather_map + broadcast_map internally; only
    the outermost call may record (no phantom rows, no double counting)."""
    cluster = TpuCommCluster(2)
    maps = [{"a": 1.0}, {"b": 2.0}]
    with trace_collectives():
        cluster.allgather_map(maps, Operands.DOUBLE)
    names = [e[0] for e in trace.events()]
    assert names == ["TpuCommCluster.allgather_map"]


def test_profiler_scope_cannot_nest(tmp_path):
    with trace_collectives():
        pass  # plain scopes nest fine (covered below)
    outer = trace_collectives(profile_dir=str(tmp_path / "p1"))
    inner = trace_collectives(profile_dir=str(tmp_path / "p2"))
    with outer:
        try:
            inner.__enter__()
            raised = False
        except RuntimeError:
            raised = True
        assert raised
    # the failed inner scope must not have corrupted the depth/profiler
    # bookkeeping: a fresh profiler scope works
    with trace_collectives(profile_dir=str(tmp_path / "p3")):
        pass
    assert trace.events() == []


def test_summary_percentiles():
    """One straggling call must be visible behind a healthy mean."""
    trace.clear()
    with trace_collectives():
        for sec in (0.01,) * 9 + (1.0,):
            trace.record("x.allreduce", sec, 100)
    a = trace.summary()["x.allreduce"]
    assert a["calls"] == 10
    assert a["p50"] == 0.01
    assert a["p95"] == 1.0
    assert a["max"] == 1.0
    header, *rows = trace.format_summary().splitlines()
    assert "p50ms" in header and "p95ms" in header and "maxms" in header
    assert "1000.000" in rows[0]  # the 1 s straggler, in ms
    trace.clear()


def test_payload_bytes_dedup_and_scalars():
    """Views sharing one base buffer count once per distinct base;
    non-numeric scalars count 0, not a phantom 8."""
    base = np.zeros(100, np.float64)
    # two views of the same buffer in one dict: counted once
    assert trace._payload_bytes(
        {"a": base[:50], "b": base[50:]}) == base[:50].nbytes
    # the same array twice in a list: counted once
    assert trace._payload_bytes([base, base]) == base.nbytes
    # distinct buffers still sum
    other = np.zeros(10, np.float32)
    assert trace._payload_bytes([base, other]) == base.nbytes + other.nbytes
    # a bare top-level array is its own size (no container, no dedup)
    assert trace._payload_bytes(base[:10]) == 80
    # scalars: numeric 8, non-numeric 0
    assert trace._payload_bytes(3) == 8
    assert trace._payload_bytes(np.float32(1.0)) == 4  # true scalar nbytes
    assert trace._payload_bytes(None) == 0
    assert trace._payload_bytes(np.str_("abc")) == 0
    assert trace._payload_bytes({"k": None}) == 0
    assert trace._payload_bytes(object()) == 0


def test_nested_scopes():
    trace.clear()
    cluster = TpuCommCluster(2)
    arrs = [np.ones(8, np.float32) for _ in range(2)]
    with trace_collectives():
        with trace_collectives(clear=False):
            cluster.barrier()
        cluster.barrier()  # outer scope still active
    assert len([e for e in trace.events()
                if e[0] == "TpuCommCluster.barrier"]) == 2
    cluster.barrier()
    assert len([e for e in trace.events()
                if e[0] == "TpuCommCluster.barrier"]) == 2
