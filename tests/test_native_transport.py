"""Native raw data plane (csrc/mp4j_transport.cpp + the wire-identical
Python raw fallback): framed and raw jobs must produce identical
collective results, for power-of-2 and folded rank counts, both
allreduce algorithms, and with the native library force-disabled."""

import numpy as np
import pytest

from tests.helpers import run_slaves
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operators
from ytk_mp4j_tpu.utils import native


@pytest.mark.parametrize("n", [4, 5])
@pytest.mark.parametrize("algo", ["rhd", "ring"])
def test_raw_matches_framed(rng, n, algo):
    data = [rng.standard_normal(1000).astype(np.float32) for _ in range(n)]
    want = np.sum(data, axis=0)

    def job(native_transport):
        def fn(slave, rank):
            arr = data[rank].copy()
            slave.allreduce_array(arr, Operands.FLOAT, Operators.SUM,
                                  algo=algo)
            return arr
        return run_slaves(n, fn, native_transport=native_transport)

    for out in job(True) + job(False):
        np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)


def test_raw_subrange_and_max(rng):
    """Sub-range semantics + a non-SUM operator through the raw plane."""
    n = 4
    data = [rng.standard_normal(50).astype(np.float64) for _ in range(n)]
    want = np.max(data, axis=0)

    def fn(slave, rank):
        arr = data[rank].copy()
        slave.allreduce_array(arr, Operands.DOUBLE, Operators.MAX,
                              from_=10, to=40)
        return arr

    for out, orig in zip(run_slaves(n, fn), data):
        np.testing.assert_allclose(out[10:40], want[10:40])
        np.testing.assert_array_equal(out[:10], orig[:10])
        np.testing.assert_array_equal(out[40:], orig[40:])


def test_python_raw_fallback_is_wire_identical(rng, monkeypatch):
    """With the native library force-disabled the raw exchange must run
    through the pure-Python path and still produce correct results (the
    wire format cannot depend on local library availability)."""
    native._load()  # settle the tri-state before patching
    monkeypatch.setattr(native, "HAVE_NATIVE", False)
    monkeypatch.setattr(native, "_lib", None)
    n = 5
    data = [rng.standard_normal(321).astype(np.float32) for _ in range(n)]
    want = np.sum(data, axis=0)

    def fn(slave, rank):
        arr = data[rank].copy()
        slave.allreduce_array(arr, Operands.FLOAT, Operators.SUM)
        return arr

    for out in run_slaves(n, fn):
        np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)


def test_compressed_operand_stays_framed(rng):
    """Compressed operands can't use the raw plane (sizes are dynamic);
    the job must still work with native_transport=True."""
    n = 4
    data = [np.full(2000, rank + 1.0, np.float32) for rank in range(n)]

    def fn(slave, rank):
        arr = data[rank].copy()
        slave.allreduce_array(arr, Operands.compressed(Operands.FLOAT),
                              Operators.SUM)
        return arr

    want = np.sum(data, axis=0)
    for out in run_slaves(n, fn):
        np.testing.assert_allclose(out, want, rtol=1e-5)
