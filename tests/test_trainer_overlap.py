"""ISSUE 17 (mp4j-overlap) conformance: the trainer epoch loops under
``MP4J_OVERLAP=1`` — step k's stats exchange posted nonblocking and
drained at the loop boundary — must be BIT-EXACT against today's
blocking loops on every backend (the exchanged stats are observational,
never control flow, so only the wait point moves), the dense
small-array coalesced plane must match the sequential ``i*`` stream
bit-exactly, shm-paired async jobs must route ring-eligible chunks
through the SPSC rings, and a fault mid-overlapped-epoch must recover
bit-exact or fail cleanly on every rank — never hang."""

import os

import numpy as np
import pytest

from helpers import run_slaves
from ytk_mp4j_tpu.models._base import StepStatsExchanger
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operators

JOIN = 60.0


def _leaves(tree):
    """Model params as a flat list of host arrays (bit-comparable)."""
    import jax

    return [np.asarray(x).copy()
            for x in jax.tree_util.tree_leaves(tree)]


# ----------------------------------------------------------------------
# trainer-overlap conformance grid: MP4J_OVERLAP on == off, bit-exact
# ----------------------------------------------------------------------
def _linear_epoch(slave, r):
    from ytk_mp4j_tpu.models.linear import LinearConfig, LinearTrainer
    from ytk_mp4j_tpu.parallel import make_mesh

    rng = np.random.default_rng(7)            # same data on every rank
    x = rng.standard_normal((64, 4)).astype(np.float32)
    y = (x @ np.array([1.0, -2.0, 0.5, 0.0], np.float32))
    cfg = LinearConfig(n_features=4, loss="squared", learning_rate=0.1)
    tr = LinearTrainer(cfg, mesh=make_mesh(1))
    params, losses = tr.fit(x, y, n_steps=4, comm=slave)
    return _leaves(params) + [losses, tr.sync_loss_history_.copy()]


def _fm_epoch(slave, r):
    from ytk_mp4j_tpu.models.fm import FMConfig, FMTrainer
    from ytk_mp4j_tpu.parallel import make_mesh

    rng = np.random.default_rng(11)
    n, nnz = 48, 3
    feats = rng.integers(0, 32, (n, nnz)).astype(np.int32)
    fields = np.broadcast_to(np.arange(nnz, dtype=np.int32) % 2,
                             (n, nnz)).copy()
    vals = np.ones((n, nnz), np.float32)
    y = ((feats[:, 0] + feats[:, 1]) % 2).astype(np.float32)
    cfg = FMConfig(n_features=32, n_fields=2, k=2, max_nnz=nnz,
                   model="fm", learning_rate=0.3, init_scale=0.1)
    tr = FMTrainer(cfg, mesh=make_mesh(1))
    params, losses = tr.fit(feats, fields, vals, y, n_steps=4, seed=3,
                            comm=slave)
    return _leaves(params) + [losses, tr.sync_loss_history_.copy()]


def _gbdt_epoch(slave, r):
    from ytk_mp4j_tpu.models.gbdt import GBDTConfig, GBDTTrainer
    from ytk_mp4j_tpu.parallel import make_mesh

    rng = np.random.default_rng(13)
    bins = rng.integers(0, 8, (96, 3)).astype(np.int32)
    y = (bins[:, 1] > 4).astype(np.float32)
    cfg = GBDTConfig(n_features=3, n_bins=8, depth=2, n_trees=3,
                     learning_rate=0.5, loss="logistic")
    tr = GBDTTrainer(cfg, mesh=make_mesh(1))
    trees, margins = tr.train(bins, y, seed=5, comm=slave)
    return [np.asarray(margins).copy(), tr.sync_round_history_]


_FAMILIES = {"linear": _linear_epoch, "fm": _fm_epoch,
             "gbdt": _gbdt_epoch}


def _run_family(monkeypatch, family, overlap, **kw):
    monkeypatch.setenv("MP4J_OVERLAP", "1" if overlap else "0")
    try:
        return run_slaves(2, _FAMILIES[family], timeout=JOIN, **kw)
    finally:
        monkeypatch.delenv("MP4J_OVERLAP", raising=False)


def _assert_same(want, got):
    for w, g in zip(want, got):
        for a, b in zip(w, g):
            if isinstance(a, dict) or isinstance(a, list) \
                    and a and isinstance(a[0], dict):
                assert a == b                  # bit-exact, no tolerance
            else:
                np.testing.assert_array_equal(np.asarray(a),
                                              np.asarray(b))


@pytest.mark.parametrize("family", ["linear", "fm", "gbdt"])
def test_trainer_overlap_bit_exact_per_family(family, monkeypatch):
    """One epoch per model family: MP4J_OVERLAP=1 == 0 bit-exact —
    params/margins, local losses AND the synced job-wide history."""
    want = _run_family(monkeypatch, family, overlap=False)
    got = _run_family(monkeypatch, family, overlap=True)
    _assert_same(want, got)


@pytest.mark.parametrize("transport", ["tcp", "shm"])
@pytest.mark.parametrize("async_on", [True, False])
def test_trainer_overlap_backend_grid(transport, async_on, monkeypatch):
    """The backend grid on the fastest family: socket {tcp, shm} x
    scheduler backend {progression thread, eager caller thread
    (MP4J_ASYNC=0's _isubmit twin)} — overlap on == off everywhere."""
    kw = {"shm": transport == "shm", "async_collectives": async_on}
    want = _run_family(monkeypatch, "linear", overlap=False, **kw)
    got = _run_family(monkeypatch, "linear", overlap=True, **kw)
    _assert_same(want, got)


# ----------------------------------------------------------------------
# coalesced array plane == sequential i*, bit-exact
# ----------------------------------------------------------------------
def _array_stream(slave, r, arrays=12, size=32):
    bufs = [np.full(size, float(r + 1) * (i + 1), np.float64)
            for i in range(arrays)]
    for b in bufs:
        slave.iallreduce(b, Operands.DOUBLE, Operators.SUM)
    slave.wait_all()
    return bufs, slave.stats()


@pytest.mark.parametrize("n", [3, 5])
def test_coalesced_array_matches_sequential_grid(n, monkeypatch):
    """The dense small-array fused plane (consecutive same-signature
    iallreduce submissions -> ONE count-negotiated multi-exchange)
    against the same stream submitted sequentially with the window
    off: bit-exact, and the window leg really fused (coalesced_elems
    booked)."""
    monkeypatch.setenv("MP4J_COALESCE_USECS", "0")
    want = run_slaves(n, _array_stream, timeout=JOIN)
    monkeypatch.setenv("MP4J_COALESCE_USECS", "500")
    got = run_slaves(n, _array_stream, timeout=JOIN)
    for (wb, _), (gb, gst) in zip(want, got):
        for a, b in zip(wb, gb):
            np.testing.assert_array_equal(a, b)
    assert sum(st.get("allreduce_array_multi", {})
               .get("coalesced_elems", 0)
               for _, st in got) > 0


def test_array_multi_ragged_offer_negotiates_min():
    """Direct allreduce_array_multi with ragged offers: the fused
    count is the min over ranks; un-merged arrays stay untouched and
    a follow-up call drains them — matching the blocking twin."""
    def mk(r, i, size=16):
        return np.full(size, float(r + 1) * (i + 1), np.float64)

    def blocking(slave, r):
        outs = [mk(r, i) for i in range(3)]
        for a in outs:
            slave.allreduce_array(a, Operands.DOUBLE, Operators.SUM)
        return outs

    def fused(slave, r):
        arrs = [mk(r, i) for i in range(3)]
        if r == 0:
            assert slave.allreduce_array_multi(
                [arrs[0]], Operands.DOUBLE, Operators.SUM) == 1
            assert slave.allreduce_array_multi(
                arrs[1:], Operands.DOUBLE, Operators.SUM) == 2
        else:
            m1 = slave.allreduce_array_multi(
                list(arrs), Operands.DOUBLE, Operators.SUM)
            assert m1 == 1          # min over offers (rank 0 offered 1)
            np.testing.assert_array_equal(arrs[1], mk(r, 1))
            assert slave.allreduce_array_multi(
                arrs[1:], Operands.DOUBLE, Operators.SUM) == 2
        return arrs

    want = run_slaves(3, blocking, timeout=JOIN)
    got = run_slaves(3, fused, timeout=JOIN)
    for w, g in zip(want, got):
        for a, b in zip(w, g):
            np.testing.assert_array_equal(a, b)


def test_engine_tiny_odd_payload_stream_ordering():
    """Regression (found by the trainer loops' 1-element stats
    arrays): k outstanding 1-element iallreduces — rhd hands some
    rank an EMPTY segment, i.e. zero-length legs — must pair
    collective k with collective k on every rank. The full-batch
    leg-graph driver once let zero-length legs anchor its per-
    (peer, dir) FIFO gate chain; born "complete", they unblocked
    successors ahead of the chain behind them and the fd slot scan
    paired the stream's bytes with the wrong collective."""
    def fn(slave, r):
        bufs = [np.array([float((r + 1) * 10 + k)]) for k in range(6)]
        for b in bufs:
            slave.iallreduce(b, Operands.DOUBLE, Operators.SUM)
        slave.wait_all()
        return [float(b[0]) for b in bufs]

    for n in (2, 3):
        want = [float(sum((rr + 1) * 10 + k for rr in range(n)))
                for k in range(6)]
        for out in run_slaves(n, fn, timeout=JOIN, shm=False,
                              async_collectives=True):
            assert out == want


# ----------------------------------------------------------------------
# shm ring routing on the engine path
# ----------------------------------------------------------------------
def test_engine_shm_legs_ride_rings():
    """A shm-paired async job's ring-eligible chunks go through the
    SPSC rings (the engine's nonblocking pumps), not the carrier
    socket: the ring share of the shm plane's wire bytes dominates for
    ring-sized payloads."""
    def fn(slave, r):
        a = np.full(600_000, float(r + 1), np.float64)   # 4.8 MB
        fut = slave.iallreduce(a, Operands.DOUBLE, Operators.SUM)
        fut.wait()
        return a, slave.stats()

    out = run_slaves(2, fn, timeout=JOIN)
    want = np.full(600_000, 3.0, np.float64)
    ring = shm = 0
    for a, st in out:
        np.testing.assert_array_equal(a, want)
        for entry in st.values():
            ring += entry.get("wire_bytes_shm_ring", 0)
            shm += entry.get("wire_bytes_shm", 0)
    assert ring > 0, "async shm job booked no ring bytes"
    assert ring >= 0.5 * shm, \
        f"ring share too low: {ring}/{shm} — chunks fell back to the " \
        f"carrier socket"


# ----------------------------------------------------------------------
# chaos mid-overlapped-epoch: recover bit-exact or fail clean — no hangs
# ----------------------------------------------------------------------
def _overlapped_epoch(slave, r):
    ex = StepStatsExchanger(slave, overlap=True)
    for k in range(4):
        ex.submit(np.full(64, float((r + 1) * (k + 1)), np.float64))
    ex.drain()
    return ex.mean_history()


def test_chaos_reset_mid_overlapped_epoch():
    """A connection reset mid-overlapped-epoch: either the engine's
    epoch-fenced recovery completes the drain bit-exact against an
    unfaulted run, or EVERY rank raises the same clean fatal — and
    nobody hangs (run_chaos's hard join deadline)."""
    from test_resilience import run_chaos
    from ytk_mp4j_tpu.exceptions import Mp4jFatalError

    kw = {"async_collectives": True}
    want, werr, _, _ = run_chaos(4, _overlapped_epoch,
                                 fault_plan=None, **kw)
    assert all(e is None for e in werr), werr
    got, errors, stats, log = run_chaos(
        4, _overlapped_epoch, fault_plan="reset:rank=1:nth=2", **kw)
    if any(errors):
        assert all(isinstance(e, Mp4jFatalError) for e in errors), \
            f"{errors}\n{log}"
    else:
        for w, g in zip(want, got):
            np.testing.assert_array_equal(w, g)
        tot = sum(int(e.get("retries", 0)) for snap in stats
                  for e in (snap or {}).values())
        assert tot >= 1, "reset fault never fired"


def test_chaos_kill_mid_overlapped_epoch_fails_clean():
    """A rank killed mid-overlapped-epoch: the killed rank dies with
    its injected fault, every survivor surfaces a clean Mp4jFatalError
    at (or before) the drain — never a hang, never a silent partial
    history."""
    from test_resilience import run_chaos
    from ytk_mp4j_tpu.resilience.faults import FaultKill
    from ytk_mp4j_tpu.exceptions import Mp4jFatalError

    _, errors, _, log = run_chaos(
        4, _overlapped_epoch, fault_plan="kill:rank=2:nth=2",
        async_collectives=True)
    assert isinstance(errors[2], FaultKill), f"{errors}\n{log}"
    survivors = [errors[r] for r in range(4) if r != 2]
    assert all(isinstance(e, Mp4jFatalError) for e in survivors), \
        f"{errors}\n{log}"
