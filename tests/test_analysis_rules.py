"""Rule-engine unit tests: every mp4j-lint rule has a known-bad snippet
it must flag and a known-good snippet it must not, plus engine-level
tests for suppressions, the baseline format, and parse failures."""

import textwrap

import pytest

from ytk_mp4j_tpu.analysis import baseline as baseline_mod
from ytk_mp4j_tpu.analysis.engine import Engine, parse_inline_suppressions
from ytk_mp4j_tpu.analysis.report import Severity
from ytk_mp4j_tpu.analysis.rules import ALL_RULES, get_rules
from ytk_mp4j_tpu.exceptions import Mp4jError

COMM_PATH = "ytk_mp4j_tpu/comm/snippet.py"


def run_rule(rule_id, src, path=COMM_PATH, baseline=None):
    engine = Engine(rules=get_rules([rule_id]), baseline=baseline)
    result = engine.lint_source(textwrap.dedent(src), path)
    assert not [f for f in result.findings if f.rule == "E001"], \
        f"snippet failed to parse: {result.findings}"
    return result


# ----------------------------------------------------------------------
# R1 — rank-conditional collective
# ----------------------------------------------------------------------
def test_r1_fires_on_one_armed_collective():
    r = run_rule("R1", """
        def step(comm, x):
            if comm.rank == 0:
                comm.broadcast_array(x)
    """)
    [f] = r.findings
    assert f.rule == "R1" and f.line == 3
    assert "broadcast_array" in f.message


def test_r1_fires_on_unbalanced_elif():
    r = run_rule("R1", """
        def step(comm, x):
            if comm.rank == 0:
                comm.barrier()
            elif comm.rank == 1:
                comm.barrier()
    """)
    # the elif arm has no matching call for ranks >= 2
    assert [f.line for f in r.findings] == [5]


def test_r1_quiet_on_balanced_branches():
    r = run_rule("R1", """
        def step(comm, x, y):
            if comm.rank == 0:
                comm.broadcast_array(x)
            else:
                comm.broadcast_array(y)
    """)
    assert not r.findings


def test_r1_quiet_on_point_to_point_and_nonrank():
    r = run_rule("R1", """
        def reduce(self, vr, mask, acc, operand):
            if vr & mask:
                self._send_segment(0, acc, operand)
            if acc is None:
                self.allreduce_array(acc)
    """)
    assert not r.findings


def test_r1_ignores_collectives_in_nested_defs():
    r = run_rule("R1", """
        def step(comm):
            if comm.rank == 0:
                def later():
                    comm.barrier()
    """)
    assert not r.findings


# ----------------------------------------------------------------------
# R2 — unbounded socket ops
# ----------------------------------------------------------------------
def test_r2_fires_on_naked_recv():
    r = run_rule("R2", """
        class C:
            def pull(self):
                return self.sock.recv(1024)
    """)
    [f] = r.findings
    assert f.rule == "R2" and "recv" in f.message
    assert f.context == "C.pull"


def test_r2_quiet_with_timeout_handler():
    r = run_rule("R2", """
        import socket
        class C:
            def pull(self):
                try:
                    return self.sock.recv(1024)
                except socket.timeout:
                    raise Mp4jError("dead peer")
    """)
    assert not r.findings


def test_r2_quiet_after_settimeout_same_receiver():
    r = run_rule("R2", """
        class C:
            def pull(self):
                self.sock.settimeout(5.0)
                return self.sock.recv(1024)
    """)
    assert not r.findings


def test_r2_settimeout_is_receiver_aware():
    r = run_rule("R2", """
        class C:
            def pull(self, ch):
                self.server.settimeout(5.0)
                return ch.recv()
    """)
    assert len(r.findings) == 1


def test_r2_settimeout_none_does_not_count():
    r = run_rule("R2", """
        class C:
            def pull(self):
                self.sock.settimeout(None)
                return self.sock.recv(1024)
    """)
    assert len(r.findings) == 1


def test_r2_quiet_on_own_wrapper_delegation():
    r = run_rule("R2", """
        class Channel:
            def recv_array(self):
                return self.recv()
    """)
    assert not r.findings


# ----------------------------------------------------------------------
# R3 — thread-group shared state outside the lock
# ----------------------------------------------------------------------
def test_r3_fires_on_unlocked_store():
    r = run_rule("R3", """
        class T:
            def f(self):
                self._g.result = 1
    """)
    [f] = r.findings
    assert "result" in f.message


def test_r3_fires_through_local_alias():
    r = run_rule("R3", """
        class T:
            def f(self):
                slots = self._g.slots
                slots[0] = None
    """)
    [f] = r.findings
    assert "slots" in f.message and f.line == 5


def test_r3_fires_on_mutator_call():
    r = run_rule("R3", """
        class T:
            def f(self, x):
                self._g.slots.append(x)
    """)
    assert len(r.findings) == 1


def test_r3_quiet_under_lock():
    r = run_rule("R3", """
        class T:
            def f(self):
                with self._g.lock:
                    self._g.max_code = 2
                    self._g.pending_closes -= 1
    """)
    assert not r.findings


def test_r3_quiet_on_non_group_receiver():
    r = run_rule("R3", """
        class T:
            def f(self):
                self.result = 1
                self.other.slots = []
    """)
    assert not r.findings


# ----------------------------------------------------------------------
# R4 — operand mismatch between paired segment transfers
# ----------------------------------------------------------------------
def test_r4_fires_on_operand_mismatch():
    r = run_rule("R4", """
        class C:
            def bcast(self, arr, operand):
                if self.rank == 0:
                    self._send_segment(1, arr, operand)
                else:
                    self._recv_segment_into(0, arr, 0, 8, Operands.DOUBLE)
    """)
    [f] = r.findings
    assert "Operands.DOUBLE" in f.message and "operand" in f.message


def test_r4_quiet_on_consistent_operand():
    r = run_rule("R4", """
        class C:
            def bcast(self, arr, operand):
                if self.rank == 0:
                    self._send_segment(1, arr, operand)
                else:
                    self._recv_segment(0, 8, operand)
    """)
    assert not r.findings


def test_r4_scopes_per_function():
    # different collectives may use different operands — only intra-
    # function disagreement is a paired-exchange mismatch
    r = run_rule("R4", """
        class C:
            def a(self, arr, operand):
                self._send_segment(1, arr, operand)
            def b(self, arr):
                self._recv_segment(0, 8, Operands.FLOAT)
    """)
    assert not r.findings


# ----------------------------------------------------------------------
# R5 — swallowed exceptions
# ----------------------------------------------------------------------
def test_r5_fires_on_bare_except_anywhere():
    r = run_rule("R5", """
        def f():
            try:
                g()
            except:
                raise RuntimeError("x")
    """, path="ytk_mp4j_tpu/models/snippet.py")
    [f] = r.findings
    assert "bare" in f.message


def test_r5_fires_on_swallowed_broad_except_in_comm():
    r = run_rule("R5", """
        def f():
            try:
                g()
            except Exception:
                pass
    """)
    [f] = r.findings
    assert "swallows" in f.message


def test_r5_quiet_on_narrow_or_handled():
    r = run_rule("R5", """
        def f():
            try:
                g()
            except OSError:
                pass
            try:
                g()
            except Exception as e:
                log(e)
    """)
    assert not r.findings


def test_r5_broad_swallow_ok_outside_hot_paths():
    r = run_rule("R5", """
        def f():
            try:
                g()
            except Exception:
                pass
    """, path="ytk_mp4j_tpu/models/snippet.py")
    assert not r.findings


# ----------------------------------------------------------------------
# R6 — aliased slot returned from a fan-out leader
# ----------------------------------------------------------------------
def test_r6_fires_on_raw_slot_return():
    r = run_rule("R6", """
        class T:
            def allreduce(self):
                def leader(slots):
                    acc = slots[0]
                    return acc
    """)
    [f] = r.findings
    assert f.line == 6


def test_r6_fires_on_conditional_slot_return():
    r = run_rule("R6", """
        class T:
            def bcast(self, root):
                def leader(slots):
                    return slots[root] if root else slots[0]
    """)
    assert len(r.findings) == 1


def test_r6_quiet_on_detached_returns():
    r = run_rule("R6", """
        class T:
            def bcast(self):
                def leader(slots):
                    return self._detach(slots[0])
            def gather(self):
                def leader(slots):
                    full = build(slots)
                    return full
    """)
    assert not r.findings


# ----------------------------------------------------------------------
# R7 — mutable defaults and module-level mutable state
# ----------------------------------------------------------------------
def test_r7_fires_on_mutable_default():
    r = run_rule("R7", """
        def f(x, acc=[], *, opts={}):
            acc.append(x)
    """, path="ytk_mp4j_tpu/models/snippet.py")   # anywhere, not just comm
    assert sorted("acc" in f.message or "opts" in f.message
                  for f in r.findings) == [True, True]


def test_r7_fires_on_mutated_module_state_in_comm():
    r = run_rule("R7", """
        _CACHE = {}

        def put(k, v):
            _CACHE[k] = v
    """)
    [f] = r.findings
    assert "_CACHE" in f.message and f.line == 2


def test_r7_quiet_on_readonly_table_and_instance_state():
    r = run_rule("R7", """
        _TABLE = {1: "a", 2: "b"}

        class C:
            def __init__(self):
                self.cache = {}

            def get(self, k):
                return _TABLE[k]

            def put(self, k, v):
                self.cache[k] = v
    """)
    assert not r.findings


def test_r7_module_state_not_flagged_outside_comm_dirs():
    r = run_rule("R7", """
        _CACHE = {}

        def put(k, v):
            _CACHE[k] = v
    """, path="ytk_mp4j_tpu/models/snippet.py")
    assert not r.findings


# ----------------------------------------------------------------------
# engine: suppressions, baseline, parse errors
# ----------------------------------------------------------------------
def test_inline_suppression_same_line_and_line_above():
    src = """
        def f(comm, x):
            if comm.rank == 0:  # mp4j-lint: disable=R1 (balanced elsewhere)
                comm.barrier()
            # mp4j-lint: disable=R1 (documented leader pattern)
            if comm.rank == 1:
                comm.barrier()
            if comm.rank == 2:
                comm.barrier()
    """
    r = run_rule("R1", src)
    assert len(r.findings) == 1        # only the unsuppressed third branch
    assert r.findings[0].line == 8
    assert len(r.suppressed) == 2


def test_inline_suppression_is_rule_specific():
    r = run_rule("R1", """
        def f(comm, x):
            if comm.rank == 0:  # mp4j-lint: disable=R2
                comm.barrier()
    """)
    assert len(r.findings) == 1


def test_parse_directive_formats():
    sup = parse_inline_suppressions(
        "x = 1  # mp4j-lint: disable=R1,R3 (reason text)\n")
    assert sup[1] == {"R1", "R3"}


def test_baseline_match_context_and_contains():
    bl = baseline_mod.parse(textwrap.dedent("""
        # a comment
        [[suppression]]
        rule = "R3"
        file = "ytk_mp4j_tpu/comm/snippet.py"
        context = "T.f"
        reason = "barrier-delimited"
    """))
    r = run_rule("R3", """
        class T:
            def f(self):
                self._g.result = 1
            def g(self):
                self._g.result = 2
    """, baseline=bl)
    assert [f.context for f in r.findings] == ["T.g"]
    assert [f.context for f in r.suppressed] == ["T.f"]
    assert not bl.unused()


def test_baseline_rejects_unsupported_syntax():
    with pytest.raises(Mp4jError):
        baseline_mod.parse("[[suppression]]\nrule = 42\n")
    with pytest.raises(Mp4jError):
        baseline_mod.parse('[[suppression]]\nrule = "R1"\n')  # missing file


def test_syntax_error_reported_as_finding():
    r = Engine(rules=get_rules()).lint_source("def f(:\n", "bad.py")
    [f] = r.findings
    assert f.rule == "E001" and f.severity == Severity.ERROR


def test_rule_catalogue_complete():
    ids = [cls.rule_id for cls in ALL_RULES]
    assert ids == [f"R{i}" for i in range(1, 29)]
    with pytest.raises(KeyError):
        get_rules(["R99"])


# ----------------------------------------------------------------------
# R8 — chunk schedule derived from rank-local state
# ----------------------------------------------------------------------
def test_r8_fires_on_rank_dependent_chunk_loop():
    r = run_rule("R8", """
        def exchange(self, arr):
            for lo, hi in chunk_ranges(arr.size - self.rank, 8, CHUNK):
                self._exchange_raw(1, 1, arr[lo:hi], None)
    """)
    [f] = r.findings
    assert f.rule == "R8" and f.line == 3
    assert "rank" in f.message or "job-wide" in f.message


def test_r8_fires_on_rank_dependent_chunk_while():
    r = run_rule("R8", """
        def drain(self, vr):
            sent = 0
            while sent < self.n_chunks - vr:
                sent += 1
    """)
    [f] = r.findings
    assert f.rule == "R8" and f.line == 4


def test_r8_clean_on_size_derived_chunk_loop():
    # the engine's real shape: schedule from (size, dtype, env knob)
    r = run_rule("R8", """
        def exchange(self, arr, operand):
            for lo, hi in tuning.chunk_ranges(arr.size,
                                              operand.dtype.itemsize,
                                              self._chunk_bytes):
                self._exchange_raw(1, 1, arr[lo:hi], None)
    """)
    assert not r.findings


def test_r8_clean_on_rank_indexed_segment_loop():
    # using the rank to pick WHICH segment moves is the normal ring /
    # halving shape; only the chunk-loop header is schedule-bearing
    r = run_rule("R8", """
        def ring(self, arr, segs):
            for s in range(self.n - 1):
                ss, se = segs[(self.rank - 1 - s) % self.n]
                self._send_chunk(arr[ss:se])
    """)
    assert not r.findings


def test_r8_scoped_to_comm_transport():
    src = """
        def exchange(self, arr):
            for lo, hi in chunk_ranges(arr.size - self.rank, 8, CHUNK):
                pass
    """
    assert not run_rule("R8", src,
                        path="ytk_mp4j_tpu/models/snippet.py").findings
    assert run_rule("R8", src,
                    path="ytk_mp4j_tpu/transport/snippet.py").findings


def test_r8_inline_suppression():
    r = run_rule("R8", """
        def exchange(self, arr):
            # mp4j-lint: disable=R8 (trip count proven equal on peers)
            for lo, hi in chunk_ranges(arr.size - self.rank, 8, CHUNK):
                pass
    """)
    assert not r.findings
    assert len(r.suppressed) == 1


# ----------------------------------------------------------------------
# R9 — pickled dict payload on a collective map path
# ----------------------------------------------------------------------
def test_r9_fires_on_dict_send_in_map_function():
    r = run_rule("R9", """
        def reduce_map(self, d, operand, operator, root):
            acc = dict(d)
            self._send(0, acc, compress=operand.compress)
    """)
    [f] = r.findings
    assert f.rule == "R9" and f.line == 4
    assert "codes" in f.message


def test_r9_fires_on_parameter_and_subscript_payloads():
    r = run_rule("R9", """
        def broadcast_map(self, d):
            self._send(1, d)

        def scatter_map(self, shares, peer):
            self._send(peer, shares[peer])
    """)
    assert [f.line for f in r.findings] == [3, 6]


def test_r9_clean_on_columnar_and_header_sends():
    # the columnar plane's real shape: tuple negotiation headers plus
    # paired column frames — neither is a pickled dict payload
    r = run_rule("R9", """
        def allreduce_map(self, d, operand, operator):
            header = (True, "int", (), [])
            self._send(0, header)
            self._channel(1).send_map_columns(codes, vals)
            self._send_map_columns(2, cols, operand)
    """)
    assert not r.findings


def test_r9_scoped_to_map_functions_in_comm():
    src = """
        def reduce_map(self, d):
            self._send(0, dict(d))
    """
    assert not run_rule("R9", src,
                        path="ytk_mp4j_tpu/models/snippet.py").findings
    # non-map collectives may pickle freely (lists, control tuples)
    r = run_rule("R9", """
        def allreduce_array(self, d):
            self._send(0, dict(d))
    """)
    assert not r.findings


# ----------------------------------------------------------------------
# R10 — peer-channel I/O bypassing the epoch fence
# ----------------------------------------------------------------------
def test_r10_fires_on_direct_channel_io():
    r = run_rule("R10", """
        class ProcessCommSlave:
            def _recv_reduce(self, peer, rbuf):
                self._channel(peer).recv_array_into(rbuf)

            def _send(self, peer, data):
                ch = self._channel(peer)
                ch.send_array(data)
    """)
    assert [f.line for f in r.findings] == [4, 8]
    assert "epoch fence" in r.findings[0].message


def test_r10_fires_on_bare_channel_constructors():
    r = run_rule("R10", """
        class ProcessCommSlave:
            def _dial(self, peer):
                ch = connect(host, port)
                ch.send_obj((self._rank, epoch))

            def _accept_loop(self):
                ch = Channel(sock)
                hs = ch.recv()
    """)
    assert [f.line for f in r.findings] == [5, 9]


def test_r10_quiet_on_fenced_and_master_channels():
    r = run_rule("R10", """
        class ProcessCommSlave:
            def _send(self, peer, data):
                self._fenced(peer).send_array(data)

            def _submit(self, peer, data):
                ch = self._fenced(peer)
                ch.send_obj(data)

            def _master_send(self, obj):
                self._master.send_obj(obj)

            def barrier(self):
                self._master_send(("barrier", 1))
    """)
    assert not r.findings


def test_r10_scoped_to_comm_slave_classes():
    # the master (control plane, no epoch) and non-comm dirs are out
    # of scope
    src = """
        class Master:
            def _serve_slave(self, rank, ch):
                kind, payload = ch.recv()
    """
    assert not run_rule("R10", src).findings
    slave_src = """
        class ProcessCommSlave:
            def _recv(self, peer):
                return self._channel(peer).recv()
    """
    assert not run_rule(
        "R10", slave_src,
        path="ytk_mp4j_tpu/models/snippet.py").findings
    assert run_rule("R10", slave_src).findings


def test_r10_inline_suppression_and_baseline():
    src = """
        class ProcessCommSlave:
            def _dial(self, peer):
                ch = connect(host, port)
                # mp4j-lint: disable=R10 (handshake pins the epoch)
                ch.send_obj((self._rank, epoch))
    """
    r = run_rule("R10", src)
    assert not r.findings and len(r.suppressed) == 1
    bl = baseline_mod.parse(textwrap.dedent("""
        [[suppression]]
        rule = "R10"
        file = "ytk_mp4j_tpu/comm/snippet.py"
        context = "ProcessCommSlave._accept_loop"
        reason = "handshake establishes the epoch"
    """))
    r = run_rule("R10", """
        class ProcessCommSlave:
            def _accept_loop(self):
                ch = Channel(sock)
                hs = ch.recv()
    """, baseline=bl)
    assert not r.findings and len(r.suppressed) == 1


def test_r9_inline_suppression_and_baseline():
    src = """
        def gather_map(self, d, root):
            # mp4j-lint: disable=R9 (sanctioned fallback)
            self._send(root, d)
    """
    r = run_rule("R9", src)
    assert not r.findings and len(r.suppressed) == 1
    bl = baseline_mod.parse(textwrap.dedent("""
        [[suppression]]
        rule = "R9"
        file = "ytk_mp4j_tpu/comm/snippet.py"
        context = "gather_map"
        reason = "negotiated fallback"
    """))
    r = run_rule("R9", """
        def gather_map(self, d, root):
            self._send(root, d)
    """, baseline=bl)
    assert not r.findings and len(r.suppressed) == 1


# ----------------------------------------------------------------------
# R11 — wall clock feeding duration/deadline arithmetic
# ----------------------------------------------------------------------
def test_r11_fires_on_direct_deadline_arithmetic():
    r = run_rule("R11", """
        import time

        def rendezvous(self):
            deadline = time.time() + self.timeout
            while time.time() < deadline:
                self.accept_one()
    """)
    assert [f.line for f in r.findings] == [5, 6]
    assert "perf_counter" in r.findings[0].message


def test_r11_fires_through_assigned_name():
    # the spans-anchor pattern: module-level wall time entering
    # arithmetic in a function elsewhere in the file
    r = run_rule("R11", """
        import time
        _epoch_wall = time.time()

        def export(t0, epoch):
            return (t0 - epoch + _epoch_wall) * 1e6
    """, path="ytk_mp4j_tpu/obs/snippet.py")
    [f] = r.findings
    assert f.line == 3 and f.context == "<module>"
    # function-local flow: assigned then subtracted
    r = run_rule("R11", """
        from time import time

        def measure(self):
            t0 = time()
            self.work()
            return time() - t0
    """)
    assert len(r.findings) == 2        # the Sub's call + t0's assign


def test_r11_quiet_on_storage_and_formatting():
    # artifact timestamps, localtime formatting, and ms extraction via
    # % are points in time, not measurements — the _log / postmortem
    # shapes must stay quiet
    r = run_rule("R11", """
        import time

        def _log(self, msg):
            now = time.time()
            ts = (time.strftime("%H:%M:%S", time.localtime(now))
                  + f".{int(now % 1 * 1000):03d}")
            print(ts, msg)

        def bundle(self):
            return {"wall_time": time.time()}
    """)
    assert not r.findings


def test_r11_quiet_on_monotonic_and_out_of_scope():
    r = run_rule("R11", """
        import time

        def wait(self):
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                t0 = time.perf_counter()
                self.step()
                self.booked += time.perf_counter() - t0
    """)
    assert not r.findings
    # same wall-clock deadline outside comm/obs/transport is out of scope
    r = run_rule("R11", """
        import time

        def wait(self):
            deadline = time.time() + 5.0
    """, path="ytk_mp4j_tpu/models/snippet.py")
    assert not r.findings


def test_r11_local_shadow_does_not_implicate_module_name():
    # a module-level STORED timestamp (quiet shape) plus a function
    # whose own local of the same name does perf_counter arithmetic:
    # the local shadows, it must not implicate the module assign
    r = run_rule("R11", """
        import time
        started = time.time()          # stored artifact timestamp

        def measure(self):
            started = time.perf_counter()
            self.work()
            return time.perf_counter() - started
    """, path="ytk_mp4j_tpu/obs/snippet.py")
    assert not r.findings
    # parameters, for-targets and with-as bindings shadow too
    r = run_rule("R11", """
        import time
        started = time.time()

        def lag(started):
            return time.monotonic() - started

        def scan(items):
            for started in items:
                if started < 5:
                    yield started + 1

        def hold(self):
            with self.pin() as started:
                return started - 1

        def bump(xs):
            return map(lambda started: started + 1, xs)

        BUMP2 = lambda started: started + 2   # module-level lambda
    """, path="ytk_mp4j_tpu/obs/snippet.py")
    assert not r.findings


def test_r11_inline_suppression_and_baseline():
    src = """
        import time
        # mp4j-lint: disable=R11 (trace anchor)
        _epoch_wall = time.time()

        def export(t0, epoch):
            return t0 - epoch + _epoch_wall
    """
    r = run_rule("R11", src, path="ytk_mp4j_tpu/obs/snippet.py")
    assert not r.findings and len(r.suppressed) == 1
    bl = baseline_mod.parse(textwrap.dedent("""
        [[suppression]]
        rule = "R11"
        file = "ytk_mp4j_tpu/obs/snippet.py"
        context = "<module>"
        reason = "trace anchor"
    """))
    r = run_rule("R11", """
        import time
        _epoch_wall = time.time()

        def export(t0, epoch):
            return t0 - epoch + _epoch_wall
    """, path="ytk_mp4j_tpu/obs/snippet.py", baseline=bl)
    assert not r.findings and len(r.suppressed) == 1


# ----------------------------------------------------------------------
# R12 — transport construction outside transport/ (SPI enforcement)
# ----------------------------------------------------------------------
def test_r12_fires_on_raw_socket_outside_transport():
    r = run_rule("R12", """
        import socket

        def open_side_channel(self):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            return s
    """)
    [f] = r.findings
    assert f.rule == "R12" and f.line == 5
    assert "socket.socket" in f.message


def test_r12_fires_on_channel_construction_outside_transport():
    for ctor in ("Channel", "TcpChannel", "ShmChannel"):
        r = run_rule("R12", f"""
            def wrap(self, sock):
                return {ctor}(sock)
        """)
        [f] = r.findings
        assert f.rule == "R12" and ctor in f.message


def test_r12_clean_inside_transport_and_on_connect():
    src = """
        import socket

        def dial(self, host, port):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            return TcpChannel(s)
    """
    # inside transport/ the constructions ARE the SPI implementation
    assert not run_rule(
        "R12", src,
        path="ytk_mp4j_tpu/transport/snippet.py").findings
    # connect() is the sanctioned factory — never flagged anywhere
    assert not run_rule("R12", """
        def get_peer(self, host, port):
            return connect(host, port, timeout=self._timeout)
    """).findings
    # a user-defined callable that merely ENDS in "socket" via a
    # non-dotted name is out of scope (only the dotted repo idiom)
    assert not run_rule("R12", """
        def make(self):
            return websocket("ws://x")
    """).findings


def test_r12_baseline_suppression_matches_rendezvous_site():
    bl = baseline_mod.parse(textwrap.dedent("""
        [[suppression]]
        rule = "R12"
        file = "ytk_mp4j_tpu/comm/snippet.py"
        context = "Master.__init__"
        reason = "rendezvous listen socket"
    """))
    r = run_rule("R12", """
        import socket

        class Master:
            def __init__(self):
                self._server = socket.socket(socket.AF_INET,
                                             socket.SOCK_STREAM)
    """, baseline=bl)
    assert not r.findings and len(r.suppressed) == 1


# ----------------------------------------------------------------------
# R13 — raw-byte read of a possibly non-contiguous array
# ----------------------------------------------------------------------
def test_r13_fires_on_unpinned_memoryview_and_tobytes():
    r = run_rule("R13", """
        def digest(arr):
            h = crc32(memoryview(arr))
            return h ^ crc32(arr.tobytes())
    """)
    assert [f.line for f in r.findings] == [3, 4]
    assert all("contiguity" in f.message or "pin" in f.message
               for f in r.findings)


def test_r13_quiet_when_pinned_or_constructed():
    assert not run_rule("R13", """
        import numpy as np

        def digest(arr):
            arr = np.ascontiguousarray(arr)
            return crc32(memoryview(arr)) ^ crc32(arr.tobytes())
    """).findings
    # contiguous-by-construction buffers: bytearray/np.empty, and
    # slices of them (the frombuffer-tail idiom in obs/audit.py)
    assert not run_rule("R13", """
        import numpy as np

        def recv(n):
            out = bytearray(n)
            fill(memoryview(out))
            u8 = np.frombuffer(out, np.uint8)
            tail = u8[8:]
            return tail.tobytes()
    """).findings
    # a call-expression argument is the callee's contract, not this
    # site's (memoryview(_raw_view(x)) — _raw_view is the baselined
    # sanctioned site whose callers pin)
    assert not run_rule("R13", """
        def frame(arr):
            return memoryview(_raw_view(arr)).cast("B")
    """).findings


def test_r13_scoped_to_comm_obs_transport():
    assert not run_rule("R13", """
        def digest(arr):
            return crc32(memoryview(arr))
    """, path="ytk_mp4j_tpu/models/snippet.py").findings


def test_r13_inline_and_baseline_suppression():
    r = run_rule("R13", """
        def nbytes_of(b):
            # mp4j-lint: disable=R13 (length read, not serialization)
            return memoryview(b).nbytes
    """)
    assert not r.findings and len(r.suppressed) == 1
    bl = baseline_mod.parse(textwrap.dedent("""
        [[suppression]]
        rule = "R13"
        file = "ytk_mp4j_tpu/comm/snippet.py"
        context = "_raw_view"
        reason = "callers pin"
    """))
    r = run_rule("R13", """
        def _raw_view(arr):
            return memoryview(arr).cast("B")
    """, baseline=bl)
    assert not r.findings and len(r.suppressed) == 1


# ----------------------------------------------------------------------
# R14 — telemetry artifact write without tmp + os.replace
# ----------------------------------------------------------------------
OBS_PATH = "ytk_mp4j_tpu/obs/snippet.py"


def test_r14_fires_on_plain_write_and_append():
    r = run_rule("R14", """
        import json

        def dump(path, obj):
            with open(path, "w", encoding="utf-8") as fh:
                json.dump(obj, fh)

        def log(path, line):
            with open(path, mode="ab") as fh:
                fh.write(line)
    """, path=OBS_PATH)
    assert [f.line for f in r.findings] == [5, 9]
    assert all("os.replace" in f.message for f in r.findings)


def test_r14_quiet_on_tmp_replace_discipline_and_reads():
    assert not run_rule("R14", """
        import json, os

        def dump(path, obj):
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(obj, fh)
            os.replace(tmp, path)

        def load(path):
            with open(path, encoding="utf-8") as fh:
                return json.load(fh)

        def load_binary(path):
            with open(path, "rb") as fh:
                return fh.read()
    """, path=OBS_PATH).findings
    # a computed mode is the caller's contract, not this site's
    assert not run_rule("R14", """
        def opener(path, mode):
            return open(path, mode)
    """, path=OBS_PATH).findings


def test_r14_scoped_to_obs():
    src = """
        def dump(path, b):
            with open(path, "wb") as fh:
                fh.write(b)
    """
    assert not run_rule("R14", src,
                        path="ytk_mp4j_tpu/comm/snippet.py").findings
    assert run_rule("R14", src, path=OBS_PATH).findings


def test_r14_inline_and_baseline_suppression():
    r = run_rule("R14", """
        def append_segment(path, frame):
            # mp4j-lint: disable=R14 (crc-framed append-only stream)
            with open(path, "ab", buffering=0) as fh:
                fh.write(frame)
    """, path=OBS_PATH)
    assert not r.findings and len(r.suppressed) == 1
    bl = baseline_mod.parse(textwrap.dedent("""
        [[suppression]]
        rule = "R14"
        file = "ytk_mp4j_tpu/obs/snippet.py"
        context = "Sink.append"
        reason = "torn-tail tolerant"
    """))
    r = run_rule("R14", """
        class Sink:
            def append(self, path, frame):
                with open(path, "ab") as fh:
                    fh.write(frame)
    """, path=OBS_PATH, baseline=bl)
    assert not r.findings and len(r.suppressed) == 1


# ----------------------------------------------------------------------
# R15 — roster-derived topology cached in a long-lived attribute
# ----------------------------------------------------------------------
def test_r15_fires_on_topology_caches():
    r = run_rule("R15", """
        class Slave:
            def __init__(self, roster):
                self._n = len(roster)
                self._fanout = self._n - 1          # cached count
                self._right = (self._rank + 1) % self._n

            def _prepare(self):
                self._peer_ports = [e[1] for e in self._roster]

            def _regroup(self):
                self._groups = self._derive_host_groups(self._roster)
    """)
    assert [f.line for f in r.findings] == [5, 6, 9, 12]
    assert all("topology" in f.message for f in r.findings)
    assert all("_set_roster" in f.message for f in r.findings)


def test_r15_quiet_on_use_time_reads_and_locals():
    assert not run_rule("R15", """
        class Slave:
            def _channel(self, peer):
                if not (0 <= peer < self._n):       # read at use time
                    raise ValueError(peer)
                n = self._n                          # local, not cached
                return [(r + 1) % n for r in range(n)]

            def _dial(self, peer):
                host, port = self._roster[peer][0], self._roster[peer][1]
                return (host, port)

            def fanout(self):
                return self._n - 1                   # derived, returned

            def __init__(self, rank, n):
                self._rank = rank                    # param, not derived
                self._n = n
                self._timeout = 5.0
                # cosmetic identity: a thread NAME is not a schedule
                self._name = f"mp4j-ctl-r{self._rank}"
    """).findings


def test_r15_scoped_to_comm_classes():
    src = """
        class Grid:
            def __init__(self):
                self._fanout = self._n - 1
    """
    assert run_rule("R15", src).findings
    assert not run_rule("R15", src,
                        path="ytk_mp4j_tpu/obs/snippet.py").findings
    # module-level / free functions take topology as arguments
    assert not run_rule("R15", """
        def fanout(n):
            return n - 1
    """).findings


def test_r15_inline_and_baseline_suppression():
    r = run_rule("R15", """
        class Slave:
            def _set_roster(self, roster):
                # mp4j-lint: disable=R15 (the sanctioned accessor)
                self._groups = self._derive_host_groups(self._roster)
    """)
    assert not r.findings and len(r.suppressed) == 1
    bl = baseline_mod.parse(textwrap.dedent("""
        [[suppression]]
        rule = "R15"
        file = "ytk_mp4j_tpu/comm/snippet.py"
        context = "Slave._sync_identity"
        reason = "the one sanctioned mirror site"
    """))
    r = run_rule("R15", """
        class Slave:
            def _sync_identity(self):
                self._stats.rank = self._rank
    """, baseline=bl)
    assert not r.findings and len(r.suppressed) == 1


# ----------------------------------------------------------------------
# R16 — un-awaited CollectiveFuture crosses a collective boundary
# ----------------------------------------------------------------------
def test_r16_fires_on_unawaited_future_before_barrier():
    r = run_rule("R16", """
        def step(comm, x):
            f = comm.iallreduce(x)
            comm.barrier()
    """)
    [f] = r.findings
    assert f.rule == "R16" and "'f'" in f.message
    assert "wait" in f.message


def test_r16_fires_on_unawaited_future_before_blocking_collective():
    r = run_rule("R16", """
        def step(comm, x, y):
            f = comm.iallreduce_map(x)
            comm.allreduce_array(y)
    """)
    [f] = r.findings
    assert f.rule == "R16" and "allreduce_array" in f.message


def test_r16_fires_on_unawaited_future_before_close():
    r = run_rule("R16", """
        def run(comm, x):
            f = comm.igather(x)
            comm.close(0)
    """)
    assert [f.rule for f in r.findings] == ["R16"]


def test_r16_quiet_when_awaited():
    r = run_rule("R16", """
        def step(comm, x):
            f = comm.iallreduce(x)
            f.wait()
            comm.barrier()

        def step2(comm, x):
            f = comm.iallreduce(x)
            out = f.result()
            comm.close(0)
    """)
    assert not r.findings


def test_r16_quiet_on_wait_all_drain():
    r = run_rule("R16", """
        def step(comm, x, y):
            f = comm.iallreduce(x)
            g = comm.iallreduce_map(y)
            comm.wait_all()
            comm.allreduce_array(y)
    """)
    assert not r.findings


def test_r16_quiet_on_other_comm_and_escape():
    # a boundary on a DIFFERENT comm object is not this future's
    # boundary; a future passed elsewhere escaped (its awaiting is the
    # callee's contract)
    r = run_rule("R16", """
        def step(comm, other, x):
            f = comm.iallreduce(x)
            other.barrier()
            f.wait()

        def step2(comm, x):
            f = comm.iallreduce(x)
            track(f)
            comm.barrier()
    """)
    assert not r.findings


def test_r16_inline_suppression():
    r = run_rule("R16", """
        def step(comm, x):
            f = comm.iallreduce(x)
            # mp4j-lint: disable=R16 (harness drains at exit)
            comm.barrier()
    """)
    assert not r.findings and len(r.suppressed) == 1


# ----------------------------------------------------------------------
# R17 — metric family missing from METRICS_DOC (doc drift)
# ----------------------------------------------------------------------
def test_r17_fires_on_undocumented_registry_family():
    r = run_rule("R17", """
        def book(self):
            self._metrics.inc("nope/undocumented_family", 1)
    """)
    [f] = r.findings
    assert f.rule == "R17" and "nope/undocumented_family" in f.message
    assert "METRICS_DOC" in f.message


def test_r17_fires_on_undocumented_gauge_and_observe():
    r = run_rule("R17", """
        def book(m):
            m.set_gauge("mystery/gauge", 1.0)
            m.observe("mystery/hist", 0.5, 1e-6, 36)
    """)
    assert len(r.findings) == 2


def test_r17_quiet_on_documented_families():
    r = run_rule("R17", """
        def book(self, m):
            self._metrics.inc("sink/bytes", 10)
            m.set_gauge("async/outstanding", 3.0)
            m.set_gauge("sink/lag_secs", 0.1)
    """)
    assert not r.findings


def test_r17_fstring_prefix_matches_wildcard_key():
    # f"latency/{family}" matches the "latency/<family>" wildcard;
    # an unknown dynamic prefix fires
    r = run_rule("R17", """
        def book(self, name):
            self.metrics.observe(f"latency/{name}", 0.1, 1e-6, 36)
            self.metrics.observe(f"wat/{name}", 0.1, 1e-6, 36)
    """)
    [f] = r.findings
    assert "wat/" in f.message and "wildcard" in f.message


def test_r17_quiet_on_non_metrics_receiver():
    # .inc()/.observe() on unrelated objects is not a registration
    r = run_rule("R17", """
        def other(counter):
            counter.inc("not/a/metric")
    """)
    assert not r.findings


def test_r17_fires_on_undocumented_prometheus_family():
    r = run_rule("R17", """
        def render(out):
            out.append("# TYPE mp4j_made_up_series gauge")
    """, path="ytk_mp4j_tpu/obs/metrics.py")
    [f] = r.findings
    assert "mp4j_made_up_series" in f.message


def test_r17_type_lines_only_checked_in_metrics_module():
    r = run_rule("R17", """
        def doc():
            return "# TYPE mp4j_made_up_series gauge"
    """)
    assert not r.findings


def test_r17_inline_suppression():
    r = run_rule("R17", """
        def book(self):
            # mp4j-lint: disable=R17 (experimental series)
            self._metrics.inc("lab/experiment", 1)
    """)
    assert not r.findings and len(r.suppressed) == 1


# ----------------------------------------------------------------------
# R18 — bare time.sleep() inside a while loop (control code)
# ----------------------------------------------------------------------
def test_r18_fires_on_sleep_in_while_loop():
    r = run_rule("R18", """
        import time
        def loop(self):
            while not self._stop_flag:
                self._tick()
                time.sleep(0.5)
    """)
    [f] = r.findings
    assert f.rule == "R18" and "Event.wait" in f.message


def test_r18_fires_in_nested_while_and_for():
    r = run_rule("R18", """
        import time
        def loop(items):
            while True:
                for it in items:
                    time.sleep(0.1)
    """)
    assert [f.rule for f in r.findings] == ["R18"]


def test_r18_quiet_on_event_wait():
    r = run_rule("R18", """
        def loop(self):
            while not self._stop.wait(0.5):
                self._tick()
    """)
    assert not r.findings


def test_r18_quiet_on_sleep_outside_loops():
    # a one-shot settle delay is pacing a single step, not a loop
    r = run_rule("R18", """
        import time
        def settle(self):
            time.sleep(0.1)
            for _ in range(3):
                time.sleep(0.1)
    """)
    assert not r.findings


def test_r18_quiet_outside_covered_dirs():
    r = run_rule("R18", """
        import time
        def loop():
            while True:
                time.sleep(1.0)
    """, path="ytk_mp4j_tpu/models/snippet.py")
    assert not r.findings


def test_r18_nested_def_resets_loop_tracking():
    # the closure's sleep runs on ITS schedule, not per-iteration of
    # the enclosing while
    r = run_rule("R18", """
        import time
        def outer(self):
            while True:
                def cb():
                    time.sleep(0.1)
                self._submit(cb)
                break
    """)
    assert not r.findings


def test_r18_inline_suppression():
    r = run_rule("R18", """
        import time
        def backoff(self):
            while self._retrying():
                # mp4j-lint: disable=R18 (bounded data-plane backoff)
                time.sleep(self._backoff)
    """)
    assert not r.findings and len(r.suppressed) == 1


def test_r17_repo_catalogue_is_complete():
    """The shipped tree itself must be R17-clean: every family the
    package registers or renders has its METRICS_DOC row."""
    import os

    from ytk_mp4j_tpu.analysis import baseline as _bl
    from ytk_mp4j_tpu.analysis.cli import DEFAULT_BASELINE
    import ytk_mp4j_tpu

    pkg = os.path.dirname(ytk_mp4j_tpu.__file__)
    engine = Engine(rules=get_rules(["R17"]),
                    baseline=_bl.load(DEFAULT_BASELINE))
    result = engine.lint_paths([pkg])
    assert not result.findings, result.findings


# ----------------------------------------------------------------------
# R22 — transport-decision size literal outside tuning/tuner
# ----------------------------------------------------------------------
def test_r22_fires_on_comparison_literal():
    r = run_rule("R22", """
        def send_raw(self, view):
            if len(view) >= 262144:
                self._ring_send(view)
    """, path="ytk_mp4j_tpu/transport/snippet.py")
    [f] = r.findings
    assert f.rule == "R22" and f.line == 3
    assert "tuning.py" in f.message


def test_r22_fires_on_clamp_literal():
    r = run_rule("R22", """
        def __init__(self, ring_bytes):
            self._piece = max(ring_bytes // 2, 8192)
    """)
    [f] = r.findings
    assert f.rule == "R22" and "8192" in f.message


def test_r22_quiet_on_referenced_knob():
    r = run_rule("R22", """
        from ytk_mp4j_tpu.utils import tuning

        def send_raw(self, view):
            if len(view) >= tuning.SHM_RING_MIN_BYTES:
                self._ring_send(view)
            self._piece = max(self._cap // 2, tuning.SHM_RING_FLOOR)
    """, path="ytk_mp4j_tpu/transport/snippet.py")
    assert not r.findings


def test_r22_quiet_on_small_protocol_constants_and_data_args():
    # small literals (header sizes, counts) and plain data arguments
    # (recv buffer sizes, listen backlogs) are not decisions
    r = run_rule("R22", """
        def serve(self, sock):
            if len(self._hdr) >= 64:
                pass
            sock.listen(64)
            while sock.recv(65536):
                pass
    """)
    assert not r.findings


def test_r22_quiet_outside_comm_transport():
    # the sanctioned literal homes: utils/tuning.py + utils/tuner.py
    # (and anything else outside the decision surface)
    r = run_rule("R22", """
        CHUNK_MIN = 256 * 1024

        def decide(n):
            return n >= 262144
    """, path="ytk_mp4j_tpu/utils/tuner.py")
    assert not r.findings


def test_r22_inline_suppression():
    r = run_rule("R22", """
        def route(self, n):
            # mp4j-lint: disable=R22 (wire-format constant, not a knob)
            return n >= 1048576
    """, path="ytk_mp4j_tpu/transport/snippet.py")
    assert not r.findings


# ----------------------------------------------------------------------
# R23 — inconsistent lockset on a shared field (ISSUE 16)
# ----------------------------------------------------------------------
def test_r23_fires_on_unlocked_thread_write():
    r = run_rule("R23", """
        import threading

        class Plane:
            def __init__(self):
                self._lock = threading.Lock()
                self.state = "idle"
                t = threading.Thread(target=self._loop, daemon=True)
                t.start()

            def _loop(self):
                self.state = "running"

            def status(self):
                with self._lock:
                    return self.state
    """)
    [f] = r.findings
    assert f.rule == "R23"
    assert f.context == "Plane._loop"
    assert "Plane.state" in f.message
    assert "candidate lock Plane._lock" in f.message
    # both witness sites with their roots travel in the message
    assert "thread:Plane._loop" in f.message
    assert "main" in f.message


def test_r23_quiet_when_lockset_consistent():
    r = run_rule("R23", """
        import threading

        class Plane:
            def __init__(self):
                self._lock = threading.Lock()
                self.state = "idle"
                t = threading.Thread(target=self._loop, daemon=True)
                t.start()

            def _loop(self):
                with self._lock:
                    self.state = "running"

            def status(self):
                with self._lock:
                    return self.state
    """)
    assert not r.findings


def test_r23_quiet_on_single_root_field():
    # only the drain thread ever touches the field: nothing to race
    r = run_rule("R23", """
        import threading

        class Plane:
            def __init__(self):
                self.state = "idle"
                t = threading.Thread(target=self._loop, daemon=True)
                t.start()

            def _loop(self):
                self.state = "running"
                self._step()

            def _step(self):
                return self.state
    """)
    assert not r.findings


def test_r23_constructor_writes_are_not_a_root():
    # __init__-time writes happen before publication: the classic
    # happens-before edge, never a race witness
    r = run_rule("R23", """
        import threading

        class Plane:
            def __init__(self):
                self._lock = threading.Lock()
                self.state = "idle"
                t = threading.Thread(target=self._loop, daemon=True)
                t.start()

            def _loop(self):
                with self._lock:
                    self.state = "running"

            def status(self):
                with self._lock:
                    return self.state
    """)
    assert not r.findings


def test_r23_scoped_to_covered_dirs():
    r = run_rule("R23", """
        import threading

        class Plane:
            def __init__(self):
                t = threading.Thread(target=self._loop, daemon=True)
                t.start()
                self.state = "idle"

            def _loop(self):
                self.state = "running"

            def status(self):
                return self.state
    """, path="ytk_mp4j_tpu/models/snippet.py")
    assert not r.findings


def test_r23_inline_suppression():
    r = run_rule("R23", """
        import threading

        class Plane:
            def __init__(self):
                t = threading.Thread(target=self._loop, daemon=True)
                t.start()
                self.state = "idle"

            def _loop(self):
                # mp4j-lint: disable=R23 (lock-free flag publication)
                self.state = "running"

            def status(self):
                return self.state
    """)
    assert not r.findings
    assert any(f.rule == "R23" for f in r.suppressed)


def test_r23_baseline_suppression_by_write_context():
    bl = baseline_mod.parse(textwrap.dedent("""
        [[suppression]]
        rule = "R23"
        file = "ytk_mp4j_tpu/comm/snippet.py"
        context = "Plane._loop"
        reason = "deliberate lock-free publication (test)"
    """))
    r = run_rule("R23", """
        import threading

        class Plane:
            def __init__(self):
                t = threading.Thread(target=self._loop, daemon=True)
                t.start()
                self.state = "idle"

            def _loop(self):
                self.state = "running"

            def status(self):
                return self.state
    """, baseline=bl)
    assert not r.findings
    assert any(f.rule == "R23" for f in r.suppressed)


# ----------------------------------------------------------------------
# R24 — resource leaked on an exception path (ISSUE 16)
# ----------------------------------------------------------------------
def test_r24_fires_on_socket_exception_edge():
    r = run_rule("R24", """
        import socket

        def probe(host):
            s = socket.create_connection((host, 9999))
            s.sendall(b"ping")
            reply = s.recv(16)
            s.close()
            return reply
    """)
    [f] = r.findings
    assert f.rule == "R24"
    assert f.line == 5          # charged at the ACQUIRE site
    assert "socket 's'" in f.message
    assert "sendall" in f.message


def test_r24_fires_on_accept_then_unprotected_call():
    # the pre-PR rendezvous shape: work between accept and the guard
    r = run_rule("R24", """
        def serve_one(server, deadline, now):
            sock, addr = server.accept()
            remaining = max(0.0, deadline - now)
            sock.settimeout(remaining)
            return sock
    """)
    [f] = r.findings
    assert f.line == 3 and "socket 'sock'" in f.message


def test_r24_fires_on_never_released():
    r = run_rule("R24", """
        import socket

        def hold(host):
            s = socket.create_connection((host, 1))
    """)
    [f] = r.findings
    assert "never released or handed off" in f.message


def test_r24_fires_on_lock_acquire_exception_edge():
    r = run_rule("R24", """
        def charge(self, ev):
            self._lock.acquire()
            self._audit(ev)
            self._lock.release()
    """)
    [f] = r.findings
    assert "lock" in f.message and "try/finally" in f.message


def test_r24_quiet_with_try_finally():
    r = run_rule("R24", """
        import socket

        def probe(host):
            s = socket.create_connection((host, 9999))
            try:
                s.sendall(b"ping")
                return s.recv(16)
            finally:
                s.close()
    """)
    assert not r.findings


def test_r24_quiet_with_with_block():
    r = run_rule("R24", """
        def read(path):
            with open(path) as fh:
                return fh.read()
    """)
    assert not r.findings


def test_r24_quiet_on_ownership_transfer():
    r = run_rule("R24", """
        import socket

        class Pool:
            def adopt(self, host):
                s = socket.create_connection((host, 9999))
                self._socks.append(s)
                self._greet(s)
    """)
    assert not r.findings


def test_r24_quiet_on_absorbing_handler():
    # `except Exception: ok = False` absorbs the body's exception
    # edges; the fall-through path owns the release
    r = run_rule("R24", """
        import socket

        def probe(host):
            s = socket.create_connection((host, 9999))
            ok = True
            try:
                s.sendall(b"ping")
            except Exception:
                ok = False
            s.close()
            return ok
    """)
    assert not r.findings


def test_r24_reraising_handler_does_not_absorb():
    r = run_rule("R24", """
        import socket

        def probe(host):
            s = socket.create_connection((host, 9999))
            try:
                s.sendall(b"ping")
            except Exception:
                raise RuntimeError("probe failed")
            s.close()
    """)
    [f] = r.findings
    assert "socket 's'" in f.message


def test_r24_inline_suppression():
    r = run_rule("R24", """
        import socket

        def probe(host):
            # mp4j-lint: disable=R24 (fd adopted by caller via errno)
            s = socket.create_connection((host, 9999))
            s.sendall(b"ping")
            s.close()
    """)
    assert not r.findings
    assert any(f.rule == "R24" for f in r.suppressed)


# ----------------------------------------------------------------------
# R25 — thread started without join/daemon/stop registration (ISSUE 16)
# ----------------------------------------------------------------------
def test_r25_fires_on_fire_and_forget_attr_thread():
    r = run_rule("R25", """
        import threading

        class Pump:
            def start(self):
                self._t = threading.Thread(target=self._drain)
                self._t.start()

            def _drain(self):
                pass
    """)
    [f] = r.findings
    assert f.rule == "R25"
    assert "'_t'" in f.message and "no function joins" in f.message


def test_r25_fires_on_inline_start():
    r = run_rule("R25", """
        import threading

        class Pump:
            def start(self):
                threading.Thread(target=self._drain).start()

            def _drain(self):
                pass
    """)
    [f] = r.findings
    assert "never be joined" in f.message


def test_r25_quiet_on_daemon_ctor_and_attr():
    r = run_rule("R25", """
        import threading

        class Pump:
            def start(self):
                self._t = threading.Thread(target=self._drain,
                                           daemon=True)
                self._t.start()

            def kick(self):
                t = threading.Thread(target=self._drain)
                t.daemon = True
                t.start()

            def _drain(self):
                pass
    """)
    assert not r.findings


def test_r25_quiet_on_join_and_registry_drain():
    r = run_rule("R25", """
        import threading

        class Pump:
            def run_once(self):
                t = threading.Thread(target=self._drain)
                t.start()
                t.join()

            def spawn(self):
                t = threading.Thread(target=self._drain)
                self._threads.append(t)
                t.start()

            def close(self):
                for t in self._threads:
                    t.join()

            def _drain(self):
                pass
    """)
    assert not r.findings


def test_r25_scoped_to_covered_dirs():
    r = run_rule("R25", """
        import threading

        class Pump:
            def start(self):
                self._t = threading.Thread(target=self._drain)
                self._t.start()

            def _drain(self):
                pass
    """, path="ytk_mp4j_tpu/models/snippet.py")
    assert not r.findings


def test_r25_inline_suppression():
    r = run_rule("R25", """
        import threading

        class Pump:
            def start(self):
                # mp4j-lint: disable=R25 (process-lifetime collector)
                self._t = threading.Thread(target=self._drain)
                self._t.start()

            def _drain(self):
                pass
    """)
    assert not r.findings
    assert any(f.rule == "R25" for f in r.suppressed)


# ----------------------------------------------------------------------
# R26 — in-loop i* submit awaited with no intervening compute
# ----------------------------------------------------------------------
def test_r26_fires_on_submit_then_wait():
    r = run_rule("R26", """
        def epoch(comm, grads):
            for g in grads:
                f = comm.iallreduce(g)
                f.wait()
    """)
    [f] = r.findings
    assert f.rule == "R26" and f.line == 5
    assert "'f'" in f.message and "overlap" in f.message


def test_r26_fires_on_lone_submit_then_wait_all():
    r = run_rule("R26", """
        def epoch(comm, grads):
            for g in grads:
                f = comm.iallreduce(g)
                comm.wait_all()
    """)
    [f] = r.findings
    assert f.rule == "R26" and "wait_all" in f.message


def test_r26_fires_on_result_in_while_loop():
    r = run_rule("R26", """
        def pump(comm, q):
            while q:
                f = comm.iallreduce_map(q.pop())
                merged = f.result()
    """)
    [f] = r.findings
    assert f.rule == "R26"


def test_r26_quiet_with_intervening_compute():
    r = run_rule("R26", """
        def epoch(comm, grads, model):
            for k, g in enumerate(grads):
                f = comm.iallreduce(g)
                model.forward(k + 1)
                f.wait()
    """)
    assert not r.findings


def test_r26_quiet_on_batched_submits_before_wait_all():
    # several outstanding submits pipeline against each other — that
    # IS the engine's k-fold amortization, not a defeated overlap
    r = run_rule("R26", """
        def epoch(comm, grads):
            for a, b in grads:
                f1 = comm.iallreduce(a)
                f2 = comm.iallreduce(b)
                comm.wait_all()
    """)
    assert not r.findings


def test_r26_quiet_outside_loops():
    # a one-shot submit-and-wait is a deliberate blocking call with
    # future plumbing (e.g. a drain helper): only LOOPS pay per-step
    r = run_rule("R26", """
        def drain(comm, x):
            f = comm.iallreduce(x)
            f.wait()
    """)
    assert not r.findings


def test_r26_inline_suppression():
    r = run_rule("R26", """
        def bench_sequential(comm, arrs):
            for a in arrs:
                f = comm.iallreduce(a)
                # mp4j-lint: disable=R26 (the sequential A/B baseline)
                f.wait()
    """)
    assert not r.findings
    assert any(f.rule == "R26" for f in r.suppressed)


# ----------------------------------------------------------------------
# R27 — HTTP fetch without explicit timeout in obs/ scrape code
# ----------------------------------------------------------------------
def test_r27_fires_on_urlopen_without_timeout():
    r = run_rule("R27", """
        import urllib.request

        def scrape(base):
            with urllib.request.urlopen(base + "/metrics.json") as resp:
                return resp.read()
    """, path=OBS_PATH)
    [f] = r.findings
    assert f.rule == "R27" and f.line == 5
    assert "timeout" in f.message


def test_r27_fires_on_http_client_connection():
    r = run_rule("R27", """
        import http.client

        def probe(host, port):
            conn = http.client.HTTPConnection(host, port)
            conn.request("GET", "/health.json")
            return conn.getresponse().read()
    """, path=OBS_PATH)
    [f] = r.findings
    assert f.rule == "R27" and "HTTPConnection" in f.message


def test_r27_quiet_with_timeout_kwarg():
    r = run_rule("R27", """
        import urllib.request
        import http.client

        def scrape(base, deadline):
            conn = http.client.HTTPSConnection("h", 443, timeout=2.0)
            with urllib.request.urlopen(base, timeout=deadline) as resp:
                return resp.read()
    """, path=OBS_PATH)
    assert not r.findings


def test_r27_quiet_with_positional_timeout():
    # urlopen(url, data, timeout) — the 3rd positional IS the bound
    r = run_rule("R27", """
        import urllib.request

        def post(base, payload):
            with urllib.request.urlopen(base, payload, 5.0) as resp:
                return resp.read()
    """, path=OBS_PATH)
    assert not r.findings


def test_r27_quiet_outside_obs():
    # comm owns its socket discipline under R2; analysis/test fetches
    # are not scrape loops
    r = run_rule("R27", """
        import urllib.request

        def fetch(base):
            with urllib.request.urlopen(base) as resp:
                return resp.read()
    """)
    assert not r.findings


def test_r27_inline_suppression():
    r = run_rule("R27", """
        import urllib.request

        def fetch_forever(base):
            # mp4j-lint: disable=R27 (interactive one-shot; ^C is the bound)
            with urllib.request.urlopen(base) as resp:
                return resp.read()
    """, path=OBS_PATH)
    assert not r.findings
    assert any(f.rule == "R27" for f in r.suppressed)


# ----------------------------------------------------------------------
# R28 — serve-path wait without a deadline / wall clock in serve/
# ----------------------------------------------------------------------
SERVE_PATH = "ytk_mp4j_tpu/serve/snippet.py"


def test_r28_fires_on_unbounded_wait():
    r = run_rule("R28", """
        class Batcher:
            def flush(self):
                self._ready.wait()
    """, path=SERVE_PATH)
    [f] = r.findings
    assert f.rule == "R28" and f.line == 4
    assert "timeout" in f.message


def test_r28_fires_on_each_unbounded_blocker():
    r = run_rule("R28", """
        def drain(self):
            self._lock.acquire()
            self._thread.join()
            return self._fut.result()
    """, path=SERVE_PATH)
    assert [f.line for f in r.findings] == [3, 4, 5]
    assert all(f.rule == "R28" for f in r.findings)


def test_r28_fires_on_wall_clock():
    r = run_rule("R28", """
        import time
        import datetime

        def stamp(self):
            self.t0 = time.time()
            self.day = datetime.datetime.now()
    """, path=SERVE_PATH)
    assert [f.line for f in r.findings] == [6, 7]
    assert "monotonic" in r.findings[0].message


def test_r28_fires_on_bare_time_import():
    r = run_rule("R28", """
        from time import time

        def stamp(self):
            return time()
    """, path=SERVE_PATH)
    [f] = r.findings
    assert f.rule == "R28" and f.line == 5


def test_r28_quiet_with_timeouts_and_monotonic():
    r = run_rule("R28", """
        import time

        def flush(self, w):
            due = time.monotonic() + self.deadline
            self._cv.wait(timeout=w)
            self._fut.result(w)
            self._thread.join(w)
            return ",".join(["a", "b"])
    """, path=SERVE_PATH)
    assert not r.findings


def test_r28_quiet_outside_serve():
    # comm/obs keep their own discipline (R2/R11/R18); R28 is the
    # serve plane's tighter contract only
    r = run_rule("R28", """
        import time

        def wait_all(self):
            self._done.wait()
            return time.time()
    """)
    assert not r.findings


def test_r28_inline_suppression():
    r = run_rule("R28", """
        def close(self):
            # mp4j-lint: disable=R28 (process teardown, not serve path)
            self._thread.join()
    """, path=SERVE_PATH)
    assert not r.findings
    assert any(f.rule == "R28" for f in r.suppressed)


# ----------------------------------------------------------------------
# diff-sarif — fingerprint-ratchet CI gate
# ----------------------------------------------------------------------
def _sarif_log(tmp_path, name, src):
    """Lint ONE canonical module path (so artifact URIs match across
    revisions, as in real CI) and emit a SARIF log named ``name``."""
    import os

    from ytk_mp4j_tpu.analysis.cli import main as cli_main
    py = tmp_path / "mod.py"
    py.write_text(textwrap.dedent(src))
    out = tmp_path / (name + ".sarif")
    rc = cli_main([str(py), "--sarif", str(out), "--no-baseline"])
    assert os.path.exists(out)
    return rc, str(out)


def test_diff_sarif_exits_zero_on_identical_and_fixed(tmp_path, capsys):
    from ytk_mp4j_tpu.analysis.cli import main as cli_main
    bad = """
        def step_a(comm, xs):
            for x in xs:
                f = comm.iallreduce(x)
                f.wait()
    """
    _rc, old = _sarif_log(tmp_path, "old", bad)
    assert cli_main(["diff-sarif", old, old]) == 0
    # NEW with the finding FIXED: fewer findings never trips the gate
    _rc, fixed = _sarif_log(tmp_path, "fixed", """
        def step_a(comm, xs):
            for x in xs:
                f = comm.iallreduce(x)
                compute(x)
                f.wait()
    """)
    assert cli_main(["diff-sarif", old, fixed]) == 0


def test_diff_sarif_nonzero_only_on_new_fingerprints(tmp_path, capsys):
    from ytk_mp4j_tpu.analysis.cli import main as cli_main
    _rc, old = _sarif_log(tmp_path, "old", """
        def step_a(comm, xs):
            for x in xs:
                f = comm.iallreduce(x)
                f.wait()
    """)
    # the pre-existing finding survives a refactor that DRIFTS its
    # line; a genuinely new finding appears in another scope
    _rc, new = _sarif_log(tmp_path, "new", """
        HEADROOM = 1  # pushes step_a down


        def step_a(comm, xs):
            for x in xs:
                f = comm.iallreduce(x)
                f.wait()


        def step_b(comm, ys):
            for y in ys:
                g = comm.iallreduce(y)
                g.wait()
    """)
    assert cli_main(["diff-sarif", old, new]) == 1
    out = capsys.readouterr().out
    assert "step_b" in out and out.count("NEW ") == 1


def test_diff_sarif_unreadable_input_is_usage_error(tmp_path):
    from ytk_mp4j_tpu.analysis.cli import main as cli_main
    missing = str(tmp_path / "nope.sarif")
    good = tmp_path / "ok.sarif"
    good.write_text("{}")
    assert cli_main(["diff-sarif", missing, str(good)]) == 2
    bad = tmp_path / "bad.sarif"
    bad.write_text("{not json")
    assert cli_main(["diff-sarif", str(good), str(bad)]) == 2
