"""Socket (CPU reference) path tests.

"Multi-node without a cluster" (SURVEY.md section 4): a real master plus N
real slaves over loopback TCP. Slaves run in threads for speed (each has
its own sockets; blocking socket I/O releases the GIL), plus one
subprocess-based run of the checkprocess program for true process-level
coverage.
"""

import subprocess
import sys

import numpy as np
import pytest

from ytk_mp4j_tpu import meta
from ytk_mp4j_tpu.comm.master import Master
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operator, Operators

from helpers import REPO_ROOT, expected_reduce, make_inputs, run_slaves


def make_all(n, length, operand, seed=7):
    return make_inputs(n, length, operand, np.random.default_rng(seed))


@pytest.mark.parametrize("algo", ["rhd", "ring"])
@pytest.mark.parametrize("n", [2, 3, 4, 5])
@pytest.mark.parametrize("op", ["SUM", "MAX"])
def test_allreduce_algos(n, op, algo):
    """Both allreduce algorithms (recursive halving/doubling — the
    reference's path — and ring) against the numpy oracle, including
    non-power-of-2 rank counts (pre/post fold)."""
    operand = Operands.DOUBLE
    alls = make_all(n, 41, operand)
    want = expected_reduce(alls, op)

    def fn(slave, r):
        arr = alls[r].copy()
        slave.allreduce_array(arr, operand, Operators.by_name(op),
                              algo=algo)
        return arr

    for got in run_slaves(n, fn):
        np.testing.assert_allclose(got, want)


@pytest.mark.parametrize("algo", ["rhd", "ring"])
@pytest.mark.parametrize("n", [4, 7])
def test_allreduce_subrange_int(n, algo):
    operand = Operands.INT
    alls = make_all(n, 20, operand)
    want = expected_reduce(alls, "SUM")

    def fn(slave, r):
        arr = alls[r].copy()
        slave.allreduce_array(arr, operand, Operators.SUM, from_=5, to=15,
                              algo=algo)
        return arr

    for r, got in enumerate(run_slaves(n, fn)):
        np.testing.assert_array_equal(got[5:15], want[5:15])
        np.testing.assert_array_equal(got[:5], alls[r][:5])
        np.testing.assert_array_equal(got[15:], alls[r][15:])


def test_allreduce_rhd_short_array():
    """Range shorter than the participant count: empty halving segments
    must be exchanged without corruption."""
    n = 5
    operand = Operands.DOUBLE
    alls = make_all(n, 3, operand)
    want = expected_reduce(alls, "SUM")

    def fn(slave, r):
        arr = alls[r].copy()
        slave.allreduce_array(arr, operand, Operators.SUM, algo="rhd")
        return arr

    for got in run_slaves(n, fn):
        np.testing.assert_allclose(got, want)


@pytest.mark.parametrize("n", [3, 4])
def test_reduce_scatter_and_allgather(n):
    operand = Operands.DOUBLE
    L = 23
    alls = make_all(n, L, operand)
    want = expected_reduce(alls, "SUM")
    ranges = meta.partition_range(0, L, n)

    def fn(slave, r):
        arr = alls[r].copy()
        slave.reduce_scatter_array(arr, operand, Operators.SUM)
        s, e = ranges[r]
        seg = arr[s:e].copy()
        # then allgather the reduced segments back to the full array
        slave.allgather_array(arr, operand)
        return seg, arr

    for r, (seg, full) in enumerate(run_slaves(n, fn)):
        s, e = ranges[r]
        np.testing.assert_allclose(seg, want[s:e])
        np.testing.assert_allclose(full, want)


@pytest.mark.parametrize("root", [0, 2])
def test_reduce_broadcast(root):
    n = 4
    operand = Operands.FLOAT
    alls = make_all(n, 17, operand)
    want = expected_reduce(alls, "SUM")

    def fn(slave, r):
        arr = alls[r].copy()
        slave.reduce_array(arr, operand, Operators.SUM, root=root)
        out1 = arr.copy()
        arr2 = alls[r].copy()
        slave.broadcast_array(arr2, operand, root=root)
        return out1, arr2

    res = run_slaves(n, fn)
    np.testing.assert_allclose(res[root][0], want, rtol=1e-5)
    for r, (reduced, bcast) in enumerate(res):
        if r != root:
            np.testing.assert_array_equal(reduced, alls[r])
        np.testing.assert_array_equal(bcast, alls[root])


def test_gather_scatter():
    n = 5
    operand = Operands.LONG
    L = 19
    alls = make_all(n, L, operand)
    ranges = meta.partition_range(0, L, n)

    def fn(slave, r):
        arr = alls[r].copy()
        slave.gather_array(arr, operand, root=0)
        g = arr.copy()
        arr2 = alls[r].copy()
        slave.scatter_array(arr2, operand, root=0)
        return g, arr2

    res = run_slaves(n, fn)
    want_g = np.concatenate([alls[q][s:e] for q, (s, e) in enumerate(ranges)])
    np.testing.assert_array_equal(res[0][0], want_g)
    for r, (_, sc) in enumerate(res):
        s, e = ranges[r]
        np.testing.assert_array_equal(sc[s:e], alls[0][s:e])


def test_custom_operator_socket():
    n = 3
    absmax = Operator.custom(
        "ABSMAX", lambda x, y: np.where(np.abs(x) >= np.abs(y), x, y), 0.0)
    operand = Operands.DOUBLE
    alls = make_all(n, 16, operand)
    stacked = np.stack(alls)
    idx = np.abs(stacked).argmax(axis=0)
    want = stacked[idx, np.arange(stacked.shape[1])]

    def fn(slave, r):
        arr = alls[r].copy()
        slave.allreduce_array(arr, operand, absmax)
        return arr

    for got in run_slaves(n, fn):
        np.testing.assert_allclose(got, want)


def test_barrier_and_logging(capfd):
    n = 3

    def fn(slave, r):
        slave.info(f"hello from {r}")
        slave.barrier()
        slave.barrier()
        return r

    assert run_slaves(n, fn) == [0, 1, 2]


def test_rendezvous_timeout():
    import pytest as _pytest
    from ytk_mp4j_tpu.exceptions import Mp4jError
    m = Master(2, timeout=0.5)
    with _pytest.raises(Mp4jError):
        m._rendezvous()


@pytest.mark.slow
def test_checkprocess_subprocess():
    """True multi-process run of the check program (the reference's check
    suite shape): 1 master + 3 slave processes over loopback."""
    master = Master(3, timeout=60.0).serve_in_thread()
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "ytk_mp4j_tpu.check.checkprocess",
             "--master", f"127.0.0.1:{master.port}", "--length", "65"],
            cwd=REPO_ROOT,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for _ in range(3)
    ]
    for p in procs:
        out, err = p.communicate(timeout=240)
        assert p.returncode == 0, f"checkprocess failed:\n{out}\n{err}"
    master.join(10)
    assert master.final_code == 0
