"""Tier-1 gate: the comm stack must lint clean forever.

Runs mp4j-lint (all rules, committed baseline, STRICT baseline mode
since ISSUE 14) over the installed ``ytk_mp4j_tpu`` package and fails
on any unsuppressed finding — the static analogue of the differential
tests: every future PR to comm/, ops/, models/ inherits the protocol
checks by construction. The whole-program rules R19-R21 run here too
(the package is the program), and the discovered lock-order graph
must stay cycle-free: the concurrency disciplines the PR texts state
in prose are a checked invariant from this gate on.

Also proves the gate has teeth: a scratch file seeded with a deliberate
rank-conditional collective must be reported by R1 at the right
file:line.
"""

import os
import textwrap

import ytk_mp4j_tpu
from ytk_mp4j_tpu.analysis import lint_paths
from ytk_mp4j_tpu.analysis.cli import DEFAULT_BASELINE, main

PKG_DIR = os.path.dirname(ytk_mp4j_tpu.__file__)


def test_repo_lints_clean():
    result = lint_paths([PKG_DIR])
    assert result.ok, (
        "unsuppressed mp4j-lint findings (fix them or add a reasoned "
        "suppression):\n" + "\n".join(f.format() for f in result.findings))


def test_cli_exits_zero_on_repo():
    assert main([PKG_DIR]) == 0


def test_cli_exits_zero_on_repo_strict():
    # strict mode: a baseline entry matching no finding is a B001
    # error — the accepted surface shrinks with the code
    assert main([PKG_DIR, "--strict"]) == 0


def test_package_lock_order_graph_is_cycle_free():
    """The job-wide lock-order graph over the real package has no
    cycle — the "master -> controller only" / outbox disciplines are
    machine-checked from this PR on (ISSUE 14 acceptance)."""
    from ytk_mp4j_tpu.analysis.engine import Engine, Program
    contexts, errors = Engine(rules=[]).load_contexts([PKG_DIR])
    assert not errors, errors
    model = Program(contexts).locks
    # sanity: the model actually sees the package's lock landscape
    # (a refactor that silently blinds discovery must fail loudly)
    displays = {d.display for d in model.locks.values()}
    assert {"Master._lock", "_Slot.lock", "Autoscaler._lock",
            "ProcessCommSlave._tel_lock",
            "ProcessCommSlave._master_lock"} <= displays
    assert len(model.edges) >= 2, "order edges vanished — model blind?"
    assert model.cycles() == [], (
        "lock-order cycle introduced:\n" + "\n".join(
            "  " + " <-> ".join(model.locks[k].display for k in scc)
            for scc in model.cycles()))


def test_package_shared_field_locksets_clean_modulo_baseline():
    """ISSUE 16 acceptance: the package's shared-field lockset report
    is clean modulo the committed baseline — every mutable field
    reachable from >= 2 thread roots either has a consistent lockset
    or a reasoned R23 suppression naming why lock-free publication is
    safe there (the cycle-free check's sibling for data races)."""
    from ytk_mp4j_tpu.analysis import baseline as baseline_mod
    from ytk_mp4j_tpu.analysis.engine import Engine, Program
    from ytk_mp4j_tpu.analysis.rules import get_rules
    contexts, errors = Engine(rules=[]).load_contexts([PKG_DIR])
    assert not errors, errors
    model = Program(contexts).races
    # sanity: the model actually sees the package's concurrency
    # (a refactor that silently blinds root discovery must fail loudly)
    assert any(r.startswith("thread:") for r in model.roots), \
        "no thread roots discovered — model blind?"
    assert "main" in model.roots
    shared = model.shared_fields()
    assert len(shared) >= 10, "shared-field discovery collapsed"
    displays = {fr.display for fr in shared}
    assert "Master._slots" in displays
    # the verdict: racy fields exist (the documented lock-free
    # publication sites) but every one is baselined with a reason
    bl = baseline_mod.load(DEFAULT_BASELINE)
    result = Engine(rules=get_rules(["R23"]),
                    baseline=bl).lint_paths([PKG_DIR])
    assert result.ok, (
        "shared field with inconsistent lockset (fix it or add a "
        "reasoned R23 suppression):\n"
        + "\n".join(f.format() for f in result.findings))


def test_committed_baseline_exists_and_is_fully_used():
    assert os.path.exists(DEFAULT_BASELINE)
    from ytk_mp4j_tpu.analysis import baseline as baseline_mod
    bl = baseline_mod.load(DEFAULT_BASELINE)
    assert bl.entries, "baseline should carry the accepted findings"
    assert all(e.reason for e in bl.entries), \
        "every baseline entry needs a recorded reason"
    # every committed suppression must still match a real finding —
    # stale entries are B001 findings in strict mode, so the gate
    # enforces it structurally; this asserts the engine-level view
    from ytk_mp4j_tpu.analysis.engine import Engine
    result = Engine(baseline=bl, strict_baseline=True,
                    baseline_path=DEFAULT_BASELINE).lint_paths([PKG_DIR])
    assert result.ok, "\n".join(f.format() for f in result.findings)
    assert not bl.unused(), \
        f"stale baseline entries: {bl.unused()}"


def test_seeded_rank_conditional_collective_is_caught(tmp_path):
    scratch = tmp_path / "ytk_mp4j_tpu" / "comm" / "seeded.py"
    scratch.parent.mkdir(parents=True)
    scratch.write_text(textwrap.dedent("""
        def broken_step(comm, grads):       # line 2
            comm.allreduce_array(grads)     # line 3
            if comm.rank == 0:              # line 4 <- R1 here
                comm.barrier()
    """))
    result = lint_paths([str(tmp_path)])
    r1 = [f for f in result.findings if f.rule == "R1"]
    assert len(r1) == 1
    assert r1[0].path.endswith("ytk_mp4j_tpu/comm/seeded.py")
    assert r1[0].line == 4
    assert r1[0].context == "broken_step"


def test_cli_reports_seeded_finding(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("def f(c):\n    if c.rank:\n        c.barrier()\n")
    assert main([str(bad)]) == 1
    out = capsys.readouterr().out
    assert "R1" in out and "bad.py:2" in out
