"""mp4j-elastic (ISSUE 10): rank replacement from warm spares and
degraded shrink mode.

The chaos grid crosses ``kill`` with {replace, shrink} membership
modes, {raw, framed, columnar-map} data planes and {tcp, shm-carrier}
transports, asserting the acceptance contract:

- **replace**: a warm spare is adopted into the dead rank's id at the
  next epoch, the fenced retry restores inputs and re-runs, and the
  job completes with results BIT-IDENTICAL to an unfaulted run — zero
  surviving-rank errors, the joiner seeded with the roster, the
  columnar keycodec vocabularies and the resume ordinal.
- **shrink**: survivors renumber contiguously, rebuild topology at
  n-1 and continue; results equal the correct n-1 reduction of the
  survivors' restored inputs.
- **off** (default): today's single clean ``Mp4jFatalError`` on every
  survivor — the pre-elastic contract, bit-for-bit.

Plus negative cases (no spare available under ``replace``; a spare
dying mid-adoption falls through to the next spare), knob-conflict
validation (``MP4J_MAX_RETRIES=0`` hard-disables both elastic modes),
membership observability (live view badges, Prometheus counters,
recovery-log events) and vocabulary continuity across an adoption.
Every scenario runs under a hard thread-join deadline — zero hangs.
"""

import io
import threading
import time

import numpy as np
import pytest

from ytk_mp4j_tpu.comm import keycodec
from ytk_mp4j_tpu.comm.master import Master, REGISTER
from ytk_mp4j_tpu.comm.process_comm import ProcessCommSlave
from ytk_mp4j_tpu.exceptions import (
    Mp4jError, Mp4jFatalError, Mp4jSpareReleased)
from ytk_mp4j_tpu.obs import telemetry
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operators
from ytk_mp4j_tpu.resilience import membership
from ytk_mp4j_tpu.resilience.faults import FaultKill
from ytk_mp4j_tpu.transport.tcp import connect
from ytk_mp4j_tpu.utils import tuning

N = 4
JOIN = 45.0


def run_elastic(n, fn, spare_fns=(), fault_plan=None, join=JOIN,
                master_kwargs=None, **slave_kwargs):
    """Master + ``n`` slave threads + one thread per entry of
    ``spare_fns`` (each a continuation body run AFTER adoption), all
    under a HARD join deadline. Returns ``(results, errors, spares,
    master, log)`` where ``spares`` is a list of per-spare dicts
    ({"adopted_rank", "resume_seq", "result" | "released" |
    "error"}). Replace-mode results index by rank: an adopted spare's
    result lands at its adopted rank."""
    log = io.StringIO()
    mk = dict(master_kwargs or {})
    mk.setdefault("spares", len(spare_fns))
    master = Master(n, timeout=join, log_stream=log,
                    **mk).serve_in_thread()
    results = [None] * n
    errors: list = [None] * n

    def worker(i):
        slave = None
        try:
            slave = ProcessCommSlave(
                "127.0.0.1", master.port, timeout=join,
                fault_plan=fault_plan, dead_rank_secs=20.0,
                **slave_kwargs)
            r = slave.rank
            out = fn(slave, r)
            # shrink renumbers mid-run: report under the FINAL rank
            results[slave.rank] = out
            slave.close(0)
        except Exception as e:
            r = slave.rank if slave is not None else i
            errors[r] = e
            if slave is not None:
                try:
                    slave.close(1)
                except Exception:
                    pass

    spares: list[dict] = [{} for _ in spare_fns]

    def spare_worker(k):
        sp = None
        try:
            sp = ProcessCommSlave(
                "127.0.0.1", master.port, timeout=join, spare=True,
                dead_rank_secs=20.0, **slave_kwargs)
            spares[k]["adopted_rank"] = sp.rank
            spares[k]["resume_seq"] = sp.resume_seq
            out = spare_fns[k](sp)
            spares[k]["result"] = out
            results[sp.rank] = out
            sp.close(0)
        except Mp4jSpareReleased as e:
            spares[k]["released"] = str(e)
        except Exception as e:
            spares[k]["error"] = e
            if sp is not None:
                try:
                    sp.close(1)
                except Exception:
                    pass

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(n)]
    threads += [threading.Thread(target=spare_worker, args=(k,),
                                 daemon=True)
                for k in range(len(spare_fns))]
    for t in threads:
        t.start()
    deadline = time.monotonic() + join
    for t in threads:
        t.join(max(0.1, deadline - time.monotonic()))
    hung = [i for i, t in enumerate(threads) if t.is_alive()]
    assert not hung, f"threads {hung} hung past the join deadline:\n" \
                     + log.getvalue()
    master.join(10.0)
    return results, errors, spares, master, log.getvalue()


# ----------------------------------------------------------------------
# deterministic two-collective bodies (fault plans target ordinal 2)
# ----------------------------------------------------------------------
_RNG = np.random.default_rng(11)
_ALLS = [_RNG.standard_normal(60_000) for _ in range(N)]
_SUM1 = sum(_ALLS)                      # every rank's state after coll 1


def _map_init(r):
    return {int(k): np.float64((r + 1) * (k + 1)) for k in range(600)}


_MAP_SUM1 = {}
for _r in range(N):
    for _k, _v in _map_init(_r).items():
        _MAP_SUM1[_k] = _MAP_SUM1.get(_k, 0.0) + _v


def _body(path, after1=None):
    """coll 1 (allreduce) -> barrier -> coll 2 (allreduce), the same
    shape as the PR 5 chaos grid; plus the matching SPARE continuation
    which reconstructs the dead rank's pre-coll-2 state (after an
    allreduce every rank holds the IDENTICAL value, recorded into
    ``after1`` — the joiner re-derives the dead rank's state without
    communication, the application-level half of the elastic
    contract; a real job would load a checkpoint here)."""
    after1 = after1 if after1 is not None else {}
    if path == "map":
        def fn(slave, r):
            d = _map_init(r)
            slave.allreduce_map(d, Operands.DOUBLE, Operators.SUM)
            after1["v"] = dict(d)     # identical on every rank
            slave.barrier()
            slave.allreduce_map(d, Operands.DOUBLE, Operators.SUM)
            return d

        def spare_fn(sp):
            assert sp.resume_seq == 1, sp.resume_seq
            d = dict(after1["v"])
            sp.allreduce_map(d, Operands.DOUBLE, Operators.SUM)
            return d
        return fn, spare_fn, after1, {}

    def fn(slave, r):
        arr = _ALLS[r].copy()
        slave.allreduce_array(arr, Operands.DOUBLE, Operators.SUM)
        after1["v"] = arr.copy()      # identical on every rank
        slave.barrier()
        slave.allreduce_array(arr, Operands.DOUBLE, Operators.SUM)
        return arr

    def spare_fn(sp):
        assert sp.resume_seq == 1, sp.resume_seq
        arr = after1["v"].copy()
        sp.allreduce_array(arr, Operands.DOUBLE, Operators.SUM)
        return arr
    return fn, spare_fn, after1, {"native_transport": path == "raw"}


def _transport_kw(transport):
    # the thread harness co-locates every rank, so the default plane is
    # the shm rings ("shm-carrier": peer re-dials renegotiate SEGMENTS
    # with the joiner); shm=False pins the all-TCP grid
    return {} if transport == "shm" else {"shm": False}


# ----------------------------------------------------------------------
# the chaos grid: kill × {replace, shrink} × planes × transports
# ----------------------------------------------------------------------
@pytest.mark.parametrize("transport", ["shm", "tcp"])
@pytest.mark.parametrize("path", ["raw", "framed", "map"])
def test_replace_kill_bit_exact_continuation(path, transport):
    """A killed rank is replaced from a warm spare: the job completes
    with results bit-identical to an unfaulted run, zero survivor
    errors, the spare adopted into the dead rank's id."""
    fn, spare_fn, _, kw = _body(path)
    kw.update(_transport_kw(transport))
    want, werr, _, _, _ = run_elastic(N, fn, **kw)
    assert all(e is None for e in werr), werr
    got, errors, spares, master, log = run_elastic(
        N, fn, spare_fns=[spare_fn],
        fault_plan="kill:rank=2:nth=2",
        master_kwargs={"elastic": "replace"}, elastic="replace", **kw)
    assert isinstance(errors[2], FaultKill)
    survivors = [errors[r] for r in range(N) if r != 2]
    assert all(e is None for e in survivors), \
        f"survivor errors: {errors}\n{log}"
    assert spares[0].get("adopted_rank") == 2, f"{spares}\n{log}"
    assert "error" not in spares[0], f"{spares[0].get('error')}\n{log}"
    for r in range(N):
        if path == "map":
            assert set(got[r]) == set(want[r])
            for k in got[r]:
                assert got[r][k] == want[r][k]   # bit-exact
        else:
            np.testing.assert_array_equal(got[r], want[r])
    assert master.final_code == 0, log
    assert "adopted as rank 2" in log


@pytest.mark.parametrize("transport", ["shm", "tcp"])
@pytest.mark.parametrize("path", ["raw", "framed", "map"])
def test_shrink_kill_continues_at_n_minus_1(path, transport):
    """A killed rank under shrink: survivors renumber contiguously and
    produce the correct n-1 reduction of their restored inputs."""
    fn, _, after1, kw = _body(path)
    kw.update(_transport_kw(transport))

    final = {}

    def fn2(slave, r):
        out = fn(slave, r)
        final[r] = (slave.rank, slave.slave_num)
        return out

    got, errors, _, master, log = run_elastic(
        N, fn2, fault_plan="kill:rank=2:nth=2",
        master_kwargs={"elastic": "shrink"}, elastic="shrink", **kw)
    assert isinstance(errors[2], FaultKill)
    survivors = [r for r in range(N) if r != 2]
    assert all(errors[r] is None for r in survivors), \
        f"survivor errors: {errors}\n{log}"
    # renumbering: old ranks 0,1,3 -> 0,1,2 at slave_num 3
    assert {final[r] for r in survivors} == {(0, 3), (1, 3), (2, 3)}, \
        f"{final}\n{log}"
    # every survivor's coll-2 input was its (identical) post-coll-1
    # state, restored by the fenced retry — the n-1 result is three
    # copies summed, bitwise 3x (x+x is exact, so either reduction
    # shape is one rounding of the exact 3x)
    for new_r in range(3):     # results index by the FINAL rank
        if path == "map":
            for k, v in got[new_r].items():
                assert v == 3.0 * after1["v"][k]
        else:
            np.testing.assert_array_equal(got[new_r],
                                          3.0 * after1["v"])
    assert master.final_code == 0, log
    assert master.slave_num == 3
    assert "shrunk to 3 rank(s)" in log


def test_replace_with_novel_vocabulary_stays_consistent():
    """Vocabulary continuity across an adoption: the joiner's imported
    codec tables must match the survivors' exactly, including codes
    grown over MULTIPLE pre-kill map collectives — a post-adoption map
    collective mixing old and new keys is bit-exact against an
    unfaulted run."""
    def fn(slave, r):
        out = []
        for step in range(3):
            base = 10_000 * step
            d = {base + int(k): np.float64((r + 1) * (k + 1))
                 for k in range(300)}
            slave.barrier()
            slave.allreduce_map(d, Operands.DOUBLE, Operators.SUM)
            out.append(d)
        return out

    def spare_fn(sp):
        # adopted at the third map collective (ordinal 3): steps 0-1
        # completed job-wide; rebuild rank 2's inputs for step 2
        assert sp.resume_seq == 2, sp.resume_seq
        base = 10_000 * 2
        d = {base + int(k): np.float64(3 * (k + 1))
             for k in range(300)}
        sp.allreduce_map(d, Operands.DOUBLE, Operators.SUM)
        return [None, None, d]

    want, werr, _, _, _ = run_elastic(N, fn)
    assert all(e is None for e in werr), werr
    got, errors, spares, _, log = run_elastic(
        N, fn, spare_fns=[spare_fn],
        fault_plan="kill:rank=2:nth=3",
        master_kwargs={"elastic": "replace"}, elastic="replace")
    assert all(errors[r] is None for r in range(N) if r != 2), \
        f"{errors}\n{log}"
    assert spares[0].get("adopted_rank") == 2, f"{spares}\n{log}"
    for r in range(N):
        if r == 2:
            assert got[2][2] == want[2][2]   # the joiner's step
        else:
            assert got[r] == want[r]         # all three steps bit-==


# ----------------------------------------------------------------------
# negative cases + the off contract
# ----------------------------------------------------------------------
def test_replace_without_spare_is_clean_fatal():
    """MP4J_ELASTIC=replace with an empty pool: today's clean
    Mp4jFatalError on every survivor — same message everywhere, within
    the bounded join, naming the missing spare."""
    fn, _, _, kw = _body("raw")
    _, errors, _, _, log = run_elastic(
        N, fn, fault_plan="kill:rank=2:nth=2",
        master_kwargs={"elastic": "replace"}, elastic="replace", **kw)
    assert isinstance(errors[2], FaultKill)
    survivors = [errors[r] for r in range(N) if r != 2]
    assert all(isinstance(e, Mp4jFatalError) for e in survivors), \
        f"{errors}\n{log}"
    msgs = {str(e) for e in survivors}
    assert len(msgs) == 1, msgs
    msg = msgs.pop()
    assert "rank 2" in msg and "no warm spare available" in msg


def test_spare_dies_mid_adoption_next_spare_adopted():
    """The first spare (registration order) dies the moment it is
    adopted: the master falls through to the NEXT spare and the job
    still completes bit-exactly."""
    fn, spare_fn, _, kw = _body("framed")
    want, werr, _, _, _ = run_elastic(N, fn, **kw)
    assert all(e is None for e in werr), werr

    log = io.StringIO()
    master = Master(N, timeout=JOIN, log_stream=log, elastic="replace",
                    spares=2, adopt_secs=4.0).serve_in_thread()

    # fake spare: registers FIRST (adopted first), reads its adopt
    # message, then drops dead without acking
    fake_ready = threading.Event()

    def fake_spare():
        ch = connect("127.0.0.1", master.port, timeout=JOIN)
        ch.send_obj((REGISTER, {"listen_port": 1, "host": "127.0.0.1",
                                "fp": "", "spare": True}))
        ch.recv()                      # registration ack
        fake_ready.set()
        try:
            ch.set_timeout(JOIN)
            ch.recv()                  # the adopt message
        except Exception:
            pass
        ch.close()                     # die without acking

    fk = threading.Thread(target=fake_spare, daemon=True)
    fk.start()
    fake_ready.wait(10.0)

    results = [None] * N
    errors: list = [None] * N

    def worker(i):
        slave = None
        try:
            slave = ProcessCommSlave(
                "127.0.0.1", master.port, timeout=JOIN,
                fault_plan="kill:rank=2:nth=2", dead_rank_secs=20.0,
                elastic="replace", **kw)
            results[slave.rank] = fn(slave, slave.rank)
            slave.close(0)
        except Exception as e:
            errors[slave.rank if slave is not None else i] = e

    spare_out: dict = {}

    def real_spare():
        try:
            sp = ProcessCommSlave("127.0.0.1", master.port,
                                  timeout=JOIN, spare=True,
                                  dead_rank_secs=20.0,
                                  elastic="replace", **kw)
            spare_out["rank"] = sp.rank
            results[sp.rank] = spare_fn(sp)
            sp.close(0)
        except Exception as e:
            spare_out["error"] = e

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(N)]
    threads.append(threading.Thread(target=real_spare, daemon=True))
    for t in threads:
        t.start()
    deadline = time.monotonic() + JOIN
    for t in threads:
        t.join(max(0.1, deadline - time.monotonic()))
    assert not any(t.is_alive() for t in threads), \
        f"HANG\n{log.getvalue()}"
    master.join(10.0)
    out = log.getvalue()
    assert isinstance(errors[2], FaultKill)
    assert all(errors[r] is None for r in range(N) if r != 2), \
        f"{errors}\n{out}"
    assert spare_out.get("rank") == 2, f"{spare_out}\n{out}"
    for r in range(N):
        np.testing.assert_array_equal(results[r], want[r])
    assert "spare #0 lost" in out
    assert "spare #1 adopted as rank 2" in out


def test_elastic_off_preserves_fatal_contract():
    """The default (off) keeps the pre-elastic behavior bit-for-bit:
    one clean identical Mp4jFatalError naming the dead rank on every
    survivor."""
    fn, _, _, kw = _body("framed")
    _, errors, _, _, log = run_elastic(
        N, fn, fault_plan="kill:rank=2:nth=2", **kw)
    assert isinstance(errors[2], FaultKill)
    survivors = [errors[r] for r in range(N) if r != 2]
    assert all(isinstance(e, Mp4jFatalError) for e in survivors), \
        f"{errors}\n{log}"
    assert len({str(e) for e in survivors}) == 1
    assert "membership" not in log


def test_surplus_nonspare_registration_rejected_during_spare_wait():
    """Regression: with spares configured, rendezvous stays open past
    slave_num — a surplus NON-spare dial-in in that window must be
    rejected (closed), never assigned an out-of-range rank (it would
    hang at its first barrier while the real job released without
    it)."""
    log = io.StringIO()
    master = Master(2, timeout=JOIN, log_stream=log, elastic="replace",
                    spares=1).serve_in_thread()
    results = [None, None]
    errors: list = [None, None]

    def worker(i):
        try:
            s = ProcessCommSlave("127.0.0.1", master.port,
                                 timeout=JOIN, dead_rank_secs=20.0,
                                 elastic="replace")
            arr = np.ones(32) * (s.rank + 1)
            s.allreduce_array(arr, Operands.DOUBLE, Operators.SUM)
            results[s.rank] = arr
            s.close(0)
        except Exception as e:
            errors[i] = e

    ts = [threading.Thread(target=worker, args=(i,), daemon=True)
          for i in range(2)]
    for t in ts:
        t.start()
    time.sleep(0.8)   # both ranks registered; rendezvous now waits
    # only on the spare — the surplus window under test
    stray = connect("127.0.0.1", master.port, timeout=JOIN)
    stray.send_obj((REGISTER, {"listen_port": 1,
                               "host": "127.0.0.1", "fp": ""}))
    stray.set_timeout(10.0)
    with pytest.raises(Exception):
        stray.recv()             # surplus: master closes -> EOF/error
    stray.close()
    # the real job proceeds once the spare registers
    spare_out: dict = {}

    def spare():
        try:
            ProcessCommSlave("127.0.0.1", master.port, timeout=JOIN,
                             spare=True, elastic="replace",
                             dead_rank_secs=20.0)
        except Mp4jSpareReleased:
            spare_out["released"] = True

    sp = threading.Thread(target=spare, daemon=True)
    sp.start()
    deadline = time.monotonic() + JOIN
    for t in ts + [sp]:
        t.join(max(0.1, deadline - time.monotonic()))
    assert not any(t.is_alive() for t in ts + [sp]), \
        f"HANG\n{log.getvalue()}"
    master.join(10.0)
    assert errors == [None, None], f"{errors}\n{log.getvalue()}"
    for r in range(2):
        np.testing.assert_array_equal(results[r], np.ones(32) * 3.0)
    assert spare_out.get("released")
    assert master.final_code == 0


def test_spare_released_when_job_completes():
    """A never-needed spare is the success case: the job completes,
    the master releases the pool, and the spare constructor raises
    Mp4jSpareReleased instead of hanging."""
    def fn(slave, r):
        arr = np.ones(64)
        slave.allreduce_array(arr, Operands.DOUBLE, Operators.SUM)
        return arr

    results, errors, spares, master, log = run_elastic(
        2, fn, spare_fns=[lambda sp: None],
        master_kwargs={"elastic": "replace"}, elastic="replace")
    assert all(e is None for e in errors), f"{errors}\n{log}"
    assert "released" in spares[0], f"{spares}\n{log}"
    assert master.final_code == 0


# ----------------------------------------------------------------------
# knob validation: fail-stop conflict (the ISSUE 10 bugfix guard)
# ----------------------------------------------------------------------
def test_failstop_conflicts_with_elastic_modes(monkeypatch):
    """MP4J_MAX_RETRIES=0 (exact fail-stop reference semantics) must
    hard-disable both elastic modes with a validated-knob conflict
    error — never a silent precedence."""
    monkeypatch.setenv("MP4J_MAX_RETRIES", "0")
    for mode in ("replace", "shrink"):
        with pytest.raises(Mp4jError, match="conflicts"):
            tuning.elastic_mode(mode)
    monkeypatch.delenv("MP4J_MAX_RETRIES")
    # the master hits the SAME validator (env MP4J_MAX_RETRIES path)
    monkeypatch.setenv("MP4J_MAX_RETRIES", "0")
    with pytest.raises(Mp4jError, match="conflicts"):
        Master(2, elastic="shrink")
    monkeypatch.delenv("MP4J_MAX_RETRIES")
    # slave-side: explicit max_retries=0 + explicit elastic
    m = Master(1, timeout=10.0, log_stream=io.StringIO())
    m.serve_in_thread()
    try:
        with pytest.raises(Mp4jError, match="conflicts"):
            ProcessCommSlave("127.0.0.1", m.port, timeout=10.0,
                             max_retries=0, elastic="replace")
        slave = ProcessCommSlave("127.0.0.1", m.port, timeout=10.0)
        slave.close(0)
    finally:
        m.join(10.0)
    # off + fail-stop remains legal (the reference contract)
    monkeypatch.setenv("MP4J_MAX_RETRIES", "0")
    assert tuning.elastic_mode() == "off"
    monkeypatch.delenv("MP4J_MAX_RETRIES")
    with pytest.raises(Mp4jError):
        tuning.elastic_mode("sideways")
    assert tuning.spares(3) == 3
    with pytest.raises(Mp4jError):
        tuning.spares(-1)
    with pytest.raises(Mp4jError):
        tuning.adopt_secs(0)


# ----------------------------------------------------------------------
# observability: badges, counters, events
# ----------------------------------------------------------------------
def test_membership_observability_after_replace():
    """After a replacement: the membership doc counts it, the live
    view renders the REPLACED badge + spares line, Prometheus exports
    the counters, and the joiner's recovery log records the
    adoption."""
    from ytk_mp4j_tpu.obs import metrics as metrics_mod

    fn, spare_fn, _, kw = _body("framed")
    events: dict = {}

    def spare_fn2(sp):
        out = spare_fn(sp)
        events["recovery"] = sp._recovery.events()
        return out

    _, errors, spares, master, log = run_elastic(
        N, fn, spare_fns=[spare_fn2],
        fault_plan="kill:rank=2:nth=2",
        master_kwargs={"elastic": "replace"}, elastic="replace", **kw)
    assert all(errors[r] is None for r in range(N) if r != 2)
    ms = master.membership_status()
    assert ms["mode"] == "replace"
    assert ms["replacements"] == 1 and ms["shrinks"] == 0
    assert ms["badges"].get("2", "").startswith("REPLACED@e")
    assert ms["events"] and ms["events"][-1]["kind"] == "replace"
    doc = master.metrics_doc()
    assert doc["cluster"]["membership"]["replacements"] == 1
    text = metrics_mod.to_prometheus(doc)
    assert "mp4j_replacements_total 1" in text
    assert "mp4j_shrinks_total 0" in text
    assert "mp4j_spares_available 0" in text
    live = telemetry.format_live(doc)
    assert "membership: mode=replace" in live
    assert "1 replacement(s)" in live
    # joiner-side recovery log carries the adoption event
    kinds = [k for _, k, _ in events.get("recovery", [])]
    assert "adopted" in kinds


def test_membership_observability_after_shrink():
    from ytk_mp4j_tpu.obs import metrics as metrics_mod

    fn, _, _, kw = _body("framed")
    _, errors, _, master, log = run_elastic(
        N, fn, fault_plan="kill:rank=2:nth=2",
        master_kwargs={"elastic": "shrink"}, elastic="shrink", **kw)
    assert all(errors[r] is None for r in range(N) if r != 2), \
        f"{errors}\n{log}"
    ms = master.membership_status()
    assert ms["shrinks"] == 1
    assert ms["events"][-1]["kind"] == "shrink"
    assert ms["events"][-1]["dead"] == [2]
    text = metrics_mod.to_prometheus(master.metrics_doc())
    assert "mp4j_shrinks_total 1" in text
    live = telemetry.format_live(master.metrics_doc())
    assert "1 shrink(s)" in live


# ----------------------------------------------------------------------
# pure-function units
# ----------------------------------------------------------------------
def test_joiner_seq_rule():
    # in-flight survivors retry #5; the joiner enters #5 fresh
    assert membership.joiner_seq({0: (5, True), 1: (4, False)}) == 4
    # nobody in flight: match the idle position
    assert membership.joiner_seq({0: (3, False), 1: (3, False)}) == 3
    assert membership.joiner_seq({}) == 0


def test_shrink_mapping_and_rosters():
    m = membership.shrink_mapping(5, {1, 3})
    assert m == {0: 0, 2: 1, 4: 2}
    roster = [("h", p, "") for p in range(5)]
    assert membership.shrink_roster(roster, m) == [
        ("h", 0, ""), ("h", 2, ""), ("h", 4, "")]
    swapped = membership.swap_roster(roster, {2: ("x", 99, "fp")})
    assert swapped[2] == ("x", 99, "fp") and swapped[0] == roster[0]


def test_vocab_export_import_roundtrip():
    codecs: dict = {}
    ic = keycodec.IntKeyCodec()
    # grown over multiple calls with per-call sorted batches — code
    # order is NOT globally sorted
    ic.encode([50, 10], 2)
    ic.encode([5, 99], 2)
    oc = keycodec.ObjKeyCodec()
    oc.encode(["z", "a"], 2)
    oc.encode(["m"], 1)
    src = {"int": ic, "obj": oc}
    vocab = membership.export_vocab(src, None)
    membership.import_vocab(codecs, vocab)
    for kind in ("int", "obj"):
        assert codecs[kind].size == src[kind].size
        codes = np.arange(src[kind].size, dtype=np.int32)
        assert codecs[kind].decode(codes) == src[kind].decode(codes)
    # pin truncates the export to pre-attempt sizes
    # (IntKeyCodec orders each novel BATCH by sorted key: 10<50 -> 0,1)
    vocab2 = membership.export_vocab(src, {"int": 2, "obj": 2})
    assert vocab2["int"] == [10, 50] and vocab2["obj"] == ["z", "a"]
    # import into an occupied table is refused
    with pytest.raises(Mp4jError):
        membership.import_vocab(codecs, {"int": [1]})
    # import_keys preserves exact code order (not sorted order)
    ic2 = keycodec.IntKeyCodec()
    ic2.import_keys([50, 10, 5, 99])
    assert ic2.encode([10, 99, 50, 5], 4).tolist() == [1, 3, 0, 2]
    with pytest.raises(Mp4jError):
        ic2.import_keys([1, 2])


# ----------------------------------------------------------------------
# mid-map-sync vocabulary replay (the PR 10 follow-up, closed in
# ISSUE 11)
# ----------------------------------------------------------------------
def test_replace_mid_map_sync_vocab_replay():
    """A rank killed BETWEEN the novelty-up and decision-down legs of
    the job's FIRST map collective: the codec kind was created by the
    in-flight attempt, so it is absent from the donor's pre-attempt
    pin — the manifest must export that kind EMPTY (every survivor's
    retry truncates it to zero), never the attempt's tentative growth.
    Shipping the tentative table instead seeds the joiner with keys no
    survivor re-offers after the rollback: its novelty exchange skips
    them (already encoded locally), the canonical growth never assigns
    them on the survivors, and the job's code tables diverge for good.
    The regression: adoption converges bit-exactly in ONE retry round,
    and a SECOND map mixing old and novel keys — the call diverged
    tables corrupt even when the first looks right — stays bit-exact
    too."""
    def mk(r):
        # per-rank-unique keys: the dead rank's keys exist nowhere
        # else, so a stale joiner vocabulary cannot hide
        return {int(r * 1000 + k): np.float64((r + 1) * (k + 1))
                for k in range(40)}

    def mk2(r, d):
        d2 = {int(5000 + k): np.float64(r + 1) for k in range(20)}
        for kk in list(d)[:5]:
            d2[kk] = np.float64(1.0)
        return d2

    def body(slave, r, sabotage=False):
        d = mk(r)
        if sabotage:
            orig = slave._grow_map_codec
            state = {"fired": False}

            def grow(decision):
                if not state["fired"]:
                    state["fired"] = True
                    # die mid-sync: the novelty went up and the
                    # decision came down (so the DONOR survivor's
                    # codec holds the attempt's full tentative
                    # growth), but no column moved — the worst case
                    # for the manifest export
                    slave._fault_kill(None)
                    raise FaultKill(
                        "fault injection: rank 2 killed mid-map-sync")
                return orig(decision)

            slave._grow_map_codec = grow
        slave.allreduce_map(d, Operands.DOUBLE, Operators.SUM)
        d2 = mk2(r, mk(r))
        slave.allreduce_map(d2, Operands.DOUBLE, Operators.SUM)
        return d, d2

    def fn_clean(slave, r):
        return body(slave, r)

    def fn_faulted(slave, r):
        return body(slave, r, sabotage=(r == 2))

    def spare_fn(sp):
        assert sp.resume_seq == 0, sp.resume_seq
        return body(sp, 2)

    want, werr, _, _, _ = run_elastic(N, fn_clean, shm=False)
    assert all(e is None for e in werr), werr
    got, errors, spares, master, log = run_elastic(
        N, fn_faulted, spare_fns=[spare_fn], shm=False,
        master_kwargs={"elastic": "replace"}, elastic="replace")
    assert isinstance(errors[2], FaultKill), f"{errors}\n{log}"
    survivors = [errors[r] for r in range(N) if r != 2]
    assert all(e is None for e in survivors), \
        f"survivor errors: {errors}\n{log}"
    assert spares[0].get("adopted_rank") == 2, f"{spares}\n{log}"
    assert "error" not in spares[0], f"{spares[0].get('error')}\n{log}"
    for r in range(N):
        for i in range(2):
            assert set(got[r][i]) == set(want[r][i]), (r, i)
            for k in got[r][i]:
                assert got[r][i][k] == want[r][i][k], (r, i, k)
    assert master.final_code == 0, log
