"""mp4j-resilience (ISSUE 5): the chaos grid and the recovery engine.

The grid crosses {connection reset mid-allreduce, slave killed at
collective N, slow rank} with {raw, framed, columnar-map} data planes
and asserts the acceptance contract: bit-exact recovery within
``MP4J_MAX_RETRIES`` (the faulted run's outputs equal an unfaulted
run's, byte for byte), or — when a rank is permanently gone — a clean
SAME-MESSAGE error on every surviving rank within the bounded join.
Zero hangs anywhere: every scenario runs under a hard thread-join
deadline.

Plus unit coverage for the fault-plan grammar, the resilience knobs,
the new ``comm.stats()`` counters (retries / reconnects / aborts_seen),
the recovery spans in the mp4j-scope ring, fail-stop mode
(``MP4J_MAX_RETRIES=0``), retry exhaustion, and the master watchdog's
escalation from log-only diagnosis to the terminal abort fan-out.
"""

import io
import json
import threading
import time

import numpy as np
import pytest

from ytk_mp4j_tpu.comm.master import Master
from ytk_mp4j_tpu.comm.process_comm import ProcessCommSlave
from ytk_mp4j_tpu.exceptions import (
    Mp4jError, Mp4jFatalError, Mp4jTransportError)
from ytk_mp4j_tpu.obs import spans
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operator, Operators
from ytk_mp4j_tpu.resilience.faults import FaultInjector, FaultKill, FaultPlan
from ytk_mp4j_tpu.transport.tcp import connect
from ytk_mp4j_tpu.utils import trace, tuning

N = 4
JOIN = 45.0


def run_chaos(n, fn, fault_plan=None, join=JOIN, master_kwargs=None,
              **slave_kwargs):
    """Master + n slave threads under a HARD join deadline. Returns
    (results, errors, stats, log): per-rank fn results, per-rank
    exceptions (None when clean), per-rank comm.stats() snapshots, and
    the master's log. Asserts no thread outlives the deadline — the
    no-hang half of every acceptance criterion."""
    log = io.StringIO()
    master = Master(n, timeout=join, log_stream=log,
                    **(master_kwargs or {})).serve_in_thread()
    results = [None] * n
    errors: list = [None] * n
    stats: list = [None] * n

    def worker(i):
        slave = None
        try:
            slave = ProcessCommSlave(
                "127.0.0.1", master.port, timeout=join,
                fault_plan=fault_plan, dead_rank_secs=20.0,
                **slave_kwargs)
            results[slave.rank] = fn(slave, slave.rank)
            stats[slave.rank] = slave.stats()
            slave.close(0)
        except Exception as e:
            r = slave.rank if slave is not None else i
            errors[r] = e
            if slave is not None:
                stats[r] = slave.stats()
                try:
                    slave.close(1)
                except Exception:
                    pass

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(n)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + join
    for t in threads:
        t.join(max(0.1, deadline - time.monotonic()))
    hung = [i for i, t in enumerate(threads) if t.is_alive()]
    assert not hung, f"ranks {hung} hung past the join deadline:\n" \
                     + log.getvalue()
    master.join(10.0)
    return results, errors, stats, log.getvalue()


# ----------------------------------------------------------------------
# the chaos grid
# ----------------------------------------------------------------------
def _body(path):
    """Two collectives on the given data plane; the fault plans target
    the SECOND (ordinal 2), so the first proves the healthy path and
    establishes peer channels."""
    if path == "map":
        def fn(slave, r):
            d = {int(k): np.float64((r + 1) * k) for k in range(800)}
            slave.allreduce_map(d, Operands.DOUBLE, Operators.SUM)
            slave.barrier()   # lockstep: recovery is per-collective
            slave.allreduce_map(d, Operands.DOUBLE, Operators.SUM)
            return d
        return fn, {}

    # raw and framed planes: 120k f64 = 960 KB -> the rhd regime, whose
    # in-place halving merges make retry idempotence non-trivial
    rng = np.random.default_rng(11)
    alls = [rng.standard_normal(120_000) for _ in range(N)]

    def fn(slave, r):
        arr = alls[r].copy()
        slave.allreduce_array(arr, Operands.DOUBLE, Operators.SUM)
        # lockstep before the faulted call: recovery is per-collective
        # (an unsynchronized schedule can put ranks a whole collective
        # apart at fault time, which aborts terminally by design)
        slave.barrier()
        slave.allreduce_array(arr, Operands.DOUBLE, Operators.SUM)
        return arr
    return fn, {"native_transport": path == "raw"}


# transport dimension (ISSUE 7): the thread harness co-locates every
# rank, so the default plane is the shm rings — "reset" faults become
# the ring-poison analogue (the injector's invalidate() poisons the
# shared header) and recovery must drain/re-negotiate SEGMENTS, not
# sockets. shm=False pins the original all-TCP grid.
def _transport_kw(transport):
    return {} if transport == "shm" else {"shm": False}


def _totals(stats, keys=("retries", "reconnects", "aborts_seen")):
    tot = dict.fromkeys(keys, 0)
    for snap in stats:
        for entry in (snap or {}).values():
            for k in keys:
                tot[k] += int(entry.get(k, 0))
    return tot


@pytest.mark.parametrize("transport", ["shm", "tcp"])
@pytest.mark.parametrize("path", ["raw", "framed", "map"])
def test_chaos_reset_recovers_bit_exactly(path, transport):
    """A connection reset mid-collective recovers without operator
    intervention, bit-exact against an unfaulted run."""
    fn, kw = _body(path)
    kw.update(_transport_kw(transport))
    want, werr, _, _ = run_chaos(N, fn, fault_plan=None, **kw)
    assert all(e is None for e in werr)
    got, errors, stats, log = run_chaos(
        N, fn, fault_plan="reset:rank=1:nth=2", **kw)
    assert all(e is None for e in errors), \
        f"recovery failed: {errors}\n{log}"
    for w, g in enumerate(got):
        if path == "map":
            assert set(g) == set(want[w])
            for k in g:
                assert g[k] == want[w][k]     # bit-exact, no tolerance
        else:
            np.testing.assert_array_equal(g, want[w])
    tot = _totals(stats)
    # every rank observed exactly one abort round; at least the faulted
    # exchange pair retried; torn channels were re-dialed
    assert tot["aborts_seen"] == N
    assert tot["retries"] >= 1
    assert tot["reconnects"] >= 2
    assert "abort round -> epoch 1" in log


def test_chaos_reset_object_map_inplace_operator_recovers():
    """Regression: the retry snapshot must DEEP-copy mutable values.
    The pickled dict plane runs ``op(acc, src)`` directly on the
    caller's value objects; with a user operator that mutates its left
    argument in place, a shallow ``dict()`` snapshot would restore the
    same already-merged objects and the retry would double-apply peer
    contributions — silently wrong 'recovered' results."""
    iadd = Operator.custom(
        "IADD", lambda a, b: (a.__setitem__(0, a[0] + b[0]), a)[1],
        [0.0])

    def fn(slave, r):
        d = {k: [float((r + 1) * k)] for k in range(50)}
        slave.allreduce_map(d, Operands.OBJECT_OPERAND(), iadd)
        slave.barrier()   # lockstep: recovery is per-collective
        slave.allreduce_map(d, Operands.OBJECT_OPERAND(), iadd)
        return d

    want, werr, _, _ = run_chaos(N, fn, fault_plan=None)
    assert all(e is None for e in werr)
    got, errors, stats, log = run_chaos(
        N, fn, fault_plan="reset:rank=1:nth=2")
    assert all(e is None for e in errors), \
        f"recovery failed: {errors}\n{log}"
    for w, g in enumerate(got):
        assert g == want[w], f"rank {w}: {g} != {want[w]}"
    assert _totals(stats)["retries"] >= 1


def test_reduce_plane_inplace_operator_values_isolated():
    """Regression: the pickled reduce planes (_reduce_map_obj /
    non-numeric reduce_array) must copy VALUES, not just the
    container. An in-place-mutating operator otherwise merges into the
    caller's value objects mid-protocol — corrupting non-root inputs
    even on a healthy run, and double-applying contributions when the
    epoch-fenced retry re-runs from the (supposedly untouched)
    input. These collectives are _SNAPSHOT_FREE on the strength of
    that copy."""
    iadd = Operator.custom(
        "IADD", lambda a, b: (a.__setitem__(0, a[0] + b[0]), a)[1],
        [0.0])

    def fn(slave, r):
        d = {k: [float((r + 1) * k)] for k in range(30)}
        orig = {k: list(v) for k, v in d.items()}
        slave.reduce_map(d, Operands.OBJECT_OPERAND(), iadd, root=0)
        slave.barrier()   # lockstep: recovery is per-collective
        slave.reduce_map(d, Operands.OBJECT_OPERAND(), iadd, root=0)
        if slave.rank != 0:
            assert d == orig, "non-root input mutated by reduce_map"
        slave.barrier()
        xs = [[float(slave.rank + 1)] for _ in range(8)]
        xs_orig = [list(v) for v in xs]
        slave.reduce_array(xs, Operands.OBJECT_OPERAND(), iadd, root=0)
        if slave.rank != 0:
            assert xs == xs_orig, "non-root input mutated by reduce_array"
        return d

    want, werr, _, _ = run_chaos(N, fn)
    assert all(e is None for e in werr), werr
    got, errors, _, log = run_chaos(
        N, fn, fault_plan="reset:rank=1:nth=2")
    if any(errors):
        # reduce-to-root completes its sender ranks early, so this
        # fault window usually spans a collective boundary — the
        # documented terminal outcome, which must then be the SAME
        # clean error on every rank (never a hang, never a silently
        # wrong root result)
        assert all(isinstance(e, Mp4jFatalError) for e in errors), \
            f"{errors}\n{log}"
        assert len({str(e) for e in errors}) == 1, errors
    else:
        assert got[0] == want[0], f"root diverged after recovery"


@pytest.mark.parametrize("transport", ["shm", "tcp"])
@pytest.mark.parametrize("path", ["raw", "framed", "map"])
def test_chaos_kill_gives_clean_identical_error(path, transport):
    """A slave killed at collective N: the killed rank raises
    FaultKill, every SURVIVOR raises the same Mp4jFatalError naming
    the dead rank, within the bounded join — never a hang, never a
    partial result."""
    fn, kw = _body(path)
    kw.update(_transport_kw(transport))
    _, errors, _, log = run_chaos(
        N, fn, fault_plan="kill:rank=2:nth=2", **kw)
    assert isinstance(errors[2], FaultKill)
    survivors = [errors[r] for r in range(N) if r != 2]
    assert all(isinstance(e, Mp4jFatalError) for e in survivors), \
        f"{errors}\n{log}"
    msgs = {str(e) for e in survivors}
    assert len(msgs) == 1, f"survivors disagree: {msgs}"
    assert "rank 2" in msgs.pop()
    assert "terminal abort" in log


@pytest.mark.parametrize("transport", ["shm", "tcp"])
@pytest.mark.parametrize("path", ["raw", "framed", "map"])
def test_chaos_slow_rank_completes_bit_exactly(path, transport):
    """A persistently slow rank is a performance event, not a fault:
    no retries, no aborts, bit-exact output."""
    fn, kw = _body(path)
    kw.update(_transport_kw(transport))
    want, werr, _, _ = run_chaos(N, fn, fault_plan=None, **kw)
    assert all(e is None for e in werr)
    got, errors, stats, _ = run_chaos(
        N, fn, fault_plan="slow:rank=3:secs=0.002", **kw)
    assert all(e is None for e in errors), errors
    for w, g in enumerate(got):
        if path == "map":
            assert g == want[w]
        else:
            np.testing.assert_array_equal(g, want[w])
    tot = _totals(stats)
    assert tot == {"retries": 0, "reconnects": 0, "aborts_seen": 0}


def test_chaos_reset_with_growing_vocabulary_stays_consistent():
    """A reset during a map collective whose keys are NOVEL exercises
    the codec rollback: a torn sync round can leave the vocabulary
    grown on some ranks only, so the retry must first truncate back to
    the pre-attempt size or code tables desync job-wide. Three calls
    with disjoint fresh keys, the middle one faulted; a final call
    proves the vocabulary still agrees everywhere."""
    def fn(slave, r):
        out = []
        for step in range(3):
            base = 10_000 * step
            d = {base + int(k): np.float64((r + 1) * (k + 1))
                 for k in range(400)}
            slave.barrier()   # lockstep (recovery is per-collective)
            slave.allreduce_map(d, Operands.DOUBLE, Operators.SUM)
            out.append(d)
        return out

    want, werr, _, _ = run_chaos(N, fn)
    assert all(e is None for e in werr)
    got, errors, stats, log = run_chaos(
        N, fn, fault_plan="reset:rank=1:nth=2")
    assert all(e is None for e in errors), f"{errors}\n{log}"
    for w, g in zip(want, got):
        assert g == w          # all three steps bit-exact, dict ==
    assert _totals(stats)["aborts_seen"] == N


def test_codec_truncate_rolls_back_a_half_grown_vocabulary():
    """Unit half of the rollback: truncate drops codes, keys AND the
    cached partition placements, so a re-grown code slot can hold a
    different key with a correct placement."""
    from ytk_mp4j_tpu.comm import keycodec

    for codec, keys_a, keys_b in (
            (keycodec.IntKeyCodec(), [5, 9, 1], [77, 42]),
            (keycodec.ObjKeyCodec(), ["a", "c", "b"], ["zz", "q"])):
        codec.encode(keys_a, len(keys_a))
        base = codec.size
        decode_before = codec.decode(np.arange(base, dtype=np.int32))
        part_before = codec.partition(
            np.arange(base, dtype=np.int32), 4).tolist()
        codec.encode(keys_b, len(keys_b))
        assert codec.size == base + len(keys_b)
        codec.truncate(base)
        assert codec.size == base
        assert codec.novel(keys_b, len(keys_b)) == keys_b   # forgotten
        # re-grow DIFFERENT keys into the same code slots
        other = [k * 2 for k in keys_b] if codec.size and \
            isinstance(keys_b[0], int) else [k + "!" for k in keys_b]
        codes = codec.encode(other, len(other))
        assert codec.decode(codes) == other
        # surviving codes keep their original keys and placements
        assert codec.decode(
            np.arange(base, dtype=np.int32)) == decode_before
        assert codec.partition(
            np.arange(base, dtype=np.int32), 4).tolist() == part_before
        # truncating to a larger-or-equal size is a no-op
        codec.truncate(codec.size + 10)
        assert codec.decode(codes) == other


# ----------------------------------------------------------------------
# recovery engine edges
# ----------------------------------------------------------------------
def test_retry_exhaustion_is_terminal_and_identical():
    """A fault that outlives the retry budget: N resets armed at the
    same ordinal cut one attempt per recovery round, so max_retries=1
    exhausts and the master fans out ONE terminal message that every
    rank raises."""
    fn, kw = _body("raw")
    _, errors, _, log = run_chaos(
        N, fn, fault_plan="reset:rank=1:nth=2;reset:rank=1:nth=2;"
                          "reset:rank=1:nth=2;reset:rank=1:nth=2",
        max_retries=1, **kw)
    assert all(isinstance(e, Mp4jFatalError) for e in errors), \
        f"{errors}\n{log}"
    msgs = {str(e) for e in errors}
    assert len(msgs) == 1, msgs
    assert "failed after 1 recovery round" in msgs.pop()


def test_failstop_mode_is_reference_behavior():
    """MP4J_MAX_RETRIES=0 restores PR-1 semantics: the first transport
    error is final, no abort round runs, peers surface their own
    bounded-timeout errors."""
    fn, kw = _body("raw")
    _, errors, stats, log = run_chaos(
        N, fn, fault_plan="reset:rank=1:nth=2", max_retries=0,
        peer_timeout=1.5, **kw)
    assert any(isinstance(e, Mp4jError) for e in errors)
    tot = _totals(stats)
    assert tot["retries"] == 0 and tot["aborts_seen"] == 0
    assert "abort round" not in log


def test_recovery_spans_land_in_scope_ring(tmp_path):
    """Abort/retry events are visible in the mp4j-scope Chrome trace
    (zero-duration 'recovery' instants)."""
    spans.configure(16384)
    spans.clear()
    try:
        fn, kw = _body("framed")
        _, errors, _, log = run_chaos(
            N, fn, fault_plan="reset:rank=1:nth=2", **kw)
        assert all(e is None for e in errors), \
            f"recovery failed: {errors}\n{log}"
        cats = {s[0] for s in spans.snapshot() if s[1] == "recovery"}
        assert "abort" in cats and "retry" in cats
        out = tmp_path / "trace.json"
        trace.export_chrome_trace(str(out))
        doc = json.loads(out.read_text())
        rec = [ev for ev in doc["traceEvents"]
               if ev.get("cat") == "recovery"]
        assert rec and all(ev["dur"] == 0 for ev in rec)
    finally:
        spans.configure(tuning.span_ring_capacity())


def test_watchdog_escalates_stalled_barrier_to_terminal_abort():
    """The PR-3 watchdog acted on nothing; now a barrier stalled past
    dead_rank_secs terminates the whole job cluster-wide instead of
    relying on each rank's local timeout."""
    def fn(slave, r):
        if r == 1:
            time.sleep(6.0)   # rank 0 waits at the barrier alone
        slave.barrier()
        return None

    _, errors, _, log = run_chaos(
        2, fn, master_kwargs={"stall_timeout": 0.3,
                              "dead_rank_secs": 1.0})
    assert all(isinstance(e, Mp4jFatalError) for e in errors), errors
    msgs = {str(e) for e in errors}
    assert len(msgs) == 1
    assert "barrier gen 0 stalled" in msgs.pop()
    assert "terminal abort" in log


def test_mixed_progress_rule():
    """The master releases an abort round only when every in-flight
    rank retries the SAME collective and idle ranks sit exactly one
    behind; anything else (a fault spanning a collective boundary) is
    terminal — a completed rank cannot re-serve its contribution."""
    ok = Master._mixed_progress
    # consistent: all retrying #5, one idle rank about to enter #5
    assert ok({0: (5, True), 1: (5, True), 2: (4, False)}) is None
    # nobody in flight: nothing to align
    assert ok({0: (3, False), 1: (3, False)}) is None
    # a rank already COMPLETED the collective others must retry
    msg = ok({0: (5, True), 1: (5, False)})
    assert msg is not None and "collective boundary" in msg
    # in-flight ranks at different collectives
    msg = ok({0: (5, True), 1: (4, True)})
    assert msg is not None and "rank 1 at collective #4" in msg
    # an idle rank two behind can never reach the retried collective
    assert ok({0: (5, True), 1: (3, False)}) is not None


def test_watchdog_escalation_works_without_stall_timeout():
    """dead_rank_secs must bound the job even when the diagnosis-only
    stall_timeout is disabled — the escalation is not allowed to ride
    on the diagnosis being armed."""
    def fn(slave, r):
        if r == 1:
            time.sleep(6.0)
        slave.barrier()
        return None

    _, errors, _, log = run_chaos(
        2, fn, master_kwargs={"stall_timeout": None,
                              "dead_rank_secs": 1.0})
    assert all(isinstance(e, Mp4jFatalError) for e in errors), errors
    assert "barrier gen 0 stalled" in str(errors[0])


def test_dead_peer_default_recovery_goes_terminal_quickly():
    """A rank that defects (clean close, nonzero code) mid-job: with
    recovery ON by default the survivors converge on one clean
    terminal error naming the departed rank — no local peer_timeout
    needed, no hang."""
    def fn(slave, r):
        if r == 1:
            raise RuntimeError("defect before the collective")
        arr = np.ones(64)
        slave.allreduce_array(arr, Operands.DOUBLE, Operators.SUM)
        return arr

    _, errors, _, log = run_chaos(2, fn)
    assert isinstance(errors[0], Mp4jFatalError)
    assert "rank 1" in str(errors[0])


def test_stray_dial_ins_rejected_at_handshake():
    """Regression: a stray connection to a slave's peer listen socket
    carrying a coercible-but-wrong-typed handshake (('1',0), (2.7,0),
    (True,0)) must be rejected at the handshake — never claim a
    healthy rank's peer slot, never launder through a recovery
    round."""
    def fn(slave, r):
        if r == 0:
            port = slave._server.getsockname()[1]
            for bad in [("1", 0), (2.7, 0), (True, 0), "junk", (7,)]:
                ch = connect("127.0.0.1", port, timeout=5.0)
                try:
                    ch.send_obj(bad)
                finally:
                    ch.close()
        else:
            time.sleep(0.8)   # strays land before the real dials
        x = np.arange(16, dtype=np.float64) + r
        slave.allreduce_array(x, Operands.DOUBLE, Operators.SUM)
        return x

    res, errors, stats, log = run_chaos(N, fn)
    assert errors == [None] * N, f"{errors}\n{log}"
    want = sum(np.arange(16, dtype=np.float64) + r for r in range(N))
    for g in res:
        np.testing.assert_array_equal(g, want)
    assert _totals(stats)["retries"] == 0   # rejected, not recovered


def test_malformed_control_frame_is_fatal_not_a_hang():
    """Regression: a malformed-but-tuple control frame (('abort',))
    used to raise out of the ctl loop's dispatch, killing the sole
    master-channel reader without setting fatal — an untimed barrier
    wait would then hang forever. It must surface as a clean terminal
    error on every rank within the bounded join."""
    log = io.StringIO()
    master = Master(2, timeout=15.0, log_stream=log).serve_in_thread()
    errors: list = [None, None]

    def worker(i):
        slave = None
        try:
            slave = ProcessCommSlave("127.0.0.1", master.port,
                                     timeout=15.0, dead_rank_secs=8.0)
            for _ in range(60):
                slave.barrier()
                time.sleep(0.05)
            slave.close(0)
        except Exception as e:
            errors[slave.rank if slave is not None else i] = e
            if slave is not None:
                try:
                    slave.close(1)
                except Exception:
                    pass

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(2)]
    for t in threads:
        t.start()
    time.sleep(0.5)
    master._send_to(0, ("abort",))    # torn frame: no epoch field
    deadline = time.monotonic() + 20.0
    for t in threads:
        t.join(max(0.0, deadline - time.monotonic()))
    assert not any(t.is_alive() for t in threads), \
        f"HANG after malformed control frame\n{log.getvalue()}"
    master.join(5.0)
    assert all(isinstance(e, Mp4jFatalError) for e in errors), errors
    assert "protocol violation" in str(errors[0])


# ----------------------------------------------------------------------
# fault-plan grammar + knobs
# ----------------------------------------------------------------------
def test_fault_plan_parse_grammar():
    plan = FaultPlan.parse(
        "seed=42; reset:rank=1:nth=3:peer=2;"
        "delay:rank=0:nth=2:secs=0.2; slow:rank=3:secs=0.01;"
        "kill:rank=2:nth=5")
    assert plan.seed == 42 and len(plan.faults) == 4
    r = plan.faults[0]
    assert (r.action, r.rank, r.nth, r.peer) == ("reset", 1, 3, 2)
    assert plan.for_rank(3)[0].action == "slow"
    assert plan.for_rank(9) == []


@pytest.mark.parametrize("bad", [
    "explode:rank=1",            # unknown action
    "reset",                     # missing rank
    "reset:rank=x",              # non-int rank
    "delay:rank=0",              # delay without secs
    "reset:rank=1:color=red",    # unknown field
    "seed=abc",                  # bad seed
    "reset:rank=1:prob=2.0",     # prob outside [0, 1]
])
def test_fault_plan_rejects_garbage(bad):
    with pytest.raises(Mp4jError):
        FaultPlan.parse(bad)


def test_fault_plan_prob_is_seed_deterministic():
    plan = FaultPlan.parse("seed=7; reset:rank=0:prob=0.5;"
                           "reset:rank=0:prob=0.5")
    picks = [not FaultInjector(plan, 0).empty for _ in range(3)]
    assert picks[0] == picks[1] == picks[2]  # same seed, same outcome
    none = FaultPlan.parse("reset:rank=0:prob=0.0")
    assert FaultInjector(none, 0).empty


def test_resilience_knobs_env_validated(monkeypatch):
    monkeypatch.setenv("MP4J_MAX_RETRIES", "3")
    assert tuning.max_retries() == 3
    monkeypatch.setenv("MP4J_MAX_RETRIES", "-1")
    with pytest.raises(Mp4jError):
        tuning.max_retries()
    monkeypatch.setenv("MP4J_RECONNECT_BACKOFF", "nope")
    with pytest.raises(Mp4jError):
        tuning.reconnect_backoff()
    monkeypatch.setenv("MP4J_DEAD_RANK_SECS", "0")
    with pytest.raises(Mp4jError):
        tuning.dead_rank_secs()
    monkeypatch.setenv("MP4J_FAULT_PLAN", " reset:rank=0 ")
    assert tuning.fault_plan_spec() == "reset:rank=0"


def test_dead_rank_secs_constructor_validated():
    """The explicit constructor arg must get the same positivity check
    as the env path: dead_rank_secs=0 would arm a watchdog that
    terminal-aborts healthy jobs (master) / instantly expire every
    recovery deadline (slave) — reject it at construction, on both.
    inf (the documented disable idiom) stays accepted."""
    with pytest.raises(Mp4jError, match="dead_rank_secs"):
        Master(1, dead_rank_secs=0.0)
    with pytest.raises(Mp4jError, match="dead_rank_secs"):
        Master(1, dead_rank_secs=-1.0)
    m = Master(1, timeout=10.0, dead_rank_secs=float("inf"),
               log_stream=io.StringIO()).serve_in_thread()
    try:
        with pytest.raises(Mp4jError, match="dead_rank_secs"):
            ProcessCommSlave("127.0.0.1", m.port, timeout=10.0,
                             dead_rank_secs=0.0)
        slave = ProcessCommSlave("127.0.0.1", m.port, timeout=10.0)
        slave.barrier()
        slave.close(0)
    finally:
        m.join(10.0)


def test_error_hierarchy():
    """Recovery retries transport errors only; fatal is never
    transport (nothing may retry it)."""
    from ytk_mp4j_tpu.exceptions import Mp4jAbortError
    assert issubclass(Mp4jTransportError, Mp4jError)
    assert issubclass(Mp4jAbortError, Mp4jTransportError)
    assert issubclass(Mp4jFatalError, Mp4jError)
    assert not issubclass(Mp4jFatalError, Mp4jTransportError)
    assert issubclass(FaultKill, Mp4jError)
    assert not issubclass(FaultKill, Mp4jTransportError)


# ----------------------------------------------------------------------
# mp4j-async chaos (ISSUE 11): {reset, kill, slow} x {2, 8 outstanding}
# x {tcp, shm} over nonblocking futures
# ----------------------------------------------------------------------
def _async_body(k):
    """One healthy blocking allreduce (establishes channels + ordinal
    1), a barrier (lockstep: recovery is per-collective), then k
    OUTSTANDING iallreduces drained by wait_all; the fault plans
    target ordinal 2 = the first batch member, so the fault lands
    inside the engine batch on every rank."""
    rng = np.random.default_rng(23)
    alls = [rng.standard_normal(120_000) for _ in range(N)]

    def fn(slave, r):
        warm = alls[r].copy()
        slave.allreduce_array(warm, Operands.DOUBLE, Operators.SUM)
        slave.barrier()
        arrs = [alls[r].copy() * (i + 1) for i in range(k)]
        futs = [slave.iallreduce(a, Operands.DOUBLE, Operators.SUM)
                for a in arrs]
        slave.wait_all()
        assert all(f.done() for f in futs)
        return arrs
    return fn


@pytest.mark.parametrize("transport", ["tcp", "shm"])
@pytest.mark.parametrize("k", [2, 8])
def test_async_reset_recovers_bit_exact(k, transport):
    """A connection reset inside an engine batch of k outstanding
    futures: the whole batch restores and re-drives at the new epoch,
    bit-exact against an unfaulted run, zero errors, zero hangs."""
    kw = {} if transport == "shm" else {"shm": False}
    fn = _async_body(k)
    want, werr, _, _ = run_chaos(N, fn, fault_plan=None, **kw)
    assert all(e is None for e in werr), werr
    got, errors, stats, log = run_chaos(
        N, fn, fault_plan="reset:rank=1:nth=2", **kw)
    assert all(e is None for e in errors), f"{errors}\n{log}"
    for r in range(N):
        for i in range(k):
            np.testing.assert_array_equal(got[r][i], want[r][i])
    # the reset forced an epoch-fenced retry somewhere (which rank
    # books it can race with the round's completion on this 1-core
    # host; the bit-exact outputs above are the real contract)
    assert any(stats[r].get("allreduce_array", {}).get("retries", 0)
               >= 1 for r in range(N)), stats


@pytest.mark.parametrize("transport", ["tcp", "shm"])
@pytest.mark.parametrize("k", [2, 8])
def test_async_kill_same_message_everywhere(k, transport):
    """A rank killed inside an engine batch: the killed rank's waiter
    raises FaultKill, every survivor raises the SAME Mp4jFatalError,
    nobody hangs."""
    kw = {} if transport == "shm" else {"shm": False}
    fn = _async_body(k)
    got, errors, _, log = run_chaos(
        N, fn, fault_plan="kill:rank=2:nth=2", **kw)
    assert isinstance(errors[2], FaultKill), f"{errors}\n{log}"
    survivor_msgs = {str(errors[r]) for r in range(N) if r != 2}
    assert all(isinstance(errors[r], Mp4jFatalError)
               for r in range(N) if r != 2), f"{errors}\n{log}"
    assert len(survivor_msgs) == 1, survivor_msgs


@pytest.mark.parametrize("transport", ["tcp", "shm"])
@pytest.mark.parametrize("k", [2, 8])
def test_async_slow_rank_still_bit_exact(k, transport):
    """An injected-slow rank inside the batch: no retry needed, just
    latency — results bit-exact, zero errors."""
    kw = {} if transport == "shm" else {"shm": False}
    fn = _async_body(k)
    want, werr, _, _ = run_chaos(N, fn, fault_plan=None, **kw)
    assert all(e is None for e in werr), werr
    got, errors, _, log = run_chaos(
        N, fn, fault_plan="slow:rank=3:nth=2:secs=0.02", **kw)
    assert all(e is None for e in errors), f"{errors}\n{log}"
    for r in range(N):
        for i in range(k):
            np.testing.assert_array_equal(got[r][i], want[r][i])
