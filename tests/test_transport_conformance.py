"""Transport-conformance grid (ISSUE 7 satellite): ONE parametrized
contract suite run against BOTH concrete transports, so any third
transport gets correctness for free by joining the fixture.

Covers the whole Channel SPI surface: every frame type (objects,
arrays across dtypes, paired map columns, the unframed raw plane) ×
compression (plain, one-shot Z, streamed ZC) × in-place receives
(``recv_array_into`` + chunk callbacks) × protocol-violation errors ×
timeout expiry × ``invalidate()`` under a BLOCKED receive (both local
and remote side — the recovery teardown's wake contract) × graceful
close (the finishing-rank drain discipline).

The shm pairs deliberately run a TINY ring (8 KiB) so multi-hundred-KB
frames wrap the ring dozens of times — the wraparound, backpressure
and spin/nap wakeup machinery is the part a happy-path test would
never touch.
"""

import secrets
import socket
import threading
import time

import numpy as np
import pytest

from ytk_mp4j_tpu.exceptions import Mp4jError, Mp4jTransportError
from ytk_mp4j_tpu.transport import shm as shm_mod
from ytk_mp4j_tpu.transport.tcp import TcpChannel

RING = 8192          # tiny on purpose: force wraparound + backpressure
TRANSPORTS = ("tcp", "shm")


def make_pair(kind):
    """(channel_a, channel_b) — a connected duplex pair of ``kind``."""
    a, b = socket.socketpair()
    if kind == "tcp":
        return TcpChannel(a), TcpChannel(b)
    name = f"mp4j-test-{secrets.token_hex(4)}"
    seg_a = shm_mod.create_segment(name, RING)
    seg_b = shm_mod.attach_segment(seg_a.token)
    return (shm_mod.ShmChannel(a, seg_a, RING, owner=True),
            shm_mod.ShmChannel(b, seg_b, RING, owner=False))


@pytest.fixture(params=TRANSPORTS)
def pair(request):
    ca, cb = make_pair(request.param)
    yield ca, cb
    for ch in (ca, cb):
        try:
            ch.close()
        except Exception:
            pass


def pump(send_fn, recv_fn, timeout=20.0):
    """Run ``send_fn`` on a helper thread while ``recv_fn`` runs here —
    the duplex discipline every large transfer needs (kernel socket
    buffers and the shm ring are both finite)."""
    box = {}

    def sender():
        try:
            send_fn()
        except BaseException as e:      # surfaced below
            box["err"] = e

    t = threading.Thread(target=sender, daemon=True)
    t.start()
    out = recv_fn()
    t.join(timeout)
    assert not t.is_alive(), "sender hung"
    if "err" in box:
        raise box["err"]
    return out


# ----------------------------------------------------------------------
# frame types × compression
# ----------------------------------------------------------------------
@pytest.mark.parametrize("compress", [False, True])
def test_obj_roundtrip(pair, compress):
    ca, cb = pair
    payload = {"k": [1, 2.5, "s"], "nested": (None, b"bytes" * 50)}
    out = pump(lambda: ca.send_obj(payload, compress=compress),
               cb.recv)
    assert out == payload


@pytest.mark.parametrize("dtype", [np.float32, np.float64, np.int32,
                                   np.int64, np.int8, np.uint16])
def test_array_roundtrip_dtypes(pair, dtype):
    ca, cb = pair
    rng = np.random.default_rng(7)
    arr = (rng.standard_normal(9001).astype(dtype)
           if np.dtype(dtype).kind == "f"
           else rng.integers(0, 100, 9001).astype(dtype))
    out = pump(lambda: ca.send_array(arr), cb.recv_array)
    assert out.dtype == arr.dtype
    np.testing.assert_array_equal(out, arr)


@pytest.mark.parametrize("compress", [False, True])
def test_large_array_wraps_ring(pair, compress):
    # ~400 KiB >> the 8 KiB shm ring: dozens of wraparounds (and the
    # compressed leg streams self-delimiting ZC chunks through it)
    ca, cb = pair
    arr = np.arange(100_000, dtype=np.float32)
    out = pump(lambda: ca.send_array(arr, compress=compress),
               cb.recv_array)
    np.testing.assert_array_equal(out, arr)


def test_bidirectional_simultaneous(pair):
    # full-duplex: both sides send ~200 KiB at once — deadlocks here
    # mean the transport serialized its directions
    ca, cb = pair
    x = np.arange(50_000, dtype=np.float64)

    def recv_both():
        return cb.recv_array()

    out_b = pump(lambda: ca.send_array(x), recv_both)
    out_a = pump(lambda: cb.send_array(x + 1), ca.recv_array)
    np.testing.assert_array_equal(out_b, x)
    np.testing.assert_array_equal(out_a, x + 1)


@pytest.mark.parametrize("compress", [False, True])
def test_map_columns_roundtrip(pair, compress):
    ca, cb = pair
    codes = np.arange(5000, dtype=np.int32)
    values = np.random.default_rng(3).standard_normal((5000, 2))
    rc, rv = pump(
        lambda: ca.send_map_columns(codes, values, compress=compress),
        cb.recv_map_columns)
    np.testing.assert_array_equal(rc, codes)
    np.testing.assert_array_equal(rv, values)


def test_malformed_map_columns_is_protocol_error(pair):
    ca, cb = pair
    codes = np.arange(4, dtype=np.int64)     # not int32: violation
    values = np.zeros((4, 1))
    with pytest.raises(Mp4jError, match="malformed map column pair"):
        pump(lambda: (ca.send_array(codes), ca.send_array(values)),
             cb.recv_map_columns)


@pytest.mark.parametrize("n", [600, 60_000, 300_000])
def test_raw_roundtrip(pair, n):
    # 2.4 KB rides the shm carrier, 240 KB sits at the hybrid
    # boundary, 1.2 MB takes the ring-piece path (150 pieces through
    # the 8 KiB test ring — wraparound + sync-byte machinery)
    ca, cb = pair
    arr = np.arange(n, dtype=np.int32)
    out = np.empty_like(arr)
    pump(lambda: ca.send_raw(arr), lambda: cb.recv_raw_into(out))
    np.testing.assert_array_equal(out, arr)


def test_duplex_exchange_shm_bidirectional():
    # the single-threaded cooperative duplex (the shm analogue of the
    # native poll loop): both directions at once, ring-sized payloads
    from ytk_mp4j_tpu.transport.shm import duplex_exchange

    ca, cb = make_pair("shm")
    try:
        big = np.arange(400_000, dtype=np.int32)
        out_a = np.empty_like(big)
        out_b = np.empty_like(big)

        def side_b():
            duplex_exchange(cb, big * 3, cb, out_b)

        t = threading.Thread(target=side_b, daemon=True)
        t.start()
        duplex_exchange(ca, big, ca, out_a)
        t.join(10.0)
        assert not t.is_alive()
        np.testing.assert_array_equal(out_a, big * 3)
        np.testing.assert_array_equal(out_b, big)
    finally:
        ca.close()
        cb.close()


# ----------------------------------------------------------------------
# in-place receives + chunk callbacks
# ----------------------------------------------------------------------
@pytest.mark.parametrize("compress", [False, True])
def test_recv_array_into_chunks_tile(pair, compress):
    ca, cb = pair
    arr = np.arange(700_000, dtype=np.float32)   # ~2.7 MB: >2 chunks
    dst = np.zeros_like(arr)
    seen = []
    pump(lambda: ca.send_array(arr, compress=compress),
         lambda: cb.recv_array_into(dst, on_chunk=seen.append
                                    if False else
                                    lambda lo, hi: seen.append((lo, hi))))
    np.testing.assert_array_equal(dst, arr)
    assert seen and seen[0][0] == 0 and seen[-1][1] == arr.size
    for (alo, ahi), (blo, bhi) in zip(seen, seen[1:]):
        assert ahi == blo and alo < ahi      # ascending, gap-free


def test_recv_array_into_mismatch_raises(pair):
    ca, cb = pair
    with pytest.raises(Mp4jError, match="does not match"):
        pump(lambda: ca.send_array(np.zeros(8, np.float64)),
             lambda: cb.recv_array_into(np.zeros(8, np.float32)))


def test_recv_array_into_rejects_obj_frame(pair):
    ca, cb = pair
    with pytest.raises(Mp4jError, match="expected an array frame"):
        pump(lambda: ca.send_obj({"not": "array"}),
             lambda: cb.recv_array_into(np.zeros(4, np.float32)))


# ----------------------------------------------------------------------
# timeouts, invalidate, close
# ----------------------------------------------------------------------
def test_recv_timeout_expires(pair):
    ca, cb = pair
    cb.set_timeout(0.2)
    t0 = time.monotonic()
    with pytest.raises(Mp4jTransportError, match="timed out"):
        cb.recv()
    assert time.monotonic() - t0 < 5.0


def test_send_timeout_when_peer_not_draining(pair):
    # fill the transport's buffering (kernel socket buffer / shm ring)
    # with nobody reading: the send must expire, not hang
    ca, cb = pair
    ca.set_timeout(0.3)
    big = np.zeros(4_000_000, np.uint8)
    with pytest.raises(Mp4jTransportError, match="timed out"):
        ca.send_array(big)


@pytest.mark.parametrize("side", ["local", "remote"])
def test_invalidate_unblocks_blocked_recv(pair, side):
    # the recovery teardown's contract: invalidate() — from EITHER end
    # — must wake a blocked receive with a transport error, promptly
    ca, cb = pair
    errs = []

    def blocked():
        try:
            cb.recv()
        except Mp4jTransportError as e:
            errs.append(e)

    t = threading.Thread(target=blocked, daemon=True)
    t.start()
    time.sleep(0.15)                    # ensure it is truly blocked
    (cb if side == "local" else ca).invalidate()
    t.join(5.0)
    assert not t.is_alive(), "invalidate did not wake the receive"
    assert len(errs) == 1


def test_invalidate_poisons_future_ops(pair):
    ca, cb = pair
    ca.invalidate()
    with pytest.raises((Mp4jTransportError, OSError)):
        ca.send_obj("x")
        # a poisoned/shutdown channel may need a receive to observe
        # the tear on some transports
        cb.recv()


def test_graceful_close_preserves_sent_frames(pair):
    # a finishing rank's last frames must survive its close: the peer
    # still reads them afterwards, and only the NEXT receive errors
    ca, cb = pair
    payload = np.arange(30_000, dtype=np.float32)

    def send_and_close():
        ca.send_array(payload)
        ca.close(graceful=True)

    out = pump(send_and_close, cb.recv_array)
    np.testing.assert_array_equal(out, payload)
    cb.set_timeout(5.0)
    with pytest.raises(Mp4jTransportError):
        cb.recv()


def test_shm_close_releases_segment():
    ca, cb = make_pair("shm")
    import glob

    ca.close()
    cb.close()
    # memfd backing leaves no name anywhere (kernel frees on last
    # close); the shm_open fallback must have unlinked its name
    assert not glob.glob("/dev/shm/mp4j-test-*")
    # the mapping is released: the segment buffer is no longer usable
    with pytest.raises((ValueError, TypeError)):
        ca._seg.buf[0]


def test_shm_carrier_death_unblocks_reader():
    # kill -9 analogue: the peer can never poison the ring, so the
    # carrier socket's EOF must surface within the liveness cadence
    ca, cb = make_pair("shm")
    try:
        errs = []

        def blocked():
            try:
                cb.recv()
            except Mp4jTransportError as e:
                errs.append(e)

        t = threading.Thread(target=blocked, daemon=True)
        t.start()
        time.sleep(0.1)
        ca.sock.close()                 # abrupt death, no poison
        t.join(5.0)
        assert not t.is_alive() and len(errs) == 1
        assert "carrier" in str(errs[0])
    finally:
        for ch in (ca, cb):
            try:
                ch.close()
            except Exception:
                pass
