"""Property grid for the shm transport and the two-level collectives
(ISSUE 7 acceptance): shm and two-level results must be BIT-IDENTICAL
to the all-TCP reference for every numeric operand × {SUM, MAX, MIN,
PROD} × non-pow2 rank counts — dense collectives AND columnar maps.

Inputs are small exact integers (stored in each operand's dtype), so
every merge order yields the same bits — which makes plain equality the
right assertion across schedules that legitimately reorder merges
(flat rhd vs intra-host tree + leader rhd).

Topology: the thread harness co-locates all ranks, so the all-shm flat
grid is the DEFAULT plane; the two-level grid builds a virtual 2-host
roster via the ``host_fp`` seam (which ranks land on which virtual
host is registration-order racy — deliberately: correctness may not
depend on the grouping).
"""

import threading

import numpy as np
import pytest

from ytk_mp4j_tpu.comm.master import Master
from ytk_mp4j_tpu.comm.process_comm import ProcessCommSlave
from ytk_mp4j_tpu.meta import partition_range
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operators

NUMERIC = [Operands.DOUBLE, Operands.FLOAT, Operands.INT,
           Operands.LONG, Operands.SHORT, Operands.BYTE]
OPS = [Operators.SUM, Operators.MAX, Operators.MIN, Operators.PROD]
LENGTH = 157                     # odd: uneven segments everywhere


def run_grid(n, fn, fps=None, timeout=60.0, **slave_kwargs):
    """Master + n slave threads; ``fps[i]`` (worker index, NOT rank —
    rank assignment is registration-order racy, on purpose) feeds the
    ``host_fp`` seam. Returns per-rank results."""
    master = Master(n, timeout=timeout).serve_in_thread()
    results = [None] * n
    errors = []

    def worker(i):
        slave = None
        try:
            kw = dict(slave_kwargs)
            if fps is not None:
                kw["host_fp"] = fps[i]
            slave = ProcessCommSlave("127.0.0.1", master.port,
                                     timeout=timeout, **kw)
            results[slave.rank] = fn(slave, slave.rank)
            slave.close(0)
        except Exception as e:  # pragma: no cover - surfaced below
            errors.append(e)
            if slave is not None:
                try:
                    slave.close(1)
                except Exception:
                    pass

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
        assert not t.is_alive(), "slave thread hung"
    if errors:
        raise errors[0]
    master.join(timeout)
    assert master.final_code == 0
    return results


def exact_inputs(n, operand, rng):
    """Per-rank arrays of small exact integers in the operand dtype:
    n PROD factors of magnitude <= 3 stay exact in every dtype here,
    so ANY merge order is bit-identical."""
    return [rng.integers(1, 4, LENGTH).astype(operand.dtype)
            for _ in range(n)]


def _virtual_hosts(n):
    """Worker-index fingerprints splitting n ranks over 2 virtual
    hosts (sizes differ for odd n — the interesting case)."""
    return ["hostA" if i < (n + 1) // 2 else "hostB" for i in range(n)]


@pytest.mark.parametrize("operand", NUMERIC, ids=lambda o: o.name)
@pytest.mark.parametrize("op", OPS, ids=lambda o: o.name)
@pytest.mark.parametrize("n", [3, 5])
def test_allreduce_grid_shm_and_twolevel_match_tcp(operand, op, n):
    rng = np.random.default_rng(hash((operand.name, op.name, n)) % 2**31)
    base = exact_inputs(n, operand, rng)

    def fn(slave, r):
        arr = base[r].copy()
        slave.allreduce_array(arr, operand, op)
        return arr

    tcp = run_grid(n, fn, shm=False)
    flat_shm = run_grid(n, fn)
    twolevel = run_grid(n, fn, fps=_virtual_hosts(n))
    for r in range(n):
        np.testing.assert_array_equal(flat_shm[r], tcp[r])
        np.testing.assert_array_equal(twolevel[r], tcp[r])
        np.testing.assert_array_equal(twolevel[r], tcp[0])


@pytest.mark.parametrize("operand", [Operands.DOUBLE, Operands.INT],
                         ids=lambda o: o.name)
@pytest.mark.parametrize("n", [3, 5])
def test_reduce_scatter_and_allgather_twolevel_match_tcp(operand, n):
    rng = np.random.default_rng(5 + n)
    base = exact_inputs(n, operand, rng)
    ranges = partition_range(0, LENGTH, n)

    def fn(slave, r):
        rs = base[r].copy()
        slave.reduce_scatter_array(rs, operand, Operators.SUM)
        ag = np.zeros(LENGTH, operand.dtype)
        s, e = ranges[slave.rank]
        ag[s:e] = base[slave.rank][s:e]
        slave.allgather_array(ag, operand, ranges=ranges)
        return rs, ag

    tcp = run_grid(n, fn, shm=False)
    twolevel = run_grid(n, fn, fps=_virtual_hosts(n))
    # reduce_scatter contract: OWN range reduced, other positions
    # untouched — assert both, against the TCP reference
    for r in range(n):
        s, e = ranges[r]
        np.testing.assert_array_equal(twolevel[r][0][s:e],
                                      tcp[r][0][s:e])
        np.testing.assert_array_equal(twolevel[r][0][:s],
                                      base[r][:s])
        np.testing.assert_array_equal(twolevel[r][0][e:],
                                      base[r][e:])
        np.testing.assert_array_equal(twolevel[r][1], tcp[r][1])


@pytest.mark.parametrize("op", OPS, ids=lambda o: o.name)
@pytest.mark.parametrize("n", [3, 5])
def test_columnar_map_grid_shm_and_twolevel_match_tcp(op, n):
    rng = np.random.default_rng(17 + n)
    # overlapping + disjoint keys across ranks; exact small values
    keys = [rng.choice(400, size=120, replace=False) for _ in range(n)]
    vals = [rng.integers(1, 4, size=120) for _ in range(n)]

    def fn(slave, r):
        d = {int(k): np.float64(v)
             for k, v in zip(keys[r], vals[r])}
        slave.allreduce_map(d, Operands.DOUBLE, op)
        return d

    tcp = run_grid(n, fn, shm=False)
    flat_shm = run_grid(n, fn)
    twolevel = run_grid(n, fn, fps=_virtual_hosts(n))
    for r in range(n):
        assert flat_shm[r] == tcp[r]          # bit-exact, no tolerance
        assert twolevel[r] == tcp[r]
        assert twolevel[r] == twolevel[0]


def test_twolevel_wire_split_attribution():
    """Analytic attribution (ISSUE 7 satellite): on a virtual 2-host
    topology every transport-tagged wire byte lands in exactly one of
    wire_bytes_shm / wire_bytes_tcp, their sum equals the directional
    totals, and BOTH planes moved bytes (intra-host vs inter-host)."""
    n = 4

    def fn(slave, r):
        arr = np.ones(50_000, np.float64) * (r + 1)
        slave.allreduce_array(arr, Operands.DOUBLE, Operators.SUM)
        return slave.stats()

    snaps = run_grid(n, fn, fps=_virtual_hosts(n))
    for snap in snaps:
        sent = sum(e.get("bytes_sent", 0) for e in snap.values())
        recv = sum(e.get("bytes_recv", 0) for e in snap.values())
        shm_b = sum(e.get("wire_bytes_shm", 0) for e in snap.values())
        tcp_b = sum(e.get("wire_bytes_tcp", 0) for e in snap.values())
        # every byte of this workload rode a peer channel (tagged):
        # the split must tile the totals exactly
        assert shm_b + tcp_b == sent + recv
        assert shm_b > 0
    # the leaders' inter-host leg is TCP on at least the two leaders
    assert sum(sum(e.get("wire_bytes_tcp", 0) for e in s.values())
               for s in snaps) > 0


def test_twolevel_nonnumeric_routes_to_safe_algo():
    """Explicit algo='twolevel' with a non-numeric operand must route
    to an object-capable schedule (allreduce/reduce_scatter: tree;
    allgather: ring) instead of crashing the leaders' raw-plane leg —
    regression for the review finding."""
    n = 4

    def fn(slave, r):
        xs = [f"r{r}-{i}" for i in range(8)]
        slave.allreduce_array(xs, Operands.STRING, Operators.SUM,
                              algo="twolevel")
        ag = [f"x{i}" if False else "" for i in range(8)]
        ranges = partition_range(0, 8, n)
        s, e = ranges[slave.rank]
        for i in range(s, e):
            ag[i] = f"own{slave.rank}-{i}"
        slave.allgather_array(ag, Operands.STRING, ranges=ranges,
                              algo="twolevel")
        return xs, ag

    out = run_grid(n, fn, fps=_virtual_hosts(n))
    for r in range(n):
        assert out[r][0] == out[0][0]       # allreduce agrees everywhere
        assert out[r][1] == out[0][1]
        for i, v in enumerate(out[r][1]):
            assert v.startswith("own")      # every slot filled
