"""mp4j-health (ISSUE 12): streaming anomaly detection and per-rank
verdicts. Detector unit grid on synthetic snapshot deltas (each
detector fires on its scenario, stays quiet on clean/noisy baselines,
hysteresis prevents flapping), the online dominator port, alert
plumbing (sink ``alerts`` records, recovery log, Prometheus, live
view, postmortem timeline), the ``mp4j-scope health`` CLI, knob
validation, and the chaos acceptance grid: an injected-``slow`` rank
reaches SUSPECT with the dominator detector named within a bounded
ordinal count while a clean 4-rank grid stays HEALTHY end-to-end with
zero alerts."""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from ytk_mp4j_tpu.exceptions import Mp4jError, Mp4jFatalError
from ytk_mp4j_tpu.obs import critpath, health, metrics, sink, spans
from ytk_mp4j_tpu.obs import postmortem, telemetry
from ytk_mp4j_tpu.obs.cli import main as scope_main
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operators
from ytk_mp4j_tpu.utils import tuning

N = 4
LIVE = {0, 1, 2, 3}


@pytest.fixture
def fresh_spans():
    spans.clear()
    yield
    spans.clear()


def _engine(**kw):
    kw.setdefault("window", 16)
    kw.setdefault("dominator_ordinals", 8)
    kw.setdefault("drift_pct", 100.0)
    kw.setdefault("hb_secs", 0.1)
    return health.HealthEngine(N, **kw)


def _cell(seq, dur, wire=0.0, links=None, family="allreduce_array"):
    return {"seq": seq, "family": family, "t0": 1000.0 + seq,
            "dur": dur,
            "phases": {"wire": wire, "reduce": dur * 0.1,
                       "serialize": 0.0},
            "links": links or {}}


def _beat(e, rank, seq, now, **payload):
    payload.setdefault("progress", {"seq": seq})
    return e.fold(rank, payload, now, LIVE)


def _clean_round(e, seq, now, dur=0.001):
    """One symmetric healthy ordinal folded from all four ranks."""
    out = []
    for r in range(N):
        out += _beat(e, r, seq, now, health_delta={
            "cells": [_cell(seq, dur, wire=dur / 2)]})
    return out


def _slow_round(e, seq, now, slow_rank=3, dur=0.021):
    """One ordinal gated by ``slow_rank``: its wire time dominates and
    every peer's wire wait votes blame on it."""
    out = []
    for r in range(N):
        if r == slow_rank:
            c = _cell(seq, dur, wire=dur * 0.95,
                      links={0: {"secs": dur * 0.9,
                                 "transport": "tcp", "bytes": 800_000}})
        else:
            c = _cell(seq, dur, wire=dur * 0.9,
                      links={slow_rank: {"secs": dur * 0.9,
                                         "transport": "tcp",
                                         "bytes": 800_000}})
        out += _beat(e, r, seq, now, health_delta={"cells": [c]})
    return out


# ----------------------------------------------------------------------
# knob validation
# ----------------------------------------------------------------------
def test_health_knob_validation(monkeypatch):
    monkeypatch.setenv("MP4J_HEALTH", "maybe")
    with pytest.raises(Mp4jError):
        tuning.health_enabled()
    monkeypatch.setenv("MP4J_HEALTH", "0")
    assert tuning.health_enabled() is False
    monkeypatch.setenv("MP4J_HEALTH", "on")
    assert tuning.health_enabled() is True
    assert tuning.health_enabled(override=False) is False
    monkeypatch.setenv("MP4J_HEALTH_WINDOW", "2")
    with pytest.raises(Mp4jError):
        tuning.health_window()
    monkeypatch.setenv("MP4J_HEALTH_WINDOW", "32")
    assert tuning.health_window() == 32
    monkeypatch.setenv("MP4J_HEALTH_DOMINATOR_ORDINALS", "1")
    with pytest.raises(Mp4jError):
        tuning.health_dominator_ordinals()
    monkeypatch.setenv("MP4J_HEALTH_DOMINATOR_ORDINALS", "500")
    assert tuning.health_dominator_ordinals() == 500
    monkeypatch.setenv("MP4J_HEALTH_DRIFT_PCT", "0.5")
    with pytest.raises(Mp4jError):
        tuning.health_drift_pct()
    monkeypatch.setenv("MP4J_HEALTH_DRIFT_PCT", "150")
    assert tuning.health_drift_pct() == 150.0


# ----------------------------------------------------------------------
# slave side: SpanFolder + AlertLog
# ----------------------------------------------------------------------
def test_span_folder_completes_cells(fresh_spans):
    f = health.SpanFolder(rank=1)
    # phases first, collective span closes the ordinal (the ring's
    # real ordering)
    spans.phase("wire", 0.002, 1, "allreduce_array", 7, peer=3,
                transport="tcp", bytes_sent=1000, bytes_recv=1000)
    spans.phase("reduce", 0.001, 1, "allreduce_array", 7)
    assert f.take() is None          # incomplete: no collective span
    spans.collective("allreduce_array", 0.0, 0.004, 1, 7)
    d = f.take()
    [c] = d["cells"]
    assert c["seq"] == 7 and c["family"] == "allreduce_array"
    assert c["dur"] == pytest.approx(0.004)
    assert c["phases"]["wire"] == pytest.approx(0.002)
    assert c["links"][3]["transport"] == "tcp"
    assert c["links"][3]["bytes"] == 2000
    assert d["dropped"] == 0
    assert f.take() is None          # nothing new


def test_span_folder_filters_other_ranks(fresh_spans):
    f = health.SpanFolder(rank=0)
    spans.collective("allreduce_array", 0.0, 0.001, 2, 5)
    spans.collective("allreduce_array", 0.0, 0.001, 0, 5)
    d = f.take()
    assert [c["seq"] for c in d["cells"]] == [5]


def test_span_folder_caps_and_counts_drops(fresh_spans):
    f = health.SpanFolder(rank=0, max_cells=4)
    for seq in range(1, 11):
        spans.collective("allreduce_array", 0.0, 0.001, 0, seq)
    d = f.take()
    assert len(d["cells"]) == 4
    # newest survive the cap
    assert [c["seq"] for c in d["cells"]] == [7, 8, 9, 10]
    assert d["dropped"] == 6


def test_alert_log_cursor_delta():
    log = health.AlertLog(maxlen=4)
    for i in range(6):
        log.note({"id": i})
    cur, evs, dropped = log.events_since(0)
    assert cur == 6 and dropped == 2
    assert [e["id"] for e in evs] == [2, 3, 4, 5]
    cur2, evs2, d2 = log.events_since(cur)
    assert evs2 == [] and d2 == 0


# ----------------------------------------------------------------------
# pure detector units
# ----------------------------------------------------------------------
def _hist(mean, count=8, bucket=10):
    counts = [0] * (metrics.LATENCY_BUCKETS + 1)
    counts[bucket] = count
    return {"lo": metrics.LATENCY_LO, "n": metrics.LATENCY_BUCKETS,
            "counts": counts, "count": count, "sum": mean * count}


def test_latency_drift_fires_after_two_folds_and_bucket_shift():
    base = {}
    for _ in range(health.WARMUP_FOLDS):
        assert health.detect_latency_drift(base, _hist(0.001),
                                           100.0) is None
    # 4x mean AND +2 buckets: first drifting fold only ARMS
    assert health.detect_latency_drift(base, _hist(0.004, bucket=12),
                                       100.0) is None
    hit = health.detect_latency_drift(base, _hist(0.004, bucket=12),
                                      100.0)
    assert hit is not None and hit[0] >= 1
    assert "baseline" in hit[1]


def test_latency_drift_quiet_on_mean_only_noise():
    """A noisy mean WITHOUT the log2-bucket shift stays quiet — the
    histogram confirmation the detector exists for."""
    base = {}
    for _ in range(health.WARMUP_FOLDS):
        health.detect_latency_drift(base, _hist(0.001), 100.0)
    for _ in range(6):
        assert health.detect_latency_drift(
            base, _hist(0.0025, bucket=10), 100.0) is None


def test_latency_drift_small_samples_ignored():
    base = {}
    for _ in range(health.WARMUP_FOLDS):
        health.detect_latency_drift(base, _hist(0.001), 100.0)
    assert health.detect_latency_drift(
        base, _hist(0.02, count=2, bucket=14), 100.0) is None


def test_latency_drift_adopts_new_normal():
    base = {}
    for _ in range(health.WARMUP_FOLDS):
        health.detect_latency_drift(base, _hist(0.001), 100.0)
    for _ in range(health.DRIFT_ADAPT_FOLDS):
        health.detect_latency_drift(base, _hist(0.004, bucket=12),
                                    100.0)
    # adopted: the sustained level is the new baseline, detector quiet
    assert health.detect_latency_drift(base, _hist(0.004, bucket=12),
                                       100.0) is None


def test_storm_quiet_on_single_recovery_round():
    base = {}
    assert health.detect_storm(base, 1) is None
    assert health.detect_storm(base, 0) is None


def test_storm_fires_on_sustained_events():
    base = {}
    hit = None
    for _ in range(4):
        hit = health.detect_storm(base, 2) or hit
    assert hit is not None and "storm" in hit[1]


def test_sink_drop_detector():
    base = {}
    assert health.detect_sink_drop(base, 0) is None
    hit = health.detect_sink_drop(base, 5)
    assert hit is not None and "dropping" in hit[1]


def test_backlog_fires_on_monotone_growth_only():
    base = {}
    for v in (1.0, 2.0, 3.0, 4.0):
        hit = health.detect_backlog(base, v)
    assert hit is not None
    base2 = {}
    for v in (1.0, 3.0, 2.0, 4.0, 1.0, 3.0, 2.0):   # oscillating
        assert health.detect_backlog(base2, v) is None


def test_hb_flap_detector():
    base = {}
    for _ in range(health.WARMUP_FOLDS + 1):
        assert health.detect_hb_flap(base, 0.1, 0.1) is None
    hit = health.detect_hb_flap(base, 1.0, 0.1)
    assert hit is not None and "flap" in hit[1]
    # the flap did not inflate the baseline out of detectability
    assert health.detect_hb_flap(base, 1.0, 0.1) is not None


# ----------------------------------------------------------------------
# engine: hysteresis + per-detector escalation on synthetic deltas
# ----------------------------------------------------------------------
def test_engine_clean_folds_zero_alerts():
    e = _engine()
    now = 0.0
    for seq in range(1, 25):
        assert _clean_round(e, seq, now) == []
        now += 0.1
    st = e.status()
    assert all(v["state"] == "HEALTHY" for v in st["ranks"].values())
    assert st["alerts_total"] == 0
    assert st["dominator"]["attributed"] == 24
    assert st["first_degraded"] is None


def test_engine_storm_escalates_one_level_per_fold():
    e = _engine()
    states = []
    for i in range(8):
        a = _beat(e, 0, 10, i * 0.1, stats_delta={
            "allreduce_array": {"retries": 3.0}})
        states += [x["to"] for x in a if x["kind"] == "state"]
    assert states[:2] == ["DEGRADED", "SUSPECT"]
    # storms cap at SUSPECT: no EVICT without the dominator contract
    assert "EVICT_RECOMMENDED" not in states
    assert e.status()["ranks"]["0"]["state"] == "SUSPECT"


def test_engine_hysteresis_prevents_flapping():
    """Alternating hit/clean folds must not bounce the state — and
    recovery needs CLEAR_FOLDS clean folds per level down."""
    e = _engine()
    now = 0.0
    transitions = []
    for i in range(12):
        payload = ({"stats_delta": {"a": {"retries": 3.0}}}
                   if i % 2 == 0 else {})
        a = _beat(e, 0, 10, now, **payload)
        transitions += [(x["from"], x["to"]) for x in a
                        if x["kind"] == "state"]
        now += 0.1
    # escalated but never stepped DOWN mid-flap (the hysteresis)
    code = {v: k for k, v in health.STATE_NAMES.items()}
    downs = [t for t in transitions if code[t[0]] > code[t[1]]]
    assert not downs, transitions
    state_mid = e.status()["ranks"]["0"]["state"]
    assert state_mid in ("DEGRADED", "SUSPECT")
    # sustained clean folds: one level down per CLEAR_FOLDS streak
    seen = []
    for i in range(12):
        a = _beat(e, 0, 10, now)
        seen += [x["to"] for x in a if x["kind"] == "state"]
        now += 0.1
    assert e.status()["ranks"]["0"]["state"] == "HEALTHY"
    if state_mid == "SUSPECT":
        assert seen == ["DEGRADED", "HEALTHY"]
    else:
        assert seen == ["HEALTHY"]


def test_engine_audit_divergence_forces_suspect():
    e = _engine()
    alerts = e.note_audit([{"seq": 9, "kind": "output",
                            "msg": "minority rank(s) [2]",
                            "ranks": [2]}], LIVE)
    [ev] = alerts
    assert ev["rank"] == 2 and ev["to"] == "SUSPECT"
    assert ev["detector"] == "audit"
    assert e.status()["ranks"]["2"]["state"] == "SUSPECT"


def test_engine_dominator_ladder_and_onset():
    """The online dominator: SUSPECT forced at half the streak, EVICT
    at the full streak, onset counted once, shares exported."""
    e = _engine()        # dominator_ordinals=8, window=16
    now = 0.0
    seq = 0
    for _ in range(20):                      # learn the baseline
        seq += 1
        assert _clean_round(e, seq, now) == []
        now += 0.1
    events = []
    for _ in range(12):
        seq += 1
        events += _slow_round(e, seq, now)
        now += 0.1
    states = [(x["to"], x["detector"]) for x in events
              if x["kind"] == "state"]
    assert ("SUSPECT", "dominator") in states
    assert ("EVICT_RECOMMENDED", "dominator") in states
    # SUSPECT arrived within dominator_ordinals slow ordinals
    st = e.status()
    assert st["ranks"]["3"]["state"] == "EVICT_RECOMMENDED"
    assert st["evict_recommended"] == [3]
    assert st["dominator"]["onsets"] == 1
    assert st["dominator"]["shares"]["3"] >= 0.5
    assert st["dominator"]["streak_rank"] == 3
    assert [x for x in events if x["kind"] == "onset"]
    assert st["first_degraded"]["rank"] == 3
    assert st["first_degraded"]["detector"] == "dominator"


def test_engine_fast_dominator_stays_quiet():
    """A topology-biased but FAST dominator (every ordinal at the
    baseline duration) must never escalate — dominance without
    slowness is not degradation."""
    e = _engine()
    now = 0.0
    for seq in range(1, 40):
        # rank 0 wins the blame vote every ordinal, at baseline speed
        for r in range(N):
            if r == 0:
                c = _cell(seq, 0.001, wire=0.00095)
            else:
                c = _cell(seq, 0.001, wire=0.0005,
                          links={0: {"secs": 0.0005,
                                     "transport": "tcp",
                                     "bytes": 1000}})
            assert _beat(e, r, seq, now, health_delta={
                "cells": [c]}) == []
        now += 0.1
    assert e.status()["alerts_total"] == 0


def test_engine_dead_and_replacement():
    e = _engine()
    [ev] = e.note_dead(2, "connection lost")
    assert ev["to"] == "DEAD" and ev["detector"] == "liveness"
    assert e.status()["ranks"]["2"]["state"] == "DEAD"
    # zombie beats after the declaration fold to nothing
    assert _beat(e, 2, 5, 1.0) == []
    [back] = e.note_replacement(2)
    assert back["from"] == "DEAD" and back["to"] == "HEALTHY"
    assert e.status()["ranks"]["2"]["state"] == "HEALTHY"
    # replacing an already-HEALTHY rank is silent
    assert e.note_replacement(1) == []


def test_engine_shrink_remaps_verdicts():
    e = _engine()
    for i in range(6):
        _beat(e, 3, 10, i * 0.1,
              stats_delta={"a": {"retries": 3.0}})
    assert e.status()["ranks"]["3"]["state"] == "SUSPECT"
    e.note_dead(2, "killed")
    e.note_shrink(3, {0: 0, 1: 1, 3: 2})
    st = e.status()
    assert st["ranks"]["2"]["state"] == "SUSPECT"   # old rank 3
    assert "3" not in st["ranks"]


def test_engine_disabled_is_inert():
    e = health.HealthEngine(N, enabled=False)
    assert e.fold(0, {"progress": {"seq": 1}}, 0.0, LIVE) == []
    assert e.note_dead(0, "x") == []
    assert e.status()["enabled"] is False


def test_engine_link_baselines_learned():
    e = _engine()
    now = 0.0
    for seq in range(1, 4):
        _beat(e, 0, seq, now, health_delta={"cells": [
            _cell(seq, 0.001, wire=0.0005,
                  links={1: {"secs": 0.001, "transport": "tcp",
                             "bytes": 1_000_000}})]})
        now += 0.1
    gbs = e.status()["ranks"]["0"]["links_gbs"]
    assert gbs["1"] == pytest.approx(1.0, rel=0.1)


# ----------------------------------------------------------------------
# rendering: Prometheus, live view, CLI formatters
# ----------------------------------------------------------------------
def _health_doc(st):
    return {"slave_num": N, "window_secs": 60.0, "hb_secs": 0.1,
            "ranks": {}, "cluster": {"stats": {}, "rates": {},
                                     "histograms": {}, "health": st}}


def _degraded_engine():
    e = _engine()
    now = 0.0
    seq = 0
    for _ in range(20):
        seq += 1
        _clean_round(e, seq, now)
        now += 0.1
    for _ in range(12):
        seq += 1
        _slow_round(e, seq, now)
        now += 0.1
    return e


def test_prometheus_health_series():
    st = _degraded_engine().status()
    text = metrics.to_prometheus(_health_doc(st))
    assert 'mp4j_rank_health_state{rank="3"} 3' in text
    assert 'mp4j_rank_health_state{rank="0"} 0' in text
    assert "# TYPE mp4j_evict_recommended gauge" in text
    assert "mp4j_evict_recommended 1" in text
    assert 'mp4j_alerts_total{rank="3",detector="dominator"}' in text
    assert "mp4j_straggler_onsets_total 1" in text
    dom_line = next(ln for ln in text.splitlines()
                    if ln.startswith('mp4j_critpath_dominator'
                                     '{rank="3"}'))
    assert float(dom_line.rsplit(" ", 1)[1]) >= 0.5
    # disabled plane: no health series at all (no zero-noise)
    off = metrics.to_prometheus(_health_doc(None))
    assert "mp4j_rank_health_state" not in off
    assert "mp4j_evict_recommended" not in off


def _live_doc(health_st=None, age=0.1):
    doc = {
        "slave_num": N, "window_secs": 60.0, "hb_secs": 0.5,
        "ranks": {
            str(r): {"progress": {"seq": 30, "current":
                                  "allreduce_array" if r == 1 else None,
                                  "last": "allreduce_array",
                                  "phase": "wire" if r == 1 else None,
                                  "current_secs": 1.2, "epoch": 1},
                     "age": age,
                     "stats": {"allreduce_array": {
                         "calls": 30, "bytes_sent": 1e8,
                         "bytes_recv": 1e8, "retries": 2,
                         "wire_bytes_tcp": 1e8, "wire_seconds": 1.0,
                         "reduce_seconds": 0.5,
                         "serialize_seconds": 0.1}},
                     "rates": {"bytes_per_sec": 123.45e6},
                     "counters": {"sink/bytes": 2.4e6},
                     "gauges": {}, "audit_seq": 30}
            for r in range(N)},
        "cluster": {"stats": {}, "rates": {"bytes_per_sec": 5e8,
                                           "collectives_per_sec": 10.0,
                                           "keys_per_sec": 0.0},
                    "histograms": {}, "health": health_st},
    }
    return doc


def test_live_view_health_column_and_width():
    st = _degraded_engine().status()
    frame = telemetry.format_live(_live_doc(st))
    lines = frame.splitlines()
    assert any("health:" in ln for ln in lines)      # head-line
    header = next(ln for ln in lines if "health" in ln and "rank" in ln)
    assert "health" in header
    row3 = next(ln for ln in lines if ln.lstrip(" *").startswith("3 "))
    assert "EVICT" in row3
    row0 = next(ln for ln in lines if ln.lstrip(" *").startswith("0 "))
    assert " ok " in row0 + " "
    # the whole frame stays within 120 columns (the live-view budget)
    for ln in lines:
        assert len(ln) <= 120, f"{len(ln)} cols: {ln!r}"


def test_live_view_without_health_plane():
    frame = telemetry.format_live(_live_doc(None))
    lines = frame.splitlines()
    header = next(ln for ln in lines if "health" in ln and "rank" in ln)
    off = header.index("health")
    row0 = next(ln for ln in lines if ln.lstrip(" *").startswith("0 "))
    # health column renders "-" when the master runs without the plane
    assert row0[off:off + 6].strip() == "-"
    assert "health:" not in frame     # no head-line


def test_live_view_stale_rank_rates_annotated():
    """A wedged rank's frozen rate window must not render as healthy
    throughput: columns older than 2x the heartbeat interval are
    annotated (ISSUE 12 satellite fix)."""
    doc = _live_doc(None)
    doc["ranks"]["2"]["age"] = 5.0    # 10x the 0.5 s heartbeat
    frame = telemetry.format_live(doc)
    row2 = next(ln for ln in frame.splitlines()
                if ln.lstrip(" *").startswith("2 "))
    assert "stale" in row2
    assert "123.45" not in row2
    row0 = next(ln for ln in frame.splitlines()
                if ln.lstrip(" *").startswith("0 "))
    assert "123.45" in row0           # fresh ranks keep real rates
    # heartbeats disabled (hb_secs 0) -> no stale marking possible
    doc["hb_secs"] = 0.0
    frame2 = telemetry.format_live(doc)
    row2b = next(ln for ln in frame2.splitlines()
                 if ln.lstrip(" *").startswith("2 "))
    assert "stale" not in row2b


def test_format_status_and_history():
    st = _degraded_engine().status()
    text = health.format_status(st)
    assert "EVICT RECOMMENDED: rank(s) 3" in text
    assert "first degradation: rank 3" in text
    alerts = st["last_alerts"]
    hist = health.format_history(alerts, [0, 1, 2, 3])
    assert "first degradation: rank 3" in hist
    assert "rank 3: EVICT_RECOMMENDED" in hist
    assert "rank 0: HEALTHY" in hist
    assert health.format_history([], [0]) .startswith("(no health")


# ----------------------------------------------------------------------
# alert plumbing: sink record kind + critpath/postmortem timeline
# ----------------------------------------------------------------------
def test_sink_drains_alert_log(tmp_path, fresh_spans):
    log = health.AlertLog()
    w = sink.SinkWriter(str(tmp_path), 0, slave_num=1, alerts=log,
                        budget_bytes=1 << 20, flush_secs=60.0)
    log.note({"id": 1, "wall": 123.0, "rank": 0, "detector": "storm",
              "kind": "state", "from": "HEALTHY", "to": "DEGRADED",
              "seq": 5, "msg": "m"})
    w.flush()
    w.close()
    doc = sink.read_rank(sink.rank_dir(str(tmp_path), 0))
    alerts = [rec for rec in doc["records"] if rec["t"] == "alerts"]
    assert alerts and alerts[0]["alerts"][0]["detector"] == "storm"
    # record-count accounting treats the batch by its alert count
    assert sink._record_count({"t": "alerts",
                               "alerts": [{}, {}, {}]}) == 3


def test_critpath_collects_and_dedups_alerts(tmp_path, fresh_spans):
    ev = {"id": 7, "wall": 50.0, "rank": 3, "detector": "dominator",
          "kind": "state", "from": "HEALTHY", "to": "SUSPECT",
          "seq": 9, "msg": "x"}
    for r in (0, 1):     # the same alert orphaned onto two ranks
        log = health.AlertLog()
        log.note(ev)
        w = sink.SinkWriter(str(tmp_path), r, slave_num=2, alerts=log,
                            budget_bytes=1 << 20, flush_secs=60.0)
        w.flush()
        w.close()
    analysis = critpath.analyze(sink.load_job(str(tmp_path)))
    assert len(analysis["health_alerts"]) == 1       # dedup by id
    report = critpath.format_report(analysis, str(tmp_path))
    assert "health timeline" in report
    assert "rank 3" in report and "SUSPECT" in report


def test_postmortem_manifest_health_timeline(tmp_path):
    st = _degraded_engine().status()
    postmortem.write_master_manifest(
        str(tmp_path), slave_num=N, reason="test fatal",
        table={}, departed={}, diagnosis=["d"], health=st)
    report = postmortem.merge_report(str(tmp_path))
    assert "health verdicts at abort time:" in report
    assert "rank 3: EVICT_RECOMMENDED" in report
    assert "first degradation was rank 3" in report
    assert "dominator" in report
    assert "EVICT was recommended for rank(s) 3" in report


def test_scope_health_cli_on_sink_dir(tmp_path, fresh_spans, capsys):
    log = health.AlertLog()
    log.note({"id": 1, "wall": 10.0, "rank": 2, "detector": "storm",
              "kind": "state", "from": "HEALTHY", "to": "DEGRADED",
              "seq": 3, "msg": "m"})
    w = sink.SinkWriter(str(tmp_path), 2, slave_num=3, alerts=log,
                        budget_bytes=1 << 20, flush_secs=60.0)
    w.flush()
    w.close()
    assert scope_main(["health", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "health timeline" in out
    assert "rank 2" in out and "DEGRADED" in out
    assert scope_main(["health", str(tmp_path), "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc[0]["detector"] == "storm"


# ----------------------------------------------------------------------
# acceptance: clean grid stays HEALTHY, slow rank reaches SUSPECT
# ----------------------------------------------------------------------
def _run_grid(rounds, tmp_dir=None, fault_plan=None, size=100_000,
              hold=None, on_degraded=None, master_kwargs=None,
              slave_kwargs=None, join=90.0):
    """Master + N slave threads running ``rounds`` allreduces; returns
    (master, errors). ``hold`` (an Event) delays close so the caller
    can interrogate the live master; ``on_degraded`` is polled."""
    from ytk_mp4j_tpu.comm.master import Master
    from ytk_mp4j_tpu.comm.process_comm import ProcessCommSlave

    master = Master(N, timeout=60.0,
                    **(master_kwargs or {})).serve_in_thread()
    errors = [None] * N

    def worker(i):
        slave = None
        try:
            kw = dict(slave_kwargs or {})
            if tmp_dir:
                kw["sink_dir"] = tmp_dir
            if fault_plan:
                kw["fault_plan"] = fault_plan
            slave = ProcessCommSlave("127.0.0.1", master.port,
                                     timeout=60.0, **kw)
            for _ in range(rounds):
                a = np.ones(size, np.float64)
                slave.allreduce_array(a, Operands.DOUBLE,
                                      Operators.SUM)
            if hold is not None:
                hold.wait(45.0)
            slave.close(0)
        except Exception as e:
            errors[slave.rank if slave is not None else i] = e
            if slave is not None:
                try:
                    slave.close(1)
                except Exception:
                    pass

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(N)]
    for t in threads:
        t.start()
    return master, errors, threads


def test_clean_grid_stays_healthy_zero_alerts(monkeypatch,
                                              fresh_spans, tmp_path):
    """Acceptance: the clean 4-rank property grid reports ZERO alerts
    and every rank ends HEALTHY — no false positives."""
    monkeypatch.setenv("MP4J_HEARTBEAT_SECS", "0.1")
    d = str(tmp_path / "trail")
    master, errors, threads = _run_grid(24, tmp_dir=d, size=20_000)
    for t in threads:
        t.join(90.0)
        assert not t.is_alive(), "rank hung"
    assert all(e is None for e in errors), errors
    master.join(15.0)
    assert master.final_code == 0
    st = master.health_status()
    assert st is not None
    assert all(v["state"] == "HEALTHY" for v in st["ranks"].values())
    assert st["alerts_total"] == 0
    assert st["dominator"]["onsets"] == 0
    assert st["dominator"]["attributed"] >= 20
    # zero alerts means zero durable alert records too
    analysis = critpath.analyze(sink.load_job(d))
    assert analysis["health_alerts"] == []


def test_chaos_slow_rank_reaches_suspect_within_bound(monkeypatch,
                                                      fresh_spans,
                                                      tmp_path,
                                                      capsys):
    """Acceptance: a fault-plan ``slow`` rank is flagged SUSPECT with
    the dominator detector named within MP4J_HEALTH_DOMINATOR_ORDINALS
    ordinals; ``Master.health_status()`` and ``/metrics`` agree; the
    alert lands in the durable sink."""
    monkeypatch.setenv("MP4J_HEARTBEAT_SECS", "0.1")
    monkeypatch.setenv("MP4J_HEALTH_DOMINATOR_ORDINALS", "12")
    monkeypatch.setenv("MP4J_HEALTH_WINDOW", "24")
    d = str(tmp_path / "trail")
    hold = threading.Event()
    # 20 clean ordinals learn the baseline, then 40 gated by rank 3's
    # 20 ms injected sleeps (20x the healthy ordinal on this host)
    master, errors, threads = _run_grid(
        60, tmp_dir=d, fault_plan="slow:rank=3:secs=0.02:nth=20",
        hold=hold, master_kwargs={"metrics_port": 0})
    try:
        deadline = time.monotonic() + 60.0
        st = None
        while time.monotonic() < deadline:
            st = master.health_status()
            s = (st or {}).get("ranks", {}).get("3", {}).get("state")
            if s in ("SUSPECT", "EVICT_RECOMMENDED"):
                break
            time.sleep(0.2)
        assert st is not None
        r3 = st["ranks"]["3"]
        assert r3["state"] in ("SUSPECT", "EVICT_RECOMMENDED"), st
        # the dominator detector is the named evidence
        assert ("dominator" in r3["alerts"]
                or "dominator" in r3["pressure"]), r3
        assert st["first_degraded"]["rank"] == 3
        assert st["first_degraded"]["detector"] == "dominator"
        # SUSPECT arrived within the configured ordinal bound of the
        # fault arming (nth=20): first_degraded names the ordinal
        assert st["first_degraded"]["seq"] <= 20 + 12 + 5
        # /metrics agrees with health_status()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{master.metrics_port}/metrics",
                timeout=5.0) as resp:
            text = resp.read().decode()
        code = {"SUSPECT": 2, "EVICT_RECOMMENDED": 3}[r3["state"]]
        # the state may escalate between the two reads — accept >=
        line = next(ln for ln in text.splitlines()
                    if ln.startswith('mp4j_rank_health_state{rank="3"'))
        assert int(line.rsplit(" ", 1)[1]) >= code - 1
        assert "mp4j_straggler_onsets_total" in text
        assert 'mp4j_critpath_dominator{rank="3"}' in text
        # the CLI's URL mode renders the live verdicts
        assert scope_main(
            ["health", f"http://127.0.0.1:{master.metrics_port}"]) == 0
        out = capsys.readouterr().out
        assert "rank" in out and ("SUSPECT" in out or "EVICT" in out)
    finally:
        hold.set()
    for t in threads:
        t.join(90.0)
        assert not t.is_alive(), "rank hung"
    assert all(e is None for e in errors), errors
    master.join(15.0)
    # the verdict survived into the durable sink
    analysis = critpath.analyze(sink.load_job(d))
    suspects = [ev for ev in analysis["health_alerts"]
                if ev.get("rank") == 3 and ev.get("kind") == "state"
                and ev.get("to") in ("SUSPECT", "EVICT_RECOMMENDED")]
    assert suspects, analysis["health_alerts"]
    assert any(ev.get("detector") == "dominator" for ev in suspects)
    report = critpath.format_report(analysis, d)
    assert "health timeline" in report
    assert scope_main(["health", d]) == 0


def test_chaos_degraded_then_fatal_postmortem_timeline(monkeypatch,
                                                       fresh_spans,
                                                       tmp_path):
    """A job that degrades and THEN dies: the postmortem manifest
    freezes the verdicts and the merged report renders the health
    timeline — what degraded first, when, which detector."""
    monkeypatch.setenv("MP4J_HEARTBEAT_SECS", "0.1")
    monkeypatch.setenv("MP4J_HEALTH_DOMINATOR_ORDINALS", "8")
    monkeypatch.setenv("MP4J_HEALTH_WINDOW", "16")
    pmdir = str(tmp_path / "pm")
    monkeypatch.setenv("MP4J_POSTMORTEM_DIR", pmdir)
    d = str(tmp_path / "trail")
    master, errors, threads = _run_grid(
        60, tmp_dir=d,
        fault_plan="slow:rank=3:secs=0.02:nth=18; kill:rank=2:nth=50",
        slave_kwargs={"dead_rank_secs": 20.0})
    for t in threads:
        t.join(90.0)
        assert not t.is_alive(), "rank hung"
    master.join(20.0)
    survivors = [r for r in range(N) if r != 2]
    assert all(isinstance(errors[r], (Mp4jError, Mp4jFatalError))
               for r in survivors), errors
    report = postmortem.merge_report(pmdir)
    assert "health verdicts at abort time:" in report
    assert "first degradation was rank 3" in report
    assert "dominator" in report
