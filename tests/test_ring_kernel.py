"""Pallas RDMA ring kernels (ops/ring_kernel.py): interpret-mode
differential tests on multi-device CPU meshes, plus a host-side
property model of the credit-backpressure protocol (the compiled-path
logic the interpreter cannot execute — remote semaphores don't exist
there; see the module docstring)."""

from functools import partial

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.operators import Operators
from ytk_mp4j_tpu.ops.ring_kernel import (ring_allgather_kernel,
                                          ring_allreduce_kernel,
                                          ring_reduce_scatter_kernel)
from ytk_mp4j_tpu.parallel import make_mesh

OPS = {"SUM": np.sum, "MAX": np.max, "MIN": np.min, "PROD": np.prod}


def _allreduce(n, data, op=Operators.SUM):
    mesh = make_mesh(n)

    # the pallas interpret path is not vma-aware (see
    # gbdt.build_histograms); check_vma off for the wrapper
    @partial(jax.shard_map, mesh=mesh, in_specs=P("mp4j"),
             out_specs=P("mp4j"), check_vma=False)
    def f(x):
        return ring_allreduce_kernel(x[0], op, "mp4j",
                                     interpret=True)[None]

    return np.asarray(jax.jit(f)(jnp.asarray(data)))


@pytest.mark.parametrize("n", [2, 4, 8])
@pytest.mark.parametrize("op_name", sorted(OPS))
def test_allreduce_matches(rng, n, op_name):
    L = 4 * n
    data = rng.standard_normal((n, L)).astype(np.float32)
    out = _allreduce(n, data, Operators.by_name(op_name))
    want = OPS[op_name](data, axis=0)
    for r in range(n):
        np.testing.assert_allclose(out[r], want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("L", [1, 7, 13])
def test_allreduce_any_length(rng, L):
    """Arbitrary L: identity padding inside the kernel wrapper."""
    n = 4
    data = rng.standard_normal((n, L)).astype(np.float32)
    out = _allreduce(n, data)
    want = data.sum(0)
    for r in range(n):
        np.testing.assert_allclose(out[r], want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("n", [2, 4])
def test_reduce_scatter_chunk_layout(rng, n):
    """Member r ends with chunk r — the coll.reduce_scatter contract."""
    L = 6 * n
    data = rng.standard_normal((n, L)).astype(np.float32)
    mesh = make_mesh(n)

    @partial(jax.shard_map, mesh=mesh, in_specs=P("mp4j"),
             out_specs=P("mp4j"), check_vma=False)
    def f(x):
        return ring_reduce_scatter_kernel(x[0], Operators.SUM, "mp4j",
                                          interpret=True)[None]

    out = np.asarray(jax.jit(f)(jnp.asarray(data)))   # [n, L/n]
    np.testing.assert_allclose(out, data.sum(0).reshape(n, -1),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("n", [2, 4, 8])
def test_allgather_block_layout(rng, n):
    c = 5
    data = rng.standard_normal((n, c)).astype(np.float32)
    mesh = make_mesh(n)

    @partial(jax.shard_map, mesh=mesh, in_specs=P("mp4j"),
             out_specs=P(None, None), check_vma=False)
    def f(x):
        return ring_allgather_kernel(x[0], "mp4j",
                                     interpret=True).reshape(n, c)

    out = np.asarray(jax.jit(f)(jnp.asarray(data)))
    np.testing.assert_allclose(out, data)


def test_single_member_noop(rng):
    data = rng.standard_normal((1, 8)).astype(np.float32)
    out = _allreduce(1, data)
    np.testing.assert_array_equal(out, data)


def test_reduce_scatter_rejects_indivisible(rng):
    mesh = make_mesh(4)

    @partial(jax.shard_map, mesh=mesh, in_specs=P("mp4j"),
             out_specs=P("mp4j"), check_vma=False)
    def f(x):
        return ring_reduce_scatter_kernel(x[0], Operators.SUM, "mp4j",
                                          interpret=True)[None]

    with pytest.raises(Mp4jError):
        jax.jit(f)(np.ones((4, 7), np.float32))



@pytest.mark.parametrize("n", [2, 4, 8])
@pytest.mark.parametrize("L", [7, 32])
@pytest.mark.parametrize("op_name", ["SUM", "MAX"])
def test_bidirectional_allreduce(rng, n, L, op_name):
    """The bidirectional ring (both halves in opposite directions)
    must agree with the unidirectional one and the oracle."""
    data = rng.standard_normal((n, L)).astype(np.float32)
    mesh = make_mesh(n)
    op = Operators.by_name(op_name)

    @partial(jax.shard_map, mesh=mesh, in_specs=P("mp4j"),
             out_specs=P("mp4j"), check_vma=False)
    def f(x):
        return ring_allreduce_kernel(x[0], op, "mp4j", interpret=True,
                                     bidirectional=True)[None]

    out = np.asarray(jax.jit(f)(jnp.asarray(data)))
    want = OPS[op_name](data, axis=0)
    for r in range(n):
        np.testing.assert_allclose(out[r], want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("n", [2, 4, 8])
def test_bidirectional_reduce_scatter_and_allgather(rng, n):
    """Chunk-halved bidirectional RS/AG match the unidirectional chunk
    layouts exactly."""
    mesh = make_mesh(n)
    L = 6 * n
    data = rng.standard_normal((n, L)).astype(np.float32)

    @partial(jax.shard_map, mesh=mesh, in_specs=P("mp4j"),
             out_specs=P("mp4j"), check_vma=False)
    def frs(x):
        return ring_reduce_scatter_kernel(
            x[0], Operators.SUM, "mp4j", interpret=True,
            bidirectional=True)[None]

    np.testing.assert_allclose(
        np.asarray(jax.jit(frs)(jnp.asarray(data))),
        data.sum(0).reshape(n, -1), rtol=1e-5, atol=1e-6)

    shards = rng.standard_normal((n, 6)).astype(np.float32)

    @partial(jax.shard_map, mesh=mesh, in_specs=P("mp4j"),
             out_specs=P(None, None), check_vma=False)
    def fag(x):
        return ring_allgather_kernel(
            x[0], "mp4j", interpret=True,
            bidirectional=True).reshape(n, 6)

    np.testing.assert_allclose(
        np.asarray(jax.jit(fag)(jnp.asarray(shards))), shards)


def test_bidirectional_odd_chunk_rejected():
    mesh = make_mesh(4)

    @partial(jax.shard_map, mesh=mesh, in_specs=P("mp4j"),
             out_specs=P("mp4j"), check_vma=False)
    def f(x):
        return ring_reduce_scatter_kernel(
            x[0], Operators.SUM, "mp4j", interpret=True,
            bidirectional=True)[None]

    with pytest.raises(Mp4jError):
        jax.jit(f)(np.ones((4, 20), np.float32))   # chunks of 5: odd


# ----------------------------------------------------------------------
# Host-side model of the compiled-path credit protocol.
#
# The slot-reuse race the credits guard is exactly what interpret mode
# cannot surface (members run serially there), so the protocol is
# verified against this discrete-event model instead: every member runs
# the same begin/finish sequence as the kernels' shared _direction
# protocol — over ONE direction (the unidirectional kernels) or BOTH
# interleaved (begin R, begin L, finish R, finish L — the bidirectional
# kernels) — while a scheduler interleaves members and DMA deliveries
# ADVERSARIALLY (including stalling one victim member as long as
# possible). The model checks
#   (a) no DMA delivery ever overwrites an unconsumed receive slot,
#   (b) every semaphore drains to zero at exit,
#   (c) the collective's result is correct on every member.
# Without credits the same adversarial scheduler DOES produce the
# overwrite (the final test) — proof the guard is load-bearing, not
# decorative.
# ----------------------------------------------------------------------
class _RingModel:
    """Direction-parameterized model: ``dirs=("R",)`` is the
    unidirectional kernel, ``dirs=("R", "L")`` the bidirectional one.
    Direction sign: R sends right/walks chunks downward, L mirrored."""

    SGN = {"R": -1, "L": +1}

    def __init__(self, n, use_credits, seed=0, victim=None,
                 mode="allreduce", dirs=("R",)):
        self.n = n
        self.use_credits = use_credits
        self.mode = mode
        self.dirs = dirs
        self.rng = np.random.default_rng(seed)
        self.victim = victim          # member to stall when possible
        z = lambda: [[0, 0] for _ in range(n)]        # noqa: E731
        self.credit = {d: z() for d in dirs}
        self.send_sem = {d: z() for d in dirs}
        self.recv_sem = {d: z() for d in dirs}
        # rbuf[d][r][slot] = (value, unconsumed)
        self.rbuf = {d: [[(None, False), (None, False)]
                         for _ in range(n)] for d in dirs}
        self.pending = []    # in-flight DMAs: (dir, src, slot, value)
        self.violations = 0
        self.out = [None] * n

    # --- the member program: mirrors the kernels' mode logic ---------
    def _member(self, me, chunks):
        """``chunks``: {dir: list of n per-chunk values}."""
        n = self.n

        def begin(d, g, value):
            slot = g % 2
            if self.use_credits and g >= 2:
                yield ("wait_credit", d, slot)
            yield ("send", d, slot, value)

        def finish(d, g):
            slot = g % 2
            yield ("wait_send", d, slot)
            yield ("wait_recv", d, slot)
            got = yield ("consume", d, slot)
            if self.use_credits:
                yield ("signal_credit", d, slot)
            return got

        def exchange(g, vals):
            """All directions' begins, then all finishes — the
            kernels' interleaving order."""
            for d in self.dirs:
                yield from begin(d, g, vals[d])
            got = {}
            for d in self.dirs:
                got[d] = yield from finish(d, g)
            return got

        # reduce-scatter lands chunk me in every direction via
        # direction-mirrored shifts; other modes use the natural layout
        shift = {d: self.SGN[d] if self.mode == "reduce_scatter" else 0
                 for d in self.dirs}

        def sel(d, j):
            return chunks[d][(j + shift[d]) % n]

        out = {d: [None] * n for d in self.dirs}
        steps = 0
        if self.mode in ("allreduce", "reduce_scatter"):
            acc = {d: sel(d, me) for d in self.dirs}
            for s in range(n - 1):
                got = yield from exchange(steps, acc)
                acc = {d: got[d] + sel(d, me + self.SGN[d] * (s + 1))
                       for d in self.dirs}
                steps += 1
            if self.mode == "reduce_scatter":
                result = {d: acc[d] for d in self.dirs}
            else:
                cur = dict(acc)
                for d in self.dirs:   # finishing chunk, mirrored
                    out[d][(me - self.SGN[d]) % n] = acc[d]
                for s in range(n - 1):
                    cur = yield from exchange(steps, cur)
                    for d in self.dirs:
                        out[d][(me + self.SGN[d] * s) % n] = cur[d]
                    steps += 1
                result = out
        else:                                    # allgather
            for d in self.dirs:
                out[d][me] = chunks[d][0]
            cur = {d: chunks[d][0] for d in self.dirs}
            for s in range(n - 1):
                cur = yield from exchange(steps, cur)
                for d in self.dirs:
                    out[d][(me + self.SGN[d] * (s + 1)) % n] = cur[d]
                steps += 1
            result = out
        if self.use_credits:
            for slot in range(min(2, steps)):
                for d in self.dirs:
                    yield ("wait_credit", d, slot)
        self.out[me] = result

    # --- the scheduler -----------------------------------------------
    def _runnable(self, r, a):
        kind, d, slot = a[0], a[1], a[2]
        if kind == "wait_credit":
            return self.credit[d][r][slot] >= 1
        if kind == "wait_send":
            return self.send_sem[d][r][slot] >= 1
        if kind == "wait_recv":
            return self.recv_sem[d][r][slot] >= 1
        return True                   # send / consume / signal_credit

    def _apply(self, r, a):
        """Execute one runnable action; returns the value to send into
        the generator (consume) or None."""
        kind, d, slot = a[0], a[1], a[2]
        if kind == "wait_credit":
            self.credit[d][r][slot] -= 1
        elif kind == "wait_send":
            self.send_sem[d][r][slot] -= 1
        elif kind == "wait_recv":
            self.recv_sem[d][r][slot] -= 1
        elif kind == "send":
            # sbuf integrity: the previous outbound on this slot must
            # have drained (send_sem wait at its step) — model-checked
            assert not any(dd == d and s == r and sl == slot
                           for dd, s, sl, _ in self.pending), \
                "sbuf overwritten with DMA in flight"
            self.pending.append((d, r, slot, a[3]))
        elif kind == "consume":
            value, unconsumed = self.rbuf[d][r][slot]
            if not unconsumed:
                # stale re-read: the slot's fresh value was consumed
                # already — the paired overwrite was counted when the
                # extra delivery landed; the broken run reads garbage
                self.violations += 1
            self.rbuf[d][r][slot] = (value, False)
            return value
        elif kind == "signal_credit":
            # credit the upstream sender whose copy we just consumed
            up = (r + self.SGN[d]) % self.n
            self.credit[d][up][slot] += 1
        return None

    def _deliver(self, i):
        d, src, slot, value = self.pending.pop(i)
        dst = (src - self.SGN[d]) % self.n
        if self.rbuf[d][dst][slot][1]:   # unconsumed data overwritten!
            self.violations += 1
        self.rbuf[d][dst][slot] = (value, True)
        self.recv_sem[d][dst][slot] += 1
        self.send_sem[d][src][slot] += 1

    def run(self, data):
        """``data``: {dir: [n, n] array} — member r's chunk j of
        direction d at data[d][r, j]."""
        n = self.n
        gens = [self._member(r, {d: list(data[d][r]) for d in self.dirs})
                for r in range(n)]
        actions = [g.send(None) for g in gens]
        done = [False] * n
        while not all(done):
            # candidate moves: deliveries (any in-flight DMA) and
            # runnable member actions
            moves = [("dma", i) for i in range(len(self.pending))]
            moves += [("mem", r) for r in range(n)
                      if not done[r] and self._runnable(r, actions[r])]
            assert moves, "deadlock: no runnable member, no DMA in flight"
            # adversarial preference: stall the victim while anything
            # else can move
            if self.victim is not None:
                non_victim = [m for m in moves
                              if m != ("mem", self.victim)]
                if non_victim:
                    moves = non_victim
            kind, i = moves[self.rng.integers(len(moves))]
            if kind == "dma":
                self._deliver(i)
                continue
            ret = self._apply(i, actions[i])
            try:
                actions[i] = gens[i].send(ret)
            except StopIteration:
                done[i] = True
        return self

    def assert_clean(self):
        assert self.violations == 0
        assert not self.pending
        for d in self.dirs:
            assert all(c == [0, 0] for c in self.credit[d])
            assert all(s == [0, 0] for s in self.send_sem[d])
            assert all(s == [0, 0] for s in self.recv_sem[d])


def _model_wants(mode, data, dirs):
    """Expected per-member result of the modeled collective."""
    def one(d):
        sums = data[d].sum(0)
        if mode == "reduce_scatter":
            return {r: sums[r] for r in range(data[d].shape[0])}
        if mode == "allgather":
            return list(data[d][:, 0])
        return list(sums)
    return {d: one(d) for d in dirs}


@pytest.mark.parametrize("dirs", [("R",), ("R", "L")],
                         ids=["unidir", "bidir"])
@pytest.mark.parametrize("n", [2, 3, 4, 8])
@pytest.mark.parametrize("seed", range(3))
@pytest.mark.parametrize("mode",
                         ["allreduce", "reduce_scatter", "allgather"])
def test_credit_protocol_safe_under_any_schedule(n, seed, mode, dirs):
    """With credits: no receive-slot overwrite, semaphores drain to
    zero, results correct — for random and victim-stalling schedules,
    in every kernel mode and both directionalities (each has its own
    step count, drain, and — bidirectionally — interleaving seams)."""
    rng = np.random.default_rng(seed)
    data = {d: rng.standard_normal((n, n)).astype(np.float64)
            for d in dirs}
    for victim in [None, 0, n - 1]:
        m = _RingModel(n, use_credits=True, seed=seed, victim=victim,
                       mode=mode, dirs=dirs)
        m.run(data)
        m.assert_clean()
        want = _model_wants(mode, data, dirs)
        for r in range(n):
            res = m.out[r]
            for d in dirs:
                if mode == "reduce_scatter":
                    np.testing.assert_allclose(res[d], want[d][r],
                                               rtol=1e-12)
                else:
                    np.testing.assert_allclose(res[d], want[d],
                                               rtol=1e-12)


def test_without_credits_adversary_overwrites_slot():
    """The race is REAL: stalling one member while its upstream runs
    free overwrites an unconsumed receive slot once the double buffer
    wraps — the credits exist to prevent exactly this."""
    n = 4
    rng = np.random.default_rng(0)
    data = {"R": rng.standard_normal((n, n)).astype(np.float64)}
    hits = 0
    for victim in range(n):
        m = _RingModel(n, use_credits=False, seed=1, victim=victim)
        m.run(data)
        hits += m.violations
    assert hits > 0
