"""Pallas RDMA ring kernels (ops/ring_kernel.py): interpret-mode
differential tests on multi-device CPU meshes, plus a host-side
property model of the credit-backpressure protocol (the compiled-path
logic the interpreter cannot execute — remote semaphores don't exist
there; see the module docstring)."""

from functools import partial

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.operators import Operators
from ytk_mp4j_tpu.ops.ring_kernel import (ring_allgather_kernel,
                                          ring_allreduce_kernel,
                                          ring_reduce_scatter_kernel)
from ytk_mp4j_tpu.parallel import make_mesh

OPS = {"SUM": np.sum, "MAX": np.max, "MIN": np.min, "PROD": np.prod}


def _allreduce(n, data, op=Operators.SUM):
    mesh = make_mesh(n)

    # the pallas interpret path is not vma-aware (see
    # gbdt.build_histograms); check_vma off for the wrapper
    @partial(jax.shard_map, mesh=mesh, in_specs=P("mp4j"),
             out_specs=P("mp4j"), check_vma=False)
    def f(x):
        return ring_allreduce_kernel(x[0], op, "mp4j",
                                     interpret=True)[None]

    return np.asarray(jax.jit(f)(jnp.asarray(data)))


@pytest.mark.parametrize("n", [2, 4, 8])
@pytest.mark.parametrize("op_name", sorted(OPS))
def test_allreduce_matches(rng, n, op_name):
    L = 4 * n
    data = rng.standard_normal((n, L)).astype(np.float32)
    out = _allreduce(n, data, Operators.by_name(op_name))
    want = OPS[op_name](data, axis=0)
    for r in range(n):
        np.testing.assert_allclose(out[r], want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("L", [1, 7, 13])
def test_allreduce_any_length(rng, L):
    """Arbitrary L: identity padding inside the kernel wrapper."""
    n = 4
    data = rng.standard_normal((n, L)).astype(np.float32)
    out = _allreduce(n, data)
    want = data.sum(0)
    for r in range(n):
        np.testing.assert_allclose(out[r], want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("n", [2, 4])
def test_reduce_scatter_chunk_layout(rng, n):
    """Member r ends with chunk r — the coll.reduce_scatter contract."""
    L = 6 * n
    data = rng.standard_normal((n, L)).astype(np.float32)
    mesh = make_mesh(n)

    @partial(jax.shard_map, mesh=mesh, in_specs=P("mp4j"),
             out_specs=P("mp4j"), check_vma=False)
    def f(x):
        return ring_reduce_scatter_kernel(x[0], Operators.SUM, "mp4j",
                                          interpret=True)[None]

    out = np.asarray(jax.jit(f)(jnp.asarray(data)))   # [n, L/n]
    np.testing.assert_allclose(out, data.sum(0).reshape(n, -1),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("n", [2, 4, 8])
def test_allgather_block_layout(rng, n):
    c = 5
    data = rng.standard_normal((n, c)).astype(np.float32)
    mesh = make_mesh(n)

    @partial(jax.shard_map, mesh=mesh, in_specs=P("mp4j"),
             out_specs=P(None, None), check_vma=False)
    def f(x):
        return ring_allgather_kernel(x[0], "mp4j",
                                     interpret=True).reshape(n, c)

    out = np.asarray(jax.jit(f)(jnp.asarray(data)))
    np.testing.assert_allclose(out, data)


def test_single_member_noop(rng):
    data = rng.standard_normal((1, 8)).astype(np.float32)
    out = _allreduce(1, data)
    np.testing.assert_array_equal(out, data)


def test_reduce_scatter_rejects_indivisible(rng):
    mesh = make_mesh(4)

    @partial(jax.shard_map, mesh=mesh, in_specs=P("mp4j"),
             out_specs=P("mp4j"), check_vma=False)
    def f(x):
        return ring_reduce_scatter_kernel(x[0], Operators.SUM, "mp4j",
                                          interpret=True)[None]

    with pytest.raises(Mp4jError):
        jax.jit(f)(np.ones((4, 7), np.float32))


# ----------------------------------------------------------------------
# Host-side model of the compiled-path credit protocol.
#
# The slot-reuse race the credits guard is exactly what interpret mode
# cannot surface (members run serially there), so the protocol is
# verified against this discrete-event model instead: every member runs
# the same exchange() sequence as the kernel, a scheduler interleaves
# members and DMA deliveries ADVERSARIALLY (including stalling one
# victim member as long as possible), and the model checks
#   (a) no DMA delivery ever overwrites an unconsumed receive slot,
#   (b) every semaphore drains to zero at exit,
#   (c) the allreduce result is correct on every member.
# Without credits the same adversarial scheduler DOES produce the
# overwrite (the final test) — proof the guard is load-bearing, not
# decorative.
# ----------------------------------------------------------------------
class _RingModel:
    def __init__(self, n, use_credits, seed=0, victim=None,
                 mode="allreduce"):
        self.n = n
        self.use_credits = use_credits
        self.mode = mode
        self.rng = np.random.default_rng(seed)
        self.victim = victim          # member to stall when possible
        self.credit = [[0, 0] for _ in range(n)]
        self.send_sem = [[0, 0] for _ in range(n)]
        self.recv_sem = [[0, 0] for _ in range(n)]
        # rbuf[r][slot] = (value, unconsumed)
        self.rbuf = [[(None, False), (None, False)] for _ in range(n)]
        self.pending = []             # in-flight DMAs: (src, slot, value)
        self.violations = 0
        self.out = [None] * n

    # --- the member program: mirrors _ring_kernel's three modes ---
    def _member(self, me, chunks):
        n = self.n

        def exchange(g, value):
            slot = g % 2
            if self.use_credits and g >= 2:
                yield ("wait_credit", slot)
            yield ("send", slot, value)
            yield ("wait_send", slot)
            yield ("wait_recv", slot)
            got = yield ("consume", slot)
            if self.use_credits:
                yield ("signal_credit", slot)
            return got

        shift = -1 if self.mode == "reduce_scatter" else 0

        def sel(j):
            return chunks[(j + shift) % n]

        steps = 0
        if self.mode in ("allreduce", "reduce_scatter"):
            out = [None] * n
            acc = sel(me)
            for s in range(n - 1):
                acc = (yield from exchange(steps, acc)) + sel(me - s - 1)
                steps += 1
            if self.mode == "reduce_scatter":
                result = acc                     # chunk me, reduced
            else:
                out[(me + 1) % n] = acc
                cur = acc
                for s in range(n - 1):
                    cur = yield from exchange(steps, cur)
                    out[(me - s) % n] = cur
                    steps += 1
                result = out
        else:                                    # allgather
            out = [None] * n
            out[me] = chunks[0]
            cur = chunks[0]
            for s in range(n - 1):
                cur = yield from exchange(steps, cur)
                out[(me - s - 1) % n] = cur
                steps += 1
            result = out
        if self.use_credits:
            for slot in range(min(2, steps)):
                yield ("wait_credit", slot)
        self.out[me] = result

    def _runnable(self, r, action):
        kind = action[0]
        slot = action[1]
        if kind == "wait_credit":
            return self.credit[r][slot] >= 1
        if kind == "wait_send":
            return self.send_sem[r][slot] >= 1
        if kind == "wait_recv":
            return self.recv_sem[r][slot] >= 1
        return True                   # send / consume / signal_credit

    def _apply(self, r, gen, action):
        """Execute one runnable action; returns the value to send into
        the generator (consume) or None."""
        kind, slot = action[0], action[1]
        if kind == "wait_credit":
            self.credit[r][slot] -= 1
        elif kind == "wait_send":
            self.send_sem[r][slot] -= 1
        elif kind == "wait_recv":
            self.recv_sem[r][slot] -= 1
        elif kind == "send":
            # sbuf integrity: the previous outbound on this slot must
            # have drained (send_sem wait at its step) — model-checked
            assert not any(s == r and sl == slot
                           for s, sl, _ in self.pending), \
                "sbuf overwritten with DMA in flight"
            self.pending.append((r, slot, action[2]))
        elif kind == "consume":
            value, unconsumed = self.rbuf[r][slot]
            if not unconsumed:
                # stale re-read: the slot's fresh value was consumed
                # already — the paired overwrite was counted when the
                # extra delivery landed; the broken run reads garbage
                self.violations += 1
            self.rbuf[r][slot] = (value, False)
            return value
        elif kind == "signal_credit":
            self.credit[(r - 1) % self.n][slot] += 1
        return None

    def _deliver(self, i):
        src, slot, value = self.pending.pop(i)
        dst = (src + 1) % self.n
        if self.rbuf[dst][slot][1]:   # unconsumed data overwritten!
            self.violations += 1
        self.rbuf[dst][slot] = (value, True)
        self.recv_sem[dst][slot] += 1
        self.send_sem[src][slot] += 1

    def run(self, data):
        """data: [n, n] — member r's chunk j at data[r, j]."""
        n = self.n
        gens = [self._member(r, list(data[r])) for r in range(n)]
        actions = [g.send(None) for g in gens]
        done = [False] * n
        while not all(done):
            # candidate moves: deliveries (any in-flight DMA) and
            # runnable member actions
            moves = [("dma", i) for i in range(len(self.pending))]
            moves += [("mem", r) for r in range(n)
                      if not done[r] and self._runnable(r, actions[r])]
            assert moves, "deadlock: no runnable member, no DMA in flight"
            # adversarial preference: stall the victim while anything
            # else can move
            if self.victim is not None:
                non_victim = [m for m in moves
                              if m != ("mem", self.victim)]
                if non_victim:
                    moves = non_victim
            kind, i = moves[self.rng.integers(len(moves))]
            if kind == "dma":
                self._deliver(i)
                continue
            r = i
            ret = self._apply(r, gens[r], actions[r])
            try:
                actions[r] = gens[r].send(ret)
            except StopIteration:
                done[r] = True
        return self


@pytest.mark.parametrize("n", [2, 3, 4, 8])
@pytest.mark.parametrize("seed", range(5))
@pytest.mark.parametrize("mode",
                         ["allreduce", "reduce_scatter", "allgather"])
def test_credit_protocol_safe_under_any_schedule(n, seed, mode):
    """With credits: no receive-slot overwrite, semaphores drain to
    zero, results correct — for random and victim-stalling schedules,
    in every kernel mode (each has its own step count and drain)."""
    rng = np.random.default_rng(seed)
    data = rng.standard_normal((n, n)).astype(np.float64)
    for victim in [None, 0, n - 1]:
        m = _RingModel(n, use_credits=True, seed=seed, victim=victim,
                       mode=mode)
        m.run(data)
        assert m.violations == 0
        assert not m.pending
        assert all(c == [0, 0] for c in m.credit), m.credit
        assert all(s == [0, 0] for s in m.send_sem)
        assert all(s == [0, 0] for s in m.recv_sem)
        if mode == "allreduce":
            want = data.sum(0)
            for r in range(n):
                np.testing.assert_allclose(m.out[r], want, rtol=1e-12)
        elif mode == "reduce_scatter":
            for r in range(n):       # member r ends with chunk r
                np.testing.assert_allclose(m.out[r], data[:, r].sum(),
                                           rtol=1e-12)
        else:                        # member q's shard at slot q
            for r in range(n):
                np.testing.assert_allclose(m.out[r], data[:, 0],
                                           rtol=1e-12)


def test_without_credits_adversary_overwrites_slot():
    """The race is REAL: stalling one member while its upstream runs
    free overwrites an unconsumed receive slot once the double buffer
    wraps — the credits exist to prevent exactly this."""
    n = 4
    rng = np.random.default_rng(0)
    data = rng.standard_normal((n, n)).astype(np.float64)
    hits = 0
    for victim in range(n):
        m = _RingModel(n, use_credits=False, seed=1, victim=victim)
        m.run(data)
        hits += m.violations
    assert hits > 0


@pytest.mark.parametrize("n", [2, 4, 8])
@pytest.mark.parametrize("L", [7, 32])
@pytest.mark.parametrize("op_name", ["SUM", "MAX"])
def test_bidirectional_allreduce(rng, n, L, op_name):
    """The bidirectional ring (both halves in opposite directions)
    must agree with the unidirectional one and the oracle."""
    data = rng.standard_normal((n, L)).astype(np.float32)
    mesh = make_mesh(n)
    op = Operators.by_name(op_name)

    @partial(jax.shard_map, mesh=mesh, in_specs=P("mp4j"),
             out_specs=P("mp4j"), check_vma=False)
    def f(x):
        return ring_allreduce_kernel(x[0], op, "mp4j", interpret=True,
                                     bidirectional=True)[None]

    out = np.asarray(jax.jit(f)(jnp.asarray(data)))
    want = OPS[op_name](data, axis=0)
    for r in range(n):
        np.testing.assert_allclose(out[r], want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("n", [2, 4, 8])
def test_bidirectional_reduce_scatter_and_allgather(rng, n):
    """Chunk-halved bidirectional RS/AG match the unidirectional chunk
    layouts exactly."""
    mesh = make_mesh(n)
    L = 6 * n
    data = rng.standard_normal((n, L)).astype(np.float32)

    @partial(jax.shard_map, mesh=mesh, in_specs=P("mp4j"),
             out_specs=P("mp4j"), check_vma=False)
    def frs(x):
        return ring_reduce_scatter_kernel(
            x[0], Operators.SUM, "mp4j", interpret=True,
            bidirectional=True)[None]

    np.testing.assert_allclose(
        np.asarray(jax.jit(frs)(jnp.asarray(data))),
        data.sum(0).reshape(n, -1), rtol=1e-5, atol=1e-6)

    shards = rng.standard_normal((n, 6)).astype(np.float32)

    @partial(jax.shard_map, mesh=mesh, in_specs=P("mp4j"),
             out_specs=P(None, None), check_vma=False)
    def fag(x):
        return ring_allgather_kernel(
            x[0], "mp4j", interpret=True,
            bidirectional=True).reshape(n, 6)

    np.testing.assert_allclose(
        np.asarray(jax.jit(fag)(jnp.asarray(shards))), shards)


def test_bidirectional_odd_chunk_rejected():
    mesh = make_mesh(4)

    @partial(jax.shard_map, mesh=mesh, in_specs=P("mp4j"),
             out_specs=P("mp4j"), check_vma=False)
    def f(x):
        return ring_reduce_scatter_kernel(
            x[0], Operators.SUM, "mp4j", interpret=True,
            bidirectional=True)[None]

    with pytest.raises(Mp4jError):
        jax.jit(f)(np.ones((4, 20), np.float32))   # chunks of 5: odd
