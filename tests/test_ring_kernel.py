"""Pallas RDMA ring allreduce (ops/ring_kernel.py): interpret-mode
differential tests on multi-device CPU meshes."""

from functools import partial

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.ops.ring_kernel import ring_allreduce_kernel
from ytk_mp4j_tpu.parallel import make_mesh


def _run(n, data):
    mesh = make_mesh(n)

    # the pallas interpret path is not vma-aware (see
    # gbdt.build_histograms); check_vma off for the wrapper
    @partial(jax.shard_map, mesh=mesh, in_specs=P("mp4j"),
             out_specs=P("mp4j"), check_vma=False)
    def f(x):
        return ring_allreduce_kernel(x[0], "mp4j", interpret=True)[None]

    return np.asarray(jax.jit(f)(jnp.asarray(data)))


@pytest.mark.parametrize("n", [2, 4, 8])
def test_matches_sum(rng, n):
    L = 4 * n
    data = rng.standard_normal((n, L)).astype(np.float32)
    out = _run(n, data)
    want = data.sum(0)
    for r in range(n):
        np.testing.assert_allclose(out[r], want, rtol=1e-5, atol=1e-6)


def test_single_member_noop(rng):
    data = rng.standard_normal((1, 8)).astype(np.float32)
    out = _run(1, data)
    np.testing.assert_array_equal(out, data)


def test_rejects_indivisible(rng):
    with pytest.raises(Mp4jError):
        _run(4, np.ones((4, 7), np.float32))
