"""Property-based + determinism tests (SURVEY.md section 5: the rebuild
replaces the reference's reliance on the JMM with property tests and
jax determinism checks).

Hypothesis drives random shapes / rank counts / sub-ranges / operators
through the device collectives against the numpy oracle; determinism
tests pin down that repeated executions are bit-identical (XLA programs
are deterministic on a fixed topology — the property the reference
cannot state about its thread interleavings)."""

from functools import partial

import numpy as np
import pytest
pytest.importorskip("hypothesis")  # property tests need hypothesis
from hypothesis import given, settings, strategies as st

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ytk_mp4j_tpu.comm.tpu_comm import TpuCommCluster
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operators
from ytk_mp4j_tpu.ops import collectives as coll, ring
from ytk_mp4j_tpu.parallel import make_mesh

_OPS = {"SUM": np.sum, "MAX": np.max, "MIN": np.min, "PROD": np.prod}


@settings(max_examples=25, deadline=None)
@given(
    n=st.integers(1, 8),
    length=st.integers(1, 40),
    op_name=st.sampled_from(sorted(_OPS)),
    data=st.data(),
)
def test_allreduce_any_rank_count_range_operator(n, length, op_name,
                                                 data):
    """allreduce over any rank count (power-of-2 or not), any sub-range,
    any builtin operator == the numpy oracle."""
    lo = data.draw(st.integers(0, length), label="lo")
    hi = data.draw(st.integers(lo, length), label="hi")
    rng = np.random.default_rng(data.draw(st.integers(0, 2**31),
                                          label="seed"))
    # magnitudes near 1 keep PROD finite for any length
    arrs = [(0.5 + rng.random(length)).astype(np.float32)
            for _ in range(n)]
    orig = [a.copy() for a in arrs]
    cluster = TpuCommCluster(n)
    cluster.allreduce_array(arrs, Operands.FLOAT,
                            Operators.by_name(op_name),
                            from_=lo, to=hi)
    want = (_OPS[op_name](np.stack([o[lo:hi] for o in orig]), axis=0)
            if hi > lo else None)
    for a, o in zip(arrs, orig):
        if hi > lo:
            np.testing.assert_allclose(a[lo:hi], want, rtol=1e-4,
                                       atol=1e-5)
        np.testing.assert_array_equal(a[:lo], o[:lo])
        np.testing.assert_array_equal(a[hi:], o[hi:])


@settings(max_examples=15, deadline=None)
@given(
    n=st.sampled_from([2, 4, 8]),
    chunks=st.integers(1, 5),
    op_name=st.sampled_from(["SUM", "MAX"]),
    seed=st.integers(0, 2**31),
)
def test_ring_allreduce_property(n, chunks, op_name, seed):
    """Hand-scheduled ring == oracle for any divisible length."""
    rng = np.random.default_rng(seed)
    L = n * chunks
    data = rng.standard_normal((n, L)).astype(np.float32)
    mesh = make_mesh(n)
    op = Operators.by_name(op_name)

    @partial(jax.shard_map, mesh=mesh, in_specs=P("mp4j"),
             out_specs=P("mp4j"))
    def f(x):
        return ring.ring_allreduce(x[0], op, "mp4j")[None]

    out = np.asarray(jax.jit(f)(jnp.asarray(data)))
    want = _OPS[op_name](data, axis=0)
    for r in range(n):
        np.testing.assert_allclose(out[r], want, rtol=1e-5, atol=1e-6)


def test_device_collective_is_bit_deterministic(rng):
    """The same jitted collective program on the same inputs must return
    bit-identical results across executions — the determinism property
    the reference's thread interleavings cannot offer."""
    mesh = make_mesh(8)
    data = rng.standard_normal((8, 64)).astype(np.float32)

    @partial(jax.shard_map, mesh=mesh, in_specs=P("mp4j"),
             out_specs=P("mp4j"))
    def f(x):
        return coll.allreduce(x[0] * 1.000001, Operators.SUM,
                              "mp4j")[None]

    g = jax.jit(f)
    a = np.asarray(g(jnp.asarray(data)))
    for _ in range(3):
        np.testing.assert_array_equal(a, np.asarray(g(jnp.asarray(data))))


def test_gbdt_training_is_bit_deterministic(rng):
    """Two identical distributed training runs produce bit-identical
    trees and margins."""
    from ytk_mp4j_tpu.models.gbdt import GBDTConfig, GBDTTrainer

    bins = rng.integers(0, 16, (512, 4)).astype(np.int32)
    y = (bins[:, 0] / 16).astype(np.float32)
    cfg = GBDTConfig(n_features=4, n_bins=16, depth=3, n_trees=2)

    outs = []
    for _ in range(2):
        tr = GBDTTrainer(cfg, mesh=make_mesh(4))
        trees, preds = tr.train(bins, y)
        outs.append((trees, preds))
    np.testing.assert_array_equal(outs[0][1], outs[1][1])
    for ta, tb in zip(outs[0][0], outs[1][0]):
        for xa, xb in zip(ta, tb):
            np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb))
