"""mp4j-audit (ISSUE 8): digest semantics, cross-rank verification,
corruption detection, record/replay, and the audit satellites.

The acceptance grid: injected ``corrupt`` faults across {tcp, shm} x
{raw, framed, columnar-map} must be detected and NAMED (collective
ordinal + ranks) under ``MP4J_AUDIT=verify`` — including the
consistent-wrong case where every rank's output is equal-but-wrong and
only the pairwise wire digests disagree; a clean multi-collective grid
must report ZERO false divergences; and ``mp4j-scope replay`` on a
captured bundle must reproduce an injected divergence digest-for-digest
offline while reporting an unfaulted bundle all-clean.
"""

import io
import json
import threading
import time

import numpy as np
import pytest

from ytk_mp4j_tpu.comm.master import Master
from ytk_mp4j_tpu.comm import process_comm as pc
from ytk_mp4j_tpu.comm.process_comm import ProcessCommSlave
from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.obs import audit as audit_mod
from ytk_mp4j_tpu.obs import cli as obs_cli
from ytk_mp4j_tpu.obs import metrics as metrics_mod
from ytk_mp4j_tpu.obs import postmortem as postmortem_mod
from ytk_mp4j_tpu.obs import telemetry
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operator, Operators
from ytk_mp4j_tpu.resilience import faults as faults_mod
from ytk_mp4j_tpu.utils import tuning

N = 4
JOIN = 45.0


def run_audited(n, fn, fault_plan=None, audit="verify", join=JOIN,
                hold=None, master_kwargs=None, **slave_kwargs):
    """Master + n thread-hosted slaves under a hard join deadline
    (the test_resilience harness shape). Returns (results, errors,
    master, log). ``hold`` is an optional (ready, release) event pair:
    workers set ``ready`` after ``fn`` and block on ``release`` before
    closing, so the main thread can interrogate the LIVE master."""
    log = io.StringIO()
    master = Master(n, timeout=join, log_stream=log,
                    **(master_kwargs or {})).serve_in_thread()
    results = [None] * n
    errors: list = [None] * n

    def worker(i):
        slave = None
        try:
            slave = ProcessCommSlave(
                "127.0.0.1", master.port, timeout=join,
                fault_plan=fault_plan, audit=audit,
                dead_rank_secs=20.0, **slave_kwargs)
            results[slave.rank] = fn(slave, slave.rank)
            if hold is not None:
                ready, release = hold
                ready.set()
                release.wait(join)
            slave.close(0)
        except Exception as e:
            r = slave.rank if slave is not None else i
            errors[r] = e
            if slave is not None:
                try:
                    slave.close(1)
                except Exception:
                    pass

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(n)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + join
    for t in threads:
        t.join(max(0.1, deadline - time.monotonic()))
    hung = [i for i, t in enumerate(threads) if t.is_alive()]
    assert not hung, f"ranks {hung} hung past the join deadline:\n" \
                     + log.getvalue()
    master.join(10.0)
    return results, errors, master, log.getvalue()


# ----------------------------------------------------------------------
# digest semantics (pure)
# ----------------------------------------------------------------------
def test_digest_bytes_sensitivity():
    base = bytes(range(256)) * 64
    h = audit_mod.digest_bytes(base)
    flipped = bytearray(base)
    flipped[1234] ^= 0x01           # one BIT
    assert audit_mod.digest_bytes(bytes(flipped)) != h
    assert audit_mod.digest_bytes(base + b"\0") != h
    assert audit_mod.digest_bytes(base[:-1]) != h
    assert audit_mod.digest_bytes(base) == h    # deterministic


def test_digest_bytes_every_byte_position_matters():
    # xor-block hashing must detect a flip at ANY offset: body words,
    # the block remainder, and the sub-8-byte tail
    base = bytes(range(251)) * 9   # 2259 bytes: blocks + rem + tail
    h = audit_mod.digest_bytes(base)
    for pos in (0, 7, 8, 1024, 2048, 2255, 2258):
        b = bytearray(base)
        b[pos] ^= 0x80
        assert audit_mod.digest_bytes(bytes(b)) != h, pos


def test_digest_array_layout_canonical():
    """Equal VALUES must digest equally whatever the memory layout —
    the false-divergence hazard (mp4j-lint R13)."""
    a = np.arange(4096, dtype=np.float64)
    strided = np.empty(8192, np.float64)[::2]
    strided[:] = a
    assert not strided.flags.c_contiguous
    assert audit_mod.digest_array(strided) == audit_mod.digest_array(a)
    big = a.astype(a.dtype.newbyteorder(">"))
    assert audit_mod.digest_array(big) == audit_mod.digest_array(a)


def test_digest_array_dtype_and_shape_distinguish():
    a = np.zeros(64, np.float32)
    assert audit_mod.digest_array(a) != audit_mod.digest_array(
        a.view(np.int32))
    assert audit_mod.digest_array(a) != audit_mod.digest_array(a[:32])


def test_digest_payload_map_order_insensitive():
    d1 = {f"k{i}": float(i) for i in range(100)}
    d2 = dict(reversed(list(d1.items())))
    assert list(d1) != list(d2)
    h1, sig1 = audit_mod.digest_payload(d1)
    h2, sig2 = audit_mod.digest_payload(d2)
    assert h1 == h2 and sig1 == sig2 == "map[100]"
    d2["k3"] = 999.0
    assert audit_mod.digest_payload(d2)[0] != h1


def test_digest_payload_list_positional():
    h1, _ = audit_mod.digest_payload(["a", "b"])
    h2, _ = audit_mod.digest_payload(["b", "a"])
    assert h1 != h2


def test_fold_wire_is_boundary_invariant():
    data = bytes(range(256)) * 100
    whole = audit_mod.fold_wire(0, data)
    split = audit_mod.fold_wire(audit_mod.fold_wire(0, data[:777]),
                                data[777:])
    assert whole == split


# ----------------------------------------------------------------------
# knobs / ring mechanics
# ----------------------------------------------------------------------
def test_audit_knobs_validated(monkeypatch):
    monkeypatch.setenv("MP4J_AUDIT", "bogus")
    with pytest.raises(Mp4jError):
        tuning.audit_mode()
    monkeypatch.setenv("MP4J_AUDIT", "VERIFY")
    assert tuning.audit_mode() == "verify"
    monkeypatch.delenv("MP4J_AUDIT")
    assert tuning.audit_mode() == "digest"
    assert tuning.audit_mode("off") == "off"
    with pytest.raises(Mp4jError):
        tuning.audit_mode("sometimes")
    monkeypatch.setenv("MP4J_AUDIT_RING", "0")
    with pytest.raises(Mp4jError):
        tuning.audit_ring()
    monkeypatch.setenv("MP4J_AUDIT_RING", "16")
    assert tuning.audit_ring() == 16
    with pytest.raises(Mp4jError):
        audit_mod.AuditRing("off")


def test_audit_ring_delta_cursor_and_drop_accounting():
    ring = audit_mod.AuditRing("verify", rank=0, capacity=4)
    for seq in range(1, 4):
        rec = ring.begin(seq, "allreduce_array", np.zeros(4), {})
        ring.commit(rec, np.ones(4))
    d1 = ring.take_delta()
    assert [r["seq"] for r in d1["records"]] == [1, 2, 3]
    assert ring.take_delta() is None          # nothing new
    # overflow unshipped records: the drop is REPORTED, never silent
    for seq in range(4, 10):
        rec = ring.begin(seq, "allreduce_array", np.zeros(4), {})
        ring.commit(rec, np.ones(4))
    d2 = ring.take_delta()
    assert d2["dropped"] == 2                 # 4 and 5 fell off
    assert [r["seq"] for r in d2["records"]] == [6, 7, 8, 9]


def test_digest_mode_ships_nothing_capture_strips_payload():
    ring = audit_mod.AuditRing("digest", rank=0, capacity=8)
    rec = ring.begin(1, "allreduce_array", np.zeros(4), {})
    ring.commit(rec, np.ones(4))
    assert ring.take_delta() is None          # record-only mode
    cap = audit_mod.AuditRing("capture", rank=0, capacity=8)
    rec = cap.begin(1, "allreduce_array", np.arange(4.0), {})
    cap.commit(rec, np.ones(4))
    delta = cap.take_delta()
    assert "cap" not in delta["records"][0]   # bytes stay off the wire
    assert "cap" in cap.records()[0]          # ...but in the bundle


# ----------------------------------------------------------------------
# ClusterAuditor (pure state machine)
# ----------------------------------------------------------------------
def _rec(seq, fam="allreduce_array", out=7, wire=None, **kw):
    return {"seq": seq, "fam": fam, "sig": "x", "in": 1, "out": out,
            **({"wire": wire} if wire else {}), **kw}


def test_cluster_auditor_verifies_and_flags_minority():
    a = audit_mod.ClusterAuditor(3)
    live = {0, 1, 2}
    assert a.fold(0, {"records": [_rec(1)]}, live) == []
    assert a.fold(1, {"records": [_rec(1)]}, live) == []
    lines = a.fold(2, {"records": [_rec(1)]}, live)
    assert lines == [] and a.verified_seq == 1
    # seq 2: rank 1 diverges
    a.fold(0, {"records": [_rec(2)]}, live)
    a.fold(1, {"records": [_rec(2, out=99)]}, live)
    lines = a.fold(2, {"records": [_rec(2)]}, live)
    assert len(lines) == 1
    msg = lines[0]
    assert "collective #2" in msg and "allreduce_array" in msg
    assert "[1]" in msg                      # minority rank named
    assert a.divergence_total == 1
    assert a.verified_seq == 1               # watermark did not advance


def test_cluster_auditor_wire_mismatch_names_pair_and_transport():
    a = audit_mod.ClusterAuditor(2)
    live = {0, 1}
    # outputs AGREE (consistent-wrong) — only the wire folds disagree
    a.fold(0, {"records": [_rec(
        1, wire={"1": {"t": "shm", "s": [111, 64], "r": [222, 64]}})]},
        live)
    lines = a.fold(1, {"records": [_rec(
        1, wire={"0": {"t": "shm", "s": [222, 64], "r": [999, 64]}})]},
        live)
    assert len(lines) == 1
    assert "rank 0 -> rank 1" in lines[0] and "shm" in lines[0]


def test_cluster_auditor_schedule_divergence_and_rooted_families():
    a = audit_mod.ClusterAuditor(2)
    live = {0, 1}
    a.fold(0, {"records": [_rec(1, fam="reduce_array", out=1)]}, live)
    # rooted family with differing outputs: legitimately NOT compared
    assert a.fold(1, {"records": [_rec(1, fam="reduce_array", out=2)]},
                  live) == []
    assert a.verified_seq == 1
    a.fold(0, {"records": [_rec(2, fam="allreduce_array")]}, live)
    lines = a.fold(1, {"records": [_rec(2, fam="broadcast_array")]},
                   live)
    assert len(lines) == 1 and "schedule" in lines[0]


def test_cluster_auditor_bounds_pending():
    a = audit_mod.ClusterAuditor(2)
    live = {0, 1}
    # rank 1 never reports: pending must stay bounded, with the loss
    # counted — not grow for the job's lifetime
    recs = [_rec(s) for s in range(1, 600)]
    a.fold(0, {"records": recs}, live)
    assert len(a._pending) <= 512
    assert a.unverified_dropped >= 80


# ----------------------------------------------------------------------
# the corrupt fault kind (satellite)
# ----------------------------------------------------------------------
def test_corrupt_fault_parses_and_is_one_shot():
    plan = faults_mod.FaultPlan.parse("corrupt:rank=1:nth=2")
    assert plan.faults[0].action == "corrupt"
    inj = faults_mod.FaultInjector(plan, 1)

    class _Ch:
        peer_rank = 3

    inj.on_collective(1)
    assert inj.take_corrupt(_Ch(), 1 << 20) is None   # not armed yet
    inj.on_collective(2)
    assert inj.take_corrupt(_Ch(), 1024) is None      # below CORRUPT_MIN
    assert inj.take_corrupt(_Ch(), 1 << 20) is not None
    assert inj.take_corrupt(_Ch(), 1 << 20) is None   # one-shot


def test_corrupt_copy_is_deterministic_and_never_mutates():
    buf = bytes(range(256)) * 64
    out1 = faults_mod.corrupt_copy(buf)
    out2 = faults_mod.corrupt_copy(buf)
    assert out1 == out2 and out1 != buf
    assert buf == bytes(range(256)) * 64
    arr = np.arange(4096, dtype=np.float64)
    keep = arr.copy()
    flipped = faults_mod.corrupt_copy(arr)
    assert np.array_equal(arr, keep)            # caller untouched
    assert not np.array_equal(flipped, arr)
    assert (flipped != arr).sum() == 1          # exactly one element


# ----------------------------------------------------------------------
# the acceptance grid: corrupt detection across transports and planes
# ----------------------------------------------------------------------
def _grid_body(path):
    if path == "map":
        def fn(slave, r):
            d = {int(k): np.float64((r + 1) * k) for k in range(1200)}
            slave.allreduce_map(d, Operands.DOUBLE, Operators.SUM)
            slave.barrier()
            slave.allreduce_map(d, Operands.DOUBLE, Operators.SUM)
            return d
        return fn, {}

    def fn(slave, r):
        arr = np.arange(120_000, dtype=np.float64) * (r + 1)
        slave.allreduce_array(arr, Operands.DOUBLE, Operators.SUM)
        slave.barrier()
        slave.allreduce_array(arr, Operands.DOUBLE, Operators.SUM)
        return arr
    return fn, {"native_transport": path == "raw"}


@pytest.mark.parametrize("transport", ["shm", "tcp"])
@pytest.mark.parametrize("path", ["raw", "framed", "map"])
def test_corrupt_fault_detected_and_named(path, transport):
    """A flipped payload byte must be flagged with the collective
    ordinal and the ranks involved — even when the corrupted
    contribution folds into a reduce and every rank's OUTPUT is
    equal-but-wrong (the wire-digest check's whole reason to exist)."""
    fn, kw = _grid_body(path)
    kw.update({} if transport == "shm" else {"shm": False})
    _, errors, master, log = run_audited(
        N, fn, fault_plan="corrupt:rank=1:nth=2", **kw)
    assert all(e is None for e in errors), (errors, log)
    st = master.audit_status()
    assert st["divergences"] >= 1, (st, log)
    msgs = " | ".join(d["msg"] for d in st["last_divergences"])
    assert "collective #2" in msgs, msgs
    assert "rank 1" in msgs, msgs        # the corrupting rank named
    assert transport in msgs, msgs       # transport attribution
    assert "DIVERGENCE" in log


def test_corrupt_detected_on_live_master_within_heartbeat():
    """Detection is LIVE, not a close-time artifact: with the job
    still running (ranks parked before close), the master flags the
    divergence within ~one heartbeat interval of the faulted
    collective."""
    fn, kw = _grid_body("raw")
    ready, release = threading.Event(), threading.Event()
    holder = {}

    def wrapped(slave, r):
        out = fn(slave, r)
        holder.setdefault("t0", time.monotonic())
        return out

    def check():
        ready.wait(JOIN)
        deadline = time.monotonic() + 5 * tuning.heartbeat_secs() + 2.0
        while time.monotonic() < deadline:
            if holder.get("master").audit_status()["divergences"]:
                holder["latency"] = time.monotonic() - holder["t0"]
                break
            time.sleep(0.05)
        release.set()

    checker = threading.Thread(target=check, daemon=True)
    checker.start()

    # run_audited sets hold=(ready, release): workers park after fn
    # until the checker observed the live master
    log = io.StringIO()
    master = Master(N, timeout=JOIN, log_stream=log).serve_in_thread()
    holder["master"] = master
    errors = [None] * N

    def worker(i):
        slave = None
        try:
            slave = ProcessCommSlave(
                "127.0.0.1", master.port, timeout=JOIN,
                fault_plan="corrupt:rank=1:nth=2", audit="verify",
                dead_rank_secs=20.0, **kw)
            wrapped(slave, slave.rank)
            ready.set()
            release.wait(JOIN)
            slave.close(0)
        except Exception as e:
            errors[i] = e

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(N)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(JOIN)
    assert not any(t.is_alive() for t in threads), log.getvalue()
    checker.join(5.0)
    master.join(10.0)
    assert all(e is None for e in errors), errors
    assert "latency" in holder, "divergence never observed live"
    assert holder["latency"] <= 5 * tuning.heartbeat_secs() + 2.0


# ----------------------------------------------------------------------
# zero false divergences: clean grid + recovery interaction
# ----------------------------------------------------------------------
@pytest.mark.parametrize("transport", ["shm", "tcp"])
def test_clean_property_grid_zero_false_divergences(transport):
    """A clean multi-collective, multi-operand, multi-plane run under
    MP4J_AUDIT=verify must verify every seq and flag nothing."""
    rng = np.random.default_rng(5)
    base = rng.integers(1, 100, 30_000)

    def fn(slave, r):
        n_coll = 0
        for operand, operator in ((Operands.DOUBLE, Operators.SUM),
                                  (Operands.INT, Operators.MAX),
                                  (Operands.FLOAT, Operators.MIN)):
            arr = (base % 97).astype(operand.dtype) * (r + 1)
            slave.allreduce_array(arr, operand, operator)
            n_coll += 1
        arr = base.astype(np.float64)
        slave.broadcast_array(arr, Operands.DOUBLE, root=1)
        slave.reduce_array(arr, Operands.DOUBLE, Operators.SUM, root=2)
        slave.allgather_array(arr, Operands.DOUBLE)
        n_coll += 3
        d = {int(k): np.float64((r + 1) * (k % 31)) for k in range(900)}
        slave.allreduce_map(d, Operands.DOUBLE, Operators.SUM)
        slave.broadcast_map(d, Operands.DOUBLE, root=3)
        n_coll += 2
        return n_coll

    kw = {} if transport == "shm" else {"shm": False}
    results, errors, master, log = run_audited(N, fn, **kw)
    assert all(e is None for e in errors), (errors, log)
    st = master.audit_status()
    assert st["divergences"] == 0, (st, log)
    assert st["verified_seq"] == results[0], st
    assert st["dropped_records"] == 0


def test_reset_recovery_under_verify_no_false_divergence():
    """An epoch-fenced retry resends everything on a fresh wire; the
    failed attempt's folds must be reset on BOTH sides or every
    recovered seq would false-diverge."""
    fn, kw = _grid_body("raw")
    want, werr, _, _ = run_audited(N, fn, fault_plan=None, **kw)
    assert all(e is None for e in werr)
    got, errors, master, log = run_audited(
        N, fn, fault_plan="reset:rank=1:nth=2", **kw)
    assert all(e is None for e in errors), (errors, log)
    for w, g in zip(want, got):
        np.testing.assert_array_equal(w, g)
    st = master.audit_status()
    assert st["divergences"] == 0, (st, log)
    assert st["verified_seq"] == 2, st


def test_restored_snapshot_digest_mismatch_is_machine_checked():
    """Reintroduce the PR 5 snapshot-corruption bug (shallow value
    copies + an in-place operator) and inject a reset AFTER a merge
    has mutated the shared values: the retry must be REFUSED with an
    error naming the snapshot digest mismatch — never silently wrong
    'recovered' results."""
    iadd = Operator.custom(
        "IADD", lambda a, b: (a.__setitem__(0, a[0] + b[0]), a)[1],
        [0.0])

    def fn(slave, r):
        d = {k: [float((r + 1) * k)] for k in range(60)}
        slave.allreduce_map(d, Operands.OBJECT_OPERAND(), iadd)
        slave.barrier()
        slave.allreduce_map(d, Operands.OBJECT_OPERAND(), iadd)
        return d

    orig = pc._copy_value
    pc._copy_value = lambda v: v
    try:
        # peer=2 pin: rank 0 merges rank 1's contribution FIRST, then
        # the cut on the rank-2 channel triggers the retry from the
        # (now tainted) shallow snapshot. Two rarer interleavings are
        # also legitimate — the abort teardown can kill the rank-1
        # recv before any merge (snapshot never tainted, clean retry),
        # or the job can go terminal before a restore runs — so retry
        # the scenario until the tainted path materializes; it does on
        # the first run in the overwhelming majority of runs.
        named = []
        for _ in range(4):
            _, errors, _, log = run_audited(
                N, fn, fault_plan="reset:rank=0:nth=2:peer=2")
            named = [e for e in errors if e is not None
                     and "snapshot" in str(e) and "digest" in str(e)]
            if named:
                break
    finally:
        pc._copy_value = orig
    assert named, (errors, log)
    assert "collective #2" in str(named[0])


# ----------------------------------------------------------------------
# record/replay (tentpole second half)
# ----------------------------------------------------------------------
def _replay_body(slave, r):
    # exact-value floats: the thread-backend replay must reproduce the
    # socket schedules bit-for-bit (order-insensitive value/operator
    # combos, the cross-backend property-grid guarantee)
    arr = (np.arange(60_000) % 97).astype(np.float64) * (r + 1)
    slave.allreduce_array(arr, Operands.DOUBLE, Operators.SUM)
    slave.barrier()
    d = {int(k): np.float64((r + 1) * (k % 31)) for k in range(800)}
    slave.allreduce_map(d, Operands.DOUBLE, Operators.SUM)
    slave.barrier()
    slave.broadcast_array(arr, Operands.DOUBLE, root=2)
    return arr


def _dump_body(dump_dir):
    def fn(slave, r):
        out = _replay_body(slave, r)
        slave.dump_audit(dump_dir)
        return out
    return fn


def test_replay_clean_bundle_all_clean(tmp_path, capsys):
    d = str(tmp_path / "bundle")
    _, errors, _, log = run_audited(N, _dump_body(d), audit="capture")
    assert all(e is None for e in errors), (errors, log)
    assert obs_cli.main(["replay", d]) == 0
    out = capsys.readouterr().out
    assert "all records clean" in out
    assert "#1 allreduce_array: ok" in out
    assert "#2 allreduce_map: ok" in out
    assert "#3 broadcast_array: ok" in out


def test_replay_reproduces_injected_divergence(tmp_path, capsys):
    d = str(tmp_path / "bundle")
    _, errors, master, log = run_audited(
        N, _dump_body(d), audit="capture",
        fault_plan="corrupt:rank=1:nth=1")
    assert all(e is None for e in errors), (errors, log)
    # the live plane flagged it...
    assert master.audit_status()["divergences"] >= 1
    # ...and the offline replay reproduces it digest-for-digest, with
    # no cluster: the recorded (corrupted) output digests disagree
    # with the clean re-execution at exactly the faulted record
    assert obs_cli.main(["replay", d]) == 1
    out = capsys.readouterr().out
    assert "#1 allreduce_array: DIVERGED" in out
    assert "recorded" in out and "replayed" in out
    assert "#2 allreduce_map: ok" in out


def test_replay_without_capture_skips_not_crashes(tmp_path, capsys):
    d = str(tmp_path / "bundle")
    _, errors, _, _ = run_audited(N, _dump_body(d), audit="verify")
    assert all(e is None for e in errors)
    assert obs_cli.main(["replay", d]) == 0
    out = capsys.readouterr().out
    assert "SKIP" in out and "capture" in out


def test_replay_nonstd_call_marked(tmp_path):
    """A call with non-default ranges must be recorded as
    non-replayable, not replayed as a different call."""
    def fn(slave, r):
        arr = np.arange(4096, dtype=np.float64)
        ranges = [(i * 1024, (i + 1) * 1024) for i in range(N)]
        slave.allgather_array(arr, Operands.DOUBLE, ranges=ranges)
        slave.dump_audit(str(tmp_path))
        return arr

    _, errors, _, _ = run_audited(N, fn, audit="capture")
    assert all(e is None for e in errors)
    docs = audit_mod.load_audit_bundles(str(tmp_path))
    assert all(doc["records"][0].get("nonstd")
               for doc in docs.values())
    assert all(doc["slave_num"] == N for doc in docs.values())
    text, diverged = audit_mod.replay_bundle(str(tmp_path))
    assert diverged == 0 and "non-default args" in text


def test_replay_survives_corrupt_capture_and_bad_records(tmp_path):
    """Torn capture bytes are the artifact replay exists to diagnose:
    a record whose payload fails to DECODE reports CAPTURE CORRUPT
    (never a traceback), a record whose re-execution RAISES reports
    REPLAY ERROR with the exception text and a fresh thread group —
    and the remaining records still replay cleanly."""
    d = str(tmp_path / "bundle")
    _, errors, _, _ = run_audited(N, _dump_body(d), audit="capture")
    assert all(e is None for e in errors)
    for rank in range(N):
        p = tmp_path / "bundle" / f"rank_{rank:04d}" / "audit.json"
        doc = json.loads(p.read_text())
        doc["records"][0]["root"] = 99          # execution raises
        doc["records"][1]["cap"] = "AAAA"       # valid b64, torn zlib
        p.write_text(json.dumps(doc))
    text, diverged = audit_mod.replay_bundle(str(tmp_path / "bundle"))
    assert diverged == 2, text
    assert "#1 allreduce_array: REPLAY ERROR" in text
    assert "TypeError" in text                  # real diagnosis kept
    assert "#2 allreduce_map: CAPTURE CORRUPT" in text
    assert "#3 broadcast_array: ok" in text     # fresh group works


def test_capture_skips_oversized_payload_without_pickling():
    ring = audit_mod.AuditRing("capture", rank=0, capacity=4)
    big = np.zeros(audit_mod.CAPTURE_MAX_BYTES // 8 + 16, np.float64)
    t0 = time.perf_counter()
    rec = ring.begin(1, "allreduce_array", big, {})
    dt = time.perf_counter() - t0
    assert rec.get("capskip") and "cap" not in rec
    # the size floor must short-circuit BEFORE the full pickle pass
    # (a serialize of 8 MiB takes far longer than the digest alone)
    assert dt < 0.2, dt


def test_replay_degrades_when_ranks_left_no_bundle(tmp_path):
    """A dead rank's bundle is gone: replay must degrade to the
    recorded cross-rank comparison — including when the DEAD rank is
    the highest one, which rank-contiguity alone cannot detect (the
    dump's slave_num is the load-bearing signal; re-executing with
    the wrong group size would flag every record of a run whose only
    fault was the kill)."""
    import shutil

    d = str(tmp_path / "bundle")
    _, errors, _, _ = run_audited(N, _dump_body(d), audit="capture")
    assert all(e is None for e in errors)
    # dead MIDDLE rank
    mid = str(tmp_path / "mid")
    shutil.copytree(d, mid)
    shutil.rmtree(mid + "/rank_0001")
    text, diverged = audit_mod.replay_bundle(mid)
    assert diverged == 0, text
    assert "cannot re-execute" in text and "[1]" in text
    assert "ok (recorded)" in text
    # dead HIGHEST rank: bundles 0..2 look contiguous
    hi = str(tmp_path / "hi")
    shutil.copytree(d, hi)
    shutil.rmtree(hi + f"/rank_{N - 1:04d}")
    text2, diverged2 = audit_mod.replay_bundle(hi)
    assert diverged2 == 0, text2
    assert "cannot re-execute" in text2 and f"[{N - 1}]" in text2


def test_ranged_collective_under_verify_no_false_divergence():
    """Explicit from_/to sub-range calls digest the whole payload but
    replicate only the range — bytes outside it legitimately differ
    per rank and must NOT trip the output comparison (the wire check
    still covers the range that moved)."""
    def fn(slave, r):
        arr = np.arange(30_000, dtype=np.float64) * (r + 1)
        slave.allreduce_array(arr, Operands.DOUBLE, Operators.SUM,
                              from_=1000, to=20_000)
        return arr

    _, errors, master, log = run_audited(N, fn)
    assert all(e is None for e in errors), (errors, log)
    st = master.audit_status()
    assert st["divergences"] == 0, (st, log)


# ----------------------------------------------------------------------
# postmortem integration: audit.json + known-good watermark (satellite)
# ----------------------------------------------------------------------
def test_postmortem_carries_audit_and_watermark(tmp_path):
    pm = str(tmp_path / "pm")

    def fn(slave, r):
        arr = (np.arange(30_000) % 97).astype(np.float64)
        slave.allreduce_array(arr, Operands.DOUBLE, Operators.SUM)
        slave.barrier()
        # let the heartbeat ship seq 1's records before the kill, so
        # the master's watermark has something to stand on
        time.sleep(3 * tuning.heartbeat_secs())
        slave.allreduce_array(arr, Operands.DOUBLE, Operators.SUM)
        return arr

    _, errors, master, log = run_audited(
        N, fn, fault_plan="kill:rank=2:nth=2", audit="verify",
        postmortem_dir=pm, master_kwargs={"postmortem_dir": pm})
    survivors = [e for i, e in enumerate(errors) if i != 2]
    assert all(e is not None for e in survivors), (errors, log)
    # survivors' bundles carry audit.json
    bundles = audit_mod.load_audit_bundles(pm)
    assert set(bundles) >= {0, 1, 3}
    with open(str(tmp_path / "pm" / "manifest.json")) as fh:
        manifest = json.load(fh)
    assert manifest["audit"]["verified_seq"] == 1
    report = postmortem_mod.merge_report(pm)
    assert "known-good watermark: collective #1" in report
    assert "DEAD rank 2" in report


# ----------------------------------------------------------------------
# live view + Prometheus families (satellite)
# ----------------------------------------------------------------------
def test_prometheus_audit_families_and_live_column():
    doc = {
        "slave_num": 2, "window_secs": 60.0,
        "ranks": {
            "0": {"progress": {"seq": 5, "current": None, "last": "x",
                               "phase": None, "current_secs": 0.0},
                  "age": 0.1, "stats": {}, "rates": {}, "histograms": {},
                  "audit_seq": 5},
            "1": {"progress": {"seq": 5, "current": None, "last": "x",
                               "phase": None, "current_secs": 0.0},
                  "age": 0.1, "stats": {}, "rates": {}, "histograms": {},
                  "audit_seq": 4},
        },
        "cluster": {"stats": {}, "rates": {}, "histograms": {},
                    "audit": {"verified_seq": 4, "verified_total": 4,
                              "divergences": 2,
                              "last_divergences": [
                                  {"seq": 5, "kind": "output",
                                   "msg": "collective #5 diverged"}],
                              "dropped_records": 0,
                              "unverified_dropped": 0,
                              "rank_seq": {"0": 5, "1": 4}}},
    }
    text = metrics_mod.to_prometheus(doc)
    assert "mp4j_audit_divergences_total 2" in text
    assert "mp4j_audit_verified_seqs 4" in text
    assert "mp4j_audit_verified_seq_watermark 4" in text
    live = telemetry.format_live(doc)
    assert "audit: verified through collective #4" in live
    assert "2 divergence(s)" in live
    assert "collective #5 diverged" in live
    assert "aud" in live.splitlines()[3]      # column header
    # live metrics doc from a real master run wires audit_seq per rank
    rows = [ln for ln in live.splitlines() if ln.lstrip().startswith(
        ("0 ", "1 "))]
    assert any(" 5 " in r for r in rows)


def test_live_master_doc_carries_audit():
    """End-to-end: the verify-mode master's metrics document includes
    the audit section, and the analytic families render."""
    fn, kw = _grid_body("raw")
    log = io.StringIO()
    master = Master(N, timeout=JOIN, log_stream=log,
                    metrics_port=0).serve_in_thread()
    errors = []

    def worker(i):
        try:
            s = ProcessCommSlave("127.0.0.1", master.port, timeout=JOIN,
                                 audit="verify", **kw)
            fn(s, s.rank)
            s.close(0)
        except Exception as e:
            errors.append(e)

    ts = [threading.Thread(target=worker, args=(i,), daemon=True)
          for i in range(N)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(JOIN)
    master.join(10.0)
    assert not errors, errors
    doc = master.metrics_doc()
    audit = doc["cluster"]["audit"]
    assert audit["verified_seq"] == 2 and audit["divergences"] == 0
    text = metrics_mod.to_prometheus(doc)
    assert "mp4j_audit_divergences_total 0" in text
    assert "mp4j_audit_verified_seq_watermark 2" in text


# ----------------------------------------------------------------------
# hybrid (thread-backend) pass-through
# ----------------------------------------------------------------------
def test_thread_group_audit_passthrough(tmp_path):
    from ytk_mp4j_tpu.comm.thread_comm import ThreadCommSlave

    log = io.StringIO()
    master = Master(1, timeout=JOIN, log_stream=log).serve_in_thread()
    slaves = ThreadCommSlave.spawn_group(
        2, "127.0.0.1", master.port, audit="digest")
    errors = []

    def worker(s):
        try:
            arr = np.arange(1024, dtype=np.float64) * (s.rank + 1)
            s.allreduce_array(arr, Operands.DOUBLE, Operators.SUM)
            s.close(0)
        except Exception as e:
            errors.append(e)

    ts = [threading.Thread(target=worker, args=(s,), daemon=True)
          for s in slaves]
    for t in ts:
        t.start()
    for t in ts:
        t.join(JOIN)
    master.join(10.0)
    assert not errors, errors
    # n=1 process job: the process-level collective never runs (no
    # peers), so the ring may be empty — the API contract is that the
    # accessor works and standalone groups return []
    assert isinstance(slaves[0].audit_records(), list)
    standalone = ThreadCommSlave.spawn_group(2)
    assert standalone[0].audit_records() == []
    assert standalone[0].dump_audit(str(tmp_path)) is None
    for s in standalone:
        s.close(0)
