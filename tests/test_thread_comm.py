"""ThreadCommSlave: standalone thread groups and hybrid process x thread
jobs (the reference's two-level nesting, SURVEY.md section 3d)."""

import threading

import numpy as np
import pytest

from ytk_mp4j_tpu import meta
from ytk_mp4j_tpu.comm.master import Master
from ytk_mp4j_tpu.comm.thread_comm import ThreadCommSlave
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operators

from helpers import expected_reduce, make_inputs


def run_threads(slaves, fn, timeout=60.0):
    """Run fn(slave, global_rank) on one thread per slave."""
    results = [None] * len(slaves)
    errors = []

    def worker(sl):
        try:
            results[sl.thread_rank] = fn(sl, sl.rank)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    ts = [threading.Thread(target=worker, args=(sl,), daemon=True)
          for sl in slaves]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout)
        assert not t.is_alive(), "thread hung"
    if errors:
        raise errors[0]
    return results


def run_hybrid(P, T, fn, timeout=60.0):
    """P processes (threads actually, each owning a ProcessCommSlave) x T
    threads; returns {global_rank: result}."""
    master = Master(P, timeout=timeout).serve_in_thread()
    out = {}
    out_lock = threading.Lock()
    errors = []

    def proc_worker():
        try:
            slaves = ThreadCommSlave.spawn_group(
                T, "127.0.0.1", master.port, timeout=timeout)

            def th(sl):
                try:
                    r = fn(sl, sl.rank)
                    with out_lock:
                        out[sl.rank] = r
                except Exception as e:  # pragma: no cover
                    errors.append(e)

            ts = [threading.Thread(target=th, args=(sl,), daemon=True)
                  for sl in slaves]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout)
            for sl in slaves:
                sl.close(0)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    ps = [threading.Thread(target=proc_worker, daemon=True)
          for _ in range(P)]
    for p in ps:
        p.start()
    for p in ps:
        p.join(timeout * 2)
        assert not p.is_alive(), "process worker hung"
    if errors:
        raise errors[0]
    master.join(timeout)
    assert master.final_code == 0
    return out


# ------------------------------------------------------------- standalone
def test_standalone_allreduce(rng):
    T = 4
    slaves = ThreadCommSlave.spawn_group(T)
    alls = make_inputs(T, 33, Operands.DOUBLE, rng)
    want = expected_reduce(alls, "SUM")

    def fn(sl, r):
        arr = alls[r].copy()
        sl.allreduce_array(arr, Operands.DOUBLE, Operators.SUM)
        return arr

    for got in run_threads(slaves, fn):
        np.testing.assert_allclose(got, want)


def test_standalone_ranks():
    T = 3
    slaves = ThreadCommSlave.spawn_group(T)
    assert [s.rank for s in slaves] == [0, 1, 2]
    assert all(s.slave_num == 3 for s in slaves)
    assert all(s.thread_num == 3 for s in slaves)


def test_standalone_thread_barrier_and_maps(rng):
    T = 3
    slaves = ThreadCommSlave.spawn_group(T)
    maps = [{f"k{r}": 1.0, "shared": float(r)} for r in range(T)]

    def fn(sl, r):
        d = dict(maps[r])
        sl.allreduce_map(d, Operands.DOUBLE, Operators.SUM)
        sl.thread_barrier()
        return d

    want = {"k0": 1.0, "k1": 1.0, "k2": 1.0, "shared": 3.0}
    for got in run_threads(slaves, fn):
        assert got == want


# ----------------------------------------------------------------- hybrid
@pytest.mark.parametrize("P,T", [(2, 2), (3, 2), (2, 3)])
def test_hybrid_allreduce(P, T, rng):
    N = P * T
    alls = make_inputs(N, 29, Operands.DOUBLE, rng)
    want = expected_reduce(alls, "SUM")

    def fn(sl, r):
        arr = alls[r].copy()
        sl.allreduce_array(arr, Operands.DOUBLE, Operators.SUM)
        return arr

    out = run_hybrid(P, T, fn)
    assert set(out) == set(range(N))
    for r, got in out.items():
        np.testing.assert_allclose(got, want)


def test_hybrid_reduce_broadcast(rng):
    P, T = 2, 2
    N = P * T
    alls = make_inputs(N, 15, Operands.DOUBLE, rng)
    want = expected_reduce(alls, "MAX")
    root = 3  # proc 1, thread 1

    def fn(sl, r):
        arr = alls[r].copy()
        sl.reduce_array(arr, Operands.DOUBLE, Operators.MAX, root=root)
        red = arr.copy()
        arr2 = alls[r].copy()
        sl.broadcast_array(arr2, Operands.DOUBLE, root=root)
        return red, arr2

    out = run_hybrid(P, T, fn)
    np.testing.assert_allclose(out[root][0], want)
    for r in range(N):
        if r != root:
            np.testing.assert_array_equal(out[r][0], alls[r])
        np.testing.assert_array_equal(out[r][1], alls[root])


def test_hybrid_allgather_reduce_scatter(rng):
    P, T = 2, 2
    N = P * T
    L = 21
    alls = make_inputs(N, L, Operands.DOUBLE, rng)
    want = expected_reduce(alls, "SUM")
    ranges = meta.partition_range(0, L, N)

    def fn(sl, r):
        arr = alls[r].copy()
        sl.reduce_scatter_array(arr, Operands.DOUBLE, Operators.SUM)
        s, e = ranges[r]
        seg = arr[s:e].copy()
        arr2 = np.zeros(L, dtype=np.float64)
        s2, e2 = ranges[r]
        arr2[s2:e2] = alls[r][s2:e2]
        sl.allgather_array(arr2, Operands.DOUBLE)
        return seg, arr2

    out = run_hybrid(P, T, fn)
    want_ag = np.zeros(L)
    for q, (s, e) in enumerate(ranges):
        want_ag[s:e] = alls[q][s:e]
    for r in range(N):
        s, e = ranges[r]
        np.testing.assert_allclose(out[r][0], want[s:e])
        np.testing.assert_array_equal(out[r][1], want_ag)


def test_hybrid_gather_scatter(rng):
    P, T = 2, 2
    N = P * T
    L = 13
    alls = make_inputs(N, L, Operands.LONG, rng)
    ranges = meta.partition_range(0, L, N)
    root = 2  # proc 1, thread 0

    def fn(sl, r):
        arr = alls[r].copy()
        sl.gather_array(arr, Operands.LONG, root=root)
        g = arr.copy()
        arr2 = alls[r].copy()
        sl.scatter_array(arr2, Operands.LONG, root=root)
        return g, arr2

    out = run_hybrid(P, T, fn)
    want_g = np.concatenate(
        [alls[q][s:e] for q, (s, e) in enumerate(ranges)])
    np.testing.assert_array_equal(out[root][0], want_g)
    for r in range(N):
        s, e = ranges[r]
        np.testing.assert_array_equal(out[r][1][s:e], alls[root][s:e])


def test_hybrid_maps(rng):
    P, T = 2, 2
    N = P * T
    maps = [{f"k{r}": float(r), "shared": 1.0} for r in range(N)]

    def fn(sl, r):
        d = dict(maps[r])
        sl.allreduce_map(d, Operands.DOUBLE, Operators.SUM)
        a = dict(d)
        d2 = dict(maps[r])
        sl.reduce_scatter_map(d2, Operands.DOUBLE, Operators.SUM)
        return a, d2

    out = run_hybrid(P, T, fn)
    want = {"k0": 0.0, "k1": 1.0, "k2": 2.0, "k3": 3.0, "shared": 4.0}
    rebuilt = {}
    for r in range(N):
        a, share = out[r]
        assert a == want
        for k, v in share.items():
            assert meta.key_partition(k, N) == r
            rebuilt[k] = v
    assert rebuilt == want


def test_hybrid_global_barrier_and_logging():
    P, T = 2, 2

    def fn(sl, r):
        sl.info(f"hello {r}")
        sl.barrier()
        return r

    out = run_hybrid(P, T, fn)
    assert set(out) == {0, 1, 2, 3}


def test_thread_maps_do_not_alias(rng):
    """After a map collective, threads must own independent value
    objects (in-place mutation on one thread must not leak)."""
    T = 2
    slaves = ThreadCommSlave.spawn_group(T)
    outs = {}

    def fn(sl, r):
        d = {"k": np.array([1.0, 2.0])}
        sl.allreduce_map(d, Operands.DOUBLE, Operators.SUM)
        outs[r] = d
        return r

    run_threads(slaves, fn)
    assert outs[0]["k"] is not outs[1]["k"]
    outs[0]["k"] += 100.0
    np.testing.assert_allclose(outs[1]["k"], [2.0, 4.0])
