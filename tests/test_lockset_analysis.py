"""ISSUE 16 — lockset race + resource-leak analysis tests.

Covers the race model itself (thread-root discovery, canonical field
identity, per-root lock contexts), the ``threading.Condition.wait``
held-set satellite (a wait RELEASES the condition for its duration),
the frozen-snippet regressions reproducing the true positives R23/R24
found on the pre-PR tree (the sink drain-thread counters, the
rendezvous channel leak), the ``mp4j-lint races`` CLI view, the SARIF
2.1.0 export (validated against the vendored schema subset), and the
engine's parsed-context/Program caching.
"""

import json
import os
import textwrap

import pytest

from ytk_mp4j_tpu.analysis import cli as cli_mod
from ytk_mp4j_tpu.analysis.engine import Engine, Program
from ytk_mp4j_tpu.analysis.report import (Finding, Severity,
                                          render_sarif)
from ytk_mp4j_tpu.analysis.rules import ALL_RULES, get_rules

COMM_PATH = "ytk_mp4j_tpu/comm/snippet.py"

SARIF_SCHEMA = os.path.join(
    os.path.dirname(cli_mod.__file__), "sarif-2.1.0-subset.json")


def run_rule(rule_id, src, path=COMM_PATH, baseline=None):
    engine = Engine(rules=get_rules([rule_id]), baseline=baseline)
    result = engine.lint_source(textwrap.dedent(src), path)
    assert not [f for f in result.findings if f.rule == "E001"], \
        f"snippet failed to parse: {result.findings}"
    return result


def program_of(src, path=COMM_PATH):
    eng = Engine(rules=[])
    ctx, errs = eng._parse(textwrap.dedent(src), path)
    assert ctx is not None, errs
    return Program([ctx])


def _summary(model, display):
    return next(s for s in model.summaries.values()
                if s.func.display == display)


# ----------------------------------------------------------------------
# race model: roots, field identity, contexts
# ----------------------------------------------------------------------
def test_race_model_discovers_thread_timer_and_main_roots():
    model = program_of("""
        import threading

        class Plane:
            def __init__(self):
                t = threading.Thread(target=self._loop, daemon=True)
                t.start()
                threading.Timer(1.0, self._tick).start()

            def _loop(self):
                pass

            def _tick(self):
                pass

            def status(self):
                return self._probe()

            def _probe(self):
                return 1
    """).races
    assert "thread:Plane._loop" in model.roots
    assert "thread:Plane._tick" in model.roots
    # status is the public surface; _probe has an internal caller, so
    # its only contexts come from status — and __init__ is no root
    main = model.roots["main"]
    assert any(k.endswith(":Plane.status") or k.endswith(".status")
               for k in main)
    assert not any("_probe" in k for k in main)


def test_race_model_canonicalizes_base_class_fields():
    model = program_of("""
        import threading

        class Base:
            def __init__(self):
                self.count = 0
                t = threading.Thread(target=self._bump, daemon=True)
                t.start()

            def _bump(self):
                self.count += 1

        class Sub(Base):
            def peek(self):
                return self.count
    """).races
    shared = model.shared_fields()
    assert [fr.display for fr in shared] == ["Base.count"]
    assert sorted(shared[0].roots) == ["main", "thread:Base._bump"]


def test_race_model_lock_context_propagates_along_call_graph():
    # the write happens two calls below the lock acquisition: the
    # per-root context fixpoint must still credit it with the lock
    r = run_rule("R23", """
        import threading

        class Plane:
            def __init__(self):
                self._lock = threading.Lock()
                self.state = "idle"
                t = threading.Thread(target=self._loop, daemon=True)
                t.start()

            def _loop(self):
                with self._lock:
                    self._mid()

            def _mid(self):
                self._write()

            def _write(self):
                self.state = "running"

            def status(self):
                with self._lock:
                    return self.state
    """)
    assert not r.findings


# ----------------------------------------------------------------------
# the Condition.wait satellite: wait() releases the lock
# ----------------------------------------------------------------------
def test_condition_wait_strips_lock_from_predicate_sites():
    model = program_of("""
        import threading

        class Q:
            def __init__(self):
                self._aux = threading.Lock()
                self._cv = threading.Condition()
                self._items = []

            def get(self):
                with self._aux:
                    with self._cv:
                        self._cv.wait_for(lambda: self._items)
                        return self._items
    """).locks
    s = _summary(model, "Q.get")
    helds = []
    for a in s.accesses:
        if a.attr == "_items":
            helds.append({model.locks[k].display for k in a.held})
    # the predicate read lost _cv but kept _aux; the post-wait read
    # holds both
    assert {"Q._aux"} in helds
    assert {"Q._aux", "Q._cv"} in helds


def test_condition_wait_on_unheld_receiver_strips_nothing():
    model = program_of("""
        import threading

        class Q:
            def __init__(self):
                self._aux = threading.Lock()
                self._cv = threading.Condition()
                self._flag = False

            def peek(self, other):
                with self._aux:
                    other.wait_for(lambda: self._flag)
    """).locks
    s = _summary(model, "Q.peek")
    helds = [{model.locks[k].display for k in a.held}
             for a in s.accesses if a.attr == "_flag"]
    assert helds == [{"Q._aux"}]


def test_r23_fires_on_wait_predicate_not_credited_with_condition():
    """The satellite's point: a predicate evaluated inside
    ``cv.wait_for`` must not be credited with the condition's lock —
    crediting it would mask this R23 finding entirely."""
    r = run_rule("R23", """
        import threading

        class Pump:
            def __init__(self):
                self._cv = threading.Condition()
                self._ready = False
                t = threading.Thread(target=self._fill, daemon=True)
                t.start()

            def _fill(self):
                with self._cv:
                    self._ready = True
                    self._cv.notify_all()

            def wait_ready(self):
                with self._cv:
                    self._cv.wait_for(lambda: self._ready)
    """)
    [f] = r.findings
    assert f.rule == "R23" and "Pump._ready" in f.message
    assert f.context == "Pump._fill"


def test_r23_quiet_on_reads_after_wait_returns():
    # only the predicate loses the lock: a read AFTER wait_for
    # returns is back under the condition — no finding
    r = run_rule("R23", """
        import threading

        class Pump:
            def __init__(self):
                self._cv = threading.Condition()
                self._ready = False
                t = threading.Thread(target=self._fill, daemon=True)
                t.start()

            def _fill(self):
                with self._cv:
                    self._ready = True
                    self._cv.notify_all()

            def wait_ready(self):
                with self._cv:
                    self._cv.wait_for(self._poll)
                    return self._ready

            def _poll(self):
                return True
    """)
    assert not r.findings


# ----------------------------------------------------------------------
# frozen-snippet regressions: the pre-PR true positives
# ----------------------------------------------------------------------
def test_r23_frozen_pre_pr_sink_counter_race():
    """Frozen pre-PR ``obs/sink.py`` shape: the drain thread bumped
    ``dropped_records``/``last_error`` WITHOUT ``_io_lock`` while the
    public ``status()`` read them — the first true positive R23 found
    on the tree (fixed in this PR by taking ``_io_lock`` on both
    sides)."""
    r = run_rule("R23", """
        import threading

        class SinkWriter:
            def __init__(self):
                self._io_lock = threading.Lock()
                self.dropped_records = 0
                self.last_error = None
                t = threading.Thread(target=self._drain, daemon=True)
                t.start()

            def _drain(self):
                while True:
                    try:
                        self._flush()
                    except Exception as e:
                        self.dropped_records += 1
                        self.last_error = repr(e)

            def _flush(self):
                with self._io_lock:
                    pass

            def status(self):
                return {"dropped_records": self.dropped_records,
                        "last_error": self.last_error}
    """, path="ytk_mp4j_tpu/obs/sink_frozen.py")
    fields = {f.message.split()[2] for f in r.findings}
    assert f"{'SinkWriter'}.dropped_records" in fields
    assert all(f.rule == "R23" and f.context == "SinkWriter._drain"
               for f in r.findings)


FROZEN_RENDEZVOUS = """
class TcpChannel:
    def __init__(self, sock):
        self._sock = sock

    def set_timeout(self, t):
        self._sock.settimeout(t)

    def recv(self):
        return None, None

    def close(self):
        self._sock.close()


def accept_pre_pr(server, deadline, now):
    sock, addr = server.accept()
    ch = TcpChannel(sock)
    remaining = max(0.0, deadline - now)
    ch.set_timeout(remaining)
    kind, payload = ch.recv()
    return ch


def accept_post_pr(server, deadline, now):
    remaining = max(0.0, deadline - now)
    sock, addr = server.accept()
    ch = TcpChannel(sock)
    try:
        ch.set_timeout(remaining)
        kind, payload = ch.recv()
    except Exception:
        ch.close()
        raise
    return ch
"""


def test_r24_frozen_pre_pr_rendezvous_channel_leak(tmp_path):
    """Frozen pre-PR ``comm/master.py`` rendezvous shape: deadline
    arithmetic and ``set_timeout`` sat between wrapping the accepted
    socket and any protection, so a slow/broken peer leaked the
    channel — the true positive R24 found on the tree (fixed in this
    PR by hoisting the arithmetic and closing in the handler)."""
    p = tmp_path / "ytk_mp4j_tpu" / "transport" / "frozen.py"
    p.parent.mkdir(parents=True)
    p.write_text(FROZEN_RENDEZVOUS)
    result = Engine(rules=get_rules(["R24"])).lint_paths(
        [str(tmp_path)])
    leaks = [f for f in result.findings if f.rule == "R24"]
    assert [f.context for f in leaks] == ["accept_pre_pr"]
    assert "channel 'ch'" in leaks[0].message
    # charged at the acquire (the TcpChannel wrap), not at the risk
    assert "ch = TcpChannel(sock)" in \
        FROZEN_RENDEZVOUS.splitlines()[leaks[0].line - 1]


# ----------------------------------------------------------------------
# mp4j-lint races — the concurrency-contract view
# ----------------------------------------------------------------------
RACY_PKG = """
import threading

class Plane:
    def __init__(self):
        self._lock = threading.Lock()
        self.state = "idle"
        self.epoch = 0
        t = threading.Thread(target=self._loop, daemon=True)
        t.start()

    def _loop(self):
        self.state = "running"
        with self._lock:
            self.epoch += 1

    def status(self):
        with self._lock:
            return (self.state, self.epoch)
"""


def _racy_tree(tmp_path):
    p = tmp_path / "ytk_mp4j_tpu" / "comm" / "plane.py"
    p.parent.mkdir(parents=True)
    p.write_text(RACY_PKG)
    return str(tmp_path)


def test_cli_races_text_reports_contract_and_race(tmp_path, capsys):
    assert cli_mod.main(["races", _racy_tree(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "shared mutable fields" in out
    # the field -> lock map: epoch is consistently under _lock,
    # state is racy with the write witness named
    assert "Plane.epoch" in out and "Plane._lock" in out
    racy_lines = [ln for ln in out.splitlines()
                  if "Plane.state" in ln and "RACE" in ln]
    assert racy_lines
    assert "write" in out and "Plane._loop" in out


def test_cli_races_dot_output(tmp_path, capsys):
    assert cli_mod.main(["races", "--dot",
                         _racy_tree(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert out.startswith("digraph")
    assert "color=red" in out          # the racy field
    assert "Plane.epoch" in out


def test_cli_races_output_file(tmp_path, capsys):
    dst = tmp_path / "races.dot"
    assert cli_mod.main(["races", "--dot", "-o", str(dst),
                         _racy_tree(tmp_path)]) == 0
    assert dst.read_text().startswith("digraph")


# ----------------------------------------------------------------------
# SARIF 2.1.0 export
# ----------------------------------------------------------------------
def _validate_sarif(doc):
    jsonschema = pytest.importorskip("jsonschema")
    with open(SARIF_SCHEMA, encoding="utf-8") as fh:
        schema = json.load(fh)
    jsonschema.validate(doc, schema)


def test_sarif_document_is_schema_valid():
    findings = [
        Finding("R23", Severity.ERROR,
                "ytk_mp4j_tpu/comm/plane.py", 13, 1,
                "shared field Plane.state has inconsistent locksets",
                context="Plane._loop"),
        Finding("E001", Severity.ERROR,
                "ytk_mp4j_tpu/comm/broken.py", 0, 0,
                "syntax error"),   # no catalogue entry -> no ruleIndex
    ]
    doc = json.loads(render_sarif(findings, ALL_RULES))
    _validate_sarif(doc)
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "mp4j-lint"
    assert len(run["tool"]["driver"]["rules"]) == len(ALL_RULES)
    r23, e001 = run["results"]
    assert r23["ruleId"] == "R23" and r23["level"] == "error"
    idx = r23["ruleIndex"]
    assert run["tool"]["driver"]["rules"][idx]["id"] == "R23"
    assert r23["partialFingerprints"]["mp4jContext/v1"] == "Plane._loop"
    loc = r23["locations"][0]["physicalLocation"]
    assert loc["region"]["startLine"] == 13
    # 0-based engine cols clamp to SARIF's 1-based minimum
    assert e001["locations"][0]["physicalLocation"]["region"][
        "startLine"] == 1
    assert "ruleIndex" not in e001


def test_sarif_empty_run_still_carries_catalogue():
    doc = json.loads(render_sarif([], ALL_RULES))
    _validate_sarif(doc)
    assert doc["runs"][0]["results"] == []
    assert doc["runs"][0]["tool"]["driver"]["rules"]


def test_cli_sarif_writes_validated_log(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def f(c):\n    if c.rank:\n        c.barrier()\n")
    out = tmp_path / "lint.sarif"
    assert cli_mod.main([str(bad), "--sarif", str(out)]) == 1
    doc = json.loads(out.read_text())
    _validate_sarif(doc)
    results = doc["runs"][0]["results"]
    assert any(r["ruleId"] == "R1" for r in results)
    # --select narrows the embedded catalogue with the run
    out2 = tmp_path / "lint2.sarif"
    assert cli_mod.main([str(bad), "--select", "R2",
                         "--sarif", str(out2)]) == 0
    doc2 = json.loads(out2.read_text())
    _validate_sarif(doc2)
    assert [r["id"] for r in
            doc2["runs"][0]["tool"]["driver"]["rules"]] == ["R2"]
    assert doc2["runs"][0]["results"] == []


# ----------------------------------------------------------------------
# engine caching: parsed contexts + Program reuse (ISSUE 16 satellite)
# ----------------------------------------------------------------------
def test_engine_caches_contexts_and_program_across_runs(tmp_path):
    tree = _racy_tree(tmp_path)
    Engine.clear_caches()
    try:
        eng = Engine(rules=get_rules(["R23"]))
        r1 = eng.lint_paths([tree])
        ctx1 = Engine._context_cache[
            next(iter(Engine._context_cache))][1]
        progs1 = list(Program._cache.values())
        assert len(progs1) == 1
        # same-process second run (the strict gate then the rule
        # tests): parsed module index and Program come from cache
        r2 = Engine(rules=get_rules(["R23"])).lint_paths([tree])
        ctx2 = Engine._context_cache[
            next(iter(Engine._context_cache))][1]
        assert ctx1 is ctx2
        assert list(Program._cache.values()) == progs1
        assert [f.format() for f in r1.findings] == \
            [f.format() for f in r2.findings]
        # an edit invalidates: the context signature changes
        p = tmp_path / "ytk_mp4j_tpu" / "comm" / "plane.py"
        p.write_text(RACY_PKG + "\n# touched\n")
        os.utime(p, ns=(1, 1))   # force a distinct (mtime, size) sig
        Engine(rules=get_rules(["R23"])).lint_paths([tree])
        ctx3 = Engine._context_cache[
            next(iter(Engine._context_cache))][1]
        assert ctx3 is not ctx1
        assert len(Program._cache) == 2
    finally:
        Engine.clear_caches()
