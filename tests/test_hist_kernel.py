"""Pallas histogram kernel (ops/hist_kernel.py): differential checks
against a numpy oracle, in interpret mode on the CPU test rig."""

import numpy as np
import pytest

import jax.numpy as jnp

from ytk_mp4j_tpu.ops.hist_kernel import (pallas_hist_supported,
                                          pallas_histograms)


def np_hist(bins, g, node_ids, n_nodes, F, B):
    out = np.zeros((n_nodes, F, B), np.float64)
    for i in range(bins.shape[0]):
        for f in range(F):
            out[node_ids[i], f, bins[i, f]] += g[i]
    return out


@pytest.mark.parametrize("n_nodes", [1, 4])
@pytest.mark.parametrize("N", [64, 77, 300])
def test_matches_numpy(rng, n_nodes, N):
    """Odd N exercises the single-step sublane-rounding path (N < tile)
    and the zero-padded rows."""
    F, B = 3, 16
    bins = rng.integers(0, B, (N, F)).astype(np.int32)
    g = rng.standard_normal(N).astype(np.float32)
    h = rng.random(N).astype(np.float32)
    nid = rng.integers(0, n_nodes, N).astype(np.int32)
    hg, hh = pallas_histograms(
        jnp.array(bins), jnp.array(g), jnp.array(h), jnp.array(nid),
        n_nodes, F, B, interpret=True)
    np.testing.assert_allclose(np.asarray(hg),
                               np_hist(bins, g, nid, n_nodes, F, B),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(hh),
                               np_hist(bins, h, nid, n_nodes, F, B),
                               rtol=1e-4, atol=1e-4)


def test_multi_tile_grid(rng):
    """N > tile: accumulation across grid steps, plus pad-row zeroing."""
    N, F, B = 100, 2, 8
    bins = rng.integers(0, B, (N, F)).astype(np.int32)
    g = rng.standard_normal(N).astype(np.float32)
    h = np.ones(N, np.float32)
    nid = np.zeros(N, np.int32)
    hg, hh = pallas_histograms(
        jnp.array(bins), jnp.array(g), jnp.array(h), jnp.array(nid),
        1, F, B, tile=32, interpret=True)
    np.testing.assert_allclose(np.asarray(hg),
                               np_hist(bins, g, nid, 1, F, B),
                               rtol=1e-4, atol=1e-4)
    assert float(np.asarray(hh).sum()) == pytest.approx(N * F, rel=1e-4)


def test_zero_weight_rows_contribute_nothing(rng):
    """g == h == 0 rows (shard padding) must leave exact zeros — the
    trainer relies on this for distributed/single-device equivalence."""
    N, F, B = 40, 2, 8
    bins = rng.integers(0, B, (N, F)).astype(np.int32)
    g = np.zeros(N, np.float32)
    h = np.zeros(N, np.float32)
    nid = np.zeros(N, np.int32)
    hg, hh = pallas_histograms(
        jnp.array(bins), jnp.array(g), jnp.array(h), jnp.array(nid),
        1, F, B, interpret=True)
    assert np.all(np.asarray(hg) == 0)
    assert np.all(np.asarray(hh) == 0)


def test_hi_lo_split_precision(rng):
    """The bf16 hi/lo split must beat plain-bf16 rounding by orders of
    magnitude: values near 1 with tiny perturbations accumulate to ~1e-7
    relative error, where a single bf16 cast alone rounds at ~4e-3."""
    N, F, B = 4096, 1, 8
    bins = rng.integers(0, B, (N, F)).astype(np.int32)
    g = (1.0 + 1e-3 * rng.standard_normal(N)).astype(np.float32)
    h = np.ones(N, np.float32)
    nid = np.zeros(N, np.int32)
    hg, _ = pallas_histograms(
        jnp.array(bins), jnp.array(g), jnp.array(h), jnp.array(nid),
        1, F, B, interpret=True)
    want = np_hist(bins, g.astype(np.float64), nid, 1, F, B)
    rel = np.abs(np.asarray(hg, np.float64) - want).max() / want.max()
    assert rel < 1e-5


def test_supported_gate():
    assert pallas_hist_supported(256, 28)
    assert pallas_hist_supported(128, 4)
    assert not pallas_hist_supported(100, 28)   # B not lane-aligned
    assert not pallas_hist_supported(8, 5)      # B not lane-aligned
    # depth-6 trees (32 nodes) fit the VMEM accumulator budget...
    assert pallas_hist_supported(256, 28, n_nodes=32)
    # ...but depth-8 (128 nodes -> ~14.7 MB accumulator) must fall back
    # to the matmul strategy instead of failing Mosaic VMEM allocation
    assert not pallas_hist_supported(256, 28, n_nodes=128)
