"""Compressed operands (socket wire) and the BFLOAT16 operand."""

import socket
import threading

import numpy as np
import pytest

from ytk_mp4j_tpu.comm.tpu_comm import TpuCommCluster
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operators
from ytk_mp4j_tpu.transport.tcp import TcpChannel as Channel

from helpers import expected_reduce, run_slaves


def _pair():
    a, b = socket.socketpair()
    return Channel(a), Channel(b)


def test_channel_compressed_roundtrip():
    tx, rx = _pair()
    arr = np.zeros(10_000, np.float64)  # highly compressible
    out = {}

    def reader():
        out["arr"] = rx.recv()
        out["obj"] = rx.recv()

    t = threading.Thread(target=reader)
    t.start()
    tx.send_array(arr, compress=True)
    tx.send_obj({"k": [1, 2, 3], "s": "x" * 5000}, compress=True)
    t.join(10)
    np.testing.assert_array_equal(out["arr"], arr)
    assert out["obj"]["s"] == "x" * 5000
    tx.close()
    rx.close()


def test_compressed_wire_is_smaller():
    """Compressible payloads must actually shrink on the wire."""
    sent = []

    class Spy:
        def setsockopt(self, *a):
            pass

        def sendall(self, b):
            sent.append(len(b))

    ch = Channel.__new__(Channel)
    ch.sock = Spy()
    arr = np.zeros(100_000, np.float64)
    ch.send_array(arr)
    plain = sum(sent)
    sent.clear()
    ch.send_array(arr, compress=True)
    packed = sum(sent)
    assert packed < plain / 20


@pytest.mark.parametrize("algo", ["rhd", "ring"])
def test_socket_allreduce_compressed_operand(algo):
    n = 3
    operand = Operands.compressed(Operands.DOUBLE)
    assert operand.compress and operand.dtype == np.float64
    rng = np.random.default_rng(3)
    alls = [rng.standard_normal(57) for _ in range(n)]
    want = expected_reduce(alls, "SUM")

    def fn(slave, r):
        arr = alls[r].copy()
        slave.allreduce_array(arr, operand, Operators.SUM, algo=algo)
        return arr

    for got in run_slaves(n, fn):
        np.testing.assert_allclose(got, want)


def test_socket_map_compressed():
    n = 3
    operand = Operands.compressed(Operands.DOUBLE)

    def fn(slave, r):
        d = {f"k{r % 2}": float(r)}
        slave.allreduce_map(d, operand, Operators.SUM)
        return d

    for d in run_slaves(n, fn):
        assert d == {"k0": 2.0, "k1": 1.0}


# ----------------------------------------------------------------------
def test_bfloat16_operand_device_path():
    cluster = TpuCommCluster(4)
    dt = Operands.BFLOAT16.dtype
    arrs = [np.full(64, float(r + 1), dt) for r in range(4)]
    cluster.allreduce_array(arrs, Operands.BFLOAT16, Operators.SUM)
    for a in arrs:
        assert a.dtype == dt
        np.testing.assert_array_equal(a.astype(np.float32), 10.0)


def test_bfloat16_operand_socket_path():
    n = 3
    dt = Operands.BFLOAT16.dtype

    def fn(slave, r):
        arr = np.full(33, float(2 ** r), dt)
        slave.allreduce_array(arr, Operands.BFLOAT16, Operators.MAX)
        return arr

    for got in run_slaves(n, fn):
        np.testing.assert_array_equal(got.astype(np.float32), 4.0)


def test_bfloat16_identities_and_lookup():
    import ml_dtypes

    dt = Operands.BFLOAT16.dtype
    assert Operands.by_dtype(dt) is Operands.BFLOAT16
    # representable extrema (not +-inf): fp8 ml_dtypes have no inf, so
    # identities use finfo bounds — and they must never be NaN
    lo = Operators.MAX.identity(dt)
    hi = Operators.MIN.identity(dt)
    assert float(lo) == float(ml_dtypes.finfo(dt).min)
    assert float(hi) == float(ml_dtypes.finfo(dt).max)
    assert float(Operators.SUM.identity(dt)) == 0.0
    # the fp8 case the guard exists for: identity stays finite, and a
    # MAX against it returns the data unchanged
    f8 = np.dtype(ml_dtypes.float8_e4m3fn)
    ident8 = Operators.MAX.identity(f8)
    assert np.isfinite(float(ident8))
    x = np.array([1.0, -2.0], f8)
    np.testing.assert_array_equal(
        np.maximum(np.full_like(x, ident8), x).astype(np.float32),
        x.astype(np.float32))
