"""FM/FFM model family: distributed embedding-gradient allreduce over the
virtual mesh — dense psum vs device-native sparse path differentially."""

import numpy as np
import pytest

from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.models.fm import FMConfig, FMTrainer
from ytk_mp4j_tpu.parallel import make_mesh


def make_sparse_classification(rng, n=256, vocab=64, n_fields=4, nnz=4):
    """Each instance: nnz active features, one per field; label from a
    planted pairwise interaction."""
    feats = np.stack([
        rng.integers(f * (vocab // n_fields), (f + 1) * (vocab // n_fields),
                     n)
        for f in range(nnz)], axis=1).astype(np.int32)
    fields = np.broadcast_to(np.arange(nnz, dtype=np.int32) % n_fields,
                             (n, nnz)).copy()
    vals = np.ones((n, nnz), np.float32)
    # planted signal: parity of (feat0 + feat1) decides the label
    y = ((feats[:, 0] + feats[:, 1]) % 2).astype(np.float32)
    return feats, fields, vals, y


def test_fm_fits_interaction(rng):
    feats, fields, vals, y = make_sparse_classification(rng)
    cfg = FMConfig(n_features=64, n_fields=4, k=8, max_nnz=4, model="fm",
                   learning_rate=0.5, init_scale=0.1)
    tr = FMTrainer(cfg, mesh=make_mesh(8))
    params, losses = tr.fit(feats, fields, vals, y, n_steps=300, seed=1)
    assert losses[-1] < losses[0] * 0.5
    p = tr.predict(params, feats, fields, vals)
    acc = float(np.mean((p > 0.5) == (y > 0.5)))
    assert acc > 0.9


def test_ffm_fits_interaction(rng):
    feats, fields, vals, y = make_sparse_classification(rng, n=256)
    cfg = FMConfig(n_features=64, n_fields=4, k=4, max_nnz=4, model="ffm",
                   learning_rate=0.5, init_scale=0.1)
    tr = FMTrainer(cfg, mesh=make_mesh(8))
    params, losses = tr.fit(feats, fields, vals, y, n_steps=300, seed=1)
    assert losses[-1] < losses[0] * 0.5
    p = tr.predict(params, feats, fields, vals)
    acc = float(np.mean((p > 0.5) == (y > 0.5)))
    assert acc > 0.9


@pytest.mark.parametrize("model", ["fm", "ffm"])
def test_distributed_matches_single_device(model, rng):
    feats, fields, vals, y = make_sparse_classification(rng, n=101)
    cfg = FMConfig(n_features=64, n_fields=4, k=4, max_nnz=4, model=model,
                   learning_rate=0.3, l2=1e-3, init_scale=0.1)
    dist = FMTrainer(cfg, mesh=make_mesh(8))
    pd, ld = dist.fit(feats, fields, vals, y, n_steps=20, seed=2)
    single = FMTrainer(cfg, mesh=make_mesh(1))
    ps, ls = single.fit(feats, fields, vals, y, n_steps=20, seed=2)
    np.testing.assert_allclose(ld, ls, rtol=1e-4, atol=1e-6)
    for a, b in zip(pd, ps):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("model", ["fm", "ffm"])
@pytest.mark.parametrize("l2", [0.0, 1e-3])
def test_sparse_grads_match_dense(model, l2, rng):
    """The sparse (row, grad) allreduce must produce the same updates as
    the dense psum — the TPU translation of the reference's sparse map
    path vs its dense array path. l2 != 0 exercises the sparse path's
    multiplicative-decay-plus-scatter form of the regularized update
    against the dense path's V - lr*(gV/denom + l2*V)."""
    feats, fields, vals, y = make_sparse_classification(rng, n=96)
    cfg = FMConfig(n_features=64, n_fields=4, k=4, max_nnz=4, model=model,
                   learning_rate=0.3, init_scale=0.1, l2=l2)
    dense = FMTrainer(cfg, mesh=make_mesh(4), sparse_grads=False)
    pdense, _ = dense.fit(feats, fields, vals, y, n_steps=10, seed=3)
    sparse = FMTrainer(cfg, mesh=make_mesh(4), sparse_grads=True)
    psparse, _ = sparse.fit(feats, fields, vals, y, n_steps=10, seed=3)
    for a, b in zip(pdense, psparse):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_sparse_refit_larger_dataset(rng):
    """Refitting with a bigger dataset must rebuild the sparse step: the
    jitted capacity baked in by the first fit would otherwise silently
    drop gradient rows (review regression)."""
    cfg = FMConfig(n_features=512, n_fields=2, k=2, max_nnz=2, model="fm",
                   learning_rate=0.5, init_scale=0.1)
    small = make_sparse_classification(rng, n=8, vocab=512, n_fields=2,
                                       nnz=2)
    big = make_sparse_classification(rng, n=256, vocab=512, n_fields=2,
                                     nnz=2)
    tr = FMTrainer(cfg, mesh=make_mesh(4), sparse_grads=True)
    tr.fit(*small, n_steps=1, seed=0)
    p_refit, _ = tr.fit(*big, n_steps=5, seed=0)
    fresh = FMTrainer(cfg, mesh=make_mesh(4), sparse_grads=True)
    p_fresh, _ = fresh.fit(*big, n_steps=5, seed=0)
    for a, b in zip(p_refit, p_fresh):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)


def test_config_validation():
    with pytest.raises(Mp4jError):
        FMConfig(n_features=8, model="deepfm")
    with pytest.raises(Mp4jError):
        FMConfig(n_features=8, model="ffm", n_fields=1)
    tr = FMTrainer(FMConfig(n_features=8, max_nnz=2), mesh=make_mesh(2))
    with pytest.raises(Mp4jError):
        tr.fit(np.zeros((4, 3), np.int32), np.zeros((4, 3), np.int32),
               np.ones((4, 3), np.float32), np.zeros(4, np.float32),
               n_steps=1)
    with pytest.raises(Mp4jError):
        tr.fit(np.full((4, 2), 99, np.int32), np.zeros((4, 2), np.int32),
               np.ones((4, 2), np.float32), np.zeros(4, np.float32),
               n_steps=1)


def test_eval_set_and_early_stopping(rng):
    n, NF, nf, K = 512, 64, 3, 4
    feats = rng.integers(0, NF, (n, K)).astype(np.int32)
    fields = rng.integers(0, nf, (n, K)).astype(np.int32)
    vals = np.ones((n, K), np.float32)
    y = (feats.min(1) < 8).astype(np.float32)
    va = (feats[:128], fields[:128], vals[:128], y[:128])
    cfg = FMConfig(model="ffm", n_features=NF, n_fields=nf, k=3,
                   max_nnz=K, learning_rate=0.5)
    tr = FMTrainer(cfg, mesh=make_mesh(2))
    params, losses = tr.fit(feats, fields, vals, y, n_steps=30,
                            eval_set=va)
    assert len(tr.eval_history_) == 30
    assert tr.eval_history_[-1] < tr.eval_history_[0]

    # noise labels: early stopping truncates and returns best params
    y_noise = (rng.random(n) > 0.5).astype(np.float32)
    va_noise = (feats[:128], fields[:128], vals[:128],
                (rng.random(128) > 0.5).astype(np.float32))
    tr2 = FMTrainer(cfg, mesh=make_mesh(2))
    params2, losses2 = tr2.fit(feats, fields, vals, y_noise, n_steps=40,
                               eval_set=va_noise,
                               early_stopping_rounds=3)
    assert len(losses2) < 40
    best = int(np.argmin(tr2.eval_history_))
    assert len(losses2) == best + 1
    # returned params reproduce the best round's validation metric
    assert tr2._eval_loss(params2, tr2._prep_eval(*va_noise)) == (
        pytest.approx(min(tr2.eval_history_), rel=1e-6))

    from ytk_mp4j_tpu.exceptions import Mp4jError
    with pytest.raises(Mp4jError):
        tr2.fit(feats, fields, vals, y, n_steps=3,
                early_stopping_rounds=2)


def test_save_load_params_roundtrip(rng, tmp_path):
    n, NF, nf, K = 256, 64, 3, 4
    feats = rng.integers(0, NF, (n, K)).astype(np.int32)
    fields = rng.integers(0, nf, (n, K)).astype(np.int32)
    vals = np.ones((n, K), np.float32)
    y = (feats.min(1) < 8).astype(np.float32)
    cfg = FMConfig(model="ffm", n_features=NF, n_fields=nf, k=3, max_nnz=K)
    tr = FMTrainer(cfg, mesh=make_mesh(2))
    params, _ = tr.fit(feats, fields, vals, y, n_steps=10)
    path = str(tmp_path / "ffm.model")
    tr.save_params(path, params)
    cfg2, params2 = FMTrainer.load_params(path, FMConfig)
    assert cfg2 == cfg
    serve = FMTrainer(cfg2, mesh=make_mesh(1))
    np.testing.assert_allclose(
        serve.predict(params2, feats, fields, vals),
        tr.predict(params, feats, fields, vals), rtol=1e-6)
