"""FM/FFM model family: distributed embedding-gradient allreduce over the
virtual mesh — dense psum vs device-native sparse path differentially."""

import numpy as np
import pytest
pytest.importorskip("hypothesis")  # property tests need hypothesis
from hypothesis import given, settings, strategies as st

from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.models.fm import FMConfig, FMTrainer
from ytk_mp4j_tpu.parallel import make_mesh


def make_sparse_classification(rng, n=256, vocab=64, n_fields=4, nnz=4):
    """Each instance: nnz active features, one per field; label from a
    planted pairwise interaction."""
    feats = np.stack([
        rng.integers(f * (vocab // n_fields), (f + 1) * (vocab // n_fields),
                     n)
        for f in range(nnz)], axis=1).astype(np.int32)
    fields = np.broadcast_to(np.arange(nnz, dtype=np.int32) % n_fields,
                             (n, nnz)).copy()
    vals = np.ones((n, nnz), np.float32)
    # planted signal: parity of (feat0 + feat1) decides the label
    y = ((feats[:, 0] + feats[:, 1]) % 2).astype(np.float32)
    return feats, fields, vals, y


def test_fm_fits_interaction(rng):
    feats, fields, vals, y = make_sparse_classification(rng)
    cfg = FMConfig(n_features=64, n_fields=4, k=8, max_nnz=4, model="fm",
                   learning_rate=0.5, init_scale=0.1)
    tr = FMTrainer(cfg, mesh=make_mesh(8))
    params, losses = tr.fit(feats, fields, vals, y, n_steps=300, seed=1)
    assert losses[-1] < losses[0] * 0.5
    p = tr.predict(params, feats, fields, vals)
    acc = float(np.mean((p > 0.5) == (y > 0.5)))
    assert acc > 0.9


def test_ffm_fits_interaction(rng):
    feats, fields, vals, y = make_sparse_classification(rng, n=256)
    cfg = FMConfig(n_features=64, n_fields=4, k=4, max_nnz=4, model="ffm",
                   learning_rate=0.5, init_scale=0.1)
    tr = FMTrainer(cfg, mesh=make_mesh(8))
    params, losses = tr.fit(feats, fields, vals, y, n_steps=300, seed=1)
    assert losses[-1] < losses[0] * 0.5
    p = tr.predict(params, feats, fields, vals)
    acc = float(np.mean((p > 0.5) == (y > 0.5)))
    assert acc > 0.9


@pytest.mark.parametrize("model", ["fm", "ffm"])
def test_distributed_matches_single_device(model, rng):
    feats, fields, vals, y = make_sparse_classification(rng, n=101)
    cfg = FMConfig(n_features=64, n_fields=4, k=4, max_nnz=4, model=model,
                   learning_rate=0.3, l2=1e-3, init_scale=0.1)
    dist = FMTrainer(cfg, mesh=make_mesh(8))
    pd, ld = dist.fit(feats, fields, vals, y, n_steps=20, seed=2)
    single = FMTrainer(cfg, mesh=make_mesh(1))
    ps, ls = single.fit(feats, fields, vals, y, n_steps=20, seed=2)
    np.testing.assert_allclose(ld, ls, rtol=1e-4, atol=1e-6)
    for a, b in zip(pd, ps):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("model", ["fm", "ffm"])
@pytest.mark.parametrize("l2", [0.0, 1e-3])
def test_sparse_grads_match_dense(model, l2, rng):
    """The sparse (row, grad) allreduce must produce the same updates as
    the dense psum — the TPU translation of the reference's sparse map
    path vs its dense array path. l2 != 0 exercises the sparse path's
    multiplicative-decay-plus-scatter form of the regularized update
    against the dense path's V - lr*(gV/denom + l2*V)."""
    feats, fields, vals, y = make_sparse_classification(rng, n=96)
    cfg = FMConfig(n_features=64, n_fields=4, k=4, max_nnz=4, model=model,
                   learning_rate=0.3, init_scale=0.1, l2=l2)
    dense = FMTrainer(cfg, mesh=make_mesh(4), sparse_grads=False)
    pdense, _ = dense.fit(feats, fields, vals, y, n_steps=10, seed=3)
    sparse = FMTrainer(cfg, mesh=make_mesh(4), sparse_grads=True)
    psparse, _ = sparse.fit(feats, fields, vals, y, n_steps=10, seed=3)
    for a, b in zip(pdense, psparse):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_sparse_refit_larger_dataset(rng):
    """Refitting with a bigger dataset must rebuild the sparse step: the
    jitted capacity baked in by the first fit would otherwise silently
    drop gradient rows (review regression)."""
    cfg = FMConfig(n_features=512, n_fields=2, k=2, max_nnz=2, model="fm",
                   learning_rate=0.5, init_scale=0.1)
    small = make_sparse_classification(rng, n=8, vocab=512, n_fields=2,
                                       nnz=2)
    big = make_sparse_classification(rng, n=256, vocab=512, n_fields=2,
                                     nnz=2)
    tr = FMTrainer(cfg, mesh=make_mesh(4), sparse_grads=True)
    tr.fit(*small, n_steps=1, seed=0)
    p_refit, _ = tr.fit(*big, n_steps=5, seed=0)
    fresh = FMTrainer(cfg, mesh=make_mesh(4), sparse_grads=True)
    p_fresh, _ = fresh.fit(*big, n_steps=5, seed=0)
    for a, b in zip(p_refit, p_fresh):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)


def test_config_validation():
    with pytest.raises(Mp4jError):
        FMConfig(n_features=8, model="deepfm")
    with pytest.raises(Mp4jError):
        FMConfig(n_features=8, model="ffm", n_fields=1)
    tr = FMTrainer(FMConfig(n_features=8, max_nnz=2), mesh=make_mesh(2))
    with pytest.raises(Mp4jError):
        tr.fit(np.zeros((4, 3), np.int32), np.zeros((4, 3), np.int32),
               np.ones((4, 3), np.float32), np.zeros(4, np.float32),
               n_steps=1)
    with pytest.raises(Mp4jError):
        tr.fit(np.full((4, 2), 99, np.int32), np.zeros((4, 2), np.int32),
               np.ones((4, 2), np.float32), np.zeros(4, np.float32),
               n_steps=1)


def test_eval_set_and_early_stopping(rng):
    n, NF, nf, K = 512, 64, 3, 4
    feats = rng.integers(0, NF, (n, K)).astype(np.int32)
    fields = rng.integers(0, nf, (n, K)).astype(np.int32)
    vals = np.ones((n, K), np.float32)
    y = (feats.min(1) < 8).astype(np.float32)
    va = (feats[:128], fields[:128], vals[:128], y[:128])
    cfg = FMConfig(model="ffm", n_features=NF, n_fields=nf, k=3,
                   max_nnz=K, learning_rate=0.5)
    tr = FMTrainer(cfg, mesh=make_mesh(2))
    params, losses = tr.fit(feats, fields, vals, y, n_steps=30,
                            eval_set=va)
    assert len(tr.eval_history_) == 30
    assert tr.eval_history_[-1] < tr.eval_history_[0]

    # noise labels: early stopping truncates and returns best params
    y_noise = (rng.random(n) > 0.5).astype(np.float32)
    va_noise = (feats[:128], fields[:128], vals[:128],
                (rng.random(128) > 0.5).astype(np.float32))
    tr2 = FMTrainer(cfg, mesh=make_mesh(2))
    params2, losses2 = tr2.fit(feats, fields, vals, y_noise, n_steps=40,
                               eval_set=va_noise,
                               early_stopping_rounds=3)
    assert len(losses2) < 40
    best = int(np.argmin(tr2.eval_history_))
    assert len(losses2) == best + 1
    # returned params reproduce the best round's validation metric
    assert tr2._eval_loss(params2, tr2._prep_eval(*va_noise)) == (
        pytest.approx(min(tr2.eval_history_), rel=1e-6))

    from ytk_mp4j_tpu.exceptions import Mp4jError
    with pytest.raises(Mp4jError):
        tr2.fit(feats, fields, vals, y, n_steps=3,
                early_stopping_rounds=2)


def test_save_load_params_roundtrip(rng, tmp_path):
    n, NF, nf, K = 256, 64, 3, 4
    feats = rng.integers(0, NF, (n, K)).astype(np.int32)
    fields = rng.integers(0, nf, (n, K)).astype(np.int32)
    vals = np.ones((n, K), np.float32)
    y = (feats.min(1) < 8).astype(np.float32)
    cfg = FMConfig(model="ffm", n_features=NF, n_fields=nf, k=3, max_nnz=K)
    tr = FMTrainer(cfg, mesh=make_mesh(2))
    params, _ = tr.fit(feats, fields, vals, y, n_steps=10)
    path = str(tmp_path / "ffm.model")
    tr.save_params(path, params)
    cfg2, params2 = FMTrainer.load_params(path, FMConfig)
    assert cfg2 == cfg
    serve = FMTrainer(cfg2, mesh=make_mesh(1))
    np.testing.assert_allclose(
        serve.predict(params2, feats, fields, vals),
        tr.predict(params, feats, fields, vals), rtol=1e-6)


# ------------------------------------------------------------- streaming
def test_fit_stream_single_chunk_matches_fit(rng):
    """The full dataset as one chunk per epoch must be numerically
    IDENTICAL to fit(n_steps=E) — both paths pad with zero-weight rows
    and run the same jitted step."""
    feats, fields, vals, y = make_sparse_classification(rng, n=101)
    cfg = FMConfig(n_features=64, n_fields=4, k=4, max_nnz=4,
                   model="ffm", learning_rate=0.3, init_scale=0.05)
    E = 4
    for sparse in (False, True):
        tr_a = FMTrainer(cfg, mesh=make_mesh(4), sparse_grads=sparse)
        p_fit, l_fit = tr_a.fit(feats, fields, vals, y, n_steps=E, seed=3)
        tr_b = FMTrainer(cfg, mesh=make_mesh(4), sparse_grads=sparse)
        p_st, l_st = tr_b.fit_stream(
            ((feats, fields, vals, y) for _ in range(E)), seed=3)
        np.testing.assert_array_equal(l_st, l_fit)
        for a, b in zip(p_fit, p_st):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fit_stream_multi_chunk(rng):
    """Uneven chunks (short final chunk) reuse one compiled step via
    batch_rows padding, and the stream actually learns."""
    feats, fields, vals, y = make_sparse_classification(rng, n=230)
    cfg = FMConfig(n_features=64, n_fields=4, k=8, max_nnz=4,
                   model="ffm", learning_rate=0.5, init_scale=0.1)
    tr = FMTrainer(cfg, mesh=make_mesh(4), sparse_grads=True)
    C = 64

    E = 12

    def chunks():
        for _ in range(E):                      # E epochs of 4 chunks
            for s in range(0, 230, C):          # last chunk = 38 rows
                yield (feats[s:s + C], fields[s:s + C],
                       vals[s:s + C], y[s:s + C])

    params, losses = tr.fit_stream(chunks(), batch_rows=C)
    assert losses.shape == (E * 4,)
    # per-chunk SGD losses are noisy; epoch means must fall steadily
    em = losses.reshape(E, 4).mean(axis=1)
    assert (np.diff(em) < 0).all(), em
    assert em[-1] < em[0] * 0.95
    preds = tr.predict(params, feats, fields, vals)
    assert np.mean((preds > 0.5) == (y > 0.5)) > 0.65


def test_fit_stream_batch_rows_not_multiple_of_shards(rng):
    """An explicit batch_rows that doesn't divide the mesh is rounded
    up, not crashed on (verify-drive regression: batch_rows=100 on 8
    shards)."""
    feats, fields, vals, y = make_sparse_classification(rng, n=100)
    cfg = FMConfig(n_features=64, n_fields=4, k=4, max_nnz=4,
                   model="ffm", learning_rate=0.3)
    tr = FMTrainer(cfg, mesh=make_mesh(8), sparse_grads=True)
    params, losses = tr.fit_stream(
        iter([(feats, fields, vals, y)]), batch_rows=100)
    assert losses.shape == (1,) and np.isfinite(losses).all()


def test_fit_stream_oversized_chunk_raises(rng):
    feats, fields, vals, y = make_sparse_classification(rng, n=64)
    cfg = FMConfig(n_features=64, n_fields=4, k=4, max_nnz=4)
    tr = FMTrainer(cfg, mesh=make_mesh(2))
    with pytest.raises(Mp4jError, match="exceeds batch_rows"):
        tr.fit_stream(iter([(feats, fields, vals, y)]), batch_rows=32)


def test_read_libsvm_formats(tmp_path):
    from ytk_mp4j_tpu.utils.libsvm import read_libsvm

    # libffm: field:feat:val
    text = ("1 0:3:1.0 1:7:2.0\n"
            "0 2:5:0.5\n"
            "\n"                              # blank lines are skipped
            "1 0:1:1.0 1:2:1.0 2:3:1.0\n")
    p = tmp_path / "data.ffm"
    p.write_text(text)
    got = list(read_libsvm(str(p), chunk_rows=2, max_nnz=3))
    assert len(got) == 2                      # 2 + 1 rows
    feats, fields, vals, y = got[0]
    assert feats.shape == (2, 3) and feats.dtype == np.int32
    np.testing.assert_array_equal(y, [1.0, 0.0])
    np.testing.assert_array_equal(feats[0], [3, 7, 0])
    np.testing.assert_array_equal(fields[0], [0, 1, 0])
    np.testing.assert_allclose(vals[0], [1.0, 2.0, 0.0])
    np.testing.assert_array_equal(got[1][0].shape, (1, 3))
    # libsvm: feat:val (field defaults to 0)
    got = list(read_libsvm(iter(["1 4:2.0 9:1.0"]), chunk_rows=8,
                           max_nnz=2))
    feats, fields, vals, y = got[0]
    np.testing.assert_array_equal(feats[0], [4, 9])
    np.testing.assert_array_equal(fields[0], [0, 0])


def test_read_libsvm_errors():
    from ytk_mp4j_tpu.utils.libsvm import read_libsvm

    def run(lines, **kw):
        return list(read_libsvm(iter(lines), **kw))

    with pytest.raises(Mp4jError, match="exceed max_nnz"):
        run(["1 1:1 2:1 3:1"], chunk_rows=4, max_nnz=2)
    with pytest.raises(Mp4jError, match="not a number"):
        run(["x 1:1"], chunk_rows=4, max_nnz=4)
    with pytest.raises(Mp4jError, match="neither"):
        run(["1 1:2:3:4"], chunk_rows=4, max_nnz=4)
    with pytest.raises(Mp4jError, match="neither"):
        run(["1 0:1:1.0 2:1.0"], chunk_rows=4, max_nnz=4)  # mixed widths
    with pytest.raises(Mp4jError, match="malformed"):
        run(["1 a:b"], chunk_rows=4, max_nnz=4)
    with pytest.raises(Mp4jError, match="chunk_rows"):
        run(["1 1:1"], chunk_rows=0, max_nnz=4)


# (the native-vs-Python reader differential lives in
# test_read_libsvm_fuzz_differential below — one hypothesis property,
# byte-strict, native-gated)


def test_read_libsvm_exotic_literals_and_overflow():
    """Literals the strict native scanner refuses but Python accepts
    (inf labels, underscore ints) must still parse via the replay path;
    out-of-int32 ids must error, never silently wrap."""
    from ytk_mp4j_tpu.utils.libsvm import read_libsvm

    got = list(read_libsvm(iter(["inf 4:1_0"]), chunk_rows=4, max_nnz=2))
    assert np.isinf(got[0][3][0]) and got[0][2][0, 0] == 10.0
    with pytest.raises((OverflowError, Mp4jError)):
        list(read_libsvm(iter(["1 5000000000:1.0"]), chunk_rows=4,
                         max_nnz=2))
    # first defect in FILE order is the one diagnosed, even when a
    # later line has a "cheaper" error class
    with pytest.raises(Mp4jError, match="line 1.*not a number"):
        list(read_libsvm(iter(["bad 1:2", "1 1:1", "1 1:1 2:1 3:1"]),
                         chunk_rows=4, max_nnz=2))


def test_stream_from_libsvm_end_to_end(rng, tmp_path):
    """File -> read_libsvm -> fit_stream: the configs[4] consumer flow
    at toy scale, never holding more than one chunk."""
    from ytk_mp4j_tpu.utils.libsvm import read_libsvm

    feats, fields, vals, y = make_sparse_classification(rng, n=200)
    lines = []
    for i in range(200):
        toks = " ".join(f"{fields[i, j]}:{feats[i, j]}:{vals[i, j]:.1f}"
                        for j in range(4))
        lines.append(f"{y[i]:.0f} {toks}\n")
    p = tmp_path / "train.ffm"
    p.write_text("".join(lines))

    cfg = FMConfig(n_features=64, n_fields=4, k=8, max_nnz=4,
                   model="ffm", learning_rate=0.5, init_scale=0.1)
    tr = FMTrainer(cfg, mesh=make_mesh(4), sparse_grads=True)
    params = None
    all_losses = []
    for _ in range(6):
        params, losses = tr.fit_stream(
            read_libsvm(str(p), chunk_rows=64, max_nnz=4),
            params=params if params is not None else None,
            batch_rows=64)
        all_losses.extend(losses)
    assert all_losses[-1] < all_losses[0] * 0.8


# -------------------------------------------------------- sharded table
@pytest.mark.parametrize("model", ["fm", "ffm"])
def test_sharded_table_matches_replicated(model, rng):
    """table_sharding='sharded' (owner-routed rows over all_to_all,
    per-member shard updates) must train exactly like the replicated
    sparse path — same losses, same predictions — while storing only
    rows/n per member."""
    feats, fields, vals, y = make_sparse_classification(rng, n=150)
    cfg = FMConfig(n_features=64, n_fields=4, k=4, max_nnz=4,
                   model=model, learning_rate=0.5, init_scale=0.1,
                   l2=1e-3)
    E = 5
    rep = FMTrainer(cfg, mesh=make_mesh(8), sparse_grads=True)
    p_rep, l_rep = rep.fit(feats, fields, vals, y, n_steps=E, seed=7)
    sh = FMTrainer(cfg, mesh=make_mesh(8), sparse_grads=True,
                   table_sharding="sharded")
    p_sh, l_sh = sh.fit(feats, fields, vals, y, n_steps=E, seed=7)
    np.testing.assert_allclose(l_sh, l_rep, rtol=1e-5, atol=1e-6)
    # the reconstructed table matches the replica
    np.testing.assert_allclose(sh.full_table(p_sh),
                               np.asarray(p_rep[2]), rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(
        sh.predict(p_sh, feats, fields, vals),
        rep.predict(p_rep, feats, fields, vals), rtol=1e-5, atol=1e-6)


def test_sharded_table_uneven_rows(rng):
    """n_rows not divisible by the shard count: the table pads, padding
    rows are never touched, and results still match replicated."""
    feats, fields, vals, y = make_sparse_classification(rng, n=90,
                                                       vocab=61)
    feats = np.clip(feats, 0, 60)
    cfg = FMConfig(n_features=61, n_fields=4, k=4, max_nnz=4,
                   model="fm", learning_rate=0.3, init_scale=0.1)
    rep = FMTrainer(cfg, mesh=make_mesh(8), sparse_grads=True)
    p_rep, l_rep = rep.fit(feats, fields, vals, y, n_steps=3, seed=1)
    sh = FMTrainer(cfg, mesh=make_mesh(8), sparse_grads=True,
                   table_sharding="sharded")
    assert sh.n_rows_padded == 64 and sh.n_rows == 61
    p_sh, l_sh = sh.fit(feats, fields, vals, y, n_steps=3, seed=1)
    np.testing.assert_allclose(l_sh, l_rep, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(sh.full_table(p_sh),
                               np.asarray(p_rep[2]), rtol=1e-5,
                               atol=1e-6)


def test_sharded_table_save_load_roundtrip(rng, tmp_path):
    """Sharded save emits the portable [n_rows, k] table; a fresh
    trainer (any sharding) restages it and keeps training/serving."""
    feats, fields, vals, y = make_sparse_classification(rng, n=80)
    cfg = FMConfig(n_features=64, n_fields=4, k=4, max_nnz=4,
                   model="ffm", learning_rate=0.3, init_scale=0.1)
    sh = FMTrainer(cfg, mesh=make_mesh(4), sparse_grads=True,
                   table_sharding="sharded")
    p, _ = sh.fit(feats, fields, vals, y, n_steps=2, seed=0)
    path = str(tmp_path / "ffm_sharded.npz")
    sh.save_params(path, p)
    cfg2, params2 = FMTrainer.load_params(path, FMConfig)
    assert params2[2].shape == (sh.n_rows, cfg.k)   # portable shape
    # serve densely from the loaded params
    dense = FMTrainer(cfg2, mesh=make_mesh(2))
    np.testing.assert_allclose(
        dense.predict(params2, feats, fields, vals),
        sh.predict(p, feats, fields, vals), rtol=1e-6)
    # and keep training sharded at a different shard count
    sh2 = FMTrainer(cfg2, mesh=make_mesh(8), sparse_grads=True,
                    table_sharding="sharded")
    p2, l2 = sh2.fit(feats, fields, vals, y, n_steps=2, params=params2)
    assert np.isfinite(l2).all()


def test_sharded_requires_sparse():
    cfg = FMConfig(n_features=8, n_fields=2, k=2, max_nnz=2, model="ffm")
    with pytest.raises(Mp4jError, match="sparse_grads"):
        FMTrainer(cfg, mesh=make_mesh(2), table_sharding="sharded")
    with pytest.raises(Mp4jError, match="table_sharding"):
        FMTrainer(cfg, mesh=make_mesh(2), sparse_grads=True,
                  table_sharding="bogus")
    # a tuned replicated-path capacity must not be silently dropped by
    # the sharded step (ADVICE round 4, low) — nor by the dense step,
    # which has no capacity at all
    with pytest.raises(Mp4jError, match="sparse_capacity"):
        FMTrainer(cfg, mesh=make_mesh(2), sparse_grads=True,
                  sparse_capacity=128, table_sharding="sharded")
    with pytest.raises(Mp4jError, match="sparse_capacity"):
        FMTrainer(cfg, mesh=make_mesh(2), sparse_capacity=128)


def test_sharded_fit_stream(rng):
    """The streaming path composes with the sharded table — the full
    configs[4] shape (streamed chunks AND a mesh-sharded vocabulary):
    losses must MATCH the replicated stream exactly, pipelined or
    serialized."""
    feats, fields, vals, y = make_sparse_classification(rng, n=128)
    cfg = FMConfig(n_features=64, n_fields=4, k=4, max_nnz=4,
                   model="ffm", learning_rate=0.5, init_scale=0.1)
    chunks = lambda: (  # noqa: E731 - two uneven chunks per epoch x 2
        (feats[s], fields[s], vals[s], y[s])
        for _ in range(2) for s in (slice(0, 80), slice(80, None)))
    rep = FMTrainer(cfg, mesh=make_mesh(4), sparse_grads=True)
    p_r, l_r = rep.fit_stream(chunks(), seed=5, batch_rows=80)
    sh = FMTrainer(cfg, mesh=make_mesh(4), sparse_grads=True,
                   table_sharding="sharded")
    p_s, l_s = sh.fit_stream(chunks(), seed=5, batch_rows=80)
    assert l_s.shape == (4,) and np.isfinite(l_s).all()
    np.testing.assert_allclose(l_s, l_r, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(sh.full_table(p_s), np.asarray(p_r[2]),
                               rtol=1e-5, atol=1e-6)
    # serialized pipeline (max_in_flight=0) is numerically identical
    sh0 = FMTrainer(cfg, mesh=make_mesh(4), sparse_grads=True,
                    table_sharding="sharded")
    _, l_s0 = sh0.fit_stream(chunks(), seed=5, batch_rows=80,
                             max_in_flight=0)
    np.testing.assert_allclose(l_s0, l_s, rtol=1e-6, atol=1e-8)


def test_sharded_table_on_hier_mesh(rng):
    """The sharded table composes with the hierarchical inter x intra
    mesh: P((inter, intra)) block-shards the table row-major over all
    members, flat_index ranks them the same way, and the all_to_all
    routing rides the axis tuple — losses must match the flat mesh."""
    from ytk_mp4j_tpu.parallel import make_hier_mesh

    feats, fields, vals, y = make_sparse_classification(rng, n=96)
    cfg = FMConfig(n_features=64, n_fields=4, k=4, max_nnz=4,
                   model="ffm", learning_rate=0.3, init_scale=0.1)
    flat = FMTrainer(cfg, mesh=make_mesh(8), sparse_grads=True,
                     table_sharding="sharded")
    p_f, l_f = flat.fit(feats, fields, vals, y, n_steps=3, seed=3)
    hier = FMTrainer(cfg, mesh=make_hier_mesh(4, 2), sparse_grads=True,
                     table_sharding="sharded")
    p_h, l_h = hier.fit(feats, fields, vals, y, n_steps=3, seed=3)
    np.testing.assert_allclose(l_h, l_f, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(hier.full_table(p_h),
                               flat.full_table(p_f), rtol=1e-5,
                               atol=1e-7)


def test_fm_sample_weight_equals_duplication(rng):
    """Integer instance weights == row duplication for the FM/FFM
    steps too (dense and sparse paths share _weighted_mean_grads), in
    fit and in weighted stream chunks."""
    feats, fields, vals, y = make_sparse_classification(rng, n=40)
    k = rng.integers(1, 4, 40)
    dup = lambda a: np.repeat(a, k, axis=0)  # noqa: E731
    cfg = FMConfig(n_features=64, n_fields=4, k=4, max_nnz=4,
                   model="ffm", learning_rate=0.3, init_scale=0.1)
    l_w_sparse = None
    for sparse in (False, True):
        tw = FMTrainer(cfg, mesh=make_mesh(4), sparse_grads=sparse)
        _, l_w = tw.fit(feats, fields, vals, y, n_steps=3, seed=2,
                        sample_weight=k.astype(np.float32))
        td = FMTrainer(cfg, mesh=make_mesh(4), sparse_grads=sparse)
        _, l_d = td.fit(dup(feats), dup(fields), dup(vals), dup(y),
                        n_steps=3, seed=2)
        np.testing.assert_allclose(l_w, l_d, rtol=1e-4, atol=1e-6)
        if sparse:
            l_w_sparse = l_w
    ts = FMTrainer(cfg, mesh=make_mesh(4), sparse_grads=True)
    _, l_s = ts.fit_stream(
        ((feats, fields, vals, y, k.astype(np.float32))
         for _ in range(3)), seed=2)
    np.testing.assert_allclose(l_s, l_w_sparse, rtol=1e-5, atol=1e-7)


def test_read_libsvm_native_rounding_parity():
    """Literals where single-rounding strtof diverges from the Python
    float()->float32 double rounding must still parse byte-identically
    on the native path (round-5 review catch: 1-ulp divergence on e.g.
    0.0000180163488039397634566 before strtod_l + cast)."""
    from ytk_mp4j_tpu.utils.libsvm import read_libsvm, _parse_chunk_slow

    lines = ["1 3:0.0000180163488039397634566",
             "0 3:0.0000049054617647925624624",
             "1 3:0.0188519200310111045837402"]
    a = list(read_libsvm(iter(lines), chunk_rows=8, max_nnz=4))[0]
    b = _parse_chunk_slow(lines, [1, 2, 3], 4)
    for x, z in zip(a, b):
        np.testing.assert_array_equal(x, z)


def test_trainer_weight_validation(rng):
    """NaN / negative / all-zero instance weights must raise on the
    trainer surfaces like they do on the binning surface — they would
    otherwise corrupt the weighted-mean steps silently (round-5 review
    catch)."""
    feats, fields, vals, y = make_sparse_classification(rng, n=16)
    tr = FMTrainer(FMConfig(n_features=64, n_fields=4, k=2, max_nnz=4,
                            model="ffm"), mesh=make_mesh(2))
    for bad in (np.full(16, np.nan), -np.ones(16), np.zeros(16)):
        with pytest.raises(Mp4jError):
            tr.fit(feats, fields, vals, y, n_steps=1,
                   sample_weight=bad)


@st.composite
def _libsvm_lines(draw):
    """Random well-formed libsvm/libffm lines the NATIVE scanner
    accepts (plain numeric labels and values, ids within int32) —
    exotic/malformed literals would route the whole chunk to the
    Python replay and make the differential compare the replay against
    itself. Refused-shape behavior is covered separately
    (test_read_libsvm_exotic_literals_and_overflow,
    test_read_libsvm_errors)."""
    n = draw(st.integers(1, 12))
    lines = []
    for _ in range(n):
        label = draw(st.one_of(
            st.integers(-5, 5).map(str),
            st.floats(-1e6, 1e6, allow_nan=False).map("{:.6g}".format)))
        k = draw(st.integers(0, 4))
        w = draw(st.sampled_from([2, 3]))
        toks = []
        for _s in range(k):
            feat = draw(st.integers(0, 2 ** 31 - 1))
            val = draw(st.one_of(
                st.floats(-1e30, 1e30, allow_nan=False)
                .map("{:.17g}".format),   # rounding-boundary widths
                st.sampled_from(["0", "1e-40", "2.5e38", "-0.0"])))
            if w == 2:
                toks.append(f"{feat}:{val}")
            else:
                toks.append(f"{draw(st.integers(0, 50))}:{feat}:{val}")
        lines.append(f"{label} " + " ".join(toks))
    return lines


@settings(max_examples=60, deadline=None)
@given(_libsvm_lines())
def test_read_libsvm_fuzz_differential(lines):
    """Property: the native fast path parses BYTE-identically
    (dtype + tobytes, so -0.0 vs +0.0 and 1-ulp rounding divergences
    fail) to the per-line Python contract on arbitrary well-formed
    chunks. Requires the native scanner — comparing the replay path
    against itself would verify nothing."""
    from ytk_mp4j_tpu.utils import native
    from ytk_mp4j_tpu.utils.libsvm import read_libsvm, _parse_chunk_slow

    native._load()
    if not native.HAVE_NATIVE:
        pytest.skip("native scanner unavailable (no toolchain)")
    got = list(read_libsvm(iter(lines), chunk_rows=64, max_nnz=4))
    want = _parse_chunk_slow(lines, list(range(1, len(lines) + 1)), 4)
    assert len(got) == 1
    for a, b in zip(got[0], want):
        assert a.dtype == b.dtype and a.shape == b.shape
        assert a.tobytes() == b.tobytes()
