"""Live metrics plane + flight recorder + bench gate (ISSUE 6).

Property tests for the log2-bucket histogram math (bucket placement
invariants over seeded sweeps including exact edges; quantile
estimates vs ``numpy.percentile``'s nearest-rank order statistic,
exact to one bucket by construction), the delta/fold algebra the
heartbeat rides on, the rate windows, the Prometheus renderer's line
grammar, the master's live HTTP endpoint during a real 4-rank socket
workload (acceptance criterion), the postmortem chaos case (a killed
rank leaves complete bundles on every survivor and the merged report
names the dead rank), the ``bench-diff`` regression gate on the two
checked-in BENCH files, and the new knob validation.
"""

import io
import json
import math
import os
import re
import threading
import time
import urllib.request

import numpy as np
import pytest

from test_resilience import run_chaos

from ytk_mp4j_tpu.comm.master import Master
from ytk_mp4j_tpu.comm.process_comm import ProcessCommSlave
from ytk_mp4j_tpu.exceptions import Mp4jError, Mp4jFatalError
from ytk_mp4j_tpu.obs import benchdiff, metrics, postmortem, telemetry
from ytk_mp4j_tpu.obs.cli import main as scope_main
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operators
from ytk_mp4j_tpu.resilience.faults import FaultKill
from ytk_mp4j_tpu.utils import stats as stats_mod
from ytk_mp4j_tpu.utils import tuning

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ----------------------------------------------------------------------
# histogram bucket math — property sweeps
# ----------------------------------------------------------------------
def _check_bucket_invariant(v, lo, n):
    """The defining property: bucket 0 holds v <= lo, bucket i holds
    (lo*2**(i-1), lo*2**i], bucket n holds the overflow."""
    idx = metrics.bucket_index(v, lo, n)
    assert 0 <= idx <= n
    if idx == 0:
        assert v <= lo
    elif idx < n:
        assert lo * 2.0 ** (idx - 1) < v <= lo * 2.0 ** idx
    else:
        assert v > lo * 2.0 ** (n - 1)
    return idx


@pytest.mark.parametrize("lo,n", [(1e-6, 36), (64.0, 27), (0.5, 8)])
def test_bucket_index_property_sweep(lo, n):
    rng = np.random.default_rng(7)
    # log-uniform sweep across (and past) the whole layout, plus the
    # exact power-of-two edges and their float neighbours — the values
    # where a naive log2 rounds the wrong way
    vals = list(np.exp(rng.uniform(np.log(lo / 8),
                                   np.log(lo * 2.0 ** (n + 2)), 4000)))
    for i in range(n):
        edge = lo * 2.0 ** i
        vals += [edge, np.nextafter(edge, 0), np.nextafter(edge, np.inf)]
    for v in vals:
        _check_bucket_invariant(float(v), lo, n)


def test_bucket_edges_layout():
    edges = metrics.bucket_edges(0.5, 4)
    assert edges == [0.5, 1.0, 2.0, 4.0]
    # exact-edge placement: an observation AT an edge belongs to the
    # bucket the edge closes (le semantics, like Prometheus)
    assert metrics.bucket_index(1.0, 0.5, 4) == 1
    assert metrics.bucket_index(4.0, 0.5, 4) == 3
    assert metrics.bucket_index(4.000001, 0.5, 4) == 4     # overflow


@pytest.mark.parametrize("dist", ["lognormal", "uniform", "exponential"])
@pytest.mark.parametrize("q", [0.0, 0.5, 0.9, 0.95, 0.99, 1.0])
def test_quantile_estimate_vs_numpy_within_bucket(dist, q):
    """hist_quantile returns the UPPER edge of the bucket holding the
    nearest-rank order statistic — so against numpy's inverted-CDF
    percentile the estimate is exact to one log2 bucket: true <= est
    and (below the overflow bucket) true > est/2."""
    lo, n = 1e-6, 36
    rng = np.random.default_rng(hash((dist, q)) % 2 ** 32)
    vals = {"lognormal": rng.lognormal(-7.0, 2.0, 3000),
            "uniform": rng.uniform(5e-7, 0.25, 3000),
            "exponential": rng.exponential(0.003, 3000)}[dist]
    reg = metrics.MetricsRegistry(enabled=True)
    for v in vals:
        reg.observe("latency/x", float(v), lo, n)
    h = reg.snapshot()["histograms"]["latency/x"]
    est = metrics.hist_quantile(h, q)
    true = float(np.percentile(vals, q * 100, method="inverted_cdf"))
    idx = metrics.bucket_index(true, lo, n)
    if idx >= n:
        assert est == math.inf
    else:
        assert est == (lo * 2.0 ** idx if idx else lo)
        assert true <= est
        if idx > 0:
            assert true > est / 2.0
    assert h["count"] == len(vals)
    assert h["sum"] == pytest.approx(float(np.sum(vals)), rel=1e-9)


def test_quantile_empty_and_overflow():
    assert metrics.hist_quantile(metrics._new_hist(1.0, 4), 0.5) == 0.0
    reg = metrics.MetricsRegistry(enabled=True)
    reg.observe("h", 1e9, 1.0, 4)           # everything overflows
    h = reg.snapshot()["histograms"]["h"]
    assert metrics.hist_quantile(h, 0.5) == math.inf


def test_registry_disabled_is_noop():
    reg = metrics.MetricsRegistry(enabled=False)
    reg.inc("c")
    reg.observe("h", 1.0, 1.0, 4)
    reg.set_gauge("g", 3.0)
    snap = reg.snapshot()
    assert snap == {"counters": {}, "gauges": {}, "histograms": {}}


# ----------------------------------------------------------------------
# delta / fold algebra (the heartbeat payload contract)
# ----------------------------------------------------------------------
def _random_registry(rng, families):
    reg = metrics.MetricsRegistry(enabled=True)
    for fam in families:
        for v in rng.lognormal(-6, 2, int(rng.integers(1, 50))):
            reg.observe(f"latency/{fam}", float(v),
                        metrics.LATENCY_LO, metrics.LATENCY_BUCKETS)
    reg.inc("events", int(rng.integers(1, 9)))
    return reg


def test_metrics_diff_fold_roundtrip():
    """fold(agg, diff(cur, prev)) == cur for every counter and bucket:
    the master's rolling view is exact, not approximate."""
    rng = np.random.default_rng(3)
    reg = _random_registry(rng, ["allreduce_array"])
    prev = reg.snapshot()
    for v in rng.lognormal(-6, 2, 40):
        reg.observe("latency/broadcast_array", float(v),
                    metrics.LATENCY_LO, metrics.LATENCY_BUCKETS)
    reg.inc("events", 5)
    cur = reg.snapshot()
    delta = metrics.diff_snapshot(cur, prev)
    folded = metrics.fold_snapshot(prev, delta)
    assert folded["counters"] == cur["counters"]
    for k, h in cur["histograms"].items():
        f = folded["histograms"][k]
        assert f["counts"] == h["counts"] and f["count"] == h["count"]
        assert f["sum"] == pytest.approx(h["sum"])


def test_metrics_diff_prunes_quiet_families():
    """The boundedness satellite: a family with no new observations
    ships NOTHING, so a long job's heartbeat is bounded by activity
    since the last beat, not by every family ever seen."""
    rng = np.random.default_rng(4)
    reg = _random_registry(rng, ["a", "b", "c"])
    prev = reg.snapshot()
    reg.observe("latency/b", 0.001,
                metrics.LATENCY_LO, metrics.LATENCY_BUCKETS)
    delta = metrics.diff_snapshot(reg.snapshot(), prev)
    assert set(delta["histograms"]) == {"latency/b"}
    assert delta["counters"] == {}
    assert delta["histograms"]["latency/b"]["count"] == 1


def test_stats_diff_snapshots_roundtrip_and_pruning():
    prev = {"allreduce_array": {"calls": 3, "bytes_sent": 100.0},
            "barrier": {"calls": 2}}
    cur = {"allreduce_array": {"calls": 5, "bytes_sent": 260.0},
           "barrier": {"calls": 2},
           "gather_map": {"calls": 1, "keys": 40}}
    delta = stats_mod.diff_snapshots(cur, prev)
    assert set(delta) == {"allreduce_array", "gather_map"}  # barrier quiet
    merged = stats_mod.merge_snapshots(prev, delta)
    # merge zero-fills the full counter schema; the recorded keys must
    # round-trip exactly (stats are monotone accumulators)
    for fam, entry in cur.items():
        for k, v in entry.items():
            assert merged[fam][k] == v, (fam, k)


def test_rate_window_sliding_derivative():
    win = metrics.RateWindow(window_secs=10.0)
    assert win.rates() == {}
    win.note(0.0, {"bytes": 0.0})
    assert win.rates() == {"bytes_per_sec": 0.0}    # one point: no rate
    win.note(2.0, {"bytes": 20.0})
    win.note(4.0, {"bytes": 100.0})
    assert win.rates()["bytes_per_sec"] == pytest.approx(25.0)  # 100/4s
    # points older than the window fall off: the rate tracks the
    # recent slope, not the lifetime average
    win.note(100.0, {"bytes": 100.0})
    win.note(102.0, {"bytes": 300.0})
    assert win.rates()["bytes_per_sec"] == pytest.approx(100.0)


def test_rate_window_coalesces_fast_notes_to_span_full_window():
    """Notes arriving much faster than window/(maxlen/2) — the master's
    cluster ring gets one per heartbeat PER RANK — coalesce instead of
    evicting old points, so the deque still spans the whole window."""
    win = metrics.RateWindow(window_secs=60.0, maxlen=512)
    t = 0.0
    # 256 ranks' worth of beats: 20000 notes over 40 s
    for i in range(20000):
        t = i * 0.002
        win.note(t, {"bytes": float(i)})
    assert len(win._points) <= 512
    t0, first = win._points[0]
    t1, last = win._points[-1]
    assert t1 - t0 == pytest.approx(t, rel=0.02)    # spans the run
    assert win.rates()["bytes_per_sec"] == pytest.approx(500.0, rel=0.05)


# ----------------------------------------------------------------------
# Prometheus renderer — line grammar + histogram consistency
# ----------------------------------------------------------------------
_PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"            # metric name
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\""  # first label
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\")*\})?"
    r" (\+Inf|-?[0-9.e+-]+)$")


def _validate_prometheus(text):
    """Format 0.0.4 gate: every non-comment line is name{labels} value;
    each metric family forms ONE contiguous block (promtool rejects a
    family reappearing after another metric); histogram buckets are
    cumulative and end at the _count."""
    hists: dict = {}
    seen_families: list = []
    for line in text.strip().splitlines():
        if line.startswith("#"):
            assert re.match(r"^# (TYPE|HELP) ", line)
            continue
        fam = re.sub(r"_(bucket|sum|count)(\{| )", r"\2",
                     line).split("{")[0].split(" ")[0]
        if not seen_families or seen_families[-1] != fam:
            assert fam not in seen_families, \
                f"family {fam!r} split across blocks"
            seen_families.append(fam)
        assert _PROM_LINE.match(line), f"bad exposition line: {line!r}"
        m = re.match(r"^(\w+)_bucket\{(.*)\} (\d+)$", line)
        if m:
            series = (m.group(1),
                      re.sub(r',?le="[^"]*"', "", m.group(2)))
            prev = hists.setdefault(series, [])
            if prev:
                assert int(m.group(3)) >= prev[-1], \
                    f"buckets not cumulative: {line!r}"
            prev.append(int(m.group(3)))
        m = re.match(r"^(\w+)_count\{?(.*?)\}? (\d+)$", line)
        if m and (m.group(1), m.group(2)) in hists:
            assert int(m.group(3)) == hists[(m.group(1), m.group(2))][-1]
    return hists


def test_to_prometheus_renders_synthetic_doc():
    reg = metrics.MetricsRegistry(enabled=True)
    for v in (1e-5, 3e-4, 0.002, 0.002, 1.0):
        reg.observe("latency/allreduce_array", v,
                    metrics.LATENCY_LO, metrics.LATENCY_BUCKETS)
    reg.observe("frame_bytes", 8192, metrics.FRAME_LO,
                metrics.FRAME_BUCKETS)
    doc = {
        "slave_num": 2, "window_secs": 60.0,
        "ranks": {"0": {
            "progress": {"seq": 4, "current": "allreduce_array",
                         "last": "barrier", "phase": "wire",
                         "current_secs": 0.1},
            "age": 0.2,
            "stats": {"allreduce_array": {
                "calls": 4, "bytes_sent": 1024, "bytes_recv": 1024,
                "wire_seconds": 0.01}},
            "rates": {"bytes_per_sec": 123.5, "collectives_per_sec": 2.0,
                      "keys_per_sec": 0.0},
        }},
        "cluster": {
            "stats": {"allreduce_array": {"calls": 4, "bytes_sent": 1024,
                                          "bytes_recv": 1024,
                                          "wire_seconds": 0.01}},
            "rates": {"bytes_per_sec": 123.5},
            "histograms": reg.snapshot()["histograms"],
        },
    }
    text = metrics.to_prometheus(doc)
    hists = _validate_prometheus(text)
    assert 'mp4j_calls_total{rank="0",collective="allreduce_array"} 4' \
        in text
    assert 'mp4j_calls_total{rank="cluster",collective=' in text
    assert 'phase="wire"' in text
    assert 'mp4j_collective_latency_seconds_bucket{collective=' \
        '"allreduce_array",le="+Inf"} 5' in text
    assert any(k[0] == "mp4j_collective_latency_seconds" for k in hists)
    assert "mp4j_frame_bytes_count 1" in text
    assert "mp4j_cluster_bytes_per_sec 123.5" in text


def test_format_live_marks_lag_and_stragglers():
    doc = {
        "slave_num": 2, "window_secs": 60.0,
        "ranks": {
            "0": {"progress": {"seq": 9, "current": None,
                               "last": "allreduce_array", "phase": None,
                               "current_secs": 0.0},
                  "age": 0.1, "stats": {}, "rates":
                      {"bytes_per_sec": 2e6}},
            "1": {"progress": {"seq": 7, "current": "allreduce_array",
                               "last": None, "phase": "wire",
                               "current_secs": 3.2},
                  "age": 0.1, "stats": {}, "rates":
                      {"bytes_per_sec": 1e6}},
        },
        "cluster": {"stats": {}, "rates": {"bytes_per_sec": 3e6,
                                           "collectives_per_sec": 1.0,
                                           "keys_per_sec": 0.0},
                    "histograms": {}},
    }
    frame = telemetry.format_live(doc)
    assert "2/2 ranks reporting" in frame
    assert "0.003 GB/s" in frame
    row1 = next(ln for ln in frame.splitlines()
                if ln.lstrip(" *").startswith("1 "))
    assert "2" in row1          # lag column: 9 - 7
    assert "in allreduce_array" in row1 and "wire" in row1


# ----------------------------------------------------------------------
# the live endpoint — acceptance criterion
# ----------------------------------------------------------------------
def test_metrics_endpoint_live_4rank_workload(monkeypatch, capsys):
    """During a live 4-rank socket workload the master endpoint serves
    valid Prometheus text AND the same document as JSON, with per-rank
    and cluster-aggregate series; ``mp4j-scope live --once`` renders
    it."""
    monkeypatch.setenv("MP4J_HEARTBEAT_SECS", "0.05")
    n = 4
    log = io.StringIO()
    master = Master(n, timeout=30.0, log_stream=log,
                    metrics_port=0).serve_in_thread()
    assert master.metrics_port                  # ephemeral port bound
    base = f"http://127.0.0.1:{master.metrics_port}"
    release = threading.Event()
    errors: list = []

    def worker():
        slave = None
        try:
            slave = ProcessCommSlave("127.0.0.1", master.port,
                                     timeout=30.0)
            arr = np.ones(32768)
            for _ in range(6):
                slave.allreduce_array(arr, Operands.DOUBLE,
                                      Operators.SUM)
            slave.barrier()
            assert release.wait(20.0)   # hold the job live for scrapes
            slave.close(0)
        except Exception as e:          # pragma: no cover - diagnostics
            errors.append(e)
            if slave is not None:
                slave.close(1)

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(n)]
    for t in threads:
        t.start()
    try:
        # wait until every rank's post-collective heartbeat has folded
        deadline = time.monotonic() + 15.0
        doc = None
        while time.monotonic() < deadline:
            with urllib.request.urlopen(base + "/metrics.json",
                                        timeout=5.0) as resp:
                doc = json.load(resp)
            done = [r for r, info in doc["ranks"].items()
                    if info["stats"].get("allreduce_array", {})
                    .get("calls") == 6]
            if len(done) == n:
                break
            time.sleep(0.05)
        assert doc is not None and len(doc["ranks"]) == n, log.getvalue()

        # JSON schema: per-rank progress/stats/rates/age + aggregates
        assert doc["slave_num"] == n
        for r in map(str, range(n)):
            info = doc["ranks"][r]
            assert info["stats"]["allreduce_array"]["calls"] == 6
            assert info["stats"]["allreduce_array"]["bytes_sent"] > 0
            assert {"seq", "current", "last", "phase",
                    "current_secs"} <= set(info["progress"])
            assert "bytes_per_sec" in info["rates"]
            assert info["age"] >= 0.0
        cl = doc["cluster"]
        assert cl["stats"]["allreduce_array"]["calls"] == 6 * n
        assert {"bytes_per_sec", "collectives_per_sec",
                "keys_per_sec"} <= set(cl["rates"])
        # the folded cluster latency histogram covers every rank's calls
        lat = cl["histograms"].get("latency/allreduce_array")
        assert lat and lat["count"] == 6 * n
        assert metrics.hist_quantile(lat, 0.99) > 0.0
        # frame-size observations rode the same fold, split by the
        # transport the bytes rode (ISSUE 7) — 4 thread slaves share
        # this host, so the whole data plane is the shm family
        frames = {k: h for k, h in cl["histograms"].items()
                  if k == "frame_bytes" or k.startswith("frame_bytes/")}
        assert sum(h["count"] for h in frames.values()) > 0
        assert cl["histograms"]["frame_bytes/shm"]["count"] > 0

        # Prometheus text: valid exposition + per-rank AND cluster rows
        with urllib.request.urlopen(base + "/metrics", timeout=5.0) as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            text = r.read().decode()
        _validate_prometheus(text)
        for who in [*map(str, range(n)), "cluster"]:
            assert (f'mp4j_calls_total{{rank="{who}",'
                    f'collective="allreduce_array"}}') in text
        assert "mp4j_collective_latency_seconds_bucket" in text
        assert f"mp4j_ranks_reporting {n}" in text

        # /health.json (ISSUE 13 satellite): the verdict document over
        # HTTP — external orchestrators read evict recommendations
        # without being in-process; same schema as health_status()
        with urllib.request.urlopen(base + "/health.json",
                                    timeout=5.0) as r:
            assert r.headers["Content-Type"].startswith(
                "application/json")
            hdoc = json.load(r)
        assert {"enabled", "ranks", "evict_recommended", "dominator",
                "alerts_total", "window"} <= set(hdoc)
        assert hdoc["enabled"] is True
        for r in map(str, range(n)):
            assert {"state", "state_code", "pressure",
                    "alerts"} <= set(hdoc["ranks"][r])
            assert hdoc["ranks"][r]["state"] == "HEALTHY"
        assert hdoc["evict_recommended"] == []

        # the live CLI view renders one frame from the same endpoint
        assert scope_main(["live", f"127.0.0.1:{master.metrics_port}",
                           "--once"]) == 0
        frame = capsys.readouterr().out
        assert f"{n}/{n} ranks reporting" in frame
        assert "idle after barrier" in frame    # the held job's state

        # unknown paths 404 instead of serving garbage
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(base + "/secrets", timeout=5.0)
    finally:
        release.set()
        for t in threads:
            t.join(20.0)
    assert not errors
    assert not any(t.is_alive() for t in threads)
    master.join(10.0)
    # endpoint shuts down with the master
    with pytest.raises(OSError):
        urllib.request.urlopen(base + "/metrics", timeout=1.0)


def test_metrics_disabled_drops_histograms_only(monkeypatch):
    """MP4J_METRICS=0 (the bench A/B knob) turns observation into a
    no-op while the stats counters keep flowing."""
    monkeypatch.setenv("MP4J_METRICS", "0")
    from ytk_mp4j_tpu.utils.stats import CommStats
    cs = CommStats()
    assert not cs.metrics.enabled
    outermost = cs.begin("allreduce_array")
    cs.add_wire(bytes_sent=100, bytes_recv=100, seconds=0.01)
    cs.end(outermost)
    assert cs.metrics.snapshot()["histograms"] == {}
    assert cs.snapshot()["allreduce_array"]["bytes_sent"] == 100


# ----------------------------------------------------------------------
# flight recorder — chaos acceptance
# ----------------------------------------------------------------------
def test_chaos_kill_survivors_write_postmortem_bundles(tmp_path, capsys):
    """Acceptance: a killed rank yields a COMPLETE postmortem bundle
    from every survivor plus the master manifest, and the merged
    ``mp4j-scope postmortem`` report names the dead rank."""
    pmdir = str(tmp_path / "pm")

    def fn(slave, r):
        arr = np.full(4096, float(r + 1))
        slave.allreduce_array(arr, Operands.DOUBLE, Operators.SUM)
        slave.barrier()
        slave.allreduce_array(arr, Operands.DOUBLE, Operators.SUM)
        return arr

    _, errors, _, log = run_chaos(
        4, fn, fault_plan="kill:rank=2:nth=2", postmortem_dir=pmdir,
        master_kwargs={"postmortem_dir": pmdir})
    assert isinstance(errors[2], FaultKill)
    assert all(isinstance(errors[r], Mp4jFatalError) for r in (0, 1, 3))

    bundles = postmortem.load_bundles(pmdir)
    assert set(bundles) == {0, 1, 3}            # the dead rank left none
    for r in (0, 1, 3):
        b = bundles[r]
        assert not b["torn"], f"rank {r} bundle torn"
        assert b["complete"]["rank"] == r
        assert b["stats"]["rank"] == r
        assert "rank 2" in b["stats"]["reason"]
        assert b["stats"]["progress"]["seq"] >= 1
        assert b["stats"]["stats"]["allreduce_array"]["calls"] >= 1
        # histogram state rode along
        assert any(k.startswith("latency/")
                   for k in b["metrics"]["histograms"])
        # the epoch/retry log recorded the fatal
        kinds = [kind for _, kind, _ in b["recovery"]["events"]]
        assert "fatal" in kinds
        # the Chrome trace is loadable JSON with events
        d = postmortem.bundle_dir(pmdir, r)
        with open(os.path.join(d, "trace.json")) as fh:
            trace_doc = json.load(fh)
        assert trace_doc["traceEvents"]

    with open(os.path.join(pmdir, "manifest.json")) as fh:
        manifest = json.load(fh)
    assert manifest["slave_num"] == 4
    assert "rank 2" in manifest["reason"]
    # the fatal-path telemetry flush landed: the manifest's final table
    # is fresh (every surviving rank's last beat, with its progress)
    assert {"0", "1", "3"} <= set(manifest["table"])

    report = postmortem.merge_report(pmdir)
    assert "DEAD rank 2" in report
    assert "bundles: 3/4 ranks" in report
    assert scope_main(["postmortem", pmdir]) == 0
    out = capsys.readouterr().out
    assert "DEAD rank 2" in out


def test_postmortem_report_tolerates_torn_bundle(tmp_path):
    root = str(tmp_path)
    postmortem.write_bundle(
        root, 0, reason="x", progress={"seq": 3}, stats={},
        metrics={"counters": {}, "gauges": {}, "histograms": {}},
        epoch=1, events=[(0.0, "fatal", "x")])
    # rank 1 died mid-dump: stats.json only, no complete marker
    d = postmortem.bundle_dir(root, 1)
    os.makedirs(d)
    with open(os.path.join(d, "stats.json"), "w") as fh:
        json.dump({"rank": 1, "progress": {"seq": 1}}, fh)
    report = postmortem.merge_report(root)
    assert "rank 1 TORN" in report
    assert "DEAD" not in report.split("TORN")[0].splitlines()[0]


def test_postmortem_dir_empty_means_disabled(tmp_path, monkeypatch):
    monkeypatch.delenv("MP4J_POSTMORTEM_DIR", raising=False)
    assert tuning.postmortem_dir() == ""
    f = tmp_path / "afile"
    f.write_text("x")
    monkeypatch.setenv("MP4J_POSTMORTEM_DIR", str(f))
    with pytest.raises(Mp4jError):
        tuning.postmortem_dir()


# ----------------------------------------------------------------------
# bench-diff — the perf regression gate
# ----------------------------------------------------------------------
def test_bench_diff_on_checked_in_bench_files(capsys):
    """Tier-1 seed of perf regression gating: the two checked-in BENCH
    rounds compare clean (r05 did not regress r04), through the real
    CLI."""
    old = os.path.join(REPO, "BENCH_r04.json")
    new = os.path.join(REPO, "BENCH_r05.json")
    assert os.path.exists(old) and os.path.exists(new)
    assert scope_main(["bench-diff", old, new]) == 0
    out = capsys.readouterr().out
    assert "socket_collective_gbs" in out
    assert "within budget" in out
    assert "REGRESSED" not in out


def test_bench_diff_flags_regression(tmp_path, capsys):
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text(json.dumps({
        "metric": "x", "value": 10.0,
        "extra": {"socket_collective_gbs": 2.0, "not_tracked": 1.0}}))
    new.write_text(json.dumps({
        "parsed": {"metric": "x", "value": 9.7,
                   "extra": {"socket_collective_gbs": 1.0}}}))
    # socket leg halved -> regression past its 20% budget; exit 1
    assert scope_main(["bench-diff", str(old), str(new)]) == 1
    out = capsys.readouterr().out
    assert "REGRESSION" in out and "socket_collective_gbs" in out
    # headline within its 10% budget
    assert re.search(r"value\s+.*\bok\b", out)
    # a blanket threshold override rescues it
    assert scope_main(["bench-diff", str(old), str(new),
                       "--threshold", "60"]) == 0


def test_bench_diff_gates_lint_v3_ratio_growth(tmp_path, capsys):
    """ISSUE 16: the lint v3-over-v2 runtime ratio is a tracked
    LOWER_IS_BETTER row — growth past its budget between bench rounds
    is a regression (the absolute <= 1.5x budget is a tier-1 assert)."""
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text(json.dumps({
        "metric": "x", "value": 10.0,
        "extra": {"lint_v3_over_v2_ratio": 1.2}}))
    new.write_text(json.dumps({
        "metric": "x", "value": 10.0,
        "extra": {"lint_v3_over_v2_ratio": 2.5}}))
    assert scope_main(["bench-diff", str(old), str(new)]) == 1
    out = capsys.readouterr().out
    assert "REGRESSION" in out and "lint_v3_over_v2_ratio" in out
    # same ratio both rounds: within budget
    assert scope_main(["bench-diff", str(old), str(old)]) == 0


def test_bench_diff_rejects_non_bench_document(tmp_path):
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"whatever": 1}))
    with pytest.raises(ValueError):
        benchdiff.load_bench(str(bad))
    assert scope_main(["bench-diff", str(bad), str(bad)]) == 2


def test_bench_diff_missing_metrics_are_skipped_not_errors():
    rows = benchdiff.compare({"value": 1.0},
                             {"value": 1.0, "trees_per_sec": 5.0})
    assert [r["metric"] for r in rows] == ["value"]
    assert rows[0]["verdict"] == "ok"


# ----------------------------------------------------------------------
# knob validation (README knob table contract)
# ----------------------------------------------------------------------
@pytest.mark.parametrize("env,bad", [
    ("MP4J_METRICS", "yes"),
    ("MP4J_METRICS_PORT", "eighty"),
    ("MP4J_METRICS_PORT", "70000"),
    ("MP4J_METRICS_WINDOW_SECS", "0"),
    ("MP4J_METRICS_WINDOW_SECS", "-5"),
])
def test_metrics_knobs_env_validated(env, bad, monkeypatch):
    monkeypatch.setenv(env, bad)
    fn = {"MP4J_METRICS": tuning.metrics_enabled,
          "MP4J_METRICS_PORT": tuning.metrics_port,
          "MP4J_METRICS_WINDOW_SECS": tuning.metrics_window_secs}[env]
    with pytest.raises(Mp4jError):
        fn()


def test_metrics_port_ctor_override_shares_env_validation():
    # the explicit Master(metrics_port=...) path must fail the same
    # clean way the env path does — not a raw socket OverflowError
    with pytest.raises(Mp4jError):
        tuning.metrics_port(override=99999)
    with pytest.raises(Mp4jError):
        Master(2, metrics_port=70000)
    assert tuning.metrics_port(override=0) == 0
    assert tuning.metrics_port(override=8080) == 8080


def test_metrics_knob_defaults(monkeypatch):
    for env in ("MP4J_METRICS", "MP4J_METRICS_PORT",
                "MP4J_METRICS_WINDOW_SECS", "MP4J_POSTMORTEM_DIR"):
        monkeypatch.delenv(env, raising=False)
    assert tuning.metrics_enabled() is True
    assert tuning.metrics_port() is None        # endpoint off by default
    assert tuning.metrics_window_secs() == \
        tuning.DEFAULT_METRICS_WINDOW_SECS
    assert tuning.postmortem_dir() == ""
