"""Durable streaming telemetry sink + critical-path attribution
(ISSUE 9): segment framing and the torn-tail property (truncate at
every byte of the final record), rotation/eviction under the disk
budget, live-job drains, the injected-slow-rank acceptance grid, the
kill chaos case (survivor segments joinable by ``mp4j-scope
analyze``), Prometheus/live rendering of the sink series, the
``analyze``/``tail`` CLI, and knob validation."""

import json
import os
import time

import numpy as np
import pytest

from helpers import run_slaves
from ytk_mp4j_tpu.exceptions import Mp4jError, Mp4jFatalError
from ytk_mp4j_tpu.obs import critpath, metrics, sink, spans, telemetry
from ytk_mp4j_tpu.obs import postmortem
from ytk_mp4j_tpu.obs.cli import main as scope_main
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operators
from ytk_mp4j_tpu.utils import tuning

N = 4


@pytest.fixture
def fresh_spans():
    """Clear the process-global span ring around a test (the thread
    harness shares it across every in-process slave)."""
    spans.clear()
    yield
    spans.clear()


def _allreduce_body(rounds=6, size=50_000):
    def fn(slave, r):
        for _ in range(rounds):
            a = np.ones(size, np.float64) * (r + 1)
            slave.allreduce_array(a, Operands.DOUBLE, Operators.SUM)
        return True
    return fn


# ----------------------------------------------------------------------
# segment framing + torn-tail tolerance
# ----------------------------------------------------------------------
def _write_segment(path, records):
    with open(path, "wb") as fh:
        offs = []
        for rec in records:
            offs.append(fh.tell())
            fh.write(sink.encode_record(rec))
        offs.append(fh.tell())
    return offs           # frame start offsets + final size


def test_record_frame_roundtrip(tmp_path):
    recs = [{"t": "meta", "rank": 0, "seg": 0},
            {"t": "spans", "spans": [["allreduce_array", "collective",
                                      1.5, 0.25, 0, 0, {"seq": 1}]]},
            {"t": "recovery", "epoch": 1, "events": [[0.1, "go", ""]]}]
    p = tmp_path / "seg_00000000.mp4j"
    _write_segment(p, recs)
    got, end, torn = sink.read_segment(str(p))
    assert got == recs
    assert not torn and end == os.path.getsize(p)


def test_torn_tail_at_every_byte_of_final_record(tmp_path):
    """The ISSUE 9 property: truncating a segment at ANY byte offset
    inside the final record loses only that record — the reader
    recovers every prior record, reports exactly one torn tail, and
    never crashes."""
    recs = [{"t": "meta", "rank": 1, "seg": 0},
            {"t": "stats", "delta": {"allreduce_array": {"calls": 3}}},
            {"t": "spans", "spans": [["wire", "phase", 2.0, 0.01, 1, 0,
                                      {"seq": 2, "peer": 0}]]},
            {"t": "recovery", "epoch": 0, "events": []}]
    whole = tmp_path / "whole.mp4j"
    offs = _write_segment(whole, recs)
    start_last, size = offs[-2], offs[-1]
    blob = whole.read_bytes()

    # clean cut exactly at the last frame boundary: no torn tail
    p = tmp_path / "cut.mp4j"
    p.write_bytes(blob[:start_last])
    got, _, torn = sink.read_segment(str(p))
    assert got == recs[:-1] and not torn

    for cut in range(start_last + 1, size):
        p.write_bytes(blob[:cut])
        got, end, torn = sink.read_segment(str(p))
        assert got == recs[:-1], f"cut at {cut} lost intact records"
        assert torn, f"cut at {cut} not reported as torn"
        assert end == start_last   # follow mode resumes at the tear


def test_corrupt_byte_stops_at_the_tear_without_crashing(tmp_path):
    recs = [{"t": "meta", "rank": 0, "seg": 0},
            {"t": "stats", "delta": {"barrier": {"calls": 1}}},
            {"t": "recovery", "epoch": 0, "events": []}]
    p = tmp_path / "seg.mp4j"
    offs = _write_segment(p, recs)
    blob = bytearray(p.read_bytes())
    mid = (offs[1] + offs[2]) // 2       # inside the middle record
    blob[mid] ^= 0xFF
    p.write_bytes(bytes(blob))
    got, _, torn = sink.read_segment(str(p))
    assert got == recs[:1] and torn     # stops at the corrupt frame


def test_oversized_length_field_is_torn_not_allocated(tmp_path):
    p = tmp_path / "seg.mp4j"
    p.write_bytes(sink.MAGIC + (2 ** 31 - 1).to_bytes(4, "little")
                  + b"\0\0\0\0junk")
    got, _, torn = sink.read_segment(str(p))
    assert got == [] and torn


# ----------------------------------------------------------------------
# rotation + eviction under the disk budget
# ----------------------------------------------------------------------
def test_rotation_eviction_never_exceeds_budget(tmp_path, fresh_spans):
    budget = 192 * 1024
    w = sink.SinkWriter(str(tmp_path), 0, slave_num=1,
                        budget_bytes=budget, flush_secs=60.0)
    filler = "x" * 512
    for round_ in range(40):
        for i in range(64):
            spans.record(f"ev{i}", "phase", time.perf_counter(),
                         0.001, 0, {"seq": round_, "pad": filler})
        w.flush()
        total = sum(
            os.path.getsize(os.path.join(w.dir, f))
            for f in os.listdir(w.dir))
        assert total <= budget, f"round {round_}: {total} > {budget}"
    assert w.evicted_segments > 0, "budget never forced an eviction"
    assert w.bytes_written > budget   # wrote far more than retained
    doc = sink.read_rank(w.dir)
    assert doc["segments"] >= 2 and doc["torn"] == 0
    # the survivors are the NEWEST records; every segment re-states
    # identity in its meta record, so eviction loses no metadata
    metas = [r for r in doc["records"] if r["t"] == "meta"]
    assert metas and all(m["rank"] == 0 for m in metas)
    last_spans = [r for r in doc["records"] if r["t"] == "spans"]
    assert last_spans[-1]["spans"][-1][6]["seq"] == 39
    w.close()


def test_single_huge_drain_stays_under_budget(tmp_path, fresh_spans):
    """The budget bound must hold for ANY drain size: one flush over
    a massive backlog streams frame-wise through many segments with
    eviction running between frames — never one oversized write that
    blows past MP4J_SINK_BYTES."""
    budget = 192 * 1024
    prior = spans._capacity
    spans.configure(20_000)
    try:
        w = sink.SinkWriter(str(tmp_path), 0, slave_num=1,
                            budget_bytes=budget, flush_secs=60.0)
        for i in range(20_000):       # ~2 MB of JSON >> budget
            spans.record(f"ev{i}", "phase", 0.0, 0.0, 0,
                         {"seq": i, "pad": "z" * 64})
        w.flush()
        w.close()
    finally:
        spans.configure(prior)
    total = sum(os.path.getsize(os.path.join(w.dir, f))
                for f in os.listdir(w.dir))
    assert total <= budget, f"{total} > {budget}"
    assert w.evicted_segments > 0
    doc = sink.read_rank(w.dir)
    assert doc["torn"] == 0
    # the newest spans survived; the evicted prefix is the oldest
    batches = [r for r in doc["records"] if r["t"] == "spans"]
    assert batches[-1]["spans"][-1][6]["seq"] == 19_999


def test_unserializable_span_arg_degrades_to_repr(tmp_path,
                                                  fresh_spans):
    """An exotic object leaking into span args must degrade to its
    repr, never kill the drain (the sink may not die of a span)."""
    w = sink.SinkWriter(str(tmp_path), 0, slave_num=1,
                        budget_bytes=1 << 20, flush_secs=60.0)
    spans.record("odd", "phase", 0.0, 0.0, 0, {"obj": object()})
    w.flush()
    w.close()
    assert w.last_error is None
    doc = sink.read_rank(w.dir)
    [batch] = [r for r in doc["records"] if r["t"] == "spans"]
    assert "object object" in batch["spans"][0][6]["obj"]


def test_huge_span_backlog_splits_into_readable_frames(tmp_path,
                                                       fresh_spans):
    """One drain over a full default-size span ring must never emit a
    frame the reader would reject as a corrupt header (which discards
    the rest of the segment): span batches split at _SPAN_BATCH."""
    prior = spans._capacity
    spans.configure(3 * sink._SPAN_BATCH)
    try:
        w = sink.SinkWriter(str(tmp_path), 0, slave_num=1,
                            budget_bytes=256 * 1024 * 1024,
                            flush_secs=60.0)
        for i in range(3 * sink._SPAN_BATCH):
            spans.record(f"ev{i}", "phase", 0.0, 0.0, 0,
                         {"seq": i, "pad": "y" * 64})
        w.flush()
        w.close()
    finally:
        spans.configure(prior)
    doc = sink.read_rank(w.dir)
    assert doc["torn"] == 0
    batches = [r for r in doc["records"] if r["t"] == "spans"]
    assert len(batches) == 3
    assert all(len(b["spans"]) <= sink._SPAN_BATCH for b in batches)
    assert sum(len(b["spans"]) for b in batches) == 3 * sink._SPAN_BATCH


def test_idle_sink_quiesces(tmp_path, fresh_spans):
    """An idle job's sink must write NOTHING after its sources drain:
    the sink's own accounting counters are excluded from the metrics
    stream, else each drain's bookkeeping would make the next delta
    non-empty forever and the budget would churn on self-noise."""
    from ytk_mp4j_tpu.obs import metrics as metrics_mod
    from ytk_mp4j_tpu.utils.stats import CommStats

    stats = CommStats()
    w = sink.SinkWriter(str(tmp_path), 0, slave_num=1, stats=stats,
                        budget_bytes=1 << 20, flush_secs=60.0)
    spans.record("ev", "phase", 0.0, 0.001, 0, {"seq": 1})
    stats.add("reduce_seconds", 0.001, bucket="allreduce_array")
    w.flush()
    settled = w.bytes_written
    assert settled > 0
    for _ in range(5):
        w.flush()
    assert w.bytes_written == settled, "idle drains kept writing"
    w.close()
    assert w.bytes_written == settled


def test_short_write_raises_instead_of_tearing_silently():
    class ShortFh:
        def __init__(self):
            self.got = b""
            self.calls = 0

        def write(self, view):
            self.calls += 1
            if self.calls == 1:
                self.got += bytes(view[:3])
                return 3          # short write, no exception
            self.got += bytes(view)
            return len(view)

    fh = ShortFh()
    sink._write_all(fh, b"abcdefgh")
    assert fh.got == b"abcdefgh" and fh.calls == 2

    class StuckFh:
        def write(self, view):
            return 0

    with pytest.raises(OSError):
        sink._write_all(StuckFh(), b"abc")


def test_ring_overflow_drops_are_reported(tmp_path, fresh_spans):
    prior = spans._capacity
    spans.configure(32)
    try:
        w = sink.SinkWriter(str(tmp_path), 0, slave_num=1,
                            budget_bytes=1 << 20, flush_secs=60.0)
        for i in range(200):
            spans.record(f"ev{i}", "phase", 0.0, 0.0, 0, None)
        w.flush()
        assert w.dropped_records == 200 - 32
        w.close()
    finally:
        spans.configure(prior)


# ----------------------------------------------------------------------
# live-job drains + analyze
# ----------------------------------------------------------------------
def test_sink_drains_live_job_and_analyze_attributes(tmp_path,
                                                     fresh_spans):
    d = str(tmp_path / "trail")
    run_slaves(N, _allreduce_body(rounds=6), sink_dir=d)
    job = sink.load_job(d)
    assert sorted(job) == list(range(N))
    for r, doc in job.items():
        kinds = {rec["t"] for rec in doc["records"]}
        assert {"meta", "spans", "stats"} <= kinds
        assert doc["torn"] == 0
        meta = next(rec for rec in doc["records"] if rec["t"] == "meta")
        assert meta["slave_num"] == N and meta["rank"] == r
        # span batches carry only THIS rank's spans (the thread
        # harness shares one process-global ring)
        for rec in doc["records"]:
            if rec["t"] == "spans":
                assert {s[4] for s in rec["spans"]} == {r}
    analysis = critpath.analyze(job)
    # 6 allreduces per rank -> 6 attributable ordinals, all 4 ranks
    assert analysis["ordinals_attributed"] == 6
    assert set(analysis["phase_totals"]) == set(range(N))
    assert sum(e["ordinals"] for e in analysis["dominators"].values()) \
        == 6
    report = critpath.format_report(analysis, d)
    assert "critical-path dominators" in report
    assert "per-phase wait decomposition" in report


def test_analyze_names_injected_slow_rank(tmp_path, fresh_spans):
    """The acceptance grid: a ``slow``-injected rank must be named the
    critical-path dominator for >= 90% of the affected ordinals, with
    per-phase wait attribution and a straggler-onset event."""
    d = str(tmp_path / "trail")
    results = [None] * N
    errors = []
    import threading

    from ytk_mp4j_tpu.comm.master import Master
    from ytk_mp4j_tpu.comm.process_comm import ProcessCommSlave

    master = Master(N, timeout=60.0).serve_in_thread()

    def worker():
        slave = None
        try:
            # 20 ms per injected I/O sleep: an order of magnitude
            # above the scheduling noise a fully loaded 1-core CI
            # host adds to each ~1 ms collective, so the dominance
            # signal survives any suite-neighbor load
            slave = ProcessCommSlave(
                "127.0.0.1", master.port, timeout=60.0, sink_dir=d,
                fault_plan="slow:rank=3:secs=0.02:nth=5")
            fn = _allreduce_body(rounds=16, size=100_000)
            results[slave.rank] = fn(slave, slave.rank)
            slave.close(0)
        except Exception as e:      # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(N)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(90.0)
        assert not t.is_alive(), "slave hung"
    assert not errors, errors

    analysis = critpath.analyze(sink.load_job(d))
    affected = [r for r in analysis["rows"] if r["seq"] >= 5]
    assert affected
    dominated = sum(1 for r in affected if r["dominator"] == 3)
    assert dominated / len(affected) >= 0.9, \
        f"rank 3 dominated only {dominated}/{len(affected)}"
    # per-phase wait attribution present and wire-dominated
    p3 = analysis["phase_totals"][3]
    assert p3["wire"] > 0
    # onset trend names the slow rank
    assert any(ev["rank"] == 3 for ev in analysis["onsets"])
    report = critpath.format_report(analysis, d)
    assert "rank 3" in report


def test_chaos_kill_survivor_segments_joinable(tmp_path, fresh_spans,
                                               monkeypatch):
    """A killed rank leaves survivors whose segments (plus a
    simulated kill-9 torn tail) still join into one ``mp4j-scope
    analyze`` report, and the postmortem report gains the full-job
    durable-sink section."""
    d = str(tmp_path / "trail")
    pmdir = str(tmp_path / "pm")
    monkeypatch.setenv("MP4J_SINK_DIR", d)
    monkeypatch.setenv("MP4J_POSTMORTEM_DIR", pmdir)
    # fast drain cadence so the victim has durable segments BEFORE the
    # kill — like a real long job, where hours of history precede the
    # crash and only the final interval is at risk
    monkeypatch.setenv("MP4J_SINK_FLUSH_SECS", "0.05")
    import threading

    from ytk_mp4j_tpu.comm.master import Master
    from ytk_mp4j_tpu.comm.process_comm import ProcessCommSlave

    master = Master(N, timeout=45.0).serve_in_thread()
    errors: list = [None] * N

    def worker(i):
        slave = None
        try:
            slave = ProcessCommSlave(
                "127.0.0.1", master.port, timeout=45.0,
                dead_rank_secs=20.0,
                fault_plan="kill:rank=2:nth=4")
            for k in range(5):
                a = np.ones(50_000, np.float64)
                slave.allreduce_array(a, Operands.DOUBLE,
                                      Operators.SUM)
                if k == 1:
                    # lockstep + one flush interval: the pre-fault
                    # ordinals reach every rank's segments
                    slave.barrier()
                    time.sleep(0.2)
            slave.close(0)
        except Exception as e:
            errors[slave.rank if slave is not None else i] = e
            if slave is not None:
                try:
                    slave.close(1)
                except Exception:
                    pass

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(N)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60.0)
        assert not t.is_alive(), "rank hung past the join deadline"
    master.join(15.0)
    survivors = [r for r in range(N) if r != 2]
    assert all(isinstance(errors[r], (Mp4jError, Mp4jFatalError))
               for r in survivors), errors

    # simulate the kill -9 artifact: cut rank 2's newest segment
    # mid-frame (the in-process "kill" can't tear a real write)
    segs = sink.list_segments(sink.rank_dir(d, 2))
    assert segs
    with open(segs[-1], "r+b") as fh:
        fh.truncate(os.path.getsize(segs[-1]) - 3)

    job = sink.load_job(d)
    assert sorted(job) == list(range(N))
    assert job[2]["torn"] == 1
    assert all(job[r]["torn"] == 0 for r in survivors)
    analysis = critpath.analyze(job)
    assert analysis["ordinals_attributed"] >= 2   # pre-fault ordinals
    assert scope_main(["analyze", d]) == 0

    # the postmortem report joins the durable history via the
    # manifest's sink_dir pointer
    report = postmortem.merge_report(pmdir)
    assert "DEAD rank 2" in report
    assert "durable sink (full-job history):" in report
    assert "torn tails: rank 2: 1" in report


# ----------------------------------------------------------------------
# critpath units (synthetic timelines)
# ----------------------------------------------------------------------
def _cell(family="allreduce_array", t0=0.0, dur=1.0, wire=0.0,
          reduce=0.0, serialize=0.0, links=None):
    return {"family": family, "t0": t0, "dur": dur,
            "phases": {"wire": wire, "reduce": reduce,
                       "serialize": serialize},
            "links": links or {}}


def test_attribute_late_arrival():
    ordinals = {7: {
        0: _cell(t0=0.0, dur=1.2, wire=1.1),
        1: _cell(t0=1.0, dur=0.2, wire=0.1),
        2: _cell(t0=0.0, dur=1.2, wire=1.1),
    }}
    [row] = critpath.attribute(ordinals)
    assert row["seq"] == 7
    assert row["dominator"] == 1 and row["cause"] == "late-arrival"


def test_attribute_late_arrival_two_ranks():
    """n=2 must still detect a straggler: the lower-median start
    keeps the early rank as the reference (the upper median would
    zero the skew and misread the peer's blocked recv as wire
    blame)."""
    ordinals = {1: {
        0: _cell(t0=0.0, dur=10.2, wire=10.1),
        1: _cell(t0=10.0, dur=0.2, wire=0.1),
    }}
    [row] = critpath.attribute(ordinals)
    assert row["dominator"] == 1 and row["cause"] == "late-arrival"


def test_onset_trend_catches_trailing_window():
    """A straggler whose onset falls in the final < window ordinals
    (the pre-crash degradation) must still emit an onset event."""
    rows = []
    for i in range(1, 102):
        rows.append({"seq": i, "family": "allreduce_array",
                     "start": float(i), "end": i + 0.5, "dur": 0.5,
                     "dominator": 3 if i >= 99 else 0,
                     "cause": "wire", "transport": None, "score": 1.0,
                     "margin": 0.0, "waits": {}})
    # regular window starts (step 2) end at 96, where rank 3 holds
    # only 2/4 of the window — only the appended tail window (start
    # 97: three of four rows) crosses the 75% share
    events = critpath.onset_trend(rows, window=4, share=0.75)
    assert any(e["rank"] == 3 for e in events)


def test_attribute_blamed_peer_link_with_transport():
    link_to_2 = {2: {"secs": 0.9, "transport": "tcp", "bytes": 1000}}
    ordinals = {3: {
        0: _cell(t0=0.0, dur=1.0, wire=0.9, links=dict(link_to_2)),
        1: _cell(t0=0.0, dur=1.0, wire=0.9, links=dict(link_to_2)),
        2: _cell(t0=0.0, dur=1.0, wire=0.95,
                 links={0: {"secs": 0.5, "transport": "tcp",
                            "bytes": 500},
                        1: {"secs": 0.45, "transport": "tcp",
                            "bytes": 500}}),
    }}
    [row] = critpath.attribute(ordinals)
    assert row["dominator"] == 2
    assert row["cause"] == "link->2 over tcp"
    assert row["transport"] == "tcp"


def test_attribute_local_reduce_dominance():
    ordinals = {1: {
        0: _cell(dur=1.0, wire=0.1, reduce=0.8),
        1: _cell(dur=0.4, wire=0.1),
    }}
    [row] = critpath.attribute(ordinals)
    assert row["dominator"] == 0 and row["cause"] == "reduce"


def test_attribute_needs_two_ranks():
    assert critpath.attribute({1: {0: _cell()}}) == []


def test_onset_trend_localizes_the_flip():
    rows = []
    for i in range(1, 81):
        rows.append({"seq": i, "family": "allreduce_array",
                     "start": float(i), "end": i + 0.5, "dur": 0.5,
                     "dominator": 0 if i <= 40 else 3,
                     "cause": "wire", "transport": None, "score": 1.0,
                     "margin": 0.0, "waits": {}})
    events = critpath.onset_trend(rows, window=16, share=0.6)
    r3 = [e for e in events if e["rank"] == 3]
    assert r3, "no onset for the late straggler"
    assert 33 <= r3[0]["onset_seq"] <= 49
    assert r3[0]["onset_wall"] == float(r3[0]["onset_seq"])


# ----------------------------------------------------------------------
# rendering: Prometheus series, live view, CLI
# ----------------------------------------------------------------------
def _doc_with_sink():
    rank = {
        "progress": {"seq": 4, "current": None, "last": "barrier",
                     "phase": None, "current_secs": 0.0},
        "age": 0.2, "stats": {}, "rates": {}, "histograms": {},
        "counters": {"sink/bytes": 2_400_000.0, "sink/records": 12.0,
                     "sink/dropped_records": 2.0},
        "gauges": {"sink/lag_secs": 1.25},
    }
    other = {**rank, "counters": {}, "gauges": {}}
    return {"slave_num": 2, "window_secs": 60.0,
            "ranks": {"0": rank, "1": other},
            "cluster": {"stats": {}, "rates": {}, "histograms": {},
                        "audit": None}}


def test_prometheus_renders_sink_series():
    text = metrics.to_prometheus(_doc_with_sink())
    assert "# TYPE mp4j_sink_bytes_total counter" in text
    assert 'mp4j_sink_bytes_total{rank="0"} 2400000' in text
    assert 'mp4j_sink_bytes_total{rank="cluster"} 2400000' in text
    assert 'mp4j_sink_dropped_records_total{rank="0"} 2' in text
    assert "# TYPE mp4j_sink_lag_seconds gauge" in text
    assert 'mp4j_sink_lag_seconds{rank="0"} 1.25' in text
    # sinkless jobs get NO sink series (absent, not zero-noise)
    doc = _doc_with_sink()
    for r in doc["ranks"].values():
        r["counters"], r["gauges"] = {}, {}
    assert "mp4j_sink" not in metrics.to_prometheus(doc)


def test_live_view_sink_column():
    frame = telemetry.format_live(_doc_with_sink())
    header = frame.splitlines()[1]
    assert "sink" in header
    row0 = next(ln for ln in frame.splitlines() if ln.lstrip()
                .startswith("0 "))
    assert "2.4M!" in row0          # dropping -> flagged
    row1 = next(ln for ln in frame.splitlines() if ln.lstrip()
                .startswith("1 "))
    assert "2.4M" not in row1       # sinkless rank renders "-"


def test_live_view_failing_sink_not_rendered_as_disarmed():
    """A full disk writes zero bytes but drops records — the column
    must flag it, not render the '-' of a disarmed sink."""
    doc = _doc_with_sink()
    doc["ranks"]["0"]["counters"] = {"sink/bytes": 0.0,
                                     "sink/dropped_records": 7.0}
    frame = telemetry.format_live(doc)
    row0 = next(ln for ln in frame.splitlines() if ln.lstrip()
                .startswith("0 "))
    assert "0.0M!" in row0


def test_cli_analyze_json_and_tail_once(tmp_path, fresh_spans, capsys):
    d = str(tmp_path / "trail")
    run_slaves(2, _allreduce_body(rounds=3), sink_dir=d)
    assert scope_main(["analyze", d]) == 0
    out = capsys.readouterr().out
    assert "critical-path report" in out
    assert scope_main(["analyze", d, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["ordinals_attributed"] == 3
    assert scope_main(["tail", d, "--once"]) == 0
    out = capsys.readouterr().out
    assert out.count("gated by rank") == 3


def test_analyze_empty_dir_reports_cleanly(tmp_path, capsys):
    assert scope_main(["analyze", str(tmp_path)]) == 0
    assert "0 attributed" in capsys.readouterr().out


# ----------------------------------------------------------------------
# knobs
# ----------------------------------------------------------------------
def test_sink_knob_validation(tmp_path, monkeypatch):
    monkeypatch.delenv("MP4J_SINK", raising=False)
    assert tuning.sink_enabled() is True
    monkeypatch.setenv("MP4J_SINK", "off")
    assert tuning.sink_enabled() is False
    monkeypatch.setenv("MP4J_SINK", "banana")
    with pytest.raises(Mp4jError):
        tuning.sink_enabled()

    monkeypatch.delenv("MP4J_SINK_DIR", raising=False)
    assert tuning.sink_dir() == ""
    f = tmp_path / "afile"
    f.write_text("x")
    monkeypatch.setenv("MP4J_SINK_DIR", str(f))
    with pytest.raises(Mp4jError):
        tuning.sink_dir()

    monkeypatch.setenv("MP4J_SINK_BYTES", "12")
    with pytest.raises(Mp4jError):
        tuning.sink_bytes()
    monkeypatch.setenv("MP4J_SINK_FLUSH_SECS", "0")
    with pytest.raises(Mp4jError):
        tuning.sink_flush_secs()
    monkeypatch.delenv("MP4J_SINK_BYTES", raising=False)
    assert tuning.sink_bytes() == tuning.DEFAULT_SINK_BYTES


def test_sink_off_knob_disarms_despite_dir(tmp_path, monkeypatch,
                                           fresh_spans):
    monkeypatch.setenv("MP4J_SINK_DIR", str(tmp_path / "trail"))
    monkeypatch.setenv("MP4J_SINK", "off")
    run_slaves(2, _allreduce_body(rounds=2))
    assert not os.path.exists(str(tmp_path / "trail"))
