"""Quantile binning front end (models/binning.py) + GBDTTrainer.predict:
the continuous-features -> bins -> train -> predict consumer flow."""

import numpy as np
import pytest

from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.models.binning import QuantileBinner
from ytk_mp4j_tpu.models.gbdt import GBDTConfig, GBDTTrainer
from ytk_mp4j_tpu.parallel import make_mesh


def test_bins_match_searchsorted(rng):
    N, F, B = 5000, 4, 16
    X = rng.standard_normal((N, F)).astype(np.float32) * [1, 10, 0.1, 3]
    bins = QuantileBinner(B).fit_transform(X, sample=None)
    assert bins.dtype == np.int32
    assert bins.min() >= 0 and bins.max() < B
    binner = QuantileBinner(B).fit(X, sample=None)
    for f in range(F):
        want = np.searchsorted(binner.edges[f], X[:, f], side="right")
        np.testing.assert_array_equal(bins[:, f], want)


def test_bins_are_balanced(rng):
    N, B = 20_000, 8
    X = rng.standard_normal((N, 1)).astype(np.float32)
    bins = QuantileBinner(B).fit_transform(X, sample=None)
    counts = np.bincount(bins[:, 0], minlength=B)
    # quantile edges -> each bucket holds ~N/B
    assert counts.min() > 0.8 * N / B
    assert counts.max() < 1.2 * N / B


def test_errors():
    with pytest.raises(Mp4jError):
        QuantileBinner(1)
    b = QuantileBinner(4)
    with pytest.raises(Mp4jError):
        b.transform(np.zeros((3, 2)))          # not fitted
    b.fit(np.random.default_rng(0).random((100, 2)), sample=None)
    with pytest.raises(Mp4jError):
        b.transform(np.zeros((3, 5)))          # wrong F


def test_nan_handling(rng):
    """NaN rows land in bin 0 (missing bucket), edges fit from finite
    values only, and an all-NaN feature raises."""
    N, B = 4000, 8
    X = rng.standard_normal((N, 2)).astype(np.float32)
    X[::7, 0] = np.nan
    b = QuantileBinner(B).fit(X, sample=None)
    clean = QuantileBinner(B).fit(X[np.isfinite(X[:, 0])], sample=None)
    np.testing.assert_allclose(b.edges[0], clean.edges[0], rtol=1e-6)
    bins = b.transform(X)
    assert (bins[::7, 0] == 0).all()
    assert bins.min() >= 0 and bins.max() < B
    X_bad = X.copy()
    X_bad[:, 1] = np.nan
    with pytest.raises(Mp4jError):
        QuantileBinner(B).fit(X_bad, sample=None)
    # inf sentinels are legal: they fit fine and bin to the top bucket
    X_inf = rng.standard_normal((N, 1)).astype(np.float32)
    X_inf[::3, 0] = np.inf
    bi = QuantileBinner(B).fit(X_inf, sample=None)
    out = bi.transform(X_inf)
    assert (out[::3, 0] == B - 1).all()


def test_save_load_exact_path(rng, tmp_path):
    """save_model must honor the exact path (np.savez normally appends
    .npz) and load_model must rebuild the binner's true granularity."""
    N, F = 200, 3
    X = rng.standard_normal((N, F)).astype(np.float32)
    y = X[:, 0].astype(np.float32)
    binner = QuantileBinner(8).fit(X, sample=None)   # coarser than n_bins
    cfg = GBDTConfig(n_features=F, n_bins=32, depth=2, n_trees=2)
    tr = GBDTTrainer(cfg, mesh=make_mesh(1))
    trees, _ = tr.train(binner.transform(X), y)
    path = str(tmp_path / "model.bin")               # no .npz suffix
    tr.save_model(path, trees, binner=binner)
    cfg2, trees2, binner2 = GBDTTrainer.load_model(path)
    assert binner2.n_bins == 8
    np.testing.assert_allclose(binner2.edges, binner.edges)


def test_predict_proba_extreme_margins_no_overflow(rng):
    """Confidently-signed margins must not overflow the sigmoid."""
    F, B = 2, 4
    cfg = GBDTConfig(n_features=F, n_bins=B, depth=1, n_trees=1,
                     learning_rate=1000.0, loss="logistic")
    tr = GBDTTrainer(cfg, mesh=make_mesh(1))
    # a tree whose leaves are huge margins
    trees = [(np.zeros(1, np.int32), np.zeros(1, np.int32),
              np.zeros(1, np.int32),
              np.array([-500.0, 500.0], np.float32))]
    bins = rng.integers(0, B, (64, F)).astype(np.int32)
    with np.errstate(over="raise"):
        p = tr.predict(bins, trees, proba=True)
    assert np.isfinite(p).all()
    assert ((p >= 0) & (p <= 1)).all()


def test_save_load_roundtrip(rng, tmp_path):
    """train -> save -> load in a fresh trainer -> identical preds on
    new continuous data (the train-then-serve flow)."""
    N, F, B = 1500, 4, 16
    X = rng.standard_normal((N, F)).astype(np.float32)
    y = (X[:, 2] + 0.1 * rng.standard_normal(N)).astype(np.float32)
    binner = QuantileBinner(B).fit(X, sample=None)
    cfg = GBDTConfig(n_features=F, n_bins=B, depth=3, n_trees=4,
                     learning_rate=0.3)
    tr = GBDTTrainer(cfg, mesh=make_mesh(2))
    trees, _ = tr.train(binner.transform(X), y)
    path = str(tmp_path / "model.npz")
    tr.save_model(path, trees, binner=binner)

    cfg2, trees2, binner2 = GBDTTrainer.load_model(path)
    assert cfg2 == cfg
    X_new = rng.standard_normal((200, F)).astype(np.float32)
    serve = GBDTTrainer(cfg2, mesh=make_mesh(1))
    np.testing.assert_allclose(
        serve.predict(binner2.transform(X_new), trees2),
        tr.predict(binner.transform(X_new), trees),
        rtol=1e-6)


def test_continuous_end_to_end(rng):
    """The full ytk-learn-style consumer flow: continuous X -> quantile
    bins -> distributed GBDT -> ensemble predict reproduces the
    training-time predictions."""
    N, F, B = 2000, 5, 32
    X = rng.standard_normal((N, F)).astype(np.float32)
    y = (np.sin(3 * X[:, 0]) + 0.1 * rng.standard_normal(N)).astype(
        np.float32)
    bins = QuantileBinner(B).fit_transform(X, sample=None)
    cfg = GBDTConfig(n_features=F, n_bins=B, depth=4, learning_rate=0.3,
                     n_trees=5)
    tr = GBDTTrainer(cfg, mesh=make_mesh(4))
    trees, train_preds = tr.train(bins, y)
    mse = float(np.mean((train_preds[:N] - y) ** 2))
    assert mse < float(np.var(y)) * 0.5

    preds = tr.predict(bins, trees)
    np.testing.assert_allclose(preds, train_preds[:N], rtol=1e-4,
                               atol=1e-5)
