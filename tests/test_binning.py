"""Quantile binning front end (models/binning.py) + GBDTTrainer.predict:
the continuous-features -> bins -> train -> predict consumer flow."""

import numpy as np
import pytest
pytest.importorskip("hypothesis")  # property tests need hypothesis
from hypothesis import given, settings, strategies as st

from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.models.binning import QuantileBinner
from ytk_mp4j_tpu.models.gbdt import GBDTConfig, GBDTTrainer
from ytk_mp4j_tpu.parallel import make_mesh


def test_bins_match_searchsorted(rng):
    N, F, B = 5000, 4, 16
    X = rng.standard_normal((N, F)).astype(np.float32) * [1, 10, 0.1, 3]
    bins = QuantileBinner(B).fit_transform(X, sample=None)
    assert bins.dtype == np.int32
    assert bins.min() >= 0 and bins.max() < B
    binner = QuantileBinner(B).fit(X, sample=None)
    for f in range(F):
        want = np.searchsorted(binner.edges[f], X[:, f], side="right")
        np.testing.assert_array_equal(bins[:, f], want)


def test_bins_are_balanced(rng):
    N, B = 20_000, 8
    X = rng.standard_normal((N, 1)).astype(np.float32)
    bins = QuantileBinner(B).fit_transform(X, sample=None)
    counts = np.bincount(bins[:, 0], minlength=B)
    # quantile edges -> each bucket holds ~N/B
    assert counts.min() > 0.8 * N / B
    assert counts.max() < 1.2 * N / B


def test_errors():
    with pytest.raises(Mp4jError):
        QuantileBinner(1)
    b = QuantileBinner(4)
    with pytest.raises(Mp4jError):
        b.transform(np.zeros((3, 2)))          # not fitted
    b.fit(np.random.default_rng(0).random((100, 2)), sample=None)
    with pytest.raises(Mp4jError):
        b.transform(np.zeros((3, 5)))          # wrong F


def test_nan_handling(rng):
    """NaN rows land in bin 0 (missing bucket), edges fit from finite
    values only, and an all-NaN feature raises."""
    N, B = 4000, 8
    X = rng.standard_normal((N, 2)).astype(np.float32)
    X[::7, 0] = np.nan
    b = QuantileBinner(B).fit(X, sample=None)
    clean = QuantileBinner(B).fit(X[np.isfinite(X[:, 0])], sample=None)
    np.testing.assert_allclose(b.edges[0], clean.edges[0], rtol=1e-6)
    bins = b.transform(X)
    assert (bins[::7, 0] == 0).all()
    assert bins.min() >= 0 and bins.max() < B
    X_bad = X.copy()
    X_bad[:, 1] = np.nan
    with pytest.raises(Mp4jError):
        QuantileBinner(B).fit(X_bad, sample=None)
    # inf sentinels are legal: they fit fine and bin to the top bucket
    X_inf = rng.standard_normal((N, 1)).astype(np.float32)
    X_inf[::3, 0] = np.inf
    bi = QuantileBinner(B).fit(X_inf, sample=None)
    out = bi.transform(X_inf)
    assert (out[::3, 0] == B - 1).all()


def test_save_load_exact_path(rng, tmp_path):
    """save_model must honor the exact path (np.savez normally appends
    .npz) and load_model must rebuild the binner's true granularity."""
    N, F = 200, 3
    X = rng.standard_normal((N, F)).astype(np.float32)
    y = X[:, 0].astype(np.float32)
    binner = QuantileBinner(8).fit(X, sample=None)   # coarser than n_bins
    cfg = GBDTConfig(n_features=F, n_bins=32, depth=2, n_trees=2)
    tr = GBDTTrainer(cfg, mesh=make_mesh(1))
    trees, _ = tr.train(binner.transform(X), y)
    path = str(tmp_path / "model.bin")               # no .npz suffix
    tr.save_model(path, trees, binner=binner)
    cfg2, trees2, binner2 = GBDTTrainer.load_model(path)
    assert binner2.n_bins == 8
    np.testing.assert_allclose(binner2.edges, binner.edges)


def test_predict_proba_extreme_margins_no_overflow(rng):
    """Confidently-signed margins must not overflow the sigmoid."""
    F, B = 2, 4
    cfg = GBDTConfig(n_features=F, n_bins=B, depth=1, n_trees=1,
                     learning_rate=1000.0, loss="logistic")
    tr = GBDTTrainer(cfg, mesh=make_mesh(1))
    # a tree whose leaves are huge margins
    trees = [(np.zeros(1, np.int32), np.zeros(1, np.int32),
              np.zeros(1, np.int32),
              np.array([-500.0, 500.0], np.float32))]
    bins = rng.integers(0, B, (64, F)).astype(np.int32)
    with np.errstate(over="raise"):
        p = tr.predict(bins, trees, proba=True)
    assert np.isfinite(p).all()
    assert ((p >= 0) & (p <= 1)).all()


def test_save_load_roundtrip(rng, tmp_path):
    """train -> save -> load in a fresh trainer -> identical preds on
    new continuous data (the train-then-serve flow)."""
    N, F, B = 1500, 4, 16
    X = rng.standard_normal((N, F)).astype(np.float32)
    y = (X[:, 2] + 0.1 * rng.standard_normal(N)).astype(np.float32)
    binner = QuantileBinner(B).fit(X, sample=None)
    cfg = GBDTConfig(n_features=F, n_bins=B, depth=3, n_trees=4,
                     learning_rate=0.3)
    tr = GBDTTrainer(cfg, mesh=make_mesh(2))
    trees, _ = tr.train(binner.transform(X), y)
    path = str(tmp_path / "model.npz")
    tr.save_model(path, trees, binner=binner)

    cfg2, trees2, binner2 = GBDTTrainer.load_model(path)
    assert cfg2 == cfg
    X_new = rng.standard_normal((200, F)).astype(np.float32)
    serve = GBDTTrainer(cfg2, mesh=make_mesh(1))
    np.testing.assert_allclose(
        serve.predict(binner2.transform(X_new), trees2),
        tr.predict(binner.transform(X_new), trees),
        rtol=1e-6)


def test_continuous_end_to_end(rng):
    """The full ytk-learn-style consumer flow: continuous X -> quantile
    bins -> distributed GBDT -> ensemble predict reproduces the
    training-time predictions."""
    N, F, B = 2000, 5, 32
    X = rng.standard_normal((N, F)).astype(np.float32)
    y = (np.sin(3 * X[:, 0]) + 0.1 * rng.standard_normal(N)).astype(
        np.float32)
    bins = QuantileBinner(B).fit_transform(X, sample=None)
    cfg = GBDTConfig(n_features=F, n_bins=B, depth=4, learning_rate=0.3,
                     n_trees=5)
    tr = GBDTTrainer(cfg, mesh=make_mesh(4))
    trees, train_preds = tr.train(bins, y)
    mse = float(np.mean((train_preds[:N] - y) ** 2))
    assert mse < float(np.var(y)) * 0.5

    preds = tr.predict(bins, trees)
    np.testing.assert_allclose(preds, train_preds[:N], rtol=1e-4,
                               atol=1e-5)


# ------------------------------------------------------- distributed fit
def _quantile_positions(X, edges):
    """Empirical CDF position of each edge: |F_hat(edge) - target q|
    is the natural error metric for a quantile sketch."""
    F = X.shape[1]
    pos = np.empty_like(edges)
    for f in range(F):
        col = np.sort(X[:, f][np.isfinite(X[:, f])])
        pos[f] = np.searchsorted(col, edges[f], side="right") / len(col)
    return pos


def test_merge_sketches_matches_single_host(rng):
    """Weighted quantile-of-quantiles: merged edges must land within
    2/Q of the target quantile positions (documented tolerance; the
    approximation error is O(1/Q) in quantile space)."""
    N, F, B, R = 40_000, 5, 32, 4
    X = np.stack([
        rng.standard_normal(N),
        rng.lognormal(0.0, 1.0, N),
        rng.uniform(-5, 5, N),
        rng.standard_normal(N) * 100 + 7,
        np.where(rng.random(N) < 0.3, np.nan, rng.standard_normal(N)),
    ], axis=1).astype(np.float32)
    # unequal shard sizes
    cuts = [0, 4_000, 14_000, 27_000, N]
    shards = [X[cuts[i]:cuts[i + 1]] for i in range(R)]

    b = QuantileBinner(B)
    sk = [b.local_sketch(s, sample=None) for s in shards]
    b.merge_sketches(np.stack([s.values for s in sk]),
                     np.stack([s.counts for s in sk]))
    qs = np.arange(1, B) / B
    pos = _quantile_positions(X, b.edges)
    err = np.abs(pos - qs[None, :]).max()
    assert err < 2.0 / B, err
    # and the exact fit passes the same bar much more tightly
    exact = QuantileBinner(B).fit(X, sample=None)
    pos_e = _quantile_positions(X, exact.edges)
    assert np.abs(pos_e - qs[None, :]).max() < err


def test_merge_sketch_feature_missing_on_some_ranks(rng):
    """A feature with data on only one rank must still bin correctly:
    NaN sketches carry zero weight in the merge."""
    B, R = 8, 3
    col = rng.standard_normal(9_000).astype(np.float32)
    shards = []
    for r in range(R):
        s = np.empty((3_000, 2), np.float32)
        s[:, 0] = rng.standard_normal(3_000)
        s[:, 1] = np.nan if r != 1 else col[:3_000]
        shards.append(s)
    b = QuantileBinner(B)
    sk = [b.local_sketch(s, sample=None) for s in shards]
    b.merge_sketches(np.stack([s.values for s in sk]),
                     np.stack([s.counts for s in sk]))
    # feature 1's edges come purely from rank 1's data
    want = QuantileBinner(B).fit(
        shards[1][:, 1:2], sample=None).edges[0]
    np.testing.assert_allclose(b.edges[1], want, rtol=1e-5, atol=1e-5)


def test_merge_sketch_no_data_anywhere_raises():
    b = QuantileBinner(4)            # Q+1 = 5 sketch points
    edges = np.full((2, 1, 5), np.nan, np.float32)
    counts = np.zeros((2, 1), np.float32)
    with pytest.raises(Mp4jError, match="no non-missing"):
        b.merge_sketches(edges, counts)


class _OneRankComm:
    """Minimal comm for exercising fit_distributed single-rank."""
    rank, slave_num = 0, 1

    def allgather_array(self, arr, operand=None, ranges=None):
        return arr


def test_all_inf_feature_raises_like_fit(rng):
    """fit() refuses a feature with no finite values; fit_distributed
    must agree instead of silently producing all-inf edges (ADVICE
    round 3): finite-value evidence rides the sketch wire and
    merge_sketches raises when no rank contributes any."""
    X = np.stack([rng.standard_normal(100).astype(np.float32),
                  np.full(100, np.inf, np.float32)], axis=1)
    with pytest.raises(Mp4jError, match="no finite"):
        QuantileBinner(8).fit(X, sample=None)
    with pytest.raises(Mp4jError, match="no finite"):
        QuantileBinner(8).fit_distributed(X, _OneRankComm(),
                                          sample=None)
    # the low-level merge enforces it whenever the evidence is supplied
    b = QuantileBinner(4)
    sk, c, fin, _ = b.local_sketch(np.full((10, 1), np.inf, np.float32),
                                sample=None)
    assert c[0] == 10          # inf is data: full merge weight kept
    assert fin[0] == 0.0       # ...but it is not finite evidence
    with pytest.raises(Mp4jError, match="no finite"):
        b.merge_sketches(sk[None], c[None], np.zeros((1, 1), np.float32))


def test_sampling_drops_all_finite_rows_still_raises():
    """If row sampling excludes every data row of a feature, the sketch
    is unusable and the distributed fit must refuse like fit() does —
    not silently emit all-inf edges or feed NaN sketch rows into the
    merge. The finite rows are placed OUTSIDE the known sample draw so
    the exclusion is deterministic."""
    N, S, seed = 10_000, 50, 0
    picked = set(np.random.default_rng(seed).choice(N, S, replace=False))
    free = [i for i in range(N) if i not in picked][:3]
    X = np.full((N, 2), np.nan, np.float32)
    X[:, 0] = np.random.default_rng(1).standard_normal(N)
    X[free, 1] = [1.0, 2.0, 3.0]          # data exists, sample misses it
    with pytest.raises(Mp4jError, match="no finite"):
        QuantileBinner(8).fit(X, sample=S, seed=seed)
    with pytest.raises(Mp4jError, match="no"):
        QuantileBinner(8).fit_distributed(X, _OneRankComm(),
                                          sample=S, seed=seed)
    # and the sketch itself reports the feature as weightless
    _, c, fin, _ = QuantileBinner(8).local_sketch(X, sample=S, seed=seed)
    assert c[1] == 0.0 and fin[1] == 0.0
    assert c[0] == N and fin[0] == 1.0


def test_mixed_inf_shard_keeps_inf_mass(rng):
    """An inf-only shard next to a finite shard must still contribute
    its inf mass to the pooled CDF (as its rows would in a single-host
    fit) — the finite-evidence check may not alter merge weights."""
    fin = rng.standard_normal((1000, 1)).astype(np.float32)
    inf = np.full((1000, 1), np.inf, np.float32)
    b = QuantileBinner(8)
    edges = b.fit_distributed(
        np.concatenate([fin, inf]), _OneRankComm(),
        sample=None).edges[0]
    # sanity: single-rank distributed fit == plain fit on the same data
    want = QuantileBinner(8).fit(np.concatenate([fin, inf]),
                                 sample=None).edges[0]
    np.testing.assert_array_equal(np.isinf(edges), np.isinf(want))
    # two-rank merge: half the total mass is inf, so the top edges
    # (quantiles > 1/2) must be inf, and the bottom ones finite
    sk = [b.local_sketch(s, sample=None) for s in (fin, inf)]
    b2 = QuantileBinner(8)
    b2.merge_sketches(np.stack([s.values for s in sk]),
                      np.stack([s.counts for s in sk]),
                      np.asarray([[1.0], [0.0]], np.float32))
    assert np.isinf(b2.edges[0][-2:]).all(), b2.edges
    assert np.isfinite(b2.edges[0][:3]).all(), b2.edges


def test_merge_sketch_edge_count_mismatch_raises():
    b = QuantileBinner(8)            # needs Q+1 = 9 points per feature
    with pytest.raises(Mp4jError):
        b.merge_sketches(np.zeros((2, 1, 3), np.float32),
                         np.ones((2, 1), np.float32))


def test_fit_distributed_over_socket_backend(rng):
    """fit_distributed on the real socket backend: every rank ends with
    identical edges matching the host-side merge of the same shards."""
    from helpers import run_slaves

    N, F, B, R = 8_000, 3, 16, 4
    X = rng.standard_normal((N, F)).astype(np.float32)
    shards = np.array_split(X, R)

    def job(slave, rank):
        binner = QuantileBinner(B).fit_distributed(
            shards[rank], slave, sample=None)
        return binner.edges

    results = run_slaves(R, job)
    for e in results[1:]:
        np.testing.assert_array_equal(e, results[0])
    b = QuantileBinner(B)
    sk = [b.local_sketch(s, sample=None) for s in shards]
    b.merge_sketches(np.stack([s.values for s in sk]),
                     np.stack([s.counts for s in sk]))
    np.testing.assert_allclose(results[0], b.edges, rtol=1e-6, atol=1e-6)


def test_local_sketch_weight_is_full_shard_count(rng):
    """Merge weights must reflect the FULL shard size even when the
    sketch itself is computed on a row sample — otherwise a large
    sampled shard weighs the same as a small unsampled one."""
    X_big = rng.standard_normal((10_000, 2)).astype(np.float32) + 5.0
    X_small = rng.standard_normal((1_000, 2)).astype(np.float32) - 5.0
    b = QuantileBinner(8)
    sk_big, c_big, *_ = b.local_sketch(X_big, sample=500, seed=0)
    sk_small, c_small, *_ = b.local_sketch(X_small, sample=500, seed=0)
    np.testing.assert_array_equal(c_big, [10_000, 10_000])
    np.testing.assert_array_equal(c_small, [1_000, 1_000])
    b.merge_sketches(np.stack([sk_big, sk_small]),
                     np.stack([c_big, c_small]))
    # 10:1 mass -> the median edge must sit in the big shard's mode
    mid = b.edges[0][len(b.edges[0]) // 2]
    assert mid > 3.0, mid


def test_local_sketch_inf_sentinels(rng):
    """inf sentinels are data (as in fit): the sketch stays monotone
    and a single-rank merge keeps the inf top edges."""
    col = np.concatenate([rng.standard_normal(1000).astype(np.float32),
                          np.full(300, np.inf, np.float32)])
    X = col[:, None]
    b = QuantileBinner(8)
    sk, c, fin, _ = b.local_sketch(X, sample=None)
    assert c[0] == 1300 and fin[0] == 1.0
    assert not np.isnan(sk).any()
    assert (sk[0][1:] >= sk[0][:-1]).all(), sk   # inf-safe monotonicity
    b.merge_sketches(sk[None], c[None])
    want = QuantileBinner(8).fit(X, sample=None).edges[0]
    # both must agree on which edges are inf, and on the finite ones
    np.testing.assert_array_equal(np.isinf(b.edges[0]), np.isinf(want))
    f = np.isfinite(want)
    np.testing.assert_allclose(b.edges[0][f], want[f], rtol=1e-5,
                               atol=1e-5)


# ------------------------------------------- sketch-merge property tests
@st.composite
def _shard_sets(draw):
    """Random shard lists: 1-5 shards, 1-3 features, varied sizes and
    scales, optional NaN contamination."""
    R = draw(st.integers(1, 5))
    F = draw(st.integers(1, 3))
    seed = draw(st.integers(0, 2**31 - 1))
    rng = np.random.default_rng(seed)
    shards = []
    for _ in range(R):
        n = draw(st.integers(5, 400))
        s = (rng.standard_normal((n, F)) *
             draw(st.floats(0.1, 100.0)) +
             draw(st.floats(-50.0, 50.0))).astype(np.float32)
        if draw(st.booleans()):
            s[rng.random((n, F)) < 0.2] = np.nan
        shards.append(s)
    # every feature must have data somewhere
    data = np.concatenate(shards)
    for f in range(F):
        if np.isnan(data[:, f]).all():
            shards[0][:, f] = rng.standard_normal(len(shards[0]))
    return shards


@settings(max_examples=30, deadline=None)
@given(_shard_sets(), st.integers(3, 32))
def test_merge_edges_monotone_and_bounded(shards, B):
    """Merged edges are nondecreasing per feature and lie within the
    pooled data's [min, max]."""
    b = QuantileBinner(B)
    sk = [b.local_sketch(s, sample=None) for s in shards]
    b.merge_sketches(np.stack([s.values for s in sk]),
                     np.stack([s.counts for s in sk]))
    data = np.concatenate(shards)
    for f in range(b.edges.shape[0]):
        e = b.edges[f]
        assert (e[1:] >= e[:-1]).all()
        col = data[:, f]
        col = col[~np.isnan(col)]
        assert e[0] >= col.min() - 1e-4
        assert e[-1] <= col.max() + 1e-4


@settings(max_examples=30, deadline=None)
@given(_shard_sets(), st.integers(3, 16), st.integers(0, 2**31 - 1))
def test_merge_is_shard_order_invariant(shards, B, seed):
    """Rank order must not affect the merged edges (the distributed fit
    must give every rank the same answer regardless of rank ids)."""
    b1, b2 = QuantileBinner(B), QuantileBinner(B)
    sk = [b1.local_sketch(s, sample=None) for s in shards]
    edges = np.stack([s.values for s in sk])
    counts = np.stack([s.counts for s in sk])
    perm = np.random.default_rng(seed).permutation(len(shards))
    b1.merge_sketches(edges, counts)
    b2.merge_sketches(edges[perm], counts[perm])
    np.testing.assert_allclose(b1.edges, b2.edges, rtol=1e-6, atol=1e-6)


@settings(max_examples=20, deadline=None)
@given(_shard_sets(), st.integers(3, 16))
def test_single_concatenated_shard_matches_fit(shards, B):
    """A one-shard merge must reproduce fit() on the same data — exact
    for DISTINCT-VALUED data (the _shard_sets strategy draws tie-free
    float32 normals; ties collapse sketch points into CDF jumps whose
    inversion legitimately differs from nanquantile's order-statistic
    interpolation — see test_merge_with_tied_values for what IS
    guaranteed under ties)."""
    data = np.concatenate(shards)
    b = QuantileBinner(B)
    sk, c, *_ = b.local_sketch(data, sample=None)
    b.merge_sketches(sk[None], c[None])
    want = QuantileBinner(B).fit(data, sample=None)
    np.testing.assert_allclose(b.edges, want.edges, rtol=1e-5, atol=1e-5)


def _tie_aware_position_err(col, edges, qs):
    """Distance from each target quantile q to the pooled empirical CDF
    INTERVAL [F(edge-), F(edge)] at the edge — the natural sketch-error
    metric under ties, where a point-position metric would charge an
    edge sitting (correctly) inside a CDF jump for the whole jump."""
    col = np.sort(col[~np.isnan(col)])
    M = col.size
    L = np.searchsorted(col, edges, side="left") / M
    R = np.searchsorted(col, edges, side="right") / M
    return np.maximum(0.0, np.maximum(L - qs, qs - R))


def test_tie_mass_rides_the_merge(rng):
    """90% of the mass in ONE tied value: every internal quantile sits
    strictly inside the jump, so all merged edges must equal the tied
    value exactly — matching fit() — instead of smearing toward the
    tail (the pre-round-4 grid-CDF merge smeared; VERDICT round 3
    item 4)."""
    B, R, N = 8, 3, 9_000
    col = np.where(rng.random(N) < 0.9, 0.0,
                   rng.uniform(1.0, 2.0, N)).astype(np.float32)
    want = QuantileBinner(B).fit(col[:, None], sample=None).edges[0]
    np.testing.assert_array_equal(want, np.zeros(B - 1))  # all qs < .9
    shards = [col[i::R][:, None] for i in range(R)]
    b = QuantileBinner(B)
    sk = [b.local_sketch(s, sample=None) for s in shards]
    b.merge_sketches(np.stack([s.values for s in sk]),
                     np.stack([s.counts for s in sk]),
                     np.stack([s.finite for s in sk]),
                     np.stack([s.cdf for s in sk]))
    np.testing.assert_array_equal(b.edges[0], want)


@st.composite
def _tied_shard_sets(draw):
    """Tie-heavy shards: ~90% of rows land on 5 distinct support
    values, the rest are continuous noise."""
    R = draw(st.integers(1, 4))
    seed = draw(st.integers(0, 2**31 - 1))
    rng = np.random.default_rng(seed)
    support = np.sort(rng.standard_normal(5) * 3).astype(np.float32)
    shards = []
    for _ in range(R):
        n = draw(st.integers(60, 400))
        tied = support[rng.integers(0, 5, n)]
        cont = rng.standard_normal(n).astype(np.float32)
        shards.append(np.where(rng.random(n) < 0.9, tied,
                               cont)[:, None].astype(np.float32))
    return shards


@settings(max_examples=25, deadline=None)
@given(_tied_shard_sets(), st.integers(4, 16))
def test_heavy_ties_position_bound(shards, B):
    """VERDICT round-3 item 4's acceptance: under 90%-mass-in-5-values
    the merged edges must land within 2/Q of the target quantiles in
    POOLED-CDF position (tie-aware: a q inside a jump an edge sits on
    costs 0) — the same documented bound as the continuous case, which
    the pre-round-4 merge could not meet under ties."""
    data = np.concatenate(shards)[:, 0]
    b = QuantileBinner(B)
    sk = [b.local_sketch(s, sample=None) for s in shards]
    b.merge_sketches(np.stack([s.values for s in sk]),
                     np.stack([s.counts for s in sk]),
                     np.stack([s.finite for s in sk]),
                     np.stack([s.cdf for s in sk]))
    qs = np.arange(1, B) / B
    err = _tie_aware_position_err(data, b.edges[0], qs)
    assert err.max() < 2.0 / B, (err, b.edges)
    # the single-host fit clears the same bar (sanity for the metric)
    exact = QuantileBinner(B).fit(data[:, None], sample=None)
    err_fit = _tie_aware_position_err(data, exact.edges[0], qs)
    assert err_fit.max() < 2.0 / B, err_fit


def test_merge_with_tied_values(rng):
    """Heavily tied data (integer-coded / clipped features) collapses
    sketch points into CDF jumps; like any quantile-of-quantiles
    sketch, the merge is then NOT exact against fit() — but it must
    stay well-formed: monotone edges inside [min, max], every edge a
    plausible value, and transform output in range."""
    B, R = 8, 3
    col = rng.integers(0, 5, 9_000).astype(np.float32)   # 5 distinct
    shards = [col[i::R][:, None] for i in range(R)]
    b = QuantileBinner(B)
    sk = [b.local_sketch(s, sample=None) for s in shards]
    b.merge_sketches(np.stack([s.values for s in sk]),
                     np.stack([s.counts for s in sk]))
    e = b.edges[0]
    assert (e[1:] >= e[:-1]).all()
    assert e[0] >= 0.0 and e[-1] <= 4.0
    out = b.transform(col[:, None])
    assert out.min() >= 0 and out.max() < B
    # a constant feature is the degenerate extreme: single-bin output
    const = np.full((600, 1), 7.0, np.float32)
    bc = QuantileBinner(B)
    skc, cc, *_ = bc.local_sketch(const, sample=None)
    bc.merge_sketches(skc[None], cc[None])
    assert len(np.unique(bc.transform(const))) == 1


def test_fit_distributed_over_thread_backend(rng):
    """fit_distributed on the thread backend: the comm duck-type (rank /
    slave_num / allgather_array) spans all three SPMD backends."""
    from ytk_mp4j_tpu.comm.thread_comm import ThreadCommSlave

    from test_thread_comm import run_threads

    N, F, B, R = 6_000, 3, 16, 4
    X = rng.standard_normal((N, F)).astype(np.float32)
    shards = np.array_split(X, R)
    slaves = ThreadCommSlave.spawn_group(R)
    results = run_threads(
        slaves,
        lambda sl, r: QuantileBinner(B).fit_distributed(
            shards[r], sl, sample=None).edges)
    for e in results[1:]:
        np.testing.assert_array_equal(e, results[0])
    b = QuantileBinner(B)
    sk = [b.local_sketch(s, sample=None) for s in shards]
    b.merge_sketches(np.stack([s.values for s in sk]),
                     np.stack([s.counts for s in sk]))
    np.testing.assert_allclose(results[0], b.edges, rtol=1e-6, atol=1e-6)


def test_fit_distributed_config_mismatch_raises(rng):
    """Ranks disagreeing on n_bins must fail loudly, not merge
    garbage: the size pre-exchange catches it before the sketch
    allgather can shear."""
    from helpers import run_slaves

    X = rng.standard_normal((400, 2)).astype(np.float32)
    shards = np.array_split(X, 2)

    def job(slave, rank):
        B = 8 if rank == 0 else 16
        QuantileBinner(B).fit_distributed(shards[rank], slave,
                                          sample=None)

    with pytest.raises(Mp4jError, match="mismatch"):
        run_slaves(2, job)


# ------------------------------------------------- weighted sketches
def test_fit_weighted_matches_numpy_oracle(rng):
    """Weighted fit == numpy's weighted quantiles (inverted_cdf is the
    one method numpy defines weights for; same convention here)."""
    N, F, B = 5_000, 3, 16
    X = np.stack([rng.standard_normal(N),
                  rng.lognormal(0.0, 1.0, N),
                  rng.integers(0, 7, N).astype(np.float64)],
                 axis=1).astype(np.float32)
    w = rng.gamma(0.3, 2.0, N)       # heavily skewed weights
    b = QuantileBinner(B).fit(X, sample=None, sample_weight=w)
    qs = np.arange(1, B) / B
    for f in range(F):
        want = np.quantile(X[:, f].astype(np.float64), qs,
                           method="inverted_cdf", weights=w)
        np.testing.assert_allclose(b.edges[f], want, rtol=1e-6,
                                   atol=1e-6)


def test_fit_weighted_integer_weights_equal_duplication(rng):
    """Integer weights must bin exactly like physically duplicated
    rows (the defining property of weighted quantiles), including
    heavy ties."""
    N, B = 800, 8
    X = rng.integers(0, 5, (N, 2)).astype(np.float32)   # many ties
    k = rng.integers(1, 6, N)
    b_w = QuantileBinner(B).fit(X, sample=None,
                                sample_weight=k.astype(np.float64))
    b_d = QuantileBinner(B).fit(np.repeat(X, k, axis=0), sample=None,
                                sample_weight=np.ones(int(k.sum())))
    np.testing.assert_array_equal(b_w.edges, b_d.edges)


def test_weighted_sketch_single_rank_merge_matches_weighted_fit(rng):
    """A one-shard weighted merge reproduces the weighted fit exactly
    for distinct-valued data (the ordinates land on the grid, so the
    inversion hits every quantile point)."""
    N, B = 4_000, 16
    X = rng.standard_normal((N, 2)).astype(np.float32)
    w = rng.gamma(1.0, 1.0, N)
    b = QuantileBinner(B)
    sk = b.local_sketch(X, sample=None, sample_weight=w)
    b.merge_sketches(sk.values[None], sk.counts[None],
                     sk.finite[None], cdf_stack=sk.cdf[None])
    want = QuantileBinner(B).fit(X, sample=None, sample_weight=w)
    np.testing.assert_allclose(b.edges, want.edges, rtol=1e-5,
                               atol=1e-5)


def test_weighted_sketch_merge_skewed_shards(rng):
    """Pooled weighted merge across shards with SKEWED weights: edges
    must land within the documented 2/Q of the pooled weighted
    quantile positions; a tied value holding ~90% of the total WEIGHT
    (not rows) must capture every internal edge exactly."""
    B, R = 16, 3
    qs = np.arange(1, B) / B
    # continuous case, weights concentrated on one shard
    shards = [rng.standard_normal((3_000, 1)).astype(np.float32) + r
              for r in range(R)]
    weights = [np.full(3_000, 10.0 ** r) for r in range(R)]
    b = QuantileBinner(B)
    sk = [b.local_sketch(s, sample=None, sample_weight=w)
          for s, w in zip(shards, weights)]
    b.merge_sketches(np.stack([s.values for s in sk]),
                     np.stack([s.counts for s in sk]),
                     np.stack([s.finite for s in sk]),
                     cdf_stack=np.stack([s.cdf for s in sk]))
    pooled = np.concatenate(shards)[:, 0].astype(np.float64)
    pw = np.concatenate(weights)
    want = np.quantile(pooled, qs, method="inverted_cdf", weights=pw)
    # position error in WEIGHTED quantile space
    o = np.argsort(pooled)
    cw = np.cumsum(pw[o]) / pw.sum()
    for e, q in zip(b.edges[0], qs):
        lo = np.searchsorted(pooled[o], e, side="left")
        hi = np.searchsorted(pooled[o], e, side="right")
        fl = cw[lo - 1] if lo > 0 else 0.0
        fr = cw[hi - 1] if hi > 0 else 0.0
        err = max(0.0, max(fl - q, q - fr))
        assert err < 2.0 / B, (e, q, err, want)
    # heavy-tie-by-weight case: one row value owns 99% of the total
    # weight, so every internal quantile of a B=16 binner lands
    # strictly inside its CDF jump
    vals = rng.standard_normal((1_000, 1)).astype(np.float32)
    vals[0, 0] = 0.5
    w = np.ones(1_000)
    w[0] = 99_000.0
    halves = [(vals[:500], w[:500]), (vals[500:], w[500:])]
    b2 = QuantileBinner(B)
    sk2 = [b2.local_sketch(s, sample=None, sample_weight=ww)
           for s, ww in halves]
    b2.merge_sketches(np.stack([s.values for s in sk2]),
                      np.stack([s.counts for s in sk2]),
                      np.stack([s.finite for s in sk2]),
                      cdf_stack=np.stack([s.cdf for s in sk2]))
    # every internal quantile (1/B..15/16) falls inside the 90% jump
    assert (b2.edges[0] == np.float32(0.5)).all(), b2.edges[0]


def test_weighted_fit_distributed_matches_weighted_fit(rng):
    """fit_distributed with per-rank weights pools to the weighted fit
    (single-rank comm: exact; the multi-rank path shares the same
    merge, covered by the skewed-shard test above)."""
    X = rng.standard_normal((2_000, 2)).astype(np.float32)
    w = rng.gamma(1.0, 1.0, 2_000)
    b = QuantileBinner(8).fit_distributed(X, _OneRankComm(),
                                          sample=None, sample_weight=w)
    want = QuantileBinner(8).fit(X, sample=None, sample_weight=w)
    np.testing.assert_allclose(b.edges, want.edges, rtol=1e-5,
                               atol=1e-5)


def test_weighted_fit_distributed_multirank_socket(rng):
    """Weighted fit_distributed over REAL socket slaves: per-rank
    weighted shards pool to job-identical edges within the pooled
    weighted-quantile tolerance."""
    from helpers import run_slaves

    B, R = 8, 3
    X = rng.standard_normal((3_000, 2)).astype(np.float32)
    w = rng.gamma(0.7, 1.0, 3_000)
    cuts = [0, 600, 1_800, 3_000]

    def job(slave, rank):
        s = slice(cuts[rank], cuts[rank + 1])
        return QuantileBinner(B).fit_distributed(
            X[s], slave, sample=None, sample_weight=w[s]).edges

    results = run_slaves(R, job)
    for e in results[1:]:
        np.testing.assert_array_equal(e, results[0])
    qs = np.arange(1, B) / B
    pooled = X[:, 0].astype(np.float64)
    o = np.argsort(pooled)
    cw = np.cumsum(w[o]) / w.sum()
    for e, q in zip(results[0][0], qs):
        lo = np.searchsorted(pooled[o], e, side="left")
        hi = np.searchsorted(pooled[o], e, side="right")
        fl = cw[lo - 1] if lo > 0 else 0.0
        fr = cw[hi - 1] if hi > 0 else 0.0
        assert max(0.0, max(fl - q, q - fr)) < 2.0 / B


def test_weight_validation_errors(rng):
    X = rng.standard_normal((10, 2)).astype(np.float32)
    b = QuantileBinner(4)
    with pytest.raises(Mp4jError, match="sample_weight"):
        b.fit(X, sample_weight=np.ones(5))
    with pytest.raises(Mp4jError, match="finite and non-negative"):
        b.fit(X, sample_weight=-np.ones(10))
    with pytest.raises(Mp4jError, match="finite and non-negative"):
        b.fit(X, sample_weight=np.full(10, np.nan))
    # zero-weight rows carry no evidence: a feature whose only finite
    # values have weight 0 must raise like an all-NaN feature
    X2 = np.stack([np.arange(10, dtype=np.float32),
                   np.full(10, np.nan, np.float32)], axis=1)
    X2[:3, 1] = 1.0
    w = np.ones(10)
    w[:3] = 0.0
    with pytest.raises(Mp4jError, match="no\nfinite values|no finite"):
        b.fit(X2, sample_weight=w)
