"""Quantile binning front end (models/binning.py) + GBDTTrainer.predict:
the continuous-features -> bins -> train -> predict consumer flow."""

import numpy as np
import pytest

from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.models.binning import QuantileBinner
from ytk_mp4j_tpu.models.gbdt import GBDTConfig, GBDTTrainer
from ytk_mp4j_tpu.parallel import make_mesh


def test_bins_match_searchsorted(rng):
    N, F, B = 5000, 4, 16
    X = rng.standard_normal((N, F)).astype(np.float32) * [1, 10, 0.1, 3]
    bins = QuantileBinner(B).fit_transform(X, sample=None)
    assert bins.dtype == np.int32
    assert bins.min() >= 0 and bins.max() < B
    binner = QuantileBinner(B).fit(X, sample=None)
    for f in range(F):
        want = np.searchsorted(binner.edges[f], X[:, f], side="right")
        np.testing.assert_array_equal(bins[:, f], want)


def test_bins_are_balanced(rng):
    N, B = 20_000, 8
    X = rng.standard_normal((N, 1)).astype(np.float32)
    bins = QuantileBinner(B).fit_transform(X, sample=None)
    counts = np.bincount(bins[:, 0], minlength=B)
    # quantile edges -> each bucket holds ~N/B
    assert counts.min() > 0.8 * N / B
    assert counts.max() < 1.2 * N / B


def test_errors():
    with pytest.raises(Mp4jError):
        QuantileBinner(1)
    b = QuantileBinner(4)
    with pytest.raises(Mp4jError):
        b.transform(np.zeros((3, 2)))          # not fitted
    b.fit(np.random.default_rng(0).random((100, 2)), sample=None)
    with pytest.raises(Mp4jError):
        b.transform(np.zeros((3, 5)))          # wrong F


def test_continuous_end_to_end(rng):
    """The full ytk-learn-style consumer flow: continuous X -> quantile
    bins -> distributed GBDT -> ensemble predict reproduces the
    training-time predictions."""
    N, F, B = 2000, 5, 32
    X = rng.standard_normal((N, F)).astype(np.float32)
    y = (np.sin(3 * X[:, 0]) + 0.1 * rng.standard_normal(N)).astype(
        np.float32)
    bins = QuantileBinner(B).fit_transform(X, sample=None)
    cfg = GBDTConfig(n_features=F, n_bins=B, depth=4, learning_rate=0.3,
                     n_trees=5)
    tr = GBDTTrainer(cfg, mesh=make_mesh(4))
    trees, train_preds = tr.train(bins, y)
    mse = float(np.mean((train_preds[:N] - y) ** 2))
    assert mse < float(np.var(y)) * 0.5

    preds = tr.predict(bins, trees)
    np.testing.assert_allclose(preds, train_preds[:N], rtol=1e-4,
                               atol=1e-5)
