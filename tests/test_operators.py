import numpy as np
import pytest

from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.operators import Operator, Operators
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.utils import native


ALL_OPS = [Operators.SUM, Operators.PROD, Operators.MAX, Operators.MIN]
NP_REF = {
    "SUM": np.add,
    "PROD": np.multiply,
    "MAX": np.maximum,
    "MIN": np.minimum,
}


@pytest.mark.parametrize("op", ALL_OPS, ids=lambda o: o.name)
@pytest.mark.parametrize("operand", Operands.NUMERIC, ids=lambda o: o.name)
def test_identity(op, operand):
    ident = op.identity(operand.dtype)
    x = np.array([3, 1, 2], dtype=operand.dtype)
    got = op.np_fn(np.full_like(x, ident), x)
    np.testing.assert_array_equal(got, x)


@pytest.mark.parametrize("op", ALL_OPS, ids=lambda o: o.name)
@pytest.mark.parametrize("operand", Operands.NUMERIC, ids=lambda o: o.name)
def test_native_reduce_matches_numpy(op, operand, rng):
    if operand.dtype.kind == "f":
        a = rng.standard_normal(257).astype(operand.dtype)
        b = rng.standard_normal(257).astype(operand.dtype)
    else:
        a = rng.integers(1, 5, 257).astype(operand.dtype)
        b = rng.integers(1, 5, 257).astype(operand.dtype)
    expect = NP_REF[op.name](a, b)
    acc = a.copy()
    native.reduce_into(op, acc, b)
    np.testing.assert_array_equal(acc, expect)


def test_native_backend_is_active():
    # The image has g++; the C++ hot loop must actually be in use.
    native._load()
    assert native.HAVE_NATIVE


def test_custom_operator():
    absmax = Operator.custom("ABSMAX",
                             lambda x, y: np.where(np.abs(x) >= np.abs(y), x, y),
                             0.0)
    a = np.array([-5.0, 1.0, 2.0])
    b = np.array([3.0, -4.0, -1.0])
    got = absmax(a, b)
    np.testing.assert_array_equal(got, [-5.0, -4.0, 2.0])
    acc = a.copy()
    native.reduce_into(absmax, acc, b)  # falls back to np_fn
    np.testing.assert_array_equal(acc, [-5.0, -4.0, 2.0])


def test_by_name():
    assert Operators.by_name("sum") is Operators.SUM
    with pytest.raises(Mp4jError):
        Operators.by_name("nope")
