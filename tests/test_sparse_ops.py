"""Device-level sparse collective ops (ops.sparse) on the virtual mesh."""

from functools import partial

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from ytk_mp4j_tpu.utils.compat import shard_map  # jax-version compat import
from jax.sharding import PartitionSpec as P

from ytk_mp4j_tpu.operators import Operator, Operators
from ytk_mp4j_tpu.ops import sparse as sp
from ytk_mp4j_tpu.parallel import make_mesh


def run_sparse_allreduce(per_rank, capacity, operator, vshape=()):
    """per_rank: list of (idx list, val list) per rank."""
    n = len(per_rank)
    mesh = make_mesh(n)
    Lmax = max(len(i) for i, _ in per_rank)
    idx = np.full((n, Lmax), sp.SENTINEL, dtype=np.int32)
    ident = operator.identity(np.float64)
    val = np.full((n, Lmax) + vshape, ident, dtype=np.float64)
    for r, (ii, vv) in enumerate(per_rank):
        for j, (i, v) in enumerate(zip(ii, vv)):
            idx[r, j] = i
            val[r, j] = v

    @jax.jit
    @partial(shard_map, mesh=mesh, check_vma=False,
             in_specs=(P("mp4j"), P("mp4j")),
             out_specs=(P(None), P(None)))
    def f(i, v):
        return sp.sparse_allreduce(i[0], v[0], capacity, operator, "mp4j")

    oi, ov = f(idx, val)
    return np.asarray(oi), np.asarray(ov)


def test_sparse_allreduce_sum_union():
    per_rank = [([1, 5, 9], [1.0, 2.0, 3.0]),
                ([5, 7], [10.0, 20.0]),
                ([1, 9, 11], [100.0, 200.0, 300.0])]
    oi, ov = run_sparse_allreduce(per_rank, capacity=8, operator=Operators.SUM)
    got = {int(i): float(v) for i, v in zip(oi, ov) if i != sp.SENTINEL}
    assert got == {1: 101.0, 5: 12.0, 7: 20.0, 9: 203.0, 11: 300.0}


def test_sparse_allreduce_exact_capacity():
    # union exactly fills capacity; sentinel segment must be dropped
    per_rank = [([0, 1], [1.0, 2.0]), ([2, 3], [3.0, 4.0])]
    oi, ov = run_sparse_allreduce(per_rank, capacity=4,
                                  operator=Operators.SUM)
    got = {int(i): float(v) for i, v in zip(oi, ov) if i != sp.SENTINEL}
    assert got == {0: 1.0, 1: 2.0, 2: 3.0, 3: 4.0}


def test_sparse_allreduce_max():
    per_rank = [([3, 4], [5.0, -2.0]), ([3, 6], [1.0, 9.0])]
    oi, ov = run_sparse_allreduce(per_rank, capacity=4,
                                  operator=Operators.MAX)
    got = {int(i): float(v) for i, v in zip(oi, ov) if i != sp.SENTINEL}
    assert got == {3: 5.0, 4: -2.0, 6: 9.0}


def test_sparse_allreduce_custom_operator():
    absmax = Operator.custom(
        "ABSMAX", lambda x, y: jnp.where(jnp.abs(x) >= jnp.abs(y), x, y),
        0.0)
    per_rank = [([0, 2], [-5.0, 1.0]), ([0, 2], [3.0, -4.0]),
                ([7], [2.0])]
    oi, ov = run_sparse_allreduce(per_rank, capacity=4, operator=absmax)
    got = {int(i): float(v) for i, v in zip(oi, ov) if i != sp.SENTINEL}
    assert got == {0: -5.0, 2: -4.0, 7: 2.0}


def test_sparse_allreduce_vector_values():
    per_rank = [([2], [[1.0, 2.0]]), ([2, 4], [[10.0, 20.0], [5.0, 6.0]])]
    oi, ov = run_sparse_allreduce(per_rank, capacity=4,
                                  operator=Operators.SUM, vshape=(2,))
    got = {int(i): list(v) for i, v in zip(oi, ov) if i != sp.SENTINEL}
    assert got == {2: [11.0, 22.0], 4: [5.0, 6.0]}


def test_block_owner_matches_meta():
    from ytk_mp4j_tpu import meta

    for size, n in ((10, 3), (8, 8), (7, 8), (100, 4), (5, 2)):
        codes = jnp.arange(size, dtype=jnp.int32)
        got = np.asarray(jax.jit(
            lambda c: sp.block_owner(c, size, n))(codes))
        want = [meta.owner_of(i, 0, size, n) for i in range(size)]
        np.testing.assert_array_equal(got, want)
    # sentinel / out-of-range codes map to n (maskable)
    codes = jnp.array([sp.SENTINEL, -1, 10], dtype=jnp.int32)
    got = np.asarray(sp.block_owner(codes, 10, 4))
    np.testing.assert_array_equal(got, [4, 4, 4])


def _stage_per_rank(per_rank, vshape=()):
    n = len(per_rank)
    Lmax = max(len(i) for i, _ in per_rank)
    idx = np.full((n, Lmax), sp.SENTINEL, dtype=np.int32)
    val = np.zeros((n, Lmax) + vshape, dtype=np.float64)
    for r, (ii, vv) in enumerate(per_rank):
        for j, (i, v) in enumerate(zip(ii, vv)):
            idx[r, j] = i
            val[r, j] = v
    return idx, val


@pytest.mark.parametrize("n,size,capacity", [(4, 20, 32), (8, 13, 16),
                                             (3, 7, 8)])
def test_sparse_reduce_scatter(n, size, capacity, rng):
    """Each member ends with exactly its block-owned share of the
    reduced union, packed ascending; shares are disjoint and cover the
    union. ``capacity >= size`` bounds the union like real callers do."""
    from ytk_mp4j_tpu import meta

    per_rank = []
    for r in range(n):
        k = int(rng.integers(1, size))
        ii = sorted(rng.choice(size, k, replace=False).tolist())
        per_rank.append((ii, [float(r * 100 + i) for i in ii]))
    idx, val = _stage_per_rank(per_rank)
    mesh = make_mesh(n)

    @jax.jit
    @partial(shard_map, mesh=mesh, check_vma=False,
             in_specs=(P("mp4j"), P("mp4j")),
             out_specs=(P("mp4j"), P("mp4j")))
    def f(i, v):
        oi, ov = sp.sparse_reduce_scatter(i[0], v[0], capacity, size,
                                          Operators.SUM, "mp4j")
        return oi[None], ov[None]

    oi, ov = map(np.asarray, f(idx, val))
    want = {}
    for ii, vv in per_rank:
        for i, v in zip(ii, vv):
            want[i] = want.get(i, 0.0) + v
    seen = {}
    for r in range(n):
        live = oi[r] != sp.SENTINEL
        codes = oi[r][live]
        assert (np.diff(codes) > 0).all()       # ascending, deduped
        for c, v in zip(codes, ov[r][live]):
            assert meta.owner_of(int(c), 0, size, n) == r
            assert int(c) not in seen           # disjoint shares
            seen[int(c)] = float(v)
    assert seen == want


def test_sparse_allgather():
    per_rank = [([5, 9], [1.0, 2.0]),
                ([1], [3.0]),
                ([5, 7], [4.0, 5.0])]   # 5 duplicates across members
    idx, val = _stage_per_rank(per_rank)
    mesh = make_mesh(3)

    @jax.jit
    @partial(shard_map, mesh=mesh, check_vma=False,
             in_specs=(P("mp4j"), P("mp4j")),
             out_specs=(P(None), P(None)))
    def f(i, v):
        return sp.sparse_allgather(i[0], v[0], "mp4j")

    oi, ov = map(np.asarray, f(idx, val))
    live = oi != sp.SENTINEL
    pairs = sorted(zip(oi[live].tolist(), ov[live].tolist()))
    assert pairs == [(1, 3.0), (5, 1.0), (5, 4.0), (7, 5.0), (9, 2.0)]
    # sentinel padding sits at the end
    assert not live[live.argmin():].any() or live.all()


def test_sparse_allgather_then_reduce_is_allreduce():
    """The documented composition: allgather + segment_reduce_sorted
    == sparse_allreduce."""
    per_rank = [([2, 4], [1.0, 2.0]), ([2, 6], [10.0, 20.0])]
    idx, val = _stage_per_rank(per_rank)
    mesh = make_mesh(2)

    @jax.jit
    @partial(shard_map, mesh=mesh, check_vma=False,
             in_specs=(P("mp4j"), P("mp4j")),
             out_specs=(P(None), P(None)))
    def f(i, v):
        gi, gv = sp.sparse_allgather(i[0], v[0], "mp4j")
        return sp.segment_reduce_sorted(gi, gv, 4, Operators.SUM)

    oi, ov = map(np.asarray, f(idx, val))
    got = {int(i): float(v) for i, v in zip(oi, ov) if i != sp.SENTINEL}
    assert got == {2: 11.0, 4: 2.0, 6: 20.0}


def test_sparse_to_dense():
    idx = jnp.array([0, 3, sp.SENTINEL], dtype=jnp.int32)
    val = jnp.array([1.5, 2.5, 99.0])
    out = sp.sparse_to_dense(idx, val, 5)
    np.testing.assert_allclose(np.asarray(out), [1.5, 0, 0, 2.5, 0])


def test_pad_to():
    idx = jnp.array([4, 2], dtype=jnp.int32)
    val = jnp.array([1.0, 2.0])
    pi, pv = sp.pad_to(idx, val, 5, Operators.PROD)
    assert pi.shape == (5,) and pv.shape == (5,)
    assert int(pi[4]) == sp.SENTINEL
    assert float(pv[3]) == 1.0  # PROD identity
    with pytest.raises(ValueError):
        sp.pad_to(idx, val, 1)


def test_sort_by_key_wide_payload_fallback(rng):
    """Payload rows wider than _MAX_SORT_PAYLOAD_COLS take the
    argsort+gather fallback; results must match the sort-network path's
    contract exactly (pairs preserved, keys ascending)."""
    L, W = 64, sp._MAX_SORT_PAYLOAD_COLS + 2
    idx = rng.integers(0, 30, L).astype(np.int32)
    val = rng.standard_normal((L, W)).astype(np.float32)
    si, sv = jax.jit(sp.sort_by_key)(jnp.asarray(idx), jnp.asarray(val))
    si, sv = np.asarray(si), np.asarray(sv)
    assert (si[1:] >= si[:-1]).all()
    order = np.argsort(idx, kind="stable")
    np.testing.assert_array_equal(si, idx[order])
    np.testing.assert_array_equal(sv, val[order])


def test_sparse_allreduce_wide_vector_values(rng):
    """Map-of-arrays operands wider than the sort-payload cutoff ride
    the fallback inside sparse_allreduce; differential vs numpy."""
    W = sp._MAX_SORT_PAYLOAD_COLS + 5
    v0 = rng.standard_normal(W)
    v1 = rng.standard_normal(W)
    v2 = rng.standard_normal(W)
    per_rank = [([3], [v0]), ([3, 1], [v1, v2])]
    oi, ov = run_sparse_allreduce(per_rank, capacity=4,
                                  operator=Operators.SUM, vshape=(W,))
    got = {int(i): v for i, v in zip(oi, ov) if i != sp.SENTINEL}
    assert set(got) == {1, 3}
    np.testing.assert_allclose(got[3], v0 + v1, rtol=1e-6)
    np.testing.assert_allclose(got[1], v2, rtol=1e-6)


def test_custom_operator_shadowing_builtin_name():
    """A user operator NAMED like a builtin must run its own fn through
    the generic segment reduction — not silently inherit segment_max
    (round-4 review regression: the reducer table was keyed by name)."""
    absmax = Operator.custom(
        "MAX", lambda a, b: jnp.where(jnp.abs(a) >= jnp.abs(b), a, b),
        0.0)
    per_rank = [([3], [-5.0]), ([3], [3.0])]
    oi, ov = run_sparse_allreduce(per_rank, capacity=2, operator=absmax)
    got = {int(i): float(v) for i, v in zip(oi, ov) if i != sp.SENTINEL}
    assert got == {3: -5.0}, got          # builtin MAX would say 3.0
