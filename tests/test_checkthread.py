"""The checkthread distributed check program (reference check-suite
shape, thread family): standalone thread group in-process, plus a true
hybrid run — 1 master + 2 slave processes x 2 threads over loopback."""

import subprocess
import sys

import pytest

from ytk_mp4j_tpu.check import checkthread
from ytk_mp4j_tpu.comm.master import Master

from helpers import REPO_ROOT


def test_checkthread_standalone():
    """Pure-thread job (no master): the whole battery in-process."""
    assert checkthread.main(["--threads", "3", "--length", "40"]) == 0


def test_checkthread_single_thread():
    assert checkthread.main(["--threads", "1", "--length", "17"]) == 0


@pytest.mark.slow
def test_checkthread_hybrid_subprocess():
    master = Master(2, timeout=60.0).serve_in_thread()
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "ytk_mp4j_tpu.check.checkthread",
             "--master", f"127.0.0.1:{master.port}", "--threads", "2",
             "--length", "53"],
            cwd=REPO_ROOT,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for _ in range(2)
    ]
    for p in procs:
        out, err = p.communicate(timeout=240)
        assert p.returncode == 0, f"checkthread failed:\n{out}\n{err}"
    master.join(10)
    assert master.final_code == 0
