"""STRING / OBJECT operands (host-only path, SURVEY.md section 7 phase 4).

Dense "arrays" of strings/objects are Python lists travelling pickled
over the socket path (the Kryo analogue); the TPU backend rejects them
with a clear error. Reduction requires an explicit (user) operator, as in
the reference's user-defined-operator interfaces.
"""

import numpy as np
import pytest

from ytk_mp4j_tpu.comm.tpu_comm import TpuCommCluster
from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operator, Operators

from helpers import run_slaves

CONCAT = Operator.custom("CONCAT", lambda a, b: a + b, "")


def test_string_allreduce_concat():
    n = 3
    alls = [[f"r{r}a", f"r{r}b", f"r{r}c", f"r{r}d"] for r in range(n)]

    def fn(slave, r):
        arr = list(alls[r])
        slave.allreduce_array(arr, Operands.STRING, CONCAT)
        return arr

    want = ["r0ar1ar2a", "r0br1br2b", "r0cr1cr2c", "r0dr1dr2d"]
    for got in run_slaves(n, fn):
        assert got == want


def test_string_broadcast_and_allgather():
    n = 4
    alls = [[f"s{r}-{i}" for i in range(8)] for r in range(n)]

    def fn(slave, r):
        arr = list(alls[r])
        slave.broadcast_array(arr, Operands.STRING, root=2)
        b = list(arr)
        arr2 = list(alls[r])
        slave.allgather_array(arr2, Operands.STRING)
        return b, arr2

    from ytk_mp4j_tpu import meta
    ranges = meta.partition_range(0, 8, n)
    want_ag = []
    for q, (s, e) in enumerate(ranges):
        want_ag.extend(alls[q][s:e])
    for b, ag in run_slaves(n, fn):
        assert b == alls[2]
        assert ag == want_ag


def test_object_operand_reduce():
    n = 3
    # objects: sets, merged with union
    union_op = Operator.custom("UNION", lambda a, b: a | b, frozenset())
    alls = [[{f"x{r}"}, {f"y{r}"}] for r in range(n)]

    def fn(slave, r):
        arr = [set(s) for s in alls[r]]
        slave.reduce_array(arr, Operands.OBJECT_OPERAND(), union_op, root=0)
        return arr

    res = run_slaves(n, fn)
    assert res[0] == [{"x0", "x1", "x2"}, {"y0", "y1", "y2"}]


def test_string_map_socket():
    n = 3
    maps = [{f"k{r}": f"v{r}"} for r in range(n)]

    def fn(slave, r):
        d = dict(maps[r])
        slave.allreduce_map(d, Operands.STRING, CONCAT)
        return d

    want = {"k0": "v0", "k1": "v1", "k2": "v2"}
    for got in run_slaves(n, fn):
        assert got == want


def test_string_rejected_on_tpu_path():
    cluster = TpuCommCluster(2)
    with pytest.raises(Mp4jError):
        cluster.allreduce_array([np.zeros(2, np.float32)] * 2,
                                Operands.STRING, Operators.SUM)
