"""Multi-host (jax.distributed) backend tests.

"Multi-node without a cluster" at the process level: N real OS
processes, each a jax.distributed participant with its own virtual CPU
devices, joined through a loopback coordinator — the DCN-scale analogue
of the socket tests' master+slaves shape."""

import socket
import subprocess
import sys

import pytest

from ytk_mp4j_tpu.comm.distributed import DistributedComm

from helpers import REPO_ROOT


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_single_process_fallback():
    """Without jax.distributed, the comm degrades to 1 rank and every
    collective is an in-place no-op."""
    import numpy as np

    from ytk_mp4j_tpu.operands import Operands
    from ytk_mp4j_tpu.operators import Operators

    comm = DistributedComm()
    assert comm.slave_num >= 1
    if comm.slave_num == 1:
        arr = np.arange(5, dtype=np.float32)
        comm.allreduce_array(arr, Operands.FLOAT, Operators.SUM)
        np.testing.assert_array_equal(arr, np.arange(5, dtype=np.float32))
        d = {"a": 1.0}
        comm.allreduce_map(d)
        assert d == {"a": 1.0}


def test_device_reduce_verdict_agreed_job_wide(monkeypatch):
    """If the local MAX/MIN probe verdicts differ across ranks (TTL
    timing, per-host env overrides), every rank must still pick the SAME
    path: verdicts are exchanged once over the always-safe path and
    AND-ed, then cached on the comm (ADVICE round 3, medium)."""
    import numpy as np

    import jax
    from jax.sharding import Mesh

    from ytk_mp4j_tpu.operators import Operators
    from ytk_mp4j_tpu.ops import collectives as coll

    comm = DistributedComm.__new__(DistributedComm)
    comm._rank, comm._n, comm._closed = 0, 3, False
    comm._djits, comm._agreed_native = {}, {}
    comm._pmesh = Mesh(np.asarray(jax.devices()[:1]), ("proc",))

    monkeypatch.setattr(coll, "resolve_native_reduce",
                        lambda operator, devices=None: True)
    definitive = {"v": True}
    monkeypatch.setattr(coll, "native_reduce_definitive",
                        lambda kind, devices=None: definitive["v"])
    exchanges = []

    def fake_exchange(obj):
        exchanges.append(obj)
        return [obj, (False, True), (True, True)]  # rank 1 disagrees

    comm._exchange_obj = fake_exchange

    # local probe said True, but the job-wide AND must win
    assert comm._device_reduce_ok(Operators.MAX) is False
    assert exchanges == [(True, True)]
    # all ranks definitive: pinned, no second exchange
    assert comm._device_reduce_ok(Operators.MAX) is False
    assert exchanges == [(True, True)]
    # SUM needs no probe and never exchanges
    assert comm._device_reduce_ok(Operators.SUM) is True
    assert exchanges == [(True, True)]
    # PROD has no device reducer at all
    assert comm._device_reduce_ok(Operators.PROD) is False


def test_device_reduce_transient_verdict_not_pinned(monkeypatch):
    """A transient probe verdict (optimistic True, not definitive) must
    NOT be pinned job-wide: each call re-exchanges until every rank's
    verdict is definitive, so a backend whose first probes hit infra
    errors can still fall back to the host path later."""
    import numpy as np

    import jax
    from jax.sharding import Mesh

    from ytk_mp4j_tpu.operators import Operators
    from ytk_mp4j_tpu.ops import collectives as coll

    comm = DistributedComm.__new__(DistributedComm)
    comm._rank, comm._n, comm._closed = 0, 2, False
    comm._djits, comm._agreed_native = {}, {}
    comm._pmesh = Mesh(np.asarray(jax.devices()[:1]), ("proc",))

    state = {"verdict": True, "definitive": False}
    monkeypatch.setattr(coll, "resolve_native_reduce",
                        lambda operator, devices=None: state["verdict"])
    monkeypatch.setattr(coll, "native_reduce_definitive",
                        lambda kind, devices=None: state["definitive"])
    exchanges = []

    def fake_exchange(obj):
        exchanges.append(obj)
        return [obj, obj]  # peer agrees with us

    comm._exchange_obj = fake_exchange

    assert comm._device_reduce_ok(Operators.MIN) is True
    assert comm._device_reduce_ok(Operators.MIN) is True
    assert len(exchanges) == 2          # transient: re-exchanged
    assert comm._agreed_native == {}    # and never pinned
    # probe finally lands a definitive rejection -> pinned False
    state.update(verdict=False, definitive=True)
    assert comm._device_reduce_ok(Operators.MIN) is False
    assert comm._agreed_native == {"pmin": False}
    assert comm._device_reduce_ok(Operators.MIN) is False
    assert len(exchanges) == 3


def test_device_reduce_rejects_shadowing_custom_operator():
    """A custom operator NAMED "MAX"/"SUM" must never take the native
    device-reduce path — even after the builtin pinned its verdict
    (ADVICE round 4, medium: the gate and the pin were keyed by
    operator.name, so the custom inherited lax.pmax)."""
    import numpy as np

    import jax
    from jax.sharding import Mesh

    from ytk_mp4j_tpu.operators import Operator, Operators

    comm = DistributedComm.__new__(DistributedComm)
    comm._rank, comm._n, comm._closed = 0, 2, False
    comm._djits = {}
    # builtin MAX already pinned native job-wide
    comm._agreed_native = {"pmax": True}
    comm._pmesh = Mesh(np.asarray(jax.devices()[:1]), ("proc",))

    absmax = Operator.custom(
        "MAX", lambda a, b: np.where(np.abs(a) >= np.abs(b), a, b), 0.0)
    assert comm._device_reduce_ok(Operators.MAX) is True
    assert comm._device_reduce_ok(absmax) is False
    fake_sum = Operator.custom("SUM", lambda a, b: a, 0.0)
    assert comm._device_reduce_ok(fake_sum) is False


def test_reduce_scatter_shadowing_custom_sum_goes_host_path():
    """reduce_scatter_array routed custom operators named "SUM" onto
    psum_scatter (name equality); the gate is now object identity and
    the custom's own fn must decide the result."""
    import numpy as np

    from ytk_mp4j_tpu.operands import Operands
    from ytk_mp4j_tpu.operators import Operator, Operators

    comm = DistributedComm.__new__(DistributedComm)
    comm._rank, comm._n, comm._closed = 0, 2, False
    comm._djits, comm._agreed_native = {}, {}

    device_calls = []
    comm._device_rows_collective = (
        lambda kind, block, lax_name:
        device_calls.append((kind, lax_name)) or block)
    # two ranks: ours and a peer row of all 10s
    comm._allgather_rows = lambda row: np.stack(
        [row, np.full_like(row, 10.0)])

    first = Operator.custom("SUM", lambda a, b: a, 0.0)  # keeps first
    arr = np.arange(4, dtype=np.float32)
    out = comm.reduce_scatter_array(arr.copy(), Operands.FLOAT, first)
    assert device_calls == []           # builtin psum_scatter NOT taken
    np.testing.assert_array_equal(out[:2], arr[:2])  # fn: keep ours

    # and the real builtin still rides the device plane
    comm.reduce_scatter_array(arr.copy(), Operands.FLOAT, Operators.SUM)
    assert device_calls == [("reduce_scatter", "psum")]


@pytest.mark.slow
@pytest.mark.parametrize("procs", [2, 3])
def test_checkdist_multiprocess(procs):
    # feature-detect (ISSUE 7 satellite): checkdist's subprocess needs
    # a jax whose CPU backend runs MULTIPROCESS computations. The
    # `jax_num_cpu_devices` config arrived alongside that support —
    # on older jax (this image) the XLA flag equivalent yields local
    # devices but cross-process CPU collectives still raise
    # "Multiprocess computations aren't implemented on the CPU
    # backend", so the whole flow must skip, not fail.
    import jax

    if not hasattr(jax.config, "jax_num_cpu_devices"):
        pytest.skip("this jax lacks jax_num_cpu_devices / multiprocess "
                    "CPU computations; checkdist multiprocess needs a "
                    "newer jax")
    port = _free_port()
    workers = [
        subprocess.Popen(
            [sys.executable, "-m", "ytk_mp4j_tpu.check.checkdist",
             "--coordinator", f"127.0.0.1:{port}",
             "--num-processes", str(procs), "--process-id", str(i),
             "--local-devices", "2", "--length", "53"],
            cwd=REPO_ROOT,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for i in range(procs)
    ]
    for p in workers:
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, f"checkdist failed:\n{out}\n{err}"
