"""Multi-host (jax.distributed) backend tests.

"Multi-node without a cluster" at the process level: N real OS
processes, each a jax.distributed participant with its own virtual CPU
devices, joined through a loopback coordinator — the DCN-scale analogue
of the socket tests' master+slaves shape."""

import socket
import subprocess
import sys

import pytest

from ytk_mp4j_tpu.comm.distributed import DistributedComm

from helpers import REPO_ROOT


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_single_process_fallback():
    """Without jax.distributed, the comm degrades to 1 rank and every
    collective is an in-place no-op."""
    import numpy as np

    from ytk_mp4j_tpu.operands import Operands
    from ytk_mp4j_tpu.operators import Operators

    comm = DistributedComm()
    assert comm.slave_num >= 1
    if comm.slave_num == 1:
        arr = np.arange(5, dtype=np.float32)
        comm.allreduce_array(arr, Operands.FLOAT, Operators.SUM)
        np.testing.assert_array_equal(arr, np.arange(5, dtype=np.float32))
        d = {"a": 1.0}
        comm.allreduce_map(d)
        assert d == {"a": 1.0}


@pytest.mark.slow
@pytest.mark.parametrize("procs", [2, 3])
def test_checkdist_multiprocess(procs):
    port = _free_port()
    workers = [
        subprocess.Popen(
            [sys.executable, "-m", "ytk_mp4j_tpu.check.checkdist",
             "--coordinator", f"127.0.0.1:{port}",
             "--num-processes", str(procs), "--process-id", str(i),
             "--local-devices", "2", "--length", "53"],
            cwd=REPO_ROOT,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for i in range(procs)
    ]
    for p in workers:
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, f"checkdist failed:\n{out}\n{err}"
