"""Multi-host (jax.distributed) backend tests.

"Multi-node without a cluster" at the process level: N real OS
processes, each a jax.distributed participant with its own virtual CPU
devices, joined through a loopback coordinator — the DCN-scale analogue
of the socket tests' master+slaves shape."""

import socket
import subprocess
import sys

import pytest

from ytk_mp4j_tpu.comm.distributed import DistributedComm

from helpers import REPO_ROOT


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_single_process_fallback():
    """Without jax.distributed, the comm degrades to 1 rank and every
    collective is an in-place no-op."""
    import numpy as np

    from ytk_mp4j_tpu.operands import Operands
    from ytk_mp4j_tpu.operators import Operators

    comm = DistributedComm()
    assert comm.slave_num >= 1
    if comm.slave_num == 1:
        arr = np.arange(5, dtype=np.float32)
        comm.allreduce_array(arr, Operands.FLOAT, Operators.SUM)
        np.testing.assert_array_equal(arr, np.arange(5, dtype=np.float32))
        d = {"a": 1.0}
        comm.allreduce_map(d)
        assert d == {"a": 1.0}


def test_device_reduce_verdict_agreed_job_wide(monkeypatch):
    """If the local MAX/MIN probe verdicts differ across ranks (TTL
    timing, per-host env overrides), every rank must still pick the SAME
    path: verdicts are exchanged once over the always-safe path and
    AND-ed, then cached on the comm (ADVICE round 3, medium)."""
    import numpy as np

    import jax
    from jax.sharding import Mesh

    from ytk_mp4j_tpu.operators import Operators
    from ytk_mp4j_tpu.ops import collectives as coll

    comm = DistributedComm.__new__(DistributedComm)
    comm._rank, comm._n, comm._closed = 0, 3, False
    comm._djits, comm._agreed_native = {}, {}
    comm._pmesh = Mesh(np.asarray(jax.devices()[:1]), ("proc",))

    monkeypatch.setattr(coll, "resolve_native_reduce",
                        lambda operator, devices=None: True)
    definitive = {"v": True}
    monkeypatch.setattr(coll, "native_reduce_definitive",
                        lambda kind, devices=None: definitive["v"])
    exchanges = []

    def fake_exchange(obj):
        exchanges.append(obj)
        return [obj, (False, True), (True, True)]  # rank 1 disagrees

    comm._exchange_obj = fake_exchange

    # local probe said True, but the job-wide AND must win
    assert comm._device_reduce_ok(Operators.MAX) is False
    assert exchanges == [(True, True)]
    # all ranks definitive: pinned, no second exchange
    assert comm._device_reduce_ok(Operators.MAX) is False
    assert exchanges == [(True, True)]
    # SUM needs no probe and never exchanges
    assert comm._device_reduce_ok(Operators.SUM) is True
    assert exchanges == [(True, True)]
    # PROD has no device reducer at all
    assert comm._device_reduce_ok(Operators.PROD) is False


def test_device_reduce_transient_verdict_not_pinned(monkeypatch):
    """A transient probe verdict (optimistic True, not definitive) must
    NOT be pinned job-wide: each call re-exchanges until every rank's
    verdict is definitive, so a backend whose first probes hit infra
    errors can still fall back to the host path later."""
    import numpy as np

    import jax
    from jax.sharding import Mesh

    from ytk_mp4j_tpu.operators import Operators
    from ytk_mp4j_tpu.ops import collectives as coll

    comm = DistributedComm.__new__(DistributedComm)
    comm._rank, comm._n, comm._closed = 0, 2, False
    comm._djits, comm._agreed_native = {}, {}
    comm._pmesh = Mesh(np.asarray(jax.devices()[:1]), ("proc",))

    state = {"verdict": True, "definitive": False}
    monkeypatch.setattr(coll, "resolve_native_reduce",
                        lambda operator, devices=None: state["verdict"])
    monkeypatch.setattr(coll, "native_reduce_definitive",
                        lambda kind, devices=None: state["definitive"])
    exchanges = []

    def fake_exchange(obj):
        exchanges.append(obj)
        return [obj, obj]  # peer agrees with us

    comm._exchange_obj = fake_exchange

    assert comm._device_reduce_ok(Operators.MIN) is True
    assert comm._device_reduce_ok(Operators.MIN) is True
    assert len(exchanges) == 2          # transient: re-exchanged
    assert comm._agreed_native == {}    # and never pinned
    # probe finally lands a definitive rejection -> pinned False
    state.update(verdict=False, definitive=True)
    assert comm._device_reduce_ok(Operators.MIN) is False
    assert comm._agreed_native == {"MIN": False}
    assert comm._device_reduce_ok(Operators.MIN) is False
    assert len(exchanges) == 3


@pytest.mark.slow
@pytest.mark.parametrize("procs", [2, 3])
def test_checkdist_multiprocess(procs):
    port = _free_port()
    workers = [
        subprocess.Popen(
            [sys.executable, "-m", "ytk_mp4j_tpu.check.checkdist",
             "--coordinator", f"127.0.0.1:{port}",
             "--num-processes", str(procs), "--process-id", str(i),
             "--local-devices", "2", "--length", "53"],
            cwd=REPO_ROOT,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for i in range(procs)
    ]
    for p in workers:
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, f"checkdist failed:\n{out}\n{err}"
