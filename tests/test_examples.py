"""Every shipped example must actually run on the virtual 8-device pod.

Examples are documentation that executes; letting them rot is worse
than not having them (this file exists because example 01's custom
operator used host-only np functions, which only ever worked on
single-device runs where the device tree-reduce is a no-op)."""

import os
import subprocess
import sys
from pathlib import Path

import pytest

EXAMPLES = sorted((Path(__file__).parents[1] / "examples").glob("*.py"))


@pytest.mark.parametrize("path", EXAMPLES, ids=lambda p: p.name)
def test_example_runs(path):
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    prog = (
        "import jax; jax.config.update('jax_platforms', 'cpu'); "
        "jax.config.update('jax_enable_x64', True); "
        f"exec(open({str(path)!r}).read())"
    )
    r = subprocess.run([sys.executable, "-c", prog], env=env,
                       capture_output=True, text=True, timeout=280,
                       cwd=str(path.parents[1]))
    assert r.returncode == 0, (path.name, r.stderr[-2000:])
