"""GBDT north-star workload: distributed histogram build + allreduce +
tree training over the virtual mesh, checked against a numpy oracle."""

import numpy as np
import pytest

import jax.numpy as jnp

from ytk_mp4j_tpu.models.gbdt import (
    GBDTConfig, GBDTTrainer, best_splits, build_histograms, predict_tree,
    train_tree_shard,
)
from ytk_mp4j_tpu.parallel import make_mesh, make_hier_mesh


def np_histograms(bins, g, h, node_ids, n_nodes, F, B):
    hg = np.zeros((n_nodes, F, B), np.float32)
    hh = np.zeros((n_nodes, F, B), np.float32)
    for i in range(bins.shape[0]):
        for f in range(F):
            hg[node_ids[i], f, bins[i, f]] += g[i]
            hh[node_ids[i], f, bins[i, f]] += h[i]
    return hg, hh


def test_histograms_match_numpy(rng):
    N, F, B = 200, 5, 8
    cfg = GBDTConfig(n_features=F, n_bins=B)
    bins = rng.integers(0, B, (N, F)).astype(np.int32)
    g = rng.standard_normal(N).astype(np.float32)
    h = np.ones(N, np.float32)
    node_ids = rng.integers(0, 4, N).astype(np.int32)
    hg, hh = build_histograms(jnp.array(bins), jnp.array(g), jnp.array(h),
                              jnp.array(node_ids), 4, cfg)
    want_g, want_h = np_histograms(bins, g, h, node_ids, 4, F, B)
    np.testing.assert_allclose(np.asarray(hg), want_g, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(hh), want_h, rtol=1e-4, atol=1e-4)


def test_hist_strategies_agree(rng):
    """The pair-packed scatter and one-hot matmul strategies (see the
    TPU performance note in models/gbdt.py) must match flat and numpy —
    N=1500 > _MATMUL_TILE also exercises the matmul path's
    non-tile-multiple padding (T=2 tiles, 548 pad rows)."""
    N, F, B = 1500, 6, 8
    bins = rng.integers(0, B, (N, F)).astype(np.int32)
    g = rng.standard_normal(N).astype(np.float32)
    h = np.ones(N, np.float32)
    node_ids = rng.integers(0, 4, N).astype(np.int32)
    outs = {}
    for mode in ("pallas", "matmul", "pair", "flat"):
        cfg = GBDTConfig(n_features=F, n_bins=B, hist_mode=mode)
        outs[mode] = build_histograms(
            jnp.array(bins), jnp.array(g), jnp.array(h),
            jnp.array(node_ids), 4, cfg)
    want_g, want_h = np_histograms(bins, g, h, node_ids, 4, F, B)
    for mode in ("pallas", "matmul", "pair", "flat"):
        np.testing.assert_allclose(np.asarray(outs[mode][0]), want_g,
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(outs[mode][1]), want_h,
                                   rtol=1e-4, atol=1e-4)


def test_logistic_objective_fits_and_matches_distributed(rng):
    """Binary-classification GBDT (the reference's Higgs objective):
    logloss falls below the base rate and the distributed run matches
    single-device."""
    N, F, B = 2048, 5, 16
    bins = rng.integers(0, B, (N, F)).astype(np.int32)
    y = (bins[:, 1] > B // 2).astype(np.float32)
    cfg = GBDTConfig(n_features=F, n_bins=B, depth=3, learning_rate=0.3,
                     n_trees=5, loss="logistic")

    dist = GBDTTrainer(cfg, mesh=make_mesh(4))
    trees, margins = dist.train(bins, y)
    p = dist.predict(bins, trees, proba=True)
    eps = 1e-7
    logloss = -np.mean(y * np.log(p + eps) + (1 - y) * np.log(1 - p + eps))
    base = y.mean()
    base_ll = -(base * np.log(base) + (1 - base) * np.log(1 - base))
    assert logloss < base_ll * 0.5
    assert ((p > 0.5) == (y > 0.5)).mean() > 0.95

    single = GBDTTrainer(cfg, mesh=make_mesh(1))
    trees_s, margins_s = single.train(bins, y)
    np.testing.assert_allclose(margins[:N], margins_s[:N], rtol=1e-4,
                               atol=1e-5)


def test_bad_loss_rejected():
    from ytk_mp4j_tpu.exceptions import Mp4jError
    with pytest.raises(Mp4jError):
        GBDTConfig(loss="hinge")
    with pytest.raises(Mp4jError):
        GBDTConfig(loss="softmax", n_classes=1)


def test_eval_set_and_early_stopping(rng):
    """Validation metric falls while signal is being learned; on pure
    noise, early stopping truncates the ensemble to the best round."""
    N, F, B = 2048, 4, 16
    bins = rng.integers(0, B, (N, F)).astype(np.int32)
    y = (bins[:, 0] / B + 0.05 * rng.standard_normal(N)).astype(np.float32)
    va_bins = rng.integers(0, B, (512, F)).astype(np.int32)
    va_y = (va_bins[:, 0] / B
            + 0.05 * rng.standard_normal(512)).astype(np.float32)
    cfg = GBDTConfig(n_features=F, n_bins=B, depth=3, n_trees=8,
                     learning_rate=0.4)
    tr = GBDTTrainer(cfg, mesh=make_mesh(4))
    trees, _ = tr.train(bins, y, eval_set=(va_bins, va_y))
    hist = tr.eval_history_
    assert len(hist) == 8
    assert hist[-1] < hist[0] * 0.5          # metric improves on signal
    # incremental margins == full re-predict
    np.testing.assert_allclose(
        tr._eval_metric(tr.predict(va_bins, trees), va_y), hist[-1],
        rtol=1e-5)

    # pure-noise labels: stops early, truncates to the best round, and
    # the returned margins match the truncated ensemble
    y_noise = rng.standard_normal(N).astype(np.float32)
    va_noise = rng.standard_normal(512).astype(np.float32)
    tr2 = GBDTTrainer(cfg, mesh=make_mesh(4))
    trees2, margins2 = tr2.train(bins, y_noise,
                                 eval_set=(va_bins, va_noise),
                                 early_stopping_rounds=2)
    assert len(trees2) < 8
    best = int(np.argmin(tr2.eval_history_))
    assert len(trees2) == best + 1
    np.testing.assert_allclose(margins2[:N], tr2.predict(bins, trees2),
                               rtol=1e-5, atol=1e-6)

    from ytk_mp4j_tpu.exceptions import Mp4jError
    with pytest.raises(Mp4jError):
        tr2.train(bins, y, early_stopping_rounds=3)   # no eval_set


def test_sample_weight_and_importance(rng):
    """Instance weights steer training (a heavily-weighted subset
    dominates); feature importance concentrates on the signal feature."""
    N, F, B = 2048, 4, 16
    bins = rng.integers(0, B, (N, F)).astype(np.int32)
    # two conflicting signals: feature 0 for the first half, feature 1
    # for the second; weights make the second half dominate
    y = np.where(np.arange(N) < N // 2,
                 (bins[:, 0] / B), (bins[:, 1] / B)).astype(np.float32)
    w = np.where(np.arange(N) < N // 2, 1e-3, 1.0).astype(np.float32)
    cfg = GBDTConfig(n_features=F, n_bins=B, depth=3, n_trees=4,
                     learning_rate=0.3)
    tr = GBDTTrainer(cfg, mesh=make_mesh(4))
    trees, _ = tr.train(bins, y, sample_weight=w)
    imp = tr.feature_importance(trees)
    assert imp.shape == (F,)
    assert abs(imp.sum() - 1.0) < 1e-9
    assert imp[1] > imp[0], imp       # weighted half's feature dominates

    # phantom splits from empty/pure nodes must not count: with signal
    # only on feature 3 and a deep tree, no importance leaks to feat 0
    bins2 = rng.integers(0, 4, (8, F)).astype(np.int32)
    y2 = (bins2[:, 3] > 1).astype(np.float32)
    cfg2 = GBDTConfig(n_features=F, n_bins=4, depth=5, n_trees=1)
    tr2 = GBDTTrainer(cfg2, mesh=make_mesh(1))
    trees2, _ = tr2.train(bins2, y2)
    imp2 = tr2.feature_importance(trees2)
    assert imp2[3] == 1.0, imp2

    from ytk_mp4j_tpu.exceptions import Mp4jError
    with pytest.raises(Mp4jError):
        tr.train(bins, y, sample_weight=np.ones(N - 1, np.float32))


def test_split_regularization_thresholds(rng):
    """min_split_gain freezes below-threshold nodes (all samples route
    left); min_child_hessian disqualifies tiny-child splits; both still
    train and an absurd min_split_gain yields single-leaf trees."""
    N, F, B = 1024, 4, 16
    bins = rng.integers(0, B, (N, F)).astype(np.int32)
    y = (bins[:, 0] / B + 0.05 * rng.standard_normal(N)).astype(np.float32)

    cfg = GBDTConfig(n_features=F, n_bins=B, depth=3, n_trees=3,
                     learning_rate=0.3, min_split_gain=1e9)
    tr = GBDTTrainer(cfg, mesh=make_mesh(2))
    trees, preds = tr.train(bins, y)
    # every node frozen -> all samples share one leaf -> the tree output
    # is constant = learning_rate * global mean correction
    for t in trees:
        bins_arr = np.asarray(t[1])
        assert (bins_arr == B - 1).all()

    cfg2 = GBDTConfig(n_features=F, n_bins=B, depth=3, n_trees=4,
                      learning_rate=0.3, min_split_gain=1e-4,
                      min_child_hessian=2.0)
    tr2 = GBDTTrainer(cfg2, mesh=make_mesh(2))
    _, preds2 = tr2.train(bins, y)
    mse = float(np.mean((preds2[:N] - y) ** 2))
    assert mse < float(np.var(y)) * 0.5

    # min_child_hessian ALONE (min_split_gain=0): a node where every
    # candidate is disqualified must freeze, not split at feat 0/bin 0
    cfg3 = GBDTConfig(n_features=F, n_bins=B, depth=6, n_trees=1,
                      learning_rate=0.3, min_child_hessian=float(N))
    trees3, _ = GBDTTrainer(cfg3, mesh=make_mesh(1)).train(bins, y)
    # no split can satisfy both children >= N hessian -> all frozen
    assert (np.asarray(trees3[0][1]) == B - 1).all()


def test_stochastic_boosting(rng):
    """subsample/colsample < 1: training still fits, is deterministic
    under a fixed seed, and varies with the seed."""
    N, F, B = 2048, 6, 16
    bins = rng.integers(0, B, (N, F)).astype(np.int32)
    y = (bins[:, 0] / B + 0.05 * rng.standard_normal(N)).astype(np.float32)
    cfg = GBDTConfig(n_features=F, n_bins=B, depth=3, learning_rate=0.3,
                     n_trees=6, subsample=0.7, colsample=0.7)
    tr = GBDTTrainer(cfg, mesh=make_mesh(4))
    trees_a, preds_a = tr.train(bins, y, seed=0)
    mse = float(np.mean((preds_a[:N] - y) ** 2))
    assert mse < float(np.var(y)) * 0.5

    tr2 = GBDTTrainer(cfg, mesh=make_mesh(4))
    trees_b, preds_b = tr2.train(bins, y, seed=0)
    np.testing.assert_array_equal(preds_a, preds_b)   # same seed

    trees_c, preds_c = tr.train(bins, y, seed=1)
    assert not np.array_equal(preds_a, preds_c)       # different seed

    from ytk_mp4j_tpu.exceptions import Mp4jError
    with pytest.raises(Mp4jError):
        GBDTConfig(subsample=0.0)
    with pytest.raises(Mp4jError):
        GBDTConfig(colsample=1.5)


def test_colsample_masks_features(rng):
    """With only one feature allowed to win, every split must use it
    (verified by comparing against a run whose data makes the masked
    features strictly better)."""
    N, F, B = 1024, 4, 8
    bins = rng.integers(0, B, (N, F)).astype(np.int32)
    # feature 3 is perfectly predictive; others noise
    y = (bins[:, 3] > B // 2).astype(np.float32)
    # colsample so small the fallback keeps exactly one feature; over
    # several seeds, some tree must be forced off feature 3 yet still
    # split on SOME feature in range
    cfg = GBDTConfig(n_features=F, n_bins=B, depth=2, n_trees=3,
                     subsample=1.0, colsample=0.26)
    tr = GBDTTrainer(cfg, mesh=make_mesh(1))
    trees, _ = tr.train(bins, y, seed=42)
    feats = np.concatenate([np.asarray(t[0]) for t in trees])
    assert ((feats >= 0) & (feats < F)).all()
    # not every split can be feature 3 under aggressive masking
    assert (feats != 3).any()


def test_softmax_out_of_range_labels_rejected(rng):
    cfg = GBDTConfig(n_features=2, n_bins=4, depth=2, n_trees=1,
                     loss="softmax", n_classes=3)
    tr = GBDTTrainer(cfg, mesh=make_mesh(1))
    bins = rng.integers(0, 4, (32, 2)).astype(np.int32)
    from ytk_mp4j_tpu.exceptions import Mp4jError
    with pytest.raises(Mp4jError):
        tr.train(bins, np.full(32, 3, np.int32))     # == n_classes
    with pytest.raises(Mp4jError):
        tr.train(bins, np.full(32, -1, np.int32))


def test_softmax_multiclass_fits_and_roundtrips(rng, tmp_path):
    """Multiclass GBDT: one tree per class per round; accuracy beats
    the base rate; distributed matches single-device; save/load/predict
    round-trips."""
    N, F, B, C = 1500, 4, 16, 3
    bins = rng.integers(0, B, (N, F)).astype(np.int32)
    y = np.clip(bins[:, 2] * C // B, 0, C - 1).astype(np.int32)
    cfg = GBDTConfig(n_features=F, n_bins=B, depth=3, learning_rate=0.4,
                     n_trees=4, loss="softmax", n_classes=C)

    dist = GBDTTrainer(cfg, mesh=make_mesh(4))
    trees, margins = dist.train(bins, y)
    assert margins.shape == (dist.n_shards * ((N + 3) // 4), C)
    proba = dist.predict(bins, trees, proba=True)
    assert proba.shape == (N, C)
    np.testing.assert_allclose(proba.sum(1), 1.0, rtol=1e-5)
    acc = float((proba.argmax(1) == y).mean())
    assert acc > 0.9

    single = GBDTTrainer(cfg, mesh=make_mesh(1))
    trees_s, margins_s = single.train(bins, y)
    np.testing.assert_allclose(margins[:N], margins_s[:N], rtol=1e-4,
                               atol=1e-5)

    path = str(tmp_path / "mc.npz")
    dist.save_model(path, trees)
    cfg2, trees2, _ = GBDTTrainer.load_model(path)
    assert cfg2 == cfg
    serve = GBDTTrainer(cfg2, mesh=make_mesh(1))
    np.testing.assert_allclose(serve.predict(bins, trees2),
                               dist.predict(bins, trees), rtol=1e-6)


def test_empty_leaf_nan_stays_isolated(rng):
    """reg_lambda=0 + an empty leaf gives that leaf value -0/0 = NaN;
    the one-hot selects must confine it to rows that route there (none),
    exactly like the gathers they replaced — one poisoned table entry
    must not contaminate every sample's prediction."""
    N, F, B = 256, 3, 4
    cfg = GBDTConfig(n_features=F, n_bins=B, depth=4, reg_lambda=0.0,
                     learning_rate=0.5)
    bins = rng.integers(0, B, (N, F)).astype(np.int32)
    y = rng.standard_normal(N).astype(np.float32)
    preds = np.zeros(N, np.float32)
    new_preds, tree = train_tree_shard(
        jnp.array(bins), jnp.array(y), jnp.array(preds), cfg)
    # depth-4 over 256 samples: empty leaves are essentially guaranteed
    assert np.isnan(np.asarray(tree[3])).any(), "test needs an empty leaf"
    assert np.isfinite(np.asarray(new_preds)).all()
    applied = np.asarray(predict_tree(jnp.array(bins), tree, cfg))
    assert np.isfinite(applied).all()


def test_best_splits_prefers_separating_feature():
    # two nodes; feature 1 cleanly separates grads in node 0
    F, B = 3, 4
    hg = np.zeros((1, F, B), np.float32)
    hh = np.ones((1, F, B), np.float32)
    # feature 1: strong negative grads below bin 2, positive above
    hg[0, 1, 0] = -10.0
    hg[0, 1, 1] = -8.0
    hg[0, 1, 2] = 9.0
    hg[0, 1, 3] = 9.0
    feat, bin_, gain, dir_ = best_splits(jnp.array(hg), jnp.array(hh), 1.0)
    assert int(feat[0]) == 1
    assert int(bin_[0]) == 1
    assert float(gain[0]) > 0
    assert int(dir_[0]) == 0          # no missing handling: always left


def test_single_device_tree_reduces_loss(rng):
    N, F, B = 512, 6, 16
    cfg = GBDTConfig(n_features=F, n_bins=B, depth=3, learning_rate=0.5)
    bins = rng.integers(0, B, (N, F)).astype(np.int32)
    # target correlated with feature 0's bins
    y = (bins[:, 0] / B + 0.05 * rng.standard_normal(N)).astype(np.float32)
    preds = np.zeros(N, np.float32)
    new_preds, tree = train_tree_shard(
        jnp.array(bins), jnp.array(y), jnp.array(preds), cfg)
    mse0 = float(np.mean((preds - y) ** 2))
    mse1 = float(np.mean((np.asarray(new_preds) - y) ** 2))
    assert mse1 < mse0 * 0.5

    # predict_tree reproduces the training-time routing deltas
    delta = np.asarray(new_preds) - preds
    applied = cfg.learning_rate * np.asarray(
        predict_tree(jnp.array(bins), tree, cfg))
    np.testing.assert_allclose(applied, delta, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("mesh_builder", [
    lambda: make_mesh(4),
    lambda: make_hier_mesh(2, 4),
], ids=["flat4", "hier2x4"])
def test_distributed_training_matches_single_device(mesh_builder, rng):
    """The histogram allreduce must make distributed training numerically
    equivalent to single-device training on the union of the data."""
    N, F, B = 1024, 4, 16
    cfg = GBDTConfig(n_features=F, n_bins=B, depth=3, learning_rate=0.3,
                     n_trees=3)
    bins = rng.integers(0, B, (N, F)).astype(np.int32)
    y = (np.sin(bins[:, 1]) + 0.1 * rng.standard_normal(N)).astype(np.float32)

    dist = GBDTTrainer(cfg, mesh=mesh_builder())
    trees_d, preds_d = dist.train(bins, y)

    single = GBDTTrainer(cfg, mesh=make_mesh(1))
    trees_s, preds_s = single.train(bins, y)

    np.testing.assert_allclose(preds_d[:N], preds_s[:N], rtol=1e-4,
                               atol=1e-5)
    for (f_d, b_d, d_d, v_d), (f_s, b_s, d_s, v_s) in zip(trees_d,
                                                          trees_s):
        np.testing.assert_array_equal(np.asarray(f_d), np.asarray(f_s))
        np.testing.assert_array_equal(np.asarray(b_d), np.asarray(b_s))
        np.testing.assert_array_equal(np.asarray(d_d), np.asarray(d_s))
        np.testing.assert_allclose(np.asarray(v_d), np.asarray(v_s),
                                   rtol=1e-4, atol=1e-5)


def test_training_fits_signal(rng):
    N, F, B = 2048, 5, 32
    cfg = GBDTConfig(n_features=F, n_bins=B, depth=4, learning_rate=0.3,
                     n_trees=10)
    bins = rng.integers(0, B, (N, F)).astype(np.int32)
    y = ((bins[:, 0] > B // 2).astype(np.float32)
         + 0.1 * rng.standard_normal(N).astype(np.float32))
    tr = GBDTTrainer(cfg, mesh=make_mesh(8))
    _, preds = tr.train(bins, y)
    mse = float(np.mean((preds[:N] - y) ** 2))
    assert mse < 0.05


def test_distributed_uneven_n_matches_single_device(rng):
    """Padding rows must be weight-0: N not divisible by shards has to
    reproduce single-device results exactly (review regression)."""
    N, F, B = 1001, 4, 16
    cfg = GBDTConfig(n_features=F, n_bins=B, depth=3, learning_rate=0.3,
                     n_trees=2)
    bins = rng.integers(0, B, (N, F)).astype(np.int32)
    y = (np.cos(bins[:, 2]) + 0.1 * rng.standard_normal(N)).astype(np.float32)
    dist = GBDTTrainer(cfg, mesh=make_mesh(8))
    _, preds_d = dist.train(bins, y)
    single = GBDTTrainer(cfg, mesh=make_mesh(1))
    _, preds_s = single.train(bins, y)
    np.testing.assert_allclose(preds_d[:N], preds_s[:N], rtol=1e-4,
                               atol=1e-5)


def test_wrong_bins_width_rejected(rng):
    """A bin matrix whose width differs from cfg.n_features must raise,
    not silently route every sample left (one-hot feature select yields
    0 for out-of-range split features)."""
    from ytk_mp4j_tpu.exceptions import Mp4jError
    N, F, B = 256, 5, 8
    cfg = GBDTConfig(n_features=F, n_bins=B, depth=2, n_trees=1)
    bins = rng.integers(0, B, (N, F)).astype(np.int32)
    y = rng.standard_normal(N).astype(np.float32)
    tr = GBDTTrainer(cfg, mesh=make_mesh(1))
    trees, _ = tr.train(bins, y)
    narrow = bins[:, : F - 1]
    with pytest.raises(Mp4jError):
        tr.predict(narrow, trees)
    with pytest.raises(Mp4jError):
        tr.train(narrow, y)
    with pytest.raises(Mp4jError):
        tr.train(bins, y, eval_set=(narrow, y))


# ----------------------------------------------------------------------
# missing-value default direction + categorical splits (ytk-learn's
# data-handling features), checked against a compact numpy oracle
# ----------------------------------------------------------------------
def _oracle_tree(bins, g, h, cfg):
    """Depth-d level-wise numpy mirror of _build_tree with missing
    direction + categorical handling (exact f64 histograms)."""
    F, B, lam = cfg.n_features, cfg.n_bins, cfg.reg_lambda
    cats = set(cfg.categorical_features)
    N = bins.shape[0]
    node = np.zeros(N, np.int64)
    feats, bs, dirs = [], [], []
    for d in range(cfg.depth):
        n_nodes = 2 ** d
        bf, bb, bd, bg = (np.zeros(n_nodes, int), np.zeros(n_nodes, int),
                          np.zeros(n_nodes, int),
                          np.full(n_nodes, -np.inf))
        for n in range(n_nodes):
            m = node == n
            for f in range(F):
                hg = np.bincount(bins[m, f], weights=g[m], minlength=B)
                hh = np.bincount(bins[m, f], weights=h[m], minlength=B)
                Gt, Ht = hg.sum(), hh.sum()

                def score(G, H):
                    return G * G / (H + lam)

                for b in range(B - 1):      # B-1 excluded everywhere
                    if f in cats:
                        GL, HL = Gt - hg[b], Ht - hh[b]
                        variants = [(GL, HL, 0)]
                    else:
                        GL = hg[: b + 1].sum()
                        HL = hh[: b + 1].sum()
                        variants = [(GL, HL, 0)]
                        if cfg.missing_bin:
                            variants.append((GL - hg[0], HL - hh[0], 1))
                    for GL, HL, dr in variants:
                        gain = (score(GL, HL) + score(Gt - GL, Ht - HL)
                                - score(Gt, Ht))
                        if gain > bg[n]:
                            bf[n], bb[n], bd[n], bg[n] = f, b, dr, gain
            if not bg[n] > cfg.min_split_gain:
                bf[n], bb[n], bd[n] = 0, B - 1, 0
        feats.append(bf)
        bs.append(bb)
        dirs.append(bd)
        v = bins[np.arange(N), bf[node]]
        go_right = v > bb[node]
        if cfg.missing_bin:
            go_right = np.where(v == 0, bd[node] > 0, go_right)
        is_cat = np.isin(bf[node], list(cats)) if cats else np.zeros(N, bool)
        go_right = np.where(is_cat, (v == bb[node]) & (bb[node] != B - 1),
                            go_right)
        node = node * 2 + go_right
    leaves = 2 ** cfg.depth
    lg = np.bincount(node, weights=g, minlength=leaves)
    lh = np.bincount(node, weights=h, minlength=leaves)
    leaf = -lg / (lh + lam)
    return (np.concatenate(feats), np.concatenate(bs),
            np.concatenate(dirs), leaf)


def _train_one(bins, y, cfg):
    preds = np.zeros(len(y), np.float32)
    new_preds, tree = train_tree_shard(
        jnp.array(bins), jnp.array(y), jnp.array(preds), cfg)
    return np.asarray(new_preds), [np.asarray(t) for t in tree]


@pytest.mark.parametrize("missing_bin", [False, True])
def test_missing_direction_matches_oracle(rng, missing_bin):
    N, F, B = 512, 4, 8
    # min_split_gain > 0: a pure/empty node's mathematically-zero gain
    # rounds to a small positive in the device's f32 while the f64
    # oracle gets exactly 0; a common threshold freezes both the same
    cfg = GBDTConfig(n_features=F, n_bins=B, depth=3, hist_mode="flat",
                     learning_rate=1.0, missing_bin=missing_bin,
                     min_split_gain=0.01)
    bins = rng.integers(1, B, (N, F)).astype(np.int32)
    missing = rng.random(N) < 0.3
    bins[missing, 0] = 0                   # bin 0 = the missing bucket
    # missing samples behave like HIGH values of f0 (the case where a
    # learned direction matters: an ordered split at b >= 1 wants the
    # missing bucket on its RIGHT side, which forced-left cannot do;
    # splitting at b = 0 instead would mis-pool missing with the lows)
    y = (((bins[:, 0] >= B // 2) | missing) * 2.0
         + 0.01 * rng.standard_normal(N)).astype(np.float32)
    g = (np.zeros(N) - y).astype(np.float64)   # squared loss at preds=0
    h = np.ones(N, np.float64)
    of, ob, od, ol = _oracle_tree(bins, g, h, cfg)
    new_preds, (tf, tb, td, lv) = _train_one(bins, y, cfg)
    np.testing.assert_array_equal(tb, ob)
    # frozen nodes (bin == B-1) keep an arbitrary argmax feature on the
    # device (routing ignores it); compare features on real splits only
    live = ob != B - 1
    np.testing.assert_array_equal(tf[live], of[live])
    np.testing.assert_array_equal(td[live], od[live])
    np.testing.assert_allclose(lv, ol, rtol=1e-4, atol=1e-5)
    if missing_bin:
        assert (td > 0).any(), "signal-bearing missing should go right"
    else:
        assert (td == 0).all()


def test_missing_direction_improves_fit(rng):
    """Learned direction must beat forced-left on data where missing
    correlates with the target."""
    N, F, B = 1024, 3, 8
    bins = rng.integers(1, B, (N, F)).astype(np.int32)
    missing = rng.random(N) < 0.4
    bins[missing, 0] = 0
    y = (missing * 3.0
         + 0.05 * rng.standard_normal(N)).astype(np.float32)
    mses = {}
    for mb in (False, True):
        cfg = GBDTConfig(n_features=F, n_bins=B, depth=2,
                         hist_mode="flat", learning_rate=1.0,
                         missing_bin=mb)
        new_preds, _ = _train_one(bins, y, cfg)
        mses[mb] = float(np.mean((new_preds - y) ** 2))
    assert mses[True] <= mses[False] * 1.0001


def test_categorical_split_matches_oracle(rng):
    N, F, B = 512, 3, 8
    cfg = GBDTConfig(n_features=F, n_bins=B, depth=2, hist_mode="flat",
                     learning_rate=1.0, categorical_features=(0,),
                     min_split_gain=0.01)
    bins = rng.integers(0, B - 1, (N, F)).astype(np.int32)
    # y depends on f0 == 3 EXACTLY — an ordered split cannot isolate it
    # in one level; the equality split can
    y = ((bins[:, 0] == 3) * 2.0
         + 0.01 * rng.standard_normal(N)).astype(np.float32)
    g = (np.zeros(N) - y).astype(np.float64)
    h = np.ones(N, np.float64)
    of, ob, od, ol = _oracle_tree(bins, g, h, cfg)
    new_preds, (tf, tb, td, lv) = _train_one(bins, y, cfg)
    np.testing.assert_array_equal(tb, ob)
    live = ob != B - 1          # frozen nodes keep an arbitrary feature
    np.testing.assert_array_equal(tf[live], of[live])
    np.testing.assert_allclose(lv, ol, rtol=1e-4, atol=1e-5)
    # the root must be the equality split on (f0, category 3)
    assert tf[0] == 0 and tb[0] == 3
    mse = float(np.mean((new_preds - y) ** 2))
    assert mse < 0.01


def test_categorical_beats_numeric_on_equality_signal(rng):
    N, F, B = 1024, 2, 16
    bins = rng.integers(0, B - 1, (N, F)).astype(np.int32)
    y = ((bins[:, 0] == 7) * 1.0
         + 0.02 * rng.standard_normal(N)).astype(np.float32)
    mses = {}
    for cats in ((), (0,)):
        cfg = GBDTConfig(n_features=F, n_bins=B, depth=1,
                         hist_mode="flat", learning_rate=1.0,
                         categorical_features=cats)
        new_preds, _ = _train_one(bins, y, cfg)
        mses[cats] = float(np.mean((new_preds - y) ** 2))
    assert mses[(0,)] < mses[()] * 0.5


def test_missing_and_categorical_roundtrip_predict(rng, tmp_path):
    """predict_tree replays training-time routing (missing + cat), and
    the dir array survives save/load."""
    N, F, B = 256, 4, 8
    cfg = GBDTConfig(n_features=F, n_bins=B, depth=3, hist_mode="flat",
                     missing_bin=True, categorical_features=(2,),
                     learning_rate=0.7, n_trees=2)
    bins = rng.integers(1, B - 1, (N, F)).astype(np.int32)
    bins[rng.random(N) < 0.3, 0] = 0
    y = (bins[:, 2] == 2) * 1.5 + (bins[:, 0] == 0) * 1.0
    y = y.astype(np.float32)
    tr = GBDTTrainer(cfg, mesh=make_mesh(1))
    trees, preds = tr.train(bins, y)
    re_pred = tr.predict(bins, trees)
    np.testing.assert_allclose(re_pred, preds[:N], rtol=1e-4, atol=1e-5)
    path = str(tmp_path / "m.npz")
    tr.save_model(path, trees)
    cfg2, trees2, _ = GBDTTrainer.load_model(path)
    assert cfg2.missing_bin and cfg2.categorical_features == (2,)
    tr2 = GBDTTrainer(cfg2, mesh=make_mesh(1))
    np.testing.assert_allclose(tr2.predict(bins, trees2), re_pred,
                               rtol=1e-5)


def test_binner_missing_bucket(rng):
    from ytk_mp4j_tpu.models.binning import QuantileBinner
    X = rng.standard_normal((500, 3)).astype(np.float32)
    X[rng.random(500) < 0.2, 0] = np.nan
    b = QuantileBinner(8, missing_bucket=True).fit(X)
    out = b.transform(X)
    nan_mask = np.isnan(X)
    assert (out[nan_mask] == 0).all()
    assert (out[~nan_mask] >= 1).all() and (out[~nan_mask] < 8).all()
    # default mode: bin 0 shared between NaN and the lowest quantile
    b0 = QuantileBinner(8).fit(X)
    out0 = b0.transform(X)
    assert (out0[nan_mask] == 0).all()
    assert (out0[~nan_mask] == 0).any()


def test_missing_bin_learns_at_zero_reg(rng):
    """reg_lambda=0: the b=0 missing-right variant is an empty-left
    0/0 = NaN that must not poison argmax and freeze every node."""
    N, F, B = 512, 3, 8
    bins = rng.integers(1, B, (N, F)).astype(np.int32)
    bins[rng.random(N) < 0.3, 0] = 0
    y = (bins[:, 0] / B).astype(np.float32)
    cfg = GBDTConfig(n_features=F, n_bins=B, depth=2, hist_mode="flat",
                     learning_rate=1.0, missing_bin=True, reg_lambda=0.0)
    new_preds, (tf, tb, td, lv) = _train_one(bins, y, cfg)
    assert (tb != B - 1).any(), "all nodes frozen: NaN poisoned argmax"
    assert float(np.mean((new_preds - y) ** 2)) < 0.5 * float(np.var(y))


def test_load_model_without_dir_arrays(tmp_path, rng):
    """Models saved before default-direction support (feat/bin/leaf
    triples) must still load, with all-left directions."""
    F, B = 3, 8
    cfg = GBDTConfig(n_features=F, n_bins=B, depth=2, n_trees=1)
    tr = GBDTTrainer(cfg, mesh=make_mesh(1))
    bins = rng.integers(0, B, (64, F)).astype(np.int32)
    y = (bins[:, 0] / B).astype(np.float32)
    trees, _ = tr.train(bins, y)
    path = str(tmp_path / "old.npz")
    tr.save_model(path, trees)
    # rewrite the file without the dir arrays (the old format)
    with np.load(path, allow_pickle=False) as z:
        arrays = {k: z[k] for k in z.files if not k.startswith("dir_")}
    with open(path, "wb") as f:
        np.savez(f, **arrays)
    cfg2, trees2, _ = GBDTTrainer.load_model(path)
    for (tf, tb, td, lv), (of, ob, od, ol) in zip(trees2, trees):
        np.testing.assert_array_equal(td, 0)
        np.testing.assert_array_equal(tf, np.asarray(of))
    np.testing.assert_allclose(
        GBDTTrainer(cfg2, mesh=make_mesh(1)).predict(bins, trees2),
        tr.predict(bins, trees), rtol=1e-6)


def test_config_rejects_bad_categorical_types():
    from ytk_mp4j_tpu.exceptions import Mp4jError
    for bad in ((1.5,), ("x",), (True,)):
        with pytest.raises(Mp4jError):
            GBDTConfig(n_features=4, categorical_features=bad)
    # numpy integer indices normalize to plain ints
    cfg = GBDTConfig(n_features=4,
                     categorical_features=(np.int64(2), np.int32(0)))
    assert cfg.categorical_features == (2, 0)


def test_binner_missing_bucket_needs_three_bins():
    from ytk_mp4j_tpu.exceptions import Mp4jError
    from ytk_mp4j_tpu.models.binning import QuantileBinner
    with pytest.raises(Mp4jError):
        QuantileBinner(2, missing_bucket=True)
    QuantileBinner(3, missing_bucket=True)    # fine
    QuantileBinner(2)                         # fine without the bucket


def test_scanned_predict_matches_unrolled(rng):
    """predict scans over the stacked ensemble (one-tree program size);
    it must match the unrolled-loop formulation to 1 ulp (FMA fusion
    differs between program shapes, so exact bit-identity across XLA
    programs is not attainable — BASELINE.md round-3 note)."""
    import jax

    cfg = GBDTConfig(n_features=7, n_bins=16, depth=4)
    T, N = 12, 500
    n_nodes, n_leaves = 2 ** cfg.depth - 1, 2 ** cfg.depth
    trees = [
        (jnp.asarray(rng.integers(0, cfg.n_features, n_nodes),
                     dtype=jnp.int32),
         jnp.asarray(rng.integers(0, cfg.n_bins, n_nodes),
                     dtype=jnp.int32),
         jnp.asarray(rng.integers(0, 2, n_nodes), dtype=jnp.int32),
         jnp.asarray(rng.standard_normal(n_leaves), dtype=jnp.float32))
        for _ in range(T)]
    bins = rng.integers(0, cfg.n_bins, (N, cfg.n_features)).astype(np.int32)
    tr = GBDTTrainer(cfg, n_devices=1)
    got = tr.predict(bins, trees)

    @jax.jit
    def unrolled(b, ts):
        out = jnp.zeros((b.shape[0],), jnp.float32)
        for t in ts:
            out = out + cfg.learning_rate * predict_tree(b, t, cfg)
        return out

    want = np.asarray(unrolled(jnp.asarray(bins), trees))
    np.testing.assert_allclose(got, want, atol=2e-7)


def test_scanned_predict_softmax_matches_unrolled(rng):
    import jax

    cfg = GBDTConfig(n_features=5, n_bins=8, depth=3, loss="softmax",
                     n_classes=3)
    T, N = 6, 300
    n_nodes, n_leaves = 2 ** cfg.depth - 1, 2 ** cfg.depth
    trees = [
        tuple(
            (jnp.asarray(rng.integers(0, cfg.n_features, n_nodes),
                         dtype=jnp.int32),
             jnp.asarray(rng.integers(0, cfg.n_bins, n_nodes),
                         dtype=jnp.int32),
             jnp.asarray(rng.integers(0, 2, n_nodes), dtype=jnp.int32),
             jnp.asarray(rng.standard_normal(n_leaves), dtype=jnp.float32))
            for _ in range(cfg.n_classes))
        for _ in range(T)]
    bins = rng.integers(0, cfg.n_bins, (N, cfg.n_features)).astype(np.int32)
    tr = GBDTTrainer(cfg, n_devices=1)
    got = tr.predict(bins, trees)

    @jax.jit
    def unrolled(b, ts):
        out = jnp.zeros((b.shape[0], cfg.n_classes), jnp.float32)
        for per_class in ts:
            out = out + cfg.learning_rate * jnp.stack(
                [predict_tree(b, t, cfg) for t in per_class], axis=1)
        return out

    want = np.asarray(unrolled(jnp.asarray(bins), trees))
    np.testing.assert_allclose(got, want, atol=2e-7)


# ------------------------------------------------- train_raw (consumer)
def _raw_problem(rng, n=400, f=6):
    X = rng.standard_normal((n, f)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] ** 2
         + 0.1 * rng.standard_normal(n)).astype(np.float32)
    return X, y


def test_train_raw_matches_manual_wiring(rng):
    """train_raw == QuantileBinner.fit + transform + train with the
    same seed (the parity VERDICT round 4 asked for), and the fitted
    binner is retained for predict_raw."""
    from ytk_mp4j_tpu.models.binning import QuantileBinner

    X, y = _raw_problem(rng)
    cfg = GBDTConfig(n_features=6, n_bins=16, depth=3, n_trees=3,
                     learning_rate=0.5)
    tr = GBDTTrainer(cfg, mesh=make_mesh(4))
    trees, margins = tr.train_raw(X, y, seed=7)

    manual_binner = QuantileBinner(16).fit(X, seed=7)
    bins = manual_binner.transform(X)
    tr2 = GBDTTrainer(cfg, mesh=make_mesh(4))
    trees2, margins2 = tr2.train(bins, y, seed=7)
    np.testing.assert_array_equal(tr.binner_.edges, manual_binner.edges)
    np.testing.assert_allclose(margins[:len(y)], margins2[:len(y)],
                               rtol=1e-6, atol=1e-7)
    for t1, t2 in zip(trees, trees2):
        for a1, a2 in zip(t1, t2):
            np.testing.assert_array_equal(np.asarray(a1),
                                          np.asarray(a2))
    # predict_raw rides the retained binner
    np.testing.assert_allclose(
        tr.predict_raw(X, trees), tr2.predict(bins, trees2),
        rtol=1e-6, atol=1e-7)
    # and actually learned the function
    pred = tr.predict_raw(X, trees)
    assert np.corrcoef(pred, y)[0, 1] > 0.8


def test_train_raw_missing_and_weights(rng):
    """NaN features flow to the missing bucket (cfg.missing_bin pairs
    with binner missing_bucket) and sample_weight reaches BOTH the
    sketch and the boosting gradients."""
    X, y = _raw_problem(rng)
    X[::5, 2] = np.nan
    cfg = GBDTConfig(n_features=6, n_bins=16, depth=3, n_trees=2,
                     missing_bin=True, learning_rate=0.5)
    tr = GBDTTrainer(cfg, mesh=make_mesh(2))
    w = np.where(y > 0, 3.0, 1.0).astype(np.float32)
    trees, _ = tr.train_raw(X, y, seed=3, sample_weight=w)
    assert tr.binner_.missing_bucket
    assert np.isfinite(tr.predict_raw(X, trees)).all()
    # weighted vs unweighted edges differ (the sketch saw the weights)
    trU = GBDTTrainer(cfg, mesh=make_mesh(2))
    trU.train_raw(X, y, seed=3)
    assert not np.array_equal(tr.binner_.edges, trU.binner_.edges)


def test_train_raw_eval_set_and_persistence(rng, tmp_path):
    """eval_set takes RAW features; save_model persists the train_raw
    binner by default and load_model returns it serving-ready."""
    X, y = _raw_problem(rng, n=600)
    Xt, yt, Xv, yv = X[:400], y[:400], X[400:], y[400:]
    cfg = GBDTConfig(n_features=6, n_bins=16, depth=3, n_trees=10,
                     learning_rate=0.3)
    tr = GBDTTrainer(cfg, mesh=make_mesh(2))
    trees, _ = tr.train_raw(Xt, yt, seed=1, eval_set=(Xv, yv),
                            early_stopping_rounds=3)
    assert len(tr.eval_history_) >= 1
    path = str(tmp_path / "raw_model.npz")
    tr.save_model(path, trees)            # binner rides along
    cfg2, trees2, binner2 = GBDTTrainer.load_model(path)
    assert binner2 is not None
    tr2 = GBDTTrainer(cfg2, mesh=make_mesh(2))
    tr2.binner_ = binner2
    np.testing.assert_allclose(tr2.predict_raw(Xv, trees2),
                               tr.predict_raw(Xv, trees),
                               rtol=1e-6, atol=1e-7)


def test_train_raw_distributed_binning(rng):
    """train_raw(comm=...) fits the binner via fit_distributed over
    the comm: every rank ends with identical edges equal to the merged
    sketch; predict stays rank-identical."""
    from helpers import run_slaves
    from ytk_mp4j_tpu.models.binning import QuantileBinner

    X, y = _raw_problem(rng)
    cfg = GBDTConfig(n_features=6, n_bins=8, depth=2, n_trees=2,
                     learning_rate=0.5)

    def job(slave, rank):
        tr = GBDTTrainer(cfg, mesh=make_mesh(1))
        trees, _ = tr.train_raw(X, y, seed=2, comm=slave)
        return tr.binner_.edges, tr.predict_raw(X[:16], trees)

    results = run_slaves(2, job)
    (e0, p0), (e1, p1) = results
    np.testing.assert_array_equal(e0, e1)
    np.testing.assert_allclose(p0, p1, rtol=1e-6, atol=1e-7)
    # replicated data on both ranks pools to the single-host sketch
    b = QuantileBinner(8)
    sk = b.local_sketch(X, sample=1_000_000, seed=2)
    b.merge_sketches(np.stack([sk.values] * 2),
                     np.stack([sk.counts] * 2),
                     np.stack([sk.finite] * 2),
                     cdf_stack=np.stack([sk.cdf] * 2))
    np.testing.assert_allclose(e0, b.edges, rtol=1e-6, atol=1e-6)


def test_train_raw_rejects_incompatible_binner(rng):
    """A FINER pre-fitted binner would emit bin ids the histogram
    one-hot silently drops; mismatched missing-bucket conventions
    silently reroute NaN — both must be errors."""
    from ytk_mp4j_tpu.exceptions import Mp4jError
    from ytk_mp4j_tpu.models.binning import QuantileBinner

    X, y = _raw_problem(rng, n=100)
    cfg = GBDTConfig(n_features=6, n_bins=16, depth=2, n_trees=1)
    tr = GBDTTrainer(cfg, mesh=make_mesh(1))
    with pytest.raises(Mp4jError, match="exceeds"):
        tr.train_raw(X, y, binner=QuantileBinner(64).fit(X))
    with pytest.raises(Mp4jError, match="missing_bucket"):
        tr.train_raw(X, y, binner=QuantileBinner(
            16, missing_bucket=True).fit(X))
    # coarser is legal (load_model's rule)
    trees, _ = tr.train_raw(X, y, binner=QuantileBinner(8).fit(X))
    assert np.isfinite(tr.predict_raw(X, trees)).all()
