import pytest

from ytk_mp4j_tpu import meta
from ytk_mp4j_tpu.exceptions import Mp4jError


def test_partition_sizes_even():
    assert meta.partition_sizes(8, 4) == [2, 2, 2, 2]


def test_partition_sizes_uneven():
    assert meta.partition_sizes(10, 4) == [3, 3, 2, 2]
    assert meta.partition_sizes(3, 5) == [1, 1, 1, 0, 0]


def test_partition_range_covers():
    for length in (0, 1, 7, 16, 101):
        for parts in (1, 2, 3, 5, 8):
            rs = meta.partition_range(5, 5 + length, parts)
            assert len(rs) == parts
            assert rs[0][0] == 5
            assert rs[-1][1] == 5 + length
            for (s0, e0), (s1, e1) in zip(rs, rs[1:]):
                assert e0 == s1
                assert s0 <= e0


def test_owner_of_matches_partition():
    for length in (1, 7, 16, 101):
        for parts in (1, 2, 3, 5, 8):
            rs = meta.partition_range(0, length, parts)
            for r, (s, e) in enumerate(rs):
                for i in range(s, e):
                    assert meta.owner_of(i, 0, length, parts) == r


def test_owner_of_out_of_range():
    with pytest.raises(Mp4jError):
        meta.owner_of(10, 0, 10, 2)


def test_padded_block():
    assert meta.padded_block(10, 4) == 3
    assert meta.padded_block(8, 4) == 2
    assert meta.padded_block(1, 8) == 1


def test_key_partition_canonicalizes_integral_keys():
    """np.integer keys must place exactly like python ints: repr-based
    hashing would split them on numpy >= 2 ('np.int64(5)' vs '5'), and
    the map codecs decode to python ints — every path must agree.
    bool stays un-canonicalized (it would collide with 0/1)."""
    import numpy as np

    for k in (0, 5, -3, 2**40):
        for np_k in (np.int32(k) if abs(k) < 2**31 else np.int64(k),
                     np.int64(k)):
            for parts in (2, 3, 7):
                assert (meta.key_partition(np_k, parts)
                        == meta.key_partition(k, parts)), (k, parts)
    assert meta.key_partition(True, 3) == meta.key_partition(True, 3)
    # strings and tuples keep their repr-based placement
    assert isinstance(meta.key_partition("w5", 4), int)
