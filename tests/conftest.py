"""Test rig: multi-device without a cluster.

The reference's check suite simulates multi-node by launching N slave JVMs
on localhost (SURVEY.md section 4). Here we simulate a TPU pod with 8
virtual CPU devices (xla_force_host_platform_device_count) and enable x64
so DOUBLE/LONG operands are exact for differential comparison.

Must run before any jax import, hence module-level env mutation in
conftest (pytest imports conftest first).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The axon sitecustomize (PYTHONPATH=/root/.axon_site) force-sets the
# jax_platforms CONFIG to "axon,cpu" at interpreter start, overriding the
# JAX_PLATFORMS env var — and the axon platform is 1 real TPU chip whose
# remote compiler rejects most collectives. Tests must run on the 8-device
# virtual CPU mesh, so override the config back (env alone is not enough).
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def n_devices():
    return jax.device_count()


@pytest.fixture
def rng():
    return np.random.default_rng(0)
