"""Hierarchical (inter x intra) device collectives — the TPU analogue of
the reference's process x thread nesting (SURVEY.md section 3d)."""

from functools import partial

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from ytk_mp4j_tpu.utils.compat import shard_map  # jax-version compat import
from jax.sharding import PartitionSpec as P

from ytk_mp4j_tpu.comm.tpu_comm import TpuCommCluster
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operator, Operators
from ytk_mp4j_tpu.ops import collectives as coll
from ytk_mp4j_tpu.parallel import make_hier_mesh

from helpers import expected_reduce, make_inputs


@pytest.fixture(scope="module")
def hier_cluster():
    return TpuCommCluster(mesh=make_hier_mesh(4, 2))


@pytest.mark.parametrize("op", ["SUM", "PROD", "MAX", "MIN"])
def test_hier_allreduce(hier_cluster, op, rng):
    n = hier_cluster.n
    assert n == 8
    arrs = make_inputs(n, 40, Operands.DOUBLE, rng)
    want = expected_reduce(arrs, op)
    hier_cluster.allreduce_array(arrs, Operands.DOUBLE,
                                 Operators.by_name(op))
    for a in arrs:
        np.testing.assert_allclose(a, want, rtol=1e-9)


@pytest.mark.parametrize("root", [0, 5])
def test_hier_broadcast(hier_cluster, root, rng):
    arrs = make_inputs(8, 17, Operands.FLOAT, rng)
    src = arrs[root].copy()
    hier_cluster.broadcast_array(arrs, Operands.FLOAT, root=root)
    for a in arrs:
        np.testing.assert_array_equal(a, src)


def test_hier_reduce_scatter(hier_cluster, rng):
    from ytk_mp4j_tpu import meta
    L = 27
    arrs = make_inputs(8, L, Operands.DOUBLE, rng)
    want = expected_reduce(arrs, "SUM")
    ranges = meta.partition_range(0, L, 8)
    hier_cluster.reduce_scatter_array(arrs, Operands.DOUBLE, Operators.SUM)
    for r, (s, e) in enumerate(ranges):
        np.testing.assert_allclose(arrs[r][s:e], want[s:e], rtol=1e-9)


def test_hier_allgather(hier_cluster, rng):
    from ytk_mp4j_tpu import meta
    L = 19
    ranges = meta.partition_range(0, L, 8)
    arrs = make_inputs(8, L, Operands.LONG, rng)
    want = np.zeros(L, dtype=np.int64)
    for r, (s, e) in enumerate(ranges):
        want[s:e] = arrs[r][s:e]
    hier_cluster.allgather_array(arrs, Operands.LONG)
    for a in arrs:
        np.testing.assert_array_equal(a, want)


def test_hier_maps(hier_cluster, rng):
    maps = [{f"k{r % 3}": float(r)} for r in range(8)]
    want = {}
    for m in maps:
        for k, v in m.items():
            want[k] = want.get(k, 0.0) + v
    hier_cluster.allreduce_map(maps, Operands.DOUBLE, Operators.SUM)
    for m in maps:
        assert set(m) == set(want)
        for k in want:
            np.testing.assert_allclose(m[k], want[k])


def test_functional_two_level_inside_jit(rng):
    """Per-level reductions composed in user jit: intra-mean then
    inter-max — the kind of staged hierarchy users write directly."""
    mesh = make_hier_mesh(2, 4)
    x = np.arange(8, dtype=np.float64).reshape(8, 1)

    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=P(("inter", "intra")),
             out_specs=P(("inter", "intra")))
    def f(v):
        intra_sum = coll.allreduce(v, Operators.SUM, "intra")
        return coll.allreduce(intra_sum, Operators.MAX, "inter")

    out = np.asarray(f(x))
    # intra groups: [0..3] sum=6, [4..7] sum=22; inter max = 22
    np.testing.assert_allclose(out, np.full((8, 1), 22.0))


def test_flat_index_layout():
    """flat_index must match the blocked global-rank layout."""
    mesh = make_hier_mesh(4, 2)

    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=P(("inter", "intra")),
             out_specs=P(("inter", "intra")))
    def f(v):
        return v + coll.flat_index(("inter", "intra"))[None, None]

    out = np.asarray(f(np.zeros((8, 1))))
    np.testing.assert_array_equal(out[:, 0], np.arange(8))
