"""Linear model family: distributed gradient-allreduce training over the
virtual mesh, checked against single-device runs and a numpy oracle."""

import numpy as np
import pytest

from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.models.linear import LinearConfig, LinearTrainer
from ytk_mp4j_tpu.parallel import make_hier_mesh, make_mesh


def make_regression(rng, n=512, d=8, noise=0.05):
    x = rng.standard_normal((n, d)).astype(np.float32)
    w_true = rng.standard_normal(d).astype(np.float32)
    y = x @ w_true + 0.5 + noise * rng.standard_normal(n).astype(np.float32)
    return x, y, w_true


def make_classification(rng, n=512, d=6):
    x = rng.standard_normal((n, d)).astype(np.float32)
    w_true = rng.standard_normal(d).astype(np.float32)
    y = (x @ w_true + 0.1 * rng.standard_normal(n) > 0).astype(np.float32)
    return x, y


def test_squared_loss_recovers_weights(rng):
    x, y, w_true = make_regression(rng)
    cfg = LinearConfig(n_features=x.shape[1], loss="squared",
                       learning_rate=0.3)
    tr = LinearTrainer(cfg, mesh=make_mesh(8))
    (w, b), losses = tr.fit(x, y, n_steps=200)
    assert losses[-1] < losses[0] * 0.01
    np.testing.assert_allclose(np.asarray(w), w_true, rtol=0.1, atol=0.05)
    assert abs(float(b) - 0.5) < 0.05


def test_logistic_separates(rng):
    x, y = make_classification(rng)
    cfg = LinearConfig(n_features=x.shape[1], loss="logistic",
                       learning_rate=0.5)
    tr = LinearTrainer(cfg, mesh=make_mesh(8))
    params, losses = tr.fit(x, y, n_steps=300)
    assert losses[-1] < losses[0]
    p = tr.predict(params, x)
    acc = float(np.mean((p > 0.5) == (y > 0.5)))
    assert acc > 0.95


@pytest.mark.parametrize("mesh_builder", [
    lambda: make_mesh(4),
    lambda: make_hier_mesh(2, 4),
], ids=["flat4", "hier2x4"])
def test_distributed_matches_single_device(mesh_builder, rng):
    """The gradient allreduce must make sharded training numerically
    equivalent to single-device training on the union of the data —
    including an uneven N that forces weight-0 padding rows."""
    x, y, _ = make_regression(rng, n=501)
    cfg = LinearConfig(n_features=x.shape[1], loss="squared",
                       learning_rate=0.2, momentum=0.9, l2=1e-3)
    dist = LinearTrainer(cfg, mesh=mesh_builder())
    pd, ld = dist.fit(x, y, n_steps=50)
    single = LinearTrainer(cfg, mesh=make_mesh(1))
    ps, ls = single.fit(x, y, n_steps=50)
    np.testing.assert_allclose(np.asarray(pd[0]), np.asarray(ps[0]),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(ld, ls, rtol=1e-4, atol=1e-6)


def test_l1_sparsifies(rng):
    x, y, _ = make_regression(rng, d=10)
    # half the features are pure noise: L1 should zero some of them out
    x[:, 5:] = rng.standard_normal((x.shape[0], 5)).astype(np.float32)
    cfg = LinearConfig(n_features=10, loss="squared", learning_rate=0.2,
                       l1=0.05)
    tr = LinearTrainer(cfg, mesh=make_mesh(4))
    (w, _), _ = tr.fit(x, y, n_steps=200)
    assert np.sum(np.abs(np.asarray(w)) < 1e-6) >= 1


def test_bad_loss_and_shape_raise(rng):
    with pytest.raises(Mp4jError):
        LinearConfig(n_features=4, loss="hinge")
    tr = LinearTrainer(LinearConfig(n_features=4), mesh=make_mesh(2))
    with pytest.raises(Mp4jError):
        tr.fit(np.zeros((8, 3), np.float32), np.zeros(8, np.float32),
               n_steps=1)


def test_save_load_params_roundtrip(rng, tmp_path):
    x, y, _ = make_regression(rng, n=256, d=4)
    cfg = LinearConfig(n_features=4, learning_rate=0.3)
    tr = LinearTrainer(cfg, mesh=make_mesh(2))
    params, _ = tr.fit(x, y, n_steps=30)
    path = str(tmp_path / "linear.model")      # exact path, no suffix
    tr.save_params(path, params)
    cfg2, params2 = LinearTrainer.load_params(path, LinearConfig)
    assert cfg2 == cfg
    serve = LinearTrainer(cfg2, mesh=make_mesh(1))
    np.testing.assert_allclose(serve.predict(params2, x),
                               tr.predict(params, x), rtol=1e-6)
    # load -> re-save round trip (numpy params, not jax arrays)
    path2 = str(tmp_path / "resaved.model")
    serve.save_params(path2, params2)
    cfg3, params3 = LinearTrainer.load_params(path2, LinearConfig)
    for a, b in zip(params2, params3):
        np.testing.assert_array_equal(a, b)


def test_eval_set_and_early_stopping(rng):
    x_all, y_all, _ = make_regression(rng, n=500, d=4)
    x, y = x_all[:400], y_all[:400]
    x_va, y_va = x_all[400:], y_all[400:]
    cfg = LinearConfig(n_features=4, learning_rate=0.3)
    tr = LinearTrainer(cfg, mesh=make_mesh(2))
    params, losses = tr.fit(x, y, n_steps=25, eval_set=(x_va, y_va))
    assert len(tr.eval_history_) == 25
    assert tr.eval_history_[-1] < tr.eval_history_[0]

    # noise validation labels: early stop truncates to the best round
    y_noise = rng.standard_normal(100).astype(np.float32)
    tr2 = LinearTrainer(cfg, mesh=make_mesh(2))
    params2, losses2 = tr2.fit(x, y, n_steps=60,
                               eval_set=(x_va, y_noise),
                               early_stopping_rounds=3)
    assert len(losses2) < 60
    best = int(np.argmin(tr2.eval_history_))
    assert len(losses2) == best + 1

    with pytest.raises(Mp4jError):
        tr2.fit(x, y, n_steps=3, early_stopping_rounds=2)


def test_softmax_multiclass_separates(rng):
    """ytk-learn multiclass_linear analogue: 3 linearly separable
    classes; loss decreases, accuracy is high, probabilities are rows
    of a stochastic matrix."""
    N, F, C = 1200, 4, 3
    centers = np.array([[3, 0, 0, 0], [0, 3, 0, 0], [0, 0, 3, 0]],
                       np.float32)
    y = rng.integers(0, C, N).astype(np.int32)
    x = centers[y] + rng.standard_normal((N, F)).astype(np.float32)
    cfg = LinearConfig(n_features=F, loss="softmax", n_classes=C,
                       learning_rate=0.5)
    tr = LinearTrainer(cfg, n_devices=4)
    params, losses = tr.fit(x, y, n_steps=60)
    assert losses[-1] < losses[0] * 0.5
    p = tr.predict(params, x)
    assert p.shape == (N, C)
    np.testing.assert_allclose(p.sum(axis=1), 1.0, rtol=1e-5)
    assert (p.argmax(1) == y).mean() > 0.9


def test_softmax_distributed_matches_single_device(rng):
    N, F, C = 203, 3, 4                       # uneven N exercises padding
    x = rng.standard_normal((N, F)).astype(np.float32)
    y = rng.integers(0, C, N).astype(np.int32)
    cfg = LinearConfig(n_features=F, loss="softmax", n_classes=C,
                       learning_rate=0.3, l2=1e-3, momentum=0.5)
    p1, l1 = LinearTrainer(cfg, n_devices=1).fit(x, y, n_steps=10)
    p8, l8 = LinearTrainer(cfg, n_devices=8).fit(x, y, n_steps=10)
    for a, b in zip(p1, p8):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(l1, l8, rtol=2e-5, atol=2e-6)


def test_softmax_label_validation(rng):
    cfg = LinearConfig(n_features=2, loss="softmax", n_classes=3)
    tr = LinearTrainer(cfg, n_devices=1)
    x = rng.standard_normal((10, 2)).astype(np.float32)
    with pytest.raises(Mp4jError, match="softmax labels"):
        tr.fit(x, np.full(10, 3, np.int32), n_steps=1)
    with pytest.raises(Mp4jError):
        LinearConfig(n_features=2, loss="softmax", n_classes=1)


def test_softmax_loss_matches_numpy(rng):
    """per_example_loss('softmax') against a plain numpy cross entropy."""
    from ytk_mp4j_tpu.models._base import per_example_loss
    import jax.numpy as jnp

    N, C = 64, 5
    z = rng.standard_normal((N, C)).astype(np.float32) * 10
    y = rng.integers(0, C, N)
    got = np.asarray(per_example_loss(jnp.asarray(z), jnp.asarray(y),
                                      "softmax"))
    m = z.max(axis=1, keepdims=True)
    lse = (m[:, 0] + np.log(np.exp(z - m).sum(axis=1)))
    want = lse - z[np.arange(N), y]
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_column_vector_labels_rejected(rng):
    """[N, 1] labels would broadcast through the loss to an [N, N]
    matrix and train silently on garbage — must raise, for every loss."""
    x = rng.standard_normal((10, 2)).astype(np.float32)
    for loss, kw in (("squared", {}), ("logistic", {}),
                     ("softmax", {"n_classes": 2})):
        tr = LinearTrainer(LinearConfig(n_features=2, loss=loss, **kw),
                           n_devices=1)
        with pytest.raises(Mp4jError, match="1-D"):
            tr.fit(x, np.zeros((10, 1)), n_steps=1)


# ------------------------------------------------------------ streaming
def test_fit_stream_matches_fit(rng):
    """One full-batch chunk per epoch == fit(n_steps=E) exactly
    (momentum state threads across chunks); serialized pipeline
    (max_in_flight=0) matches the double-buffered default."""
    x, y, _ = make_regression(rng, n=96, d=5)
    cfg = LinearConfig(n_features=5, learning_rate=0.1, momentum=0.9,
                       l2=1e-3)
    tr = LinearTrainer(cfg, mesh=make_mesh(4))
    p_f, l_f = tr.fit(x, y, n_steps=4)
    tr2 = LinearTrainer(cfg, mesh=make_mesh(4))
    p_s, l_s = tr2.fit_stream(((x, y) for _ in range(4)))
    np.testing.assert_allclose(l_s, l_f, rtol=1e-6, atol=1e-8)
    for a, b in zip(p_s, p_f):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-8)
    tr3 = LinearTrainer(cfg, mesh=make_mesh(4))
    _, l_s0 = tr3.fit_stream(((x, y) for _ in range(4)),
                             max_in_flight=0)
    np.testing.assert_allclose(l_s0, l_s, rtol=1e-7, atol=1e-9)


def test_fit_stream_uneven_chunks_and_softmax(rng):
    """Short final chunks pad with zero-weight rows; the softmax
    family streams too; oversized chunks raise."""
    x = rng.standard_normal((100, 4)).astype(np.float32)
    y = rng.integers(0, 3, 100)
    cfg = LinearConfig(n_features=4, loss="softmax", n_classes=3,
                       learning_rate=0.3)
    tr = LinearTrainer(cfg, mesh=make_mesh(4))
    chunks = [(x[:64], y[:64]), (x[64:], y[64:])] * 2
    params, losses = tr.fit_stream(iter(chunks), batch_rows=64)
    assert losses.shape == (4,) and np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    with pytest.raises(Mp4jError, match="exceeds batch_rows"):
        tr.fit_stream(iter([(x, y)]), batch_rows=64)


def test_linear_stream_from_libsvm_text(rng):
    """libsvm text -> dense_chunks -> fit_stream: the ytk-learn linear
    consumer flow end-to-end (duplicate ids accumulate; padded slots
    are inert; out-of-range ids raise)."""
    from ytk_mp4j_tpu.utils.libsvm import dense_chunks, read_libsvm

    x, y = make_classification(rng, n=128, d=6)
    lines = []
    for i in range(128):
        toks = " ".join(f"{j}:{x[i, j]:.5f}" for j in range(6))
        lines.append(f"{y[i]:.0f} {toks}")
    cfg = LinearConfig(n_features=6, loss="logistic", learning_rate=0.5)
    tr = LinearTrainer(cfg, mesh=make_mesh(4))
    params = None
    for _ in range(8):
        params, losses = tr.fit_stream(
            dense_chunks(read_libsvm(iter(lines), chunk_rows=64,
                                     max_nnz=6), 6),
            params=params, batch_rows=64)
    acc = float(np.mean((tr.predict(params, x) > 0.5) == (y > 0.5)))
    assert acc > 0.9, acc
    # duplicate feature ids accumulate; slot-0 padding adds nothing
    got = list(dense_chunks(read_libsvm(
        iter(["1 2:1.5 2:0.5 0:3.0"]), chunk_rows=4, max_nnz=4), 4))
    np.testing.assert_allclose(got[0][0][0], [3.0, 0.0, 2.0, 0.0])
    with pytest.raises(Mp4jError, match="out of range"):
        list(dense_chunks(read_libsvm(iter(["1 9:1.0"]), chunk_rows=4,
                                      max_nnz=4), 6))


def test_sample_weight_equals_duplication(rng):
    """Integer instance weights must train EXACTLY like physically
    duplicated rows (the weighted-mean loss/grad identity), in both
    fit and fit_stream."""
    x, y, _ = make_regression(rng, n=48, d=4)
    k = rng.integers(1, 4, 48)
    xd, yd = np.repeat(x, k, axis=0), np.repeat(y, k)
    cfg = LinearConfig(n_features=4, learning_rate=0.2, momentum=0.5)
    _, l_w = LinearTrainer(cfg, mesh=make_mesh(4)).fit(
        x, y, n_steps=3, sample_weight=k.astype(np.float32))
    _, l_d = LinearTrainer(cfg, mesh=make_mesh(4)).fit(
        xd, yd, n_steps=3)
    np.testing.assert_allclose(l_w, l_d, rtol=1e-5, atol=1e-7)
    _, l_s = LinearTrainer(cfg, mesh=make_mesh(4)).fit_stream(
        ((x, y, k.astype(np.float32)) for _ in range(3)))
    np.testing.assert_allclose(l_s, l_w, rtol=1e-6, atol=1e-8)
    with pytest.raises(Mp4jError, match="sample_weight"):
        LinearTrainer(cfg, mesh=make_mesh(2)).fit(
            x, y, n_steps=1, sample_weight=np.ones(7))
