"""Differential checks of the TPU (device) collective path against numpy.

Mirrors the reference's check-suite pattern (SURVEY.md section 4): every
collective x element type x operator on generated data, compared against
locally computed expected values. Runs on the 8-virtual-device CPU mesh.
"""

import numpy as np
import pytest

from ytk_mp4j_tpu import meta
from ytk_mp4j_tpu.comm.tpu_comm import TpuCommCluster
from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operator, Operators

from helpers import expected_reduce, make_inputs


def assert_close(got, want, operand):
    if operand.dtype.kind == "f":
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    else:
        np.testing.assert_array_equal(got, want)


@pytest.fixture(scope="module")
def cluster():
    return TpuCommCluster()


@pytest.fixture(scope="module")
def cluster5():
    # non-power-of-2 rank count (reference supports these, SURVEY.md 3b)
    return TpuCommCluster(5)


@pytest.mark.parametrize("op", ["SUM", "PROD", "MAX", "MIN"])
@pytest.mark.parametrize("operand", Operands.NUMERIC, ids=lambda o: o.name)
def test_allreduce_all_types(cluster, operand, op, rng):
    arrs = make_inputs(cluster.n, 100, operand, rng)
    want = expected_reduce(arrs, op)
    cluster.allreduce_array(arrs, operand, Operators.by_name(op))
    for a in arrs:
        assert_close(a, want, operand)


def test_allreduce_subrange(cluster, rng):
    operand = Operands.DOUBLE
    arrs = make_inputs(cluster.n, 50, operand, rng)
    orig = [a.copy() for a in arrs]
    want = expected_reduce(arrs, "SUM")
    cluster.allreduce_array(arrs, operand, Operators.SUM, from_=10, to=30)
    for a, o in zip(arrs, orig):
        np.testing.assert_allclose(a[10:30], want[10:30])
        np.testing.assert_array_equal(a[:10], o[:10])
        np.testing.assert_array_equal(a[30:], o[30:])


def test_allreduce_empty_range(cluster, rng):
    arrs = make_inputs(cluster.n, 10, Operands.FLOAT, rng)
    orig = [a.copy() for a in arrs]
    cluster.allreduce_array(arrs, Operands.FLOAT, Operators.SUM,
                            from_=4, to=4)
    for a, o in zip(arrs, orig):
        np.testing.assert_array_equal(a, o)


def test_allreduce_nonpow2(cluster5, rng):
    operand = Operands.DOUBLE
    arrs = make_inputs(5, 33, operand, rng)
    want = expected_reduce(arrs, "SUM")
    cluster5.allreduce_array(arrs, operand, Operators.SUM)
    for a in arrs:
        np.testing.assert_allclose(a, want)


@pytest.mark.parametrize("root", [0, 3])
def test_reduce(cluster, root, rng):
    operand = Operands.DOUBLE
    arrs = make_inputs(cluster.n, 40, operand, rng)
    orig = [a.copy() for a in arrs]
    want = expected_reduce(arrs, "SUM")
    cluster.reduce_array(arrs, operand, Operators.SUM, root=root)
    np.testing.assert_allclose(arrs[root], want)
    for r, (a, o) in enumerate(zip(arrs, orig)):
        if r != root:
            np.testing.assert_array_equal(a, o)


@pytest.mark.parametrize("root", [0, 2])
def test_broadcast(cluster, root, rng):
    operand = Operands.FLOAT
    arrs = make_inputs(cluster.n, 31, operand, rng)
    src = arrs[root].copy()
    cluster.broadcast_array(arrs, operand, root=root)
    for a in arrs:
        np.testing.assert_array_equal(a, src)


def test_broadcast_subrange(cluster, rng):
    operand = Operands.INT
    arrs = make_inputs(cluster.n, 20, operand, rng)
    orig = [a.copy() for a in arrs]
    src = arrs[1].copy()
    cluster.broadcast_array(arrs, operand, root=1, from_=5, to=15)
    for r, (a, o) in enumerate(zip(arrs, orig)):
        np.testing.assert_array_equal(a[5:15], src[5:15])
        np.testing.assert_array_equal(a[:5], o[:5])
        np.testing.assert_array_equal(a[15:], o[15:])


def test_allgather(cluster, rng):
    operand = Operands.DOUBLE
    L = 45  # uneven over 8 ranks
    ranges = meta.partition_range(0, L, cluster.n)
    arrs = make_inputs(cluster.n, L, operand, rng)
    want = np.zeros(L, dtype=operand.dtype)
    for r, (s, e) in enumerate(ranges):
        want[s:e] = arrs[r][s:e]
    cluster.allgather_array(arrs, operand)
    for a in arrs:
        np.testing.assert_array_equal(a, want)


def test_gather(cluster, rng):
    operand = Operands.LONG
    L = 37
    ranges = meta.partition_range(0, L, cluster.n)
    arrs = make_inputs(cluster.n, L, operand, rng)
    orig = [a.copy() for a in arrs]
    want = np.zeros(L, dtype=operand.dtype)
    for r, (s, e) in enumerate(ranges):
        want[s:e] = arrs[r][s:e]
    root = 2
    cluster.gather_array(arrs, operand, root=root)
    np.testing.assert_array_equal(arrs[root], want)
    for r, (a, o) in enumerate(zip(arrs, orig)):
        if r != root:
            np.testing.assert_array_equal(a, o)


def test_scatter(cluster, rng):
    operand = Operands.FLOAT
    L = 43
    ranges = meta.partition_range(0, L, cluster.n)
    arrs = make_inputs(cluster.n, L, operand, rng)
    root = 1
    src = arrs[root].copy()
    orig = [a.copy() for a in arrs]
    cluster.scatter_array(arrs, operand, root=root)
    for r, (s, e) in enumerate(ranges):
        np.testing.assert_array_equal(arrs[r][s:e], src[s:e])
        # outside own segment unchanged (except root keeps its own array)
        if r != root:
            mask = np.ones(L, bool)
            mask[s:e] = False
            np.testing.assert_array_equal(arrs[r][mask], orig[r][mask])


@pytest.mark.parametrize("op", ["SUM", "MAX", "PROD"])
def test_reduce_scatter(cluster, op, rng):
    operand = Operands.DOUBLE
    L = 53  # uneven
    ranges = meta.partition_range(0, L, cluster.n)
    arrs = make_inputs(cluster.n, L, operand, rng)
    orig = [a.copy() for a in arrs]
    want = expected_reduce(orig, op)
    cluster.reduce_scatter_array(arrs, operand, Operators.by_name(op))
    for r, (s, e) in enumerate(ranges):
        assert_close(arrs[r][s:e], want[s:e], operand)
        mask = np.ones(L, bool)
        mask[s:e] = False
        np.testing.assert_array_equal(arrs[r][mask], orig[r][mask])


def test_custom_operator_allreduce(cluster, rng):
    import jax.numpy as jnp
    absmax = Operator.custom(
        "ABSMAX",
        lambda x, y: jnp.where(jnp.abs(x) >= jnp.abs(y), x, y),
        0.0,
    )
    operand = Operands.DOUBLE
    arrs = make_inputs(cluster.n, 64, operand, rng)
    stacked = np.stack(arrs)
    idx = np.abs(stacked).argmax(axis=0)
    want = stacked[idx, np.arange(stacked.shape[1])]
    cluster.allreduce_array(arrs, operand, absmax)
    for a in arrs:
        np.testing.assert_allclose(a, want)


def test_string_operand_rejected(cluster):
    with pytest.raises(Mp4jError):
        cluster.allreduce_array([None] * cluster.n, Operands.STRING,
                                Operators.SUM)


def test_barrier(cluster):
    cluster.barrier()  # must simply complete


def test_wrong_rank_count(cluster):
    with pytest.raises(Mp4jError):
        cluster.allreduce_array([np.zeros(3, np.float32)] * (cluster.n - 1),
                                Operands.FLOAT, Operators.SUM)


@pytest.mark.parametrize("bad_root", [-1, 99])
def test_bad_root_rejected(cluster, bad_root, rng):
    arrs = make_inputs(cluster.n, 5, Operands.FLOAT, rng)
    orig = [a.copy() for a in arrs]
    for call in (
        lambda: cluster.broadcast_array(arrs, Operands.FLOAT, root=bad_root),
        lambda: cluster.reduce_array(arrs, Operands.FLOAT, Operators.SUM,
                                     root=bad_root),
        lambda: cluster.gather_array(arrs, Operands.FLOAT, root=bad_root),
        lambda: cluster.scatter_array(arrs, Operands.FLOAT, root=bad_root),
    ):
        with pytest.raises(Mp4jError):
            call()
    for a, o in zip(arrs, orig):
        np.testing.assert_array_equal(a, o)


def test_noncontiguous_2d_allreduce(cluster, rng):
    # Fortran-ordered 2-D inputs must still receive results (copyto path).
    arrs = [np.asfortranarray(rng.standard_normal((4, 3)))
            for _ in range(cluster.n)]
    want = expected_reduce(arrs, "SUM")
    cluster.allreduce_array(arrs, Operands.DOUBLE, Operators.SUM)
    for a in arrs:
        np.testing.assert_allclose(a, want)


def test_native_reduce_fallback_matches(cluster, rng):
    """With native pmax/pmin emission forced off (the axon-style
    compiler-rejection scenario), MAX/MIN allreduce must transparently
    take the gathered tree path and produce identical results."""
    from ytk_mp4j_tpu.ops import collectives as coll
    arrs = make_inputs(cluster.n, 33, Operands.FLOAT, rng)
    native = [a.copy() for a in arrs]
    cluster.allreduce_array(native, Operands.FLOAT, Operators.MAX)
    coll.set_native_reduce(False)
    try:
        fb_cluster = TpuCommCluster(cluster.n)   # fresh jit cache
        fallback = [a.copy() for a in arrs]
        fb_cluster.allreduce_array(fallback, Operands.FLOAT, Operators.MAX)
        mins = [a.copy() for a in arrs]
        fb_cluster.allreduce_array(mins, Operands.FLOAT, Operators.MIN)
    finally:
        coll.set_native_reduce(None)
    want = expected_reduce(arrs, "MAX")
    for a, b in zip(native, fallback):
        np.testing.assert_array_equal(a, want)
        np.testing.assert_array_equal(b, want)
    want_min = expected_reduce(arrs, "MIN")
    for a in mins:
        np.testing.assert_array_equal(a, want_min)


def test_native_reduce_probe_caches():
    from ytk_mp4j_tpu.ops import collectives as coll
    coll.set_native_reduce(None)
    r1 = coll._native_reduce_ok("pmax")
    assert ("cpu", "pmax") in coll._PROBE_CACHE
    assert coll._native_reduce_ok("pmax") == r1   # cached, no re-probe


# ----------------------------------------------------------------------
# algorithm selection (reference parity: ProcessCommSlave's algo arg):
# "xla" / "ring" (ppermute) / "rdma" (Pallas kernel, interpreted on CPU
# meshes) must be result-identical through the driver API
# ----------------------------------------------------------------------
@pytest.mark.parametrize("algo", ["ring", "rdma"])
def test_allreduce_algo_equivalence(cluster, algo, rng):
    operand = Operands.FLOAT
    for op_name in ("SUM", "MAX"):
        arrs = make_inputs(cluster.n, 37, operand, rng)   # 37: pads
        want = [a.copy() for a in arrs]
        cluster.allreduce_array(want, operand, Operators.by_name(op_name))
        got = [a.copy() for a in arrs]
        cluster.allreduce_array(got, operand, Operators.by_name(op_name),
                                algo=algo)
        for a, b in zip(got, want):
            np.testing.assert_allclose(a, b, rtol=1e-6)


@pytest.mark.parametrize("algo", ["ring", "rdma"])
def test_reduce_scatter_algo_equivalence(cluster, algo, rng):
    operand = Operands.FLOAT
    arrs = make_inputs(cluster.n, 41, operand, rng)
    want = [a.copy() for a in arrs]
    cluster.reduce_scatter_array(want, operand, Operators.SUM)
    got = [a.copy() for a in arrs]
    cluster.reduce_scatter_array(got, operand, Operators.SUM, algo=algo)
    for a, b in zip(got, want):
        # ring merges sequentially; XLA's reduction order differs
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("algo", ["ring", "rdma"])
def test_allgather_algo_equivalence(cluster, algo, rng):
    operand = Operands.FLOAT
    arrs = make_inputs(cluster.n, 29, operand, rng)
    want = [a.copy() for a in arrs]
    cluster.allgather_array(want, operand)
    got = [a.copy() for a in arrs]
    cluster.allgather_array(got, operand, algo=algo)
    for a, b in zip(got, want):
        np.testing.assert_array_equal(a, b)


def test_algo_validation(cluster, rng):
    arrs = make_inputs(cluster.n, 8, Operands.FLOAT, rng)
    with pytest.raises(Mp4jError):
        cluster.allreduce_array(arrs, Operands.FLOAT, Operators.SUM,
                                algo="bogus")


def test_algo_rejects_hierarchical_mesh(rng):
    from ytk_mp4j_tpu.parallel import make_hier_mesh
    cl = TpuCommCluster(mesh=make_hier_mesh(2, 2))
    arrs = make_inputs(4, 8, Operands.FLOAT, rng)
    with pytest.raises(Mp4jError):
        cl.allreduce_array(arrs, Operands.FLOAT, Operators.SUM,
                           algo="rdma")


def test_native_reduce_flip_rebuilds_same_cluster(cluster, rng):
    """set_native_reduce after a MAX allreduce must take effect on the
    SAME cluster: the resolved decision is part of the jit cache key,
    so the flip builds a fallback program instead of replaying the
    cached native one."""
    from ytk_mp4j_tpu.ops import collectives as coll
    arrs = make_inputs(cluster.n, 17, Operands.FLOAT, rng)
    first = [a.copy() for a in arrs]
    cluster.allreduce_array(first, Operands.FLOAT, Operators.MAX)
    coll.set_native_reduce(False)
    try:
        flipped = [a.copy() for a in arrs]
        cluster.allreduce_array(flipped, Operands.FLOAT, Operators.MAX)
    finally:
        coll.set_native_reduce(None)
    want = expected_reduce(arrs, "MAX")
    for a, b in zip(first, flipped):
        np.testing.assert_array_equal(a, want)
        np.testing.assert_array_equal(b, want)
    natives = {k[5] for k in cluster._jits
               if k[0] == "allreduce" and k[3] is Operators.MAX
               and k[4] == "xla"}
    assert False in natives and len(natives) == 2, natives


def test_transient_probe_verdict_is_rate_limited(monkeypatch):
    """A transient probe failure must not re-probe on every resolve
    call (a rejection message containing a transient token would
    otherwise trigger a fresh compile probe each time); within the TTL
    the optimistic verdict is reused, after it the probe re-runs."""
    from ytk_mp4j_tpu.ops import collectives as coll

    coll.set_native_reduce(None)
    coll._PROBE_CACHE.pop(("cpu", "pmax"), None)
    coll._TRANSIENT_AT.clear()
    calls = []
    monkeypatch.setattr(coll, "_probe",
                        lambda kind, devs: calls.append(kind) or None)
    try:
        assert coll._native_reduce_ok("pmax") is True   # optimistic
        assert coll._native_reduce_ok("pmax") is True
        assert len(calls) == 1                          # rate-limited
        # TTL expiry -> one more probe
        coll._TRANSIENT_AT[("cpu", "pmax")] -= coll._TRANSIENT_TTL + 1
        assert coll._native_reduce_ok("pmax") is True
        assert len(calls) == 2
    finally:
        coll._TRANSIENT_AT.clear()
        coll._PROBE_CACHE.pop(("cpu", "pmax"), None)
