"""Columnar socket map plane (ISSUE 4): bit-exactness against the
pickled-dict reference path across operand dtypes x operators x key
kinds x map shapes x non-power-of-2 rank counts, vocabulary-sync
invariants, negotiated fallbacks, duplicate-key naming, and analytic
``comm.stats()`` wire-byte accounting.

The bit-exactness contract: both planes apply ``operator.np_fn`` with
identical operand order per key (``op(acc, src)`` up the same binomial
tree), so for dtype-typed values the results must match byte for byte
— not just approximately (see ops/sparse.py host-twin section)."""

import numpy as np
import pytest

from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.operands import Operand, Operands
from ytk_mp4j_tpu.operators import Operator, Operators
from ytk_mp4j_tpu.ops import sparse as sparse_ops

from helpers import run_slaves

NUMERIC_OPERANDS = [op for op in Operands.NUMERIC if op is not None]
OPERATORS = ["SUM", "PROD", "MAX", "MIN"]


def make_values(operand, rng, n):
    """Values typed to the operand dtype — the columnar plane computes
    in the declared dtype (like the device path), so dtype-typed
    inputs are the bit-exactness regime. Small positive ints keep PROD
    finite on the narrow dtypes."""
    if operand.dtype.kind == "f" or operand.dtype.kind == "V":
        vals = rng.standard_normal(n)
    else:
        vals = rng.integers(1, 4, n)
    return [operand.dtype.type(v) for v in vals]


def make_maps(n_ranks, operand, rng, n_keys=60, fill=0.6, key=str):
    maps = []
    for _ in range(n_ranks):
        ks = [key(k) for k in rng.integers(0, int(n_keys / fill), n_keys)]
        maps.append(dict(zip(ks, make_values(operand, rng, n_keys))))
    return maps


def run_plane(maps, columnar, call, n=None, **slave_kwargs):
    """Run ``call(slave, dict(maps[rank]))`` on every rank of a socket
    job pinned to one map plane; returns (per-rank dicts, per-rank
    stats snapshots)."""
    n = len(maps) if n is None else n

    def fn(slave, r):
        d = dict(maps[r])
        call(slave, d)
        return d, slave.stats()

    out = run_slaves(n, fn, map_columnar=columnar, **slave_kwargs)
    return [d for d, _ in out], [s for _, s in out]


def assert_bit_identical(a: dict, b: dict):
    assert set(a) == set(b)
    for k in a:
        va, vb = np.asarray(a[k]), np.asarray(b[k])
        assert va.dtype == vb.dtype, (k, va.dtype, vb.dtype)
        assert va.shape == vb.shape, k
        assert va.tobytes() == vb.tobytes(), (k, a[k], b[k])


def assert_planes_agree(maps, call, n=None):
    col, col_stats = run_plane(maps, True, call, n=n)
    pkl, _ = run_plane(maps, False, call, n=n)
    for dc, dp in zip(col, pkl):
        assert_bit_identical(dc, dp)
    return col, col_stats


# ------------------------------------------------- the full dtype x op grid
@pytest.mark.parametrize("operand", NUMERIC_OPERANDS,
                         ids=lambda o: o.name)
@pytest.mark.parametrize("op", OPERATORS)
def test_allreduce_bit_identical_across_dtypes_and_ops(operand, op, rng):
    operator = Operators.by_name(op)
    maps = make_maps(3, operand, rng)   # 3: non-power-of-2

    def call(slave, d):
        slave.allreduce_map(d, operand, operator)

    assert_planes_agree(maps, call)


@pytest.mark.parametrize("key,kind", [
    (lambda k: int(k), "int"),
    (lambda k: f"w{k}", "str"),
    (lambda k: np.int64(k), "np-int"),
    (lambda k: bool(k % 2), "bool-obj"),   # bool is an OBJ key by rule
])
def test_allreduce_key_kinds(key, kind, rng):
    maps = make_maps(4, Operands.DOUBLE, rng, n_keys=40, key=key)

    def call(slave, d):
        slave.allreduce_map(d, Operands.DOUBLE, Operators.SUM)

    assert_planes_agree(maps, call)


@pytest.mark.parametrize("shape", ["empty", "some-empty", "disjoint",
                                   "overlap"])
def test_allreduce_map_shapes(shape, rng):
    n = 5   # non-power-of-2, exercises the fold-free binomial tree
    if shape == "empty":
        maps = [{} for _ in range(n)]
    elif shape == "some-empty":
        maps = make_maps(n, Operands.DOUBLE, rng, n_keys=25)
        maps[0] = {}
        maps[3] = {}
    elif shape == "disjoint":
        maps = [{r * 1000 + i: float(i) for i in range(30)}
                for r in range(n)]
    else:   # fully overlapping key sets
        vals = [make_values(Operands.DOUBLE, rng, 30) for _ in range(n)]
        maps = [dict(zip(range(30), v)) for v in vals]

    def call(slave, d):
        slave.allreduce_map(d, Operands.DOUBLE, Operators.SUM)

    assert_planes_agree(maps, call)


@pytest.mark.parametrize("collective",
                         ["reduce", "broadcast", "scatter", "gather",
                          "reduce_scatter", "allgather"])
def test_full_family_bit_identical(collective, rng):
    n = 3
    if collective in ("gather", "allgather"):
        maps = [{r * 100 + i: float(r + i) for i in range(12)}
                for r in range(n)]   # disjoint, per the contract
    else:
        maps = make_maps(n, Operands.DOUBLE, rng, n_keys=35)

    def call(slave, d):
        if collective == "reduce":
            slave.reduce_map(d, Operands.DOUBLE, Operators.SUM, root=2)
        elif collective == "broadcast":
            slave.broadcast_map(d, Operands.DOUBLE, root=1)
        elif collective == "scatter":
            slave.scatter_map(d, Operands.DOUBLE, root=0)
        elif collective == "gather":
            slave.gather_map(d, Operands.DOUBLE, root=1)
        elif collective == "reduce_scatter":
            slave.reduce_scatter_map(d, Operands.DOUBLE, Operators.SUM)
        else:
            slave.allgather_map(d, Operands.DOUBLE)

    assert_planes_agree(maps, call)


def test_vector_values_and_compressed_operand(rng):
    maps = [{f"e{i}": rng.standard_normal(4) for i in range(10 + r)}
            for r in range(3)]
    operand = Operands.compressed(Operands.DOUBLE)

    def call(slave, d):
        slave.allreduce_map(d, operand, Operators.SUM)

    assert_planes_agree(maps, call)


# ---------------------------------------------------- vocabulary invariants
def test_vocab_identical_across_ranks_and_calls(rng):
    """The sync invariant: after any sequence of columnar collectives
    with drifting key sets, every rank holds byte-identical code->key
    tables — and later calls reuse codes (novelty exchange empty)."""
    batches = [make_maps(3, Operands.DOUBLE, rng, n_keys=20 + 10 * s,
                         key=lambda k: f"f{k}") for s in range(4)]

    def fn(slave, r):
        outs = []
        for maps in batches:
            d = dict(maps[r])
            slave.allreduce_map(d, Operands.DOUBLE, Operators.SUM)
            outs.append(d)
        codec = slave._map_codecs["obj"]
        return outs, list(codec._by_code)

    res = run_slaves(3, fn, map_columnar=True)
    vocab0 = res[0][1]
    assert all(vocab == vocab0 for _, vocab in res)
    # the vocabulary is the union of every key ever seen, grown once
    every_key = set()
    for maps in batches:
        for m in maps:
            every_key |= set(m)
    assert set(vocab0) == every_key and len(vocab0) == len(every_key)
    # and the results still match the pickled plane per batch
    for b, maps in enumerate(batches):
        pkl, _ = run_plane(maps, False, lambda s, d: s.allreduce_map(
            d, Operands.DOUBLE, Operators.SUM))
        for r in range(3):
            assert_bit_identical(res[r][0][b], pkl[r])


# ------------------------------------------------------ negotiated fallback
def test_fallback_object_values(rng):
    """Complex values under a DOUBLE operand cannot pack into the
    float64 column — the negotiation must divert every rank to the
    pickled plane, which still merges them (np.add handles complex)."""
    maps = [{i: complex(i, r) for i in range(10)} for r in range(3)]

    def call(slave, d):
        slave.allreduce_map(d, Operands.DOUBLE, Operators.SUM)

    col, col_stats = run_plane(maps, True, call)
    pkl, _ = run_plane(maps, False, call)
    assert col == pkl
    # nothing was encoded columnar: the fallback engaged job-wide
    assert all(s["allreduce_map"]["keys"] == 0 for s in col_stats)


def test_fallback_mixed_key_kinds_across_ranks(rng):
    """Rank 0 int keys, rank 1 str keys: kinds differ job-wide, so the
    negotiation falls back rather than desyncing vocabularies."""
    maps = [{i: float(i) for i in range(8)},
            {f"k{i}": float(i) for i in range(8)},
            {}]

    def call(slave, d):
        slave.allreduce_map(d, Operands.DOUBLE, Operators.SUM)

    col, col_stats = run_plane(maps, True, call)
    pkl, _ = run_plane(maps, False, call)
    for dc, dp in zip(col, pkl):
        assert set(dc) == set(dp)
    assert all(s["allreduce_map"]["keys"] == 0 for s in col_stats)


def test_fallback_unsortable_key_mix_within_rank(rng):
    """int+str keys in ONE map read as obj kind (str first) but cannot
    be canonically ordered for codec growth — negotiated fallback."""
    maps = [{"a": 1.0, 2: 2.0, "c": 3.0}, {"a": 4.0}, {}]

    def call(slave, d):
        slave.allreduce_map(d, Operands.DOUBLE, Operators.SUM)

    col, _ = run_plane(maps, True, call)
    pkl, _ = run_plane(maps, False, call)
    for dc, dp in zip(col, pkl):
        assert set(dc) == set(dp)
        for k in dc:
            assert float(dc[k]) == float(dp[k])


def test_fallback_object_operator(rng):
    """A custom (non-ufunc) operator keeps the pickled plane — its fn
    is arbitrary host Python the segment reducer cannot honor."""
    first = Operator.custom("FIRST", lambda a, b: a, 0.0)
    maps = make_maps(3, Operands.DOUBLE, rng, n_keys=15)

    def call(slave, d):
        slave.allreduce_map(d, Operands.DOUBLE, first)

    col, col_stats = run_plane(maps, True, call)
    pkl, _ = run_plane(maps, False, call)
    for dc, dp in zip(col, pkl):
        assert_bit_identical(dc, dp)
    assert all(s["allreduce_map"]["keys"] == 0 for s in col_stats)


# --------------------------------------------------- gather duplicate naming
@pytest.mark.parametrize("columnar", [True, False],
                         ids=["columnar", "pickle"])
def test_gather_duplicate_names_key_and_both_ranks(columnar):
    maps = [{0: 1.0, 7: 1.0}, {1: 2.0}, {7: 3.0}]  # 0 and 2 both own 7

    def fn(slave, r):
        d = dict(maps[r])
        try:
            slave.gather_map(d, Operands.DOUBLE, root=0)
        except Mp4jError as e:
            return str(e)
        return None

    res = run_slaves(3, fn, map_columnar=columnar)
    msg = res[0]
    assert msg is not None and "7" in msg
    assert "ranks 0 and 2" in msg, msg


def test_thread_gather_duplicate_names_global_ranks():
    """The thread leader's disjoint-union check must name the key and
    both owner GLOBAL ranks (helper tested directly: a leader raise
    inside a live _fan_in_out strands sibling threads at the barrier
    by design — fail-stop — so the full collective cannot be driven
    through a conflict in-process)."""
    from ytk_mp4j_tpu.comm.thread_comm import ThreadCommSlave

    slaves = ThreadCommSlave.spawn_group(3)
    slots = [{"x": 1.0}, {"y": 2.0}, {"x": 3.0}]
    with pytest.raises(Mp4jError, match=r"'x'.*global ranks 0 and 2"):
        slaves[0]._disjoint_union_slots(slots, "gather_map")
    # disjoint slots stay on the fast path
    ok = slaves[0]._disjoint_union_slots(
        [{"a": 1.0}, {"b": 2.0}, {}], "gather_map")
    assert ok == {"a": 1.0, "b": 2.0}


def test_tpu_gather_duplicate_names_both_ranks():
    from ytk_mp4j_tpu.comm.tpu_comm import TpuCommCluster

    cl = TpuCommCluster(4)
    maps = [{"a": 1.0}, {"b": 2.0}, {}, {"a": 9.0}]
    with pytest.raises(Mp4jError, match=r"'a'.*ranks 0 and 3"):
        cl.gather_map(maps, Operands.DOUBLE, root=0)


# ------------------------------------------------------- analytic accounting
def test_columnar_stats_wire_bytes_and_keys(rng):
    """Analytic wire accounting for a 2-rank int-keyed allreduce: the
    non-root ships exactly one (codes, values) pair up the tree —
    K*4 codes bytes + K*8 value bytes plus bounded frame/negotiation
    overhead — and books keys == its map size."""
    K = 256
    maps = [{i: float(i) for i in range(K)},
            {i + K // 2: float(i) for i in range(K)}]

    def call(slave, d):
        slave.allreduce_map(d, Operands.DOUBLE, Operators.SUM)

    _, stats = run_plane(maps, True, call)
    for r, snap in enumerate(stats):
        e = snap["allreduce_map"]
        assert e["calls"] == 1
        assert e["keys"] == K
        assert e["serialize_seconds"] > 0
    payload = K * (4 + 8)                  # codes:int32 + values:f64
    union_payload = 2 * K * (4 + 8) * 3 // 4   # 50% overlap -> 1.5K keys
    # rank 1 (vr=1): novelty header + one column pair up; receives the
    # union columns in the broadcast down-sweep
    sent1 = stats[1]["allreduce_map"]["bytes_sent"]
    assert payload <= sent1 <= payload + 8192, sent1
    recv1 = stats[1]["allreduce_map"]["bytes_recv"]
    assert union_payload <= recv1 <= union_payload + 8192, recv1
    # rank 0 merges: vectorized reduce time is booked as reduce phase
    assert stats[0]["allreduce_map"]["reduce_seconds"] > 0


def test_map_columnar_env_knob(monkeypatch):
    from ytk_mp4j_tpu.utils import tuning

    monkeypatch.delenv("MP4J_MAP_COLUMNAR", raising=False)
    assert tuning.map_columnar_enabled() is True
    monkeypatch.setenv("MP4J_MAP_COLUMNAR", "0")
    assert tuning.map_columnar_enabled() is False
    monkeypatch.setenv("MP4J_MAP_COLUMNAR", "yes")
    with pytest.raises(Mp4jError):
        tuning.map_columnar_enabled()


# ------------------------------------------------------- merge-kernel twins
@pytest.mark.parametrize("op", OPERATORS)
def test_np_merge_twins_match_dict_oracle(op, rng):
    np_fn = Operators.by_name(op).np_fn
    for _ in range(10):
        ka = np.unique(rng.integers(0, 50, 20)).astype(np.int32)
        kb = np.unique(rng.integers(0, 50, 20)).astype(np.int32)
        va = rng.standard_normal(ka.size)
        vb = rng.standard_normal(kb.size)
        mc, mv = sparse_ops.np_merge_sorted_columns(ka, va, kb, vb,
                                                    np_fn)
        oracle = dict(zip(ka.tolist(), va))
        for k, v in zip(kb.tolist(), vb):
            oracle[k] = np_fn(oracle[k], v) if k in oracle else v
        assert mc.tolist() == sorted(oracle)
        for k, v in zip(mc.tolist(), mv):
            assert np.float64(v).tobytes() == \
                np.float64(oracle[k]).tobytes()


def test_np_merge_twins_property():
    """Hypothesis form of the oracle test (skips with the other
    hypothesis suites when the package is absent)."""
    hyp = pytest.importorskip("hypothesis")
    st = pytest.importorskip("hypothesis.strategies")

    @hyp.given(
        st.lists(st.tuples(st.integers(0, 99),
                           st.floats(-1e6, 1e6)), max_size=40),
        st.lists(st.tuples(st.integers(0, 99),
                           st.floats(-1e6, 1e6)), max_size=40))
    @hyp.settings(deadline=None, max_examples=50)
    def prop(a, b):
        da, db = dict(a), dict(b)
        ka = np.asarray(sorted(da), np.int32)
        kb = np.asarray(sorted(db), np.int32)
        va = np.asarray([da[k] for k in ka.tolist()])
        vb = np.asarray([db[k] for k in kb.tolist()])
        mc, mv = sparse_ops.np_merge_sorted_columns(ka, va, kb, vb,
                                                    np.add)
        oracle = dict(da)
        for k, v in db.items():
            oracle[k] = np.add(oracle[k], v) if k in oracle else v
        assert mc.tolist() == sorted(oracle)
        for k, v in zip(mc.tolist(), mv):
            assert np.float64(v).tobytes() == \
                np.float64(oracle[k]).tobytes()

    prop()


# ---------------------------------------------------- pack_values satellites
def test_pack_values_ndarray_fast_path_no_copy():
    from ytk_mp4j_tpu.comm import keycodec

    arr = np.arange(6.0).reshape(3, 2)
    out = keycodec.pack_values(arr, 3, (2,), np.float64)
    assert out is arr                       # no copy when dtype matches
    out32 = keycodec.pack_values(arr, 3, (2,), np.float32)
    assert out32.dtype == np.float32
    with pytest.raises(Mp4jError, match="share"):
        keycodec.pack_values(arr, 3, (3,), np.float64)


def test_pack_values_from_dict_view_rejects_shape_mixes():
    from ytk_mp4j_tpu.comm import keycodec

    d = {0: 1.0, 1: 2.5, 2: 3.0}
    v = keycodec.pack_values(d.values(), 3, (), np.float64)
    assert v.tolist() == [1.0, 2.5, 3.0]
    # a stray shape-(1,) array must raise, not silently flatten
    bad = {0: 1.0, 1: np.ones(1)}
    with pytest.raises(Mp4jError, match="share"):
        keycodec.pack_values(bad.values(), 2, (), np.float64)
    with pytest.raises(Mp4jError):
        keycodec.pack_values({0: "x"}.values(), 1, (), np.float64)
