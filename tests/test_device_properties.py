"""Hypothesis fuzz of the DEVICE cluster path — the analogue of
tests/test_socket_properties.py for TpuCommCluster: random lengths,
values, operators, dtypes, sub-ranges and algorithms against the numpy
oracle on the virtual 8-device mesh.

Lengths draw from a small fixed pool so the jit cache amortizes
compiles across examples (a fresh shape per example would make every
case a full XLA compile)."""

import numpy as np
import pytest
pytest.importorskip("hypothesis")  # property tests need hypothesis
from hypothesis import given, settings, strategies as st

from ytk_mp4j_tpu import meta
from ytk_mp4j_tpu.comm.tpu_comm import TpuCommCluster
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operators

from helpers import expected_reduce

LENGTHS = (1, 7, 16, 33)
OPS = ("SUM", "MAX", "MIN", "PROD")
ALGOS = ("xla", "ring", "rdma")


@pytest.fixture(scope="module")
def cluster():
    return TpuCommCluster()


def _inputs(n, length, operand, seed):
    rng = np.random.default_rng(seed)
    if operand.dtype.kind == "f":
        return [rng.standard_normal(length).astype(operand.dtype)
                for _ in range(n)]
    return [rng.integers(1, 4, length).astype(operand.dtype)
            for _ in range(n)]


def _tol(operand):
    # ring/rdma merge sequentially; float association differs
    return dict(rtol=2e-5, atol=1e-5) if operand.dtype.kind == "f" \
        else dict(rtol=0, atol=0)


@settings(max_examples=40, deadline=None)
@given(length=st.sampled_from(LENGTHS),
       op_name=st.sampled_from(OPS),
       algo=st.sampled_from(ALGOS),
       operand=st.sampled_from((Operands.FLOAT, Operands.DOUBLE,
                                Operands.INT)),
       seed=st.integers(0, 2 ** 16))
def test_allreduce_fuzz(cluster, length, op_name, algo, operand, seed):
    arrs = _inputs(cluster.n, length, operand, seed)
    want = expected_reduce(arrs, op_name)
    cluster.allreduce_array(arrs, operand, Operators.by_name(op_name),
                            algo=algo)
    for a in arrs:
        np.testing.assert_allclose(a, want, **_tol(operand))


@settings(max_examples=25, deadline=None)
@given(length=st.sampled_from(LENGTHS),
       op_name=st.sampled_from(OPS),
       algo=st.sampled_from(ALGOS),
       seed=st.integers(0, 2 ** 16))
def test_reduce_scatter_fuzz(cluster, length, op_name, algo, seed):
    operand = Operands.DOUBLE
    arrs = _inputs(cluster.n, length, operand, seed)
    want = expected_reduce(arrs, op_name)
    orig = [a.copy() for a in arrs]
    cluster.reduce_scatter_array(arrs, operand,
                                 Operators.by_name(op_name), algo=algo)
    for r, (s, e) in enumerate(meta.partition_range(0, length,
                                                    cluster.n)):
        np.testing.assert_allclose(arrs[r][s:e], want[s:e],
                                   rtol=1e-9, atol=1e-12)
        mask = np.ones(length, bool)
        mask[s:e] = False
        np.testing.assert_array_equal(arrs[r][mask], orig[r][mask])


@settings(max_examples=20, deadline=None)
@given(length=st.sampled_from(LENGTHS),
       sub=st.booleans(),
       seed=st.integers(0, 2 ** 16))
def test_subrange_fuzz(cluster, length, sub, seed):
    """Sub-ranges leave the outside untouched for every algo."""
    operand = Operands.DOUBLE
    rng = np.random.default_rng(seed)
    lo, hi = (0, length)
    if sub and length > 2:
        lo = int(rng.integers(0, length - 1))
        hi = int(rng.integers(lo + 1, length + 1))
    base = _inputs(cluster.n, length, operand, seed)
    want = expected_reduce([a[lo:hi] for a in base], "SUM")
    for algo in ALGOS:
        arrs = [a.copy() for a in base]
        cluster.allreduce_array(arrs, operand, Operators.SUM,
                                from_=lo, to=hi, algo=algo)
        for a, o in zip(arrs, base):
            np.testing.assert_allclose(a[lo:hi], want, rtol=1e-9)
            np.testing.assert_array_equal(a[:lo], o[:lo])
            np.testing.assert_array_equal(a[hi:], o[hi:])


@settings(max_examples=15, deadline=None)
@given(n_keys=st.integers(0, 30),
       overlap=st.floats(0.0, 1.0),
       op_name=st.sampled_from(("SUM", "MAX")),
       seed=st.integers(0, 2 ** 16))
def test_map_allreduce_fuzz(cluster, n_keys, overlap, op_name, seed):
    rng = np.random.default_rng(seed)
    pool = max(1, int(n_keys / max(overlap, 1e-3)))
    maps = []
    for _ in range(cluster.n):
        ks = rng.choice(pool, size=min(n_keys, pool), replace=False)
        maps.append({f"k{k}": float(rng.standard_normal()) for k in ks})
    op = Operators.by_name(op_name)
    want: dict = {}
    for m in maps:
        for k, v in m.items():
            want[k] = op.np_fn(want[k], v) if k in want else v
    cluster.allreduce_map(maps, Operands.DOUBLE, op)
    for m in maps:
        assert set(m) == set(want)
        for k in want:
            np.testing.assert_allclose(m[k], want[k], rtol=1e-12)
