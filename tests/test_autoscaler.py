"""mp4j-autopilot (ISSUE 13): the closed-loop elastic autoscaler.

Three layers, mirroring the module's design:

- **policy units** — the pure core (``decide`` / ``gate`` /
  ``resolve_pending`` / ``audit_green``) driven on synthetic
  health/membership/audit documents, no sockets;
- **round machinery** — planned eviction driven directly through
  ``Master.request_planned_evict`` (quiesce at a collective boundary,
  spare adoption via the manifest path, the victim's clean
  ``Mp4jEvicted``, bit-exact continuation), plus grow via
  ``resize_point()``;
- **chaos acceptance** — the closed loop end-to-end: a
  persistently-slow injected rank is detected (health), decided on
  (autoscaler) and replaced (planned evict + spare adoption) with NO
  test intervention between fault and recovery; the spare pool drains
  to zero and the provision hook refills it; two injected adoption
  failures trip the circuit breaker and the job still completes clean
  in recommend-only; ``off``/``observe`` grids prove no action ever
  fires.

Every value in the collective bodies is an exact small integer in
float64, so bit-exactness is ANALYTIC: round ``k`` of an N-rank
allreduce of ``full(_, k % 7 + 1)`` must equal ``N * (k % 7 + 1)``
exactly on every rank, whatever prefix of the loop the rank ran.
"""

import io
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from ytk_mp4j_tpu.comm.master import Master, REGISTER
from ytk_mp4j_tpu.comm.process_comm import ProcessCommSlave
from ytk_mp4j_tpu.exceptions import (
    Mp4jError, Mp4jEvicted, Mp4jFatalError, Mp4jSpareReleased)
from ytk_mp4j_tpu.obs import critpath, sink, spans
from ytk_mp4j_tpu.obs.cli import main as scope_main
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operators
from ytk_mp4j_tpu.resilience import autoscaler
from ytk_mp4j_tpu.transport.tcp import connect
from ytk_mp4j_tpu.utils import tuning

N = 4
JOIN = 90.0


@pytest.fixture
def fresh_spans():
    spans.clear()
    yield
    spans.clear()


@pytest.fixture
def fast_detection(monkeypatch):
    """The proven ISSUE-12 chaos parameters: 0.1 s heartbeats, a
    12-ordinal eviction streak over a 24-ordinal window."""
    monkeypatch.setenv("MP4J_HEARTBEAT_SECS", "0.1")
    monkeypatch.setenv("MP4J_HEALTH_DOMINATOR_ORDINALS", "12")
    monkeypatch.setenv("MP4J_HEALTH_WINDOW", "24")


# ----------------------------------------------------------------------
# knob validation
# ----------------------------------------------------------------------
def test_autoscale_knob_validation(monkeypatch):
    monkeypatch.setenv("MP4J_AUTOSCALE", "aggressive")
    with pytest.raises(Mp4jError):
        tuning.autoscale_mode()
    for v in ("off", "observe", "act"):
        monkeypatch.setenv("MP4J_AUTOSCALE", v)
        assert tuning.autoscale_mode() == v
    assert tuning.autoscale_mode("observe") == "observe"
    monkeypatch.setenv("MP4J_AUTOSCALE_COOLDOWN_SECS", "-1")
    with pytest.raises(Mp4jError):
        tuning.autoscale_cooldown_secs()
    monkeypatch.setenv("MP4J_AUTOSCALE_COOLDOWN_SECS", "2.5")
    assert tuning.autoscale_cooldown_secs() == 2.5
    with pytest.raises(Mp4jError):
        tuning.autoscale_budget(0)
    monkeypatch.setenv("MP4J_AUTOSCALE_BUDGET", "3")
    assert tuning.autoscale_budget() == 3
    monkeypatch.setenv("MP4J_PROVISION_CMD", " spawn-spare.sh ")
    assert tuning.provision_cmd() == "spawn-spare.sh"
    # a typo'd knob fails MASTER construction, not the first action
    monkeypatch.setenv("MP4J_AUTOSCALE", "act")
    monkeypatch.setenv("MP4J_AUTOSCALE_BUDGET", "zero")
    with pytest.raises(Mp4jError):
        Master(2, autoscale="act")


def test_elastic_grow_mode_validated(monkeypatch):
    assert tuning.elastic_mode("grow", max_retries=2) == "grow"
    # grow needs the fenced retry like every elastic mode
    with pytest.raises(Mp4jError):
        tuning.elastic_mode("grow", max_retries=0)


# ----------------------------------------------------------------------
# policy units (pure functions, no sockets)
# ----------------------------------------------------------------------
def _health_doc(evict=(), why="dominator streak"):
    return {"evict_recommended": list(evict),
            "ranks": {str(r): {"state": "EVICT_RECOMMENDED",
                               "why": why} for r in evict}}


def _ms_doc(mode="replace", spares=1, events=()):
    return {"mode": mode, "spares_available": spares,
            "events": list(events)}


def test_decide_proposes_evict_then_provision():
    props = autoscaler.decide(_health_doc([2, 3]), _ms_doc(spares=1),
                              provisionable=True)
    assert [p["action"] for p in props] == ["evict_replace"]
    assert props[0]["rank"] == 2          # lowest recommended first
    assert "dominator streak" in props[0]["why"]
    props = autoscaler.decide(_health_doc([2]), _ms_doc(spares=0),
                              provisionable=True)
    assert [p["action"] for p in props] == ["provision"]
    # an empty pool with nothing to provision WITH proposes nothing
    props = autoscaler.decide(_health_doc([2]), _ms_doc(spares=0),
                              provisionable=False)
    assert props == []


def test_decide_quiet_without_mode_or_verdicts():
    assert autoscaler.decide(_health_doc([2]), _ms_doc(mode="off"),
                             provisionable=True) == []
    assert autoscaler.decide(_health_doc([2]), _ms_doc(mode="shrink"),
                             provisionable=True) == []
    assert autoscaler.decide(_health_doc([]), _ms_doc(spares=1),
                             provisionable=False) == []
    assert autoscaler.decide(None, None, provisionable=True) == []


def _serve_doc(qps, active=True):
    return {"active": active, "qps": qps}


_LOAD_KW = dict(idle_qps=1.0, busy_qps=100.0, idle_secs=60.0)


def test_decide_load_shrink_needs_sustained_idle():
    # first idle sample only ARMS the window
    props, since = autoscaler.decide_load(
        _serve_doc(0.2), _ms_doc(), None, 1000.0, **_LOAD_KW)
    assert props == [] and since == 1000.0
    # mid-window: still quiet, window keeps its origin
    props, since = autoscaler.decide_load(
        _serve_doc(0.2), _ms_doc(), since, 1030.0, **_LOAD_KW)
    assert props == [] and since == 1000.0
    # window elapsed: propose the shrink
    props, since = autoscaler.decide_load(
        _serve_doc(0.2), _ms_doc(), since, 1061.0, **_LOAD_KW)
    assert [p["action"] for p in props] == ["serve_shrink"]
    assert "over-provisioned" in props[0]["why"]
    # a traffic burst DISARMS the window
    props, since = autoscaler.decide_load(
        _serve_doc(50.0), _ms_doc(), 1000.0, 1061.0, **_LOAD_KW)
    assert props == [] and since is None


def test_decide_load_grow_on_busy_rate_with_spares():
    props, since = autoscaler.decide_load(
        _serve_doc(250.0), _ms_doc(spares=2), None, 5.0, **_LOAD_KW)
    assert [p["action"] for p in props] == ["serve_grow"]
    assert "resize_point" in props[0]["why"]
    assert since is None
    # no spares: nothing to pace in, so no proposal
    props, _ = autoscaler.decide_load(
        _serve_doc(250.0), _ms_doc(spares=0), None, 5.0, **_LOAD_KW)
    assert props == []


def test_decide_load_quiet_for_batch_jobs():
    assert autoscaler.decide_load(
        None, _ms_doc(), 1.0, 2.0, **_LOAD_KW) == ([], None)
    assert autoscaler.decide_load(
        _serve_doc(0.0, active=False), _ms_doc(), 1.0, 2.0,
        **_LOAD_KW) == ([], None)


def test_serve_actions_are_observe_first_even_in_act_mode():
    """The ACTIONS vocabulary carries the serve pair, and the state
    ledger counts them under `observed` — by construction the tick
    wiring routes them through _observe() only (never _execute), so
    the ledger is the contract an act-mode job can rely on."""
    assert "serve_shrink" in autoscaler.ACTIONS
    assert "serve_grow" in autoscaler.ACTIONS
    st = autoscaler.ControllerState()
    assert st.serve_idle_since is None
    assert st.observed["serve_shrink"] == 0
    assert st.actions["serve_grow"] == 0


def test_gate_rails():
    st = autoscaler.ControllerState()
    kw = dict(cooldown_secs=10.0, budget=2, audit=None)
    ok, _ = autoscaler.gate(st, 100.0, "evict_replace", **kw)
    assert ok
    # one action in flight at a time
    st.pending = {"action": "provision"}
    ok, why = autoscaler.gate(st, 100.0, "evict_replace", **kw)
    assert not ok and "in flight" in why
    st.pending = None
    # per-action cooldown (another action's stamp does not block)
    st.last_action["evict_replace"] = 95.0
    ok, why = autoscaler.gate(st, 100.0, "evict_replace", **kw)
    assert not ok and "cooldown" in why
    ok, _ = autoscaler.gate(st, 100.0, "provision", **kw)
    assert ok
    ok, _ = autoscaler.gate(st, 106.0, "evict_replace", **kw)
    assert ok
    # job-lifetime budget
    st.budget_used = 2
    ok, why = autoscaler.gate(st, 106.0, "evict_replace", **kw)
    assert not ok and "budget" in why
    st.budget_used = 0
    # audit-green precondition
    ok, why = autoscaler.gate(st, 106.0, "evict_replace",
                              cooldown_secs=10.0, budget=2,
                              audit={"divergences": 1})
    assert not ok and "audit divergence" in why
    assert autoscaler.audit_green({"divergences": 0})
    assert not autoscaler.audit_green({"divergences": 3})
    # the breaker outranks everything
    st.tripped = True
    st.tripped_why = "2 consecutive failed action(s)"
    ok, why = autoscaler.gate(st, 106.0, "provision", **kw)
    assert not ok and "breaker" in why


def test_resolve_pending_success_failure_deadline():
    pend = {"action": "evict_replace", "rank": 2, "since": 50.0,
            "deadline": 80.0}
    ok_ev = {"kind": "planned_evict", "rank": 2, "spare": 0,
             "epoch": 1, "mono": 51.0}
    v, d = autoscaler.resolve_pending(
        pend, _ms_doc(events=[ok_ev]), 52.0)
    assert v == "ok" and "rank 2" in d
    # an event from BEFORE dispatch never confirms this action
    v, _ = autoscaler.resolve_pending(
        pend, _ms_doc(events=[{**ok_ev, "mono": 49.0}]), 52.0)
    assert v == "pending"
    v, d = autoscaler.resolve_pending(
        pend, _ms_doc(events=[{"kind": "evict_abort", "ranks": [2],
                               "why": "pool exhausted",
                               "mono": 51.0}]), 52.0)
    assert v == "failed" and "pool exhausted" in d
    v, d = autoscaler.resolve_pending(pend, _ms_doc(), 81.0)
    assert v == "failed" and "not confirmed" in d
    # provision resolves on pool refill
    v, _ = autoscaler.resolve_pending(
        {"action": "provision", "since": 50.0, "deadline": 80.0},
        _ms_doc(spares=1), 52.0)
    assert v == "ok"


def test_evicted_is_a_clean_fatal_subclass():
    # every wait a terminal abort breaks must break for an eviction,
    # and nothing may retry it — subclassing is the contract
    assert issubclass(Mp4jEvicted, Mp4jFatalError)


# ----------------------------------------------------------------------
# shared cluster harness
# ----------------------------------------------------------------------
def _analytic_body(rounds, size=100_000):
    """``rounds`` allreduces whose round-k result is exactly
    ``N * (k % 7 + 1)`` — resumable from any ordinal (the app-level
    half of the elastic contract: state is a pure function of the
    resume position)."""
    def body(slave, start):
        out = []
        for k in range(start, rounds):
            a = np.full(size, float(k % 7 + 1))
            slave.allreduce_array(a, Operands.DOUBLE,
                                  Operators.SUM)
            out.append(float(a[0]))
        return out
    return body


def _check_analytic(vals, rounds, n=N):
    start = rounds - len(vals)
    for j, v in enumerate(vals):
        assert v == n * ((start + j) % 7 + 1), (start, j, v)


def _run_autopilot(rounds, *, master_kwargs, slave_kwargs=None,
                   spare_count=0, body=None, join=JOIN):
    """Master + N workers + ``spare_count`` real spares; workers that
    get evicted record it and close(0). Returns (results-by-final-
    rank, errors, evicted, spares, master, log)."""
    log = io.StringIO()
    mk = dict(master_kwargs)
    mk.setdefault("spares", spare_count)
    master = Master(N, timeout=join, log_stream=log,
                    **mk).serve_in_thread()
    body = body or _analytic_body(rounds)
    results: dict[int, list] = {}
    errors: list = [None] * N
    evicted: dict = {}
    spares: list[dict] = [{} for _ in range(spare_count)]

    def worker(i):
        s = None
        try:
            s = ProcessCommSlave("127.0.0.1", master.port,
                                 timeout=join, dead_rank_secs=30.0,
                                 **(slave_kwargs or {}))
            results[s.rank] = body(s, 0)
            s.close(0)
        except Mp4jEvicted as e:
            evicted[s.rank] = str(e)
            s.close(0)
        except Exception as e:
            errors[s.rank if s is not None else i] = e
            if s is not None:
                try:
                    s.close(1)
                except Exception:
                    pass

    def spare_worker(k):
        s = None
        try:
            kw = dict(slave_kwargs or {})
            kw.pop("fault_plan", None)   # spares are healthy
            s = ProcessCommSlave("127.0.0.1", master.port,
                                 timeout=join * 2, spare=True,
                                 dead_rank_secs=30.0, **kw)
            spares[k]["adopted_rank"] = s.rank
            spares[k]["resume_seq"] = s.resume_seq
            results[s.rank] = body(s, s.resume_seq)
            s.close(0)
        except Mp4jSpareReleased as e:
            spares[k]["released"] = str(e)
        except Exception as e:
            spares[k]["error"] = e

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(N)]
    threads += [threading.Thread(target=spare_worker, args=(k,),
                                 daemon=True)
                for k in range(spare_count)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + join
    for t in threads:
        t.join(max(0.1, deadline - time.monotonic()))
    hung = [i for i, t in enumerate(threads) if t.is_alive()]
    assert not hung, f"threads {hung} hung:\n{log.getvalue()[-6000:]}"
    master.join(15.0)
    return results, errors, evicted, spares, master, log.getvalue()


SLOW3 = "slow:rank=3:secs=0.02:nth=20"


# ----------------------------------------------------------------------
# chaos acceptance: the closed loop, autonomously
# ----------------------------------------------------------------------
def test_autopilot_evicts_slow_rank_autonomously(fast_detection,
                                                 fresh_spans,
                                                 tmp_path):
    """THE acceptance proof: with MP4J_AUTOSCALE=act, a slow-injected
    rank is replaced with NO intervention between fault and recovery —
    detection (health dominator streak), decision (autoscaler),
    action (planned evict + spare adoption) all autonomous; every
    rank's results are bit-exact, survivors see zero errors, the
    victim exits with a clean Mp4jEvicted, and the action history
    lands in the durable sink interleaved with the verdicts."""
    d = str(tmp_path / "trail")
    rounds = 240
    results, errors, evicted, spares, master, log = _run_autopilot(
        rounds,
        master_kwargs={"elastic": "replace", "adopt_secs": 10.0,
                       "autoscale": "act", "autoscale_cooldown": 2.0,
                       "autoscale_tick": 0.2},
        slave_kwargs={"elastic": "replace", "fault_plan": SLOW3,
                      "sink_dir": d},
        spare_count=1)
    assert all(e is None for e in errors), f"{errors}\n{log[-4000:]}"
    assert list(evicted) == [3], (evicted, log[-4000:])
    assert "evicted by the autoscaler" in evicted[3]
    assert spares[0].get("adopted_rank") == 3, (spares, log[-4000:])
    assert master.final_code == 0, log[-4000:]
    # bit-exact: every rank's analytic values, over whatever suffix/
    # prefix of the loop it ran — fault to recovery fully covered
    assert set(results) == set(range(N))
    for r in range(N):
        _check_analytic(results[r], rounds)
    # the spare resumed mid-job (not from 0): the loop really was
    # closed mid-flight, not restarted
    assert 0 < spares[0]["resume_seq"] < rounds
    # the controller's ledger: ONE net eviction, no failures. Under a
    # scheduler tail the boundary fence can cancel benignly and
    # re-dispatch after the cooldown — by design the dispatch counter
    # stays monotone (Prometheus rate()) and the refund lands in
    # `retried`, so net = dispatched - retried (seen 1-in-5 on the
    # loaded 1-core CI host; the single-eviction outcome assertions
    # above are unchanged)
    asc = master.autoscale_status()
    dispatched = asc["actions"]["evict_replace"]
    retried = asc["retried"].get("evict_replace", 0)
    assert dispatched - retried == 1, asc
    assert not any(asc["failures"].values()), asc
    assert not asc["tripped"]
    assert master.membership_status()["planned_evictions"] == 1
    assert "planned eviction" in log
    # timeline satellite: the action events interleave with verdict
    # transitions in the durable sink's alert history
    analysis = critpath.analyze(sink.load_job(d))
    kinds = {ev.get("kind") for ev in analysis["health_alerts"]}
    assert "autoscale" in kinds and "state" in kinds, kinds
    acts = [ev for ev in analysis["health_alerts"]
            if ev.get("kind") == "autoscale"
            and ev.get("event") == "action"]
    assert acts and acts[0]["action"] == "evict_replace"
    assert scope_main(["health", d]) == 0


def test_autopilot_provisions_spare_when_pool_drains(fast_detection,
                                                     fresh_spans):
    """Pool drains to 0 -> the provision hook fires (once — the
    cooldown holds) -> the provisioned spare registers and is adopted
    by the subsequent planned eviction."""
    rounds = 240
    hook_calls = []
    provisioned: dict = {}
    body = _analytic_body(rounds)

    def run_provisioned_spare(master):
        try:
            s = ProcessCommSlave("127.0.0.1", master.port,
                                 timeout=60.0, spare=True,
                                 dead_rank_secs=30.0,
                                 elastic="replace")
        except Mp4jSpareReleased:
            # a LATER provisioned spare (the controller refills the
            # pool again after the eviction consumed the first one)
            # idles to release at job end — the success case
            return
        provisioned["rank"] = s.rank
        provisioned["resume_seq"] = s.resume_seq
        provisioned["result"] = body(s, s.resume_seq)
        s.close(0)

    def hook(master):
        hook_calls.append(time.monotonic())
        threading.Thread(target=run_provisioned_spare, args=(master,),
                         daemon=True).start()

    results, errors, evicted, _, master, log = _run_autopilot(
        rounds,
        master_kwargs={"elastic": "replace", "adopt_secs": 10.0,
                       "autoscale": "act", "autoscale_cooldown": 2.0,
                       "autoscale_tick": 0.2, "provision_hook": hook},
        slave_kwargs={"elastic": "replace", "fault_plan": SLOW3},
        spare_count=0)
    assert all(e is None for e in errors), f"{errors}\n{log[-4000:]}"
    # the hook fired; a SECOND firing is legitimate (the eviction
    # consumed the provisioned spare, so the pool hit 0 again and
    # the controller refilled it after the cooldown) — the cooldown
    # is what bounds the rate, not a one-shot rule
    assert len(hook_calls) >= 1, hook_calls
    if len(hook_calls) >= 2:
        assert hook_calls[1] - hook_calls[0] >= 2.0, hook_calls
    assert list(evicted) == [3], (evicted, log[-4000:])
    assert provisioned.get("rank") == 3, (provisioned, log[-4000:])
    assert master.final_code == 0, log[-4000:]
    for r in range(N):
        vals = results[r] if r != 3 else provisioned["result"]
        _check_analytic(vals, rounds)
    asc = master.autoscale_status()
    assert asc["actions"]["provision"] >= 1
    # net evictions (dispatched minus benign fence-cancel retries; the
    # dispatch counter is monotone by design — see the autonomous-evict
    # test's ledger note)
    assert asc["actions"]["evict_replace"] \
        - asc["retried"].get("evict_replace", 0) == 1, asc
    assert not asc["tripped"]


def _fake_spare(master, died=None):
    """A spare that registers, pings, reads its adopt message and
    drops dead without acking — the injected adoption failure."""
    ch = connect("127.0.0.1", master.port, timeout=JOIN)
    ch.send_obj((REGISTER, {"listen_port": 1, "host": "127.0.0.1",
                            "fp": "", "spare": True}))
    ch.recv()                       # registration ack
    try:
        ch.set_timeout(JOIN)
        ch.recv()                   # the adopt message
    except Exception:
        pass
    ch.close()                      # die without acking
    if died is not None:
        died.append(1)


def test_circuit_breaker_trips_after_two_failed_evictions(
        fast_detection, fresh_spans):
    """Safety proof: two consecutive planned evictions whose spares
    all die mid-adoption (the rounds abort back to plain releases)
    trip the breaker to recommend-only — and the job STILL completes
    clean, slow rank and all, with a structured trip alert and the
    Prometheus gauge set. A real spare registered after the trip is
    never consumed."""
    rounds = 320
    # compute-paced body: the 40 ms gap dwarfs the 20 ms injected
    # slowness, so at any quiesce instant the victim is either inside
    # the SAME collective as its peers or idle one behind — exactly
    # the coherent shapes an abandoned eviction may safely release
    # (the abandon-soundness rule in _try_advance_round); detection
    # still sees every ordinal gated by rank 3's in-collective delay
    def body(slave, start):
        out = []
        for k in range(start, rounds):
            a = np.full(20_000, float(k % 7 + 1))
            slave.allreduce_array(a, Operands.DOUBLE,
                                  Operators.SUM)
            out.append(float(a[0]))
            time.sleep(0.04)
        return out

    log = io.StringIO()
    master = Master(N, timeout=JOIN, log_stream=log,
                    elastic="replace", spares=0, adopt_secs=8.0,
                    autoscale="act", autoscale_cooldown=1.0,
                    autoscale_tick=0.2).serve_in_thread()
    results: dict[int, list] = {}
    errors: list = [None] * N

    def worker(i):
        s = None
        try:
            s = ProcessCommSlave("127.0.0.1", master.port,
                                 timeout=JOIN, dead_rank_secs=30.0,
                                 elastic="replace", fault_plan=SLOW3)
            results[s.rank] = body(s, 0)
            s.close(0)
        except Exception as e:
            errors[s.rank if s is not None else i] = e

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(N)]
    for t in threads:
        t.start()

    # two waves of two fake spares: wave 1 fails action 1, wave 2
    # fails action 2 -> trip; then one REAL spare that must idle
    real_released: dict = {}

    def real_spare():
        try:
            ProcessCommSlave("127.0.0.1", master.port, timeout=JOIN,
                             spare=True, dead_rank_secs=30.0,
                             elastic="replace")
        except Mp4jSpareReleased as e:
            real_released["why"] = str(e)
        except Exception as e:
            real_released["error"] = e

    def orchestrate():
        for _ in range(2):
            threading.Thread(target=_fake_spare, args=(master,),
                             daemon=True).start()
        deadline = time.monotonic() + 60.0
        fails_seen = 0
        while time.monotonic() < deadline:
            asc = master.autoscale_status() or {}
            if asc.get("tripped"):
                break
            fails = asc.get("consecutive_failures", 0)
            if fails == 1 and fails_seen == 0:
                fails_seen = 1
                for _ in range(2):
                    threading.Thread(target=_fake_spare,
                                     args=(master,),
                                     daemon=True).start()
            time.sleep(0.2)
        threading.Thread(target=real_spare, daemon=True).start()

    orch = threading.Thread(target=orchestrate, daemon=True)
    orch.start()
    deadline = time.monotonic() + JOIN
    for t in threads:
        t.join(max(0.1, deadline - time.monotonic()))
    assert not any(t.is_alive() for t in threads), \
        f"ranks hung:\n{log.getvalue()[-6000:]}"
    orch.join(10.0)
    master.join(15.0)

    txt = log.getvalue()
    assert all(e is None for e in errors), f"{errors}\n{txt[-5000:]}"
    assert master.final_code == 0, txt[-5000:]
    for r in range(N):
        _check_analytic(results[r], rounds)
    asc = master.autoscale_status()
    assert asc["tripped"], (asc, txt[-5000:])
    assert asc["consecutive_failures"] >= 2
    assert "circuit breaker tripped" in txt
    ms = master.membership_status()
    assert ms["planned_evictions"] == 0           # nothing ever landed
    aborts = [e for e in ms["events"] if e["kind"] == "evict_abort"]
    assert len(aborts) >= 2, ms["events"]
    # tripped -> recommend-only: the real spare was never consumed
    assert "why" in real_released, (real_released, txt[-3000:])


# ----------------------------------------------------------------------
# off / observe grids: no action ever fires
# ----------------------------------------------------------------------
def test_autoscale_off_is_todays_behavior(fast_detection, fresh_spans):
    """MP4J_AUTOSCALE=off: no controller exists at all — the slow
    rank keeps its verdict, the spare idles to release, zero
    membership changes. Today's behavior bit-for-bit."""
    rounds = 160
    results, errors, evicted, spares, master, log = _run_autopilot(
        rounds,
        master_kwargs={"elastic": "replace", "adopt_secs": 10.0,
                       "autoscale": "off"},
        slave_kwargs={"elastic": "replace", "fault_plan": SLOW3},
        spare_count=1)
    assert all(e is None for e in errors), f"{errors}\n{log[-3000:]}"
    assert evicted == {}, evicted
    assert master.final_code == 0
    assert master.autoscale_status() is None
    assert master.metrics_doc()["cluster"]["autoscale"] is None
    ms = master.membership_status()
    assert ms["planned_evictions"] == 0 and ms["replacements"] == 0
    assert not any(e["kind"].startswith(("planned_evict", "grow"))
                   for e in ms["events"])
    assert "released" in spares[0], spares
    assert "autoscale:" not in log
    for r in range(N):
        _check_analytic(results[r], rounds)


def test_autoscale_observe_logs_but_never_acts(fast_detection,
                                               fresh_spans):
    """MP4J_AUTOSCALE=observe: the controller runs the full decision
    path and LOGS the would-be eviction, but the roster never
    changes and the spare idles to release."""
    rounds = 240
    results, errors, evicted, spares, master, log = _run_autopilot(
        rounds,
        master_kwargs={"elastic": "replace", "adopt_secs": 10.0,
                       "autoscale": "observe",
                       "autoscale_cooldown": 1.0,
                       "autoscale_tick": 0.2},
        slave_kwargs={"elastic": "replace", "fault_plan": SLOW3},
        spare_count=1)
    assert all(e is None for e in errors), f"{errors}\n{log[-3000:]}"
    assert evicted == {}, evicted
    assert master.final_code == 0
    asc = master.autoscale_status()
    assert asc["mode"] == "observe"
    assert sum(asc["actions"].values()) == 0
    assert asc["observed"]["evict_replace"] >= 1, (asc, log[-3000:])
    assert "would evict_replace" in log
    ms = master.membership_status()
    assert ms["planned_evictions"] == 0 and ms["replacements"] == 0
    assert "released" in spares[0], spares
    for r in range(N):
        _check_analytic(results[r], rounds)


# ----------------------------------------------------------------------
# grow mode: resize_point() expands n between epochs
# ----------------------------------------------------------------------
def _grow_cluster(autoscale_mode, n0=2, spare_count=2, join=JOIN):
    """n0 ranks run pre-resize collectives, hit resize_point(), run
    post-resize collectives at whatever n came back; spares run the
    post half when adopted."""
    log = io.StringIO()
    master = Master(n0, timeout=join, log_stream=log, elastic="grow",
                    spares=spare_count, adopt_secs=10.0,
                    autoscale=autoscale_mode, autoscale_cooldown=0.0,
                    autoscale_tick=0.2).serve_in_thread()
    out: dict = {}
    errs: dict = {}

    def post(s):
        a = np.ones(4096)
        s.allreduce_array(a, Operands.DOUBLE, Operators.SUM)
        d = {f"k{s.rank}": np.float64(1.0), "shared": np.float64(2.0)}
        s.allreduce_map(d)
        return float(a[0]), {k: float(v) for k, v in d.items()}

    def worker(i):
        s = None
        try:
            s = ProcessCommSlave("127.0.0.1", master.port,
                                 timeout=join, dead_rank_secs=30.0,
                                 elastic="grow")
            a = np.ones(4096)
            s.allreduce_array(a, Operands.DOUBLE,
                              Operators.SUM)   # at n0
            out[("pre", s.rank)] = float(a[0])
            roster = s.resize_point()
            out[("roster", s.rank)] = len(roster)
            out[("n", s.rank)] = s.slave_num
            out[("post", s.rank)] = post(s)
            s.close(0)
        except Exception as e:
            errs[i] = e

    def spare_worker(k):
        s = None
        try:
            s = ProcessCommSlave("127.0.0.1", master.port,
                                 timeout=join * 2, spare=True,
                                 dead_rank_secs=30.0, elastic="grow")
            out[("adopt", k)] = (s.rank, s.resume_seq, s.slave_num)
            out[("post", s.rank)] = post(s)
            s.close(0)
        except Mp4jSpareReleased as e:
            out[("released", k)] = str(e)
        except Exception as e:
            errs[("sp", k)] = e

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(n0)]
    threads += [threading.Thread(target=spare_worker, args=(k,),
                                 daemon=True)
                for k in range(spare_count)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + join
    for t in threads:
        t.join(max(0.1, deadline - time.monotonic()))
    assert not any(t.is_alive() for t in threads), \
        f"hung:\n{log.getvalue()[-5000:]}"
    master.join(15.0)
    return out, errs, master, log.getvalue()


def test_grow_expands_n_at_resize_point(fresh_spans):
    """MP4J_ELASTIC=grow + MP4J_AUTOSCALE=act: resize_point() adopts
    both registered spares into NEW rank ids, every rank returns the
    grown roster, and the post-resize collectives (dense + columnar
    map — the vocabulary seeded from rank 0's donation) run at n=4."""
    out, errs, master, log = _grow_cluster("act")
    assert not errs, (errs, log[-4000:])
    assert master.final_code == 0, log[-4000:]
    n = 4
    assert out[("roster", 0)] == n and out[("n", 1)] == n
    assert {out[("adopt", k)][0] for k in range(2)} == {2, 3}
    for r in range(n):
        a0, d = out[("post", r)]
        assert a0 == float(n)                     # bit-exact at n=4
        assert d["shared"] == 2.0 * n
        assert all(d[f"k{j}"] == 1.0 for j in range(n))
    ms = master.membership_status()
    assert ms["grows"] == 1
    assert any(e["kind"] == "grow" and e["ranks"] == [2, 3]
               for e in ms["events"])
    asc = master.autoscale_status()
    assert asc["actions"]["grow"] == 1
    assert "grow round complete" in log


def test_grow_observe_mode_keeps_roster(fresh_spans):
    """observe: resize_point() is a no-op rendezvous — the would-be
    growth is logged, the spares idle to release, n stays n0."""
    out, errs, master, log = _grow_cluster("observe")
    assert not errs, (errs, log[-4000:])
    assert master.final_code == 0, log[-4000:]
    assert out[("roster", 0)] == 2 and out[("n", 1)] == 2
    assert ("released", 0) in out and ("released", 1) in out, out
    for r in range(2):
        a0, d = out[("post", r)]
        assert a0 == 2.0 and d["shared"] == 4.0
    asc = master.autoscale_status()
    assert asc["actions"]["grow"] == 0
    assert asc["observed"]["grow"] >= 1
    assert master.membership_status()["grows"] == 0
    assert "would grow" in log or "adopt 2 spare(s)" in log


def test_grow_joiner_immediate_second_resize(fresh_spans):
    """Freshly adopted grow joiners' apps may hit their NEXT
    resize_point immediately — the completeness scan their arrivals
    trigger must neither release the still-finalizing generation
    unchanged (the orphaned-grow regression) nor complete the NEXT
    generation early against the old slave_num (with TWO joiners,
    gen+1 collects 2 arrivals == the pre-grow n while the survivors
    are still inside gen's grow — out-of-order completion would
    strand the survivors' eventual arrivals forever)."""
    log = io.StringIO()
    master = Master(2, timeout=JOIN, log_stream=log, elastic="grow",
                    spares=2, adopt_secs=10.0, autoscale="act",
                    autoscale_cooldown=0.0,
                    autoscale_tick=0.2).serve_in_thread()
    out: dict = {}
    errs: dict = {}

    def finish(s, tag):
        r2 = s.resize_point()           # gen 1: no spares -> no-op
        a = np.ones(1024)
        s.allreduce_array(a, Operands.DOUBLE, Operators.SUM)
        out[tag] = (len(r2), s.slave_num, float(a[0]))
        s.close(0)

    def worker(i):
        s = None
        try:
            s = ProcessCommSlave("127.0.0.1", master.port,
                                 timeout=JOIN, dead_rank_secs=30.0,
                                 elastic="grow")
            s.resize_point()            # gen 0: grows 2 -> 4
            finish(s, ("w", s.rank))
        except Exception as e:
            errs[i] = e

    def spare_worker():
        s = None
        try:
            s = ProcessCommSlave("127.0.0.1", master.port,
                                 timeout=JOIN * 2, spare=True,
                                 dead_rank_secs=30.0, elastic="grow")
            # adopted at gen 0 with resize_gen seeded to 1: the very
            # first thing the continuation does is resize again —
            # the racing arrival this regression pins
            finish(s, ("j", s.rank))
        except Exception as e:
            errs["sp"] = e

    threads = [threading.Thread(target=worker, args=(i,),
                                daemon=True) for i in range(2)]
    threads += [threading.Thread(target=spare_worker, daemon=True)
                for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(JOIN)
    assert not any(t.is_alive() for t in threads), \
        f"hung:\n{log.getvalue()[-5000:]}"
    master.join(15.0)
    assert not errs, (errs, log.getvalue()[-4000:])
    assert master.final_code == 0, log.getvalue()[-4000:]
    assert set(out) == {("w", 0), ("w", 1), ("j", 2), ("j", 3)}, out
    for tag, (roster_n, n, a0) in out.items():
        assert roster_n == 4 and n == 4 and a0 == 4.0, (tag, out)
    assert master.membership_status()["grows"] == 1


def test_resize_point_noop_when_elastic_off(fresh_spans):
    """resize_point() exists on every job: without grow mode it is a
    cheap rendezvous returning the unchanged roster."""
    log = io.StringIO()
    master = Master(2, timeout=30.0,
                    log_stream=log).serve_in_thread()
    out = {}

    def worker(i):
        s = ProcessCommSlave("127.0.0.1", master.port, timeout=30.0)
        out[s.rank] = len(s.resize_point())
        a = np.ones(128)
        s.allreduce_array(a, Operands.DOUBLE, Operators.SUM)
        out[("post", s.rank)] = float(a[0])
        s.close(0)

    ts = [threading.Thread(target=worker, args=(i,), daemon=True)
          for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(30.0)
    assert not any(t.is_alive() for t in ts), log.getvalue()
    master.join(10.0)
    assert master.final_code == 0
    assert out[0] == 2 and out[1] == 2
    assert out[("post", 0)] == 2.0


# ----------------------------------------------------------------------
# observability surfaces
# ----------------------------------------------------------------------
def test_autoscale_prometheus_and_live_surfaces(fast_detection,
                                                fresh_spans):
    """The ledger lands on /metrics (R17-documented families), in the
    metrics document, and on the mp4j-scope live head-line."""
    from ytk_mp4j_tpu.obs import telemetry
    rounds = 240
    hold = threading.Event()
    log = io.StringIO()
    master = Master(N, timeout=JOIN, log_stream=log,
                    elastic="replace", spares=1, adopt_secs=10.0,
                    autoscale="act", autoscale_cooldown=2.0,
                    autoscale_tick=0.2,
                    metrics_port=0).serve_in_thread()
    body = _analytic_body(rounds, size=60_000)
    results: dict = {}
    errors: list = [None] * N
    evicted: dict = {}

    def worker(i):
        s = None
        try:
            s = ProcessCommSlave("127.0.0.1", master.port,
                                 timeout=JOIN, dead_rank_secs=30.0,
                                 elastic="replace", fault_plan=SLOW3)
            results[s.rank] = body(s, 0)
            hold.wait(30.0)
            s.close(0)
        except Mp4jEvicted:
            evicted[s.rank] = True
            s.close(0)
        except Exception as e:
            errors[s.rank if s is not None else i] = e

    def spare_worker():
        try:
            s = ProcessCommSlave("127.0.0.1", master.port,
                                 timeout=JOIN * 2, spare=True,
                                 dead_rank_secs=30.0,
                                 elastic="replace")
            results[s.rank] = body(s, s.resume_seq)
            hold.wait(30.0)
            s.close(0)
        except Mp4jSpareReleased:
            pass
        except Exception as e:
            errors[0] = errors[0] or e

    threads = [threading.Thread(target=worker, args=(i,),
                                daemon=True) for i in range(N)]
    threads.append(threading.Thread(target=spare_worker, daemon=True))
    for t in threads:
        t.start()
    try:
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            asc = master.autoscale_status()
            if asc and asc["actions"]["evict_replace"] >= 1 \
                    and evicted:
                break
            time.sleep(0.2)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{master.metrics_port}/metrics",
                timeout=5.0) as resp:
            text = resp.read().decode()
        assert ('mp4j_autoscale_actions_total{action="evict_replace"}'
                in text), text[-2000:]
        assert "mp4j_autoscale_tripped 0" in text
        doc = master.metrics_doc()
        frame = telemetry.format_live(doc)
        assert "autoscale: mode=act" in frame, frame
        assert all(len(ln) <= 120 for ln in frame.splitlines()), frame
    finally:
        hold.set()
    for t in threads:
        t.join(60.0)
    assert not any(t.is_alive() for t in threads), \
        log.getvalue()[-5000:]
    master.join(15.0)
    assert all(e is None for e in errors), errors
    assert master.final_code == 0


def test_postmortem_reports_autoscaler_section(tmp_path):
    """The manifest freezes the controller ledger and the merged
    report renders the actions-taken section."""
    from ytk_mp4j_tpu.obs import postmortem
    asc = {"mode": "act", "tripped": True,
           "tripped_why": "2 consecutive failed action(s); last: "
                          "adoption timeout",
           "actions": {"evict_replace": 2, "provision": 1, "grow": 0},
           "observed": {"evict_replace": 0, "provision": 0, "grow": 0},
           "budget": {"limit": 16, "used": 3},
           "events": [{"id": -1, "wall": 1000.0, "kind": "autoscale",
                       "event": "action", "action": "evict_replace",
                       "rank": 2, "mode": "act",
                       "msg": "health verdict EVICT_RECOMMENDED"}]}
    postmortem.write_master_manifest(
        str(tmp_path), slave_num=N, reason="test fatal", table={},
        departed={}, diagnosis=["d"], autoscale=asc)
    report = postmortem.merge_report(str(tmp_path))
    assert "autoscaler: mode=act TRIPPED" in report
    assert "breaker tripped" in report
    assert "autoscaler event: action evict_replace rank 2" in report
