"""mp4j-fleet tests (ISSUE 18): the cross-job fleet plane.

Three layers:

- pure folds: ``job_summary`` / ``fold_fleet`` / ``detect_contention``
  over synthetic control documents (the contention semantics are fully
  specified here — overlapping busy windows + simultaneous slow-link
  verdicts on one host fingerprint);
- the poller state machine (``LIVE -> STALE -> GONE``, restart via
  job-id change, backoff, garbage absorption) driven deterministically
  through the injectable ``fetch``/``now`` seams;
- the acceptance criterion end-to-end: two REAL concurrent jobs
  (separate masters, separate processes, ephemeral metrics ports) on
  this host, the poller folds both and names the shared host with
  per-job byte attribution; SIGKILL of one entire job degrades its
  rows ``STALE -> GONE`` with zero poller exceptions while the
  survivor stays LIVE; ``fleet-report`` reconstructs the merged
  timeline including the death from crc-framed fleet segments, which
  survive byte-level truncation (the sink torn-tail property).
"""

import json
import os
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

import pytest

from tests.helpers import REPO_ROOT
from ytk_mp4j_tpu.obs import fleet, sink as sink_mod, telemetry
from ytk_mp4j_tpu.obs.cli import main as scope_main


# ----------------------------------------------------------------------
# synthetic control documents
# ----------------------------------------------------------------------
def _mdoc(jid, *, fp="hostA", bps=100.0, slow=True, nranks=2,
          roster_gen=1, health_states=None):
    """A minimal /metrics.json document: ``nranks`` ranks on one host
    fingerprint, each moving ``bps`` bytes/s, with (optionally) a
    tuner applied-decision on every rank — the slow-link verdict."""
    ranks = {}
    tuner = {"ranks": {}}
    for i in range(nranks):
        r = str(i)
        ranks[r] = {
            "host_fp": fp,
            "stats": {"allreduce_array": {"bytes_sent": 1000,
                                          "bytes_recv": 1000,
                                          "retries": 1}},
            "rates": {"bytes_per_sec": bps},
        }
        if slow:
            tuner["ranks"][r] = {"applied": {
                str((i + 1) % nranks): {"chunk_bytes": 4096,
                                        "compress": None}}}
    hs = health_states or {}
    return {
        "job_id": jid, "started_wall": 1.0, "roster_gen": roster_gen,
        "slave_num": nranks, "ranks": ranks,
        "cluster": {
            "rates": {"bytes_per_sec": nranks * bps,
                      "collectives_per_sec": 5.0, "keys_per_sec": 1.0},
            "tuner": tuner,
            "health": {"ranks": {r: {"state": s}
                                 for r, s in hs.items()},
                       "alerts_total": 0},
        },
    }


# ----------------------------------------------------------------------
# pure folds
# ----------------------------------------------------------------------
def test_job_summary_folds_hosts_health_and_bytes():
    s = fleet.job_summary(_mdoc("aaaa", health_states={
        "0": "HEALTHY", "1": "DEGRADED"}))
    assert s["job_id"] == "aaaa" and s["slave_num"] == 2
    h = s["hosts"]["hostA"]
    assert h["ranks"] == [0, 1]
    assert h["wire_bytes"] == 4000          # 2 ranks x (1000+1000)
    assert h["bytes_per_sec"] == pytest.approx(200.0)
    assert h["slow_links"] == ["0->1", "1->0"]
    assert s["retries"] == 2
    assert s["health"]["states"] == {"HEALTHY": 1, "DEGRADED": 1}


def test_job_summary_health_falls_back_to_metrics_doc():
    # health endpoint unreachable (hdoc None): the metrics doc's
    # cluster.health section carries the same schema
    doc = _mdoc("aaaa", health_states={"0": "CRITICAL", "1": "HEALTHY"})
    s = fleet.job_summary(doc, None)
    assert s["health"]["states"] == {"HEALTHY": 1, "CRITICAL": 1}
    # an explicit health doc WINS over the embedded section
    s2 = fleet.job_summary(doc, {"ranks": {"0": {"state": "HEALTHY"},
                                           "1": {"state": "HEALTHY"}},
                                 "alerts_total": 7})
    assert s2["health"]["states"] == {"HEALTHY": 2}
    assert s2["health"]["alerts_total"] == 7


def test_fold_fleet_shared_host_contention_and_aggregate():
    js = {u: {"url": u, "state": fleet.LIVE, "age": 0.1,
              "summary": fleet.job_summary(_mdoc(j))}
          for u, j in (("u1", "aaaa"), ("u2", "bbbb"))}
    m = fleet.fold_fleet(js)
    assert m["shared_hosts"] == ["hostA"]
    row = m["hosts"]["hostA"]["jobs"]
    assert set(row) == {"aaaa", "bbbb"}
    assert all(j["wire_bytes"] == 4000 for j in row.values())
    [c] = m["contention"]
    assert c["host_fp"] == "hostA" and c["jobs"] == ["aaaa", "bbbb"]
    assert set(c["slow"]) == {"aaaa", "bbbb"}
    assert m["aggregate"]["live"] == 2 and m["aggregate"]["ranks"] == 4
    assert m["aggregate"]["bytes_per_sec"] == pytest.approx(400.0)
    # render: both ids, the shared host and the contention line
    text = telemetry.format_fleet(m)
    assert "aaaa" in text and "bbbb" in text
    assert "shared host hostA" in text and "CONTENTION" in text


def test_fold_fleet_stale_job_is_history_not_load():
    """A STALE job's last summary still places its ranks on the host
    (co-residency) but contributes NO byte rate — a frozen rate from
    a wedged master must not manufacture phantom load or contention."""
    js = {"u1": {"url": "u1", "state": fleet.LIVE, "age": 0.1,
                 "summary": fleet.job_summary(_mdoc("aaaa"))},
          "u2": {"url": "u2", "state": fleet.STALE, "age": 9.0,
                 "summary": fleet.job_summary(_mdoc("bbbb"))}}
    m = fleet.fold_fleet(js)
    assert m["shared_hosts"] == ["hostA"]           # still co-resident
    assert m["hosts"]["hostA"]["jobs"]["bbbb"]["bytes_per_sec"] == 0.0
    assert m["contention"] == []                    # only one busy job
    assert m["aggregate"]["live"] == 1
    assert m["aggregate"]["bytes_per_sec"] == pytest.approx(200.0)


def test_detect_contention_needs_two_busy_and_two_slow():
    def host(jobs):
        return {"fp": {"jobs": jobs}}
    busy_slow = {"bytes_per_sec": 10.0, "slow_links": ["0->1"]}
    busy_ok = {"bytes_per_sec": 10.0, "slow_links": []}
    idle_slow = {"bytes_per_sec": 0.0, "slow_links": ["0->1"]}
    # two busy, both slow -> contended
    assert fleet.detect_contention(host({"a": busy_slow,
                                         "b": busy_slow}))
    # two busy, one slow -> not contended (no simultaneous verdicts)
    assert not fleet.detect_contention(host({"a": busy_slow,
                                             "b": busy_ok}))
    # one busy one idle, both holding verdicts -> no overlapping busy
    # window, not contended
    assert not fleet.detect_contention(host({"a": busy_slow,
                                             "b": idle_slow}))
    # the "" fingerprint is the MP4J_SHM=0 opt-out, never a host
    assert not fleet.detect_contention(
        {"": {"jobs": {"a": busy_slow, "b": busy_slow}}})


# ----------------------------------------------------------------------
# the poller state machine (injected fetch + clock)
# ----------------------------------------------------------------------
def _stage():
    return {"clock": [0.0], "alive": [True], "jid": ["cafe"],
            "fetches": [0]}


def _poller(st, **kw):
    def fetch(url):
        st["fetches"][0] += 1
        if not st["alive"][0]:
            raise OSError("connection refused")
        return _mdoc(st["jid"][0]), None
    kw.setdefault("poll_secs", 1.0)
    kw.setdefault("stale_secs", 2.0)
    return fleet.FleetPoller(["h:1"], fetch=fetch,
                             now=lambda: st["clock"][0], **kw)


def test_poller_live_stale_gone_ladder_and_recovery():
    st = _stage()
    p = _poller(st)
    p.poll_once()
    assert p.states() == {"http://h:1": fleet.LIVE}
    st["alive"][0] = False
    # GONE at 3x stale_secs after the last good scrape; the ladder
    # advances every sweep even while backoff skips the fetch itself
    for t in (1.5, 3.0, 5.5, 10.0, 20.0, 40.0):
        st["clock"][0] = t
        p.poll_once()
    assert p.states() == {"http://h:1": fleet.GONE}
    assert p.scrape_errors > 0
    # a model is still produced, with the last summary flagged GONE
    m = p.model()
    assert m["jobs"]["http://h:1"]["state"] == fleet.GONE
    assert m["jobs"]["http://h:1"]["summary"]["job_id"] == "cafe"
    # recovery under the SAME job id: back, not a restart
    st["alive"][0] = True
    st["clock"][0] = 60.0
    p.poll_once()
    assert p.states() == {"http://h:1": fleet.LIVE}
    kinds = [e["kind"] for e in p.events()]
    assert kinds == ["job_up", "job_stale", "job_gone", "job_back"]


def test_poller_detects_restart_via_job_id_change():
    st = _stage()
    p = _poller(st)
    p.poll_once()
    st["jid"][0] = "beef"                   # master restarted in place
    st["clock"][0] = 1.0
    p.poll_once()
    ev = p.events()[-1]
    assert ev["kind"] == "job_restart"
    assert "cafe" in ev["msg"] and "beef" in ev["msg"]
    assert p.states() == {"http://h:1": fleet.LIVE}


def test_poller_backoff_skips_probes_of_a_dead_master():
    st = _stage()
    p = _poller(st)
    p.poll_once()
    st["alive"][0] = False
    # many sweeps in a short window: capped exponential backoff must
    # collapse most of them into no-fetch staleness bookkeeping
    for i in range(1, 40):
        st["clock"][0] = i * 0.5
        p.poll_once()
    assert st["fetches"][0] < 20            # 1 good + a backoff tail
    assert p.states() == {"http://h:1": fleet.GONE}


def test_poller_absorbs_garbage_documents():
    """poll_once never raises: torn JSON, wrong types and exploding
    fetches are each that job's staleness problem, not the poller's."""
    docs = [ValueError("torn json"), 42, ["not", "a", "doc"],
            OSError("reset"), KeyError("x")]
    def fetch(url):
        d = docs.pop(0) if docs else {"job_id": "ok", "slave_num": 0,
                                      "ranks": {}, "cluster": {}}
        if isinstance(d, Exception):
            raise d
        return d, None
    clock = [0.0]
    p = fleet.FleetPoller(["h:1"], poll_secs=0.1, stale_secs=10.0,
                          fetch=fetch, now=lambda: clock[0])
    for i in range(40):
        clock[0] = i * 10.0                 # defeats backoff entirely
        p.poll_once()
    assert p.scrape_errors == 5
    assert p.states() == {"http://h:1": fleet.LIVE}


def test_poller_thread_lifecycle():
    """start()/stop(): the background sweep thread is a daemon, makes
    progress without any manual poll_once, and joins cleanly."""
    def fetch(url):
        return _mdoc("cafe"), None
    p = fleet.FleetPoller(["h:1"], poll_secs=0.02, stale_secs=5.0,
                          fetch=fetch)
    p.start()
    try:
        deadline = time.monotonic() + 5.0
        while p.model() is None and time.monotonic() < deadline:
            time.sleep(0.01)
        assert p.model() is not None
        assert p._thread.daemon
    finally:
        p.stop()
    assert p._thread is None                # joined and released


# ----------------------------------------------------------------------
# FleetSink — durability properties
# ----------------------------------------------------------------------
def test_fleet_sink_torn_tail_at_every_byte(tmp_path):
    """The sink torn-tail property holds for fleet segments: truncate
    the (single) segment at ANY byte inside the final record — every
    prior record is recovered, exactly one torn tail, no crash."""
    d = tmp_path / "fleet"
    fs = fleet.FleetSink(str(d), budget_bytes=1 << 20)
    recs = [{"t": "fleet_event", "wall": float(i), "kind": "job_up",
             "msg": f"job {i}"} for i in range(4)]
    offs = []
    for r in recs:
        fs.append(r)
        offs.append(fs.bytes_written)
    fs.close()
    assert fs.dropped_records == 0
    [seg] = sink_mod.list_segments(str(d))
    blob = open(seg, "rb").read()
    assert len(blob) == offs[-1]
    stored = sink_mod.read_segment(seg)[0]
    assert [r["kind"] == "job_up" for r in stored] == [True] * 4

    start_last = offs[-2]
    for cut in range(start_last + 1, len(blob)):
        with open(seg, "wb") as fh:
            fh.write(blob[:cut])
        got, end, torn = sink_mod.read_segment(seg)
        assert [g["wall"] for g in got] == [0.0, 1.0, 2.0], \
            f"cut at {cut} lost intact records"
        assert torn, f"cut at {cut} not reported as torn"
        assert end == start_last
        # the report layer sees the same three events and counts the tear
        rep = fleet.fleet_report(str(d))
        assert len(rep["events"]) == 3 and rep["torn"] == 1


def test_fleet_sink_rotation_eviction_and_reader(tmp_path):
    d = tmp_path / "fleet"
    budget = 512 * 1024
    fs = fleet.FleetSink(str(d), budget_bytes=budget)
    big = "x" * 2048
    for i in range(600):
        fs.append({"t": "fleet", "wall": float(i), "pad": big})
    fs.close()
    segs = sink_mod.list_segments(str(d))
    assert len(segs) > 1                    # rotated
    total = sum(os.path.getsize(p) for p in segs)
    assert total <= budget                  # evicted under the budget
    doc = fleet.read_fleet(str(d))
    assert doc["torn"] == 0
    walls = [r["wall"] for r in doc["records"]]
    assert walls == sorted(walls)           # oldest-first, gap at head
    assert walls[-1] == 599.0               # newest survived eviction
    assert fs.dropped_records == 0


def test_fleet_sink_append_never_raises(tmp_path):
    # a FILE where the directory should be: every append degrades to
    # a counted drop, the poller must never see an exception
    f = tmp_path / "not_a_dir"
    f.write_text("x")
    fs = fleet.FleetSink(str(f), budget_bytes=1 << 20)
    fs.append({"t": "fleet", "wall": 0.0})
    fs.append({"t": "fleet", "wall": 1.0})
    fs.close()
    assert fs.dropped_records == 2
    assert fs.last_error


# ----------------------------------------------------------------------
# end-to-end: two real jobs, one SIGKILL (the acceptance criterion)
# ----------------------------------------------------------------------
_JOB_DRIVER = """
import json, sys, threading, time
import numpy as np
from ytk_mp4j_tpu.comm.master import Master
from ytk_mp4j_tpu.comm.process_comm import ProcessCommSlave
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operators

n = int(sys.argv[1])
master = Master(n, timeout=120.0, metrics_port=0).serve_in_thread()

def worker():
    slave = ProcessCommSlave("127.0.0.1", master.port, timeout=120.0)
    arr = np.ones(8192)
    while True:
        slave.allreduce_array(arr, Operands.DOUBLE, Operators.SUM)
        time.sleep(0.02)

for _ in range(n):
    threading.Thread(target=worker, daemon=True).start()
print(json.dumps({"metrics_port": master.metrics_port,
                  "job_id": master.job_id}), flush=True)
threading.Event().wait()        # run until SIGKILLed by the test
"""


def _spawn_job(nranks=2):
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "MP4J_HEARTBEAT_SECS": "0.1",
           "PYTHONPATH": REPO_ROOT}
    proc = subprocess.Popen(
        [sys.executable, "-c", _JOB_DRIVER, str(nranks)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        cwd=REPO_ROOT, env=env, text=True)
    line = proc.stdout.readline()
    if not line:
        proc.kill()
        raise AssertionError(
            f"job driver died at startup: {proc.stderr.read()[-2000:]}")
    head = json.loads(line)
    return proc, f"http://127.0.0.1:{head['metrics_port']}", \
        head["job_id"]


def test_fleet_two_real_jobs_shared_host_then_sigkill(tmp_path, capsys):
    """ISSUE 18 acceptance: two real concurrent jobs on this host ->
    the fleet fold names the shared host fingerprint with BOTH job
    ids and per-job byte attribution; SIGKILL of one entire job walks
    its rows STALE -> GONE with zero poller exceptions while the
    survivor stays LIVE; the fleet-report reconstructs the merged
    timeline including the death from the crc-framed segments."""
    nranks = 2
    proc_a = proc_b = None
    sink_dir = str(tmp_path / "fleet")
    try:
        proc_a, url_a, jid_a = _spawn_job(nranks)
        proc_b, url_b, jid_b = _spawn_job(nranks)
        fs = fleet.FleetSink(sink_dir, budget_bytes=4 << 20)
        poller = fleet.FleetPoller([url_a, url_b], poll_secs=0.2,
                                   stale_secs=0.6, sink=fs)

        # -- phase 1: both jobs folded, shared host, byte attribution
        deadline = time.monotonic() + 60.0
        model = None
        while time.monotonic() < deadline:
            model = poller.poll_once()      # never raises, by contract
            jobs = model["jobs"]
            ok = [j for j in jobs.values()
                  if j["state"] == fleet.LIVE and j["summary"]
                  and j["summary"]["ranks_reporting"] == nranks
                  and j["summary"]["wire_bytes"] > 0]
            if len(ok) == 2 and model["shared_hosts"]:
                break
            time.sleep(0.1)
        assert model is not None and model["shared_hosts"], \
            f"no shared host observed: {json.dumps(model, default=str)[:800]}"
        [fp] = model["shared_hosts"]
        row = model["hosts"][fp]["jobs"]
        assert set(row) == {jid_a, jid_b}   # both job ids, one host
        for jid in (jid_a, jid_b):
            assert row[jid]["wire_bytes"] > 0       # per-job bytes
            assert sorted(row[jid]["ranks"]) == list(range(nranks))
        frame = telemetry.format_fleet(model)
        assert jid_a in frame and jid_b in frame
        assert f"shared host {fp}" in frame

        # the CLI one-shot sees the same shared host (own poller)
        assert scope_main(["fleet", url_a, url_b, "--once"]) == 0
        out = capsys.readouterr().out
        assert jid_a in out and jid_b in out and "shared host" in out

        # -- phase 2: SIGKILL job B entirely (master + slaves die)
        proc_b.kill()
        proc_b.wait(10.0)
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            poller.poll_once()              # must absorb the corpse
            if poller.states()[url_b] == fleet.GONE:
                break
            time.sleep(0.1)
        states = poller.states()
        assert states[url_b] == fleet.GONE, states
        assert states[url_a] == fleet.LIVE, states      # survivor
        surv = poller.model()["jobs"][url_a]["summary"]
        assert surv["job_id"] == jid_a
        assert surv["ranks_reporting"] == nranks        # unaffected
        kinds = [e["kind"] for e in poller.events()]
        assert "job_stale" in kinds and "job_gone" in kinds
        poller.stop()                       # closes the sink too

        # -- phase 3: offline reconstruction from the fleet segments
        rep = fleet.fleet_report(sink_dir)
        assert rep["snapshots"] > 0 and rep["torn"] == 0
        by_kind = {}
        for ev in rep["events"]:
            by_kind.setdefault(ev["kind"], []).append(ev)
        assert {jid_a, jid_b} <= {e["job_id"]
                                  for e in by_kind["job_up"]}
        assert any(e["job_id"] == jid_b for e in by_kind["job_gone"])
        assert rep["jobs"][url_b]["state"] == fleet.GONE
        assert rep["jobs"][url_a]["state"] == fleet.LIVE
        assert scope_main(["fleet-report", sink_dir]) == 0
        out = capsys.readouterr().out
        assert "job_gone" in out and jid_b in out
    finally:
        for proc in (proc_a, proc_b):
            if proc is not None:
                proc.kill()
                proc.wait(10.0)
