"""mp4j-tuner (ISSUE 15): frame-level ring routing, the per-link
policy core, fenced leader demotion, and the audit-trip rail.

Four layers of coverage:

- a PROPERTY GRID asserting the framed/columnar-map planes produce
  bit-identical results ring-routed vs carrier-routed (all numeric
  operands x SUM/MAX/MIN/PROD x compression on/off x n in {2, 3, 5}),
  with the ring run proving the bytes actually rode the rings;
- a CHAOS GRID: {reset, kill, slow} x {ring-routed framed,
  ring-routed map} stays green (bit-exact recovery / one consistent
  fatal / no hangs);
- a TUNER-POLICY UNIT SUITE that never opens a socket: hysteresis,
  the compression probe/measure cycle, chunk adaptation, shm-link
  exclusion, boundary-only application, the audit-trip fallback, and
  the leader-demotion policy;
- INTEGRATION: a fenced leader demotion applied mid-job at a
  collective boundary (results bit-exact before/after), and an
  injected audit divergence tripping an actively adapted link back
  to static defaults with zero wrong results.
"""

import io
import threading
import time

import numpy as np
import pytest

from tests.helpers import run_slaves
from ytk_mp4j_tpu.comm.master import Master
from ytk_mp4j_tpu.comm.process_comm import ProcessCommSlave
from ytk_mp4j_tpu.exceptions import Mp4jError, Mp4jFatalError
from ytk_mp4j_tpu.obs import cli as cli_mod
from ytk_mp4j_tpu.obs import critpath
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operators
from ytk_mp4j_tpu.utils import tuner, tuning

JOIN = 60.0

NUMERIC_OPERANDS = [Operands.DOUBLE, Operands.FLOAT, Operands.INT,
                    Operands.LONG, Operands.SHORT, Operands.BYTE]
OPERATORS = [Operators.SUM, Operators.MAX, Operators.MIN,
             Operators.PROD]


# ----------------------------------------------------------------------
# property grid: ring-routed == carrier-routed, bit-exact
# ----------------------------------------------------------------------
def _grid_fn(compress: bool):
    """Every numeric operand x operator over the FRAMED dense plane
    (native_transport=False below forces it) plus the columnar map
    plane; returns results + wire-split totals."""
    def fn(slave, r):
        out = {}
        for od in NUMERIC_OPERANDS:
            odx = Operands.compressed(od) if compress else od
            rng = np.random.default_rng(hash(od.name) % 1000 + r)
            for op in OPERATORS:
                if od.dtype.kind == "f":
                    arr = rng.standard_normal(4096).astype(od.dtype)
                else:
                    arr = rng.integers(1, 4, 4096).astype(od.dtype)
                slave.allreduce_array(arr, odx, op)
                out[(od.name, op.name)] = arr.copy()
            d = {f"k{i}": np.asarray((r + 1) * (i % 5 + 1),
                                     od.dtype)
                 for i in range(600)}
            res = slave.allreduce_map(d, odx, Operators.SUM)
            out[(od.name, "map")] = {k: np.asarray(v).copy()
                                     for k, v in res.items()}
        totals = {"shm": 0, "ring": 0, "tcp": 0}
        for fam in slave.stats().values():
            totals["shm"] += fam["wire_bytes_shm"]
            totals["ring"] += fam["wire_bytes_shm_ring"]
            totals["tcp"] += fam["wire_bytes_tcp"]
        return out, totals
    return fn


@pytest.mark.parametrize("n", [2, 3, 5])
@pytest.mark.parametrize("compress", [False, True])
def test_ring_routed_frames_bit_exact_vs_carrier(n, compress,
                                                 monkeypatch):
    fn = _grid_fn(compress)
    kw = dict(native_transport=False, tuner="off", timeout=JOIN)
    # carrier-routed reference: frame routing disabled job-wide
    monkeypatch.setenv("MP4J_SHM_FRAME_MIN", "0")
    carrier = run_slaves(n, fn, **kw)
    # ring-routed: a threshold below every test frame
    monkeypatch.setenv("MP4J_SHM_FRAME_MIN", "512")
    ring = run_slaves(n, fn, **kw)
    for r in range(n):
        c_out, c_tot = carrier[r]
        g_out, g_tot = ring[r]
        assert c_out.keys() == g_out.keys()
        for key in c_out:
            cv, gv = c_out[key], g_out[key]
            if isinstance(cv, dict):
                assert cv.keys() == gv.keys()
                for k in cv:
                    assert np.array_equal(cv[k], gv[k]), (key, k)
            else:
                assert cv.dtype == gv.dtype
                assert np.array_equal(cv, gv), key
        # carrier run never touches the rings; the ring run's framed
        # bytes overwhelmingly ride them (headers/syncs stay carrier)
        assert c_tot["ring"] == 0
        assert g_tot["ring"] > 0.5 * g_tot["shm"]
        # the acceptance split: co-located framed/map traffic is shm,
        # not tcp
        assert g_tot["shm"] > 0 and g_tot["tcp"] == 0


# ----------------------------------------------------------------------
# chaos grid over the ring-routed planes
# ----------------------------------------------------------------------
def _run_chaos(n, fn, fault_plan, **slave_kwargs):
    log = io.StringIO()
    master = Master(n, timeout=JOIN, log_stream=log).serve_in_thread()
    results, errors = [None] * n, [None] * n

    def worker(i):
        slave = None
        try:
            slave = ProcessCommSlave(
                "127.0.0.1", master.port, timeout=JOIN,
                fault_plan=fault_plan, dead_rank_secs=20.0,
                **slave_kwargs)
            results[slave.rank] = fn(slave, slave.rank)
            slave.close(0)
        except Exception as e:
            r = slave.rank if slave is not None else i
            errors[r] = e
            if slave is not None:
                try:
                    slave.close(1)
                except Exception:
                    pass

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(n)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + JOIN
    for t in threads:
        t.join(max(0.1, deadline - time.monotonic()))
    hung = [i for i, t in enumerate(threads) if t.is_alive()]
    assert not hung, f"ranks {hung} hung:\n" + log.getvalue()
    master.join(10.0)
    return results, errors


def _chaos_body(plane):
    if plane == "map":
        def fn(slave, r):
            d = {int(k): np.float64((r + 1) * k) for k in range(900)}
            slave.allreduce_map(d, Operands.DOUBLE, Operators.SUM)
            slave.barrier()
            slave.allreduce_map(d, Operands.DOUBLE, Operators.SUM)
            return d
        return fn, {}
    rng = np.random.default_rng(7)
    alls = [rng.standard_normal(120_000) for _ in range(4)]

    def fn(slave, r):
        arr = alls[r].copy()
        slave.allreduce_array(arr, Operands.DOUBLE, Operators.SUM)
        slave.barrier()
        slave.allreduce_array(arr, Operands.DOUBLE, Operators.SUM)
        return arr
    return fn, {"native_transport": False}


@pytest.mark.parametrize("plane", ["framed", "map"])
@pytest.mark.parametrize("fault", ["reset", "slow", "kill"])
def test_chaos_ring_routed_planes(plane, fault, monkeypatch):
    monkeypatch.setenv("MP4J_SHM_FRAME_MIN", "512")
    fn, kw = _chaos_body(plane)
    kw = dict(kw, tuner="off")
    plans = {"reset": "reset:rank=1:nth=2",
             "slow": "slow:rank=1:secs=0.002",
             "kill": "kill:rank=1:nth=2"}
    want, werr = _run_chaos(4, fn, None, **kw)
    assert all(e is None for e in werr)
    got, gerr = _run_chaos(4, fn, plans[fault], **kw)
    if fault == "kill":
        survivors = [e for r, e in enumerate(gerr) if r != 1]
        assert all(isinstance(e, Mp4jFatalError) for e in survivors), \
            gerr
        return
    assert all(e is None for e in gerr), gerr
    for r in range(4):
        if plane == "map":
            assert want[r].keys() == got[r].keys()
            for k in want[r]:
                assert want[r][k] == got[r][k]
        else:
            assert np.array_equal(want[r], got[r])


# ----------------------------------------------------------------------
# policy core units (no sockets)
# ----------------------------------------------------------------------
def _win(bytes_=0, secs=0.0, comp_raw=0, comp_wire=0, xfers=0,
         xfer_bytes=0, shm=0):
    return {"bytes": bytes_, "secs": secs, "frames": 1,
            "comp_raw": comp_raw, "comp_wire": comp_wire,
            "bytes_shm": shm, "xfers": xfers,
            "xfer_bytes": xfer_bytes}


CHUNK = 1024 * 1024


def test_policy_compress_probe_commits_after_sustain():
    # compressed traffic with no plain baseline: the policy proposes a
    # probe (compress off) and commits it only after SUSTAIN windows
    st = tuner.initial_state()
    w = _win(bytes_=4_000_000, secs=0.03, comp_raw=40_000_000,
             comp_wire=4_000_000)
    decisions = []
    for _ in range(tuner.SUSTAIN_WINDOWS):
        st, d = tuner.decide_link(w, st, CHUNK)
        decisions.append(d)
    assert decisions[:-1] == [None] * (tuner.SUSTAIN_WINDOWS - 1)
    assert decisions[-1] is not None
    assert decisions[-1]["compress"] is False
    assert st["probing"] is True


def test_policy_probe_keeps_off_on_fast_link():
    st = tuner.initial_state()
    # compressed payload rate ~0.13 GB/s (the zlib-bound signature)
    w = _win(bytes_=4_000_000, secs=0.3, comp_raw=40_000_000,
             comp_wire=4_000_000)
    for _ in range(tuner.SUSTAIN_WINDOWS):
        st, d = tuner.decide_link(w, st, CHUNK)
    # plain traffic now flows at 1 GB/s >> the zlib-bound payload rate
    st, d = tuner.decide_link(_win(bytes_=40_000_000, secs=0.04),
                              st, CHUNK)
    assert d is None and st["probing"] is False
    assert st["compress"] is False


def test_policy_probe_reverts_in_one_window_on_slow_link():
    st = tuner.initial_state()
    # compressed payload rate ~1.33 GB/s equivalent... make it high:
    # payload 40 MB in 0.03 s
    w = _win(bytes_=4_000_000, secs=0.03, comp_raw=40_000_000,
             comp_wire=4_000_000)
    for _ in range(tuner.SUSTAIN_WINDOWS):
        st, d = tuner.decide_link(w, st, CHUNK)
    assert st["compress"] is False and st["probing"]
    # plain traffic is SLOWER than the compressed payload rate: the
    # failed probe reverts immediately, not after SUSTAIN windows
    st, d = tuner.decide_link(_win(bytes_=4_000_000, secs=1.0),
                              st, CHUNK)
    assert d is not None and d["compress"] is True
    assert st["probing"] is False


def test_policy_hysteresis_resets_on_disagreement():
    st = tuner.initial_state()
    w = _win(bytes_=4_000_000, secs=0.03, comp_raw=40_000_000,
             comp_wire=4_000_000)
    st, d = tuner.decide_link(w, st, CHUNK)
    assert d is None and st["pend_n"] == 1
    # an evidence-free window breaks the streak
    st, d = tuner.decide_link(_win(), st, CHUNK)
    assert st["pend_n"] == 0
    st, d = tuner.decide_link(w, st, CHUNK)
    assert d is None and st["pend_n"] == 1


def test_policy_chunk_adapts_toward_transfer_size():
    st = tuner.initial_state()
    # 32 MB transfers: target 8 MB -> doubles one step per commit
    w = _win(bytes_=32_000_000, secs=0.03, xfers=1,
             xfer_bytes=32 * 1024 * 1024)
    d = None
    for _ in range(tuner.SUSTAIN_WINDOWS):
        st, d = tuner.decide_link(w, st, CHUNK)
    assert d is not None and d["chunk_bytes"] == 2 * CHUNK
    # tiny transfers: halves, bounded by CHUNK_MIN
    st = tuner.initial_state()
    w = _win(bytes_=1_000_000, secs=0.03, xfers=100,
             xfer_bytes=100 * 64 * 1024)
    for _ in range(tuner.SUSTAIN_WINDOWS):
        st, d = tuner.decide_link(w, st, CHUNK)
    assert d is not None and d["chunk_bytes"] == CHUNK // 2


def test_policy_shm_links_never_get_chunk_decisions():
    # the raw plane's ring/carrier routing makes the chunk schedule
    # part of the shm wire contract — the policy must not touch it
    st = tuner.initial_state()
    w = _win(bytes_=32_000_000, secs=0.03, xfers=1,
             xfer_bytes=32 * 1024 * 1024, shm=32_000_000)
    for _ in range(tuner.SUSTAIN_WINDOWS + 2):
        st, d = tuner.decide_link(w, st, CHUNK)
        assert d is None or not d.get("chunk_bytes")


def test_policy_sockbuf_raises_toward_bdp():
    # 1 GB/s sustained bulk on tcp with small applied buffers: the
    # BDP at the assumed RTT (~1 MB) dwarfs them — one doubling per
    # sustained verdict, per buffer
    st = tuner.initial_state()
    w = _win(bytes_=40_000_000, secs=0.04)
    w["transport"] = "tcp"
    w["so_sndbuf"] = 128 * 1024
    w["so_rcvbuf"] = 256 * 1024
    d = None
    for _ in range(tuner.SUSTAIN_WINDOWS):
        st, d = tuner.decide_link(w, st, CHUNK)
    assert d is not None
    assert d["so_sndbuf"] == 256 * 1024
    assert d["so_rcvbuf"] == 512 * 1024


def test_policy_sockbuf_quiet_cases():
    # links carrying shm bytes, trickle windows, non-tcp transports
    # and at-cap buffers never propose a resize
    bufs = {"so_sndbuf": 128 * 1024, "so_rcvbuf": 128 * 1024}
    quiet = [
        {**_win(bytes_=40_000_000, secs=0.04, shm=1),
         "transport": "tcp", **bufs},
        {**_win(bytes_=1_000_000, secs=0.01),
         "transport": "tcp", **bufs},
        {**_win(bytes_=40_000_000, secs=0.04),
         "transport": "shm", **bufs},
        {**_win(bytes_=40_000_000, secs=0.04), "transport": "tcp",
         "so_sndbuf": tuner.SOCKBUF_MAX,
         "so_rcvbuf": tuner.SOCKBUF_MAX},
    ]
    for w in quiet:
        st = tuner.initial_state()
        for _ in range(tuner.SUSTAIN_WINDOWS + 1):
            st, d = tuner.decide_link(w, st, CHUNK)
            assert d is None or not (d.get("so_sndbuf")
                                     or d.get("so_rcvbuf")), w


def test_link_tuner_boundary_only_application():
    # decisions commit on the heartbeat side but take effect ONLY when
    # the collective boundary drains the queue
    tun = tuner.LinkTuner("act", CHUNK)
    w = {1: _win(bytes_=4_000_000, secs=0.03, comp_raw=40_000_000,
                 comp_wire=4_000_000)}
    cum: dict[int, dict] = {}

    def feed():
        # accumulate (link stats are monotone; observe() diffs)
        prev = cum.get(1, dict.fromkeys(w[1], 0))
        cum[1] = {k: prev[k] + v for k, v in w[1].items()}
        return tun.observe({1: dict(cum[1])})

    committed = []
    for _ in range(tuner.SUSTAIN_WINDOWS):
        committed += feed()
    assert committed and committed[0][0] == 1
    # committed but NOT applied: the hot-path reads still say static
    assert tun.effective_compress(1, True) is True
    assert tun.effective_chunk(1, CHUNK) == CHUNK
    assert tun.dirty
    pending, revert = tun.take_pending()
    assert 1 in pending and revert is False
    # now — and only now — the decision is live
    assert tun.effective_compress(1, True) is False
    assert not tun.dirty


def test_link_tuner_observe_mode_never_queues():
    tun = tuner.LinkTuner("observe", CHUNK)
    w = _win(bytes_=4_000_000, secs=0.03, comp_raw=40_000_000,
             comp_wire=4_000_000)
    cum = dict.fromkeys(w, 0)
    for i in range(tuner.SUSTAIN_WINDOWS + 2):
        cum = {k: cum[k] + v for k, v in w.items()}
        tun.observe({1: dict(cum)})
    assert tun.decisions_total >= 1       # recorded
    assert not tun.dirty                  # never queued
    assert tun.effective_compress(1, True) is True


def test_link_tuner_trip_reverts_and_latches():
    tun = tuner.LinkTuner("act", CHUNK)
    w = _win(bytes_=4_000_000, secs=0.03, comp_raw=40_000_000,
             comp_wire=4_000_000)
    cum = dict.fromkeys(w, 0)
    for _ in range(tuner.SUSTAIN_WINDOWS):
        cum = {k: cum[k] + v for k, v in w.items()}
        tun.observe({1: dict(cum)})
    tun.take_pending()
    assert tun.effective_compress(1, True) is False
    tun.trip("audit divergence at collective #7")
    assert tun.tripped
    pending, revert = tun.take_pending()
    assert revert is True and pending == {}
    # back to static defaults, and the policy is frozen for good
    assert tun.effective_compress(1, True) is True
    before = tun.decisions_total
    cum = {k: cum[k] + v for k, v in w.items()}
    assert tun.observe({1: dict(cum)}) == []
    assert tun.decisions_total == before


def test_policy_leader_demotion_fires_and_rotates():
    groups = [[0, 1], [2, 3]]
    rows = [{"seq": i, "dom": 0, "cause": "link->0 over tcp",
             "slow": True} for i in range(tuner.LEADER_WINDOW)]
    ov = tuner.decide_leaders(rows, groups, {})
    assert ov == {0: 1}
    # demoting again rotates back (cyclic through the group)
    rows = [{"seq": i, "dom": 1, "cause": "link->1 over tcp",
             "slow": True} for i in range(tuner.LEADER_WINDOW)]
    ov2 = tuner.decide_leaders(rows, groups, ov)
    assert ov2 == {0: 0}


def test_policy_leader_demotion_quiet_cases():
    groups = [[0, 1], [2, 3]]
    base = {"seq": 0, "cause": "link->0 over tcp", "slow": True}
    rows = [dict(base, seq=i, dom=0)
            for i in range(tuner.LEADER_WINDOW)]
    # below-share windows, fast rows, non-link causes, non-leaders,
    # singleton groups: all quiet
    assert tuner.decide_leaders(rows[:4], groups, {}) is None
    assert tuner.decide_leaders(
        [dict(r, slow=False) for r in rows], groups, {}) is None
    assert tuner.decide_leaders(
        [dict(r, cause="reduce") for r in rows], groups, {}) is None
    assert tuner.decide_leaders(
        [dict(r, dom=1) for r in rows], groups, {}) is None
    assert tuner.decide_leaders(
        [dict(r, dom=0) for r in rows], [[0], [1, 2, 3]],
        {}) is None


def test_leaders_for_validates_overrides():
    groups = [[0, 1], [2, 3]]
    assert tuner.leaders_for(groups, None) == [0, 2]
    assert tuner.leaders_for(groups, {0: 1}) == [1, 2]
    # a stale override (not a member of the group) falls back
    assert tuner.leaders_for(groups, {0: 3}) == [0, 2]
    assert tuner.leaders_for(groups, {9: 1}) == [0, 2]


# ----------------------------------------------------------------------
# knob validation
# ----------------------------------------------------------------------
def test_tuner_knob_validation(monkeypatch):
    monkeypatch.setenv("MP4J_TUNER", "sometimes")
    with pytest.raises(Mp4jError):
        tuning.tuner_mode()
    monkeypatch.setenv("MP4J_TUNER", "ACT")
    assert tuning.tuner_mode() == "act"
    monkeypatch.delenv("MP4J_TUNER")
    assert tuning.tuner_mode() == "observe"
    assert tuning.tuner_mode("off") == "off"
    monkeypatch.setenv("MP4J_TUNER_WINDOW_SECS", "0")
    with pytest.raises(Mp4jError):
        tuning.tuner_window_secs()
    monkeypatch.setenv("MP4J_TUNER_WINDOW_SECS", "1.5")
    assert tuning.tuner_window_secs() == 1.5
    monkeypatch.setenv("MP4J_SHM_FRAME_MIN", "-1")
    with pytest.raises(Mp4jError):
        tuning.shm_frame_min()
    monkeypatch.setenv("MP4J_SHM_FRAME_MIN", "0")
    assert tuning.shm_frame_min() == 0


def test_so_buf_map_parsing(monkeypatch):
    monkeypatch.setenv("MP4J_SO_BUF_MAP", "")
    assert tuning.so_buf_map() == {}
    monkeypatch.setenv("MP4J_SO_BUF_MAP", "2:262144,3:524288/1048576")
    assert tuning.so_buf_map() == {2: (262144, 262144),
                                   3: (524288, 1048576)}
    for bad in ("2", "2:abc", "x:1", "2:-1", "2:1/-4"):
        monkeypatch.setenv("MP4J_SO_BUF_MAP", bad)
        with pytest.raises(Mp4jError):
            tuning.so_buf_map()


def test_so_buf_map_applies_per_link(monkeypatch):
    monkeypatch.setenv("MP4J_SO_BUF_MAP", "0:262144,1:262144")

    def fn(slave, r):
        arr = np.arange(1000, dtype=np.float64)
        slave.allreduce_array(arr, Operands.DOUBLE, Operators.SUM)
        return slave.link_stats()
    links = run_slaves(2, fn, shm=False, tuner="off")
    for r in range(2):
        peer = 1 - r
        lk = links[r][peer]
        # the kernel doubles setsockopt sizes on Linux; the recorded
        # applied value reflects the readback, so just require it
        # moved to at least the requested size
        assert lk.get("so_sndbuf", 0) >= 262144
        assert lk.get("so_rcvbuf", 0) >= 262144
        assert lk.get("transport") == "tcp"


# ----------------------------------------------------------------------
# integration: fenced leader demotion + audit trip
# ----------------------------------------------------------------------
def test_fenced_leader_demotion_mid_job():
    """4 ranks as 2 virtual hosts run two-level collectives while the
    operator demotes host 0's leader through the master's fence: every
    rank switches at the same boundary and the results stay exact."""
    master = Master(4, timeout=JOIN).serve_in_thread()
    stop = threading.Event()
    demoted = threading.Event()
    errors: list = []
    slaves: list = [None] * 4
    base = np.arange(2048, dtype=np.float64)

    def worker(i):
        try:
            s = ProcessCommSlave(
                "127.0.0.1", master.port, timeout=JOIN,
                host_fp=("h0" if i < 2 else "h1"), tuner="act")
            slaves[s.rank] = s
            it = 0
            while not stop.is_set() and it < 400:
                a = base.copy()
                s.allreduce_array(a, Operands.DOUBLE, Operators.SUM)
                assert np.array_equal(a, base * 4)
                it += 1
                if demoted.is_set() and s._leader_overrides:
                    break
                time.sleep(0.002)
            s.close(0)
        except Exception as e:
            errors.append(e)
            stop.set()

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(4)]
    for t in threads:
        t.start()
    # wait for the job to be running, then demote group 0's leader
    deadline = time.monotonic() + JOIN
    while any(s is None for s in slaves) \
            and time.monotonic() < deadline:
        time.sleep(0.01)
    assert all(s is not None for s in slaves)
    groups = slaves[0]._host_groups
    assert len(groups) == 2 and len(groups[0]) == 2
    new_leader = groups[0][1]
    assert master.request_tuner_leaders({0: new_leader})
    # the fence completes at a collective boundary; workers exit once
    # they observe the override
    demoted.set()
    for t in threads:
        t.join(JOIN)
    stop.set()
    assert not errors, errors
    assert not any(t.is_alive() for t in threads)
    for s in slaves:
        assert s._leaders[0] == new_leader
        assert s._leader_overrides == {0: new_leader}
    st = master.tuner_status()
    assert st["overrides"] == {0: new_leader}
    assert st["demotions"] == 1
    master.join(10.0)
    assert master.final_code == 0


def test_audit_divergence_trips_adaptive_link():
    """An applied per-link decision + an (injected) cross-rank audit
    divergence: the master pushes the trip, every rank reverts to
    static defaults at its next boundary, the policy stays frozen —
    and every collective before/during/after stays bit-exact."""
    master = Master(2, timeout=JOIN, tuner="act").serve_in_thread()
    barrier = threading.Barrier(2, timeout=JOIN)
    tripped_seen = threading.Event()
    errors: list = []
    out: dict = {}
    base = np.arange(4096, dtype=np.float64)

    def worker(i):
        try:
            s = ProcessCommSlave("127.0.0.1", master.port,
                                 timeout=JOIN, tuner="act", shm=False)
            peer = 1 - s.rank
            # inject an adaptive decision directly (the probe's
            # commit, without waiting out real windows)
            s._tuner._pending[peer] = {"compress": False,
                                       "chunk_bytes": 2 * CHUNK}
            a = base.copy()
            s.allreduce_array(a, Operands.DOUBLE, Operators.SUM)
            assert np.array_equal(a, base * 2)
            assert s._tuner.effective_chunk(peer, CHUNK) == 2 * CHUNK
            barrier.wait()
            if s.rank == 0:
                # fabricate the divergence verdict on the master —
                # the trip path from detection to fan-out is real
                master._tuner_tick([{"seq": 3,
                                     "err": "wire fold mismatch"}])
            # keep hitting boundaries until the trip lands + applies
            # on EVERY rank (the exit is itself agreed through a MIN
            # allreduce so the SPMD schedule never desyncs)
            deadline = time.monotonic() + JOIN
            while time.monotonic() < deadline:
                a = base.copy()
                s.allreduce_array(a, Operands.DOUBLE, Operators.SUM)
                assert np.array_equal(a, base * 2)
                st = s.tuner_status()
                done = np.asarray(
                    [1.0 if (st["tripped"] and not st["applied"]
                             and not s._tuner.dirty) else 0.0])
                s.allreduce_array(done, Operands.DOUBLE,
                                  Operators.MIN)
                if done[0] == 1.0:
                    break
                time.sleep(0.01)
            st = s.tuner_status()
            assert st["tripped"], "trip never reached this rank"
            assert st["applied"] == {}
            assert s._tuner.effective_chunk(peer, CHUNK) == CHUNK
            out[s.rank] = st
            s.close(0)
        except Exception as e:
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(JOIN)
    assert not any(t.is_alive() for t in threads)
    assert not errors, errors
    master.join(10.0)
    assert master.final_code == 0
    st = master.tuner_status()
    assert st["tripped"] and "divergence" in st["tripped"]


# ----------------------------------------------------------------------
# observability surfaces
# ----------------------------------------------------------------------
def test_tuner_status_rides_metrics_doc():
    def fn(slave, r):
        arr = np.arange(512, dtype=np.float64)
        slave.allreduce_array(arr, Operands.DOUBLE, Operators.SUM)
        return None
    master_holder: dict = {}

    # run a tiny job with an observing master and scrape the doc
    master = Master(2, timeout=JOIN, tuner="observe").serve_in_thread()
    master_holder["m"] = master

    def worker():
        s = ProcessCommSlave("127.0.0.1", master.port, timeout=JOIN,
                             tuner="observe")
        fn(s, s.rank)
        s.close(0)

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(JOIN)
    doc = master.metrics_doc()
    tun = doc["cluster"]["tuner"]
    assert tun is not None and tun["mode"] == "observe"
    assert tun["tripped"] is None
    # the rendered view names the mode and the per-rank lines
    text = cli_mod._format_tuner_doc(tun)
    assert "mode=observe" in text
    master.join(10.0)


def test_critpath_collects_tuner_events():
    job = {0: {"records": [
        {"t": "recovery",
         "events": [[1.0, "tuner", "link->1 applied chunk=None "
                                   "compress=False"],
                    [2.0, "abort", "epoch->1"]]},
    ]}, 1: {"records": []}}
    a = critpath.analyze(job)
    assert a["tuner_events"] == [{"rank": 0, "ts": 1.0,
                                  "msg": "link->1 applied chunk=None "
                                         "compress=False"}]


def test_format_tuner_doc_off_and_tripped():
    assert "off" in cli_mod._format_tuner_doc(None)
    text = cli_mod._format_tuner_doc({
        "mode": "act", "demotions": 1, "version": 1,
        "tripped": "audit divergence at collective #7",
        "overrides": {0: 1},
        "ranks": {"0": {"decisions_total": 2, "tripped": None,
                        "applied": {"1": {"compress": False,
                                          "chunk_bytes": None}}}},
        "events": []})
    assert "TRIPPED" in text and "mode=act" in text
    assert "compress=False" in text


def test_policy_compress_off_reenables_on_degraded_link():
    # post-review regression: a committed compress=False suppresses
    # all compressed evidence, so the re-enable rule must work from
    # the REMEMBERED ratio — the decision is not a life sentence
    st = tuner.initial_state()
    w = _win(bytes_=4_000_000, secs=0.3, comp_raw=40_000_000,
             comp_wire=4_000_000)
    for _ in range(tuner.SUSTAIN_WINDOWS):
        st, d = tuner.decide_link(w, st, CHUNK)
    # probe wins on a fast plain link
    st, d = tuner.decide_link(_win(bytes_=40_000_000, secs=0.04),
                              st, CHUNK)
    assert st["compress"] is False and st["probing"] is False
    # the link degrades below COMPRESS_ON_GBS: plain 4 MB in 1 s
    slow = _win(bytes_=4_000_000, secs=1.0)
    d = None
    for _ in range(tuner.SUSTAIN_WINDOWS):
        st, d = tuner.decide_link(slow, st, CHUNK)
    assert d is not None and d["compress"] is True


def test_link_tuner_reset_drops_decisions_keeps_trip():
    tun = tuner.LinkTuner("act", CHUNK)
    w = _win(bytes_=4_000_000, secs=0.3, comp_raw=40_000_000,
             comp_wire=4_000_000)
    cum = dict.fromkeys(w, 0)
    for _ in range(tuner.SUSTAIN_WINDOWS):
        cum = {k: cum[k] + v for k, v in w.items()}
        tun.observe({1: dict(cum)})
    tun.take_pending()
    assert tun.effective_compress(1, True) is False
    tun.reset()
    # a renumbered/replaced peer 1 starts from static defaults
    assert tun.effective_compress(1, True) is True
    assert tun.effective_chunk(1, CHUNK) == CHUNK
    assert not tun.dirty
    tun.trip("divergence")
    tun.reset()
    assert tun.tripped        # the latch survives membership churn


def test_tuner_fence_converges_on_unequal_parked_seqs():
    # post-review regression: every rank acked but at DIFFERENT
    # ordinals (rooted collectives let ranks complete ordinals a peer
    # never touched) — the master must advance the behind ranks, not
    # bleed the fence to its deadline
    master = Master(2, timeout=JOIN, tuner="act").serve_in_thread()
    errors: list = []
    out: dict = {}
    base = np.arange(256, dtype=np.float64)

    def worker(i):
        try:
            s = ProcessCommSlave("127.0.0.1", master.port,
                                 timeout=JOIN, tuner="off", shm=False)
            # skew the schedule with rooted sends: rank 0 runs two
            # extra broadcast ordinals rank 1 observes passively
            it = 0
            while it < 800:
                a = base.copy()
                s.allreduce_array(a, Operands.DOUBLE, Operators.SUM)
                it += 1
                flag = np.asarray(
                    [1.0 if s._leader_overrides or it > 3 else 0.0])
                s.allreduce_array(flag, Operands.DOUBLE,
                                  Operators.MIN)
                if flag[0] == 1.0 and s._leader_overrides:
                    break
                if it > 600:
                    break
                time.sleep(0.002)
            out[s.rank] = dict(s._leader_overrides)
            s.close(0)
        except Exception as e:
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(2)]
    for t in threads:
        t.start()
    time.sleep(0.3)
    assert master.request_tuner_leaders({0: 0})
    for t in threads:
        t.join(JOIN)
    assert not errors, errors
    master.join(10.0)
    assert master.final_code == 0
    assert master.tuner_status()["demotions"] == 1


def test_injected_sockbuf_decision_applies_at_boundary():
    # the act-mode per-link socket-buffer application path (decision
    # structs may carry so_sndbuf/so_rcvbuf; the default policy emits
    # none, so drive it by injection like the trip test)
    def fn(slave, r):
        peer = 1 - slave.rank
        arr = np.arange(1000, dtype=np.float64)
        slave.allreduce_array(arr, Operands.DOUBLE, Operators.SUM)
        slave._tuner._pending[peer] = {"so_sndbuf": 262144,
                                       "so_rcvbuf": 262144}
        slave.allreduce_array(arr, Operands.DOUBLE, Operators.SUM)
        return slave.link_stats()

    links = run_slaves(2, fn, shm=False, tuner="act")
    for r in range(2):
        lk = links[r][1 - r]
        # kernel readback (Linux doubles setsockopt values): require
        # at least the requested size was applied and recorded
        assert lk.get("so_sndbuf", 0) >= 262144
        assert lk.get("so_rcvbuf", 0) >= 262144
