"""Differential tests: TPU (XLA) path vs CPU socket reference path.

The build plan's core correctness argument (SURVEY.md section 7 phase 3):
the socket path re-implements the reference's semantics, and the TPU path
must agree with it on identical inputs — exactly for integer operands,
to float tolerance for floating ones (reduction orders legitimately
differ: ring order vs XLA's).
"""

import numpy as np
import pytest

from ytk_mp4j_tpu import meta
from ytk_mp4j_tpu.comm.tpu_comm import TpuCommCluster
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operators

from helpers import run_slaves as socket_run


@pytest.fixture(scope="module")
def cluster():
    return TpuCommCluster(4)


@pytest.mark.parametrize("op", ["SUM", "PROD", "MAX", "MIN"])
@pytest.mark.parametrize("operand",
                         [Operands.DOUBLE, Operands.INT, Operands.SHORT,
                          Operands.BYTE],
                         ids=lambda o: o.name)
def test_allreduce_differential(cluster, operand, op, rng):
    n = 4
    if operand.dtype.kind == "f":
        alls = [rng.standard_normal(33).astype(operand.dtype)
                for _ in range(n)]
    else:
        alls = [rng.integers(1, 4, 33).astype(operand.dtype)
                for _ in range(n)]
    operator = Operators.by_name(op)

    sock = socket_run(
        n, lambda s, r: s.allreduce_array(alls[r].copy(), operand, operator))
    tpu = [a.copy() for a in alls]
    cluster.allreduce_array(tpu, operand, operator)

    for got_s, got_t in zip(sock, tpu):
        if operand.dtype.kind == "f":
            np.testing.assert_allclose(got_t, got_s, rtol=1e-9)
        else:
            np.testing.assert_array_equal(got_t, got_s)


@pytest.mark.parametrize("op", ["SUM", "PROD"])
@pytest.mark.parametrize("operand", [Operands.SHORT, Operands.BYTE],
                         ids=lambda o: o.name)
def test_narrow_int_wraparound_differential(cluster, operand, op, rng):
    """Socket and device paths must WRAP identically on int8/int16
    overflow (numpy and Java both wrap; a path that silently upcast to
    a wider accumulator would diverge here, which the in-range
    differential above cannot observe)."""
    n = 4
    hi = int(np.iinfo(operand.dtype).max)
    alls = [rng.integers(hi // 2, hi, 29).astype(operand.dtype)
            for _ in range(n)]                  # SUM and PROD both wrap
    operator = Operators.by_name(op)
    sock = socket_run(
        n, lambda s, r: s.allreduce_array(alls[r].copy(), operand, operator))
    tpu = [a.copy() for a in alls]
    cluster.allreduce_array(tpu, operand, operator)
    for got_s, got_t in zip(sock, tpu):
        np.testing.assert_array_equal(got_t, got_s)


def test_reduce_scatter_differential(cluster, rng):
    n = 4
    operand = Operands.DOUBLE
    L = 29
    alls = [rng.standard_normal(L).astype(operand.dtype) for _ in range(n)]
    ranges = meta.partition_range(0, L, n)

    sock = socket_run(
        n, lambda s, r: s.reduce_scatter_array(alls[r].copy(), operand,
                                               Operators.SUM))
    tpu = [a.copy() for a in alls]
    cluster.reduce_scatter_array(tpu, operand, Operators.SUM)

    for r, (s, e) in enumerate(ranges):
        np.testing.assert_allclose(tpu[r][s:e], sock[r][s:e], rtol=1e-9)


def test_allgather_differential(cluster, rng):
    n = 4
    operand = Operands.LONG
    L = 21
    alls = [rng.integers(0, 100, L).astype(operand.dtype) for _ in range(n)]

    sock = socket_run(
        n, lambda s, r: s.allgather_array(alls[r].copy(), operand))
    tpu = [a.copy() for a in alls]
    cluster.allgather_array(tpu, operand)

    for got_s, got_t in zip(sock, tpu):
        np.testing.assert_array_equal(got_t, got_s)


def test_broadcast_differential(cluster, rng):
    n = 4
    operand = Operands.FLOAT
    alls = [rng.standard_normal(15).astype(operand.dtype) for _ in range(n)]

    sock = socket_run(
        n, lambda s, r: s.broadcast_array(alls[r].copy(), operand, root=2))
    tpu = [a.copy() for a in alls]
    cluster.broadcast_array(tpu, operand, root=2)

    for got_s, got_t in zip(sock, tpu):
        np.testing.assert_array_equal(got_t, got_s)
