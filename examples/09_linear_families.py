"""The linear model family end-to-end: regression, binary logistic,
and ytk-learn's multiclass_linear analogue (softmax) — each a single
jitted shard_map step whose gradient allreduce is one psum over the
mesh, with eval-set early stopping and params persistence."""
import numpy as np

from ytk_mp4j_tpu.models.linear import LinearConfig, LinearTrainer

rng = np.random.default_rng(0)
N, F = 6_000, 6

# -- regression -------------------------------------------------------
w_true = rng.standard_normal(F).astype(np.float32)
X = rng.standard_normal((N, F)).astype(np.float32)
y = X @ w_true + 0.05 * rng.standard_normal(N).astype(np.float32)
reg = LinearTrainer(LinearConfig(n_features=F, loss="squared",
                                 learning_rate=0.3, momentum=0.9))
params, losses = reg.fit(X, y, n_steps=60)
print(f"squared: loss {losses[0]:.3f} -> {losses[-1]:.4f}, "
      f"|w - w_true| = {np.abs(np.asarray(params[0]) - w_true).max():.3f}")
assert losses[-1] < 0.01

# -- binary logistic with L1 sparsity ---------------------------------
# only features 0 and 1 are informative; the proximal L1 shrink must
# zero (most of) the other four
yb = (X[:, 0] - X[:, 1] > 0).astype(np.float32)
logit = LinearTrainer(LinearConfig(n_features=F, loss="logistic",
                                   learning_rate=0.5, l1=3e-2))
params, losses = logit.fit(X, yb, n_steps=80)
acc = ((logit.predict(params, X) > 0.5) == yb).mean()
nnz = int((np.abs(np.asarray(params[0])) > 1e-6).sum())
print(f"logistic: acc {acc:.3f}, {nnz}/{F} nonzero weights (L1)")
assert acc > 0.9 and nnz < F

# -- multiclass softmax with early stopping ---------------------------
C = 3
centers = rng.standard_normal((C, F)).astype(np.float32) * 2.5
yc = rng.integers(0, C, N).astype(np.int32)
Xc = centers[yc] + rng.standard_normal((N, F)).astype(np.float32)
mc = LinearTrainer(LinearConfig(n_features=F, loss="softmax", n_classes=C,
                                learning_rate=0.5, momentum=0.9))
params, losses = mc.fit(Xc[:5000], yc[:5000], n_steps=150,
                        eval_set=(Xc[5000:], yc[5000:]),
                        early_stopping_rounds=8)
proba = mc.predict(params, Xc[5000:])
acc = (proba.argmax(1) == yc[5000:]).mean()
print(f"softmax: {len(losses)} rounds kept "
      f"(eval history {len(mc.eval_history_)}), holdout acc {acc:.3f}")
assert acc > 0.9
np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-5)

# -- persistence: save, reload, serve identically ---------------------
mc.save_params("/tmp/mc_linear.npz", params)
cfg2, params2 = LinearTrainer.load_params("/tmp/mc_linear.npz",
                                          LinearConfig)
serve = LinearTrainer(cfg2, n_devices=1)
np.testing.assert_allclose(serve.predict(params2, Xc[5000:]), proba,
                           rtol=1e-6)
print("saved, reloaded, and served identically")

# -- streaming: the same libsvm text the FFM family consumes ----------
from ytk_mp4j_tpu.utils.libsvm import dense_chunks, read_libsvm  # noqa: E402

lines = [f"{int(yb[i])} " + " ".join(f"{j}:{X[i, j]:.4f}"
                                     for j in range(F))
         for i in range(2000)]
streamer = LinearTrainer(LinearConfig(n_features=F, loss="logistic",
                                      learning_rate=0.5))
sparams = None
for _ in range(6):   # 6 epochs, chunked, double-buffered
    sparams, slosses = streamer.fit_stream(
        dense_chunks(read_libsvm(iter(lines), chunk_rows=500,
                                 max_nnz=F), F),
        params=sparams, batch_rows=500)
sacc = ((streamer.predict(sparams, X[:2000]) > 0.5)
        == (yb[:2000] > 0.5)).mean()
print(f"streamed logistic from libsvm text: acc {sacc:.3f}")
assert sacc > 0.9
