"""The functional layer: collectives INSIDE your own jitted code.

This is the perf path — the collective is one XLA ICI op in your
program, fused and scheduled by the compiler (no host round-trips).
"""
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ytk_mp4j_tpu.operators import Operators
from ytk_mp4j_tpu.ops import collectives as coll
from ytk_mp4j_tpu.ops import sparse as sparse_ops
from ytk_mp4j_tpu.parallel import make_mesh

mesh = make_mesh()  # 1-D "mp4j" axis over all devices
n = mesh.size


@partial(jax.shard_map, mesh=mesh, in_specs=P("mp4j"), out_specs=P("mp4j"))
def train_step(x):
    grad = jnp.sin(x) * 2.0                     # your compute
    grad = coll.allreduce(grad, Operators.SUM, "mp4j")   # one psum
    return grad


x = jax.device_put(np.ones((n, 8), np.float32),
                   NamedSharding(mesh, P("mp4j")))
print("dense:", np.asarray(jax.jit(train_step)(x))[0, :3])


# sparse allreduce inside jit: static-capacity (index, value) buffers
@partial(jax.shard_map, mesh=mesh, check_vma=False,
         in_specs=(P("mp4j"), P("mp4j")), out_specs=(P(None), P(None)))
def sparse_step(idx, val):
    return sparse_ops.sparse_allreduce(idx[0], val[0], capacity=8,
                                       operator=Operators.SUM,
                                       axis_name="mp4j")


idx = np.full((n, 4), sparse_ops.SENTINEL, np.int32)
val = np.zeros((n, 4), np.float32)
for r in range(n):
    idx[r, 0] = r % 3          # each rank touches one "key code"
    val[r, 0] = float(r + 1)
oi, ov = jax.jit(sparse_step)(
    jax.device_put(idx, NamedSharding(mesh, P("mp4j"))),
    jax.device_put(val, NamedSharding(mesh, P("mp4j"))))
print("sparse:", np.asarray(oi)[:3], np.asarray(ov)[:3])
