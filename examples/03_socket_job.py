"""The CPU socket reference path: a self-contained master + 4 slaves job
on loopback TCP (in threads here; in production each slave is its own
process pointed at the master's host:port, see README)."""
import threading

import numpy as np

from ytk_mp4j_tpu.comm.master import Master
from ytk_mp4j_tpu.comm.process_comm import ProcessCommSlave
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operators

N = 4
master = Master(N, timeout=30.0).serve_in_thread()


def slave_main():
    s = ProcessCommSlave("127.0.0.1", master.port, timeout=30.0)
    s.info(f"slave {s.rank}/{s.slave_num} up")

    # size-aware allreduce (default algo="auto": tree / recursive
    # halving / pipelined ring by payload size; README Transport tuning)
    arr = np.full(1000, float(s.rank + 1))
    s.allreduce_array(arr, Operands.DOUBLE, Operators.SUM)
    assert arr[0] == sum(range(1, N + 1))

    # compressed operand: zlib on the wire for compressible payloads
    zeros = np.zeros(100_000)
    s.allreduce_array(zeros, Operands.compressed(Operands.DOUBLE),
                      Operators.SUM)

    # sparse map allreduce (pickle standing in for Kryo)
    d = {f"grad:{s.rank % 2}": float(s.rank)}
    s.allreduce_map(d, Operands.DOUBLE, Operators.SUM)

    s.barrier()
    s.info(f"done: {sorted(d.items())}")
    s.close(0)


threads = [threading.Thread(target=slave_main) for _ in range(N)]
for t in threads:
    t.start()
for t in threads:
    t.join()
master.join()
print("job exit code:", master.final_code)
