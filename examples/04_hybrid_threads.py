"""Hybrid process x thread nesting (the reference's ThreadCommSlave):
threads reduce through shared memory, thread 0 runs the process-level
collective, results fan back out. Here: one process, 4 threads (pass
master args to spawn_group to join a multi-process job)."""
import threading

import numpy as np

from ytk_mp4j_tpu.comm.thread_comm import ThreadCommSlave
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operators

T = 4
slaves = ThreadCommSlave.spawn_group(T)  # standalone thread group


def thread_main(slave):
    r = slave.rank
    arr = np.full(100, float(r), np.float32)
    slave.allreduce_array(arr, Operands.FLOAT, Operators.SUM)
    assert arr[0] == sum(range(T))

    slave.thread_barrier()               # the reference's threadBarrier()

    d = {f"k{r}": float(r)}
    slave.allgather_map(d, Operands.DOUBLE)
    assert len(d) == T

    slave.close(0)
    return arr


threads = [threading.Thread(target=thread_main, args=(s,)) for s in slaves]
for t in threads:
    t.start()
for t in threads:
    t.join()
print("hybrid group done")
