"""Advanced GBDT consumer flow: multiclass softmax objective,
validation-driven early stopping, stochastic boosting, instance
weights, feature importance, and model persistence — the full
ytk-learn-style workflow on a TPU mesh."""
import numpy as np

from ytk_mp4j_tpu.models.binning import QuantileBinner
from ytk_mp4j_tpu.models.gbdt import GBDTConfig, GBDTTrainer

rng = np.random.default_rng(0)
N, F, B, C = 30_000, 10, 64, 3
X = rng.standard_normal((N, F)).astype(np.float32)
y = (np.digitize(X[:, 4], [-0.5, 0.5])).astype(np.int32)  # 3 classes
w = np.ones(N, np.float32)

binner = QuantileBinner(B).fit(X[: N - 5000])
bins_tr = binner.transform(X[: N - 5000])
bins_va = binner.transform(X[N - 5000:])

cfg = GBDTConfig(n_features=F, n_bins=B, depth=4, n_trees=30,
                 learning_rate=0.3, loss="softmax", n_classes=C,
                 subsample=0.9, colsample=0.9, min_split_gain=1e-6)
trainer = GBDTTrainer(cfg)
trees, _ = trainer.train(
    bins_tr, y[: N - 5000], sample_weight=w[: N - 5000],
    eval_set=(bins_va, y[N - 5000:]), early_stopping_rounds=5)

proba = trainer.predict(bins_va, trees, proba=True)
acc = float((proba.argmax(1) == y[N - 5000:]).mean())
imp = trainer.feature_importance(trees)
print(f"rounds kept: {len(trees)} (history {len(trainer.eval_history_)})")
print(f"holdout acc: {acc:.3f}; top feature: {int(imp.argmax())} "
      f"({imp.max():.0%} of splits)")
assert acc > 0.9 and imp.argmax() == 4

trainer.save_model("/tmp/gbdt_multiclass.npz", trees, binner=binner)
cfg2, trees2, binner2 = GBDTTrainer.load_model("/tmp/gbdt_multiclass.npz")
serve = GBDTTrainer(cfg2)
np.testing.assert_allclose(
    serve.predict(binner2.transform(X[N - 5000:]), trees2, proba=True),
    proba, rtol=1e-5)
print("saved, reloaded, and served identically")

# -- missing values + categorical features (ytk-learn data handling) --
# NaN-laden continuous features: missing_bucket reserves bin 0 for NaN
# and the trainer LEARNS each split's default direction. Feature 9 is a
# TRUE categorical: its small integer codes are placed directly as bin
# ids (in [1, B-2] — bin 0 is the missing bucket, bin B-1 the freeze
# sentinel), NOT quantile-binned; equality splits ("code == c goes
# right") need real category codes, not ordered quantile buckets.
Xm = X.copy()
Xm[rng.random(N) < 0.25, 2] = np.nan
codes = rng.integers(0, 6, N)                 # 6 categories
ym = ((np.isnan(Xm[:, 2]) | (Xm[:, 2] > 0.8))
      & (codes == 2)).astype(np.float32)
# distributed-style fit under missing_bucket: NaN rows are excluded
# from each shard's sketch (per-feature finite counts weight the merge)
mbinner = QuantileBinner(B, missing_bucket=True)
msk = [mbinner.local_sketch(s) for s in np.array_split(Xm, 3)]
mbinner.merge_sketches(np.stack([s.values for s in msk]),
                       np.stack([s.counts for s in msk]))
mbins = np.array(mbinner.transform(Xm))       # writable copy
mbins[:, 9] = codes + 1                       # codes -> bins [1, 6]
mcfg = GBDTConfig(n_features=F, n_bins=B, depth=4, n_trees=20,
                  learning_rate=0.3, loss="logistic",
                  missing_bin=True, categorical_features=(9,))
mtr = GBDTTrainer(mcfg)
mtrees, _ = mtr.train(mbins, ym)
macc = float(((mtr.predict(mbins, mtrees, proba=True) > 0.5) == ym).mean())
print(f"missing+categorical acc: {macc:.3f}")
assert macc > 0.95
