"""End-to-end consumer: distributed GBDT (the north-star workload).
Samples shard over the mesh; each boosting round is ONE jitted
shard_map step whose histogram allreduce is a psum."""
import numpy as np

from ytk_mp4j_tpu.models.gbdt import GBDTConfig, GBDTTrainer

rng = np.random.default_rng(0)
N, F, B = 20_000, 8, 32
bins = rng.integers(0, B, (N, F)).astype(np.int32)
y = ((bins[:, 0] > B // 2).astype(np.float32)
     + 0.1 * rng.standard_normal(N).astype(np.float32))

cfg = GBDTConfig(n_features=F, n_bins=B, depth=4, n_trees=5,
                 learning_rate=0.3)
trainer = GBDTTrainer(cfg)  # all available devices, data-parallel
trees, preds = trainer.train(bins, y)

mse0 = float(np.mean(y ** 2))
mse = float(np.mean((preds[:N] - y) ** 2))
print(f"mse: {mse0:.4f} -> {mse:.4f} after {len(trees)} trees")
assert mse < mse0
