"""End-to-end consumer: distributed GBDT (the north-star workload).
Continuous features are quantile-binned on device, samples shard over
the mesh, each boosting round is ONE jitted shard_map step whose
histogram allreduce is a psum, and ensemble predict runs in one jit."""
import numpy as np

from ytk_mp4j_tpu.models.binning import QuantileBinner
from ytk_mp4j_tpu.models.gbdt import GBDTConfig, GBDTTrainer

rng = np.random.default_rng(0)
N, F, B = 20_000, 8, 32
X = rng.standard_normal((N, F)).astype(np.float32)
y = ((X[:, 0] > 0).astype(np.float32)
     + 0.1 * rng.standard_normal(N).astype(np.float32))

# continuous -> bin ids. Binning is fit DISTRIBUTED-style: each data
# shard is sketched independently (per-feature quantile CDF + count)
# and the sketches merge into one set of edges — on a real multi-host
# job the same two calls run per rank with the sketches riding one
# allgather (QuantileBinner.fit_distributed; check/checkdist.py).
binner = QuantileBinner(B)
sketches = [binner.local_sketch(s) for s in np.array_split(X, 4)]
binner.merge_sketches(np.stack([s.values for s in sketches]),
                      np.stack([s.counts for s in sketches]))
bins = binner.transform(X)

cfg = GBDTConfig(n_features=F, n_bins=B, depth=4, n_trees=5,
                 learning_rate=0.3)
trainer = GBDTTrainer(cfg)  # all available devices, data-parallel
trees, train_preds = trainer.train(bins, y)

preds = trainer.predict(bins, trees)            # ensemble inference
mse0 = float(np.mean(y ** 2))
mse = float(np.mean((preds - y) ** 2))
print(f"mse: {mse0:.4f} -> {mse:.4f} after {len(trees)} trees")
assert mse < mse0
