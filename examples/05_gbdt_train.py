"""End-to-end consumer: distributed GBDT (the north-star workload).
Continuous features are quantile-binned on device, samples shard over
the mesh, each boosting round is ONE jitted shard_map step whose
histogram allreduce is a psum, and ensemble predict runs in one jit."""
import numpy as np

from ytk_mp4j_tpu.models.binning import QuantileBinner
from ytk_mp4j_tpu.models.gbdt import GBDTConfig, GBDTTrainer

rng = np.random.default_rng(0)
N, F, B = 20_000, 8, 32
X = rng.standard_normal((N, F)).astype(np.float32)
y = ((X[:, 0] > 0).astype(np.float32)
     + 0.1 * rng.standard_normal(N).astype(np.float32))

# The one-call consumer path (ytk-learn shape): RAW continuous
# features in, the trainer quantile-bins internally (train_raw) and
# keeps the fitted binner for serving. On a multi-process job, pass
# ``comm=`` and the binner fits DISTRIBUTED (each rank sketches its
# own shard, one allgather merges — check/checkdist.py runs that).
cfg = GBDTConfig(n_features=F, n_bins=B, depth=4, n_trees=5,
                 learning_rate=0.3)
trainer = GBDTTrainer(cfg)  # all available devices, data-parallel
trees, train_preds = trainer.train_raw(X, y)

preds = trainer.predict_raw(X, trees)           # ensemble inference
mse0 = float(np.mean(y ** 2))
mse = float(np.mean((preds - y) ** 2))
print(f"mse: {mse0:.4f} -> {mse:.4f} after {len(trees)} trees")
assert mse < mse0

# the manual wiring underneath: the sketch/merge pair is what
# fit_distributed runs per rank on a multi-host job (edges are the
# merge's 2/Q-approximation of train_raw's exact local fit)
binner = QuantileBinner(B)
sketches = [binner.local_sketch(s) for s in np.array_split(X, 4)]
binner.merge_sketches(np.stack([s.values for s in sketches]),
                      np.stack([s.counts for s in sketches]))
bins = binner.transform(X)
manual_preds = GBDTTrainer(cfg).train(bins, y)[1]
assert np.isfinite(manual_preds).all()
