"""Device-path collectives with TpuCommCluster.

Runs on whatever devices are available; to simulate an 8-chip pod on CPU:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 python 01_tpu_cluster.py

(under the axon TPU tunnel the flag is consumed at startup; on a plain
machine it yields 8 virtual devices).
"""
import numpy as np

from ytk_mp4j_tpu import trace, trace_collectives
from ytk_mp4j_tpu.comm.tpu_comm import TpuCommCluster
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operator, Operators

cluster = TpuCommCluster()  # all devices; TpuCommCluster(5) also works
n = cluster.slave_num
print(f"{n} rank(s)")

with trace_collectives():
    # dense allreduce, in place across per-rank buffers
    arrs = [np.full(1 << 16, float(r + 1), np.float32) for r in range(n)]
    cluster.allreduce_array(arrs, Operands.FLOAT, Operators.SUM)
    assert arrs[0][0] == sum(range(1, n + 1))

    # sub-range semantics (the reference's [from, to))
    arrs = [np.arange(10, dtype=np.float32) for _ in range(n)]
    cluster.allreduce_array(arrs, Operands.FLOAT, Operators.SUM,
                            from_=2, to=6)

    # reduce-scatter + allgather over per-rank segments
    arrs = [np.ones(13, np.float32) * (r + 1) for r in range(n)]
    cluster.reduce_scatter_array(arrs, Operands.FLOAT, Operators.SUM)
    cluster.allgather_array(arrs, Operands.FLOAT)

    # sparse Map<K, V> operands (keys on host, values on device)
    maps = [{f"w:{r % 3}": np.ones(4, np.float32) * r} for r in range(n)]
    cluster.allreduce_map(maps, Operands.FLOAT, Operators.SUM)

    # pipelined map allreduce: chain dispatches, resolve later — the
    # deferred handles overlap host encodes with device work, so k
    # chained calls pay ~one round-trip instead of k (the steady-state
    # configs[2] rate; chained A/B in BASELINE.md)
    step1 = [{r: 1.0} for r in range(n)]
    step2 = [{r + 1: 2.0} for r in range(n)]
    h1 = cluster.allreduce_map_async(step1, Operands.FLOAT,
                                     Operators.SUM)
    h2 = cluster.allreduce_map_async(step2, Operands.FLOAT,
                                     Operators.SUM)
    h1.result(), h2.result()                 # mutates in place, like
    assert len(step1[0]) == n                # the sync call

    # user-defined operator: on the DEVICE path the reduction runs
    # inside jit, so write it with jnp (jnp also works on host numpy
    # inputs; an np-only fn would fail to trace on multi-device meshes)
    import jax.numpy as jnp
    absmax = Operator.custom(
        "ABSMAX",
        lambda x, y: jnp.where(jnp.abs(x) >= jnp.abs(y), x, y), 0.0)
    # (64-bit operands need jax_enable_x64 on the device path)
    arrs = [np.full(8, float(r - 1), np.float32) for r in range(n)]
    cluster.allreduce_array(arrs, Operands.FLOAT, absmax)

print(trace.format_summary())
