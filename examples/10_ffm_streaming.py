"""Streaming FFM ingestion — the Criteo-scale configs[4] consumer flow:
a libffm-format text file streamed chunk-by-chunk through
``FMTrainer.fit_stream`` (one jitted step per chunk, never more than
one chunk in host memory), checked against the in-memory fit on the
same data.

The pipeline is fully composed: text parses through the native C++
chunk scanner (csrc/mp4j_parse.cpp), chunk k+1 stages while the device
runs step k (fit_stream double-buffers; ``max_in_flight=0`` would
serialize), and at pod scale the same loop runs with
``table_sharding="sharded"`` so the vocabulary shards over the mesh
(examples stay replicated — 1-chip measurement keeps it faster)."""
import os
import tempfile

import numpy as np

from ytk_mp4j_tpu.models.fm import FMConfig, FMTrainer
from ytk_mp4j_tpu.utils.libsvm import read_libsvm

rng = np.random.default_rng(0)
N, VOCAB, FIELDS, NNZ = 4_000, 512, 4, 4
feats = np.stack([rng.integers(f * (VOCAB // FIELDS),
                               (f + 1) * (VOCAB // FIELDS), N)
                  for f in range(NNZ)], axis=1).astype(np.int32)
fields = np.broadcast_to(np.arange(NNZ, dtype=np.int32) % FIELDS,
                         (N, NNZ)).copy()
vals = np.ones((N, NNZ), np.float32)
y = ((feats[:, 0] + feats[:, 1]) % 2).astype(np.float32)

# write the libffm file the way ytk-learn would consume it
fd, path = tempfile.mkstemp(suffix=".ffm")
with os.fdopen(fd, "w") as fh:
    for i in range(N):
        toks = " ".join(f"{fields[i, j]}:{feats[i, j]}:{vals[i, j]:.1f}"
                        for j in range(NNZ))
        fh.write(f"{y[i]:.0f} {toks}\n")

cfg = FMConfig(n_features=VOCAB, n_fields=FIELDS, k=8, max_nnz=NNZ,
               model="ffm", learning_rate=0.5, init_scale=0.1)
CHUNK = 1_000

# stream: 3 passes over the file, one optimizer step per chunk
streamer = FMTrainer(cfg, sparse_grads=True)
params = streamer.init_params(seed=1)
stream_losses = []
for epoch in range(3):
    params, losses = streamer.fit_stream(
        read_libsvm(path, chunk_rows=CHUNK, max_nnz=NNZ),
        params=params, batch_rows=CHUNK)
    stream_losses.extend(losses.tolist())
    print(f"epoch {epoch}: mean chunk loss {losses.mean():.4f}")

# reference: the same data fit in memory
memory = FMTrainer(cfg, sparse_grads=True)
mem_params, mem_losses = memory.fit(feats, fields, vals, y, n_steps=12,
                                    seed=1)

acc = float(np.mean(
    (streamer.predict(params, feats, fields, vals) > 0.5) == (y > 0.5)))
print(f"stream final loss {stream_losses[-1]:.4f} "
      f"(in-memory {mem_losses[-1]:.4f}), train acc {acc:.3f}")
assert stream_losses[-1] < stream_losses[0]
os.unlink(path)
