"""Instance-weighted training across the consumer families (round 5).

ytk-learn weights examples end to end; here the SAME weight vector
flows through (a) the quantile sketch — weighted bins via the
inverted-CDF convention, where integer weights behave exactly like
physically duplicated rows — (b) GBDT boosting gradients via the
one-call train_raw, and (c) the FM/linear weighted-mean steps.
"""
import numpy as np

from ytk_mp4j_tpu.models.binning import QuantileBinner
from ytk_mp4j_tpu.models.fm import FMConfig, FMTrainer
from ytk_mp4j_tpu.models.gbdt import GBDTConfig, GBDTTrainer
from ytk_mp4j_tpu.models.linear import LinearConfig, LinearTrainer

rng = np.random.default_rng(0)
N, F = 4_000, 6
X = rng.standard_normal((N, F)).astype(np.float32)
y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
# upweight the positive class 3x (the classic imbalance treatment)
w = np.where(y > 0, 3.0, 1.0).astype(np.float32)

# (a) weighted quantile bins: integer weights == row duplication
b_w = QuantileBinner(16).fit(X, sample_weight=w)
b_dup = QuantileBinner(16).fit(
    np.repeat(X, w.astype(np.int64), axis=0),
    sample_weight=np.ones(int(w.sum())))
np.testing.assert_array_equal(b_w.edges, b_dup.edges)
print("weighted bins == duplicated-row bins")

# (b) one-call weighted GBDT: the weights reach the sketch AND the
# boosting gradients; the fitted binner rides save_model
cfg = GBDTConfig(n_features=F, n_bins=16, depth=4, n_trees=5,
                 loss="logistic", learning_rate=0.3)
tr = GBDTTrainer(cfg)
trees, _ = tr.train_raw(X, y, sample_weight=w)
proba = tr.predict_raw(X, trees, proba=True)
recall = float(np.mean((proba[y > 0] > 0.5)))
print(f"gbdt weighted positive-class recall: {recall:.3f}")
assert recall > 0.9

# (c) the linear family: same vector, same semantics
ltr = LinearTrainer(LinearConfig(n_features=F, loss="logistic",
                                 learning_rate=0.5))
params, losses = ltr.fit(X, y, n_steps=60, sample_weight=w)
lrecall = float(np.mean(ltr.predict(params, X)[y > 0] > 0.5))
print(f"linear weighted positive-class recall: {lrecall:.3f}")
assert lrecall > 0.9

# (d) FM: integer weights == duplicated rows, loss-for-loss (the
# weighted-mean step normalizes by the weight sum)
feats = rng.integers(0, 32, (256, 2)).astype(np.int32)
fm_fields = np.broadcast_to(np.arange(2, dtype=np.int32),
                            (256, 2)).copy()
vals = np.ones((256, 2), np.float32)
yf = rng.integers(0, 2, 256).astype(np.float32)
k = rng.integers(1, 4, 256)
fcfg = FMConfig(n_features=32, n_fields=2, k=4, max_nnz=2, model="ffm",
                learning_rate=0.3, init_scale=0.1)
_, l_w = FMTrainer(fcfg).fit(feats, fm_fields, vals, yf, n_steps=3,
                             seed=1, sample_weight=k.astype(np.float32))
d = lambda a: np.repeat(a, k, axis=0)  # noqa: E731
_, l_d = FMTrainer(fcfg).fit(d(feats), d(fm_fields), d(vals), d(yf),
                             n_steps=3, seed=1)
np.testing.assert_allclose(l_w, l_d, rtol=1e-4, atol=1e-6)
print("ffm weighted losses == duplicated-row losses")
