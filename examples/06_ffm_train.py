"""FFM consumer: field-aware factorization machine with the sparse
embedding-gradient allreduce (the Criteo-shaped workload of
BASELINE.md configs[4]); train, persist, and serve."""
import numpy as np

from ytk_mp4j_tpu.models.fm import FMConfig, FMTrainer

rng = np.random.default_rng(0)
N, NF, NFIELDS, K = 20_000, 1000, 4, 6
feats = rng.integers(0, NF, (N, K)).astype(np.int32)
fields = rng.integers(0, NFIELDS, (N, K)).astype(np.int32)
vals = np.ones((N, K), np.float32)
y = (feats.min(1) < NF // 10).astype(np.float32)

cfg = FMConfig(model="ffm", n_features=NF, n_fields=NFIELDS, k=4,
               max_nnz=K, learning_rate=0.5)
trainer = FMTrainer(cfg, sparse_grads=True)  # device sparse allreduce
params, losses = trainer.fit(feats, fields, vals, y, n_steps=100)
print(f"logloss: {losses[0]:.4f} -> {losses[-1]:.4f}")
assert losses[-1] < losses[0]

trainer.save_params("/tmp/ffm_model.npz", params)
cfg2, params2 = FMTrainer.load_params("/tmp/ffm_model.npz", FMConfig)
serve = FMTrainer(cfg2)
p = serve.predict(params2, feats[:5], fields[:5], vals[:5])
print("served probs:", np.round(p, 3))
