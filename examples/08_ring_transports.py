"""The transport schedules, side by side.

The same allreduce runs as (1) one fused XLA collective, (2) a
hand-scheduled ppermute ring, (3) the Pallas RDMA ring kernel that
owns the transport itself (remote DMA + entry barrier + credit
backpressure; interpreted off-TPU), and (4) the bidirectional RDMA
variant that rings the buffer's halves in opposite directions so both
full-duplex ICI link directions carry payload — selectable per call on
the driver API and composable inside your own jitted shard_map code.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 python 08_ring_transports.py
"""
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ytk_mp4j_tpu.comm.tpu_comm import TpuCommCluster
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operators
from ytk_mp4j_tpu.ops import collectives as coll
from ytk_mp4j_tpu.ops import ring, ring_kernel
from ytk_mp4j_tpu.parallel import make_mesh

cluster = TpuCommCluster()
n = cluster.slave_num
print(f"{n} rank(s)")

# -- driver API: same call, three schedules, identical results --------
# analytic ground truth, not a self-comparison: sum_r (r+1) * iota
want = np.arange(1000, dtype=np.float32) * (n * (n + 1) / 2)
for algo in ("xla", "ring", "rdma"):
    arrs = [np.arange(1000, dtype=np.float32) * (r + 1) for r in range(n)]
    cluster.allreduce_array(arrs, Operands.FLOAT, Operators.SUM, algo=algo)
    assert np.allclose(arrs[0], want, rtol=1e-5)
    print(f"algo={algo:4s}: ok (first elems {arrs[0][:3]})")

# -- functional layer: the same schedules inside YOUR jit -------------
mesh = make_mesh(n)
on_tpu = mesh.devices.flat[0].platform == "tpu"
data = np.tile(np.arange(n, dtype=np.float32)[:, None], (1, 16 * n))


@partial(jax.shard_map, mesh=mesh, in_specs=P("mp4j"),
         out_specs=(P("mp4j"),) * 4, check_vma=False)
def four_ways(x):
    v = x[0]
    a = coll.allreduce(v, Operators.SUM, "mp4j")
    b = ring.ring_allreduce(v, Operators.SUM, "mp4j")
    c = ring_kernel.ring_allreduce_kernel(v, Operators.SUM, "mp4j",
                                          interpret=not on_tpu)
    # both full-duplex ICI link directions busy at once
    d = ring_kernel.ring_allreduce_kernel(v, Operators.SUM, "mp4j",
                                          interpret=not on_tpu,
                                          bidirectional=True)
    return a[None], b[None], c[None], d[None]


a, b, c, d = jax.jit(four_ways)(data)
want = data.sum(0)
for name, out in (("psum", a), ("ppermute ring", b),
                  ("rdma kernel", c), ("rdma bidirectional", d)):
    assert np.allclose(np.asarray(out)[0], want, rtol=1e-5)
    print(f"in-jit {name}: ok")
print("all transports agree")
