#!/usr/bin/env python
"""North-star benchmark: GBDT histogram allreduce (BASELINE.md).

Measures the flagship workload — per-tree-level (node x feature x bin)
gradient/hessian histogram build + allreduce (ytk-learn GBDT shape:
F=28 features, 256 bins, depth-6 trees, Higgs-like synthetic data) — on:

1. the TPU path: one jitted shard_map step per tree over the available
   chip(s) (one-hot MXU matmul histograms + psum allreduce);
2. the CPU socket baseline: the same tree build with numpy histograms
   and the histogram allreduce over real loopback TCP via
   ProcessCommSlave ring collectives (the reference's architecture).

Timing honesty: the axon tunnel's ``block_until_ready`` does not
actually block on remote execution, so every timed region here is
closed by ``np.asarray`` of a device value — a full host round-trip.

Metric (GB/s/chip): bytes of training data scanned per histogram pass
(depth levels x N x (F bin-bytes + 8 grad/hess bytes)) per second per
chip — a rate, so the two paths may use different N. vs_baseline is the
TPU rate over the socket rate.

TPU context (measured, see models/gbdt.py + ops/hist_kernel.py):
scatter histograms are bound by the chip's serial scatter unit
(~13 ns/element); the "matmul" strategy routes the build onto the MXU
instead (tiled one-hot matmul, hi/lo bf16 split), ~6x end-to-end; the
default "pallas" strategy fuses the one-hot generation and the matmul
in VMEM, a further ~26% (measured 170 vs 230 ms/tree on v5e) — near
the VPU floor of the one-hot generation itself. The collective (psum
over ICI vs Kryo-socket rounds, socket allreduce GB/s in extras)
additionally scales with chips while the socket ring does not.
The timed loop chains ``trees`` steps per host sync because the axon
tunnel costs ~100 ms per round-trip + ~2 ms per dispatch (measured);
small-rep timings are dominated by that, not device work.

Prints exactly one JSON line.
"""

import collections
import json
import os
import queue as pyqueue
import sys
import threading
import time

import numpy as np


def make_data(n, f, b, seed=0):
    rng = np.random.default_rng(seed)
    bins = rng.integers(0, b, (n, f)).astype(np.int32)
    y = (bins[:, 0] / b + 0.1 * rng.standard_normal(n)).astype(np.float32)
    return bins, y


def scanned_bytes(n, f, depth):
    # per level the trainer scans every sample's F bin bytes + g/h floats
    return depth * n * (f + 8)


# ----------------------------------------------------------------------
def _aot_compile(jitted, *args):
    """Compile ``jitted`` for ``args`` ONCE (AOT), returning
    (callable, flops): the executable serves both the timed loop and
    the MFU numerator, instead of paying the jit compile AND a second
    lower().compile() just for cost analysis (review round 5). Falls
    back to the plain jit callable when AOT is unavailable. (Scatter
    BYTE costs from this analysis are fantasy-magnitude — measured
    round 4 — but the flop count is the standard MFU numerator.)"""
    try:
        compiled = jitted.lower(*args).compile()
        c = compiled.cost_analysis()
        if isinstance(c, (list, tuple)):
            c = c[0]
        fl = float(c.get("flops", 0.0))
        return compiled, (fl if fl > 0 else None)
    except Exception:
        return jitted, None


def gbdt_hist_mxu_flops(n, f, b, depth):
    """Analytic MXU flops of the fused Pallas histogram matmuls per
    tree. XLA's cost_analysis cannot see inside the Pallas custom call,
    so the cost-analysis MFU is only the XLA-visible remainder; this is
    the kernel's own arithmetic: level 0 histograms 1 node, levels
    d >= 1 histogram 2**(d-1) LEFT children (sibling subtraction,
    models/gbdt.py), and per level the kernel contracts the
    [tile, 4*n_nodes] hi/lo-split operand with the per-feature
    [tile, B] one-hot — 2 * N * 4*n_nodes * B * F flops."""
    nodes = 1 + sum(2 ** (d - 1) for d in range(1, depth))
    return 2.0 * n * 4 * nodes * b * f


def bench_tpu(n=1_000_000, f=28, b=256, depth=6, trees=10):
    import jax
    from ytk_mp4j_tpu.models.gbdt import GBDTConfig, GBDTTrainer

    cfg = GBDTConfig(n_features=f, n_bins=b, depth=depth,
                     learning_rate=0.1, n_trees=trees)
    tr = GBDTTrainer(cfg)  # all available real devices
    bins, y = make_data(n, f, b)
    dbins, dy, dpreds, dw = tr.shard_data(bins, y)
    kd = jax.random.key_data(jax.random.key(0))
    step, flops = _aot_compile(tr._build_step(), dbins, dy, dpreds, dw,
                               kd)
    # warmup; np.asarray forces a real host round-trip
    dpreds, tree = step(dbins, dy, dpreds, dw, kd)
    np.asarray(tree[0])
    t0 = time.perf_counter()
    for _ in range(trees):
        dpreds, tree = step(dbins, dy, dpreds, dw, kd)
    np.asarray(tree[0])  # sync: steps chain on device
    dt = (time.perf_counter() - t0) / trees
    n_chips = jax.device_count()
    gbs_per_chip = scanned_bytes(n, f, depth) / dt / 1e9 / n_chips
    flops_per_sec = None if flops is None else flops / dt / n_chips
    hist_fps = gbdt_hist_mxu_flops(n, f, b, depth) / dt / n_chips
    return gbs_per_chip, 1.0 / dt, n_chips, flops_per_sec, hist_fps


# ----------------------------------------------------------------------
def _numpy_histograms(bins, g, h, node_ids, n_nodes, f, b):
    hg = np.zeros((n_nodes, f, b), np.float32)
    hh = np.zeros((n_nodes, f, b), np.float32)
    base = node_ids.astype(np.int64) * (f * b)
    for j in range(f):
        ids = base + j * b + bins[:, j]
        hg.reshape(-1)[:] += np.bincount(ids, weights=g,
                                         minlength=n_nodes * f * b)
        hh.reshape(-1)[:] += np.bincount(ids, weights=h,
                                         minlength=n_nodes * f * b)
    return hg, hh


def _run_socket_job(procs, body, native_transport, join_timeout=300.0,
                    master_kwargs=None, **slave_kwargs):
    """Master + ``procs`` slave worker PROCESSES; ``body(slave, rank)``
    returns a per-rank result. Returns ``(results, stats)`` where
    ``stats`` is the merged cross-rank ``comm.stats()`` snapshot of the
    whole job (emitted in the BENCH extra so every socket workload's
    wire/reduce/serialize budget is tracked across rounds). Raises the
    first worker error, or a RuntimeError naming the hung ranks if any
    worker missed the join deadline without raising. ``slave_kwargs``
    forward to every ProcessCommSlave (e.g. ``map_columnar=False`` for
    the pickled-plane A/B leg).

    Real OS processes (fork), matching the reference's unit of
    parallelism — N slave JVMs on one host (SURVEY.md section 4). A
    thread-based harness would share the GIL, understating the baseline
    (pickle framing holds the GIL); fork also lets ``body`` closures
    capture the benchmark data without pickling. Socket benches must
    run BEFORE any TPU client exists in this process (see main) — the
    children inherit the parent image and a forked device runtime is
    not fork-safe."""
    import multiprocessing as mp

    from ytk_mp4j_tpu.comm.master import Master
    from ytk_mp4j_tpu.comm.process_comm import ProcessCommSlave

    ctx = mp.get_context("fork")
    # frozen legs pin MP4J_ELASTIC=off, the nonblocking scheduler off
    # and the health plane off (the shm/audit/sink precedent):
    # historical figures stay comparable whatever the caller's env
    # says; the async/health legs opt back in explicitly. autoscale
    # joins the pin list (ISSUE 13): a frozen figure must not move
    # because an operator exported MP4J_AUTOSCALE=act
    mk = {"elastic": "off", "health": False, "autoscale": "off",
          "tuner": "off"}
    mk.update(master_kwargs or {})
    master = Master(procs, timeout=60.0, **mk).serve_in_thread()
    q = ctx.Queue()
    slave_kwargs.setdefault("elastic", "off")
    slave_kwargs.setdefault("async_collectives", False)
    slave_kwargs.setdefault("health", False)
    # frozen figures must not move because an operator exported
    # MP4J_TUNER=act (ISSUE 15): the tuner's own A/B leg opts back in
    slave_kwargs.setdefault("tuner", "off")

    def worker():
        try:
            # child-only pin (after fork, parent env untouched): frozen
            # figures must not move because an operator exported
            # MP4J_OVERLAP=1 (ISSUE 17) — the trainer-overlap leg opts
            # back in explicitly via StepStatsExchanger(overlap=True)
            os.environ["MP4J_OVERLAP"] = "0"
            slave = ProcessCommSlave("127.0.0.1", master.port, timeout=60.0,
                                     native_transport=native_transport,
                                     **slave_kwargs)
            res = body(slave, slave.rank)
            snap = slave.stats()
            slave.close(0)
            q.put(("ok", slave.rank, (res, snap)))
        except Exception as e:  # pragma: no cover
            q.put(("err", -1, repr(e)))

    ps = [ctx.Process(target=worker, daemon=True) for _ in range(procs)]
    for p in ps:
        p.start()
    results = [None] * procs
    deadline = time.monotonic() + join_timeout
    got = 0
    while got < procs:
        try:
            kind, rank, payload = q.get(timeout=1.0)
        except pyqueue.Empty:
            # fail fast on a child killed by a signal (segfault / OOM):
            # it can never report, so waiting out the deadline would
            # misdiagnose the crash as a hang
            dead = [p.exitcode for p in ps
                    if not p.is_alive() and p.exitcode not in (0, None)]
            if dead:
                for p in ps:
                    p.terminate()
                raise RuntimeError(
                    f"socket benchmark worker died without reporting "
                    f"(exit codes {dead})")
            if time.monotonic() > deadline:
                break
            continue
        if kind == "err":
            for p in ps:
                p.terminate()
            raise RuntimeError(f"socket benchmark worker failed: {payload}")
        results[rank] = payload
        got += 1
    for p in ps:
        p.join(max(0.1, deadline - time.monotonic()))
        if p.is_alive():
            p.terminate()
    if any(r is None for r in results):
        hung = [i for i, r in enumerate(results) if r is None]
        raise RuntimeError(
            f"socket benchmark workers hung past the join timeout: "
            f"ranks {hung}")
    from ytk_mp4j_tpu.utils.stats import merge_snapshots

    stats = merge_snapshots(*(snap for _, snap in results))
    return [res for res, _ in results], _round_stats(stats)


def _round_stats(stats):
    """Snapshot floats trimmed for the one-line BENCH JSON."""
    return {name: {k: (round(v, 6) if isinstance(v, float) else v)
                   for k, v in entry.items()}
            for name, entry in stats.items()}


def bench_socket(n=200_000, f=28, b=256, depth=6, procs=4,
                 native_transport=False):
    """The reference-architecture baseline: numpy histogram build + ring
    allreduce of the histogram buffers over loopback TCP. Also returns
    the pure collective rate (allreduce GB/s of the histogram buffers).

    ``native_transport=False`` is the FROZEN baseline: the fully framed
    per-message path mirroring the reference's Kryo-framed JVM sockets.
    True measures our native C++ raw data plane (reported in extras,
    not used as the comparison baseline)."""
    from ytk_mp4j_tpu.operands import Operands
    from ytk_mp4j_tpu.operators import Operators

    bins, y = make_data(n, f, b, seed=1)
    per = n // procs

    def body(slave, r):
        lb = bins[r * per:(r + 1) * per]
        ly = y[r * per:(r + 1) * per]
        g = ly.copy()          # preds=0 -> g = -y up to sign; fine
        h = np.ones_like(g)
        node_ids = np.zeros(per, np.int32)
        slave.barrier()
        t0 = time.perf_counter()
        lam = 1.0
        cbytes = 0
        csecs = 0.0
        for d in range(depth):
            n_nodes = 2 ** d
            hg, hh = _numpy_histograms(lb, g, h, node_ids, n_nodes, f, b)
            flat = np.concatenate([hg.reshape(-1), hh.reshape(-1)])
            c0 = time.perf_counter()
            slave.allreduce_array(flat, Operands.FLOAT, Operators.SUM)
            csecs += time.perf_counter() - c0
            cbytes += flat.nbytes
            hg = flat[:hg.size].reshape(n_nodes, f, b)
            hh = flat[hg.size:].reshape(n_nodes, f, b)
            # split finding + routing (numpy mirror of the TPU path)
            cg, ch = np.cumsum(hg, -1), np.cumsum(hh, -1)
            Gt, Ht = cg[..., -1:], ch[..., -1:]
            gain = (cg ** 2 / (ch + lam)
                    + (Gt - cg) ** 2 / (Ht - ch + lam)
                    - Gt ** 2 / (Ht + lam))
            gain[..., -1] = -np.inf
            best = gain.reshape(n_nodes, -1).argmax(-1)
            feat, bin_ = best // b, best % b
            v = np.take_along_axis(lb, feat[node_ids][:, None],
                                   axis=1)[:, 0]
            node_ids = node_ids * 2 + (v > bin_[node_ids])
        return time.perf_counter() - t0, cbytes, csecs

    # frozen baseline legs stay all-TCP: MP4J_SHM now defaults on,
    # and the reference figures must keep measuring the socket wire
    # (audit="off" likewise pins the pre-ISSUE-8 wire figure — the
    # audit tax has its own A/B leg, see bench_audit_overhead)
    results, stats = _run_socket_job(procs, body, native_transport,
                                     shm=False, audit="off",
                                     sink_dir="")
    dt = max(res[0] for res in results)
    _, cbytes, csecs = results[0]
    # the socket job scanned n samples total across `procs` workers on
    # one host: rate per "chip" = whole-job rate (one machine)
    return (scanned_bytes(n, f, depth) / dt / 1e9, cbytes / csecs / 1e9,
            stats)


def bench_socket_collective(f=28, b=256, depth=6, procs=4, reps=3,
                            native_transport=True, shm=False,
                            algo="auto", audit="off", sink_dir="",
                            health=False):
    """Allreduce rate alone over the tree-level histogram buffer shapes
    (no numpy histogram/split work — used for the native-transport
    extras figure without re-running the whole socket workload).

    ``shm=False`` pins the all-TCP plane (the headline
    ``socket_collective_gbs`` figure bench-diff gates for continuity);
    ``audit="off"`` likewise pins the pre-ISSUE-8 figure — the audit
    plane's cost is measured by its own interleaved A/B
    (``bench_audit_overhead``), not smeared into every frozen leg;
    ``shm=True`` negotiates the intra-host shared-memory transport
    (ISSUE 7 — the 4 forked slaves share this host, so every pair
    rides it). ``algo`` forwards to every allreduce (``"twolevel"``
    forces the topology-aware schedule; on this single-host roster
    that is the binomial reduce+broadcast over shm with a no-op
    leader leg — the intra-host half of the two-level figure).

    Bench-host caveat (measured, ISSUE 7): this virtualized 1-core
    host's loopback TCP is itself a same-kernel memcpy with
    first-class scheduler wakeups, so the shm figure lands at TCP
    PARITY here rather than above it — the acceptance anchor is the
    r05 TCP figure (0.041 GB/s), which shm clears >=3x. The ring's
    syscall-free bulk path is the structural win on real multi-core
    hosts. Two environment findings are load-bearing for anyone
    re-tuning this: (a) mappings of files from the mounted /dev/shm
    tmpfs degraded ALL socket ops in the mapping process ~20x (hence
    the memfd segment backing); (b) every user-space wait discipline
    (spin, yield, select-parked doorbells) lost ms-scale scheduler
    tails to the kernel's recv wakeup on this oversubscribed host
    (hence the carrier sync-byte protocol)."""
    from ytk_mp4j_tpu.operands import Operands
    from ytk_mp4j_tpu.operators import Operators

    sizes = [2 * (2 ** d) * f * b for d in range(depth)]

    def body(slave, r):
        bufs = [np.ones(s, np.float32) for s in sizes]
        slave.barrier()
        t0 = time.perf_counter()
        nbytes = 0
        for _ in range(reps):
            for buf in bufs:
                slave.allreduce_array(buf, Operands.FLOAT,
                                      Operators.SUM, algo=algo)
                nbytes += buf.nbytes
        return nbytes / (time.perf_counter() - t0)

    rates, stats = _run_socket_job(procs, body, native_transport,
                                   join_timeout=120.0, shm=shm,
                                   audit=audit, sink_dir=sink_dir,
                                   health=health,
                                   master_kwargs={"health": health})
    return min(rates) / 1e9, stats


def bench_socket_allreduce_sweep(procs=4, reps=8, native_transport=True):
    """Size sweep grounding the ``algo="auto"`` thresholds: per-size,
    per-algo allreduce GB/s over the default (native raw) data plane,
    emitted in the JSON ``extra`` so the thresholds stay data-grounded
    and tracked across rounds. Sizes bracket the latency-bound ->
    bandwidth-bound transition (4 KiB ... 8 MiB payloads)."""
    from ytk_mp4j_tpu.operands import Operands
    from ytk_mp4j_tpu.operators import Operators

    sizes = [1024, 16_384, 65_536, 262_144, 1_048_576, 2_097_152]  # f32
    algos = ("tree", "rhd", "ring", "auto")

    def _reps(size):
        # latency-bound sizes are the noisiest on a shared host and the
        # cheapest to repeat: 4x reps below 256 KiB
        return reps * 4 if size * 4 < 262_144 else reps

    def body(slave, r):
        out = {(s, a): [] for s in sizes for a in algos}
        for size in sizes:
            buf = np.ones(size, np.float32)
            # interleave algos per rep so system-load drift spreads
            # evenly instead of biasing whole blocks
            for _ in range(_reps(size)):
                for algo in algos:
                    slave.barrier()
                    t0 = time.perf_counter()
                    slave.allreduce_array(buf, Operands.FLOAT,
                                          Operators.SUM, algo=algo)
                    out[(size, algo)].append(time.perf_counter() - t0)
        return out

    # all-TCP: this sweep grounds the MP4J_ALGO_* thresholds for
    # the inter-host (TCP) regime the auto rule serves
    rates, stats = _run_socket_job(procs, body, native_transport,
                                   join_timeout=600.0, shm=False,
                                   audit="off", sink_dir="")
    sweep = {}
    for size in sizes:
        row = {}
        for algo in algos:
            # per rep: the slowest rank defines the collective's time;
            # across reps: the best rep (min) is the standard
            # noise-robust microbenchmark statistic on a shared host
            dt = min(max(res[(size, algo)][k] for res in rates)
                     for k in range(_reps(size)))
            row[algo] = round(size * 4 / dt / 1e9, 4)
        sweep[f"{size * 4}B"] = row
    return sweep, stats


def bench_socket_async_overlap(procs=4, k=4, size=262_144, reps=8):
    """ISSUE 11 figures: ``socket_async_overlap_gbs`` — k outstanding
    1 MB ``iallreduce`` futures driven by the helper-thread scheduler
    (the native leg-graph driver: every leg of every outstanding
    collective in ONE C++ poll loop) — against
    ``socket_async_sequential_gbs``, the same k collectives as
    sequential blocking calls. Isolated leg, all-TCP, audit/sink off
    (the frozen-leg precedent); the k sequential leg runs with the
    scheduler pinned off (``async_collectives=False``) so it is the
    exact pre-ISSUE-11 path.

    MEASURED REALITY on this bench host (documented like PR 7's
    shm-parity caveat): this is a ONE-core Firecracker guest, and the
    sequential blocking path already saturates the core — its loopback
    wire runs at the kernel-TCP CPU ceiling (~1.4 GB/s aggregate
    duplex, measured) with 0% idle, so there is no latency to hide:
    overlap cannot create CPU cycles, and every scheduling layer adds
    some. The async figure lands BELOW sequential here (~0.6-0.7x;
    rusage shows the delta is scheduler CPU + extra context switches,
    the same class of 1-core scheduler-tail cost PR 7 measured for
    user-space shm waits). The structural win of k outstanding
    collectives — per-exchange wakeups and rounds amortized k-fold,
    wire idle time on real multi-core/NIC hosts filled with other
    collectives' work — needs a host where the wire is not the same
    CPU the ranks compute on. The figure the async plane DOES win on
    this host is ``socket_coalesce_keys_per_sec`` (fixed-cost
    amortization, ~2.5x — see bench_socket_coalesce); bench-diff gates
    both async figures so neither regresses further."""
    from ytk_mp4j_tpu.operands import Operands
    from ytk_mp4j_tpu.operators import Operators

    def body_seq(slave, r):
        bufs = [np.ones(size, np.float32) for _ in range(k)]
        slave.barrier()
        t0 = time.perf_counter()
        n = 0
        for _ in range(reps):
            for b in bufs:
                slave.allreduce_array(b, Operands.FLOAT,
                                      Operators.SUM)
                n += b.nbytes
        return n / (time.perf_counter() - t0)

    def body_async(slave, r):
        bufs = [np.ones(size, np.float32) for _ in range(k)]
        slave.barrier()
        t0 = time.perf_counter()
        n = 0
        for _ in range(reps):
            futs = [slave.iallreduce(b, Operands.FLOAT,
                                     Operators.SUM) for b in bufs]
            slave.wait_all()
            n += sum(b.nbytes for b in bufs)
        return n / (time.perf_counter() - t0)

    seq, _ = _run_socket_job(procs, body_seq, True, shm=False,
                             audit="off", sink_dir="",
                             async_collectives=False)
    asy, stats = _run_socket_job(procs, body_async, True, shm=False,
                                 audit="off", sink_dir="",
                                 async_collectives=True)
    return {"async": min(asy) / 1e9, "sequential": min(seq) / 1e9,
            "stats": stats}


def bench_socket_coalesce(procs=4, maps=400, keys=16, window_us=500):
    """ISSUE 11 coalescing figure: ``maps`` tiny ``iallreduce_map``
    submissions (``keys`` int keys each) under the
    ``MP4J_COALESCE_USECS`` window vs the same stream with coalescing
    off (each map its own negotiation + tree walk). Fusion ships the
    whole backlog as ONE vocabulary sync + columnar frame train per
    negotiated batch, so the per-collective fixed cost (two tree walks
    of small pickled frames, their syscalls and scheduler wakeups)
    amortizes across the batch — measured ~2.5x keys/s at this config
    on the bench host. Frozen legs elsewhere pin async off per the
    shm/audit/sink precedent; this leg IS the async plane's figure."""
    from ytk_mp4j_tpu.operands import Operands
    from ytk_mp4j_tpu.operators import Operators

    def body(slave, r):
        ds = [{key + 1000 * i: np.float64((r + 1) * (key + 1))
               for key in range(keys)} for i in range(maps)]
        slave.barrier()
        t0 = time.perf_counter()
        for d in ds:
            slave.iallreduce_map(d, Operands.DOUBLE, Operators.SUM)
        slave.wait_all()
        return maps * keys / (time.perf_counter() - t0)

    prior = os.environ.get("MP4J_COALESCE_USECS")
    try:
        os.environ["MP4J_COALESCE_USECS"] = str(window_us)
        on, stats = _run_socket_job(procs, body, True, shm=False,
                                    audit="off", sink_dir="",
                                    async_collectives=True)
        os.environ["MP4J_COALESCE_USECS"] = "0"
        off, _ = _run_socket_job(procs, body, True, shm=False,
                                 audit="off", sink_dir="",
                                 async_collectives=True)
    finally:
        if prior is None:
            os.environ.pop("MP4J_COALESCE_USECS", None)
        else:
            os.environ["MP4J_COALESCE_USECS"] = prior
    return {"on": min(on), "off": min(off), "stats": stats}


def bench_socket_coalesce_array(procs=4, arrays=400, size=256,
                                window_us=500):
    """ISSUE 17 dense-coalescing figure: ``arrays`` tiny ``iallreduce``
    submissions (``size`` float32 elems each, tree-schedule payloads)
    under the ``MP4J_COALESCE_USECS`` window vs the same stream with
    the window off (each array its own negotiation + tree walk). The
    array twin of ``bench_socket_coalesce``: consecutive same-signature
    submissions fuse into ONE count-negotiated multi-exchange
    (``allreduce_array_multi``), so the per-collective fixed cost
    amortizes across the backlog — acceptance is >= 2x elems/s over
    the sequential ``i*`` stream on this host. Needs procs >= 3: the
    fused walk is pinned to the tree schedule and ``algo="auto"`` only
    selects tree at n >= 3 (at n=2 RHD degenerates to the optimal
    pairwise exchange)."""
    from ytk_mp4j_tpu.operands import Operands
    from ytk_mp4j_tpu.operators import Operators

    def body(slave, r):
        bufs = [np.full(size, float(r + 1) * (i + 1), np.float32)
                for i in range(arrays)]
        slave.barrier()
        t0 = time.perf_counter()
        for b in bufs:
            slave.iallreduce(b, Operands.FLOAT, Operators.SUM)
        slave.wait_all()
        return arrays * size / (time.perf_counter() - t0)

    prior = os.environ.get("MP4J_COALESCE_USECS")
    try:
        os.environ["MP4J_COALESCE_USECS"] = str(window_us)
        on, stats = _run_socket_job(procs, body, True, shm=False,
                                    audit="off", sink_dir="",
                                    async_collectives=True)
        os.environ["MP4J_COALESCE_USECS"] = "0"
        off, _ = _run_socket_job(procs, body, True, shm=False,
                                 audit="off", sink_dir="",
                                 async_collectives=True)
    finally:
        if prior is None:
            os.environ.pop("MP4J_COALESCE_USECS", None)
        else:
            os.environ["MP4J_COALESCE_USECS"] = prior
    return {"on": min(on), "off": min(off), "stats": stats}


def bench_trainer_overlap(procs=2, steps=30, grad_elems=65_536,
                          matmul_dim=192, matmul_reps=6):
    """ISSUE 17 trainer-overlap A/B: a trainer-shaped epoch loop —
    per step, a device-compute stand-in (BLAS matmuls, GIL released)
    plus a dense per-step gradient/statistics exchange through
    ``StepStatsExchanger`` — run with overlap ON (``iallreduce``
    posted, step k's wire rides the progression thread under step
    k+1's compute, ``drain()`` at the epoch boundary) vs OFF (today's
    blocking ``allreduce_array`` per step). Identical collectives in
    identical submit order; only the wait point moves.

    MULTI-CORE ONLY: ``len(os.sched_getaffinity(0))`` decides. On a
    1-core host (this bench rig) the wire and the compute time-share
    the same CPU, so overlap cannot create cycles — the leg records a
    ``skipped_1core`` marker INSTEAD of a bogus figure (the
    ``socket_async_overlap_gbs`` lesson, measured and documented in
    that leg's docstring: dense overlap lands BELOW sequential at 1
    core). When nproc > 1 the gate is >= 1.3x steps/s; a miss is
    reported in the ``gate`` field and the frozen ratio is bench-diff
    budgeted so it cannot silently regress between rounds."""
    nproc = len(os.sched_getaffinity(0))
    if nproc < 2:
        return {"skipped_1core": True, "nproc": nproc}

    from ytk_mp4j_tpu.models._base import StepStatsExchanger

    def make_body(overlap):
        def body(slave, r):
            rng = np.random.default_rng(r)
            a = rng.standard_normal((matmul_dim, matmul_dim),
                                    np.float32)
            grads = [np.full(grad_elems, float(r + 1) * (k + 1),
                             np.float64) for k in range(steps)]
            ex = StepStatsExchanger(slave, overlap=overlap)
            slave.barrier()
            t0 = time.perf_counter()
            for g in grads:
                ex.submit(g)
                # step k+1's independent compute: overlap mode drives
                # step k's wire under it, blocking mode already paid
                for _ in range(matmul_reps):
                    a = np.tanh(a @ a) + 0.1
            ex.drain()
            return steps / (time.perf_counter() - t0)
        return body

    blk, _ = _run_socket_job(procs, make_body(False), True, shm=False,
                             audit="off", sink_dir="",
                             async_collectives=True)
    ovl, stats = _run_socket_job(procs, make_body(True), True,
                                 shm=False, audit="off", sink_dir="",
                                 async_collectives=True)
    ratio = min(ovl) / min(blk)
    return {"overlap": min(ovl), "blocking": min(blk),
            "ratio": ratio, "nproc": nproc, "gate_min": 1.3,
            "gate": "ok" if ratio >= 1.3 else
                    f"MISS: {ratio:.2f}x < 1.3x dense-overlap gate",
            "stats": stats}


def bench_socket_tuner_act(procs=4, size=400_000, reps=6,
                           warmup_secs=3.0):
    """mp4j-tuner acceptance A/B (ISSUE 15): a compressed-operand
    allreduce stream, ``MP4J_TUNER=off`` vs ``act`` on the same host.

    The static policy zlib-compresses every frame (the operand says
    so); the tuner's probe/measure cycle observes that the link's
    plain payload rate beats the zlib-bound compressed rate by an
    order of magnitude on this loopback host and commits
    ``compress=False`` per link at a collective boundary. The act
    figure must be the measured net win bench-diff gates
    (``socket_tuner_act_gbs``); the ``tuner`` extra records the
    decisions the act leg actually converged to, so the win is
    attributable, not anecdotal. Both legs pay the same warmup wall
    time (the act leg needs ~SUSTAIN_WINDOWS decision windows to
    converge; the off leg idles the same period for thermal parity).
    All-TCP (``shm=False``): loopback TCP is this host's
    wire-vs-zlib contrast; the shm rings would only widen it."""
    from ytk_mp4j_tpu.operands import Operands
    from ytk_mp4j_tpu.operators import Operators

    comp = Operands.compressed(Operands.DOUBLE)

    def body(slave, r):
        rng = np.random.default_rng(5)
        arr = rng.integers(0, 3, size).astype(np.float64)
        # convergence warmup: decision windows fold on the heartbeat
        # cadence, so the act leg needs WALL time and boundaries (the
        # off leg runs the same loop — parity, and a stronger static
        # baseline via warm channels). The exit is AGREED through a
        # MIN allreduce: a wall-clock-local break would leave ranks a
        # collective apart and deadlock the schedule (R1's lesson)
        deadline = time.monotonic() + warmup_secs
        flag = np.zeros(1)
        while True:
            a = arr.copy()
            slave.allreduce_array(a, comp, Operators.SUM)
            flag[0] = 1.0 if time.monotonic() >= deadline else 0.0
            slave.allreduce_array(flag, Operands.DOUBLE,
                                  Operators.MIN)
            if flag[0] == 1.0:
                break
        slave.barrier()
        t0 = time.perf_counter()
        nbytes = 0
        for _ in range(reps):
            a = arr.copy()
            slave.allreduce_array(a, comp, Operators.SUM)
            nbytes += arr.nbytes
        rate = nbytes / (time.perf_counter() - t0)
        st = slave.tuner_status()
        return rate, st

    out = {}
    decisions = None
    prior = {k: os.environ.get(k)
             for k in ("MP4J_TUNER_WINDOW_SECS", "MP4J_HEARTBEAT_SECS")}
    os.environ["MP4J_TUNER_WINDOW_SECS"] = "0.3"
    os.environ["MP4J_HEARTBEAT_SECS"] = "0.1"
    try:
        for mode in ("off", "act"):
            rates_status, _ = _run_socket_job(
                procs, body, True, join_timeout=180.0, shm=False,
                audit="off", sink_dir="", tuner=mode,
                master_kwargs={"tuner": mode})
            out[mode] = min(rate for rate, _ in rates_status) / 1e9
            if mode == "act":
                decisions = {i: st for i, (_, st)
                             in enumerate(rates_status)}
    finally:
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    out["decisions"] = decisions
    return out


def bench_socket_recovery_latency(procs=4, reps=9, size=262_144):
    """ISSUE 5 acceptance workload: inject ONE connection reset into a
    ``reps``-iteration allreduce loop and report (a) the recovery
    latency — the faulted iteration's wall time over the healthy
    median, i.e. what one epoch-fenced abort/retry round costs end to
    end — and (b) the steady-state decomposition of the resilience
    layer, same loop, no faults:

    - ``failstop_gbs`` (``max_retries=0``): the EPOCH FENCE ALONE —
      fence polls, control thread, recovery wrapper and the
      (rank, epoch) peer handshake all stay active; only retry and
      its input-preservation snapshot are off. Measured
      indistinguishable from a snapshot-suppressed default run, i.e.
      the fence's steady-state cost is ~0 (it is a flag check).
    - ``default_gbs`` (``MP4J_MAX_RETRIES`` default): adds the
      input-preservation snapshot — ONE pooled memcpy pass of the
      payload per mutating collective, the irreducible price of
      re-runnable in-place merges (a retry needs the original bytes;
      staging the result instead costs the same pass at commit time,
      so the pass is conserved, not an implementation accident). On a
      real NIC that pass vanishes next to wire time; on THIS bench
      host the "wire" is loopback — itself memcpy through the kernel
      on one shared core — so the snapshot shows as a visible slice
      and ``failstop_gbs`` is the fence-only figure comparable with
      BENCH history.

    Returns ``(summary, stats)`` where stats is the FAULTED leg's
    merged snapshot — its nonzero ``retries``/``aborts_seen`` prove
    the fault actually fired (a silent no-op fault would report a
    flattering zero latency)."""
    from ytk_mp4j_tpu.operands import Operands
    from ytk_mp4j_tpu.operators import Operators

    fault_at = reps // 2 + 1    # collective ordinal of the faulted rep

    def body(slave, r):
        buf = np.ones(size, np.float32)
        times = []
        for _ in range(reps):
            # lockstep per iteration (outside the timed window):
            # recovery is per-collective, so the faulted call must not
            # find ranks a whole collective apart on a loaded host
            slave.barrier()
            t0 = time.perf_counter()
            slave.allreduce_array(buf, Operands.FLOAT, Operators.SUM)
            times.append(time.perf_counter() - t0)
        return times

    res, stats = _run_socket_job(
        procs, body, True, fault_plan=f"reset:rank=1:nth={fault_at}",
        dead_rank_secs=30.0, shm=False, audit="off", sink_dir="")
    # per iteration the slowest rank defines the collective's time
    per_iter = [max(res[r][k] for r in range(procs))
                for k in range(reps)]
    healthy = sorted(per_iter[:fault_at - 1] + per_iter[fault_at:])
    median = healthy[len(healthy) // 2]
    recovery_latency = per_iter[fault_at - 1] - median
    retries = sum(e.get("retries", 0) for e in stats.values())
    if retries < 1:
        raise RuntimeError(
            "recovery bench: the injected reset never fired "
            "(0 retries recorded) — latency figure would be bogus")

    def steady_gbs(**kw):
        r2, _ = _run_socket_job(procs, body, True, shm=False,
                                audit="off", sink_dir="", **kw)
        dt = max(sum(ts) for ts in r2)
        return size * 4 * reps / dt / 1e9

    summary = {
        "recovery_latency_ms": round(recovery_latency * 1e3, 3),
        "healthy_iter_ms": round(median * 1e3, 3),
        "retries": int(retries),
        "steady_state": {
            "default_gbs": round(steady_gbs(), 4),
            "failstop_gbs": round(steady_gbs(max_retries=0), 4),
        },
    }
    return summary, stats


def _run_elastic_job(procs, body, fault_plan, elastic, spare_body=None,
                     join_timeout=120.0, master_kwargs=None,
                     trigger=None, **slave_kwargs):
    """Master + ``procs`` worker PROCESSES under an elastic mode, plus
    one warm-spare process when ``spare_body`` is given (ISSUE 10).
    Workers that die to an injected kill report ``("killed", rank)``;
    workers released by a planned eviction (ISSUE 13) report
    ``("evicted", rank)``; the spare reports under its adopted rank.
    ``trigger(master)`` (ISSUE 13) runs on a daemon thread after the
    master starts — the planned-evict leg drives the actuation from
    it. Returns ``(results, killed_ranks)`` with results keyed by
    FINAL rank (evicted ranks count in ``killed`` — they left the
    roster either way)."""
    import multiprocessing as mp

    from ytk_mp4j_tpu.comm.master import Master
    from ytk_mp4j_tpu.comm.process_comm import ProcessCommSlave
    from ytk_mp4j_tpu.exceptions import Mp4jEvicted
    from ytk_mp4j_tpu.resilience.faults import FaultKill

    ctx = mp.get_context("fork")
    # frozen-leg pin (the shm/audit/sink/async precedent): the
    # replacement/shrink latency figures predate the health plane and
    # the autoscaler, and must not drift with MP4J_HEALTH or
    # MP4J_AUTOSCALE; the evict/grow legs opt back in via
    # master_kwargs
    mk = {"health": False, "autoscale": "off"}
    mk.update(master_kwargs or {})
    master = Master(procs, timeout=60.0, elastic=elastic,
                    spares=1 if spare_body is not None else 0,
                    adopt_secs=15.0, **mk).serve_in_thread()
    q = ctx.Queue()
    slave_kwargs.setdefault("health", False)

    def worker():
        try:
            slave = ProcessCommSlave(
                "127.0.0.1", master.port, timeout=60.0,
                fault_plan=fault_plan, elastic=elastic,
                dead_rank_secs=60.0, **slave_kwargs)
            start_rank = slave.rank
            try:
                res = body(slave, slave.rank)
            except FaultKill:
                q.put(("killed", start_rank, None))
                return
            except Mp4jEvicted:
                slave.close(0)
                q.put(("evicted", start_rank, None))
                return
            q.put(("ok", slave.rank, res))
            slave.close(0)
        except Exception as e:  # pragma: no cover
            q.put(("err", -1, repr(e)))

    def spare_worker():
        try:
            sp = ProcessCommSlave(
                "127.0.0.1", master.port, timeout=60.0, spare=True,
                elastic=elastic, dead_rank_secs=60.0, **slave_kwargs)
            res = spare_body(sp)
            q.put(("ok", sp.rank, res))
            sp.close(0)
        except Exception as e:  # pragma: no cover
            q.put(("err", -1, repr(e)))

    ps = [ctx.Process(target=worker, daemon=True)
          for _ in range(procs)]
    if spare_body is not None:
        ps.append(ctx.Process(target=spare_worker, daemon=True))
    for p in ps:
        p.start()
    if trigger is not None:
        threading.Thread(target=trigger, args=(master,),
                         daemon=True).start()
    expected = len(ps)
    results: dict[int, object] = {}
    killed: list[int] = []
    deadline = time.monotonic() + join_timeout
    got = 0
    while got < expected:
        try:
            kind, rank, payload = q.get(timeout=1.0)
        except pyqueue.Empty:
            dead = [p.exitcode for p in ps
                    if not p.is_alive() and p.exitcode not in (0, None)]
            if dead or time.monotonic() > deadline:
                for p in ps:
                    p.terminate()
                raise RuntimeError(
                    f"elastic benchmark stalled (exit codes {dead}, "
                    f"{got}/{expected} reported)")
            continue
        if kind == "err":
            for p in ps:
                p.terminate()
            raise RuntimeError(f"elastic benchmark worker: {payload}")
        if kind in ("killed", "evicted"):
            killed.append(rank)
        else:
            results[rank] = payload
        got += 1
    for p in ps:
        p.join(10.0)
    master.join(10.0)
    return results, killed


def _timed_elastic_loop(reps):
    """The shared per-iteration-timed allreduce loop of both elastic
    latency legs, plus the spare's resume half (skips the barrier of
    the iteration it resumes INTO — that generation completed before
    the kill could fire, see README 'Elastic membership'). The kill
    point lives ONLY in the caller's fault-plan string."""
    from ytk_mp4j_tpu.operands import Operands
    from ytk_mp4j_tpu.operators import Operators

    size = 262_144

    def body(slave, r):
        buf = np.ones(size, np.float32)
        times = []
        for _ in range(reps):
            slave.barrier()
            t0 = time.perf_counter()
            slave.allreduce_array(buf, Operands.FLOAT, Operators.SUM)
            times.append(time.perf_counter() - t0)
        return times

    def spare_body(sp):
        buf = np.ones(size, np.float32)
        times = []
        for k in range(sp.resume_seq + 1, reps + 1):
            if not (k == sp.resume_seq + 1
                    and sp.resume_barrier_gen > sp.resume_seq):
                sp.barrier()
            t0 = time.perf_counter()
            sp.allreduce_array(buf, Operands.FLOAT, Operators.SUM)
            times.append(time.perf_counter() - t0)
        return times

    return body, spare_body


def bench_socket_replacement_latency(procs=4, reps=9):
    """ISSUE 10 acceptance workload (replace): ``kill -9`` one rank
    mid-loop with a warm spare registered and measure kill -> adopted
    spare -> first completed collective, as the faulted iteration's
    wall time over the healthy median on the SURVIVORS (the spare's
    first collective completes inside that same window — survivors
    cannot finish the retry without its contribution). Asserts the
    replacement actually happened (a silently-fatal run would report
    garbage)."""
    fault_at = reps // 2 + 1
    body, spare_body = _timed_elastic_loop(reps)
    results, killed = _run_elastic_job(
        procs, body, f"kill:rank=1:nth={fault_at}", "replace",
        spare_body=spare_body, shm=False, audit="off", sink_dir="")
    if killed != [1] or len(results) != procs:
        raise RuntimeError(
            f"replacement bench: expected rank 1 killed + {procs} "
            f"finishers, got killed={killed} results={sorted(results)}")
    survivors = [r for r in range(procs) if r != 1]
    per_iter = [max(results[r][k] for r in survivors)
                for k in range(reps)]
    healthy = sorted(per_iter[:fault_at - 1] + per_iter[fault_at:])
    median = healthy[len(healthy) // 2]
    return {
        "replacement_latency_ms": round(
            (per_iter[fault_at - 1] - median) * 1e3, 3),
        "healthy_iter_ms": round(median * 1e3, 3),
        "spare_iters": len(results[1]),
    }


def bench_socket_planned_evict_ms(procs=4, reps=11):
    """ISSUE 13 actuation workload: mid-loop, the master is asked to
    PLANNED-EVICT live rank 1 (the autoscaler's actuation API,
    detection excluded — detection latency is a pure function of
    MP4J_HEALTH_DOMINATOR_ORDINALS x iteration time, a config choice,
    not a protocol cost). Measured: the boundary fence + abort round
    + manifest + spare adoption + first post-adoption collective, as
    the worst faulted iteration's wall time over the healthy median
    on the survivors. Asserts the eviction actually landed."""
    body, spare_body = _timed_elastic_loop(reps)

    def trigger(master):
        # fire as soon as the request is accepted (rendezvous seated,
        # spare pooled): the boundary fence quiesces at whichever
        # iteration comes next — the figure is the actuation cost,
        # independent of WHICH iteration pays it (argmax below)
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if master.request_planned_evict(1, "bench actuation"):
                return
            time.sleep(0.002)

    results, killed = _run_elastic_job(
        procs, body, None, "replace", spare_body=spare_body,
        trigger=trigger, shm=False, audit="off", sink_dir="")
    if killed != [1] or len(results) != procs:
        raise RuntimeError(
            f"planned-evict bench: expected rank 1 evicted + {procs} "
            f"finishers, got evicted={killed} results={sorted(results)}")
    survivors = [r for r in range(procs) if r != 1]
    per_iter = [max(results[r][k] for r in survivors)
                for k in range(reps)]
    ordered = sorted(per_iter)
    median = ordered[len(ordered) // 2]
    worst = max(per_iter)
    return {
        "planned_evict_ms": round((worst - median) * 1e3, 3),
        "healthy_iter_ms": round(median * 1e3, 3),
        "spare_iters": len(results[1]),
    }


def bench_socket_grow_latency_ms(procs=2, reps=9):
    """ISSUE 13 grow workload: mid-loop every rank hits
    ``resize_point()`` with one registered spare and
    MP4J_ELASTIC=grow + MP4J_AUTOSCALE=act — the roster EXPANDS to
    procs+1 and the loop continues at the new n. Measured: the
    resize_point wall time itself (fence-free quiesce + adoption +
    roster release), max over the pre-existing ranks."""
    from ytk_mp4j_tpu.operands import Operands
    from ytk_mp4j_tpu.operators import Operators

    size = 262_144
    grow_at = reps // 2 + 1

    def body(slave, r):
        buf = np.ones(size, np.float32)
        out = {"iters": [], "resize_ms": None, "grown_n": None}
        for k in range(reps):
            if k == grow_at:
                t0 = time.perf_counter()
                roster = slave.resize_point()
                out["resize_ms"] = (time.perf_counter() - t0) * 1e3
                out["grown_n"] = len(roster)
            slave.barrier()
            t0 = time.perf_counter()
            slave.allreduce_array(buf, Operands.FLOAT, Operators.SUM)
            out["iters"].append(time.perf_counter() - t0)
        return out

    def spare_body(sp):
        buf = np.ones(size, np.float32)
        for k in range(sp.resume_seq, reps):
            sp.barrier()
            sp.allreduce_array(buf, Operands.FLOAT, Operators.SUM)
        return {"iters": [], "resize_ms": None,
                "grown_n": sp.slave_num}

    results, killed = _run_elastic_job(
        procs, body, None, "grow", spare_body=spare_body,
        master_kwargs={"autoscale": "act", "autoscale_cooldown": 0.0},
        shm=False, audit="off", sink_dir="")
    if killed or len(results) != procs + 1:
        raise RuntimeError(
            f"grow bench: expected {procs + 1} finishers, got "
            f"killed={killed} results={sorted(results)}")
    grown = [results[r]["grown_n"] for r in range(procs)]
    if any(g != procs + 1 for g in grown):
        raise RuntimeError(f"grow bench: roster did not grow: {grown}")
    return {
        "grow_latency_ms": round(
            max(results[r]["resize_ms"] for r in range(procs)), 3),
        "grown_n": procs + 1,
    }


def bench_socket_shrink_latency(procs=4, reps=9):
    """ISSUE 10 acceptance workload (shrink): same kill, no spare —
    survivors renumber to n-1 and continue; the figure is the faulted
    iteration's wall time over the healthy median."""
    fault_at = reps // 2 + 1
    body, _ = _timed_elastic_loop(reps)
    results, killed = _run_elastic_job(
        procs, body, f"kill:rank=1:nth={fault_at}", "shrink",
        shm=False, audit="off", sink_dir="")
    if killed != [1] or len(results) != procs - 1:
        raise RuntimeError(
            f"shrink bench: expected rank 1 killed + {procs - 1} "
            f"finishers, got killed={killed} results={sorted(results)}")
    per_iter = [max(results[r][k] for r in results)
                for k in range(reps)]
    healthy = sorted(per_iter[:fault_at - 1] + per_iter[fault_at:])
    median = healthy[len(healthy) // 2]
    return {
        "shrink_latency_ms": round(
            (per_iter[fault_at - 1] - median) * 1e3, 3),
        "healthy_iter_ms": round(median * 1e3, 3),
        "final_ranks": len(results),
    }


def bench_audit_overhead(rounds=2):
    """ISSUE 8 acceptance workload: interleaved A/B of the audit plane
    on the isolated headline collective leg — ``off`` vs ``digest``
    (the production default) vs ``verify`` (the diagnostic mode),
    best-of-``rounds`` per mode with modes interleaved per round so
    system-load drift spreads evenly (the ``metrics_overhead``
    precedent).

    Cost anatomy, measured on the bench host: ``digest`` adds 2
    payload-hash passes per rank per collective (block-xor at 21-35
    GB/s, obs/audit.py); ``verify`` adds zlib.crc32 folds over every
    wire byte (~1 GB/s — the diagnostic mode you arm when you need
    cross-rank proof, not a default). 1-CORE CAVEAT (the PR 5/7
    pattern): this host serializes all 4 ranks' digest passes onto the
    one core the collective also runs on, so the printed overhead is
    ~4x what a host with a core per rank pays — the per-rank digest
    cost on this leg is 2 passes x payload/24GB/s ~= 2% of the wire
    time, within the <=3% budget; the printed figure is that times the
    rank count sharing the core."""
    rates = {m: 0.0 for m in ("off", "digest", "verify")}
    for _ in range(rounds):
        for mode in rates:
            gbs, _ = bench_socket_collective(native_transport=True,
                                             audit=mode)
            rates[mode] = max(rates[mode], gbs)
    off = rates["off"]
    return {
        "socket_collective_gbs_audit_off": round(off, 4),
        "socket_collective_gbs_audit_digest": round(rates["digest"], 4),
        "socket_collective_gbs_audit_verify": round(rates["verify"], 4),
        "digest_overhead_pct": round((off - rates["digest"]) / off * 100,
                                     2) if off else None,
        "verify_overhead_pct": round((off - rates["verify"]) / off * 100,
                                     2) if off else None,
        "core_sharing_note": (
            "1-core host: 4 ranks' digest passes serialize onto the "
            "collective's core, overstating the per-rank tax ~4x "
            "(see bench_audit_overhead docstring)"),
    }


def bench_lint_runtime(reps=3):
    """ISSUE 14 + 16: mp4j-lint's own runtime over this repo — the
    per-file pass, the v2 two-pass run (per-file rules + the R19-R21
    lock-model pass) and the v3 run (adds the R23 lockset / R24-R25
    resource whole-program passes). The full mode rides the tier-1
    gate on every CI run, so its cost is tracked like any other
    figure; budgets: the full run stays <= 2x the per-file pass, and
    v3 stays <= 1.5x v2 (the race/resource models reuse v2's parsed
    index, call graph and lock summaries — their marginal cost is the
    fixpoint over already-built structures, not a re-parse). Engine
    caches are cleared between timed legs so every leg pays the full
    parse it would pay on a cold CI run."""
    import time as _time

    from ytk_mp4j_tpu.analysis.engine import Engine, ProgramRule
    from ytk_mp4j_tpu.analysis.rules import get_rules

    pkg = os.path.dirname(os.path.abspath(
        __import__("ytk_mp4j_tpu").__file__))
    v2_ids = ("R19", "R20", "R21")
    per_file = inf = float("inf")
    full = v2 = inf
    for _ in range(reps):
        rules = [r for r in get_rules()
                 if not isinstance(r, ProgramRule)]
        eng = Engine(rules=rules)
        eng.clear_caches()
        t0 = _time.perf_counter()
        eng.lint_paths([pkg])
        per_file = min(per_file, _time.perf_counter() - t0)
        rules = [r for r in get_rules()
                 if not isinstance(r, ProgramRule)
                 or r.rule_id in v2_ids]
        eng = Engine(rules=rules)
        eng.clear_caches()
        t0 = _time.perf_counter()
        eng.lint_paths([pkg])
        v2 = min(v2, _time.perf_counter() - t0)
        eng = Engine()
        eng.clear_caches()
        t0 = _time.perf_counter()
        eng.lint_paths([pkg])
        full = min(full, _time.perf_counter() - t0)
    return {
        "lint_runtime_secs": round(full, 3),
        "lint_perfile_secs": round(per_file, 3),
        "lint_wholeprogram_ratio": round(full / per_file, 3),
        "lint_v2_secs": round(v2, 3),
        "lint_v3_over_v2_ratio": round(full / v2, 3),
    }


def bench_sink_overhead(rounds=2):
    """ISSUE 9 acceptance workload: interleaved A/B of the durable
    telemetry sink on the isolated headline collective leg — sink off
    vs armed (segments under a throwaway dir, default flush cadence),
    best-of-``rounds`` per mode with modes interleaved per round so
    system-load drift spreads evenly (the ``metrics_overhead`` /
    ``bench_audit_overhead`` precedent). Budget: <= 3%.

    Cost anatomy: the collective hot path pays NOTHING new (the ring
    appends it drains were already booked by ISSUES 3/6/8); the sink
    adds one background thread per rank that wakes each flush
    interval, diffs snapshots and issues one unbuffered write —
    amortized over every collective in the interval. On this shared
    1-core host the drain thread time-shares the collective's core,
    so the printed delta carries the usual ~10% run-to-run noise
    floor; the per-rank steady-state cost is the snapshot diff
    (~100 us) once per second."""
    import shutil
    import tempfile

    rates = {m: 0.0 for m in ("off", "on")}
    for _ in range(rounds):
        for mode in rates:
            d = tempfile.mkdtemp(prefix="mp4j_sink_bench_") \
                if mode == "on" else ""
            try:
                gbs, _ = bench_socket_collective(native_transport=True,
                                                 sink_dir=d)
                rates[mode] = max(rates[mode], gbs)
            finally:
                if d:
                    shutil.rmtree(d, ignore_errors=True)
    off = rates["off"]
    return {
        "socket_collective_gbs_sink_off": round(off, 4),
        "socket_collective_gbs_sink_on": round(rates["on"], 4),
        "sink_overhead_pct": round((off - rates["on"]) / off * 100, 2)
        if off else None,
    }


def bench_health_overhead(rounds=2):
    """ISSUE 12 acceptance workload: interleaved A/B of the streaming
    health plane on the isolated headline collective leg — health off
    (the frozen-leg pin) vs armed on BOTH sides (slaves fold + ship
    per-ordinal span cells on each heartbeat; the master runs the
    detector set and online dominator attribution per fold),
    best-of-``rounds`` per mode with modes interleaved per round so
    system-load drift spreads evenly (the ``metrics_overhead`` /
    ``bench_audit_overhead`` / ``bench_sink_overhead`` precedent).
    Budget: <= 3%.

    Cost anatomy: the collective hot path pays NOTHING new (the span
    appends the folder reads were already booked by ISSUE 3); the
    slave side adds one O(delta) span-ring fold per heartbeat
    (~0.5 s), the master side a handful of dict updates plus one
    ``critpath.attribute`` call per completed ordinal — all on
    control-plane threads. On this shared 1-core host those threads
    time-share the collective's core, so the printed delta carries
    the usual ~10% run-to-run noise floor."""
    rates = {m: 0.0 for m in ("off", "on")}
    for _ in range(rounds):
        for mode in rates:
            gbs, _ = bench_socket_collective(native_transport=True,
                                             health=(mode == "on"))
            rates[mode] = max(rates[mode], gbs)
    off = rates["off"]
    return {
        "socket_collective_gbs_health_off": round(off, 4),
        "socket_collective_gbs_health_on": round(rates["on"], 4),
        "health_overhead_pct": round((off - rates["on"]) / off * 100, 2)
        if off else None,
    }


def bench_fleet_scrape(procs=4, sweeps=60, size=65_536):
    """ISSUE 18 observability figure: one full ``FleetPoller`` sweep
    (fetch ``/metrics.json`` + ``/health.json``, fold the job summary,
    rebuild the fleet model, run contention detection) against a LIVE
    ``procs``-rank job running an allreduce loop in this process —
    p50/p99 sweep latency plus the scrape loop's CPU share at the
    default poll cadence. The poller rides HTTP out of band, so no
    frozen socket leg arms it; this leg is the fleet plane's own
    figure, gated via ``fleet_scrape_p99_ms`` so a fold/detector
    regression (an accidental O(n^2) pass, an unbounded fetch) cannot
    creep in silently.

    CPU share is ``time.thread_time`` over the sweep loop (the fetches
    block off-GIL, so the thread clock charges only the poller's own
    fold work) divided by the default poll period — what one idle-free
    sweep costs per cadence slot. The p99 on this shared 1-core host
    carries the worker ranks' GIL interference; that contention IS the
    deployment reality for an in-host scraper, so it stays in the
    figure. Worker exit is agreed through a MIN allreduce (the R1
    lesson: a rank-local break leaves ranks a collective apart)."""
    from ytk_mp4j_tpu.comm.master import Master
    from ytk_mp4j_tpu.comm.process_comm import ProcessCommSlave
    from ytk_mp4j_tpu.obs.fleet import FleetPoller
    from ytk_mp4j_tpu.operands import Operands
    from ytk_mp4j_tpu.operators import Operators
    from ytk_mp4j_tpu.utils import tuning

    master = Master(procs, timeout=60.0, metrics_port=0, elastic="off",
                    health=False, autoscale="off",
                    tuner="off").serve_in_thread()
    stop = threading.Event()
    errs = []

    def worker():
        try:
            slave = ProcessCommSlave(
                "127.0.0.1", master.port, timeout=60.0, elastic="off",
                async_collectives=False, health=False, tuner="off",
                shm=False, audit="off", sink_dir="")
            buf = np.ones(size, np.float32)
            flag = np.zeros(1)
            while True:
                slave.allreduce_array(buf, Operands.FLOAT,
                                      Operators.SUM)
                flag[0] = 1.0 if stop.is_set() else 0.0
                slave.allreduce_array(flag, Operands.DOUBLE,
                                      Operators.MIN)
                if flag[0] == 1.0:
                    break
            slave.close(0)
        except Exception as e:  # pragma: no cover
            errs.append(repr(e))

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(procs)]
    for t in threads:
        t.start()
    url = f"http://127.0.0.1:{master.metrics_port}"
    poller = FleetPoller([url], poll_secs=0.05, stale_secs=30.0)
    try:
        poller.poll_once()      # warmup: connection + lazy-path setup
        lat = []
        w0 = time.perf_counter()
        c0 = time.thread_time()
        for _ in range(sweeps):
            t0 = time.perf_counter()
            poller.poll_once()
            lat.append(time.perf_counter() - t0)
        cpu = time.thread_time() - c0
        wall = time.perf_counter() - w0
        st = poller.model()["jobs"][url]
    finally:
        stop.set()
        for t in threads:
            t.join(30.0)
        master.join(10.0)
    if errs:
        raise RuntimeError(f"fleet scrape bench worker failed: {errs}")
    if st["state"] != "LIVE" or st["summary"] is None:
        raise RuntimeError(
            f"fleet scrape bench: job never scraped LIVE "
            f"(state={st['state']}) — latency figures would be bogus")
    lat.sort()
    p50 = lat[len(lat) // 2]
    p99 = lat[min(len(lat) - 1, int(len(lat) * 0.99))]
    return {
        "fleet_scrape_p50_ms": round(p50 * 1e3, 3),
        "fleet_scrape_p99_ms": round(p99 * 1e3, 3),
        "fleet_scrape_cpu_ms_per_sweep": round(cpu / sweeps * 1e3, 3),
        "fleet_scrape_cpu_share_at_default_cadence": round(
            cpu / sweeps / tuning.fleet_poll_secs(), 4),
        "sweeps": sweeps,
        "wall_secs": round(wall, 3),
        "ranks_reporting": st["summary"]["ranks_reporting"],
    }


def _serve_fm_servable(n_features=4096, k=8, seed=7):
    """A synthesized (numpy-only) FM servable for the serve legs: the
    serve plane never trains, it pulls rows — random parameters
    exercise exactly the same dispatch/caching/scoring paths as a
    trained table, without touching the device runtime (the chaos leg
    forks, so nothing here may initialize a backend)."""
    from ytk_mp4j_tpu.models.fm import FMConfig, FMServable

    rng = np.random.default_rng(seed)
    cfg = FMConfig(n_features=n_features, k=k, model="fm")
    w0 = np.float32(0.1)
    w = rng.standard_normal(n_features).astype(np.float32)
    V = (0.05 * rng.standard_normal((n_features, k))).astype(
        np.float32)
    return FMServable((w0, w, V), cfg)


def _serve_gbdt_servable(n_features=16, n_bins=16, depth=4,
                         n_trees=32, seed=5):
    """A synthesized (numpy-only) GBDT servable: random level-ordered
    trees in the trainer's component layout — the reduce dispatch
    cares about routing + margin reduction, not about the split
    quality, and synthesizing keeps the fork-safety of this block."""
    from ytk_mp4j_tpu.models.gbdt import GBDTConfig, GBDTServable

    rng = np.random.default_rng(seed)
    cfg = GBDTConfig(n_features=n_features, n_bins=n_bins,
                     depth=depth, n_trees=n_trees, loss="logistic",
                     hist_mode="flat")
    n_internal = 2 ** depth - 1
    trees = []
    for _ in range(n_trees):
        trees.append((
            rng.integers(0, n_features, n_internal).astype(np.int32),
            rng.integers(1, n_bins - 1, n_internal).astype(np.int32),
            np.zeros(n_internal, np.int32),
            (0.1 * rng.standard_normal(2 ** depth)).astype(
                np.float32)))
    return GBDTServable(trees, cfg)


def _serve_fm_requests(n_reqs, n_features, nnz=16, seed=11):
    rng = np.random.default_rng(seed)
    return [(rng.integers(0, n_features, nnz).astype(np.int64),
             np.zeros(nnz, np.int32),
             rng.standard_normal(nnz).astype(np.float32))
            for _ in range(n_reqs)]


def _serve_threads_job(procs, servable, frontend_body, max_batch,
                       deadline_ms=2.0, cache_rows=0):
    """One live serve job on threads (master + ``procs`` slave
    threads, no fork — the bench_fleet_scrape harness shape): the
    rank-0 thread builds the :class:`ServeFrontend` and runs
    ``frontend_body(fe, slave)``; every other rank answers rounds in
    :func:`serve_worker` until the frontend's STOP. Returns the
    frontend body's result."""
    from ytk_mp4j_tpu.comm.master import Master
    from ytk_mp4j_tpu.comm.process_comm import ProcessCommSlave
    from ytk_mp4j_tpu.serve import ServeFrontend, serve_worker

    master = Master(procs, timeout=60.0, elastic="off", health=False,
                    autoscale="off", tuner="off").serve_in_thread()
    out = {}
    errs = []

    def worker():
        try:
            slave = ProcessCommSlave(
                "127.0.0.1", master.port, timeout=60.0, elastic="off",
                async_collectives=False, health=False, tuner="off",
                shm=False, audit="off", sink_dir="")
            if slave.rank == 0:
                fe = ServeFrontend(slave, servable,
                                   deadline_ms=deadline_ms,
                                   max_batch=max_batch,
                                   cache_rows=cache_rows)
                try:
                    out["result"] = frontend_body(fe, slave)
                finally:
                    fe.close()
            else:
                serve_worker(slave, servable, max_batch=max_batch)
            slave.close(0)
        except Exception as e:  # pragma: no cover
            errs.append(repr(e))

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(procs)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120.0)
    master.join(10.0)
    if errs:
        raise RuntimeError(f"serve bench job failed: {errs}")
    if any(t.is_alive() for t in threads) or "result" not in out:
        raise RuntimeError("serve bench job hung")
    return out["result"]


def bench_serve_latency_qps(procs=4, reqs=512):
    """ISSUE 19 acceptance workload: the micro-batching A/B. Three
    full serve jobs over the same synthesized FM servable and the
    same request stream, cache OFF for the first two so every batch
    pays the pull round (the amortization is the figure, not the
    cache):

    - **batched** (``max_batch=32``, open loop): per-request latency
      (enqueue -> resolve, the batcher's own ``on_latency`` hook) is
      the p50/p99 figure; QPS is requests over wall.
    - **unbatched** (``max_batch=1``, same open loop): one pull round
      per REQUEST — the latency-optimal, throughput-terrible corner
      the batcher exists to escape. The batched/unbatched QPS ratio
      is ``serve_speedup`` (acceptance: >= 3x at bit-exact results —
      bitwise equality itself is tier-1's job, tests/test_serve.py).
    - **warm cache** (``max_batch=32``, cache sized to the table):
      pass 1 fills, pass 2 replays the stream — the hit-rate and the
      zero-collective warm QPS figure.
    """
    servable = _serve_fm_servable()
    requests = _serve_fm_requests(reqs, servable.n_rows)

    def open_loop(fe, _slave):
        # bounded in-flight window: deep enough to keep full batches
        # forming, shallow enough that the latency series measures
        # the serve plane, not the submitter's own queue
        window = 64
        lats = []
        orig = fe._batcher._on_latency
        fe._batcher._on_latency = \
            lambda s: (lats.append(s), orig(s))
        t0 = time.perf_counter()
        futs = collections.deque()
        for r in requests:
            futs.append(fe.submit(r))
            if len(futs) >= window:
                futs.popleft().wait(120.0)
        while futs:
            futs.popleft().wait(120.0)
        wall = time.perf_counter() - t0
        return {"wall": wall, "lats": lats,
                "batches": fe._batcher.batches}

    def warm_loop(fe, _slave):
        for f in [fe.submit(r) for r in requests]:
            f.wait(120.0)
        cold = fe.cache_stats()
        t0 = time.perf_counter()
        for f in [fe.submit(r) for r in requests]:
            f.wait(120.0)
        wall = time.perf_counter() - t0
        warm = fe.cache_stats()
        return {"wall": wall, "cold": cold, "warm": warm}

    batched = _serve_threads_job(procs, servable, open_loop,
                                 max_batch=32)
    unbatched = _serve_threads_job(procs, servable, open_loop,
                                   max_batch=1)
    cached = _serve_threads_job(procs, servable, warm_loop,
                                max_batch=32,
                                cache_rows=servable.n_rows)
    lat = sorted(batched["lats"])
    if len(lat) != reqs:
        raise RuntimeError(
            f"serve bench: {len(lat)} latencies for {reqs} requests")
    qps_b = reqs / batched["wall"]
    qps_u = reqs / unbatched["wall"]
    speedup = qps_b / qps_u
    if speedup < 1.5:
        # the batched plane not clearly beating one-round-per-request
        # means the amortization is structurally broken (an extra
        # collective crept into the batch path), not host noise
        raise RuntimeError(
            f"serve bench: batched {qps_b:.0f} QPS vs unbatched "
            f"{qps_u:.0f} QPS (x{speedup:.2f}) — batching is not "
            "amortizing the pull round")
    d = {k: cached["warm"][k] - cached["cold"][k]
         for k in ("hits", "misses")}
    warm_lookups = d["hits"] + d["misses"]
    return {
        "serve_batched_qps": round(qps_b, 1),
        "serve_unbatched_qps": round(qps_u, 1),
        "serve_speedup": round(speedup, 2),
        "serve_p50_ms": round(lat[len(lat) // 2] * 1e3, 3),
        "serve_p99_ms": round(
            lat[min(len(lat) - 1, int(len(lat) * 0.99))] * 1e3, 3),
        "serve_batches": batched["batches"],
        "serve_warm_qps": round(reqs / cached["wall"], 1),
        "serve_warm_hit_rate": round(
            d["hits"] / warm_lookups, 4) if warm_lookups else 1.0,
        "serve_cold_hit_rate": round(
            cached["cold"]["hit_rate"], 4),
        "reqs": reqs,
        "procs": procs,
    }


def bench_serve_chaos(procs=3, reqs=48):
    """ISSUE 19 chaos leg: kill a serving rank mid-stream with a warm
    spare registered (the PR 10 replace machinery) and measure the
    blip a CALLER sees. GBDT reduce dispatch — every round is one
    fixed-shape allreduce, so the adopted spare just joins the next
    round and the batch the dead rank could not score is DELIVERED
    degraded (bitmap gap), never hung. ``max_batch=1`` so every
    request dispatches immediately ("full") and the per-request
    latency series brackets the recovery window exactly; the p99 over
    the stream IS the blip."""
    servable = _serve_gbdt_servable()
    rng = np.random.default_rng(3)
    requests = [rng.integers(0, 16, 16).astype(np.int64)
                for _ in range(reqs)]

    def body(slave, _r):
        from ytk_mp4j_tpu.serve import ServeFrontend, serve_worker
        if slave.rank == 0:
            fe = ServeFrontend(slave, servable, deadline_ms=5.0,
                               max_batch=1)
            lats = []
            for req in requests:
                t0 = time.perf_counter()
                fe.predict(req, timeout=60.0)
                lats.append(time.perf_counter() - t0)
            degraded = fe.degraded_batches
            fe.close()
            return {"lats": lats, "degraded": degraded}
        return serve_worker(slave, servable, max_batch=1)

    def spare_body(sp):
        from ytk_mp4j_tpu.serve import serve_worker
        return serve_worker(sp, servable, max_batch=1)

    # rank 1 answers ~2 serve rounds per request (announce + flush):
    # nth=reqs lands the kill mid-stream
    results, killed = _run_elastic_job(
        procs, body, f"kill:rank=1:nth={reqs}", "replace",
        spare_body=spare_body, shm=False, audit="off", sink_dir="")
    if killed != [1] or len(results) != procs:
        raise RuntimeError(
            f"serve chaos bench: expected rank 1 killed + {procs} "
            f"finishers, got killed={killed} "
            f"results={sorted(results)}")
    fe_out = results[0]
    spare_out = results[1]        # the spare reports under rank 1
    if spare_out.get("rounds", 0) < 1:
        raise RuntimeError(
            "serve chaos bench: adopted spare answered no serve "
            "rounds — the recovery never reached the serve plane")
    lats = fe_out["lats"]
    if len(lats) != reqs:
        raise RuntimeError(
            f"serve chaos bench: frontend delivered {len(lats)} of "
            f"{reqs} predictions")
    s = sorted(lats)
    median = s[len(s) // 2]
    return {
        "serve_chaos_p99_ms": round(
            s[min(len(s) - 1, int(len(s) * 0.99))] * 1e3, 3),
        "serve_chaos_healthy_p50_ms": round(median * 1e3, 3),
        "serve_chaos_blip_ms": round((max(lats) - median) * 1e3, 3),
        "serve_chaos_degraded_batches": fe_out["degraded"],
        "serve_chaos_spare_rounds": spare_out["rounds"],
        "reqs": reqs,
        "procs": procs,
    }


def bench_ffm_tpu(n=8192, n_features=100_000, n_fields=8, k=8,
                  max_nnz=8, steps=10):
    """FFM sparse embedding-gradient allreduce workload (BASELINE.md
    configs[4], Criteo-shaped synthetic minibatch): steps/sec of the
    full jitted sparse train step (score + grads + device-native sparse
    allreduce + update) on the available chip(s)."""
    import jax
    from ytk_mp4j_tpu.models.fm import FMConfig, FMTrainer

    rng = np.random.default_rng(3)
    feats = rng.integers(0, n_features, (n, max_nnz)).astype(np.int32)
    fields = rng.integers(0, n_fields, (n, max_nnz)).astype(np.int32)
    vals = np.ones((n, max_nnz), np.float32)
    y = (rng.random(n) > 0.5).astype(np.float32)
    cfg = FMConfig(model="ffm", n_features=n_features, n_fields=n_fields,
                   k=k, max_nnz=max_nnz, learning_rate=0.05)
    tr = FMTrainer(cfg, sparse_grads=True)
    params, _ = tr.fit(feats, fields, vals, y, n_steps=1)  # builds _step
    sharded = tr.shard_data(feats, fields, vals, y)
    step, flops = _aot_compile(tr._step, params, *sharded)
    # warm with the SAME arrays the timed loop uses — a fresh
    # shard_data product can trigger a silent recompile that would
    # otherwise land inside the timed region (measured: 6.9 s)
    params, loss = step(params, *sharded)
    np.asarray(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, loss = step(params, *sharded)
    np.asarray(loss)
    dt = (time.perf_counter() - t0) / steps
    # same per-chip normalization as bench_tpu (cost_analysis flops are
    # whole-program — verified on a 4-device mesh; both steps are SPMD
    # over all devices)
    n_chips = jax.device_count()
    return 1.0 / dt, None if flops is None else flops / dt / n_chips


def bench_ffm_stream(chunks=6, rows=8192, max_in_flight=2):
    """configs[4] ingestion: rows/sec through ``fit_stream`` — chunk
    staging + padding + one sparse FFM step per chunk (the out-of-core
    path a Criteo-scale run must ride; chunk synthesis stands in for
    the file reader). ``max_in_flight=0`` serializes host staging with
    device compute (the round-4 behavior) — the A/B denominator for
    the double-buffering win."""
    from ytk_mp4j_tpu.models.fm import FMConfig, FMTrainer

    rng = np.random.default_rng(3)
    cfg = FMConfig(model="ffm", n_features=100_000, n_fields=8, k=8,
                   max_nnz=8, learning_rate=0.05)
    tr = FMTrainer(cfg, sparse_grads=True)

    def gen(n):
        for _ in range(n):
            feats = rng.integers(0, cfg.n_features,
                                 (rows, 8)).astype(np.int32)
            fields = rng.integers(0, 8, (rows, 8)).astype(np.int32)
            vals = np.ones((rows, 8), np.float32)
            y = (rng.random(rows) > 0.5).astype(np.float32)
            yield feats, fields, vals, y

    params, _ = tr.fit_stream(gen(1), batch_rows=rows)  # compile once
    t0 = time.perf_counter()
    params, _ = tr.fit_stream(gen(chunks), params=params,
                              batch_rows=rows,
                              max_in_flight=max_in_flight)
    return chunks * rows / (time.perf_counter() - t0)


def _make_ffm_lines(rows, n_features=100_000, n_fields=8, max_nnz=8,
                    seed=3):
    rng = np.random.default_rng(seed)
    feats = rng.integers(0, n_features, (rows, max_nnz))
    vals = rng.random((rows, max_nnz))
    y = (rng.random(rows) > 0.5).astype(np.int32)
    return [
        f"{y[i]} " + " ".join(
            f"{j % n_fields}:{feats[i, j]}:{vals[i, j]:.4f}"
            for j in range(max_nnz))
        for i in range(rows)
    ]


def bench_libsvm_reader(rows=100_000, chunk_rows=8192):
    """Reader alone: rows/sec through ``read_libsvm`` (the native
    csrc/mp4j_parse.cpp scanner) on Criteo-shaped libffm text held in
    memory — no training, no device."""
    from ytk_mp4j_tpu.utils.libsvm import read_libsvm

    lines = _make_ffm_lines(rows)
    t0 = time.perf_counter()
    got = sum(c[3].size
              for c in read_libsvm(iter(lines), chunk_rows=chunk_rows,
                                   max_nnz=8))
    assert got == rows
    return rows / (time.perf_counter() - t0)


def bench_ffm_stream_text(chunks=6, rows=8192, max_in_flight=2):
    """configs[4] END-TO-END: libffm TEXT -> native chunk parse ->
    pad/stage -> double-buffered sparse FFM steps; rows/sec with the
    reader INCLUDED (the figure round 4's bench excluded)."""
    from ytk_mp4j_tpu.models.fm import FMConfig, FMTrainer
    from ytk_mp4j_tpu.utils.libsvm import read_libsvm

    cfg = FMConfig(model="ffm", n_features=100_000, n_fields=8, k=8,
                   max_nnz=8, learning_rate=0.05)
    tr = FMTrainer(cfg, sparse_grads=True)
    lines = _make_ffm_lines(chunks * rows)
    params, _ = tr.fit_stream(            # compile once
        read_libsvm(iter(lines[:rows]), chunk_rows=rows, max_nnz=8),
        batch_rows=rows)
    t0 = time.perf_counter()
    params, _ = tr.fit_stream(
        read_libsvm(iter(lines), chunk_rows=rows, max_nnz=8),
        params=params, batch_rows=rows, max_in_flight=max_in_flight)
    return chunks * rows / (time.perf_counter() - t0)


def bench_device_map(keys=50_000, reps=5):
    """configs[2] on the DEVICE path: merged keys/sec for an int-keyed
    map allreduce on the default backend (n=1 driver, union == map —
    the host encode/decode + one device round-trip per call is the
    measured quantity; the union merge itself rides the device at any
    n). The full union-size A/B vs the socket loop is in BASELINE.md;
    this extra pins the headline size every round."""
    from ytk_mp4j_tpu.comm.tpu_comm import TpuCommCluster
    from ytk_mp4j_tpu.operands import Operands
    from ytk_mp4j_tpu.operators import Operators

    cl = TpuCommCluster(1)
    base = {i: float(i) for i in range(keys)}
    cl.allreduce_map([dict(base)], Operands.FLOAT, Operators.SUM)  # warm
    per_call = [[dict(base)] for _ in range(reps)]
    t0 = time.perf_counter()
    nk = 0
    for ms in per_call:
        cl.allreduce_map(ms, Operands.FLOAT, Operators.SUM)
        nk += len(ms[0])
    return nk / (time.perf_counter() - t0)


def bench_device_map_chained(keys=50_000, chain=8):
    """configs[2] STEADY-STATE: ``chain`` map allreduces dispatched per
    host resolution (``allreduce_map_async`` + deferred ``result()``),
    so the per-call tunnel round-trip amortizes across the chain — the
    rate a real pod (no tunnel) sees per call. The sync variant
    (``bench_device_map``) pays the full round-trip every call."""
    from ytk_mp4j_tpu.comm.tpu_comm import TpuCommCluster
    from ytk_mp4j_tpu.operands import Operands
    from ytk_mp4j_tpu.operators import Operators

    cl = TpuCommCluster(1)
    base = {i: float(i) for i in range(keys)}
    cl.allreduce_map([dict(base)], Operands.FLOAT, Operators.SUM)  # warm
    batches = [[dict(base)] for _ in range(chain)]
    t0 = time.perf_counter()
    handles = [cl.allreduce_map_async(ms, Operands.FLOAT, Operators.SUM)
               for ms in batches]
    for h in handles:
        h.result()
    return chain * keys / (time.perf_counter() - t0)


def bench_socket_map(procs=4, keys=20_000, reps=3, int_keys=False,
                     columnar=None, join_timeout=120.0, shm=False):
    """Map<String,Double> sparse-grad allreduce over loopback TCP
    (BASELINE.md configs[2]). Returns merged keys/sec on the job's
    DEFAULT map plane — since ISSUE 4, the columnar (codes, values)
    data plane; ``columnar=False`` forces the pickled-dict reference
    path (the pre-ISSUE-4 Kryo-analogue figure) for the A/B.

    ``int_keys=True`` uses {feature id -> value} integer keys — the
    actual ytk-learn sparse-gradient shape. One UNTIMED warmup call
    precedes the loop: a sparse-gradient stream's vocabulary is
    near-persistent, so the steady-state rate (codec warm, novelty
    exchange empty) is the honest per-call figure; the warmup is a
    no-op for the pickled plane, which keeps no per-call state."""
    from ytk_mp4j_tpu.operands import Operands
    from ytk_mp4j_tpu.operators import Operators

    def body(slave, r):
        # 50% overlap across ranks, like sparse gradient updates; one
        # dict per rep (allreduce_map merges in place), built OUTSIDE
        # the timed region so only the collective is measured
        def key(i):
            c = (r * keys // 2 + i) % (procs * keys)
            return c if int_keys else f"w{c}"
        dicts = [
            {key(i): float(i) for i in range(keys)}
            for _ in range(reps + 1)
        ]
        slave.allreduce_map(dicts.pop(), Operands.DOUBLE,
                            Operators.SUM)     # untimed codec warmup
        slave.barrier()
        t0 = time.perf_counter()
        nkeys = 0
        for d in dicts:
            slave.allreduce_map(d, Operands.DOUBLE, Operators.SUM)
            nkeys += len(d)   # post-merge union size = keys merged
        return nkeys / (time.perf_counter() - t0)

    # all-TCP by default for figure continuity: the map keys/sec rows
    # are bench-diff-gated against pre-shm rounds; ``shm=True`` is the
    # ISSUE 15 leg (socket_map_shm_keys_s) — co-located pairs ride the
    # rings, and the frame-level routing carries the column frames
    rates, stats = _run_socket_job(procs, body, native_transport=False,
                                   join_timeout=join_timeout,
                                   map_columnar=columnar, shm=shm,
                                   audit="off", sink_dir="")
    return min(rates), stats


def bench_socket_map_sweep(procs=4,
                           sizes=(1_000, 10_000, 100_000, 500_000),
                           reps=3):
    """Columnar-vs-pickle A/B over map sizes, int AND str keys — the
    honest re-run of the old ``_merge_maps`` packed-merge measurement
    (which paid a per-call union sort + Python pack the grow-only
    codec amortizes away). Emitted in the BENCH ``extra`` so the
    crossover threshold is data-grounded, not guessed. Returns
    ``({"<keys>": {"int"|"str": {"columnar"|"pickle": keys/s}}},
    merged_stats)``."""
    from ytk_mp4j_tpu.utils.stats import merge_snapshots

    sweep = {}
    snaps = []
    for keys in sizes:
        # big unions are slow on the pickled leg and the least noisy;
        # repeat the cheap latency-bound sizes instead
        r = reps if keys <= 10_000 else 1
        row = {}
        for kind, int_keys in (("int", True), ("str", False)):
            cell = {}
            for plane, columnar in (("columnar", True),
                                    ("pickle", False)):
                rate, stats = bench_socket_map(
                    procs=procs, keys=keys, reps=r, int_keys=int_keys,
                    columnar=columnar, join_timeout=600.0)
                cell[plane] = round(rate, 0)
                snaps.append(stats)
            row[kind] = cell
        sweep[str(keys)] = row
    return sweep, _round_stats(merge_snapshots(*snaps))


def main():
    from ytk_mp4j_tpu.utils import tuning

    # MP4J_BENCH_N=11e6 runs the full Higgs-scale config (BASELINE.md
    # configs[3]); the default 1e6 keeps driver runs fast (the rate is a
    # per-byte measure and was measured slightly HIGHER at 11M: 3.33 vs
    # 3.05 GB/s/chip, so the default understates nothing).
    n_tpu = int(float(os.environ.get("MP4J_BENCH_N", "1e6")))
    # socket benches FIRST: they fork real slave processes, and forking
    # after the TPU client exists is not fork-safe (the children would
    # inherit live device-runtime threads/fds)
    sock_gbs, sock_workload_coll_gbs, sock_stats = bench_socket()
    # socket_collective_gbs: the DEFAULT socket data plane (native raw
    # + algo="auto" + pipelined chunked engine) over the tree-level
    # histogram buffer shapes, isolated from the workload's compute
    # skew. The pre-PR2 figure under this key was the framed in-GBDT
    # csecs rate, now kept as socket_collective_in_workload_gbs.
    sock_coll_gbs, sock_coll_stats = bench_socket_collective(
        native_transport=True)
    # ISSUE 7: the same isolated collective leg over the intra-host
    # shared-memory rings (the 4 forked slaves co-locate, so rendezvous
    # negotiates shm for every pair), and with the topology-aware
    # two-level schedule forced (on this single-host roster: binomial
    # reduce+broadcast over shm, leader leg a no-op)
    sock_shm_coll_gbs, sock_shm_coll_stats = bench_socket_collective(
        native_transport=True, shm=True)
    sock_twolevel_gbs, sock_twolevel_stats = bench_socket_collective(
        native_transport=True, shm=True, algo="twolevel")
    # audit-plane overhead A/B (ISSUE 8): off vs digest vs verify,
    # interleaved, on the isolated headline leg (frozen legs above pin
    # audit="off" so historical figures stay comparable)
    audit_overhead = bench_audit_overhead()
    # durable-sink overhead A/B (ISSUE 9): the same isolated headline
    # leg with segments streaming to a throwaway dir (frozen legs pin
    # sink_dir="" the way they pin shm=False / audit="off")
    sink_overhead = bench_sink_overhead()
    health_overhead = bench_health_overhead()
    lint_runtime = bench_lint_runtime()
    # metrics-plane overhead A/B (ISSUE 6 acceptance: <= 3% on the
    # headline leg): the same isolated collective leg with
    # MP4J_METRICS=0 — histogram observes become flag checks, the
    # heartbeat ships empty metric deltas. The default-on figure is
    # sock_coll_gbs itself (every socket figure in this file carries
    # the full metrics tax); forked slaves inherit the env toggle.
    prior_metrics = os.environ.get("MP4J_METRICS")
    os.environ["MP4J_METRICS"] = "0"
    try:
        sock_coll_gbs_nometrics, _ = bench_socket_collective(
            native_transport=True)
    finally:
        # restore, don't delete: a caller-exported MP4J_METRICS must
        # keep governing every later leg (and the A/B note below is
        # only honest when the ON leg really ran with metrics on)
        if prior_metrics is None:
            del os.environ["MP4J_METRICS"]
        else:
            os.environ["MP4J_METRICS"] = prior_metrics
    sock_framed_coll_gbs, sock_framed_coll_stats = bench_socket_collective(
        native_transport=False)
    sweep, sweep_stats = bench_socket_allreduce_sweep()
    map_keys, map_stats = bench_socket_map()
    map_int_keys, map_int_stats = bench_socket_map(int_keys=True)
    # columnar-vs-pickle A/B at the headline config (the pickle legs
    # are the pre-ISSUE-4 reference figures) + the size sweep that
    # grounds the crossover claim
    map_pickle_keys, _ = bench_socket_map(columnar=False)
    map_int_pickle_keys, _ = bench_socket_map(int_keys=True,
                                              columnar=False)
    map_sweep, map_sweep_stats = bench_socket_map_sweep()
    # ISSUE 11: the nonblocking-collective figures — k outstanding
    # iallreduces vs k sequential blocking calls (isolated leg; see
    # bench_socket_async_overlap's 1-core caveat) and the tiny-map
    # coalescing A/B (window on vs off)
    async_overlap = bench_socket_async_overlap()
    coalesce = bench_socket_coalesce()
    # ISSUE 17 (mp4j-overlap): the dense small-array coalescing A/B
    # (the array twin of the map figure above) and the trainer-shaped
    # overlap epoch — multi-core only, records skipped_1core on this
    # 1-core rig instead of a bogus figure (see the leg docstring)
    coalesce_array = bench_socket_coalesce_array()
    trainer_overlap = bench_trainer_overlap()
    # ISSUE 15 (mp4j-tuner): the framed + columnar-map planes over the
    # shm rings (frame-level routing — these bytes were carrier-bound
    # before), and the tuner act-vs-off A/B on a compressed-operand
    # stream (frozen legs everywhere else pin MP4J_TUNER=off)
    framed_shm_gbs, framed_shm_stats = bench_socket_collective(
        native_transport=False, shm=True)
    map_shm_keys, map_shm_stats = bench_socket_map(shm=True)
    tuner_ab = bench_socket_tuner_act()
    recovery, recovery_stats = bench_socket_recovery_latency()
    replacement = bench_socket_replacement_latency()
    shrinkage = bench_socket_shrink_latency()
    planned_evict = bench_socket_planned_evict_ms()
    grow = bench_socket_grow_latency_ms()
    # ISSUE 18 (mp4j-fleet): FleetPoller sweep latency + CPU share
    # against a live 4-rank job in this process (threads, no fork —
    # safe at any point in the socket block; the poller scrapes HTTP
    # out of band so no frozen leg changes)
    fleet_scrape = bench_fleet_scrape()
    # ISSUE 19 (mp4j-serve): the inference plane. The A/B leg runs on
    # threads; the chaos leg forks worker processes, so both stay in
    # this socket block ahead of any device-runtime init (the
    # servables are synthesized numpy-only for exactly that reason)
    serve_ab = bench_serve_latency_qps()
    serve_chaos = bench_serve_chaos()
    (tpu_gbs, trees_per_sec, n_chips, gbdt_fps,
     gbdt_hist_fps) = bench_tpu(n=n_tpu)
    ffm_steps, ffm_fps = bench_ffm_tpu()
    ffm_stream_rows = bench_ffm_stream()
    ffm_stream_rows_serial = bench_ffm_stream(max_in_flight=0)
    reader_rows = bench_libsvm_reader()
    ffm_text_rows = bench_ffm_stream_text()
    dev_map_keys = bench_device_map()
    dev_map_keys_chained = bench_device_map_chained()
    print(json.dumps({
        "metric": "gbdt-histogram-allreduce GB/s/chip",
        "value": round(tpu_gbs, 4),
        "unit": "GB/s/chip",
        "vs_baseline": round(tpu_gbs / sock_gbs, 2),
        "extra": {
            "trees_per_sec": round(trees_per_sec, 4),
            "socket_baseline_gbs": round(sock_gbs, 4),
            "socket_collective_gbs": round(sock_coll_gbs, 4),
            "socket_framed_collective_gbs": round(sock_framed_coll_gbs, 4),
            "socket_collective_in_workload_gbs": round(
                sock_workload_coll_gbs, 4),
            # continuity alias: previous rounds tracked the native rate
            # under this key (socket_collective_gbs now measures it)
            "socket_native_collective_gbs": round(sock_coll_gbs, 4),
            # ISSUE 7: the same collective leg with the data plane on
            # the intra-host shared-memory rings (acceptance: >= 3x
            # the TCP socket_collective_gbs figure), and with the
            # two-level schedule forced (single-host: the intra half)
            "socket_shm_collective_gbs": round(sock_shm_coll_gbs, 4),
            "socket_twolevel_gbs": round(sock_twolevel_gbs, 4),
            "socket_allreduce_sweep": sweep,
            "ffm_sparse_steps_per_sec": round(ffm_steps, 3),
            "ffm_stream_rows_per_sec": round(ffm_stream_rows, 0),
            "ffm_stream_rows_per_sec_serialized": round(
                ffm_stream_rows_serial, 0),
            "libsvm_reader_rows_per_sec": round(reader_rows, 0),
            "ffm_stream_text_rows_per_sec": round(ffm_text_rows, 0),
            "vs_baseline_derate_caveat": (
                "this host has ONE core, so the 4 socket-baseline "
                "slaves time-share it; on a realistic 4-core host the "
                "socket denominator rises up to ~4x and the honest "
                "ratio lands near vs_baseline/4 (see BASELINE.md) — "
                "still clearing the >=10x north star, but vs_baseline "
                "as printed is environment-specific"),
            # headline map figures ride the DEFAULT socket map plane —
            # columnar (codes, values) since ISSUE 4; the *_pickle_*
            # keys are the frozen pickled-dict reference legs of the
            # same config, and socket_map_allreduce_sweep carries the
            # full columnar-vs-pickle A/B over 1k..500k keys x
            # {int, str} so the crossover is measured, not guessed
            "socket_map_allreduce_keys_per_sec": round(map_keys, 0),
            "socket_map_int_allreduce_keys_per_sec": round(map_int_keys, 0),
            "socket_map_pickle_keys_per_sec": round(map_pickle_keys, 0),
            "socket_map_int_pickle_keys_per_sec": round(
                map_int_pickle_keys, 0),
            "socket_map_allreduce_sweep": map_sweep,
            # ISSUE 11 (mp4j-async): k outstanding iallreduces on the
            # helper-thread scheduler vs the same k as sequential
            # blocking calls, plus the coalescing A/B. On this 1-core
            # host the sequential path saturates the core at the
            # kernel-TCP CPU ceiling, so overlap has no idle to fill
            # and the dense async figure lands BELOW sequential (the
            # measured, documented reality — see the leg docstring);
            # the coalescing figure is the async plane's honest win
            # here (~2.5x, fixed-cost amortization)
            "socket_async_overlap_gbs": round(async_overlap["async"], 4),
            "socket_async_sequential_gbs": round(
                async_overlap["sequential"], 4),
            "socket_async_overlap_ratio": round(
                async_overlap["async"] / async_overlap["sequential"],
                3),
            "socket_coalesce_keys_per_sec": round(coalesce["on"], 0),
            "socket_coalesce_off_keys_per_sec": round(
                coalesce["off"], 0),
            "socket_coalesce_ratio": round(
                coalesce["on"] / coalesce["off"], 3),
            # ISSUE 17 (mp4j-overlap): the dense small-array fused
            # plane (count-negotiated allreduce_array_multi) vs the
            # same stream as sequential i* submissions — acceptance
            # >= 2x elems/s — and the trainer-overlap epoch A/B. The
            # trainer leg is multi-core only: on this 1-core rig the
            # dict records skipped_1core and NO ratio figure is
            # emitted (bench-diff skips missing metrics, so the gate
            # arms itself the first time the bench runs on a
            # multi-core host)
            "socket_coalesce_array_elems_per_sec": round(
                coalesce_array["on"], 0),
            "socket_coalesce_array_off_elems_per_sec": round(
                coalesce_array["off"], 0),
            "socket_coalesce_array_ratio": round(
                coalesce_array["on"] / coalesce_array["off"], 3),
            "socket_trainer_overlap": {
                k: v for k, v in trainer_overlap.items()
                if k != "stats"},
            **({"socket_trainer_overlap_ratio": round(
                    trainer_overlap["ratio"], 3),
                "socket_trainer_overlap_steps_per_sec": round(
                    trainer_overlap["overlap"], 2),
                "socket_trainer_blocking_steps_per_sec": round(
                    trainer_overlap["blocking"], 2)}
               if "ratio" in trainer_overlap else {}),
            # ISSUE 15 (mp4j-tuner): the framed/columnar-map planes
            # over the shm rings (frame-level routing — previously
            # carrier-bound even intra-host), and the tuner A/B: act
            # must be a net win over off on this compressed-operand
            # leg (the probe discovers the loopback link outruns the
            # zlib bound and disables per-link compression); the
            # `tuner` extra records the converged decisions
            "socket_framed_shm_gbs": round(framed_shm_gbs, 4),
            "socket_map_shm_keys_s": round(map_shm_keys, 0),
            "socket_tuner_act_gbs": round(tuner_ab["act"], 4),
            "socket_tuner_off_gbs": round(tuner_ab["off"], 4),
            "socket_tuner_ratio": round(
                tuner_ab["act"] / tuner_ab["off"], 3),
            "tuner": tuner_ab["decisions"],
            # mp4j-resilience (ISSUE 5): one injected connection reset
            # in a 4-rank allreduce loop; recovery_latency_ms is the
            # full epoch-fenced abort/retry round end to end.
            # steady_state decomposes the no-fault cost: failstop_gbs
            # (max_retries=0) carries the epoch fence alone (~0, a
            # flag check — the figure comparable with BENCH history);
            # default_gbs adds the input-preservation snapshot, one
            # pooled memcpy pass per mutating collective, which this
            # 1-core loopback host amplifies because its "wire" is
            # itself memcpy (see bench_socket_recovery_latency doc)
            "socket_recovery": recovery,
            # scalar alias for bench-diff gating (lower is better)
            "socket_recovery_latency_ms": recovery[
                "recovery_latency_ms"],
            # mp4j-elastic (ISSUE 10): kill -> adopted spare (or n-1
            # shrink) -> first completed collective, measured as the
            # faulted iteration's wall time over the healthy median;
            # frozen legs elsewhere pin MP4J_ELASTIC=off so these are
            # the ONLY figures that pay the membership machinery
            "socket_replacement_latency_ms": replacement[
                "replacement_latency_ms"],
            "socket_shrink_latency_ms": shrinkage[
                "shrink_latency_ms"],
            # ISSUE 13: actuation latencies — planned evict (fence ->
            # round -> adoption -> first post-adoption collective,
            # detection excluded by design) and grow (resize_point
            # wall time). Frozen legs elsewhere pin MP4J_AUTOSCALE=off
            "socket_planned_evict_ms": planned_evict[
                "planned_evict_ms"],
            "socket_grow_latency_ms": grow["grow_latency_ms"],
            # ISSUE 18 (mp4j-fleet): one full fleet sweep (both
            # endpoint fetches + fold + contention detection) against
            # a live 4-rank job; the p99 row is bench-diff-gated
            # (lower is better) so a fold/detector regression cannot
            # creep in silently
            "fleet_scrape": fleet_scrape,
            "fleet_scrape_p99_ms": fleet_scrape[
                "fleet_scrape_p99_ms"],
            "serve": serve_ab,
            "serve_chaos": serve_chaos,
            "serve_batched_qps": serve_ab["serve_batched_qps"],
            "serve_unbatched_qps": serve_ab["serve_unbatched_qps"],
            "serve_speedup": serve_ab["serve_speedup"],
            "serve_p50_ms": serve_ab["serve_p50_ms"],
            "serve_p99_ms": serve_ab["serve_p99_ms"],
            "serve_chaos_p99_ms": serve_chaos["serve_chaos_p99_ms"],
            "socket_elastic": {"replace": replacement,
                               "shrink": shrinkage,
                               "planned_evict": planned_evict,
                               "grow": grow},
            # merged cross-rank comm.stats() snapshot per socket
            # workload: where the wire/reduce/serialize budget actually
            # went (schema: ytk_mp4j_tpu/utils/stats.py)
            "socket_stats": {
                "gbdt_workload": sock_stats,
                "collective_native": sock_coll_stats,
                "collective_shm": sock_shm_coll_stats,
                "collective_twolevel": sock_twolevel_stats,
                "collective_framed": sock_framed_coll_stats,
                "collective_framed_shm": framed_shm_stats,
                "map_shm": map_shm_stats,
                "allreduce_sweep": sweep_stats,
                "map_allreduce": map_stats,
                "map_int_allreduce": map_int_stats,
                "map_sweep": map_sweep_stats,
                "recovery": recovery_stats,
            },
            # telemetry overhead (ISSUE 3 acceptance, qualitative): the
            # spans + heartbeats are DEFAULT-ON in every socket figure
            # in this file, so socket_collective_gbs already carries
            # the full observability tax. A heartbeat is one ~300 B
            # control frame per rank per 0.5 s riding the master
            # channel (never the data plane); a span is one
            # bounded-deque append per chunk/phase. Measured A/B on
            # the bench host (on vs MP4J_SPAN_RING=0 +
            # MP4J_HEARTBEAT_SECS=0, interleaved rounds): the delta is
            # noise-dominated (run-to-run spread ~10% on this shared
            # 1-core host; the telemetry-ON median came out FASTER),
            # with best-of-N within the <2% target.
            "telemetry": {
                "heartbeat_secs": tuning.heartbeat_secs(),
                "span_ring_capacity": tuning.span_ring_capacity(),
                "default_on": True,
            },
            # metrics-plane overhead (ISSUE 6 acceptance: <= 3% on the
            # headline socket_collective_gbs leg). Same leg, metrics
            # on (the default — sock_coll_gbs itself) vs MP4J_METRICS=0
            # (observes become one flag check; heartbeats ship empty
            # metric deltas). Positive overhead_pct = metrics cost;
            # run-to-run spread on this shared 1-core host is ~10%, so
            # small negatives are noise, not a speedup.
            # audit-plane overhead (ISSUE 8): interleaved off/digest/
            # verify A/B on the headline leg; the digest figure is
            # bench-diff-gated (socket_collective_gbs_audit_digest).
            # The printed pct carries the 1-core x4 serialization
            # amplification — per-rank cost ~2%, see the leg docstring
            "audit_overhead": audit_overhead,
            "socket_collective_gbs_audit_digest":
                audit_overhead["socket_collective_gbs_audit_digest"],
            # durable-sink overhead (ISSUE 9 acceptance: <= 3% on the
            # headline leg, inside this host's ~10% noise floor); the
            # armed figure is bench-diff-gated so the sink tax cannot
            # silently creep
            "sink_overhead": sink_overhead,
            "socket_collective_gbs_sink_on":
                sink_overhead["socket_collective_gbs_sink_on"],
            # health-plane overhead (ISSUE 12 acceptance: <= 3% on the
            # isolated headline leg, inside this host's ~10% noise
            # floor); the armed figure is bench-diff-gated so the
            # detector tax cannot silently creep
            "health_overhead": health_overhead,
            "socket_collective_gbs_health_on":
                health_overhead["socket_collective_gbs_health_on"],
            # mp4j-lint runtime (ISSUE 14): the whole-program R19-R21
            # pass rides the tier-1 gate, so its cost is a tracked
            # figure — full two-pass run vs the per-file pass alone
            # (budget: <= 2x)
            "lint_runtime": lint_runtime,
            "lint_runtime_secs": lint_runtime["lint_runtime_secs"],
            # ISSUE 16: v3 (R23-R25 lockset/resource passes) over v2
            # (R19-R21) — flattened so bench-diff gates it (<= 1.5x)
            "lint_v3_over_v2_ratio":
                lint_runtime["lint_v3_over_v2_ratio"],
            "metrics_overhead": {
                # False means the caller exported MP4J_METRICS=0 and
                # the "on" leg really ran off — overhead_pct is then
                # an off-vs-off null, not a measurement
                "default_on": tuning.metrics_enabled(),
                "socket_collective_gbs_metrics_on": round(
                    sock_coll_gbs, 4),
                "socket_collective_gbs_metrics_off": round(
                    sock_coll_gbs_nometrics, 4),
                "overhead_pct": round(
                    (sock_coll_gbs_nometrics - sock_coll_gbs)
                    / sock_coll_gbs_nometrics * 100, 2),
            },
            "device_map_int_allreduce_keys_per_sec": round(dev_map_keys, 0),
            "device_map_chained_keys_per_sec": round(
                dev_map_keys_chained, 0),
            # MFU vs the v5e per-chip bf16 MXU peak (197 TFLOP/s).
            # gbdt_hist_mxu_* is the ANALYTIC flop count of the fused
            # Pallas histogram matmuls (cost_analysis cannot see inside
            # the custom call; gbdt_step_* below is the XLA-visible
            # remainder only — routing, splits, leaf math). The
            # histogram's one-hot GENERATION is VPU-bound (~15 ms/tree
            # dtype-invariant floor, BASELINE.md), so MXU utilization
            # is structurally capped well below peak — the number
            # grounds "fast" against the hardware ceiling, not a claim
            # of matmul saturation; the FFM sparse step is gather/
            # scatter-unit-bound, lower still.
            "gbdt_hist_mxu_tflops_per_sec_per_chip": round(
                gbdt_hist_fps / 1e12, 3),
            "gbdt_hist_mxu_mfu_vs_v5e_bf16_peak": round(
                gbdt_hist_fps / 197e12, 4),
            "gbdt_step_xla_visible_tflops_per_sec_per_chip": (
                None if gbdt_fps is None else round(gbdt_fps / 1e12, 3)),
            "ffm_step_tflops_per_sec_per_chip": (
                None if ffm_fps is None else round(ffm_fps / 1e12, 4)),
            "ffm_step_mfu_vs_v5e_bf16_peak": (
                None if ffm_fps is None
                else round(ffm_fps / 197e12, 6)),
            "n_chips": n_chips,
            "config": f"Higgs-like synthetic, F=28, B=256, depth=6, "
                      f"N_tpu={n_tpu:.0e}, N_socket=2e5/4 procs; 10 "
                      "chained trees per host sync (amortizes the "
                      "~100ms axon tunnel round-trip); timing closed "
                      "by host round-trip (honest under axon's "
                      "non-blocking block_until_ready); "
                      "socket_collective_gbs = the default socket data "
                      "plane (native raw, algo=auto, chunked engine) "
                      "isolated over the tree-level buffer shapes — "
                      "the framed in-workload figure previous rounds "
                      "tracked under that key is "
                      "socket_collective_in_workload_gbs",
        },
    }))


if __name__ == "__main__":
    sys.exit(main())
