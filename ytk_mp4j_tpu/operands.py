"""Operand (element type + container) system.

The reference describes WHAT is being communicated with operand objects
from a factory (``Operands.DOUBLE_OPERAND()`` etc., SURVEY.md section 2
[U]); element types are double, float, int, long, short, byte, String and
generic Object (user serializer). Containers are dense arrays with a
``[from, to)`` range, or sparse ``Map<K, V>``.

TPU-first redesign: numeric operands map to numpy/jax dtypes and are
eligible for the device (ICI) path; ``STRING`` and ``OBJECT`` operands are
host-only (not TPU-representable) and always travel the socket /
in-process path with pickle standing in for Kryo — mirroring the
reference's Kryo-only handling of those types (SURVEY.md section 7 phase 4).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import numpy as np

from ytk_mp4j_tpu.exceptions import Mp4jError


@dataclass(frozen=True)
class Operand:
    name: str
    dtype: np.dtype | None  # None => host-only (STRING / OBJECT)
    # Optional user codec for OBJECT operands (stands in for a user Kryo
    # serializer): (dumps, loads) over bytes.
    dumps: Callable[[Any], bytes] | None = None
    loads: Callable[[bytes], Any] | None = None
    # zlib-compress this operand's payloads on socket transports (a
    # bandwidth/CPU trade for compressible data; no effect on the device
    # path, where payloads never leave HBM). See Operands.compressed().
    compress: bool = False

    @property
    def is_numeric(self) -> bool:
        return self.dtype is not None

    @property
    def columnar_maps(self) -> bool:
        """Whether map collectives may ship this operand as a columnar
        (codes:int32, values:[n, *vshape]) pair on the socket plane
        (``comm.process_comm``): numeric operands only — STRING/OBJECT
        values have no dense column form and keep the pickled-dict
        path. A pure function of the operand, so it is part of the
        job-wide wire decision both ends of an exchange derive
        independently (the same R4 discipline as the raw/framed
        choice). Columnar merges compute in ``dtype`` — the declared
        operand is load-bearing, exactly as on the device path's
        ``pack_values`` cast."""
        return self.is_numeric

    def check_array(self, arr) -> np.ndarray:
        """Validate/coerce a host array for this operand."""
        if not self.is_numeric:
            raise Mp4jError(f"{self.name} operand has no dense-array form")
        a = np.asarray(arr)
        if a.dtype != self.dtype:
            raise Mp4jError(
                f"array dtype {a.dtype} does not match operand {self.name} "
                f"({self.dtype})"
            )
        return a

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return f"Operand({self.name})"


class Operands:
    """Factory namespace mirroring the reference's ``Operands`` class."""

    DOUBLE = Operand("DOUBLE", np.dtype(np.float64))
    FLOAT = Operand("FLOAT", np.dtype(np.float32))
    INT = Operand("INT", np.dtype(np.int32))
    LONG = Operand("LONG", np.dtype(np.int64))
    SHORT = Operand("SHORT", np.dtype(np.int16))
    BYTE = Operand("BYTE", np.dtype(np.int8))
    STRING = Operand("STRING", None)

    # TPU-native extension (no Java analogue): the chip's preferred
    # 16-bit float. Device-eligible; on socket transports numpy computes
    # through ml_dtypes.
    try:
        import ml_dtypes as _mld

        BFLOAT16 = Operand("BFLOAT16", np.dtype(_mld.bfloat16))
    except ImportError:  # pragma: no cover - ml_dtypes ships with jax
        BFLOAT16 = None

    @staticmethod
    def compressed(operand: Operand) -> Operand:
        """A copy of ``operand`` whose payloads are zlib-compressed on
        socket transports (the reference-era Kryo-with-compression
        trade; the device path is unaffected)."""
        from dataclasses import replace

        return replace(operand, compress=True)

    # Factory-method spellings for parity with the reference API shape.
    @staticmethod
    def DOUBLE_OPERAND() -> Operand:
        return Operands.DOUBLE

    @staticmethod
    def FLOAT_OPERAND() -> Operand:
        return Operands.FLOAT

    @staticmethod
    def INT_OPERAND() -> Operand:
        return Operands.INT

    @staticmethod
    def LONG_OPERAND() -> Operand:
        return Operands.LONG

    @staticmethod
    def SHORT_OPERAND() -> Operand:
        return Operands.SHORT

    @staticmethod
    def BYTE_OPERAND() -> Operand:
        return Operands.BYTE

    @staticmethod
    def STRING_OPERAND() -> Operand:
        return Operands.STRING

    @staticmethod
    def OBJECT_OPERAND(dumps=None, loads=None) -> Operand:
        """Generic object operand with an optional user codec (the Kryo
        analogue). Defaults to pickle."""
        return Operand("OBJECT", None, dumps=dumps, loads=loads)

    NUMERIC = tuple(
        op for op in (DOUBLE, FLOAT, INT, LONG, SHORT, BYTE, BFLOAT16)
        if op is not None)

    @classmethod
    def by_dtype(cls, dtype) -> Operand:
        dt = np.dtype(dtype)
        for op in cls.NUMERIC:
            if op.dtype == dt:
                return op
        raise Mp4jError(f"no operand for dtype {dt}")
