"""mp4j-resilience (ISSUE 5): fault injection + epoch-fenced recovery.

The reference is fail-stop (SURVEY.md section 5): no failure detection,
no retry, no way to even *test* failure behavior. This package is the
deliberate departure from that scope:

- :mod:`ytk_mp4j_tpu.resilience.faults` — a deterministic, seedable
  fault plan (``MP4J_FAULT_PLAN``) hooked into the socket transport:
  delay sends, cut a peer connection mid-frame, slow a rank, or kill a
  slave at the Nth collective. The substrate for the chaos grid in
  ``tests/test_resilience.py`` and for exercising the recovery engine.
- :mod:`ytk_mp4j_tpu.resilience.recovery` — the epoch-fenced
  abort/retry engine: on a transport failure the slave reports to the
  master over the control plane, the master broadcasts an abort round
  targeting ``epoch+1``, every rank tears down its peer channels (the
  drain — stale frames die with their connections, whose epoch is
  pinned at dial time), acks, and re-runs the failed collective from
  its preserved input once the master releases the round. Permanently
  dead ranks escalate to a terminal abort: every survivor raises the
  same clean ``Mp4jFatalError`` naming the dead rank — never a hang,
  never a partial result.
- :mod:`ytk_mp4j_tpu.resilience.membership` — elastic membership
  (ISSUE 10): warm-spare replacement, degraded shrink, and the grow
  roster algebra; pure protocol functions + the master's spare pool
  and membership event log.
- :mod:`ytk_mp4j_tpu.resilience.autoscaler` — mp4j-autopilot
  (ISSUE 13): the closed-loop controller that reads
  ``Master.health_status()`` verdicts and ACTS through the membership
  machinery — planned eviction, spare auto-provisioning, grow
  approval — behind cooldown/budget/audit-green/circuit-breaker
  safety rails (``MP4J_AUTOSCALE=off|observe|act``).
"""

from ytk_mp4j_tpu.resilience.faults import (  # noqa: F401
    Fault, FaultInjector, FaultKill, FaultPlan)
from ytk_mp4j_tpu.resilience.recovery import (  # noqa: F401
    RECOVERABLE, RecoveryManager)
