"""Epoch-fenced abort/retry recovery for the socket backend.

The job-wide **epoch** is an integer every rank agrees on, advanced
only by the master's abort protocol. Peer connections pin the epoch at
dial time (it rides the peer handshake), so "drain stale-epoch frames"
has a sharp mechanical meaning: an abort round closes every connection
of the old epoch, and whatever bytes were in flight die with their
sockets — no frame parsing of torn streams, no heuristics, and it
covers the unframed raw plane for free.

Protocol (one **abort round**, driven by the master, ISSUE 5)::

    rank r: collective fails with a transport error
         -> ABORT_REQ {epoch, collective, error}          (control plane)
    master: first request for this epoch fans out ("abort", epoch+1)
    every rank (control thread): tear down peer channels  <- the drain;
            also unblocks any rank stuck in a data-plane call
         -> ABORT_ACK {epoch+1}
    master: all live ranks acked -> ("abort_go", epoch+1)
    every rank: epoch := epoch+1; failed collectives restore their
            preserved input and re-run; peer channels re-dial lazily
            with capped exponential backoff (MP4J_RECONNECT_BACKOFF)

Terminal aborts: a dead control connection, a stalled abort round
(``MP4J_DEAD_RANK_SECS`` without full acks), an escalated barrier
stall, or an exhausted retry budget (``MP4J_MAX_RETRIES``) makes the
master fan out ("abort_fatal", msg): every surviving rank raises the
SAME :class:`~ytk_mp4j_tpu.exceptions.Mp4jFatalError` within its
bounded wait — never a hang, never a partial result.

What retries: only :data:`RECOVERABLE` failures (transport errors and
raw OS socket errors). Validation/misuse errors propagate untouched —
the reference's semantics, see ``exceptions.py``.

Idempotence: the recovery wrapper snapshots the collective's mutable
payload (array/list/map) at the OUTERMOST entry and restores it before
each retry, because several collectives merge into the caller's buffer
mid-flight (recursive halving, composed reduce+scatter). This copy is
the only steady-state cost of resilience — the fence itself is a flag
check — and it is skipped entirely at ``MP4J_MAX_RETRIES=0``.
"""

from __future__ import annotations

import collections
import threading
import time

from ytk_mp4j_tpu.exceptions import (
    Mp4jAbortError, Mp4jError, Mp4jEvicted, Mp4jFatalError,
    Mp4jTransportError)
from ytk_mp4j_tpu.obs import spans

# the recoverable class: wire-level Mp4jTransportError (which includes
# the fence's Mp4jAbortError) plus raw socket/OS failures surfaced by
# an abort teardown cutting a live operation (EBADF, ECONNRESET, EOF
# from a helper-thread send, ...)
RECOVERABLE = (Mp4jTransportError, OSError, EOFError)


class RecoveryManager:
    """Per-slave recovery state machine.

    Two call sides, matching the slave's two threads:

    - the CONTROL thread delivers master messages via
      :meth:`on_abort` / :meth:`on_go` / :meth:`on_fatal` (and MUST
      keep doing so while a collective blocks — that is what unhangs
      it);
    - the COLLECTIVE thread runs attempts through :meth:`run` and
      polls the epoch fence via :meth:`poll`.

    ``send_ctl(kind, payload)`` ships a control message to the master
    (best-effort; may raise). ``teardown()`` closes every peer channel
    (idempotent; called from the control thread). ``stats`` is the
    slave's :class:`~ytk_mp4j_tpu.utils.stats.CommStats` — retries and
    aborts land in its counters and in the span ring.
    """

    def __init__(self, *, rank: int, max_retries: int,
                 dead_rank_secs: float, send_ctl, teardown, stats,
                 wake=None, drain=None, progress=None,
                 terminal_hook=None):
        self.rank = rank
        self.max_retries = max_retries
        self.dead_rank_secs = dead_rank_secs
        self._send_ctl = send_ctl
        self._teardown = teardown
        self._stats = stats
        self._wake = wake or (lambda: None)
        self._drain = drain or (lambda: None)
        # flight-recorder hook (ISSUE 6): fired exactly once, on the
        # FIRST terminal abort, BEFORE the fatal flag wakes any waiter
        # — the slave's final telemetry flush + postmortem dump must
        # land before the collective thread raises and the caller
        # starts tearing the process down
        self._terminal_hook = terminal_hook
        self._terminal_fired = False
        # bounded epoch/retry event log — the postmortem bundle's
        # recovery.json (monotonic timestamps: deltas are what matter)
        self._events: collections.deque = collections.deque(maxlen=256)
        # own lock (NOT _cond: _note runs inside _cond-held sections);
        # keeps (deque, count) consistent for the sink's cursor math
        self._events_lock = threading.Lock()
        self._event_count = 0    # events ever noted (sink cursor)
        # (collective ordinal, in-flight flag) for the abort ack: the
        # master refuses to release a round whose ranks sit at
        # DIFFERENT collectives — recovery is per-collective, and a
        # fault spanning a collective boundary is unrecoverable (a
        # completed rank cannot re-serve its contribution)
        self._progress = progress or (lambda: (0, False))
        self._cond = threading.Condition()
        self.epoch = 0          # last epoch the master released (go)
        self._target = 0        # highest abort epoch announced
        self._fatal: str | None = None
        # planned eviction (ISSUE 13): the terminal message is a clean
        # release, not a failure — waiters raise Mp4jEvicted instead
        # of Mp4jFatalError and the postmortem recorder stays quiet
        self._evicted = False
        # the soft boundary fence (ISSUE 13): while set, the
        # collective thread PARKS at its next outermost entry (acking
        # its position) instead of starting the collective — the
        # master's planned-eviction quiesce, with the wire untouched.
        # ``_fence_goal`` is the ordinal the master wants COMPLETED
        # before parking (fence_advance): a rank parked early would
        # starve a peer's in-flight batch that still needs it, so the
        # master advances laggards to the global max ordinal first
        self._fence_token: int | None = None
        self._fence_goal = 0
        self._requested = 0     # highest abort epoch we asked for
        self._tl = threading.local()

    # ------------------------------------------------------------------
    # control-thread side
    # ------------------------------------------------------------------
    def _note(self, kind: str, detail: str = "") -> None:
        with self._events_lock:
            self._events.append((time.monotonic(), kind, detail))
            self._event_count += 1

    def note(self, kind: str, detail: str = "") -> None:
        """Public event-log append for the membership layer (ISSUE 10):
        replacement/adoption/shrink events join the same durable log
        the abort/retry protocol writes, so the sink (PR 9) and
        ``mp4j-scope postmortem`` report full membership history."""
        self._note(kind, detail)

    def events(self) -> list[tuple]:
        """The bounded epoch/retry event log (postmortem bundle)."""
        with self._events_lock:
            return list(self._events)

    def seed(self, epoch: int) -> None:
        """Pin a freshly adopted joiner's recovery state to the epoch
        the membership round released (ISSUE 10): the joiner was never
        part of epochs < ``epoch``, so both the released epoch and the
        announce target start there — the fence sees a quiescent,
        current state, and the joiner's peer dials pin the epoch every
        survivor expects."""
        with self._cond:
            self.epoch = int(epoch)
            self._target = int(epoch)
            self._requested = int(epoch)

    def events_since(self, cursor: int) -> tuple[int, list[tuple], int]:
        """``(new_cursor, events, dropped)`` — the durable sink's
        non-destructive delta read over the bounded event log
        (ISSUE 9), mirroring ``obs.spans.take_since``."""
        with self._events_lock:
            return spans.ring_delta(self._events, self._event_count,
                                    cursor)

    def on_fence(self, token: int) -> None:
        """The master wants every rank parked at a collective
        boundary (ISSUE 13 planned eviction): arm the fence. The
        collective thread acks and parks at its NEXT outermost entry
        — nothing is torn down, so a canceled fence costs nothing."""
        with self._cond:
            self._fence_token = int(token)
            self._fence_goal = 0
            self._cond.notify_all()
        self._note("fence", f"token={token}")
        self._wake()

    def on_fence_advance(self, token: int, goal: int) -> None:
        """The master moved the fence's park ordinal: this rank
        parked (or would park) BEHIND a peer's in-flight ordinal, and
        a rank parked early starves every peer whose admitted batch
        still needs it — run through ordinal ``goal`` first, then
        park and re-ack. Parking after COMPLETING an ordinal is
        starvation-free: completion implies this rank's sends for it
        (and everything before it) are already on the wire."""
        with self._cond:
            if self._fence_token == int(token):
                self._fence_goal = max(self._fence_goal, int(goal))
            self._cond.notify_all()
        self._note("fence_advance", f"token={token} goal={goal}")
        self._wake()

    def on_fence_release(self, token: int) -> None:
        """The master canceled the fence (a rank could not reach a
        boundary in time, or the eviction became moot): parked ranks
        resume exactly where they were — zero disruption."""
        with self._cond:
            if self._fence_token == int(token):
                self._fence_token = None
            self._cond.notify_all()
        self._note("fence_release", f"token={token}")
        self._wake()

    def _join_fence(self) -> None:
        """Collective-thread side of the fence: at an OUTERMOST
        collective entry with the fence armed — and this rank's
        position at or past the fence goal — ack the position and
        park until the fence resolves: into an abort round (the
        eviction proceeds; ``_join_pending_round`` below takes over),
        a release (canceled; resume free), an ADVANCE (a peer's
        in-flight batch still needs this rank — resume through the
        new goal, re-park at the next boundary), or a terminal
        message. Bounded: a masterless fence must not hang the job
        past the recovery deadline."""
        deadline = time.monotonic() + self.dead_rank_secs
        while True:
            with self._cond:
                tok = self._fence_token
                goal = self._fence_goal
            if tok is None:
                return
            seq, _ = self._progress()
            if seq < goal:
                return      # run on; re-park once the goal completes
            try:
                self._send_ctl("fence_ack",
                               {"token": tok, "seq": seq})
            except (Mp4jError, OSError):
                pass    # master gone; its watchdog owns the outcome
            self._note("fence_park", f"token={tok} seq={seq}")
            with self._cond:
                while (self._fence_token == tok
                       and self._fence_goal <= seq
                       and self._fatal is None
                       and self._target <= self.epoch):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return
                    self._cond.wait(min(remaining, 0.5))
                if (self._fence_token != tok or self._fatal is not None
                        or self._target > self.epoch):
                    return

    def on_abort(self, target: int) -> None:
        """Master announced an abort round targeting ``target``: tear
        down the old epoch's data plane and ack. Runs on the control
        thread so it fires even while the collective thread is blocked
        mid-exchange (the teardown is what unblocks it)."""
        with self._cond:
            if target <= self._target:
                return          # duplicate/stale announcement
            self._target = target
            # an abort round supersedes any armed fence: the round IS
            # the quiesce now, and the parked ranks fall through into
            # _join_pending_round to wait for the go
            self._fence_token = None
            self._cond.notify_all()
        self._note("abort", f"epoch->{target}")
        self._teardown()
        self._stats.add("aborts_seen", 1)
        spans.mark("abort", self.rank, epoch=target)
        try:
            seq, inflight = self._progress()
            self._send_ctl("abort_ack", {"epoch": target, "seq": seq,
                                         "inflight": inflight})
        except (Mp4jError, OSError):
            pass   # master gone; its watchdog turns this terminal
        self._wake()

    def on_go(self, epoch: int) -> None:
        """Master released the round: advance the job-wide epoch."""
        with self._cond:
            if epoch > self.epoch:
                self.epoch = epoch
            self._cond.notify_all()
        self._note("go", f"epoch={epoch}")
        self._wake()

    def on_fatal(self, msg: str) -> None:
        """Terminal abort (from the master's fan-out, or locally when
        the master is unreachable): record the one job-wide message and
        wake every waiter. The FIRST call also fires the terminal hook
        — final telemetry flush + postmortem dump (ISSUE 6) — before
        the fatal flag is published, so every survivor's bundle is on
        disk before any thread raises; the hook is wrapped: a recorder
        failure must never block the abort itself."""
        with self._cond:
            first = not self._terminal_fired
            self._terminal_fired = True
        if first:
            self._note("fatal", msg[:120])
            if self._terminal_hook is not None:
                try:
                    self._terminal_hook(msg)
                # the job is dying with `msg`; a best-effort recorder
                # error (full disk, dead master channel) must not
                # replace or delay that
                # mp4j-lint: disable=R5 (best-effort flight recorder)
                except Exception:
                    pass
        with self._cond:
            if self._fatal is None:
                self._fatal = msg
            self._cond.notify_all()
        self._teardown()
        spans.mark("abort_fatal", self.rank)
        self._wake()

    def on_evicted(self, msg: str) -> None:
        """Planned eviction (ISSUE 13): the master's autoscaler
        replaced this LIVE rank from a warm spare at a collective
        boundary, and this message is the release. Terminal like a
        fatal (the data plane belongs to the replacement now; every
        blocked wait must break), but CLEAN: waiters raise
        :class:`Mp4jEvicted`, the terminal hook stays unfired (a
        planned eviction leaves no postmortem — nothing failed), and
        ``close()`` skips the master handshake the master already
        wrote off."""
        with self._cond:
            self._terminal_fired = True   # no flight-recorder dump
            if self._fatal is None:
                self._fatal = msg
                self._evicted = True
            self._cond.notify_all()
        self._note("evicted", msg[:120])
        self._teardown()
        spans.mark("evicted", self.rank)
        self._wake()

    @property
    def fatal(self) -> str | None:
        return self._fatal

    @property
    def evicted(self) -> bool:
        """Whether the terminal message is a planned eviction."""
        return self._evicted

    def fatal_exc(self, msg: str | None = None) -> Mp4jError:
        """THE terminal-exception constructor: every site that raises
        the job-wide terminal message must come through here so a
        planned eviction surfaces as :class:`Mp4jEvicted` (clean
        release) and everything else as :class:`Mp4jFatalError` —
        two sites deciding independently would disagree."""
        text = self._fatal if msg is None else msg
        return (Mp4jEvicted(text) if self._evicted
                else Mp4jFatalError(text))

    # ------------------------------------------------------------------
    # collective-thread side
    # ------------------------------------------------------------------
    def poll(self) -> None:
        """The epoch fence: one flag check on the hot path. Raises
        when this rank must stop touching the data plane — a pending
        abort round (recoverable), a terminal abort (fatal), or a
        ZOMBIE attempt: once the master releases a new epoch, an
        attempt started under the old one may still be unwinding, and
        without the attempt-epoch pin it would acquire fresh channels
        and consume (or corrupt) frames that belong to the retry."""
        if self._fatal is not None:
            raise self.fatal_exc()
        if self._target > self.epoch:
            raise Mp4jAbortError(
                f"epoch fence: abort round -> {self._target} in flight "
                f"(this rank still at epoch {self.epoch})")
        att = getattr(self._tl, "attempt_epoch", None)
        if att is not None and att != self.epoch:
            raise Mp4jAbortError(
                f"epoch fence: attempt pinned to epoch {att} but the "
                f"job moved to epoch {self.epoch} (zombie attempt)")

    def check_channel(self, ch_epoch: int) -> None:
        """Validate a just-acquired channel's pinned epoch against the
        running attempt (or, outside any attempt, the current epoch).
        Closes the fence's one remaining gap: a thread that passed
        ``poll`` and then BLOCKED waiting for a peer dial-in can wake
        holding a channel from a newer epoch after a full abort round
        completed mid-wait — using it would steal the retry's frames."""
        att = getattr(self._tl, "attempt_epoch", None)
        want = att if att is not None else self.epoch
        if ch_epoch != want:
            raise Mp4jAbortError(
                f"epoch fence: channel pinned to epoch {ch_epoch} but "
                f"this attempt runs at epoch {want}")

    def abort_pending(self) -> bool:
        """Non-raising fence read — wait-predicate form of
        :meth:`poll` (peer-connect waits wake on it)."""
        return self._fatal is not None or self._target > self.epoch

    def enter(self) -> bool:
        """Outermost-collective tracking for the recovery wrapper
        (composed collectives recover at the outermost frame only)."""
        depth = getattr(self._tl, "depth", 0)
        self._tl.depth = depth + 1
        return depth == 0

    def exit(self) -> None:
        self._tl.depth = getattr(self._tl, "depth", 1) - 1

    def run(self, name: str, attempt, preserve, restore):
        """Run ``attempt()`` under the abort/retry engine.

        ``preserve()`` snapshots the collective's mutable input (called
        once, before the first attempt); ``restore(saved)`` puts it
        back before a retry. Raises ``Mp4jFatalError`` with the
        master's job-wide message when recovery is impossible."""
        saved = preserve() if self.max_retries > 0 else None
        tries = 0
        try:
            return self._run_rounds(name, attempt, restore, saved, tries)
        finally:
            self._tl.attempt_epoch = None

    def _run_rounds(self, name, attempt, restore, saved, tries):
        while True:
            self._join_fence()
            self._join_pending_round()
            # release fds of channels the last round tore down — only
            # the collective thread may do this (native-poll fd-reuse
            # hazard, see Channel.invalidate)
            self._drain()
            epoch0 = self.epoch
            self._tl.attempt_epoch = epoch0   # pin (see poll)
            try:
                return attempt()
            except Mp4jFatalError:
                raise
            except RECOVERABLE as e:
                if self.max_retries == 0:
                    # fail-stop (the reference's contract): first
                    # transport error is final, nothing job-wide
                    if isinstance(e, Mp4jError):
                        raise
                    raise Mp4jTransportError(
                        f"collective '{name}' failed: {e!r}") from e
                if self._fatal is not None:
                    raise self.fatal_exc() from e
                if tries >= self.max_retries:
                    self._go_terminal(
                        f"collective '{name}' on rank {self.rank} "
                        f"failed after {tries} recovery "
                        f"round(s): {e}", cause=e)
                tries += 1
                self._stats.add("retries", 1, bucket=name)
                self._note("retry", f"{name} attempt={tries}")
                spans.mark("retry", self.rank, collective=name,
                           attempt=tries, error=repr(e)[:120])
                self._request_abort(epoch0, name, e)
                self._await_epoch_past(epoch0, name)
                if restore is not None:
                    restore(saved)

    # ------------------------------------------------------------------
    def _join_pending_round(self) -> None:
        """A rank entering a collective while an abort round is in
        flight (its control thread already tore down and acked) waits
        here for the go instead of dialing into a dying epoch."""
        deadline = time.monotonic() + self.dead_rank_secs
        with self._cond:
            while self._fatal is None and self._target > self.epoch:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(min(remaining, 0.5))
        if self._fatal is not None:
            raise self.fatal_exc()
        if self._target > self.epoch:
            self._go_terminal(
                f"rank {self.rank}: abort round -> {self._target} "
                f"stalled for {self.dead_rank_secs:.1f}s with no "
                "release from the master")

    def _request_abort(self, epoch0: int, name: str, e) -> None:
        with self._cond:
            if self._requested > epoch0:
                return     # this epoch's round is already requested
            self._requested = epoch0 + 1
        try:
            self._send_ctl("abort_req", {
                "epoch": epoch0, "collective": name,
                "error": repr(e)[:300]})
        except (Mp4jError, OSError):
            self._go_terminal(
                f"rank {self.rank}: master unreachable while "
                f"requesting recovery of '{name}' ({e})")

    def _await_epoch_past(self, epoch0: int, name: str) -> None:
        deadline = time.monotonic() + self.dead_rank_secs
        with self._cond:
            while self._fatal is None and self.epoch <= epoch0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(min(remaining, 0.5))
        if self._fatal is not None:
            raise self.fatal_exc()
        if self.epoch <= epoch0:
            self._go_terminal(
                f"rank {self.rank}: recovery of '{name}' stalled for "
                f"{self.dead_rank_secs:.1f}s (abort round never "
                "completed — dead rank or dead master)")

    def _go_terminal(self, msg: str, cause=None):
        """Ask the master to fan out a terminal abort, then raise the
        SAME message it broadcasts (so every rank's error reads
        identically); fall back to the local message if the master is
        gone. Never returns."""
        try:
            self._send_ctl("abort_req", {"fatal": True, "error": msg})
        except (Mp4jError, OSError):
            self.on_fatal(msg)
        deadline = time.monotonic() + min(self.dead_rank_secs, 10.0)
        with self._cond:
            while self._fatal is None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(min(remaining, 0.25))
        raise self.fatal_exc(self._fatal or msg) from cause
